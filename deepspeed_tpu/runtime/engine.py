"""DeepSpeedEngine — TPU-native rebuild of deepspeed/runtime/engine.py:102.

The reference engine wraps a mutable torch module and drives
forward/backward/step imperatively, hand-scheduling collectives. Here the
engine owns a functional **TrainState** (params / optimizer state / loss-scale
state) sharded over a `jax.sharding.Mesh`, and one jitted, donated
**train step** that fuses: micro-batch gradient accumulation (lax.scan over
the reference's GAS loop, engine.py:985-1092), ZeRO grad reduce-scatter
(stage2.py:614-746 → a sharding constraint), overflow check + dynamic loss
scaling (fp16/loss_scaler.py:79), global-norm clipping (runtime/utils.py
clip_grad_norm_), the optimizer update, and updated-param all-gather
(stage2.py:~1470 → param sharding constraint).

API parity: `train_batch`, `forward`/`backward`/`step` (emulated over the
functional core, same call pattern as the reference loop, engine.py:1005,
1077, 1234), `save_checkpoint`/`load_checkpoint` (engine.py:1562-1891),
`is_gradient_accumulation_boundary` (engine.py:975).
"""

import functools
import inspect
import os
import time
from typing import Any, Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp
import flax.struct
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.config.config import DeepSpeedConfig
from deepspeed_tpu.config import constants as C
from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.runtime import precision as prec
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader, RepeatingLoader
from deepspeed_tpu.runtime.lr_schedules import get_lr_schedule, _Schedule
from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner
from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop
from deepspeed_tpu.ops.adam import FusedAdam, Adam, DeepSpeedCPUAdam
from deepspeed_tpu.ops.lamb import FusedLamb
from deepspeed_tpu.ops.sgd import SGD
from deepspeed_tpu.ops.optimizer import TpuOptimizer, OptaxOptimizer
from deepspeed_tpu.utils.logging import logger, log_dist
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer
from deepspeed_tpu.utils.memory import see_memory_usage
from deepspeed_tpu.telemetry.anomaly import Watchdog
from deepspeed_tpu.telemetry.recorder import default_recorder
from deepspeed_tpu.telemetry.registry import default_registry
from deepspeed_tpu.runtime.elastic import faults as _faults
from deepspeed_tpu.telemetry.spans import span as tel_span, annotate, \
    TraceWindow

FORWARD_MICRO_TIMER = "forward_microstep"
BACKWARD_MICRO_TIMER = "backward_microstep"
STEP_MICRO_TIMER = "step_microstep"
FORWARD_GLOBAL_TIMER = "forward"
BACKWARD_GLOBAL_TIMER = "backward"
STEP_GLOBAL_TIMER = "step"
# pure readback round-trip measured by the instrumented mode; reported so
# tunneled/disaggregated deployments can see what the fences cost
FENCE_TIMER = "fence"


@flax.struct.dataclass
class TrainState:
    params: Any
    opt_state: Any
    scaler: Any
    global_step: jax.Array            # optimizer steps taken
    skipped_steps: jax.Array


def _global_norm(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves))


def _tree_where(pred, a, b):
    return jax.tree_util.tree_map(
        lambda x, y: jnp.where(pred, x, y) if hasattr(x, "dtype") else x, a, b)


def _build_optimizer(name, params_dict):
    p = dict(params_dict or {})
    betas = tuple(p.pop("betas", (0.9, 0.999)))
    name = (name or "adam").lower()
    common = dict(lr=p.pop("lr", 1e-3), betas=betas, eps=p.pop("eps", 1e-8),
                  weight_decay=p.pop("weight_decay", 0.0))
    if name in (C.ADAM_OPTIMIZER, "fusedadam"):
        adam_w = p.pop("adam_w_mode", True)
        opt = FusedAdam(adam_w_mode=adam_w,
                        bias_correction=p.pop("bias_correction", True),
                        moment_dtype=p.pop("moment_dtype", "fp32"), **common)
    elif name == C.ADAMW_OPTIMIZER:
        opt = FusedAdam(adam_w_mode=True,
                        bias_correction=p.pop("bias_correction", True),
                        moment_dtype=p.pop("moment_dtype", "fp32"), **common)
    elif name == C.CPU_ADAM_OPTIMIZER:
        opt = DeepSpeedCPUAdam(adam_w_mode=p.pop("adam_w_mode", True),
                               bias_correction=p.pop("bias_correction", True),
                               moment_dtype=p.pop("moment_dtype", "fp32"),
                               **common)
    elif name in (C.LAMB_OPTIMIZER, "fusedlamb"):
        opt = FusedLamb(bias_correction=p.pop("bias_correction", True),
                        max_coeff=p.pop("max_coeff", 10.0),
                        min_coeff=p.pop("min_coeff", 0.01),
                        moment_dtype=p.pop("moment_dtype", "fp32"), **common)
    elif name == C.ONEBIT_ADAM_OPTIMIZER:
        from deepspeed_tpu.runtime.fp16.onebit.adam import OnebitAdam
        opt = OnebitAdam(freeze_step=p.pop("freeze_step", 100000), **common)
    elif name == C.ONEBIT_LAMB_OPTIMIZER:
        from deepspeed_tpu.runtime.fp16.onebit.lamb import OnebitLamb
        opt = OnebitLamb(freeze_step=p.pop("freeze_step", 100000), **common)
    elif name == C.SGD_OPTIMIZER:
        opt = SGD(lr=common["lr"], momentum=p.pop("momentum", 0.0),
                  weight_decay=common["weight_decay"],
                  nesterov=p.pop("nesterov", False))
    else:
        raise ValueError(f"Unknown optimizer type {name}")
    if p:
        # a key the chosen optimizer never reads must not vanish silently
        # (e.g. moment_dtype on an optimizer without half-storage support)
        logger.warning(f"optimizer '{name}' ignores config params: "
                       f"{sorted(p)}")
    return opt


class DeepSpeedEngine:
    """See module docstring. Construction mirrors the reference's
    `_configure_*` phases (engine.py:149-220)."""

    def __init__(self,
                 args=None,
                 model=None,
                 optimizer=None,
                 model_parameters=None,
                 training_data=None,
                 lr_scheduler=None,
                 mesh=None,
                 mpu=None,
                 collate_fn=None,
                 config=None,
                 rng=None,
                 loss_fn=None,
                 param_tp_specs=None,
                 dont_change_device=False):
        mesh_lib.init_distributed()

        self.module = model
        self.client_optimizer = optimizer
        self.client_lr_scheduler = lr_scheduler
        self.training_data = training_data
        self.collate_fn = collate_fn
        self.mpu = mpu
        self._loss_fn_user = loss_fn
        self._param_tp_specs = param_tp_specs

        # -- config + mesh (reference engine.py:566 + _set_distributed_vars)
        # peek only at the mesh section first — full validation needs the
        # mesh-derived dp world size (batch triangle, config.py:837)
        explicit_mesh = mesh is not None
        if mesh is None:
            from deepspeed_tpu.config.config import MeshConfigSection
            pd = (config._param_dict if isinstance(config, DeepSpeedConfig)
                  else DeepSpeedConfig.load_param_dict(config))
            mc = MeshConfigSection(pd)
            mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(
                data=mc.data, model=mc.model, pipe=mc.pipe, seq=mc.seq,
                expert=mc.expert))
        if mpu is not None:
            mesh = self._adopt_mpu(mpu, mesh, explicit_mesh)
        self.mesh = mesh
        mesh_lib.set_current_mesh(mesh)
        # pipeline modules re-layout their params for the 1F1B executor;
        # this must see the FINAL mesh (after distributed init + config
        # resolution) and precede any param/state initialization
        if hasattr(model, "lower_to_spmd") and \
                mesh_lib.mesh_axis_size(mesh, mesh_lib.PIPE_AXIS) > 1:
            model.lower_to_spmd(mesh)
        self.dp_world_size = mesh_lib.dp_world_size(mesh)
        self._config = DeepSpeedConfig(config, mpu=mpu,
                                       world_size=self.dp_world_size)

        self.precision = prec.PrecisionConfig.from_ds_config(self._config)
        param_offload = self._config.zero_config.offload_param
        self._param_offload_host = bool(param_offload.enabled)
        self._param_offload_nvme = False
        self._param_swapper = None
        self._params_parked = False
        self._parked_via_push = False
        if self._param_offload_host:
            from deepspeed_tpu.utils.platform import is_tpu_backend
            if param_offload.device == C.OFFLOAD_NVME_DEVICE:
                # ZeRO-Infinity parameter tier: params REST on NVMe and
                # stream disk -> bounded staging -> HBM around each step
                # (swap_tensor/PartitionedParamSwapper); they are NOT
                # pinned_host-resident
                if not param_offload.nvme_path:
                    raise ValueError(
                        "offload_param device=nvme requires nvme_path")
                self._param_offload_nvme = True
                self._param_offload_host = False
            elif not is_tpu_backend():
                # the CPU PJRT backend advertises pinned_host but aborts
                # executing programs that move between memory spaces — the
                # tier is a no-op off-TPU (host RAM is already "host")
                logger.warning("offload_param: non-TPU backend, params "
                               "stay in default memory")
                self._param_offload_host = False
        self.zero = ZeroPartitioner(
            mesh, self._config.zero_optimization_stage,
            tp_specs=param_tp_specs,
            param_persistence_threshold=(
                self._config.zero_config.param_persistence_threshold
                if self._config.zero_optimization_stage >= 3 else 0),
            param_memory_kind="pinned_host" if self._param_offload_host
            else None)

        # -- optimizer (reference _configure_optimizer engine.py:647)
        if optimizer is not None:
            if isinstance(optimizer, TpuOptimizer):
                self.optimizer = optimizer
            elif hasattr(optimizer, "init") and hasattr(optimizer, "update"):
                self.optimizer = OptaxOptimizer(optimizer)
            else:
                raise TypeError("optimizer must be a TpuOptimizer or optax transform")
        else:
            self.optimizer = _build_optimizer(self._config.optimizer_name,
                                              self._config.optimizer_params)

        # -- lr scheduler (reference _configure_lr_scheduler engine.py:494)
        if lr_scheduler is not None:
            self.lr_scheduler = lr_scheduler
        elif self._config.scheduler_name:
            self.lr_scheduler = get_lr_schedule(self._config.scheduler_name,
                                                self._config.scheduler_params,
                                                self.optimizer)
        else:
            self.lr_scheduler = None

        # -- progressive layer drop (reference engine.py:1018)
        self.progressive_layer_drop = None
        if self._config.pld_config.enabled:
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=self._config.pld_config.theta,
                gamma=self._config.pld_config.gamma)

        # -- MoQ quantize-aware training + eigenvalue (reference
        # engine.py:761-791 _configure_quantization)
        self.quantizer = None
        self.eigenvalue = None
        qcfg = self._config.quantize_training_config
        if qcfg.enabled:
            from deepspeed_tpu.runtime.quantize import Quantizer
            self.quantizer = Quantizer(
                q_target_bits=qcfg.target_bits,
                q_start_bits=qcfg.start_bits,
                q_period=qcfg.quantize_period,
                q_offset=qcfg.schedule_offset,
                q_groups=qcfg.groups,
                q_mixed_fp16=qcfg.fp16_mixed_quantize,
                q_change_ratio=qcfg.quantize_change_ratio,
                q_type=qcfg.q_type,
                q_rounding=qcfg.q_rounding,
                q_verbose=qcfg.verbose,
                q_eigenvalue=qcfg.eigenvalue_enabled,
                use_quantizer_kernel=qcfg.quantizer_kernel,
                layer_num=qcfg.eigenvalue_layer_num)
            if qcfg.eigenvalue_enabled:
                from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
                self.eigenvalue = Eigenvalue(
                    verbose=qcfg.eigenvalue_verbose,
                    max_iter=qcfg.eigenvalue_max_iter,
                    tol=qcfg.eigenvalue_tol,
                    stability=qcfg.eigenvalue_stability,
                    gas_boundary_resolution=(
                        qcfg.eigenvalue_gas_boundary_resolution),
                    layer_name=qcfg.eigenvalue_layer_name,
                    layer_num=max(qcfg.eigenvalue_layer_num, 1))

        # -- dataloader (reference deepspeed_io engine.py:928)
        self.training_dataloader = None
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(training_data)

        # -- timers / counters (reference engine.py:176-180)
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_micro_batch_size_per_gpu(),
            num_workers=self.dp_world_size,
            steps_per_output=self._config.steps_per_print)
        self.global_steps = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self.global_samples = 0
        self.scalar_history = []  # tensorboard-lite: list of (step, dict)

        # -- unified telemetry (deepspeed_tpu/telemetry): per-step
        # counters/histograms into the process-wide registry (sync-free),
        # window folds + exports at steps_per_print boundaries where the
        # existing loss readback is already the fence, and the
        # config-gated XLA trace window (profiling.trace_dir/trace_steps)
        self.telemetry = default_registry()
        self._trace_window = TraceWindow.from_config(
            self._config.profiling_config)
        self._tel_exporter = None      # lazy JSONL stream (monitor gate)
        self._tel_bridge = None        # lazy SummaryEventWriter bridge
        self._tel_window_t0 = None     # open measurement window start
        self._tel_window_step0 = 0
        self._tel_window_tokens = 0
        self._tel_flops_per_step = None  # lazily priced via cost analysis

        # -- flight recorder + anomaly watchdog (ISSUE 6): the recorder
        # is the process-wide event ring (monitor.flight_recorder sizes/
        # gates it); the watchdog (monitor.watchdog, opt-in) evaluates
        # NaN-loss / step-time / swap-stall rules ONLY at the
        # steps_per_print boundary and window folds — the fences this
        # engine already pays — and dumps the ring to JSONL on trigger
        mc = self._config.monitor_config
        self.flight_recorder = default_recorder().configure(
            enabled=mc.flight_recorder.enabled,
            capacity=mc.flight_recorder.capacity)
        self.watchdog = Watchdog.from_config(
            mc.watchdog, recorder=self.flight_recorder,
            registry=self.telemetry, source="train")

        # -- cluster telemetry plane (ISSUE 12): cross-rank aggregation
        # at the fences this engine already pays (the steps_per_print
        # loss readback; snapshot commit fences) — a ~7-float gloo
        # allgather folded on rank 0 into cluster/* skew gauges and the
        # watchdog's rank_straggler rule. Single-process it degenerates
        # to local gauges with no collective.
        self._cluster = None
        self._tel_last_step_s = None   # the just-closed window's mean
        self._tel_last_host_step_s = None  # rank-attributable component
        self._tel_window_dispatch_s = 0.0  # blocked-in-dispatch seconds
        self._tel_last_fence_ts = None
        if mc.cluster.enabled:
            from deepspeed_tpu.telemetry.cluster import ClusterAggregator
            self._cluster = ClusterAggregator(
                registry=self.telemetry, recorder=self.flight_recorder,
                watchdog=self.watchdog)
        # live /metrics + /healthz endpoint (monitor.serve_port, rank 0
        # only — that is where the cluster gauges fold; a bind failure
        # warns instead of killing training)
        self._metrics_server = None
        from deepspeed_tpu.utils.logging import _process_index
        if mc.serve_port and _process_index() == 0:
            from deepspeed_tpu.telemetry.serve import start_metrics_server
            self._metrics_server = start_metrics_server(
                mc.serve_port, host=mc.serve_host,
                registry=self.telemetry, watchdog=self.watchdog,
                fence_age_fn=lambda: self._tel_last_fence_ts)

        # -- elastic preemption tolerance (runtime/elastic, ISSUE 7):
        # periodic async snapshots through the swap tier's write-behind
        # aio handle, a SIGTERM hook with a grace budget, auto-resume
        # from the newest valid manifest. All gated on the `snapshot`
        # config block; the snapshotter itself is built lazily (it may
        # ride the param swapper's write handle, which exists only
        # after state init).
        self._snap_cfg = self._config.snapshot_config
        self._snapshotter = None
        self._preemption = None
        self.preempted = False
        self._auto_resumed = False
        if self._snap_cfg.enabled:
            from deepspeed_tpu.runtime.elastic.preemption import (
                PreemptionHandler)
            self._preemption = PreemptionHandler(
                signals=self._snap_cfg.signals,
                grace_s=self._snap_cfg.grace_secs,
                recorder=self.flight_recorder)

        # -- collective hang watchdog + heartbeat (runtime/elastic/hang,
        # ISSUE 15): a daemon thread riding the same blocked-in-dispatch
        # interval the train/host_step_s accounting measures — a
        # collective stalled past fault_tolerance.hang_deadline_s
        # becomes one latched rank_dead dump + a distinct EXIT_HANG
        # exit instead of an eternal hang; the thread also rewrites
        # this rank's heartbeat file for the launcher-level supervisor.
        # restart_epoch (stamped by the supervisor into child envs) is
        # breadcrumbed into the ring so view.py can stitch the
        # die → detect → shrink → resume timeline across epochs.
        self._hangdog = None
        self._fence_ref = None   # the last step's loss array: the
        #                          pre-boundary-collective fence target
        self._fenced_step = None  # step already fenced (once per step)
        self._restart_epoch = int(
            os.environ.get("DSTPU_RESTART_EPOCH", "0") or 0)
        if self._restart_epoch:
            self.flight_recorder.record(
                "restart_epoch", epoch=self._restart_epoch,
                world=jax.process_count())
        ftc = self._config.fault_tolerance_config
        if ftc.enabled:
            from deepspeed_tpu.runtime.elastic.hang import HangWatchdog
            hb_dir = ftc.heartbeat_dir \
                or os.environ.get("DSTPU_HEARTBEAT_DIR") or None
            self._hangdog = HangWatchdog(
                deadline_s=ftc.hang_deadline_s,
                poll_s=ftc.hang_poll_s or None,
                rank=_process_index(), world=jax.process_count(),
                watchdog=self.watchdog, recorder=self.flight_recorder,
                registry=self.telemetry, heartbeat_dir=hb_dir,
                heartbeat_interval_s=ftc.heartbeat_interval_s,
                restart_epoch=self._restart_epoch)

        # ZeRO-Offload: optimizer state + fp32 master on host (cpu) or NVMe
        self._offload_cfg = self._config.zero_config.offload_optimizer
        self._host_runner = None
        if self._offload_cfg.enabled:
            # fail at construction, not at the first train_batch: the host
            # tier only has SIMD steps for the Adam/LAMB families, and the
            # NVMe tier needs somewhere to put the moments
            from deepspeed_tpu.ops.lamb import FusedLamb
            if not isinstance(self.optimizer, (FusedAdam, FusedLamb)):
                raise ValueError(
                    "optimizer offload supports Adam/AdamW/LAMB optimizers "
                    f"only, got {type(self.optimizer).__name__}")
            if self._offload_cfg.device == C.OFFLOAD_NVME_DEVICE and \
                    not self._offload_cfg.nvme_path:
                raise ValueError(
                    "offload_optimizer device=nvme requires nvme_path")

        # stage3_prefetch decides BEFORE state init: the partitioner must
        # exclude the layer dim from stacked-leaf sharding so the prefetch
        # scan (parallel/prefetch.py) can slice whole layers device-locally
        if self._prefetch_active():
            self.zero.layer_stacked_prefixes = (
                self.module.prefetch_layer_subtree,)

        self._rng = rng if rng is not None else jax.random.PRNGKey(self._config.seed)
        self.state: Optional[TrainState] = None
        self.state_shardings = None
        self._jit_train_batch = None
        self._jit_micro_grads = None
        self._jit_grads_finite = None
        self._jit_grad_norm = None
        self._jit_apply_grads = None
        self._jit_eval = None
        self._pending_grads = None
        self._pending_loss = None
        self._pending_micro = None
        self._accum_loss = None
        self._last_lr = None

        if model_parameters is not None:
            self._init_state(model_parameters)

        if self._config.flops_profiler_config.enabled:
            from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler
            self.flops_profiler = FlopsProfiler(self)
        else:
            self.flops_profiler = None

        log_dist(f"DeepSpeedEngine initialized: mesh={dict(self.mesh.shape)} "
                 f"zero_stage={self.zero_optimization_stage()} "
                 f"precision={self.precision.compute_dtype.__name__}", ranks=[0])

    # ------------------------------------------------------------------
    # config accessors (parity with reference engine.py:270-470)
    # ------------------------------------------------------------------
    def train_batch_size(self):
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self._config.gradient_accumulation_steps

    def zero_optimization_stage(self):
        return self._config.zero_optimization_stage

    def zero_optimization(self):
        return self._config.zero_enabled

    def fp16_enabled(self):
        return self._config.fp16_enabled

    def bfloat16_enabled(self):
        return self._config.bf16_enabled

    def gradient_clipping(self):
        return self._config.gradient_clipping

    def steps_per_print(self):
        return self._config.steps_per_print

    def wall_clock_breakdown(self):
        return self._config.wall_clock_breakdown

    def dump_state(self):
        return self._config.dump_state

    def get_lr(self):
        if self._last_lr is not None:
            return [float(self._last_lr)]
        return [float(getattr(self.optimizer, "lr", 0.0))]

    def get_global_grad_norm(self):
        return getattr(self, "_last_grad_norm", None)

    @property
    def loss_scale(self):
        if self.state is None:
            return 1.0
        return float(jax.device_get(self.state.scaler["loss_scale"]))

    def is_gradient_accumulation_boundary(self):
        return (self.micro_steps + 1) % self.gradient_accumulation_steps() == 0

    def _compressed_comm_active(self):
        """True when the train step should use the 1-bit compressed
        collective path (reference onebit wiring: engine's own allreduce is
        disabled and the optimizer communicates compressed momentum,
        onebit/adam.py:92-104). Requires a pure-DP layout: the momentum
        collective assumes replicated params (ZeRO stage 0, no tp/sp/pp)."""
        cached = getattr(self, "_compressed_comm_cached", None)
        if cached is not None:
            return cached
        self._compressed_comm_cached = self._compute_compressed_comm()
        return self._compressed_comm_cached

    def _compute_compressed_comm(self):
        if not getattr(self.optimizer, "supports_compressed_comm", False):
            return False
        if self._offload_cfg.enabled or self._param_offload_host:
            return False
        dp = mesh_lib.mesh_axis_size(self.mesh, mesh_lib.DATA_AXIS)
        if dp <= 1:
            return False
        pure_dp = (self.zero_optimization_stage() == 0
                   and self._pure_dp_mesh())
        if not pure_dp:
            logger.warning(
                "1-bit optimizer requested with ZeRO stage "
                f"{self.zero_optimization_stage()} or a non-data mesh axis; "
                "compressed communication disabled (exact-comm fallback)")
            return False
        return True

    def _pure_dp_mesh(self):
        """True when only the data axis is live — the explicit-comm
        train paths shard_map the data axis alone, so every other mesh
        axis must be trivial (the one shared gate of the 1-bit / CSR /
        overlap / prefetch dispatch)."""
        return all(mesh_lib.mesh_axis_size(self.mesh, a) == 1
                   for a in (mesh_lib.PIPE_AXIS, mesh_lib.SEQ_AXIS,
                             mesh_lib.MODEL_AXIS, mesh_lib.EXPERT_AXIS))

    def _comm_hierarchy(self):
        """Resolved slow/fast split of the data axis for the link-aware
        compressed exchange (ISSUE 10), cached. None = flat single-link
        exchange — either the hierarchy block is off, or no slow axis
        exists, in which case the fallback is LOUD (warning + flight
        breadcrumb): silently compressing the fast links would be the
        exact mistake this layer exists to avoid."""
        cached = getattr(self, "_comm_hier_cached", "unset")
        if cached != "unset":
            return cached
        hier = None
        hcfg = self._config.comm_config.hierarchy
        if hcfg.enabled and (self._compressed_comm_active()
                             or self._prefetch_active()):
            from deepspeed_tpu.parallel import topology as topo
            hier, reason = topo.derive_data_hierarchy(
                self.mesh, slow_axis=hcfg.slow_axis)
            if hier is None:
                # latched per (axis, reason): elastic restarts and test
                # harnesses rebuild engines in one process, and the same
                # fallback repeating per rebuild buries the one
                # occurrence that matters (the router_block episode rule)
                if topo.latch_fallback(hcfg.slow_axis
                                       if hcfg.slow_axis else "auto",
                                       reason):
                    logger.warning(
                        f"comm.hierarchy enabled but no usable slow axis "
                        f"({reason}); falling back to the FLAT "
                        f"single-link schedule — every link pays the "
                        f"full exchange")
                    self.flight_recorder.record("comm_hierarchy_fallback",
                                                reason=reason)
            else:
                log_dist(
                    f"comm.hierarchy: data axis split {hier.inter}x"
                    f"{hier.intra} (source={hier.source}, "
                    f"compression={hcfg.compression})", ranks=[0])
        self._comm_hier_cached = hier
        return hier

    def _comm_plan(self):
        """The static overlap.HierarchyPlan for the hierarchical
        compressed exchange, or None (flat path)."""
        hier = self._comm_hierarchy()
        if hier is None:
            return None
        from deepspeed_tpu.parallel import overlap
        hcfg = self._config.comm_config.hierarchy
        return overlap.HierarchyPlan(
            inter_axis=mesh_lib.DATA_INTER_AXIS,
            intra_axis=mesh_lib.DATA_INTRA_AXIS,
            inter=hier.inter, intra=hier.intra,
            compression=hcfg.compression,
            min_bucket_bytes=hcfg.min_bucket_bytes,
            bucket_elems=self._config.zero_config.reduce_bucket_size)

    def _prefetch_hier_plan(self):
        """The HierarchyPlan for the stage-3 prefetch stream (ISSUE 16):
        the same resolved slow/fast split as `_comm_plan`, re-bucketed by
        ``stage3_prefetch_bucket_size`` (the replicated-leaf bucket leg
        belongs to the prefetch stream, not the 1-bit reduce stream).
        None when prefetch or the hierarchy is off/unresolvable."""
        if not self._prefetch_active():
            return None
        plan = self._comm_plan()
        if plan is None:
            return None
        import dataclasses
        return dataclasses.replace(plan, bucket_elems=int(
            self._config.zero_config.prefetch_bucket_size))

    _PF_ERR_KEYS = ("pf_group_we", "pf_outer_we", "pf_bucket_we",
                    "pf_bucket_se")

    def _prefetch_error_states(self, params):
        """Persistent error-feedback opt_state for the hierarchical
        prefetch stream's compressed slow hops (ISSUE 16), or {} when
        the stream runs flat. Three legs, mirroring the train program's
        exchanges: the per-layer packed dtype groups (``pf_group_we`` —
        [dp, L, E] per group, or None where the policy keeps the hop
        exact), the step-persistent outer leaves (``pf_outer_we`` —
        {key: [dp, E] per leaf}), and the replicated-leaf bucket stream
        (``pf_bucket_we``/``pf_bucket_se`` — the two-level 1-bit
        exchange's chunk-shaped states). The leading [dp] dim is the
        per-device copy, sharded over the (split) data axis; the train
        fn slices ``x[0]`` inside shard_map and re-wraps ``x[None]``,
        the 1-bit optimizer's pattern."""
        plan = self._prefetch_hier_plan()
        if plan is None:
            return {}
        from deepspeed_tpu.parallel import overlap
        from deepspeed_tpu.parallel import prefetch as prefetch_lib
        tm = jax.tree_util.tree_map
        subtree = self.module.prefetch_layer_subtree
        param_spec_tree = self.zero.param_specs(params)
        layer_plan = self.zero.explicit_shard_plan(
            params[subtree], specs=param_spec_tree[subtree])
        full_plan = self.zero.explicit_shard_plan(params,
                                                  specs=param_spec_tree)
        n = plan.world
        mode = self._config.zero_config.stage3_prefetch_gather
        cast_bf16 = self._config.grad_dtype == "bf16"
        fused_ids, _ = self._select_fused_matmul_leaves(
            params[subtree], layer_plan, mode, n, plan.axes, cast_bf16)
        bump = lambda shape: jnp.zeros((n,) + tuple(shape),  # noqa: E731
                                       jnp.float32)
        group_specs = prefetch_lib.plan_group_errors(
            jax.tree_util.tree_leaves(params[subtree]), layer_plan, n,
            fused_ids, plan)
        pf_outer = {}
        for k in params:
            if k == subtree:
                continue
            op = self.zero.explicit_shard_plan(params[k],
                                               specs=param_spec_tree[k])
            errs = []
            for leaf, e in zip(jax.tree_util.tree_leaves(params[k]), op):
                if e is None or not prefetch_lib.outer_compress(
                        leaf.size // n, plan):
                    errs.append(None)
                else:
                    errs.append(bump((prefetch_lib.outer_error_numel(
                        leaf.size // n, plan),)))
            pf_outer[k] = errs
        repl = [leaf for leaf, e in zip(jax.tree_util.tree_leaves(params),
                                        full_plan) if e is None]
        bwe, bse = overlap.hierarchical_error_states(repl, plan)
        return {
            "pf_group_we": [bump(s) if s is not None else None
                            for s in group_specs],
            "pf_outer_we": pf_outer,
            "pf_bucket_we": [tm(lambda x: bump(x.shape), e) for e in bwe],
            "pf_bucket_se": [tm(lambda x: bump(x.shape), e) for e in bse],
        }

    # ------------------------------------------------------------------
    # state init
    # ------------------------------------------------------------------
    def _example_from_batch(self, batch):
        def first_micro(x):
            arr = np.asarray(x)
            mb = self.train_micro_batch_size_per_gpu() * self.dp_world_size
            return arr[:mb] if arr.ndim > 0 and arr.shape[0] >= mb else arr
        return jax.tree_util.tree_map(first_micro, batch)

    def _model_inputs(self, batch):
        """Extract the positional model input from a batch pytree."""
        if isinstance(batch, dict):
            for key in ("input_ids", "inputs", "x"):
                if key in batch:
                    return batch[key]
            return next(iter(batch.values()))
        if isinstance(batch, (tuple, list)):
            return batch[0]
        return batch

    def _maybe_derive_tp_specs(self, x):
        """Auto-derive Megatron-style TP specs for known in-tree models when
        the mesh has a model axis (shape-only, via eval_shape)."""
        if self._param_tp_specs is not None:
            return
        # models may publish their own base specs (TP/pipe axes)
        if hasattr(self.module, "param_partition_specs"):
            try:
                shapes = jax.eval_shape(
                    lambda r, xx: self.module.init(r, xx), self._rng, x)
                self._param_tp_specs = self.module.param_partition_specs(shapes)
                self.zero.tp_specs = self._param_tp_specs
                return
            except Exception as e:
                logger.warning(f"model param_partition_specs failed: {e}")
        if mesh_lib.mesh_axis_size(self.mesh, mesh_lib.MODEL_AXIS) <= 1:
            return
        try:
            from deepspeed_tpu.models.sharding import tp_specs_for
            shapes = jax.eval_shape(
                lambda r, xx: self.module.init(r, xx), self._rng, x)
            specs = tp_specs_for(
                self.module, shapes["params"] if "params" in shapes
                else shapes)
            if specs is not None:
                self._param_tp_specs = specs
                self.zero.tp_specs = specs
                return
        except Exception as e:
            logger.warning(f"TP spec auto-derivation failed: {e}")
        logger.warning(
            f"mesh has model axis "
            f"{mesh_lib.mesh_axis_size(self.mesh, mesh_lib.MODEL_AXIS)} but "
            f"no tensor-parallel sharding rules are known for "
            f"{type(self.module).__name__}: parameters will be REPLICATED "
            f"across the model axis (TP is a no-op). Register rules via "
            f"deepspeed_tpu.models.sharding.register_tp_rules or expose "
            f"param_partition_specs on the model.")

    def _make_offload_runner(self, params):
        """Pick the offload tier: the device-streamed step (state in the
        accelerator host's pinned_host memory, update on device —
        offload_stream.py) when the backend supports it, the numpy/SIMD
        host runner (offload.py) for NVMe state, LAMB, non-pinned-host
        backends, or an explicit ``stream: "host"``."""
        from deepspeed_tpu.runtime.zero.offload import HostOffloadOptimizer
        cfg = self._offload_cfg
        want_stream = cfg.stream != "host" \
            and cfg.device == C.OFFLOAD_CPU_DEVICE \
            and not isinstance(self.optimizer, FusedLamb)
        if want_stream:
            from deepspeed_tpu.runtime.zero.offload_stream import (
                StreamedOffloadOptimizer, backend_supports_offload_stream)
            if backend_supports_offload_stream(self.mesh.devices.flat[0]):
                # TPU: state rests in pinned_host; CPU: memory spaces are
                # collapsed (unpinned_host only) so the moves are no-ops
                # but the tier runs with identical semantics
                return StreamedOffloadOptimizer(
                    params, self.optimizer, self.mesh, self.zero)
            if cfg.stream == "device":
                raise ValueError(
                    "offload_optimizer stream='device' requires a backend "
                    "with an addressable host memory space")
            logger.warning("offload: backend reports no addressable "
                           "memories; using the host runner")
        elif cfg.stream == "device":
            raise ValueError(
                "offload_optimizer stream='device' supports device='cpu' "
                "with Adam/AdamW only (NVMe state and LAMB run on the host "
                "runner)")
        return HostOffloadOptimizer(
            params, self.optimizer, cfg, self._config.aio_config)

    def _offload_streamed(self):
        from deepspeed_tpu.runtime.zero.offload_stream import (
            StreamedOffloadOptimizer)
        return isinstance(self._host_runner, StreamedOffloadOptimizer)

    def _init_state(self, params=None, example_batch=None):
        if params is None:
            x = jnp.asarray(self._model_inputs(example_batch))
            self._maybe_derive_tp_specs(x)
            params = self._init_params(x)

        if self._offload_cfg.enabled:
            # fp32 master + moments to host/NVMe; device keeps compute-dtype
            # params only (the ZeRO-Offload memory shape)
            self._host_runner = self._make_offload_runner(params)
            params = jax.tree_util.tree_map(
                lambda p: jnp.asarray(p, self.precision.compute_dtype)
                if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating) else
                jnp.asarray(p), params)
            opt_state = {}
        elif self._compressed_comm_active():
            opt_state = self.optimizer.init_compressed(
                params, mesh_lib.mesh_axis_size(self.mesh, mesh_lib.DATA_AXIS),
                comm=self._comm_plan())
        else:
            opt_state = self.optimizer.init(params)
            pf_err = self._prefetch_error_states(params)
            if pf_err:
                # the hierarchical prefetch stream's error feedback rides
                # opt_state (checkpointed + reconciled like the 1-bit
                # worker/server errors); the train fn pops these around
                # opt.step, which only knows its own fields
                opt_state = dict(opt_state, **pf_err)
        scaler = prec.init_scaler_state(self.precision)
        state = TrainState(params=params, opt_state=opt_state, scaler=scaler,
                           global_step=jnp.zeros((), jnp.int32),
                           skipped_steps=jnp.zeros((), jnp.int32))

        # shard the state onto the mesh per ZeRO stage
        self.state_shardings = self._build_state_shardings(state)
        self.state = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s), state, self.state_shardings)
        if self._param_offload_nvme:
            # the files themselves are first written by the post-step
            # _park_params — params are device-resident until then, so an
            # eager write here would be dead work the first park overwrites
            self._param_swapper = self._make_param_swapper()
        see_memory_usage("after engine state init",
                         force=self._config.memory_breakdown)

    def _make_param_swapper(self):
        """The NVMe param-tier swapper, wired to the offload_param
        pipeline knobs (pipeline_read/pipeline_write/buffer_count) and
        this engine's telemetry registry."""
        from deepspeed_tpu.runtime.swap_tensor import PartitionedParamSwapper
        pc = self._config.zero_config.offload_param
        return PartitionedParamSwapper(
            pc.nvme_path, self._config.aio_config,
            pipeline_read=pc.pipeline_read,
            pipeline_write=pc.pipeline_write,
            buffer_count=pc.buffer_count,
            registry=self.telemetry,
            fsync=pc.fsync)

    def _param_swap_order(self):
        """The per-layer swap schedule: the order param leaves stream
        disk→host→device at unpark, derived from the partitioner's
        layer-stacked prefixes (the stage3_prefetch layer contract).
        First-consumed leaves first — outer (embedding-side) leaves, then
        the stacked transformer blocks the in-jit prefetch pipeline
        slices layer by layer — so the device assembles inputs in compute
        order while later groups are still on disk. Pure metadata: any
        permutation is correct; this one pipelines best."""
        order = getattr(self, "_param_swap_order_cache", None)
        if order is not None and len(order) == len(
                jax.tree_util.tree_leaves(self.state_shardings.params)):
            return order
        flat, _ = jax.tree_util.tree_flatten_with_path(
            self.state_shardings.params)
        stacked = set(self.zero.layer_stacked_prefixes or ())
        if not stacked:
            sub = getattr(self.module, "prefetch_layer_subtree", None)
            if sub:
                stacked = {sub}

        def head(path):
            if not path:
                return ""
            p = path[0]
            return str(getattr(p, "key", getattr(p, "name", p)))

        outer = [i for i, (p, _) in enumerate(flat) if head(p) not in stacked]
        inner = [i for i, (p, _) in enumerate(flat) if head(p) in stacked]
        # flatten order puts the block subtree ("h") before ln_f/wpe/wte;
        # reversing the outer list puts the embedding leaves first
        order = outer[::-1] + inner
        self._param_swap_order_cache = order
        return order

    # -- NVMe parameter residency (ZeRO-Infinity param tier) ---------------
    def _ensure_params_resident(self):
        """Parked params (resting on NVMe) stream back to the device before
        any computation that reads them — in swap-schedule order, through
        the pipelined read window (and the write-behind byte cache) when
        the offload_param pipeline knobs are on."""
        if not self._params_parked:
            return
        t0 = time.perf_counter()
        leaves = self._param_swapper.swap_in_device(
            jax.tree_util.tree_leaves(self.state_shardings.params),
            order=self._param_swap_order())
        self.telemetry.histogram("swap/unpark_s").observe(
            time.perf_counter() - t0)
        self.state = TrainState(
            params=jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(self.state_shardings.params),
                leaves),
            opt_state=self.state.opt_state, scaler=self.state.scaler,
            global_step=self.state.global_step,
            skipped_steps=self.state.skipped_steps)
        self._params_parked = False

    def _park_params(self):
        """Write the (updated) device params back to NVMe and free their
        HBM — params rest on disk between steps, so at rest the chip holds
        no parameter bytes and host RAM holds only the bounded staging
        pool. With ``pipeline_write`` the disk writes run behind this call
        (swap-out of step N overlaps everything up to step N+1's unpark,
        whose drain fence guarantees no leaf is re-read mid-write); when
        the host optimizer already parked the updated leaves directly
        (``_parked_via_push``), only the stale device copies remain to
        free."""
        if self._param_swapper is None or self._params_parked:
            return
        t0 = time.perf_counter()
        leaves = jax.tree_util.tree_leaves(self.state.params)
        if getattr(self, "_parked_via_push", False):
            self._parked_via_push = False
        else:
            self._param_swapper.swap_out_device(leaves)
        for leaf in leaves:
            try:
                leaf.delete()
            except Exception:
                pass
        self._params_parked = True
        self.telemetry.histogram("swap/park_s").observe(
            time.perf_counter() - t0)

    # -- collective hang guard (runtime/elastic/hang, ISSUE 15) ------------
    # Every region that can block on a PEER process — the step dispatch
    # plus the boundary exchanges (cluster allgather, preemption
    # agreement) — is bracketed so the hang watchdog can tell "blocked
    # on a dead/stuck peer" from "idle between steps". Two attribute
    # stores per call; the first region of each kind is compile-exempt.

    def _guard_enter(self, kind, step=None):
        if self._hangdog is not None:
            self._hangdog.enter_dispatch(kind, step)

    def _guard_exit(self):
        if self._hangdog is not None:
            self._hangdog.exit_dispatch()

    def stop_fault_tolerance(self):
        """Stop the hang-watchdog daemon thread and remove this rank's
        heartbeat file. Engines have no general teardown hook, so a
        process that builds SEVERAL fault_tolerance-enabled engines
        (sequential jobs, test loops) should call this on each retired
        engine — otherwise every retired engine's thread keeps polling
        and rewriting the same heartbeat file. Called automatically
        when a preemption finalizes (the engine trains no further)."""
        if self._hangdog is not None:
            self._hangdog.stop()
            self._hangdog = None

    def _fence_step_program(self):
        """Multi-process only: block until the just-dispatched step
        program — and with it every IN-program cross-process collective
        — has completed, before any OUT-of-program collective runs at
        the boundary (the preemption agreement, the snapshot barriers).
        Two XLA programs' gloo ops interleave on the same TCP pair when
        the first is still in flight as the second dispatches (observed
        as ``gloo EnforceNotMet: op.preamble.length <= op.nbytes`` —
        one rank's boundary allgather recv met the peer's still-flowing
        step psum). The loss output alone is NOT a sufficient fence:
        output buffers become ready per-chain and the loss chain does
        not depend on the grad allreduce, so waiting on the loss can
        pass while the update collectives still flow — the fence waits
        on the UPDATED state leaves (each downstream of its own grad
        exchange) plus the loss. Leaves already parked/donated are
        skipped: their chains completed before the park could run. The
        wait itself is guarded — a peer that died mid-step parks us
        HERE, and the hang watchdog must see it. Latched per step:
        a boundary that is commit + agreement + cluster exchange at
        once fences the leaf tree exactly once."""
        if jax.process_count() == 1:
            return
        if self._fenced_step == self.global_steps:
            return
        self._fenced_step = self.global_steps
        self._guard_enter("fence", self.global_steps)
        try:
            leaves = [] if self.state is None else \
                jax.tree_util.tree_leaves(
                    (self.state.params, self.state.opt_state))
            if self._fence_ref is not None:
                leaves.append(self._fence_ref)
            for leaf in leaves:
                if getattr(leaf, "is_deleted", None) \
                        and leaf.is_deleted():
                    continue
                try:
                    jax.block_until_ready(leaf)  # sync-ok: boundary
                except Exception:                # fence
                    pass    # a just-donated buffer's chain is done
        finally:
            self._guard_exit()

    # -- elastic snapshots + preemption (runtime/elastic, ISSUE 7) ---------
    def _make_snapshotter(self):
        """The async snapshotter, on its OWN dedicated write-behind aio
        handle (the swap tier's write-handle pattern, not its handle:
        `aio_handle_wait` drains a whole handle, so literally sharing
        the park stream would make step N+1's unpark drain fence eat
        the snapshot writes after ~0 overlap — and charge them to
        swap/stall_s while ckpt/stall_s reads a structural 0)."""
        from deepspeed_tpu.runtime.elastic.snapshot import AsyncSnapshotter
        sc = self._snap_cfg
        return AsyncSnapshotter(
            sc.path, aio_config=self._config.aio_config,
            fsync=sc.fsync, keep=sc.keep, registry=self.telemetry,
            recorder=self.flight_recorder)

    def _snapshot_trees(self):
        """The {stem: pytree} payload of one snapshot — the same state
        save_checkpoint persists, but leaves already parked on NVMe
        become FileLeaf markers (bytes come off the swap files, or the
        write-behind staging cache for the most recent parks) instead of
        being re-serialized from the device."""
        from deepspeed_tpu.runtime.elastic.snapshot import FileLeaf
        state = self.state
        if self._host_runner is not None:
            # fp32 master + host moments, like save_checkpoint
            params = self._host_runner.params_tree()
            opt_state = self._host_runner.state_dict()
        elif self._params_parked and self._param_swapper is not None:
            sw = self._param_swapper
            if sw.has_pending_writes:
                # the files must be whole before FileLeaf reads them;
                # cache-backed leaves wouldn't need this, but the
                # uncached rest do and the fence drains the whole handle
                sw.drain_writes()
            flat, tdef = jax.tree_util.tree_flatten(
                self.state_shardings.params)
            leaves = []
            for i in range(len(flat)):
                shape, dtype = sw.meta[i]
                value, source = sw.staged_leaf(i)
                leaves.append(value if source == "cache"
                              else FileLeaf(value, shape, dtype))
            params = jax.tree_util.tree_unflatten(tdef, leaves)
            opt_state = state.opt_state
        else:
            params = state.params
            opt_state = state.opt_state
        return {
            "model_states": {"params": params},
            "optim_states": {
                "opt_state": opt_state,
                "scaler": state.scaler,
                "global_step": state.global_step,
                "skipped_steps": state.skipped_steps,
            },
        }

    def _begin_snapshot(self, tag=None):
        """Stage + submit one async snapshot (returns its tag). The
        disk writes overlap the following step; the next _elastic_step
        boundary is the commit point."""
        if self._snapshotter is None:
            self._snapshotter = self._make_snapshotter()
        if self._snapshotter.in_flight:
            self._snapshotter.finalize()
        tag = tag or f"global_step{self.global_steps}"
        meta = {
            "zero_stage": self.zero_optimization_stage(),
            "world_size": jax.process_count(),
            "dp_world_size": self.dp_world_size,
            "train_batch_size": self.train_batch_size(),
            "micro_batch": self.train_micro_batch_size_per_gpu(),
            "grad_accum": self.gradient_accumulation_steps(),
            "elastic": bool(self._config.elasticity_enabled),
        }
        self._snapshotter.begin(tag, self._snapshot_trees(),
                                extra=self._ckpt_extra(), meta=meta)
        return tag

    def _elastic_commit(self):
        """Commit point of the previous boundary's snapshot — runs
        BEFORE this step's ``_park_params`` so the drain fence waits
        only on writes that had a whole step to land (park and
        snapshot share one write handle when the NVMe tier is
        pipelined; fencing AFTER the park would synchronously eat the
        park the write-behind exists to hide, every post-boundary
        step). The measured stall feeds ckpt/stall_s and the
        watchdog's snapshot-stall rule."""
        if not self._snap_cfg.enabled:
            return
        if self._snapshotter is not None and self._snapshotter.in_flight:
            # the finalize path's _sync barriers (and the commit-fence
            # cluster exchange below) are OUT-of-program collectives:
            # the just-dispatched step program must be done first
            self._fence_step_program()
            _, stall = self._snapshotter.finalize()
            # stall observations happen ONLY at commit fences: feeding
            # zeros on the 99 in-between steps would pin the watchdog's
            # rolling median at 0 (factor never participates) and
            # re-arm its latch between commits (one dump per interval
            # instead of per episode)
            self.telemetry.histogram("ckpt/stall_s").observe(stall)
            if self.watchdog is not None:
                # host wall timer this method already kept — no fence
                self.watchdog.observe_ckpt_stall(
                    stall, step=self.global_steps)
            # ISSUE 12: the commit fence is the second aligned
            # aggregation point (snapshot begins happen at aligned
            # interval boundaries, so in_flight agrees across ranks) —
            # the fresh ckpt/stall_s observation rides the exchange
            if self._cluster is not None:
                # step_time_s is explicitly UNMEASURED here: the last
                # boundary's value is stale, and re-feeding it would
                # let one slow window satisfy the straggler rule's
                # K-CONSECUTIVE-fences debounce by itself (the rule
                # skips NaN ranks). This fence aggregates the fresh
                # ckpt stall; step-time skew belongs to boundaries.
                self._guard_enter("exchange", self.global_steps)
                try:
                    self._cluster.exchange_from_registry(
                        step=self.global_steps,
                        overrides={"step_time_s": None,
                                   "ckpt_stall_s": stall})
                finally:
                    self._guard_exit()
                self._tel_last_fence_ts = time.time()
                # NO window re-stamp here (unlike the boundary
                # exchange): this fence sits mid-window and moving t0
                # would shrink window_s under an unchanged step count,
                # corrupting train/step_time_s. The cost: the wait for
                # the slowest rank's arrival lands in this window —
                # once per snapshot interval, not per boundary.

    def _elastic_step(self):
        """Step-boundary elastic hook (after the park): the
        fault-injection point, preemption handling, and the periodic
        begin — whose commit rides the NEXT boundary's
        ``_elastic_commit``."""
        _faults.fire("step_end", step=self.global_steps, engine=self)
        sc = self._snap_cfg
        if not sc.enabled or self.preempted:
            return
        at_boundary = bool(sc.interval_steps) \
            and self.global_steps % sc.interval_steps == 0
        # multi-process: the snapshot path contains collective barriers
        # (ckpt._sync), so ranks must AGREE before entering it — a
        # per-rank signal flag would send ranks down mismatched barrier
        # sequences and deadlock. The agreement collective runs only at
        # interval boundaries (every rank reaches the same global_steps
        # in SPMD lockstep); single-process keeps the immediate
        # any-step preemption response.
        if jax.process_count() == 1:
            preempt_now = self._preemption is not None \
                and self._preemption.requested
        else:
            if at_boundary:
                # the agreement allgather + the snapshot path's _sync
                # barriers must not race the step program's own gloo
                # ops (see _fence_step_program)
                self._fence_step_program()
            preempt_now = at_boundary and self._preempt_agreed()
        if preempt_now:
            self._preempt_finalize()
        elif at_boundary:
            self._begin_snapshot()

    def _preempt_agreed(self):
        """Cross-process preemption agreement (multi-process only,
        called at aligned interval boundaries): any rank's pending
        signal preempts the whole job; ranks that never saw the signal
        adopt it; and EVERY rank restarts its grace clock at the
        agreement point — per-rank clocks started at arbitrary signal
        arrivals, and a diverged (or already-expired) budget check
        would send ranks down mismatched barrier sequences, or skip
        the final snapshot entirely whenever the signal landed more
        than grace_secs before a boundary. The commit protocol makes a
        past-deadline attempt harmless (a SIGKILL mid-commit leaves
        the previous snapshot intact), so attempting is always the
        better branch; the budget bounds the snapshot WORK from
        here."""
        pre = self._preemption
        if pre is None:
            return False
        from jax.experimental import multihost_utils
        self._guard_enter("exchange", self.global_steps)
        try:
            flags = multihost_utils.process_allgather(  # sync-ok: boundary
                np.asarray([pre.requested], np.float64))   # agreement
        finally:
            self._guard_exit()
        agreed = bool(np.any(flags))
        if agreed:
            if not pre.requested:
                pre.request("peer")
            if (pre.remaining() or 0) <= 0:
                logger.warning(
                    "preemption signal predates this boundary by more "
                    "than the grace budget; attempting the final "
                    "snapshot anyway (commit is atomic)")
            pre.restart_clock()
        return agreed

    def _preempt_finalize(self):
        """Final snapshot inside the grace budget, then mark the engine
        preempted. When the budget is already spent, the snapshot is
        abandoned rather than half committed — the previous committed
        one stays ``latest`` (the manifest is the commit point). In
        the multi-process shape _preempt_agreed restarted every rank's
        clock at the same boundary, so this check cannot diverge
        across ranks."""
        pre = self._preemption
        pre.poll_event()   # the signal handler deferred its ring event
        snapshotted = False
        tag = None
        if (pre.remaining() or 0) > 0:
            try:
                tag = self._begin_snapshot(
                    tag=f"global_step{self.global_steps}_final")
                self._snapshotter.finalize()
                snapshotted = True
            except _faults.SimulatedCrash:
                raise
            except Exception as e:
                logger.warning(f"preemption snapshot failed: {e}")
                try:
                    self._snapshotter.abort("preempt_grace")
                except Exception:
                    pass
        else:
            logger.warning("preemption grace budget already spent; "
                           "keeping the previous snapshot")
        self.preempted = True
        self.stop_fault_tolerance()   # no further training: retire the
        #                               watchdog thread + heartbeat
        self.flight_recorder.record(
            "preempt", step=self.global_steps, snapshotted=snapshotted,
            tag=tag, source=pre.source, remaining_s=pre.remaining())
        if self.watchdog is not None:
            self.watchdog.note_preempt(
                step=self.global_steps, snapshotted=snapshotted,
                grace_s=pre.grace_s, source=pre.source)

    def finalize_pending_snapshot(self):
        """Clean-shutdown hook: commit a snapshot still in flight (a
        run whose last step began one would otherwise leave an
        uncommitted ``.saving`` orphan — harmless, resume clears it,
        but the snapshot itself is lost). Returns the committed dir or
        None."""
        if self._snapshotter is not None and self._snapshotter.in_flight:
            path, _ = self._snapshotter.finalize()
            return path
        return None

    def _maybe_auto_resume(self):
        """Startup auto-resume (once): when the snapshot block is on
        and a valid manifest exists under snapshot.path, adopt the
        newest valid snapshot before the first step."""
        sc = self._snap_cfg
        if not sc.enabled or not sc.auto_resume or self._auto_resumed:
            return
        self._auto_resumed = True
        if self.global_steps:
            return   # an explicit load_checkpoint already positioned us
        from deepspeed_tpu.runtime.elastic.resume import elastic_resume
        res = elastic_resume(self, sc.path)
        if res is not None:
            log_dist(f"auto-resumed from snapshot tag={res[0]} at "
                     f"step={self.global_steps}", ranks=[0])

    # ------------------------------------------------------------------
    # loss
    # ------------------------------------------------------------------
    def _init_params(self, x):
        """Initialize params born-sharded when ZeRO-3 is on (the zero.Init
        path, partition_parameters.py:265 analog) so the full model never
        materializes on one device; eager init otherwise."""
        if self.zero_optimization_stage() >= 3:
            try:
                from deepspeed_tpu.runtime.zero.init import sharded_init
                params, _ = sharded_init(
                    self.module, self._rng, x, self.mesh,
                    stage=self.zero_optimization_stage(),
                    tp_specs=self._param_tp_specs,
                    param_persistence_threshold=(
                        self._config.zero_config.param_persistence_threshold),
                    layer_stacked_prefixes=self.zero.layer_stacked_prefixes)
                return params
            except Exception as e:
                logger.warning(f"sharded init unavailable ({e}); "
                               f"falling back to eager init")
        variables = self.module.init(self._rng, x)
        return variables["params"] if "params" in variables else variables

    def _resolve_loss_fn(self) -> Callable:
        if self._loss_fn_user is not None:
            fn = self._loss_fn_user
            n = len(inspect.signature(fn).parameters)

            def user_loss(params, batch, rng, keep_prob):
                args = (params, batch, rng, keep_prob)[:n]
                return fn(*args)
            return user_loss

        model = self.module
        accepts_keep_prob = False
        accepts_deterministic = False
        fused_loss = False
        try:
            sig = inspect.signature(type(model).__call__)
            accepts_keep_prob = "keep_prob" in sig.parameters
            accepts_deterministic = "deterministic" in sig.parameters
            # models with a fused head+loss path (chunked cross entropy —
            # no [B, S, V] buffer) take `labels` and return the scalar loss
            fused_loss = "labels" in sig.parameters and \
                getattr(getattr(model, "config", None), "loss_chunk", 0) > 0
        except (TypeError, ValueError):
            pass
        has_dropout = getattr(getattr(model, "config", None), "dropout", 0.0) > 0
        model_cfg = getattr(model, "config", None)
        uses_moe = getattr(model_cfg, "moe_experts", 0) and \
            getattr(model_cfg, "moe_experts", 0) > 0
        moe_aux_coeff = float(getattr(model_cfg, "moe_aux_coeff", 0.01))

        def apply_model(params, inputs, kwargs):
            """Runs the model; when it carries MoE blocks, collect the sown
            load-balancing losses so the router actually trains balanced
            (the aux term of Switch/GShard)."""
            if uses_moe:
                out, vs = model.apply({"params": params}, inputs,
                                      mutable=["losses"], **kwargs)
                aux = sum(jnp.sum(l) for l in jax.tree_util.tree_leaves(
                    vs.get("losses", {})))
                return out, moe_aux_coeff * aux
            return model.apply({"params": params}, inputs, **kwargs), 0.0

        def default_loss(params, batch, rng, keep_prob):
            from deepspeed_tpu.models.gpt2 import lm_loss
            kwargs = {}
            if accepts_keep_prob:
                kwargs["keep_prob"] = keep_prob
            if accepts_deterministic:
                kwargs["deterministic"] = not has_dropout
            if has_dropout:
                kwargs["rngs"] = {"dropout": rng}
            if isinstance(batch, dict) and "input_ids" in batch:
                labels = batch.get("labels", batch["input_ids"])
                if fused_loss:
                    loss, aux = apply_model(params, batch["input_ids"],
                                            {**kwargs, "labels": labels})
                    return loss + aux
                logits, aux = apply_model(params, batch["input_ids"], kwargs)
                return lm_loss(logits, labels) + aux
            if isinstance(batch, (tuple, list)) and len(batch) == 2:
                x, y = batch
                out, aux = apply_model(params, x, kwargs)
                if jnp.issubdtype(jnp.asarray(y).dtype, jnp.integer):
                    logp = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
                    ll = jnp.take_along_axis(logp, y[..., None], axis=-1)
                    return -ll.mean() + aux
                return jnp.mean(jnp.square(out.astype(jnp.float32) -
                                           y.astype(jnp.float32))) + aux
            # bare array → LM on itself
            if fused_loss:
                loss, aux = apply_model(params, batch,
                                        {**kwargs, "labels": batch})
                return loss + aux
            logits, aux = apply_model(params, batch, kwargs)
            return lm_loss(logits, batch) + aux
        return default_loss

    # ------------------------------------------------------------------
    # jitted step construction
    # ------------------------------------------------------------------
    def _lr_fn(self):
        sched = self.lr_scheduler
        base_lr = getattr(self.optimizer, "lr", 1e-3)
        if sched is None:
            return lambda step: jnp.float32(base_lr)
        if isinstance(sched, _Schedule):
            return lambda step: sched.lr_at(step).astype(jnp.float32)
        if callable(sched):
            return lambda step: jnp.asarray(sched(step), jnp.float32)
        return lambda step: jnp.float32(base_lr)

    def _keep_prob_fn(self):
        pld = self.progressive_layer_drop
        if pld is None:
            return lambda step: jnp.float32(1.0)
        return lambda step: pld.theta_at(step)

    def _apply_grads(self, state, grads, loss):
        """Unscale, clip, step, scaler update — one fused update.

        The loss-scale inverse and clip coefficient are folded into ONE
        scalar passed to the optimizer's gradient read (`grad_scale`), so
        the full gradient tree is never re-materialized for unscaling or
        clipping (the reference does both as separate tensor passes,
        fused_optimizer.py:194-246)."""
        cfg = self._config
        scale = state.scaler["loss_scale"]
        inv = 1.0 / scale
        finite = prec.grads_finite(grads) if self.precision.fp16 \
            else jnp.asarray(True)

        # one read-only pass: norm of the RAW (still loss-scaled) grads
        grad_norm = _global_norm(grads) * inv
        gscale = inv
        if cfg.gradient_clipping and cfg.gradient_clipping > 0:
            gscale = inv * jnp.minimum(
                1.0, cfg.gradient_clipping / (grad_norm + 1e-6))

        lr = self._lr_fn()(state.global_step)
        params = state.params
        if self._param_offload_host:
            # param offload tier: stream host-resident params to HBM for
            # the update (compute ops cannot mix memory spaces)
            params = jax.device_put(
                params, self.zero.device_param_shardings(params))
        if "grad_scale" in inspect.signature(
                self.optimizer.step).parameters:
            new_params, new_opt = self.optimizer.step(
                params, grads, state.opt_state, lr, grad_scale=gscale)
        else:
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) * gscale, grads)
            new_params, new_opt = self.optimizer.step(params, grads,
                                                      state.opt_state, lr)
        # skip-on-overflow (reference fused_optimizer.py:194-246); done
        # before moving back so both branches live in device memory
        new_params = _tree_where(finite, new_params, params)
        new_opt = _tree_where(finite, new_opt, state.opt_state)
        if self._param_offload_host:
            new_params = jax.device_put(
                new_params, self.zero.param_shardings(new_params))
        else:
            # constrain updated params back to their resting sharding (the
            # stage-1/2 all-gather of updated partitions, stage2.py:~1470)
            new_params = jax.tree_util.tree_map(
                lambda p, s: jax.lax.with_sharding_constraint(p, s),
                new_params, self.zero.param_shardings(new_params))
        new_scaler = prec.update_scaler(state.scaler, self.precision, finite)
        return TrainState(
            params=new_params,
            opt_state=new_opt,
            scaler=new_scaler,
            global_step=state.global_step + finite.astype(jnp.int32),
            skipped_steps=state.skipped_steps + (~finite).astype(jnp.int32),
        ), {"loss": loss, "grad_norm": grad_norm, "lr": lr,
            "overflow": ~finite, "loss_scale": new_scaler["loss_scale"]}

    def _pinned(self, jitted):
        """Run a GSPMD-jitted engine program with the models' layout pins
        scoped to THIS engine's mesh (mesh_lib.layout_pins): the pins
        must never read the ambient registry — it outlives engines, and
        a trace in another context constraining to a stale foreign-device
        mesh crashes GSPMD. Python-call scoping survives however jax
        re-traces custom_vjp backwards. `lower` passes through for
        train_step_memory_stats."""
        mesh = self.mesh

        def call(*args, **kwargs):
            with mesh_lib.layout_pins(mesh):
                return jitted(*args, **kwargs)

        def lower(*args, **kwargs):
            with mesh_lib.layout_pins(mesh):
                return jitted.lower(*args, **kwargs)
        call.lower = lower
        return call

    def _build_jit_fns(self):
        loss_fn = self._resolve_loss_fn()
        gas = self.gradient_accumulation_steps()
        batch_sh = mesh_lib.batch_sharding(self.mesh)
        repl = NamedSharding(self.mesh, PartitionSpec())

        def accumulate_grads(state, batch, rng):
            if gas == 1:
                # no accumulation: skip the scan and the fp32 zero-buffer
                # init+add pass entirely (one full extra read/write of the
                # gradient tree per step otherwise)
                batch = jax.tree_util.tree_map(
                    lambda x: jax.lax.with_sharding_constraint(x, batch_sh),
                    batch)
                with annotate("ds_fwd_bwd"):
                    loss, grads = self._micro_loss_and_grads(
                        state, batch, rng, loss_fn=loss_fn)
                return grads, loss
            # batch leading dim = gas * micro_global; scan over gas chunks
            def to_chunks(x):
                assert x.shape[0] % gas == 0, (
                    f"train_batch got leading dim {x.shape[0]} not divisible "
                    f"by gradient_accumulation_steps={gas}; pass a global "
                    f"batch of micro*gas samples or use forward/backward/step")
                return x.reshape((gas, x.shape[0] // gas) + x.shape[1:])
            chunked = jax.tree_util.tree_map(to_chunks, batch)
            rngs = jax.random.split(rng, gas)

            acc_dtype = jnp.bfloat16 \
                if self._config.grad_accum_dtype == "bf16" else jnp.float32

            def micro(acc, inp):
                micro_batch, r = inp
                micro_batch = jax.tree_util.tree_map(
                    lambda x: jax.lax.with_sharding_constraint(x, batch_sh),
                    micro_batch)
                with annotate("ds_fwd_bwd"):
                    loss, grads = self._micro_loss_and_grads(
                        state, micro_batch, r, loss_fn=loss_fn)
                acc_g, acc_l = acc
                acc_g = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(acc_dtype) / gas, acc_g, grads)
                return (acc_g, acc_l + loss / gas), None

            zero_g = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, acc_dtype), state.params)
            zero_g = self.zero.constrain_grads(zero_g)
            (grads, loss), _ = jax.lax.scan(micro, (zero_g, jnp.float32(0.0)),
                                            (chunked, rngs))
            return grads, loss

        def train_batch_fn(state, batch, rng):
            grads, loss = accumulate_grads(state, batch, rng)
            with annotate("ds_optimizer"):
                return self._apply_grads(state, grads, loss)

        def grads_batch_fn(state, batch, rng):
            # offload path: grads stay on device; host applies the step.
            # finiteness + norm are computed here so the host only pulls two
            # scalars instead of re-scanning every leaf
            grads, loss = accumulate_grads(state, batch, rng)
            finite = prec.grads_finite(grads) if self.precision.fp16 \
                else jnp.asarray(True)
            return grads, loss, finite, _global_norm(grads)

        self._jit_grads_batch = self._pinned(jax.jit(grads_batch_fn))

        def micro_grads_fn(state, batch, rng):
            batch = jax.tree_util.tree_map(
                lambda x: jax.lax.with_sharding_constraint(x, batch_sh), batch)
            loss, grads = self._micro_loss_and_grads(state, batch, rng,
                                                     loss_fn=loss_fn)
            return loss, grads

        def apply_grads_fn(state, grads, loss):
            with annotate("ds_optimizer"):
                return self._apply_grads(state, grads, loss)

        self._jit_train_batch = self._pinned(
            jax.jit(train_batch_fn, donate_argnums=(0,)))
        self._jit_micro_grads = self._pinned(jax.jit(micro_grads_fn))
        self._jit_apply_grads = self._pinned(
            jax.jit(apply_grads_fn, donate_argnums=(0, 1)))

        def loss_batch_fn(state, batch, rng):
            # forward-only twin of accumulate_grads, for the
            # wall_clock_breakdown forward-phase measurement
            if gas == 1:
                b = jax.tree_util.tree_map(
                    lambda x: jax.lax.with_sharding_constraint(x, batch_sh),
                    batch)
                return self._micro_loss(state, b, rng, loss_fn=loss_fn)
            chunked = jax.tree_util.tree_map(
                lambda x: x.reshape((gas, x.shape[0] // gas) + x.shape[1:]),
                batch)
            rngs = jax.random.split(rng, gas)

            def micro(acc, inp):
                b, r = inp
                b = jax.tree_util.tree_map(
                    lambda x: jax.lax.with_sharding_constraint(x, batch_sh), b)
                return acc + self._micro_loss(state, b, r,
                                              loss_fn=loss_fn) / gas, None
            total, _ = jax.lax.scan(micro, jnp.float32(0.0), (chunked, rngs))
            return total
        self._jit_loss_batch = self._pinned(jax.jit(loss_batch_fn))
        if self._compressed_comm_active():
            self._jit_train_batch = self._build_compressed_train_fn(loss_fn)
        elif self._sparse_grad_active():
            self._jit_train_batch = self._build_sparse_train_fn(loss_fn)
        elif self._prefetch_active():
            self._jit_train_batch = self._build_prefetch_train_fn()
        elif self._overlap_comm_active():
            self._jit_train_batch = self._build_overlap_train_fn(loss_fn)
        if not self._prefetch_active():
            # the live-gathered registry describes the most recently
            # BUILT train path; a non-prefetch engine must not inherit
            # a previous engine's prefetch window in see_memory_usage
            from deepspeed_tpu.utils import memory as memory_lib
            memory_lib.record_live_gathered_param_bytes(None)

        try:
            accepts_det = "deterministic" in inspect.signature(
                type(self.module).__call__).parameters
        except (TypeError, ValueError):
            accepts_det = False

        try:
            accepts_inference = "inference" in inspect.signature(
                self.module.apply).parameters
        except (TypeError, ValueError, AttributeError):
            accepts_inference = False

        def eval_fn(state, x):
            x = jax.lax.with_sharding_constraint(x, batch_sh)
            params = state.params
            if self._param_offload_host:
                params = jax.device_put(
                    params, self.zero.device_param_shardings(params))
            kwargs = {}
            if accepts_inference:
                # pipeline modules: run the forward-only InferenceSchedule
                # program instead of the differentiable 1F1B primal
                kwargs["inference"] = True
            if accepts_det:
                kwargs["deterministic"] = True
            return self.module.apply({"params": params}, x, **kwargs)
        self._jit_eval = self._pinned(jax.jit(eval_fn))
        self._last_lr = None

    def _local_grad_accumulator(self, loss_fn, axis):
        """Shared scaffold for the explicit-comm (shard_map) train paths
        (1-bit compressed, row-sparse): per-device rng folding and local
        gradient accumulation over gas microbatches — grads come back
        LOCAL to the data shard, in fp32, loss averaged locally."""
        gas = self.gradient_accumulation_steps()
        keep_fn = self._keep_prob_fn()

        def accumulate(state, batch, rng):
            tm = jax.tree_util.tree_map
            rng = jax.random.fold_in(rng, mesh_lib.linear_axis_index(axis))
            scale = state.scaler["loss_scale"]
            keep_prob = keep_fn(state.global_step)

            def micro_grads(micro, r):
                def scaled(p):
                    loss = loss_fn(p, micro, r, keep_prob)
                    return (loss * scale).astype(jnp.float32), loss
                return jax.grad(scaled, has_aux=True)(state.params)

            if gas == 1:
                grads, loss = micro_grads(batch, rng)
                grads = tm(lambda g: g.astype(jnp.float32), grads)
            else:
                chunked = tm(lambda x: x.reshape(
                    (gas, x.shape[0] // gas) + x.shape[1:]), batch)
                rngs = jax.random.split(rng, gas)

                def body(acc, inp):
                    micro, r = inp
                    g, l = micro_grads(micro, r)
                    acc_g, acc_l = acc
                    return (tm(lambda a, gg: a + gg.astype(jnp.float32)
                               / gas, acc_g, g), acc_l + l / gas), None
                zero_g = tm(lambda p: jnp.zeros(p.shape, jnp.float32),
                            state.params)
                (grads, loss), _ = jax.lax.scan(
                    body, (zero_g, jnp.float32(0.0)), (chunked, rngs))
            return grads, loss

        return accumulate

    @staticmethod
    def _finish_explicit_state(state, new_params, new_opt, finite,
                               precision):
        """Overflow-skip + scaler/counter epilogue shared by the explicit-
        comm train paths (mirrors _apply_grads' tail)."""
        new_params = _tree_where(finite, new_params, state.params)
        new_opt = _tree_where(finite, new_opt, state.opt_state)
        new_scaler = prec.update_scaler(state.scaler, precision, finite)
        return TrainState(
            params=new_params, opt_state=new_opt, scaler=new_scaler,
            global_step=state.global_step + finite.astype(jnp.int32),
            skipped_steps=state.skipped_steps + (~finite).astype(jnp.int32))

    def _build_compressed_train_fn(self, loss_fn):
        """shard_map train step for 1-bit optimizers: grads stay LOCAL to
        each data shard (no GSPMD psum), the optimizer's step_local runs the
        warmup pmean / compressed momentum collective itself (the
        reference's compressed_allreduce replacing the engine allreduce,
        comm/nccl.py:47). Params replicated; error-feedback state per-device
        with a leading [dp] axis.

        With comm.hierarchy resolved (ISSUE 10) the program shard_maps a
        data-axis-split view of the same mesh ((data_inter, data_intra) —
        metadata-only reshard) and the optimizer runs the link-aware
        bucketed exchange: fast-axis hops uncompressed, slow-axis hops
        sign-packed per the per-bucket policy."""
        plan = self._comm_plan()
        if plan is not None:
            mesh = mesh_lib.split_data_axis(self.mesh, plan.inter)
            axis = plan.axes
            self._install_comm_wire_model(plan)
        else:
            mesh = self.mesh
            axis = mesh_lib.DATA_AXIS
        cfg = self._config
        state = self.state
        lr_fn = self._lr_fn()
        opt = self.optimizer
        precision = self.precision
        accumulate = self._local_grad_accumulator(loss_fn, axis)
        spec_like = lambda tree, s: jax.tree_util.tree_map(  # noqa: E731
            lambda _: s, tree)

        opt_specs = {
            k: spec_like(v, PartitionSpec(axis))
            if k in ("worker_error", "server_error") else
            spec_like(v, PartitionSpec())
            for k, v in state.opt_state.items()}
        state_specs = TrainState(
            params=spec_like(state.params, PartitionSpec()),
            opt_state=opt_specs,
            scaler=spec_like(state.scaler, PartitionSpec()),
            global_step=PartitionSpec(),
            skipped_steps=PartitionSpec())

        def train_fn(state, batch, rng):
            batch_specs = spec_like(batch, PartitionSpec(axis))

            @functools.partial(
                mesh_lib.shard_map, mesh=mesh,
                in_specs=(state_specs, batch_specs, PartitionSpec()),
                out_specs=(state_specs, spec_like(
                    {"loss": 0, "grad_norm": 0, "lr": 0, "overflow": 0,
                     "loss_scale": 0}, PartitionSpec())),
                check_vma=False)
            def inner(state, batch, rng):
                tm = jax.tree_util.tree_map
                # per-device dropout streams over distinct data shards
                grads, loss = accumulate(state, batch, rng)
                scale = state.scaler["loss_scale"]

                inv = 1.0 / scale
                grads = tm(lambda g: g * inv, grads)
                loss = jax.lax.pmean(loss, axis)
                local_finite = prec.grads_finite(grads) if precision.fp16 \
                    else jnp.asarray(True)
                finite = jax.lax.pmin(
                    local_finite.astype(jnp.int32), axis) > 0
                # metrics-only norm: mean of the local-shard grad norms
                # (the exact global norm would need an uncompressed
                # collective, defeating the compression)
                grad_norm = jax.lax.pmean(_global_norm(grads), axis)

                opt_local = dict(state.opt_state)
                for key in ("worker_error", "server_error"):
                    opt_local[key] = tm(lambda x: x[0], opt_local[key])

                lr = lr_fn(state.global_step)
                clip = cfg.gradient_clipping or None
                new_params, new_opt = opt.step_local(
                    state.params, grads, opt_local, lr, axis, clip=clip,
                    comm=plan)

                for key in ("worker_error", "server_error"):
                    new_opt[key] = tm(lambda x: x[None], new_opt[key])

                new_state = self._finish_explicit_state(
                    state, new_params, new_opt, finite, precision)
                return new_state, {
                    "loss": loss, "grad_norm": grad_norm, "lr": lr,
                    "overflow": ~finite,
                    "loss_scale": new_state.scaler["loss_scale"]}

            return inner(state, batch, rng)

        return self._jit_explicit_comm(train_fn)

    def _jit_explicit_comm(self, train_fn):
        """jit an explicit-comm (shard_map) train program with the models'
        GSPMD layout pins disabled for its traces (see
        mesh_lib.no_layout_pins — inside shard_map the pins poison avals
        with foreign-mesh shardings). The wrapper keeps the jitted fn's
        `lower` (train_step_memory_stats uses it), entering the same
        pin-free mode so an explicit lowering doesn't re-poison."""
        jitted = jax.jit(train_fn, donate_argnums=(0,))

        def call(state, batch, rng):
            with mesh_lib.no_layout_pins():
                return jitted(state, batch, rng)

        def lower(*args, **kwargs):
            with mesh_lib.no_layout_pins():
                return jitted.lower(*args, **kwargs)
        call.lower = lower
        return call

    def _overlap_comm_active(self):
        """True when the train step should run the bucketed gradient-sync
        scheduler (parallel/overlap.py): the explicit-comm train path whose
        per-bucket ring reduce-scatter/all-gather XLA can float over
        backward compute — the reference's `overlap_comm` + IPG buckets
        (stage2.py:614-746). Requires a multi-device pure-DP data axis and
        an elementwise optimizer (the per-shard ZeRO update slices param
        tensors)."""
        cached = getattr(self, "_overlap_comm_cached", None)
        if cached is None:
            cached = self._overlap_comm_cached = self._compute_overlap_comm()
        return cached

    def _compute_overlap_comm(self):
        zc = self._config.zero_config
        if not zc.overlap_comm:
            return False
        if self._offload_cfg.enabled or self._param_offload_host or \
                self._param_offload_nvme:
            # overlap_comm keeps its offload meaning there: per-microbatch
            # d2h gradient streaming (_host_offload_step_overlapped)
            return False
        if self._compressed_comm_active() or self._sparse_grad_active():
            return False
        if mesh_lib.mesh_axis_size(self.mesh, mesh_lib.DATA_AXIS) <= 1:
            return False
        if not self._pure_dp_mesh():
            log_dist("overlap_comm: non-data mesh axes are live — the "
                     "explicit bucket scheduler shard_maps the data axis "
                     "only; falling back to the fused GSPMD exchange",
                     ranks=[0])
            return False
        if self.zero_optimization_stage() >= 3:
            log_dist("overlap_comm supports ZeRO stages 0-2 (stage 3 "
                     "shards params at rest, which the explicit path does "
                     "not re-gather) — for the stage-3 explicit path set "
                     "zero_optimization.stage3_prefetch; falling back to "
                     "the fused GSPMD exchange", ranks=[0])
            return False
        if not getattr(self.optimizer, "elementwise_update", False):
            log_dist(f"overlap_comm needs an elementwise optimizer "
                     f"(Adam/AdamW/SGD) — the per-shard ZeRO update slices "
                     f"tensors, which breaks per-tensor statistics of "
                     f"{type(self.optimizer).__name__}; falling back to "
                     f"the fused GSPMD exchange", ranks=[0])
            return False
        return True

    def _build_overlap_train_fn(self, loss_fn):
        """shard_map train step with the bucketed gradient-sync scheduler:
        grads stay LOCAL to each data shard through backward, then sync as
        a stream of per-bucket ring reduce-scatter + all-gather programs
        (parallel/overlap.py) instead of one implicit monolithic psum.
        ZeRO stage-1/2 semantics are explicit: optimizer moments keep their
        resting sharded layout (each device updates only its param slice)
        and updated slices all-gather back — stage2.py's partition update +
        param all-gather, with the exchange XLA can schedule early."""
        from deepspeed_tpu.parallel import overlap as overlap_lib
        mesh = self.mesh
        axis = mesh_lib.DATA_AXIS
        cfg = self._config
        zc = cfg.zero_config
        n = mesh_lib.mesh_axis_size(mesh, axis)
        lr_fn = self._lr_fn()
        opt = self.optimizer
        precision = self.precision
        accumulate = self._local_grad_accumulator(loss_fn, axis)
        bucket_elems = int(zc.reduce_bucket_size)
        mode = zc.overlap_reduce
        spec_like = lambda tree, s: jax.tree_util.tree_map(  # noqa: E731
            lambda _: s, tree)

        params = self.state.params
        plan = self.zero.explicit_shard_plan(params)
        moment_specs = self.zero.opt_param_like_specs(params)
        param_like = getattr(opt, "param_like_state_fields", ())
        opt_specs = {
            k: moment_specs if k in param_like else spec_like(
                v, PartitionSpec())
            for k, v in self.state.opt_state.items()}
        state_specs = TrainState(
            params=spec_like(params, PartitionSpec()),
            opt_state=opt_specs,
            scaler=spec_like(self.state.scaler, PartitionSpec()),
            global_step=PartitionSpec(),
            skipped_steps=PartitionSpec())
        takes_gscale = "grad_scale" in inspect.signature(opt.step).parameters

        def train_fn(state, batch, rng):
            batch_specs = spec_like(batch, PartitionSpec(axis))

            @functools.partial(
                mesh_lib.shard_map, mesh=mesh,
                in_specs=(state_specs, batch_specs, PartitionSpec()),
                out_specs=(state_specs, spec_like(
                    {"loss": 0, "grad_norm": 0, "lr": 0, "overflow": 0,
                     "loss_scale": 0}, PartitionSpec())),
                check_vma=False)
            def inner(state, batch, rng):
                tm = jax.tree_util.tree_map
                with annotate("ds_fwd_bwd"):
                    grads, loss = accumulate(state, batch, rng)
                # the bucket stream — mean-reduced full grads on every
                # device (identical across the axis afterwards)
                with annotate("ds_overlap_bucket_sync"):
                    grads = overlap_lib.bucketed_allreduce(
                        grads, axis, n, bucket_elems, mode=mode, mean=True)
                loss = jax.lax.pmean(loss, axis)
                scale = state.scaler["loss_scale"]
                inv = 1.0 / scale
                finite = prec.grads_finite(grads) if precision.fp16 \
                    else jnp.asarray(True)
                grad_norm = _global_norm(grads)
                gscale = inv
                if cfg.gradient_clipping and cfg.gradient_clipping > 0:
                    gscale = inv * jnp.minimum(
                        1.0, cfg.gradient_clipping /
                        (grad_norm * inv + 1e-6))
                lr = lr_fn(state.global_step)

                # per-shard ZeRO update: slice each leaf to the moment
                # shard this device owns, step, gather the slices back
                idx = jax.lax.axis_index(axis)
                p_leaves, tdef = jax.tree_util.tree_flatten(state.params)
                g_leaves = jax.tree_util.tree_leaves(grads)

                def shard_leaf(x, entry):
                    if entry is None:
                        return x
                    d, sz = entry
                    return jax.lax.dynamic_slice_in_dim(x, idx * sz, sz, d)

                p_loc = jax.tree_util.tree_unflatten(
                    tdef, [shard_leaf(x, e) for x, e in zip(p_leaves, plan)])
                g_loc = jax.tree_util.tree_unflatten(
                    tdef, [shard_leaf(x, e) for x, e in zip(g_leaves, plan)])
                with annotate("ds_optimizer"):
                    if takes_gscale:
                        new_p_loc, new_opt = opt.step(
                            p_loc, g_loc, state.opt_state, lr,
                            grad_scale=gscale)
                    else:
                        g_loc = tm(lambda g: g * gscale, g_loc)
                        new_p_loc, new_opt = opt.step(p_loc, g_loc,
                                                      state.opt_state, lr)

                def gather_leaf(x, entry):
                    if entry is None:
                        return x
                    d, _ = entry
                    return jax.lax.all_gather(x, axis, axis=d, tiled=True)

                with annotate("ds_param_allgather"):
                    new_params = jax.tree_util.tree_unflatten(
                        tdef, [gather_leaf(x, e) for x, e in
                               zip(jax.tree_util.tree_leaves(new_p_loc),
                                   plan)])
                new_state = self._finish_explicit_state(
                    state, new_params, new_opt, finite, precision)
                return new_state, {
                    "loss": loss, "grad_norm": grad_norm * inv, "lr": lr,
                    "overflow": ~finite,
                    "loss_scale": new_state.scaler["loss_scale"]}

            return inner(state, batch, rng)

        return self._jit_explicit_comm(train_fn)

    def _prefetch_active(self):
        """True when the train step should run the ZeRO-3 layer-wise
        parameter-gather prefetch pipeline (parallel/prefetch.py): the
        explicit-comm stage-3 train path that all-gathers each layer's
        param shards ONE LAYER AHEAD of use (double-buffered, forward
        and backward) and reduce-scatters each layer's param grads
        inside the backward scan — the reference's
        PartitionedParameterCoordinator prefetch (stage3.py:287-447)
        made structural. Requires a multi-device pure-DP data axis, an
        elementwise optimizer, and a model exposing the layered-apply
        contract (prefetch_apply + prefetch_layer_subtree)."""
        cached = getattr(self, "_prefetch_cached", None)
        if cached is None:
            cached = self._prefetch_cached = self._compute_prefetch()
        return cached

    def _compute_prefetch(self):
        zc = self._config.zero_config
        if not zc.stage3_prefetch:
            return False
        if self._offload_cfg.enabled or self._param_offload_host:
            # the NVMe param tier COMPOSES (its swap schedule streams
            # disk→host→device before the step; the in-jit pipeline then
            # gathers layer by layer) — but the optimizer-offload and
            # pinned-host tiers run the step off-device/off-schedule
            log_dist("stage3_prefetch: optimizer/pinned-host offload "
                     "tiers stream state through host memory on their own "
                     "schedule; falling back to the fused GSPMD stage-3 "
                     "exchange", ranks=[0])
            return False
        if self._compressed_comm_active() or self._sparse_grad_active():
            return False
        if mesh_lib.mesh_axis_size(self.mesh, mesh_lib.DATA_AXIS) <= 1:
            log_dist("stage3_prefetch: single-device data axis — nothing "
                     "is sharded, the fused path is the whole program",
                     ranks=[0])
            return False
        if not self._pure_dp_mesh():
            log_dist("stage3_prefetch: non-data mesh axes are live — the "
                     "prefetch pipeline shard_maps the data axis only; "
                     "falling back to the fused GSPMD exchange", ranks=[0])
            return False
        sub = getattr(self.module, "prefetch_layer_subtree", None)
        if not sub or not hasattr(self.module, "prefetch_apply"):
            log_dist(f"stage3_prefetch: {type(self.module).__name__} does "
                     f"not expose the layered-apply contract "
                     f"(prefetch_apply + a non-None prefetch_layer_subtree "
                     f"— scanned layers, no MoE, no dropout); falling back "
                     f"to the fused GSPMD exchange", ranks=[0])
            return False
        if self._loss_fn_user is not None:
            log_dist("stage3_prefetch: a custom loss_fn drives model.apply "
                     "itself, which the layered pipeline cannot intercept; "
                     "falling back to the fused GSPMD exchange", ranks=[0])
            return False
        if not getattr(self.optimizer, "elementwise_update", False):
            log_dist(f"stage3_prefetch needs an elementwise optimizer "
                     f"(Adam/AdamW/SGD) — the per-shard ZeRO-3 update "
                     f"slices tensors, which breaks per-tensor statistics "
                     f"of {type(self.optimizer).__name__}; falling back to "
                     f"the fused GSPMD exchange", ranks=[0])
            return False
        return True

    def prefetch_live_param_stats(self):
        """Static live-parameter accounting of the prefetch pipeline
        (populated when the stage3_prefetch train path is built): peak
        gathered-full-parameter elements/bytes — two layers (current +
        in-flight) plus the step-persistent outer gathers — the
        observable behind ``stage3_max_live_parameters``. None when the
        prefetch path is not active/built."""
        return getattr(self, "_prefetch_stats", None)

    def _build_prefetch_train_fn(self):
        """shard_map train step for ZeRO-3 with layer-wise gather
        prefetch: params/moments stay SHARDED through the whole step
        (in_specs = out_specs = the stage-3 resting specs — no
        gather-at-entry, no re-shard at exit). The forward/backward run
        through parallel/prefetch.make_prefetched_scan (double-buffered
        per-layer gathers; backward interleaves each layer's re-gather
        with its grad reduce-scatter); outer leaves (embeddings, final
        LN, head) gather once per step via gathered-param custom VJPs;
        below-threshold replicated leaves exchange through the PR-1
        bucketed allreduce (overlap_comm's machinery) — composing both
        explicit schedulers in one program.

        With ``comm.hierarchy`` resolved (ISSUE 16) the program
        shard_maps the data-axis-split view of the same mesh and every
        stage-3 exchange runs the two-level link-aware schedule: packed
        per-layer gathers and grad reduce-scatters take ONE inter-host
        hop per chunk (fp32 partial sums stay on the fast links), the
        per-bucket policy compresses the slow grad hops to
        error-compensated sign bits, and the persistent residuals
        thread through the step as ``pf_*`` opt_state (see
        `_prefetch_error_states`)."""
        from deepspeed_tpu.parallel import overlap as overlap_lib
        from deepspeed_tpu.parallel import prefetch as prefetch_lib
        cfg = self._config
        zc = cfg.zero_config
        n = mesh_lib.mesh_axis_size(self.mesh, mesh_lib.DATA_AXIS)
        hplan = self._prefetch_hier_plan()
        if hplan is not None:
            # metadata-only reshard: same devices, the data axis viewed
            # as (inter, intra) so the two-level collectives can bind
            # each level by name
            mesh = mesh_lib.split_data_axis(self.mesh, hplan.inter)
            axis = hplan.axes
        else:
            mesh = self.mesh
            axis = mesh_lib.DATA_AXIS
        lr_fn = self._lr_fn()
        opt = self.optimizer
        precision = self.precision
        model = self.module
        subtree = model.prefetch_layer_subtree
        mode = zc.stage3_prefetch_gather
        cast_bf16 = cfg.grad_dtype == "bf16"
        bucket_elems = int(zc.prefetch_bucket_size)
        spec_like = lambda tree, s: jax.tree_util.tree_map(  # noqa: E731
            lambda _: s, tree)
        tm = jax.tree_util.tree_map

        params = self.state.params
        param_spec_tree = self.zero.param_specs(params)
        full_plan = self.zero.explicit_shard_plan(params,
                                                  specs=param_spec_tree)
        layer_plan = self.zero.explicit_shard_plan(
            params[subtree], specs=param_spec_tree[subtree])
        outer_keys = [k for k in params if k != subtree]
        outer_plans = {k: self.zero.explicit_shard_plan(
            params[k], specs=param_spec_tree[k]) for k in outer_keys}

        fused_ids, fused_cfg = self._select_fused_matmul_leaves(
            params[subtree], layer_plan, mode, n, axis, cast_bf16)

        self._record_prefetch_stats(params, subtree, layer_plan,
                                    outer_plans, cast_bf16,
                                    fused_ids=fused_ids)

        if hplan is not None:
            # shard_map specs on the split mesh spell the data axis as
            # the (inter, intra) pair; the device layout is unchanged
            def _resplit_spec(s):
                return PartitionSpec(*(
                    (hplan.inter_axis, hplan.intra_axis)
                    if p == mesh_lib.DATA_AXIS else p
                    for p in tuple(s)))
            sm_param_specs = tm(_resplit_spec, param_spec_tree)
            self._install_prefetch_wire_model(hplan, params, fused_ids,
                                              cast_bf16)
        else:
            sm_param_specs = param_spec_tree

        def gather_outer(p, oerrs=None):
            out = {}
            with annotate("ds_prefetch_outer_gather"):
                for k in outer_keys:
                    leaves, tdef = jax.tree_util.tree_flatten(p[k])
                    errs_k = oerrs[k] if oerrs is not None else \
                        [None] * len(leaves)
                    gathered = []
                    for x, e, er in zip(leaves, outer_plans[k], errs_k):
                        if e is None:
                            gathered.append(x)
                        elif er is not None:
                            # compressed slow-hop RS in the backward;
                            # the new residual returns as er's cotangent
                            gathered.append(
                                prefetch_lib.make_gathered_param_with_error(
                                    e, axis, n, mode, hplan)(x, er))
                        else:
                            gathered.append(
                                prefetch_lib.make_gathered_param(
                                    e, axis, n, mode, hier=hplan)(x))
                    out[k] = jax.tree_util.tree_unflatten(tdef, gathered)
            return out

        def micro_loss(p_view, micro, keep_prob, gerrs=None):
            # the model builds the per-layer body (it closes over
            # keep_prob) and hands it in through the layer_scan hook
            def run_layers(body, x, h_shards):
                fn = prefetch_lib.make_prefetched_scan(
                    body, layer_plan, axis, n, mode=mode,
                    fused_ids=fused_ids, fused_cfg=fused_cfg, hier=hplan)
                return fn(x, h_shards) if hplan is None \
                    else fn(x, h_shards, gerrs)
            if isinstance(micro, dict) and "input_ids" in micro:
                ids = micro["input_ids"]
                labels = micro.get("labels", micro["input_ids"])
            else:
                ids = micro
                labels = micro
            return model.prefetch_apply(p_view, ids, run_layers,
                                        deterministic=True,
                                        keep_prob=keep_prob, labels=labels)

        gas = self.gradient_accumulation_steps()
        keep_fn = self._keep_prob_fn()

        def cast_params(p):
            if not cast_bf16:
                return p
            return tm(lambda x: x.astype(jnp.bfloat16)
                      if x.dtype == jnp.float32 else x, p)

        def accumulate(state, batch, rng, perr=None):
            """Prefetch-path twin of _local_grad_accumulator. Dropout
            is gated off, so no per-micro rng plumbing; grads come back
            fp32 (sharded leaves as SUMS over the axis), loss locally
            averaged. ``perr`` (hierarchical path) carries the
            compressed slow hops' persistent residuals
            ({"groups": ..., "outer": ...}); the updated state returns
            as the third result — read back through ``jax.grad`` extra
            argnums, since the exchanges live inside custom VJPs.

            gas == 1 differentiates straight through the gather custom
            VJPs. gas > 1 hoists the OUTER gathers above the microbatch
            scan — wte/wpe/head gather once per STEP — and runs the
            per-micro ``jax.grad`` with the gathered view as an
            EXPLICIT argument (grad-inside-scan: a custom-VJP call on a
            tracer closed over INTO a differentiated scan would need
            the unsupported custom_vjp transpose). Outer cotangents
            accumulate in gathered space and reduce-scatter ONCE at the
            end; only the per-layer pipeline (whose per-micro exchange
            is the point) communicates inside the scan — group
            residuals therefore thread through the microbatch carry,
            outer residuals update once at the final reduce-scatter."""
            del rng
            scale = state.scaler["loss_scale"]
            keep_prob = keep_fn(state.global_step)

            if gas == 1:
                if hplan is None:
                    def total(p_shard):
                        p = cast_params(p_shard)
                        p_view = gather_outer(p)
                        p_view[subtree] = p[subtree]
                        loss = micro_loss(p_view, batch, keep_prob)
                        return (loss * scale).astype(jnp.float32), loss
                    grads, loss = jax.grad(total, has_aux=True)(
                        state.params)
                    return (tm(lambda g: g.astype(jnp.float32), grads),
                            loss, None)

                def total(p_shard, pe):
                    p = cast_params(p_shard)
                    p_view = gather_outer(p, pe["outer"])
                    p_view[subtree] = p[subtree]
                    loss = micro_loss(p_view, batch, keep_prob,
                                      pe["groups"])
                    return (loss * scale).astype(jnp.float32), loss
                (grads, new_perr), loss = jax.grad(
                    total, argnums=(0, 1), has_aux=True)(state.params,
                                                         perr)
                return (tm(lambda g: g.astype(jnp.float32), grads),
                        loss, new_perr)

            p = cast_params(state.params)
            outer_view = {}
            for k in outer_keys:
                leaves, tdef = jax.tree_util.tree_flatten(p[k])
                outer_view[k] = jax.tree_util.tree_unflatten(tdef, [
                    prefetch_lib.gather_leaf(x, e, axis, n, mode,
                                             hier=hplan)
                    for x, e in zip(leaves, outer_plans[k])])
            h_shards = p[subtree]

            def micro_grads(view, hs, ge, micro):
                def f(v, h, e):
                    pv = dict(v)
                    pv[subtree] = h
                    loss = micro_loss(pv, micro, keep_prob, e)
                    return (loss * scale).astype(jnp.float32), loss
                if hplan is None:
                    (gv, gh), loss = jax.grad(
                        f, argnums=(0, 1), has_aux=True)(view, hs, ge)
                    return (gv, gh, ge), loss
                return jax.grad(f, argnums=(0, 1, 2), has_aux=True)(
                    view, hs, ge)

            chunked = tm(lambda x: x.reshape(
                (gas, x.shape[0] // gas) + x.shape[1:]), batch)

            def body(acc, micro):
                acc_v, acc_h, acc_l, ge = acc
                (gv, gh, ge2), loss = micro_grads(outer_view, h_shards,
                                                  ge, micro)
                add = lambda a, g: a + g.astype(jnp.float32) / gas  # noqa: E731
                return (tm(add, acc_v, gv), tm(add, acc_h, gh),
                        acc_l + loss / gas, ge2), None

            zeros = lambda t: tm(  # noqa: E731
                lambda x: jnp.zeros(x.shape, jnp.float32), t)
            ge0 = perr["groups"] if hplan is not None else ()
            (g_view, g_h, loss, ge_fin), _ = jax.lax.scan(
                body, (zeros(outer_view), zeros(h_shards),
                       jnp.float32(0.0), ge0), chunked)

            # manual outer backward: the accumulated gathered-space
            # cotangents reduce-scatter once (SUM over the axis, like
            # the gas==1 custom-VJP path); replicated leaves stay local
            grads = {subtree: g_h}
            new_oerrs = {}
            for k in outer_keys:
                leaves, tdef = jax.tree_util.tree_flatten(g_view[k])
                errs_k = perr["outer"][k] if hplan is not None else \
                    [None] * len(leaves)
                outs, ne = [], []
                for x, e, er in zip(leaves, outer_plans[k], errs_k):
                    if e is not None and er is not None:
                        piece, er2 = prefetch_lib.scatter_grad_with_error(
                            x, e, n, er, hplan)
                        outs.append(piece)
                        ne.append(er2)
                    else:
                        outs.append(prefetch_lib.scatter_grad(
                            x, e, axis, n, mode, hier=hplan))
                        ne.append(er)
                grads[k] = jax.tree_util.tree_unflatten(tdef, outs)
                new_oerrs[k] = ne
            new_perr = {"groups": ge_fin, "outer": new_oerrs} \
                if hplan is not None else None
            return grads, loss, new_perr

        opt_specs = {
            k: sm_param_specs
            if k in getattr(opt, "param_like_state_fields", ())
            else spec_like(v, PartitionSpec(axis))
            if k in self._PF_ERR_KEYS
            else spec_like(v, PartitionSpec())
            for k, v in self.state.opt_state.items()}
        state_specs = TrainState(
            params=sm_param_specs,
            opt_state=opt_specs,
            scaler=spec_like(self.state.scaler, PartitionSpec()),
            global_step=PartitionSpec(),
            skipped_steps=PartitionSpec())
        takes_gscale = "grad_scale" in inspect.signature(opt.step).parameters
        inv_n = np.float32(1.0 / n)

        def train_fn(state, batch, rng):
            batch_specs = spec_like(batch, PartitionSpec(axis))

            @functools.partial(
                mesh_lib.shard_map, mesh=mesh,
                in_specs=(state_specs, batch_specs, PartitionSpec()),
                out_specs=(state_specs, spec_like(
                    {"loss": 0, "grad_norm": 0, "lr": 0, "overflow": 0,
                     "loss_scale": 0}, PartitionSpec())),
                check_vma=False)
            def inner(state, batch, rng):
                opt_local = dict(state.opt_state)
                if hplan is not None:
                    # per-device residuals: slice the leading [dp] copy
                    # (re-wrapped [None] below — the 1-bit pattern)
                    slice0 = lambda t: tm(lambda x: x[0], t)  # noqa: E731
                    perr = {
                        "groups": tuple(
                            slice0(e)
                            for e in opt_local.pop("pf_group_we")),
                        "outer": {k: [slice0(e) for e in v]
                                  for k, v in
                                  opt_local.pop("pf_outer_we").items()}}
                    bwe = [slice0(e) for e in opt_local.pop("pf_bucket_we")]
                    bse = [slice0(e) for e in opt_local.pop("pf_bucket_se")]
                else:
                    perr = None
                with annotate("ds_fwd_bwd_prefetch"):
                    grads, loss, new_perr = accumulate(state, batch, rng,
                                                       perr)
                loss = jax.lax.pmean(loss, axis)
                # sharded-leaf grads came back reduce-scattered as SUMS
                # over the axis (the custom VJPs); scale to the mean.
                # Replicated (below-threshold) leaves are LOCAL — they
                # mean-exchange through the PR-1 bucket stream (under
                # the hierarchy: the two-level policy-compressed bucket
                # exchange with its own persistent error feedback).
                g_leaves, g_tdef = jax.tree_util.tree_flatten(grads)
                g_leaves = [g * inv_n if e is not None else g
                            for g, e in zip(g_leaves, full_plan)]
                repl_ids = [i for i, e in enumerate(full_plan)
                            if e is None]
                if repl_ids:
                    with annotate("ds_overlap_bucket_sync"):
                        if hplan is not None:
                            red, bwe, bse = overlap_lib.\
                                bucketed_hierarchical_compressed_allreduce(
                                    [g_leaves[i] for i in repl_ids],
                                    bwe, bse, hplan)
                        else:
                            red = overlap_lib.bucketed_allreduce(
                                [g_leaves[i] for i in repl_ids], axis, n,
                                bucket_elems, mode=mode, mean=True)
                    for i, g in zip(repl_ids, red):
                        g_leaves[i] = g
                grads = jax.tree_util.tree_unflatten(g_tdef, g_leaves)

                scale = state.scaler["loss_scale"]
                inv = 1.0 / scale
                local_finite = prec.grads_finite(grads) if precision.fp16 \
                    else jnp.asarray(True)
                finite = jax.lax.pmin(
                    local_finite.astype(jnp.int32), axis) > 0
                # exact global norm: sharded leaves partition the full
                # tensor across the axis (psum of shard norms covers each
                # element once); replicated grads are identical everywhere
                shard_sq = sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g, e in zip(g_leaves, full_plan) if e is not None)
                repl_sq = sum(
                    jnp.sum(jnp.square(g_leaves[i].astype(jnp.float32)))
                    for i in repl_ids)
                grad_norm = jnp.sqrt(
                    jax.lax.psum(jnp.float32(shard_sq), axis)
                    + jnp.float32(repl_sq))
                gscale = inv
                if cfg.gradient_clipping and cfg.gradient_clipping > 0:
                    gscale = inv * jnp.minimum(
                        1.0, cfg.gradient_clipping /
                        (grad_norm * inv + 1e-6))
                lr = lr_fn(state.global_step)

                # ZeRO-3 update runs entirely on local shards: params and
                # moments already rest in the shard layout — no slicing,
                # no post-update gather (params stay sharded at rest)
                with annotate("ds_optimizer"):
                    if takes_gscale:
                        new_params, new_opt = opt.step(
                            state.params, grads, opt_local, lr,
                            grad_scale=gscale)
                    else:
                        grads = tm(lambda g: g * gscale, grads)
                        new_params, new_opt = opt.step(state.params, grads,
                                                       opt_local, lr)
                if hplan is not None:
                    # re-attach the updated residuals (opt.step only
                    # keeps its own fields); an overflow step reverts
                    # them with the rest of the state in
                    # _finish_explicit_state — the discarded grads'
                    # compression error must not compensate a future
                    # exchange
                    bump = lambda t: tm(lambda x: x[None], t)  # noqa: E731
                    new_opt = dict(new_opt)
                    new_opt["pf_group_we"] = [bump(e) for e in
                                              new_perr["groups"]]
                    new_opt["pf_outer_we"] = {
                        k: [bump(e) for e in v]
                        for k, v in new_perr["outer"].items()}
                    new_opt["pf_bucket_we"] = [bump(e) for e in bwe]
                    new_opt["pf_bucket_se"] = [bump(e) for e in bse]
                new_state = self._finish_explicit_state(
                    state, new_params, new_opt, finite, precision)
                return new_state, {
                    "loss": loss, "grad_norm": grad_norm * inv, "lr": lr,
                    "overflow": ~finite,
                    "loss_scale": new_state.scaler["loss_scale"]}

            return inner(state, batch, rng)

        return self._jit_explicit_comm(train_fn)

    def _select_fused_matmul_leaves(self, layer_subtree, layer_plan,
                                    mode, n, axis, cast_bf16):
        """Which layer-stacked leaves stream through the tile-granular
        fused matmul+collective kernels (ISSUE 8) when
        ``stage3_prefetch_gather: fused_matmul``: the dominant 2D
        projection kernels — sharded, per-layer matrices named
        ``kernel``, shard at least ``collective_matmul.min_shard_bytes``
        — consumed by the model's CollectiveDense layers as resting
        shards. Everything else (biases, LN scales, below-threshold
        weights) keeps the packed per-layer ring gather. Returns
        ``(fused_ids, CollectiveMatmulConfig)`` — ``((), None)`` in
        other gather modes or when the pipeline must fall back, with
        ``_prefetch_active``-style logging of the reason."""
        if mode != "fused_matmul":
            return (), None
        from deepspeed_tpu.ops.pallas import fused_collective as fc
        from deepspeed_tpu.telemetry.registry import default_registry
        zc = self._config.zero_config
        if not getattr(self.module, "supports_collective_matmul", False):
            log_dist(
                f"stage3_prefetch_gather=fused_matmul: "
                f"{type(self.module).__name__} does not mark "
                f"supports_collective_matmul (its dense layers would "
                f"reject shard-shaped kernels); falling back to the "
                f"ring gather", ranks=[0])
            return (), None
        # the per-leaf contract: only leaves the model DECLARES as
        # CollectiveDense-consumed may receive shards — a 3D "kernel"
        # under a plain nn.Dense would trip flax's declared-param shape
        # check at trace time with an opaque error
        cm_paths = tuple(getattr(self.module, "collective_matmul_paths",
                                 ()))
        if not cm_paths:
            log_dist(
                f"stage3_prefetch_gather=fused_matmul: "
                f"{type(self.module).__name__} declares no "
                f"collective_matmul_paths; falling back to the ring "
                f"gather", ranks=[0])
            return (), None
        min_bytes = int(zc.collective_matmul_min_shard_bytes)
        flat, _ = jax.tree_util.tree_flatten_with_path(layer_subtree)
        fused, skipped_small, skipped_shape = [], 0, 0
        for i, ((path, leaf), e) in enumerate(zip(flat, layer_plan)):
            if e is None:
                continue
            name = getattr(path[-1], "key", None)
            joined = "/".join(str(getattr(k, "key", k)) for k in path)
            if leaf.ndim != 3 or name != "kernel" or \
                    not any(joined == p or joined.endswith("/" + p)
                            for p in cm_paths):
                skipped_shape += 1
                continue
            itemsize = 2 if (cast_bf16 and leaf.dtype == jnp.float32) \
                else jnp.dtype(leaf.dtype).itemsize
            shard_bytes = int(np.prod(leaf.shape[1:])) // n * itemsize
            if shard_bytes < min_bytes:
                skipped_small += 1
                continue
            fused.append(i)
        reg = default_registry()
        reg.gauge("comm/zero3_prefetch/fused_leaves").set(len(fused))
        reg.gauge("comm/zero3_prefetch/ring_leaves").set(
            skipped_shape + skipped_small)
        if not fused:
            log_dist(
                f"stage3_prefetch_gather=fused_matmul: no layer leaf "
                f"qualifies for fused streaming ({skipped_small} sharded "
                f"kernels below min_shard_bytes={min_bytes}, "
                f"{skipped_shape} non-2D/non-kernel leaves); the gather "
                f"behaves as ring", ranks=[0])
            return (), None
        log_dist(
            f"stage3_prefetch_gather=fused_matmul: {len(fused)} "
            f"projection kernels/layer stream through fused "
            f"all-gather+matmul / matmul+reduce-scatter "
            f"(backend={zc.collective_matmul_backend}, "
            f"tile_m={zc.collective_matmul_tile_m}); {skipped_small} "
            f"below-threshold + {skipped_shape} non-matrix leaves ride "
            f"the packed ring gather", ranks=[0])
        hier = self._prefetch_hier_plan()
        cfg = fc.CollectiveMatmulConfig(
            axis_name=axis, axis_size=n,
            backend=zc.collective_matmul_backend,
            tile_m=zc.collective_matmul_tile_m,
            min_shard_bytes=min_bytes,
            vmem_budget_bytes=zc.collective_matmul_vmem_budget_bytes,
            hierarchy=fc.RingHierarchy(
                inter_axis=hier.inter_axis, intra_axis=hier.intra_axis,
                inter=hier.inter, intra=hier.intra)
            if hier is not None else None)
        return tuple(fused), cfg

    def _record_prefetch_stats(self, params, subtree, layer_plan,
                               outer_plans, cast_bf16, fused_ids=()):
        """Static live-gathered-parameter accounting (the
        ``stage3_max_live_parameters`` observable, utils/memory.py)."""
        from deepspeed_tpu.utils import memory as memory_lib

        def leaf_bytes_per_el(leaf):
            return 2 if (cast_bf16 and leaf.dtype == jnp.float32) \
                else jnp.dtype(leaf.dtype).itemsize

        layer_leaves = jax.tree_util.tree_leaves(params[subtree])
        per_layer_elems = per_layer_bytes = 0
        fused_stream_elems = fused_stream_bytes = 0
        persistent_elems = persistent_bytes = 0
        n_ring = mesh_lib.mesh_axis_size(self.mesh, mesh_lib.DATA_AXIS)
        for i, (leaf, e) in enumerate(zip(layer_leaves, layer_plan)):
            full = int(np.prod(leaf.shape[1:] or (1,)))
            if e is None:
                # below-persistence-threshold stacked leaves stay FULLY
                # replicated (all layers resident) — persistent share
                persistent_elems += full * leaf.shape[0]
                persistent_bytes += full * leaf.shape[0] * \
                    leaf_bytes_per_el(leaf)
                continue
            if i in fused_ids:
                # fused-streamed weights are never materialized full:
                # live footprint is ~2 ring chunks (current + in-flight)
                fused_stream_elems += 2 * (full // max(n_ring, 1))
                fused_stream_bytes += 2 * (full // max(n_ring, 1)) * \
                    leaf_bytes_per_el(leaf)
                continue
            per_layer_elems += full
            per_layer_bytes += full * leaf_bytes_per_el(leaf)
        outer_elems = outer_bytes = 0
        for k, plan in outer_plans.items():
            for leaf, e in zip(jax.tree_util.tree_leaves(params[k]), plan):
                full = int(np.prod(leaf.shape or (1,)))
                if e is None:
                    persistent_elems += full
                    persistent_bytes += full * leaf_bytes_per_el(leaf)
                    continue
                outer_elems += full
                outer_bytes += full * leaf_bytes_per_el(leaf)
        n_layers = layer_leaves[0].shape[0] if layer_leaves else 0
        stats = {
            # double buffer (computing layer + in-flight gather) + the
            # step-persistent full leaves: outer gathers AND replicated
            # below-threshold leaves (always resident) — the full live
            # window stage3_max_live_parameters governs. Fused-streamed
            # weights (ISSUE 8) count only their ~2 live ring chunks —
            # in BOTH the element and byte totals.
            "live_param_elements": 2 * per_layer_elems + outer_elems
            + persistent_elems + fused_stream_elems,
            "live_param_bytes": 2 * per_layer_bytes + outer_bytes
            + persistent_bytes + fused_stream_bytes,
            "per_layer_gather_bytes": per_layer_bytes,
            "fused_stream_bytes": fused_stream_bytes,
            "fused_leaves_per_layer": len(fused_ids),
            "outer_gather_bytes": outer_bytes,
            "persistent_replicated_bytes": persistent_bytes,
            "layers": int(n_layers),
        }
        self._prefetch_stats = stats
        memory_lib.record_live_gathered_param_bytes(
            stats["live_param_bytes"])
        max_live = int(self._config.zero_config.max_live_parameters)
        if max_live and stats["live_param_elements"] > max_live:
            logger.warning(
                f"stage3_prefetch: the 2-layer double buffer holds "
                f"{stats['live_param_elements']} full-parameter elements "
                f"live, above stage3_max_live_parameters={max_live}; the "
                f"pipeline depth is structural (one layer ahead) — raise "
                f"the knob or shrink the layer")

    def _sparse_grad_active(self):
        """True when the train step should exchange embedding gradients
        row-compressed (reference sparse_gradients, engine.py:195-202 +
        the CSR bucket split at :1459-1515). Requires the explicit-comm
        layout (pure DP, replicated params) since GSPMD otherwise reduces
        gradients implicitly with no collective to replace."""
        if not self._config.sparse_gradients_enabled:
            return False
        if not self._pure_dp_mesh() or self.zero_optimization_stage() > 0 \
                or self._offload_cfg.enabled \
                or self._compressed_comm_active():
            log_dist("sparse_gradients requires a pure-DP mesh with ZeRO "
                     "stage 0 (explicit grad exchange); falling back to "
                     "dense reduction", ranks=[0])
            return False
        if not self._sparse_leaf_paths():
            log_dist(
                "sparse_gradients enabled but the model declares no "
                "sparse_grad_params — falling back to dense reduction. "
                "(The declaration is deliberate: a name heuristic would "
                "silently drop gradient for tied embeddings, whose head "
                "term is dense over the vocabulary.)", ranks=[0])
            return False
        return True

    def _sparse_leaf_paths(self):
        # strictly opt-in: models declare which leaves have row-sparse
        # gradients (GPT2LMHeadModel does, when untied)
        pats = getattr(self.module, "sparse_grad_params", ())
        return tuple(p.lower() for p in pats)

    def _build_sparse_train_fn(self, loss_fn):
        """shard_map train step exchanging embedding grads as compressed
        rows: per-shard grads stay local, dense leaves psum, sparse leaves
        go through CSRTensor compress → all_gather(rows) → scatter-add
        (runtime/csr_tensor.py). Numerically exact: the row budget is the
        shard's token count, and every token touches one row."""
        from deepspeed_tpu.runtime.csr_tensor import CSRTensor
        mesh = self.mesh
        axis = mesh_lib.DATA_AXIS
        cfg = self._config
        lr_fn = self._lr_fn()
        opt = self.optimizer
        precision = self.precision
        accumulate = self._local_grad_accumulator(loss_fn, axis)
        sparse_pats = self._sparse_leaf_paths()
        spec_like = lambda tree, s: jax.tree_util.tree_map(  # noqa: E731
            lambda _: s, tree)
        state_specs = TrainState(
            params=spec_like(self.state.params, PartitionSpec()),
            opt_state=spec_like(self.state.opt_state, PartitionSpec()),
            scaler=spec_like(self.state.scaler, PartitionSpec()),
            global_step=PartitionSpec(),
            skipped_steps=PartitionSpec())

        def is_sparse_path(path):
            name = "/".join(str(getattr(k, "key", k)) for k in path).lower()
            return any(p in name for p in sparse_pats)

        def local_tokens(batch):
            # the CSR row budget must cover the LARGEST token stream in the
            # batch (a smaller auxiliary id array must not shrink it — the
            # exchange would silently drop gradient rows)
            counts = [int(np.prod(leaf.shape))
                      for leaf in jax.tree_util.tree_leaves(batch)
                      if jnp.issubdtype(leaf.dtype, jnp.integer)
                      and leaf.ndim >= 2]
            return max(counts) if counts else None

        def train_fn(state, batch, rng):
            batch_specs = spec_like(batch, PartitionSpec(axis))

            @functools.partial(
                mesh_lib.shard_map, mesh=mesh,
                in_specs=(state_specs, batch_specs, PartitionSpec()),
                out_specs=(state_specs, spec_like(
                    {"loss": 0, "grad_norm": 0, "lr": 0, "overflow": 0,
                     "loss_scale": 0}, PartitionSpec())),
                check_vma=False)
            def inner(state, batch, rng):
                tm = jax.tree_util.tree_map
                grads, loss = accumulate(state, batch, rng)
                scale = state.scaler["loss_scale"]
                tokens = local_tokens(batch)

                def reduce_leaf(path, g):
                    if tokens is not None and g.ndim == 2 \
                            and is_sparse_path(path) and tokens < g.shape[0]:
                        # row-compressed exchange (reference CSR allreduce)
                        csr = CSRTensor.from_dense(g, tokens)
                        all_idx = jax.lax.all_gather(csr.indices, axis)
                        all_val = jax.lax.all_gather(csr.values, axis)
                        out = jnp.zeros_like(g)
                        return out.at[all_idx.reshape(-1)].add(
                            all_val.reshape(-1, g.shape[1]), mode="drop")
                    return jax.lax.psum(g, axis)

                grads = jax.tree_util.tree_map_with_path(reduce_leaf, grads)
                # loss_fn averaged over the LOCAL shard; the exchange above
                # sums shard gradients, so normalize to the global mean
                dp = mesh.shape[axis]
                grads = tm(lambda g: g / dp, grads)
                loss = jax.lax.pmean(loss, axis)
                finite = prec.grads_finite(grads) if precision.fp16 \
                    else jnp.asarray(True)
                grad_norm = _global_norm(grads)
                inv = 1.0 / scale
                gscale = inv
                if cfg.gradient_clipping and cfg.gradient_clipping > 0:
                    gscale = inv * jnp.minimum(
                        1.0, cfg.gradient_clipping / (grad_norm * inv + 1e-6))
                lr = lr_fn(state.global_step)
                if "grad_scale" in inspect.signature(opt.step).parameters:
                    new_params, new_opt = opt.step(
                        state.params, grads, state.opt_state, lr,
                        grad_scale=gscale)
                else:
                    grads = tm(lambda g: g * gscale, grads)
                    new_params, new_opt = opt.step(state.params, grads,
                                                   state.opt_state, lr)
                new_state = self._finish_explicit_state(
                    state, new_params, new_opt, finite, precision)
                return new_state, {
                    "loss": loss, "grad_norm": grad_norm * inv, "lr": lr,
                    "overflow": ~finite,
                    "loss_scale": new_state.scaler["loss_scale"]}

            return inner(state, batch, rng)

        return self._jit_explicit_comm(train_fn)

    def _micro_loss_and_grads(self, state, micro_batch, rng, loss_fn=None):
        if loss_fn is None:
            loss_fn = self._resolve_loss_fn()
        keep_prob = self._keep_prob_fn()(state.global_step)
        scale = state.scaler["loss_scale"]

        cast_bf16 = self._config.grad_dtype == "bf16"

        def scaled_loss(p):
            if cast_bf16:
                # one whole-tree fp32→bf16 cast INSIDE the differentiated
                # function: cotangents (incl. layer-scan grad stacks)
                # materialize in bf16, and the model reads half the param
                # bytes per pass. The reference fp16 engine's grads-in-fp16
                # semantics (engine.py:624 model.half()).
                p = jax.tree_util.tree_map(
                    lambda x: x.astype(jnp.bfloat16)
                    if x.dtype == jnp.float32 else x, p)
            loss = loss_fn(p, micro_batch, rng, keep_prob)
            return (loss * scale).astype(jnp.float32), loss

        params = state.params
        if self._param_offload_host:
            # stream the host-resident params into HBM for compute; grads
            # come out device-resident (the swap-in of the reference's
            # partitioned_param_swapper, done by XLA's h2d DMA)
            params = jax.device_put(
                params, self.zero.device_param_shardings(params))
        grads, loss = jax.grad(scaled_loss, has_aux=True)(params)
        grads = self.zero.constrain_grads(grads)
        return loss, grads

    def _micro_loss(self, state, micro_batch, rng, loss_fn=None):
        """Forward-only loss (no grad) — the wall_clock_breakdown forward
        phase. Mirrors _micro_loss_and_grads' param handling."""
        if loss_fn is None:
            loss_fn = self._resolve_loss_fn()
        keep_prob = self._keep_prob_fn()(state.global_step)
        params = state.params
        if self._param_offload_host:
            params = jax.device_put(
                params, self.zero.device_param_shardings(params))
        if self._config.grad_dtype == "bf16":
            params = jax.tree_util.tree_map(
                lambda x: x.astype(jnp.bfloat16)
                if x.dtype == jnp.float32 else x, params)
        return loss_fn(params, micro_batch, rng, keep_prob)

    def _globalize_batch(self, batch):
        """Multi-host: every process feeds the FULL global batch (the
        reference gives each rank a per-rank loader instead); jax extracts
        each process's addressable shards. Single-process: plain upload.

        make_array_from_process_local_data is the wrong tool here: with
        the default global_shape it treats each process's rows as that
        process's PRIVATE shard and stacks them — the global batch
        silently doubles with duplicated rows (mean losses hide that:
        mean of duplicates == mean, but any path sensitive to WHICH rows
        a device holds — per-device compressed-gradient pieces, sample
        accounting — diverges from the single-process run) — and with an
        explicit global_shape it verifies cross-process equality with a
        host-side gloo all-reduce, one more independent collective for
        the multi-device interleave flake (ROADMAP standing backlog) to
        race. make_array_from_callback slices the local copy per
        addressable device with no collective at all."""
        if jax.process_count() == 1:
            return jax.tree_util.tree_map(jnp.asarray, batch)
        sh = mesh_lib.batch_sharding(self.mesh)

        def globalize(x):
            x = np.asarray(x)
            return jax.make_array_from_callback(
                x.shape, sh, lambda idx, _x=x: _x[idx])

        return jax.tree_util.tree_map(globalize, batch)

    def _ensure_ready(self, batch):
        if self.state is None:
            self._init_state(example_batch=self._example_from_batch(batch))
        if self._jit_train_batch is None:
            self._build_jit_fns()
        self._maybe_auto_resume()

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    # ------------------------------------------------------------------
    # public training API
    # ------------------------------------------------------------------
    def train_batch(self, batch=None, data_iter=None):
        """One full optimizer step over gas×micro samples.

        `batch` may carry the full global batch (leading dim
        micro*gas[*dp]) or a micro batch (then gas must be 1); alternatively
        pass `data_iter` to pull gas micro-batches, like the reference
        PipelineEngine.train_batch(data_iter) (pipe/engine.py:250)."""
        if batch is None:
            assert data_iter is not None, "need batch or data_iter"
            micro = [next(data_iter) for _ in range(self.gradient_accumulation_steps())]
            batch = jax.tree_util.tree_map(
                lambda *xs: np.concatenate(
                    [np.asarray(x) for x in xs]),  # sync-ok: host loader data
                *micro)
        # state init inspects host-side shapes; globalize only after
        self._ensure_ready(batch)
        self._ensure_params_resident()
        batch = self._globalize_batch(batch)
        if self.flops_profiler is not None:
            self.flops_profiler.maybe_profile(batch)

        step_idx = self.global_steps
        # events recorded during this step (spans, swap I/O) carry it
        self.flight_recorder.set_step(step_idx)
        if self._trace_window is not None:
            self._trace_window.on_step_begin(step_idx)
        self.tput_timer.start()
        # the span measures host-side DISPATCH of the step (async under
        # jit — no sync); device-true step time comes from the boundary
        # window fold below. The same interval feeds the cluster
        # plane's per-rank SELF time (ISSUE 12): time spent blocked
        # INSIDE the dispatch call is where a healthy rank absorbs a
        # straggler's delay (backends that execute cross-process
        # collectives synchronously block right here), so host_step_s
        # excludes it — what remains is rank-attributable host work.
        # ISSUE 15: the hang_in_collective fault point sits BEFORE the
        # dispatch guard — the injected rank models "stuck elsewhere"
        # (its own watchdog sees no dispatch, its heartbeat keeps
        # beating), while its PEERS block inside the collective below
        # and their guard converts the stall into EXIT_HANG.
        _faults.fire("collective_enter", step=step_idx, engine=self)
        _t_disp = time.perf_counter()
        self._guard_enter("step", step_idx)
        try:
            with tel_span("train/step_dispatch", self.telemetry):
                if self._host_runner is not None:
                    metrics = self._host_offload_step(batch)
                elif self.wall_clock_breakdown() and not (
                        self._compressed_comm_active()
                        or self._sparse_grad_active()
                        or self._overlap_comm_active()
                        or self._prefetch_active()):
                    # (1-bit / CSR / overlap paths keep their fused
                    # shard_map programs — their comm scheduling lives
                    # inside the step and cannot be split into phase
                    # programs)
                    metrics = self._train_batch_instrumented(batch)
                else:
                    self.state, metrics = self._jit_train_batch(
                        self.state, batch, self._next_rng())
        finally:
            self._guard_exit()
        self._tel_window_dispatch_s += time.perf_counter() - _t_disp
        self._fence_ref = metrics["loss"]
        self.tput_timer.stop()

        gas = self.gradient_accumulation_steps()
        self.micro_steps += gas
        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        self._record_metrics(metrics)
        if hasattr(self.lr_scheduler, "step"):
            self.lr_scheduler.step()
        self._moq_boundary(batch, metrics)
        self._elastic_commit()
        self._park_params()
        self._elastic_step()
        loss = metrics["loss"]
        self._telemetry_step(batch, loss)
        if self._trace_window is not None:
            self._trace_window.on_step_end(
                step_idx,   # sync-ok: config-gated trace-window close
                fence=lambda: jax.block_until_ready(loss))
        if self.global_steps % self.steps_per_print() == 0:
            self._report_progress(loss)
        return loss

    @staticmethod
    def _adopt_mpu(mpu, mesh, explicit_mesh):
        """Map a Megatron-style mpu object onto the mesh (the reference
        adopts mpu groups for TP, engine.py:636-641) — or reject loudly.
        On TPU, tensor parallelism IS the mesh 'model' axis: an mpu that
        agrees with the mesh is redundant-but-welcome; one that disagrees
        would silently train with the wrong sharding, so it is an error.
        When the mesh came from config defaults (model=1), the mpu's TP
        degree is adopted by rebuilding the mesh with model=mp."""
        mp = None
        for name in ("get_model_parallel_world_size",
                     "get_tensor_model_parallel_world_size"):
            if hasattr(mpu, name):
                mp = int(getattr(mpu, name)())
                break
        if mp is None:
            raise ValueError(
                "initialize(mpu=...) requires an object exposing "
                "get_model_parallel_world_size(); on TPU, express tensor "
                "parallelism as the mesh 'model' axis instead "
                "(make_mesh(MeshConfig(model=N)))")
        mesh_mp = mesh_lib.mesh_axis_size(mesh, mesh_lib.MODEL_AXIS)
        if mesh_mp == mp:
            return mesh
        if explicit_mesh or mesh_mp != 1:
            raise ValueError(
                f"mpu reports model_parallel_world_size={mp} but the mesh "
                f"'model' axis is {mesh_mp}; make them agree (or drop the "
                f"mpu argument — the mesh axis alone defines TP here)")
        # config-default mesh: adopt the mpu's TP degree
        shape = dict(mesh.shape)
        log_dist(f"adopting mpu model_parallel_world_size={mp} as the mesh "
                 f"'model' axis", ranks=[0])
        return mesh_lib.make_mesh(
            mesh_lib.MeshConfig(data=-1, model=mp,
                                pipe=shape.get(mesh_lib.PIPE_AXIS, 1),
                                seq=shape.get(mesh_lib.SEQ_AXIS, 1),
                                expert=shape.get(mesh_lib.EXPERT_AXIS, 1)),
            devices=list(mesh.devices.flat))

    def _train_batch_instrumented(self, batch):
        """wall_clock_breakdown for the fused train path (reference wraps
        every phase with synchronized timers, engine.py:1028-1047): the step
        splits into forward-loss, fwd+bwd-grads and optimizer-apply
        programs with a data-dependent readback as the fence after each —
        the TPU analog of the reference's cuda.synchronize-per-phase.
        Numerics match the fused program; while the flag is on, throughput
        pays one extra forward and loses cross-phase fusion, exactly as the
        reference pays its per-phase synchronize — a measurement mode, not
        the production path. The backward phase is reported as (grads
        program − forward program) since XLA computes fwd+bwd fused."""
        rng = self._next_rng()
        t0 = time.perf_counter()
        lval = self._jit_loss_batch(self.state, batch, rng)
        float(jax.device_get(lval))  # data-dependent fence (tunnel-safe)
        fwd_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        grads, loss, _, _ = self._jit_grads_batch(self.state, batch, rng)
        float(jax.device_get(loss))
        fwdbwd_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        self.state, metrics = self._jit_apply_grads(self.state, grads, loss)
        float(jax.device_get(metrics["grad_norm"]))
        step_s = time.perf_counter() - t0

        # each phase fence pays one full readback round trip; on tunneled
        # backends that RTT is ~100 ms — an order of magnitude above the
        # apply program itself — so phases must be reported NET of it.
        # metrics["lr"] is already materialized by the grad_norm fence, so
        # re-reading it measures the pure RTT (r3's "130 ms optimizer
        # phase" was ~90 ms of this artifact).
        t0 = time.perf_counter()
        float(jax.device_get(metrics["lr"]))
        fence_s = time.perf_counter() - t0

        self.timers(FORWARD_GLOBAL_TIMER).elapsed_ += \
            max(fwd_s - fence_s, 0.0)
        # grads program = fwd+bwd fused; report bwd as its excess over fwd
        self.timers(BACKWARD_GLOBAL_TIMER).elapsed_ += \
            max(fwdbwd_s - fwd_s, 0.0)
        self.timers(STEP_GLOBAL_TIMER).elapsed_ += \
            max(step_s - fence_s, 0.0)
        self.timers(FENCE_TIMER).elapsed_ += fence_s

        # the instrumented phases are REAL device measurements (each one
        # fenced) — feed them to the span histograms so the telemetry
        # stream carries per-phase times whenever this mode is on
        reg = self.telemetry
        for tag, dur in (("train/forward", max(fwd_s - fence_s, 0.0)),
                         ("train/backward", max(fwdbwd_s - fwd_s, 0.0)),
                         ("train/optimizer", max(step_s - fence_s, 0.0)),
                         ("train/fence", fence_s)):
            reg.histogram(f"span/{tag}").observe(dur)
            self.flight_recorder.record("span", tag=tag, dur_s=dur)

        if self.global_steps % self.steps_per_print() == 0:
            # per-step means over the print interval (reference resets each
            # log; cumulative totals would read as ever-growing phase times)
            self.timers.log([FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER,
                             STEP_GLOBAL_TIMER], reset=True,
                            normalizer=max(self.steps_per_print(), 1))
        return metrics

    def wall_clock_times(self, reset=False):
        """Per-phase seconds accumulated since the last reset/log by the
        instrumented path ({'forward', 'backward', 'step'}; offload engines
        report 'backward' as the fused fwd+bwd program and 'step' as the
        host optimizer). Empty unless wall_clock_breakdown is enabled."""
        out = {}
        for name in (FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER,
                     STEP_GLOBAL_TIMER, FENCE_TIMER):
            if name in self.timers.timers:
                out[name] = self.timers(name).elapsed(reset=reset)
        return out

    def _host_offload_step(self, batch):
        """Device grads → host SIMD Adam (cpu/NVMe state) → device params.
        The ZeRO-Offload step (reference stage2.py:747-925 + cpu_adam).

        With ``zero_optimization.overlap_comm`` and gas > 1, gradients
        stream to the host per microbatch while the device computes the
        next one (the reference's reduction-stream overlap,
        stage2.py:679-746); otherwise the accumulation runs fused on
        device and only the final tree transfers."""
        gas = self.gradient_accumulation_steps()
        if gas > 1 and self._config.zero_config.overlap_comm \
                and not self._offload_streamed():
            # host-fold overlap only helps when the step runs on THIS host;
            # the streamed tier accumulates on device (the gas scan in
            # accumulate_grads) and never moves gradients off the device
            return self._host_offload_step_overlapped(batch, gas)
        wcb = self.wall_clock_breakdown()
        t0 = time.perf_counter()
        grads, loss, finite, scaled_norm = self._jit_grads_batch(
            self.state, batch, self._next_rng())
        if wcb:
            # phase accounting for offload (the flag must not silently
            # no-op here): 'backward' = the fused fwd+bwd device program,
            # 'step' = host transfer+SIMD+push
            float(jax.device_get(loss))
            self.timers(BACKWARD_GLOBAL_TIMER).elapsed_ += \
                time.perf_counter() - t0
            t0 = time.perf_counter()
        metrics = self._host_apply_grads(grads, loss, finite=finite,
                                         scaled_norm=scaled_norm)
        if wcb:
            self.timers(STEP_GLOBAL_TIMER).elapsed_ += \
                time.perf_counter() - t0
        return metrics

    def _host_offload_step_overlapped(self, batch, gas):
        """Per-micro dispatch: while the device computes micro k+1, micro
        k's gradient leaves copy d2h (`copy_to_host_async`) and fold into
        fp32 host accumulators; the final SIMD step + h2d push then run on
        the host tree via the streamed step. Device compute hides
        (gas-1)/gas of the transfer+accumulate time."""
        lead = jax.tree_util.tree_leaves(batch)[0].shape[0]
        assert lead % gas == 0, (
            f"train_batch got leading dim {lead} not divisible by "
            f"gradient_accumulation_steps={gas}")
        m = lead // gas
        inv_gas = np.float32(1.0 / gas)

        wcb = self.wall_clock_breakdown()
        t0 = time.perf_counter()
        acc = None
        losses = []
        pending = None

        def fold(leaves):
            nonlocal acc
            if acc is None:
                acc = [np.asarray(g, np.float32) * inv_gas for g in leaves]
            else:
                for i, g in enumerate(leaves):
                    acc[i] += np.asarray(g, np.float32) * inv_gas

        for k in range(gas):
            micro = jax.tree_util.tree_map(
                lambda x: x[k * m:(k + 1) * m], batch)
            loss_k, grads_k = self._jit_micro_grads(self.state, micro,
                                                    self._next_rng())
            losses.append(loss_k)
            leaves_k = jax.tree_util.tree_leaves(grads_k)
            for g in leaves_k:
                if hasattr(g, "copy_to_host_async"):
                    try:
                        g.copy_to_host_async()
                    except Exception:
                        pass
            if pending is not None:
                fold(pending)   # overlaps micro k's device compute
            pending = leaves_k
        fold(pending)
        loss = sum(float(jax.device_get(l)) for l in losses) / gas

        # norm on host (BLAS dot per leaf): serves clipping AND the fp16
        # finite check — inf/nan gradients make the norm non-finite
        scaled_norm = float(np.sqrt(sum(
            float(np.dot(a.ravel(), a.ravel())) for a in acc)))
        finite = bool(np.isfinite(scaled_norm)) if self.precision.fp16 \
            else True
        grads_tree = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(self.state.params), acc)
        if wcb:
            # 'backward' = device compute with the overlapped d2h+fold
            # (the losses device_get above fenced the last micro)
            self.timers(BACKWARD_GLOBAL_TIMER).elapsed_ += \
                time.perf_counter() - t0
            t0 = time.perf_counter()
        metrics = self._host_apply_grads(grads_tree, jnp.float32(loss),
                                         finite=finite,
                                         scaled_norm=scaled_norm)
        if wcb:
            self.timers(STEP_GLOBAL_TIMER).elapsed_ += \
                time.perf_counter() - t0
        return metrics

    def _host_apply_grads(self, grads, loss, finite=None, scaled_norm=None):
        """Shared offload update, pipelined: overflow/norm resolve from two
        device scalars, then the leaves stream d2h while earlier leaves run
        the SIMD step and updated leaves push h2d — the reference's
        overlapped offload step (stage2.py:747-925 + pipelined swapper),
        expressed with JAX async transfers (see
        HostOffloadOptimizer.step_streamed).

        ``finite``/``scaled_norm`` are device scalars when coming from the
        fused grads fn; the forward/backward/step path computes them here
        (also on device — the host never scans the gradient tree)."""
        fp16 = self.precision.fp16
        scale = float(jax.device_get(self.state.scaler["loss_scale"])) \
            if fp16 else 1.0

        # overflow-skip applies under fp16 only, matching _apply_grads —
        # bf16/fp32 runs step unconditionally like the device path. Resolve
        # the device finite scalar BEFORE transferring the gradient tree so
        # skipped steps don't pull the full model's grads just to drop them.
        if finite is not None:
            finite = bool(jax.device_get(finite))
        elif fp16:
            if self._jit_grads_finite is None:
                self._jit_grads_finite = jax.jit(prec.grads_finite)
            finite = bool(jax.device_get(self._jit_grads_finite(grads)))
        else:
            finite = True
        new_scaler = prec.update_scaler(self.state.scaler, self.precision,
                                        jnp.asarray(finite))
        step_now = int(jax.device_get(self.state.global_step))
        lr = float(jax.device_get(self._lr_fn()(jnp.asarray(step_now))))
        if not finite:
            self.state = TrainState(
                params=self.state.params, opt_state=self.state.opt_state,
                scaler=new_scaler, global_step=self.state.global_step,
                skipped_steps=self.state.skipped_steps + 1)
            return {"loss": loss, "grad_norm": jnp.float32(0.0),
                    "lr": jnp.float32(lr), "overflow": jnp.asarray(True),
                    "loss_scale": new_scaler["loss_scale"]}

        if scaled_norm is None:
            if self._jit_grad_norm is None:
                self._jit_grad_norm = jax.jit(_global_norm)
            scaled_norm = self._jit_grad_norm(grads)
        norm = float(jax.device_get(scaled_norm)) / scale

        # fold unscale + clip into one coefficient, consumed inside the
        # native step's gradient read — no host-side rescale pass
        coef = 1.0 / scale
        clip = self._config.gradient_clipping
        if clip and clip > 0 and norm > clip:
            coef *= clip / (norm + 1e-6)

        out_dtype = self.precision.compute_dtype
        if self._offload_streamed():
            # device-streamed tier: the update runs on the accelerator with
            # state in pinned_host — gradients never leave the device
            new_leaves = self._host_runner.step(
                jax.tree_util.tree_leaves(grads), lr, grad_scale=coef,
                out_dtype=out_dtype)
        elif self._param_swapper is not None \
                and self._param_swapper.pipeline_write \
                and self.quantizer is None:
            # (MoQ reads state.params at the step boundary, which this
            # shortcut leaves stale — quantizing engines keep the push)
            # pipelined NVMe park, host-optimizer shortcut: each leaf's
            # updated compute-dtype copy comes OUT of the SIMD step on the
            # host, so park it straight to the write-behind queue — no h2d
            # push + d2h re-read round trip (that round trip was the whole
            # park cost on tunneled backends). The device copies that fed
            # fwd+bwd are stale now; _park_params just frees them.
            swapper = self._param_swapper

            def park(i, host_arr):
                swapper.write_behind(i, host_arr)
                return None

            self._host_runner.step_streamed(
                jax.tree_util.tree_leaves(grads), lr, grad_scale=coef,
                push_fn=park, out_dtype=out_dtype)
            self._parked_via_push = True
            new_leaves = jax.tree_util.tree_leaves(self.state.params)
        else:
            shard_leaves = jax.tree_util.tree_leaves(
                self.state_shardings.params)
            # on the CPU backend device_put ALIASES host memory — the
            # runner's staging buffers are reused next step, so alias would
            # corrupt the live params; accelerator backends copy over the
            # wire
            aliases_host = self.mesh.devices.flat[0].platform == "cpu"

            def push(i, host_arr):
                # async dispatch: the h2d copy overlaps the remaining leaf
                # steps, and the next step's jit consumes the futures
                # directly
                if aliases_host:
                    host_arr = np.array(host_arr, copy=True)
                return jax.device_put(host_arr, shard_leaves[i])

            new_leaves = self._host_runner.step_streamed(
                jax.tree_util.tree_leaves(grads), lr, grad_scale=coef,
                push_fn=push, out_dtype=out_dtype)
        new_params = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(self.state.params), new_leaves)
        self.state = TrainState(
            params=new_params,
            opt_state=self.state.opt_state,
            scaler=new_scaler,
            global_step=self.state.global_step + 1,
            skipped_steps=self.state.skipped_steps)
        return {"loss": loss, "grad_norm": jnp.float32(norm),
                "lr": jnp.float32(lr), "overflow": jnp.asarray(False),
                "loss_scale": new_scaler["loss_scale"]}

    def forward(self, batch):
        """Parity shim: computes loss+grads for one micro batch and stashes
        them for `backward`/`step` (the reference runs fwd here and autograd
        later; under XLA fwd+bwd are one fused program)."""
        # state init inspects host-side shapes; globalize only after
        self._ensure_ready(batch)
        self._ensure_params_resident()
        batch = self._globalize_batch(batch)
        if self.wall_clock_breakdown():
            self.timers(FORWARD_MICRO_TIMER).start()
        # the micro program's loss reduction is a cross-process
        # collective on a dp mesh — guard it like the fused dispatch
        # (a dead peer parks this call forever otherwise, ISSUE 15).
        # Own kind: each jitted program gets its own first-occurrence
        # compile allowance
        self._guard_enter("micro", self.global_steps)
        try:
            loss, grads = self._jit_micro_grads(self.state, batch,
                                                self._next_rng())
        finally:
            self._guard_exit()
        if self.wall_clock_breakdown():
            self.timers(FORWARD_MICRO_TIMER).stop()
        self._pending_loss = loss
        self._pending_micro = (loss, grads)
        self._moq_batch = batch   # last micro batch, for eigenvalue at step()
        return loss

    __call__ = forward

    def backward(self, loss=None, allreduce_gradients=True):
        """Accumulate the stashed micro-grads (reference engine.py:1077)."""
        assert self._pending_micro is not None, "forward() must precede backward()"
        if self.wall_clock_breakdown():
            self.timers(BACKWARD_MICRO_TIMER).start()
        mloss, grads = self._pending_micro
        self._pending_micro = None
        gas = self.gradient_accumulation_steps()
        scaled = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) / gas, grads)
        if self._pending_grads is None:
            self._pending_grads = scaled
            self._accum_loss = mloss / gas
        else:
            self._pending_grads = jax.tree_util.tree_map(
                jnp.add, self._pending_grads, scaled)
            self._accum_loss = self._accum_loss + mloss / gas
        self.micro_steps += 1
        if self.wall_clock_breakdown():
            self.timers(BACKWARD_MICRO_TIMER).stop()
        return loss if loss is not None else mloss

    def step(self):
        """Optimizer step at GAS boundaries (reference engine.py:1234)."""
        if self.micro_steps % self.gradient_accumulation_steps() != 0:
            return  # not at boundary — reference also early-outs
        assert self._pending_grads is not None, "backward() must precede step()"
        self.flight_recorder.set_step(self.global_steps)
        if self.wall_clock_breakdown():
            self.timers(STEP_MICRO_TIMER).start()
        # own kind ("apply", not "step"): _jit_apply_grads compiles on
        # ITS first dispatch — sharing the fused path's kind would
        # spend the compile allowance on the wrong program
        self._guard_enter("apply", self.global_steps)
        try:
            if self._host_runner is not None:
                metrics = self._host_apply_grads(self._pending_grads,
                                                 self._accum_loss)
            else:
                self.state, metrics = self._jit_apply_grads(
                    self.state, self._pending_grads, self._accum_loss)
        finally:
            self._guard_exit()
        self._fence_ref = metrics["loss"]
        self._pending_grads = None
        self._accum_loss = None
        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        self._record_metrics(metrics)
        if hasattr(self.lr_scheduler, "step"):
            self.lr_scheduler.step()
        if self.wall_clock_breakdown():
            self.timers(STEP_MICRO_TIMER).stop()
        self._moq_boundary(getattr(self, "_moq_batch", None), metrics)
        self._elastic_commit()
        self._park_params()
        self._elastic_step()
        self._telemetry_step(getattr(self, "_moq_batch", None),
                             metrics["loss"])
        if self.global_steps % self.steps_per_print() == 0:
            self._report_progress(metrics["loss"])

    def _moq_boundary(self, batch, metrics):
        """MoQ hook at every optimizer-step boundary (reference
        engine.py:1199-1206 quantizer call in _take_model_step +
        eigenvalue computation at :1250-1257)."""
        q = self.quantizer
        if q is None:
            return
        if self.global_steps < self._config.quantize_training_config.\
                schedule_offset:
            return
        eigenvalues = None
        if self.eigenvalue is not None and batch is not None and \
                q.any_precision_switch() and \
                self.global_steps % self.eigenvalue.gas_boundary_resolution \
                == 0:
            loss_fn = self._resolve_loss_fn()

            def params_loss(p):
                return loss_fn(p, batch, jax.random.PRNGKey(0),
                               jnp.float32(1.0))
            try:
                eigenvalues = self.eigenvalue.compute_layer_eigenvalues(
                    params_loss, self.state.params, self._next_rng())
            except Exception as e:  # curvature is advisory, never fatal
                logger.warning(f"eigenvalue computation failed: {e}")
        overflow = bool(jax.device_get(metrics.get("overflow", False)))
        new_params = q.quantize_tree(self.state.params, overflow=overflow,
                                     eigenvalues=eigenvalues,
                                     key=self._next_rng())
        self.state = TrainState(params=new_params,
                                opt_state=self.state.opt_state,
                                scaler=self.state.scaler,
                                global_step=self.state.global_step,
                                skipped_steps=self.state.skipped_steps)

    def eval_batch(self, batch):
        self._ensure_params_resident()
        # state init inspects host-side shapes; globalize only after
        self._ensure_ready(batch)
        batch = self._globalize_batch(batch)
        return self._jit_eval(self.state, self._model_inputs(batch))

    def zero_grad(self):
        self._pending_grads = None

    # ------------------------------------------------------------------
    # bookkeeping / reporting
    # ------------------------------------------------------------------
    def _record_metrics(self, metrics):
        self._last_lr = metrics["lr"]
        self._last_grad_norm = metrics["grad_norm"]
        if self._config.tensorboard_config.enabled:
            host = {k: float(jax.device_get(v)) for k, v in metrics.items()}
            self.scalar_history.append((self.global_steps, host))
            writer = self._summary_writer()
            if writer is not None:
                # reference tags, engine.py:1095-1105 / :1272-1298
                writer.add_scalar("Train/Samples/train_loss", host["loss"],
                                  self.global_samples)
                writer.add_scalar("Train/Samples/lr", host["lr"],
                                  self.global_samples)
                writer.add_scalar("Train/Samples/loss_scale",
                                  host["loss_scale"], self.global_samples)
                writer.add_scalar("Train/Samples/grad_norm",
                                  host["grad_norm"], self.global_samples)
                if self.global_steps % self.steps_per_print() == 0:
                    writer.flush()

    # ------------------------------------------------------------------
    # unified telemetry (deepspeed_tpu/telemetry)
    # ------------------------------------------------------------------
    def _install_comm_wire_model(self, plan):
        """Trace-time bytes-on-wire cost model for the hierarchical
        exchange (ISSUE 10): the bucket plan + per-bucket policy are
        static, so each phase's per-device, per-step wire bytes are one
        host-side dict computed once — the per-step telemetry just
        advances counters by it (sync-free)."""
        from deepspeed_tpu.parallel import overlap
        leaves = jax.tree_util.tree_leaves(self.state.params)
        buckets = overlap.plan_buckets([l.shape for l in leaves],
                                       plan.bucket_elems, plan.world)
        flags = overlap.plan_bucket_compression(buckets, plan)
        self.flight_recorder.record(
            "comm_hierarchy_plan", buckets=len(buckets),
            compressed=int(sum(flags)), inter=plan.inter,
            intra=plan.intra, policy=plan.compression,
            min_bucket_bytes=plan.min_bucket_bytes)
        self._comm_wire_model = {
            "warmup": overlap.hierarchy_wire_bytes(
                buckets, [False] * len(buckets), plan),
            "compressed": overlap.hierarchy_wire_bytes(buckets, flags,
                                                       plan),
        }
        self.comm_hierarchy = plan

    def _install_prefetch_wire_model(self, plan, params, fused_ids,
                                     cast_bf16):
        """Trace-time per-device, per-step bytes-on-wire model for the
        hierarchical stage-3 prefetch stream (ISSUE 16) — single phase
        (the stream has no warmup). Sums the four legs of one step:
        packed per-layer group gathers (forward + backward re-gather)
        and grad reduce-scatters, the step-persistent outer exchanges,
        the fused collective-matmul streams, and the replicated-leaf
        bucket leg. ``inter_uncompressed`` here is the slow-link bytes
        the FLAT single-ring schedule would have paid for the same
        exchanges (ni of the n ring edges cross hosts) — the honest
        reduction denominator for this stream, unlike the 1-bit model
        whose denominator is the same two-level schedule uncompressed
        (see docs/observability.md)."""
        from deepspeed_tpu.parallel import overlap
        from deepspeed_tpu.parallel import prefetch as prefetch_lib
        subtree = self.module.prefetch_layer_subtree
        n = plan.world
        gas = self.gradient_accumulation_steps()
        param_spec_tree = self.zero.param_specs(params)
        layer_plan = self.zero.explicit_shard_plan(
            params[subtree], specs=param_spec_tree[subtree])
        full_plan = self.zero.explicit_shard_plan(params,
                                                  specs=param_spec_tree)
        intra = inter = flat_inter = 0

        def add(w, times=1):
            nonlocal intra, inter, flat_inter
            intra += times * w["intra"]
            inter += times * w["inter"]
            flat_inter += times * w["flat_inter"]

        def isz(dt):
            return 2 if (cast_bf16 and jnp.dtype(dt) == jnp.float32) \
                else jnp.dtype(dt).itemsize

        # per-layer packed dtype groups: 2(L+1) gathers (forward +
        # backward, each with one redundant edge gather) and L grad RS
        # per group per microbatch
        stacked = jax.tree_util.tree_leaves(params[subtree])
        fused = set(fused_ids)
        groups = {}
        for i, (leaf, entry) in enumerate(zip(stacked, layer_plan)):
            if entry is None or i in fused:
                continue
            groups.setdefault(jnp.dtype(leaf.dtype), []).append(i)
        gerrs = prefetch_lib.plan_group_errors(stacked, layer_plan, n,
                                               fused_ids, plan)
        L = int(stacked[0].shape[0]) if stacked else 0
        for (dt, ids), err in zip(groups.items(), gerrs):
            m = sum(int(np.prod(stacked[i].shape[1:])) // n for i in ids)
            add(overlap.two_level_gather_wire_bytes(m * isz(dt), plan),
                times=gas * 2 * (L + 1))
            add(overlap.two_level_rs_wire_bytes(m * 4, plan,
                                                err is not None),
                times=gas * L)
        # fused collective-matmul leaves: per layer per microbatch, two
        # all-gather+matmul streams (forward + dx) and one exact
        # matmul+reduce-scatter (dw)
        for i in fused_ids:
            leaf = stacked[i]
            m = int(np.prod(leaf.shape[1:])) // n
            add(overlap.two_level_gather_wire_bytes(
                m * isz(leaf.dtype), plan), times=gas * 2 * L)
            add(overlap.two_level_rs_wire_bytes(m * 4, plan, False),
                times=gas * L)
        # step-persistent outer leaves: one gather + one grad RS per step
        # (gas > 1 hoists the gathers; gas == 1 is one microbatch)
        for k in params:
            if k == subtree:
                continue
            op = self.zero.explicit_shard_plan(params[k],
                                               specs=param_spec_tree[k])
            for leaf, e in zip(jax.tree_util.tree_leaves(params[k]), op):
                if e is None:
                    continue
                m = leaf.size // n
                add(overlap.two_level_gather_wire_bytes(
                    m * isz(leaf.dtype), plan))
                add(overlap.two_level_rs_wire_bytes(
                    m * 4, plan, prefetch_lib.outer_compress(m, plan)))
        # replicated-leaf bucket leg: the ISSUE-10 two-level exchange,
        # once per step; flat baseline = ring allreduce (RS + AG)
        repl_shapes = [l.shape for l, e in zip(
            jax.tree_util.tree_leaves(params), full_plan) if e is None]
        compressed_buckets = 0
        if repl_shapes:
            buckets = overlap.plan_buckets(repl_shapes, plan.bucket_elems,
                                           n)
            flags = overlap.plan_bucket_compression(buckets, plan)
            compressed_buckets = int(sum(flags))
            w = overlap.hierarchy_wire_bytes(buckets, flags, plan)
            intra += w["intra"]
            inter += w["inter"]
            flat_inter += sum(2 * (n - 1) * (b.padded // n) * 4
                              * plan.inter // n for b in buckets)
        self._pf_wire_model = {
            "intra": int(intra), "inter": int(inter),
            "inter_uncompressed": int(flat_inter)}
        self.flight_recorder.record(
            "comm_hierarchy_plan", stream="zero3_prefetch",
            groups=len(groups),
            compressed=sum(1 for e in gerrs if e is not None)
            + compressed_buckets,
            inter=plan.inter, intra=plan.intra,
            policy=plan.compression,
            min_bucket_bytes=plan.min_bucket_bytes)
        self.comm_hierarchy = plan

    def _comm_wire_step(self):
        """Per-step comm accounting for the compressed train paths: the
        onebit_freeze ring event at the warmup→compressed transition,
        and (hierarchical path only) the ``comm/bytes_on_wire/*``
        counter advance from the trace-time cost model. Which phase ran
        is mirrored from the host counters — the optimizer's own count
        lives on device and reading it back would be a sync. fp16
        overflow skips lag the optimizer count behind global_steps;
        ``self.skipped_steps`` (the steps_per_print-boundary-synced
        mirror) corrects for them, so the mirror can misattribute at
        most the steps between an overflow and the next boundary.
        The hierarchical stage-3 prefetch stream (ISSUE 16) advances
        the same counters from its own single-phase model (no warmup
        — the policy is static from step one).
        Returns the step's byte dict or None."""
        if self._compressed_comm_active():
            freeze = int(getattr(self.optimizer, "freeze_step", 0) or 0)
            frozen = (self.global_steps - self.skipped_steps) > freeze
            if frozen and not getattr(self, "_onebit_freeze_recorded",
                                      False):
                self._onebit_freeze_recorded = True
                self.flight_recorder.record(
                    "onebit_freeze", step=self.global_steps,
                    freeze_step=freeze,
                    hierarchical=getattr(self, "_comm_wire_model", None)
                    is not None)
            model = getattr(self, "_comm_wire_model", None)
            if model is None:
                return None
            w = model["compressed" if frozen else "warmup"]
        else:
            w = getattr(self, "_pf_wire_model", None)
            if w is None:
                return None
        reg = self.telemetry
        reg.counter("comm/bytes_on_wire/intra").inc(w["intra"])
        reg.counter("comm/bytes_on_wire/inter").inc(w["inter"])
        reg.counter("comm/bytes_on_wire/inter_uncompressed").inc(
            w["inter_uncompressed"])
        reg.gauge("comm/bytes_per_step/intra").set(w["intra"])
        reg.gauge("comm/bytes_per_step/inter").set(w["inter"])
        reg.gauge("comm/bytes_per_step/inter_uncompressed").set(
            w["inter_uncompressed"])
        return w

    def _telemetry_step(self, batch, loss):
        """Per-step recording (sync-free) + the steps_per_print-boundary
        window fold. Between boundaries only host counters move; AT the
        boundary the loss readback — the same fence _report_progress
        pays right after — closes a wall-clock window whose mean is the
        honest per-step time (the SynchronizedWallClockTimer
        sync-per-read pattern, retired). The boundary readback is also
        where the watchdog's NaN/inf rule sees the loss — the one
        fence the anomaly layer is allowed to ride (ISSUE 6)."""
        reg = self.telemetry
        reg.counter("train/steps").inc()
        reg.counter("train/samples").inc(self.train_batch_size())
        tokens = 0
        if isinstance(batch, dict) and "input_ids" in batch:
            tokens = int(np.prod(batch["input_ids"].shape))
        if tokens:
            reg.counter("train/tokens").inc(tokens)
        self._tel_window_tokens += tokens
        # swap tier: host seconds this step actually BLOCKED on disk I/O
        # (the pipelined schedules shrink this toward zero while the
        # bytes_read/written counters keep moving — I/O hidden behind
        # compute). Host timers only, sync-free.
        stall = 0.0
        have_swap = self._param_swapper is not None
        if have_swap:
            stall += self._param_swapper.take_stall_s()
        opt_swapper = getattr(self._host_runner, "swapper", None)
        if opt_swapper is not None:
            have_swap = True
            stall += opt_swapper.take_stall_s()
        if have_swap:
            reg.histogram("swap/stall_s").observe(stall)
            if self.watchdog is not None:
                # host wall timer the swapper already kept — no fence
                self.watchdog.observe_swap_stall(
                    stall, step=self.global_steps)
        wire = self._comm_wire_step()
        self.flight_recorder.record(
            "step", step=self.global_steps, tokens=tokens,
            samples=self.train_batch_size(),
            **({"swap_stall_s": stall} if have_swap else {}),
            **({"comm_intra_bytes": wire["intra"],
                "comm_inter_bytes": wire["inter"]} if wire else {}))
        if self.global_steps % self.steps_per_print() != 0:
            return
        # per-rank SELF step time (ISSUE 12): host time this rank OWNS
        # per step — window wall time to ARRIVE at this fence
        # (pre-readback stamp) minus the seconds spent blocked inside
        # the step-dispatch calls. In synchronous SPMD every rank's
        # FENCED wall time converges to the slowest rank, and a
        # backend that executes cross-process collectives synchronously
        # parks the healthy rank inside dispatch — so only the
        # remainder (driver loop, park/unpark, swap stalls, GC pauses,
        # an injected sleep) is attributable to THIS rank. First
        # (compile) window dropped like the fenced one.
        t_arrive = time.perf_counter()
        steps_in_window = self.global_steps - self._tel_window_step0
        if steps_in_window > 0 and self._tel_window_t0 is not None \
                and self._tel_window_step0 > 0:
            self._tel_last_host_step_s = max(
                (t_arrive - self._tel_window_t0
                 - self._tel_window_dispatch_s), 0.0) / steps_in_window
            reg.histogram("train/host_step_s").observe(
                self._tel_last_host_step_s)
        lval = float(jax.device_get(loss))  # sync-ok: steps_per_print boundary
        self.flight_recorder.record("loss", step=self.global_steps,
                                    loss=lval)
        if self.watchdog is not None:
            self.watchdog.check_loss(lval, step=self.global_steps)
        self._telemetry_fold(batch)
        self._telemetry_export()
        # ISSUE 12: cross-rank aggregation rides the fence the loss
        # readback above already paid — every rank reaches this exact
        # boundary in SPMD lockstep, so the allgather is aligned. The
        # just-closed window's step time is threaded directly (the
        # process-wide registry may hold another engine's history).
        if self._cluster is not None:
            # the loss readback above is NOT a sufficient fence for the
            # exchange: the loss chain is independent of the grad
            # allreduces, so the step program's collectives can still
            # be in flight (see _fence_step_program)
            self._fence_step_program()
            self._guard_enter("exchange", self.global_steps)
            try:
                self._cluster.exchange_from_registry(
                    loss=lval, step=self.global_steps,
                    overrides={"step_time_s": self._cluster_step_value(),
                               "swap_stall_s": stall if have_swap
                               else None})
            finally:
                self._guard_exit()
            # re-open the window AFTER the exchange (same rule as the
            # fold's MFU-pricing re-stamp): the allgather blocks until
            # the SLOWEST rank arrives, and charging that wait to the
            # next window would hand every healthy rank the straggler's
            # time — the exact skew signal this plane exists to expose
            self._tel_window_dispatch_s = 0.0
            self._tel_window_t0 = time.perf_counter()
        self._tel_last_fence_ts = time.time()

    def _cluster_step_value(self):
        """The per-rank step time the cluster vector carries (ISSUE
        12): single-process the fenced window mean IS self time (no
        peer to wait on); multi-process the host-arrival component —
        the fenced figure converges to the slowest rank under the
        boundary collectives, which would blind the straggler rule."""
        if jax.process_count() == 1:
            return self._tel_last_step_s
        return self._tel_last_host_step_s

    def _telemetry_priced(self):
        """Whether the MFU cost analysis may be priced: an explicit
        ``lower().compile()`` re-traces the train fn outside the jit
        call cache (a real recompile when no persistent XLA cache is
        on), so it only happens — ONCE per engine — for engines whose
        config opted into a telemetry export, or on an explicit
        telemetry_flush()."""
        return self._config.monitor_config.enabled \
            or self._config.tensorboard_config.enabled

    def _telemetry_fold(self, batch=None, price_mfu=None):
        """Close the open measurement window (caller has fenced): one
        step-time observation (window mean), throughput gauges, MFU, and
        the memory gauges. Windows containing step 0 are dropped — they
        measure compile, not steady state."""
        reg = self.telemetry
        now = time.perf_counter()
        if self._tel_window_t0 is not None:
            steps = self.global_steps - self._tel_window_step0
            window_s = now - self._tel_window_t0
            if steps > 0 and window_s > 0 and self._tel_window_step0 > 0:
                step_s = window_s / steps
                self._tel_last_step_s = step_s
                reg.histogram("train/step_time_s").observe(step_s)
                self.flight_recorder.record(
                    "window", step=self.global_steps, steps=steps,
                    step_s=step_s)
                if self.watchdog is not None:
                    # outlier check on the already-fenced window mean
                    self.watchdog.observe_step_time(
                        step_s, step=self.global_steps)
                reg.gauge("train/samples_per_sec").set(
                    steps * self.train_batch_size() / window_s)
                if self._tel_window_tokens:
                    reg.gauge("train/tokens_per_sec").set(
                        self._tel_window_tokens / window_s)
                if price_mfu is None:
                    price_mfu = self._telemetry_priced()
                self._telemetry_mfu(batch, step_s, price=price_mfu)
        self._tel_window_step0 = self.global_steps
        self._tel_window_tokens = 0
        self._telemetry_memory_gauges()
        # open the next window AFTER the fold's own work (the one-time
        # MFU pricing retrace can take seconds — charging it to the
        # next window would corrupt its step-time observation)
        self._tel_window_dispatch_s = 0.0
        self._tel_window_t0 = time.perf_counter()

    def _telemetry_mfu(self, batch, step_s, price=False):
        """MFU as a first-class logged metric: flops/step from the
        COMPILED train step's XLA cost analysis (exact, fusion-aware)
        over the mesh's peak. Host-offload engines skip it: their step
        is not one compiled program."""
        if self._host_runner is not None or step_s <= 0:
            return
        if self._tel_flops_per_step is None and batch is not None and price:
            from deepspeed_tpu.profiling.flops_profiler import \
                compiled_step_flops
            self._tel_flops_per_step = compiled_step_flops(
                self._jit_train_batch, self.state, batch, self._rng)
        flops = self._tel_flops_per_step
        if not flops:
            return
        from deepspeed_tpu.profiling.flops_profiler import peak_device_flops
        reg = self.telemetry
        # cost_analysis() of a partitioned module reports PER-DEVICE
        # flops (verified on an 8-device SPMD matmul: 2N^3/8, not
        # 2N^3): per-device flops over ONE device's peak IS the MFU
        # under uniform sharding; the flops gauge scales to the global
        # step figure
        ndev = int(self.mesh.devices.size)
        dev = self.mesh.devices.flat[0]
        reg.gauge("train/flops_per_step").set(flops * ndev)
        reg.gauge("train/mfu").set(
            flops / step_s / peak_device_flops(dev))

    def _telemetry_memory_gauges(self):
        """Satellite of the scalar stream: live-gathered-parameter bytes
        of the stage3_prefetch pipeline (utils/memory.py — previously
        only warned), the prefetch window breakdown, and host RSS."""
        from deepspeed_tpu.utils import memory as memory_lib
        reg = self.telemetry
        # host RSS, live-gathered window, per-device HBM where the
        # backend exposes it — one canonical observable list
        for k, v in memory_lib.memory_metrics().items():
            reg.gauge(f"memory/{k}").set(v)
        stats = self.prefetch_live_param_stats()
        if stats:
            reg.gauge("memory/prefetch_live_param_elements").set(
                stats["live_param_elements"])
            reg.gauge("memory/prefetch_per_layer_gather_bytes").set(
                stats["per_layer_gather_bytes"])
            reg.gauge("memory/prefetch_outer_gather_bytes").set(
                stats["outer_gather_bytes"])

    def _telemetry_exporters(self):
        mc = self._config.monitor_config
        out = []
        if mc.enabled:
            if self._tel_exporter is None:
                from deepspeed_tpu.telemetry.registry import (
                    JsonlExporter, _process_rank)
                path = mc.jsonl_path or os.path.join(
                    mc.output_path,
                    f"telemetry_rank{_process_rank()}.jsonl")
                try:
                    self._tel_exporter = JsonlExporter(
                        path, self.telemetry,
                        max_bytes=int(mc.jsonl_max_mb * 2**20),
                        max_files=mc.jsonl_max_files)
                except OSError as e:
                    logger.warning(f"telemetry JSONL unavailable: {e}")
                    self._tel_exporter = False
            if self._tel_exporter:
                out.append(self._tel_exporter)
        if self._config.tensorboard_config.enabled:
            if self._tel_bridge is None:
                from deepspeed_tpu.telemetry.registry import SummaryBridge
                writer = self._summary_writer()
                self._tel_bridge = SummaryBridge(writer, self.telemetry) \
                    if writer is not None else False
            if self._tel_bridge:
                out.append(self._tel_bridge)
        return out

    def _telemetry_export(self):
        exporters = self._telemetry_exporters()
        if not exporters:
            return
        snap = self.telemetry.snapshot()
        for e in exporters:
            e.export(self.global_steps, snapshot=snap)

    def telemetry_snapshot(self):
        """The current registry snapshot (no fence, no fold)."""
        return self.telemetry.snapshot()

    def telemetry_flush(self, batch=None):
        """Fence, fold the open window, export, and return the
        snapshot — a programmatic steps_per_print boundary for bench /
        notebook use off the print cadence. Pass the current batch to
        (lazily) price MFU."""
        if self.state is not None:
            # fence on a DERIVED value: a device_get of global_step
            # itself would populate that array's client-side npy cache
            # and zero out any later fence probe on it (bench.py
            # measures the tunnel RTT exactly that way)
            int(jax.device_get(self.state.global_step + 0))  # sync-ok: flush
        self._telemetry_fold(batch, price_mfu=batch is not None)
        self._telemetry_export()
        return self.telemetry.snapshot()

    def _summary_writer(self):
        if getattr(self, "_summary_writer_obj", None) is None:
            try:
                from deepspeed_tpu.utils.monitor import SummaryEventWriter
                tb = self._config.tensorboard_config
                self._summary_writer_obj = SummaryEventWriter(
                    tb.output_path, tb.job_name)
            except Exception as e:
                logger.warning(f"summary writer unavailable: {e}")
                self._summary_writer_obj = False
        return self._summary_writer_obj or None

    def _sync_skipped_steps(self):
        if self.state is not None:
            self.skipped_steps = int(jax.device_get(self.state.skipped_steps))

    def _report_progress(self, loss):
        lr = self.get_lr()
        self._sync_skipped_steps()
        log_dist(f"step={self.global_steps}, skipped={self.skipped_steps}, "
                 f"loss={float(jax.device_get(loss)):.6f}, lr={lr}, "
                 f"loss_scale={self.loss_scale}", ranks=[0])

    # ------------------------------------------------------------------
    # dataloader factory (reference deepspeed_io engine.py:928)
    # ------------------------------------------------------------------
    def deepspeed_io(self, dataset, batch_size=None, route="train",
                     data_sampler=None, collate_fn=None, num_local_io_workers=None):
        # each yielded batch is the *global* micro batch — GSPMD shards it
        # over the data axis (the reference instead gives each rank a
        # per-rank loader of micro_batch_size, dataloader.py:33)
        batch_size = batch_size or (self.train_micro_batch_size_per_gpu()
                                    * self.dp_world_size)
        return DeepSpeedDataLoader(
            dataset,
            batch_size=batch_size,
            data_parallel_world_size=1,   # GSPMD shards the global batch
            data_parallel_rank=0,
            collate_fn=collate_fn or self.collate_fn,
            seed=self._config.seed)

    # ------------------------------------------------------------------
    # checkpointing (reference engine.py:1562-1891)
    # ------------------------------------------------------------------
    def _ckpt_extra(self, client_state=None):
        """The counters + scheduler state every save carries — shared
        by the blocking save and the async snapshot path."""
        self._sync_skipped_steps()
        extra = {
            "global_steps": self.global_steps,
            "micro_steps": self.micro_steps,
            "global_samples": self.global_samples,
            "skipped_steps": self.skipped_steps,
            "client_state": client_state or {},
        }
        if isinstance(self.lr_scheduler, _Schedule):
            extra["lr_scheduler"] = self.lr_scheduler.state_dict()
        return extra

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True):
        from deepspeed_tpu.runtime import checkpointing as ckpt
        assert self.state is not None, "no state to save"
        self._ensure_params_resident()
        tag = tag or f"global_step{self.global_steps}"
        extra = self._ckpt_extra(client_state)
        state = self.state
        if self._host_runner is not None:
            # persist fp32 master + host moments, not the bf16 device copy
            state = TrainState(params=self._host_runner.params_tree(),
                               opt_state=self._host_runner.state_dict(),
                               scaler=self.state.scaler,
                               global_step=self.state.global_step,
                               skipped_steps=self.state.skipped_steps)
        ckpt.save_checkpoint(save_dir, tag, state, extra,
                             save_latest=save_latest,
                             zero_stage=self.zero_optimization_stage())
        return True

    def train_step_memory_stats(self, batch):
        """Compiled-executable memory breakdown of the jitted train step
        (XLA buffer assignment — exact, not sampled; works on tunneled
        backends where device.memory_stats() is unavailable). Call after
        at least one train_batch so the executable cache is warm; returns
        bytes for arguments (resident state), temporaries (activations,
        remat workspaces), outputs, and the peak estimate the compiler
        budgeted. The SURVEY §7 'memory evidence' instrument."""
        assert self._jit_train_batch is not None and self.state is not None, \
            "run a train_batch first (the stats read the compiled step)"
        if self._host_runner is not None:
            raise NotImplementedError(
                "ZeRO-Offload engines split the step across device grads "
                "and a host optimizer; the on-device fused step these "
                "stats would compile is not the program that runs")
        batch = self._globalize_batch(batch)
        lowered = self._jit_train_batch.lower(self.state, batch, self._rng)
        ma = lowered.compile().memory_analysis()
        args = int(ma.argument_size_in_bytes)
        temp = int(ma.temp_size_in_bytes)
        out = int(ma.output_size_in_bytes)
        alias = int(ma.alias_size_in_bytes)
        return {
            "argument_bytes": args,
            "temp_bytes": temp,
            "output_bytes": out,
            "alias_bytes": alias,
            "generated_code_bytes": int(ma.generated_code_size_in_bytes),
            # donated state aliases outputs, so peak ≈ args + temps + code
            "peak_hbm_estimate_bytes": args + temp + max(out - alias, 0)
            + int(ma.generated_code_size_in_bytes),
        }

    def _ckpt_shardings(self, struct):
        """Target shardings for sharded checkpoint loading — derived from
        the ShapeDtypeStruct trees in the checkpoint index, so each process
        reads only the windows of its own shards."""
        try:
            param_sh = self.zero.param_shardings(struct["params"])
            opt_sh = self.zero.opt_state_shardings(
                struct.get("opt_state", {}), struct["params"],
                getattr(self.optimizer, "param_like_state_fields", ()))
        except Exception as e:
            logger.warning(f"sharded-load sharding derivation failed ({e}); "
                           f"assembling full arrays on host")
            return None
        repl = NamedSharding(self.mesh, PartitionSpec())
        out = {"params": param_sh, "opt_state": opt_sh,
               "scaler": jax.tree_util.tree_map(lambda _: repl,
                                                struct.get("scaler", {})),
               "global_step": repl, "skipped_steps": repl}
        return out

    def load_checkpoint(self, load_dir, tag=None, load_module_only=False,
                        load_optimizer_states=True, load_lr_scheduler_states=True):
        from deepspeed_tpu.runtime import checkpointing as ckpt
        # an explicit load expresses intent — auto-resume must never
        # clobber it afterwards (global_steps==0 is NOT a reliable
        # proxy: a step-0 save or module-only restore lands there too)
        self._auto_resumed = True
        # an in-flight snapshot captures PRE-load state and its staging
        # dir would be swept as an orphan by the elastic route below —
        # abandon it before adopting different state
        if self._snapshotter is not None and self._snapshotter.in_flight:
            self._snapshotter.abort("load_checkpoint")
        # elastic-snapshot directories (runtime/elastic, ISSUE 7) load
        # through the validating snapshot reader — with fallback to the
        # newest VALID generation when the pointed-at one is corrupt
        from deepspeed_tpu.runtime.elastic.snapshot import (
            has_snapshots, is_snapshot_dir)
        resolved = tag or ckpt.read_latest_tag(load_dir)
        # route by pointer/tag when one resolves; by SCAN when none
        # does (a crash before the first-ever `latest` write leaves a
        # committed snapshot with no pointer — resume's mtime walk
        # still finds it)
        if (resolved is not None and is_snapshot_dir(
                ckpt.resolve_ckpt_dir(load_dir, resolved))) \
                or (resolved is None and has_snapshots(load_dir)):
            from deepspeed_tpu.runtime.elastic.resume import elastic_resume
            res = elastic_resume(
                self, load_dir, tag=tag,
                load_module_only=load_module_only,
                load_optimizer_states=load_optimizer_states,
                load_lr_scheduler_states=load_lr_scheduler_states)
            if res is None:
                logger.warning(
                    f"no valid snapshot in {load_dir}, tag={tag}")
                return None, {}
            return res
        shardings_fn = None if self._offload_cfg.enabled \
            else self._ckpt_shardings
        # module-only restores substitute the live optimizer state below —
        # skip the (2x param bytes) opt_state shard reads entirely then
        want_opt = load_optimizer_states and not load_module_only
        loaded = ckpt.load_checkpoint(
            load_dir, tag, shardings_fn=shardings_fn,
            load_optimizer=want_opt or self.state is None)
        if loaded is None:
            logger.warning(f"Unable to find checkpoint in {load_dir}, tag={tag}")
            return None, {}
        state_tree, extra = loaded
        keep_live_opt = load_module_only or not load_optimizer_states
        self._adopt_ckpt_tree(state_tree, extra,
                              keep_live_opt=keep_live_opt,
                              load_lr=load_lr_scheduler_states)
        tag = tag or ckpt.read_latest_tag(load_dir)
        return tag, extra.get("client_state", {})

    def _adopt_ckpt_tree(self, state_tree, extra, keep_live_opt=False,
                         load_lr=True):
        """Adopt a loaded {params, opt_state, scaler, global_step,
        skipped_steps} tree + counter dict — shared by load_checkpoint
        and the elastic resume path (runtime/elastic/resume.py)."""
        if keep_live_opt and self.state is not None:
            # keep the live (possibly non-addressable) sharded opt_state
            # as-is — device_get would gather/fail on multi-host shards
            state_tree["opt_state"] = self.state.opt_state
        template = TrainState(
            params=state_tree["params"],
            opt_state=state_tree["opt_state"],
            scaler=state_tree["scaler"],
            global_step=jnp.asarray(state_tree["global_step"], jnp.int32),
            skipped_steps=jnp.asarray(state_tree["skipped_steps"], jnp.int32))
        if self._offload_cfg.enabled:
            self._adopt_loaded_state_offload(template)
        else:
            self._adopt_loaded_state(template)
        if self._param_offload_nvme:
            # un-park onto the LOADED params: the swap files still hold
            # pre-load weights, and a parked engine would otherwise swap
            # the stale copies back in on the next step (the next park
            # rewrites the files from the loaded weights). Also covers a
            # fresh engine restoring before any train_batch (no swapper
            # exists yet — the configured tier must not silently disable).
            if self._param_swapper is None:
                self._param_swapper = self._make_param_swapper()
            self._params_parked = False
        self.global_steps = extra.get("global_steps", 0)
        self.micro_steps = extra.get("micro_steps", 0)
        self.global_samples = extra.get("global_samples", 0)
        self.skipped_steps = extra.get("skipped_steps", 0)
        if load_lr and isinstance(self.lr_scheduler, _Schedule) \
                and "lr_scheduler" in extra:
            self.lr_scheduler.load_state_dict(extra["lr_scheduler"])

    def _adopt_loaded_state(self, template: TrainState):
        template = self._restore_error_lists(template)
        self.state_shardings = self._build_state_shardings(template)
        self.state = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(jnp.asarray(x), s),
            template, self.state_shardings)

    def _restore_error_lists(self, template: TrainState):
        """The checkpoint serializer rebuilds every container as a dict
        (checkpointing._unflatten), so the hierarchical comm path's
        per-BUCKET error LISTS come back digit-keyed — and uncompressed
        buckets' None entries were dropped at save. Rebuild the lists
        against the plan's bucket count so the loaded residuals land in
        the positions the train program's per-bucket zip expects."""
        if not isinstance(template.opt_state, dict):
            return template
        if self._prefetch_active():
            return self._restore_prefetch_error_state(template)
        plan = self._comm_plan()
        if plan is None:
            return self._restore_flat_error_trees(template)
        from deepspeed_tpu.parallel import overlap
        # canonical zero state for the CURRENT policy — the checkpoint
        # may have been written under a different compression/bucket
        # config, so loaded residuals only land where the shapes still
        # agree; anything else resets to zero (or drops) with a warning
        # instead of tripping a cryptic trace error on a None operand
        canon = dict(zip(
            ("worker_error", "server_error"),
            overlap.hierarchical_error_states(template.params, plan)))
        dp = mesh_lib.mesh_axis_size(self.mesh, mesh_lib.DATA_AXIS)
        opt_state, changed = dict(template.opt_state), False
        for key, zeros in canon.items():
            v = opt_state.get(key)
            if isinstance(v, list):
                continue        # live state kept as-is (keep_live_opt)
            loaded = v if isinstance(v, dict) \
                and all(k.isdigit() for k in v) else {}
            out = []
            for i, z in enumerate(zeros):
                lv = loaded.get(str(i))
                if z is None:
                    if lv is not None:
                        logger.warning(
                            f"{key}[{i}]: bucket is uncompressed under "
                            f"the current comm.hierarchy policy — "
                            f"checkpointed residual dropped")
                    out.append(None)
                elif lv is not None \
                        and tuple(np.shape(lv)) == (dp,) + z.shape:
                    out.append(lv)
                else:
                    if lv is not None:
                        logger.warning(
                            f"{key}[{i}]: checkpointed residual shape "
                            f"{np.shape(lv)} does not match the current "
                            f"plan ({(dp,) + z.shape}) — reset to zero")
                    out.append(jnp.zeros((dp,) + z.shape, z.dtype))
            opt_state[key] = out
            changed = True
        return template.replace(opt_state=opt_state) if changed \
            else template

    def _restore_flat_error_trees(self, template: TrainState):
        """The reverse policy flip: a checkpoint written by the
        HIERARCHICAL path (per-bucket error lists, digit-keyed after the
        round trip) resumed on the FLAT compressed path. The bucket-flat
        residuals have no per-leaf interpretation here — reset to zero
        per-leaf trees (warned) instead of handing
        tree_compressed_allreduce a digit-dict and crashing the trace."""
        if not self._compressed_comm_active():
            return template
        opt_state = template.opt_state

        def hier_format(v):
            # digit-keyed = a round-tripped per-bucket list; None/absent =
            # an all-None ("never"-policy) list the serializer dropped
            return v is None or (isinstance(v, dict) and v
                                 and all(s.isdigit() for s in v))
        needs = [k for k in ("worker_error", "server_error")
                 if opt_state and hier_format(opt_state.get(k))]
        if not needs:
            return template
        logger.warning(
            f"checkpoint carries hierarchical per-bucket error state "
            f"({needs}) but the engine runs the FLAT compressed "
            f"exchange — error feedback resets to zero")
        from deepspeed_tpu.parallel import compression as comp
        dp = mesh_lib.mesh_axis_size(self.mesh, mesh_lib.DATA_AXIS)
        we, se = comp.init_error_states(template.params, dp)
        bump = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda x: jnp.zeros((dp,) + x.shape, x.dtype), t)
        opt_state = dict(opt_state)
        opt_state["worker_error"] = bump(we)
        opt_state["server_error"] = bump(se)
        return template.replace(opt_state=opt_state)

    def _restore_prefetch_error_state(self, template: TrainState):
        """Checkpoint reconciliation for the hierarchical prefetch
        stream's ``pf_*`` residuals (ISSUE 16), riding the PR-10 rules:
        the serializer digit-keys the per-group/per-bucket lists and
        drops None entries, and the checkpoint may have been written
        under a different hierarchy/compression policy — rebuild
        canonical zero state for the CURRENT policy and keep only
        shape-matching residuals (reset or drop the rest, warned)."""
        canon = self._prefetch_error_states(template.params)
        opt_state = dict(template.opt_state)
        stale = [k for k in self._PF_ERR_KEYS
                 if k in opt_state and k not in canon]
        if stale:
            logger.warning(
                f"checkpoint carries prefetch error state {stale} but "
                f"the engine runs the flat stage-3 stream — dropped")
            for k in stale:
                del opt_state[k]
        if not canon:
            return template.replace(opt_state=opt_state) if stale \
                else template

        def fit_list(key, zeros, loaded):
            if isinstance(loaded, list):
                return loaded      # live state kept as-is (keep_live_opt)
            ld = loaded if isinstance(loaded, dict) and loaded \
                and all(s.isdigit() for s in loaded) else {}
            out = []
            for i, z in enumerate(zeros):
                lv = ld.get(str(i))
                if z is None:
                    if lv is not None:
                        logger.warning(
                            f"{key}[{i}]: slow hop is exact under the "
                            f"current comm.hierarchy policy — "
                            f"checkpointed residual dropped")
                    out.append(None)
                elif lv is not None and tuple(np.shape(lv)) == z.shape:
                    out.append(lv)
                else:
                    if lv is not None:
                        logger.warning(
                            f"{key}[{i}]: checkpointed residual shape "
                            f"{np.shape(lv)} does not match the current "
                            f"plan ({z.shape}) — reset to zero")
                    out.append(jnp.zeros(z.shape, z.dtype))
            return out

        def fit(key, zeros, loaded):
            if isinstance(zeros, dict):
                src = loaded if isinstance(loaded, dict) else {}
                return {k: fit(f"{key}.{k}", v, src.get(k))
                        for k, v in zeros.items()}
            return fit_list(key, zeros, loaded)

        for key, zeros in canon.items():
            opt_state[key] = fit(key, zeros, opt_state.get(key))
        return template.replace(opt_state=opt_state)

    def _build_state_shardings(self, state: TrainState) -> TrainState:
        """Shardings for a full TrainState per ZeRO stage + the
        compressed-comm special cases — shared by _init_state and the
        checkpoint/elastic adoption paths (which previously rebuilt a
        subset of this and mis-sharded the error-feedback state)."""
        params, opt_state, scaler = state.params, state.opt_state, \
            state.scaler
        param_sh = self.zero.param_shardings(params)
        opt_sh = self.zero.opt_state_shardings(
            opt_state, params,
            getattr(self.optimizer, "param_like_state_fields", ()))
        state_mesh = self.mesh
        if self._compressed_comm_active():
            plan = self._comm_plan()
            if plan is not None:
                # hierarchical path (ISSUE 10): rest the whole TrainState
                # on the split-mesh view. The device layout is identical
                # (metadata-only), but the hierarchical train program's
                # shard_map shardings then match its inputs from step one
                # instead of forcing a second-step retrace when the first
                # output comes back on the split mesh.
                state_mesh = mesh_lib.split_data_axis(self.mesh, plan.inter)

                def resplit(s):
                    spec = tuple(
                        (plan.inter_axis, plan.intra_axis)
                        if p == mesh_lib.DATA_AXIS else p
                        for p in tuple(s.spec))
                    return NamedSharding(state_mesh, PartitionSpec(*spec))
                param_sh = jax.tree_util.tree_map(resplit, param_sh)
                opt_sh = jax.tree_util.tree_map(resplit, opt_sh)
            # per-device error-feedback state: leading [dp] axis sharded
            # over data so every worker keeps exactly its own error tensors
            err_sh = NamedSharding(
                state_mesh,
                PartitionSpec((plan.inter_axis, plan.intra_axis)
                              if plan is not None else mesh_lib.DATA_AXIS))
            for key in ("worker_error", "server_error"):
                if key in opt_state:
                    opt_sh[key] = jax.tree_util.tree_map(
                        lambda _: err_sh, opt_state[key])
        elif self._prefetch_active():
            plan = self._prefetch_hier_plan()
            if plan is not None:
                # hierarchical stage-3 stream (ISSUE 16): same
                # metadata-only split-mesh rest as the 1-bit path, plus
                # the pf_* residuals' per-device [dp] leading axis
                state_mesh = mesh_lib.split_data_axis(self.mesh, plan.inter)

                def resplit(s):
                    spec = tuple(
                        (plan.inter_axis, plan.intra_axis)
                        if p == mesh_lib.DATA_AXIS else p
                        for p in tuple(s.spec))
                    return NamedSharding(state_mesh, PartitionSpec(*spec))
                param_sh = jax.tree_util.tree_map(resplit, param_sh)
                opt_sh = jax.tree_util.tree_map(resplit, opt_sh)
                err_sh = NamedSharding(
                    state_mesh,
                    PartitionSpec((plan.inter_axis, plan.intra_axis)))
                for key in self._PF_ERR_KEYS:
                    if key in opt_state:
                        opt_sh[key] = jax.tree_util.tree_map(
                            lambda _: err_sh, opt_state[key])
        repl = NamedSharding(state_mesh, PartitionSpec())
        scaler_sh = jax.tree_util.tree_map(lambda _: repl, scaler)
        return TrainState(params=param_sh, opt_state=opt_sh,
                          scaler=scaler_sh, global_step=repl,
                          skipped_steps=repl)

    def _adopt_loaded_state_offload(self, template: TrainState):
        self._host_runner = self._make_offload_runner(template.params)
        if template.opt_state:
            self._host_runner.load_state_dict(template.opt_state)
        device_params = jax.tree_util.tree_map(
            lambda p: np.asarray(p, self.precision.compute_dtype)
            if np.issubdtype(np.asarray(p).dtype, np.floating) else
            np.asarray(p), template.params)
        surrogate = TrainState(params=device_params, opt_state={},
                               scaler=template.scaler,
                               global_step=template.global_step,
                               skipped_steps=template.skipped_steps)
        self._adopt_loaded_state(surrogate)

    def save_fp16_model(self, save_dir, save_filename="mp_rank_00_model_states.npz"):
        """Gathered model weights only (reference engine.py:1955)."""
        from deepspeed_tpu.runtime import checkpointing as ckpt
        self._ensure_params_resident()
        os.makedirs(save_dir, exist_ok=True)
        ckpt.save_tree(os.path.join(save_dir, save_filename), self.state.params)
