from deepspeed_tpu.moe.layer import (
    MoE, MoEMLP, TopKGate, load_balance_loss, expert_shardings,
    apply_with_losses)
