"""Mixture-of-Experts layer with expert parallelism — a beyond-reference
capability (the 2021 reference snapshot predates deepspeed/moe; SURVEY §2.3
marks EP "not present"). Built TPU-first:

- experts live stacked on a leading [E] axis sharded over the mesh's
  `expert` axis (aliased onto `data`, parallel/mesh.py:25), so expert
  weights are expert-parallel with zero per-expert module objects;
- top-k gating (Switch/GShard style) with capacity-factor truncation and
  the standard load-balancing auxiliary loss;
- dispatch/combine are einsums against a one-hot dispatch mask — under
  GSPMD the [tokens→experts] regroup lowers to the all_to_all the
  reference-era MoE implementations issue by hand;
- everything is dense-shaped and static (capacity fixes the expert batch),
  so XLA tiles it onto the MXU.
"""

import dataclasses
from typing import Any, Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.parallel import mesh as mesh_lib


def load_balance_loss(gate_probs, expert_mask):
    """Switch-transformer aux loss: E * sum_e f_e * P_e, where f_e is the
    fraction of tokens routed to expert e and P_e the mean gate prob."""
    E = gate_probs.shape[-1]
    f = expert_mask.mean(axis=0)          # [E] fraction of tokens
    p = gate_probs.mean(axis=0)           # [E] mean router prob
    return E * jnp.sum(f * p)


class TopKGate(nn.Module):
    """Router: logits → top-k expert assignment with capacity truncation.

    Returns (dispatch [T, E, C] one-hot, combine [T, E, C] weights,
    aux_loss). T = tokens, E = experts, C = capacity per expert.
    """
    num_experts: int
    k: int = 1
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):                 # x: [T, H]
        T = x.shape[0]
        E = self.num_experts
        C = max(1, int(np.ceil(self.capacity_factor * self.k * T / E)))
        logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                          param_dtype=self.param_dtype,
                          name="wg")(x.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)          # [T, E]

        dispatch = jnp.zeros((T, E, C), jnp.float32)
        combine = jnp.zeros((T, E, C), jnp.float32)
        remaining = probs
        mask_total = jnp.zeros((T, E), jnp.float32)
        for _ in range(self.k):
            choice = jnp.argmax(remaining, axis=-1)       # [T]
            onehot = jax.nn.one_hot(choice, E)            # [T, E]
            mask_total = mask_total + onehot
            # position of each token within its chosen expert's buffer
            pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot   # [T, E]
            keep = (pos < C).astype(jnp.float32) * onehot
            pos_c = jax.nn.one_hot(pos.sum(axis=-1).astype(jnp.int32), C)
            d = keep[:, :, None] * pos_c[:, None, :]      # [T, E, C]
            gate_w = (probs * onehot).sum(axis=-1)        # [T]
            dispatch = dispatch + d
            combine = combine + d * gate_w[:, None, None]
            remaining = remaining * (1.0 - onehot)        # mask for next k

        aux = load_balance_loss(probs, jnp.clip(mask_total, 0.0, 1.0))
        return dispatch, combine, aux


class MoEMLP(nn.Module):
    """Expert FFN bank: stacked [E, ...] kernels, expert-sharded over the
    mesh's expert axis when one exists."""
    num_experts: int
    d_model: int
    d_ff: int
    activation: Callable = nn.gelu
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xe):               # [E, C, H]
        E, C, H = xe.shape
        init = nn.initializers.normal(0.02)
        wi = self.param("wi", init, (E, H, self.d_ff), self.param_dtype)
        wo = self.param("wo", init, (E, self.d_ff, H), self.param_dtype)
        h = jnp.einsum("ech,ehf->ecf", xe, wi.astype(self.dtype))
        h = self.activation(h)
        return jnp.einsum("ecf,efh->ech", h, wo.astype(self.dtype))


class MoE(nn.Module):
    """Drop-in MoE block: [B, S, H] → [B, S, H] (+ aux loss via the
    'losses' mutable collection or returned when `return_aux`)."""
    num_experts: int
    d_ff: int
    k: int = 1
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    return_aux: bool = False

    @nn.compact
    def __call__(self, x):
        B, S, H = x.shape
        T = B * S
        flat = x.reshape(T, H)
        dispatch, combine, aux = TopKGate(
            self.num_experts, k=self.k,
            capacity_factor=self.capacity_factor,
            param_dtype=self.param_dtype, name="gate")(flat)

        # [T,H] → [E,C,H]: the token→expert regroup (GSPMD lowers this to
        # the EP all_to_all when experts are sharded)
        xe = jnp.einsum("tec,th->ech", dispatch.astype(self.dtype), flat)
        mesh = mesh_lib.current_mesh()
        if mesh is not None and \
                mesh_lib.mesh_axis_size(mesh, mesh_lib.DATA_AXIS) > 1 and \
                self.num_experts % mesh_lib.mesh_axis_size(
                    mesh, mesh_lib.DATA_AXIS) == 0:
            from jax.sharding import NamedSharding, PartitionSpec as P
            xe = jax.lax.with_sharding_constraint(
                xe, NamedSharding(mesh, P(mesh_lib.DATA_AXIS)))
        ye = MoEMLP(self.num_experts, H, self.d_ff, dtype=self.dtype,
                    param_dtype=self.param_dtype, name="experts")(xe)
        y = jnp.einsum("tec,ech->th", combine.astype(self.dtype), ye)
        y = y.reshape(B, S, H)

        if self.is_mutable_collection("losses"):
            self.sow("losses", "moe_aux", aux)
        if self.return_aux:
            return y, aux
        return y


def expert_shardings(params, mesh):
    """PartitionSpec tree sharding the stacked expert kernels over the
    expert(=data) axis; router + everything else replicated."""
    from jax.sharding import PartitionSpec as P

    def leaf(path, x):
        names = [str(getattr(p, "key", p)) for p in path]
        if "experts" in names and names[-1] in ("wi", "wo"):
            return P(mesh_lib.DATA_AXIS)
        return P()
    return jax.tree_util.tree_map_with_path(leaf, params)
