"""Mixture-of-Experts layer with expert parallelism — a beyond-reference
capability (the 2021 reference snapshot predates deepspeed/moe; SURVEY §2.3
marks EP "not present"). Built TPU-first:

- experts live stacked on a leading [E] axis sharded over the mesh's
  `expert` axis (aliased onto `data`, parallel/mesh.py:25), so expert
  weights are expert-parallel with zero per-expert module objects;
- top-k gating (Switch/GShard style) with per-group capacity-factor
  truncation and the standard load-balancing auxiliary loss; slot
  positions carry across the k rounds so second choices never collide
  with first choices in an expert's buffer;
- routing is GROUPED (GShard's group axis = batch row): dispatch/combine
  masks are [G, S, E, C] with C ∝ S/E, so their memory and the dispatch
  einsum cost scale with S² per group instead of (B·S)² global;
- dispatch/combine are einsums against one-hot masks — under GSPMD the
  [tokens→experts] regroup lowers to the all_to_all reference-era MoE
  implementations issue by hand;
- everything is dense-shaped and static (capacity fixes the expert
  batch), so XLA tiles it onto the MXU.

The aux loss is sown into the "losses" collection; the engine adds it to
the objective when the model opts in (GPT2Config.moe_experts).
"""

import dataclasses
from typing import Any, Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.parallel import mesh as mesh_lib


def load_balance_loss(gate_probs, expert_mask):
    """Switch-transformer aux loss: E * sum_e f_e * P_e, where f_e is the
    fraction of tokens routed to expert e and P_e the mean gate prob.
    Inputs [T, E]."""
    E = gate_probs.shape[-1]
    f = expert_mask.mean(axis=0)          # [E] fraction of tokens
    p = gate_probs.mean(axis=0)           # [E] mean router prob
    return E * jnp.sum(f * p)


class TopKGate(nn.Module):
    """Router: logits → top-k expert assignment with capacity truncation.

    Input [T, H] → (dispatch [T, E, C] one-hot, combine [T, E, C] weights,
    aux_loss). T = tokens, E = experts, C = capacity per expert. Slot
    occupancy accumulates across the k rounds, so a round-2 assignment
    lands after all round-1 tokens of the same expert and is dropped when
    the expert is full.
    """
    num_experts: int
    k: int = 1
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):                 # x: [T, H]
        T = x.shape[0]
        E = self.num_experts
        C = max(1, int(np.ceil(self.capacity_factor * self.k * T / E)))
        logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                          param_dtype=self.param_dtype,
                          name="wg")(x.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)          # [T, E]

        dispatch = jnp.zeros((T, E, C), jnp.float32)
        combine = jnp.zeros((T, E, C), jnp.float32)
        remaining = probs
        mask_total = jnp.zeros((T, E), jnp.float32)
        occupancy = jnp.zeros((E,), jnp.float32)          # filled slots
        for _ in range(self.k):
            choice = jnp.argmax(remaining, axis=-1)       # [T]
            onehot = jax.nn.one_hot(choice, E)            # [T, E]
            mask_total = mask_total + onehot
            # slot index = this round's order within the expert, offset by
            # slots already filled in earlier rounds
            pos = ((jnp.cumsum(onehot, axis=0) - 1.0)
                   + occupancy[None, :]) * onehot          # [T, E]
            keep = (pos < C).astype(jnp.float32) * onehot
            pos_c = jax.nn.one_hot(
                jnp.clip(pos.sum(axis=-1), 0, C - 1).astype(jnp.int32), C)
            d = keep[:, :, None] * pos_c[:, None, :]      # [T, E, C]
            gate_w = (probs * onehot).sum(axis=-1)        # [T]
            dispatch = dispatch + d
            combine = combine + d * gate_w[:, None, None]
            occupancy = occupancy + keep.sum(axis=0)
            remaining = remaining * (1.0 - onehot)        # mask for next k

        aux = load_balance_loss(probs, jnp.clip(mask_total, 0.0, 1.0))
        return dispatch, combine, aux


class MoEMLP(nn.Module):
    """Expert FFN bank: stacked [E, ...] kernels, expert-sharded over the
    mesh's expert axis when one exists. ``out_init_std`` lets residual
    stacks scale the output projection like their dense c_proj."""
    num_experts: int
    d_model: int
    d_ff: int
    activation: Callable = nn.gelu
    dropout: float = 0.0
    out_init_std: float = 0.02
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xe, deterministic=True):   # [E, C, H]
        E, C, H = xe.shape
        wi = self.param("wi", nn.initializers.normal(0.02),
                        (E, H, self.d_ff), self.param_dtype)
        wo = self.param("wo", nn.initializers.normal(self.out_init_std),
                        (E, self.d_ff, H), self.param_dtype)
        h = jnp.einsum("ech,ehf->ecf", xe, wi.astype(self.dtype))
        h = self.activation(h)
        if self.dropout > 0:
            h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        return jnp.einsum("ecf,efh->ech", h, wo.astype(self.dtype))


class MoE(nn.Module):
    """Drop-in MoE block: [B, S, H] → [B, S, H]. The load-balancing aux
    loss is sown into the 'losses' collection (and returned when
    ``return_aux``); batch rows are the routing groups."""
    num_experts: int
    d_ff: int
    k: int = 1
    capacity_factor: float = 1.25
    dropout: float = 0.0
    out_init_std: float = 0.02
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    return_aux: bool = False

    @nn.compact
    def __call__(self, x, deterministic=True):
        B, S, H = x.shape
        E = self.num_experts
        # one router shared across groups; routing per batch row bounds the
        # one-hot masks at [B, S, E, C] with C ∝ S/E
        gate = nn.vmap(TopKGate, in_axes=0, out_axes=0,
                       variable_axes={"params": None},
                       split_rngs={"params": False})(
            E, k=self.k, capacity_factor=self.capacity_factor,
            param_dtype=self.param_dtype, name="gate")
        dispatch, combine, aux = gate(x)          # [B,S,E,C], aux [B]
        aux = aux.mean()

        C = dispatch.shape[-1]
        # [B,S,H] → [E, B*C, H]: the token→expert regroup (GSPMD lowers
        # this to the EP all_to_all when experts are sharded)
        xe = jnp.einsum("bsec,bsh->ebch", dispatch.astype(self.dtype), x)
        xe = xe.reshape(E, B * C, H)
        mesh = mesh_lib.current_mesh()
        eaxis = _expert_axis(mesh)
        ep = eaxis is not None and \
            E % mesh_lib.mesh_axis_size(mesh, eaxis) == 0
        if ep:
            from jax.sharding import NamedSharding, PartitionSpec as P
            xe = jax.lax.with_sharding_constraint(
                xe, NamedSharding(mesh, P(eaxis)))
        ye = MoEMLP(E, H, self.d_ff, dropout=self.dropout,
                    out_init_std=self.out_init_std, dtype=self.dtype,
                    param_dtype=self.param_dtype,
                    name="experts")(xe, deterministic)
        ye = ye.reshape(E, B, C, H)
        y = jnp.einsum("bsec,ebch->bsh", combine.astype(self.dtype), ye)

        if self.is_mutable_collection("losses"):
            self.sow("losses", "moe_aux", aux)
        if self.return_aux:
            return y, aux
        return y


def _expert_axis(mesh):
    """The mesh axis experts shard over: a dedicated 'expert' axis when the
    mesh has one (EP independent of DP), otherwise aliased onto 'data'
    (classic expert-parallel-over-DP), None when neither is non-trivial."""
    if mesh is None:
        return None
    if mesh_lib.mesh_axis_size(mesh, mesh_lib.EXPERT_AXIS) > 1:
        return mesh_lib.EXPERT_AXIS
    if mesh_lib.mesh_axis_size(mesh, mesh_lib.DATA_AXIS) > 1:
        return mesh_lib.DATA_AXIS
    return None


def expert_shardings(params, mesh):
    """PartitionSpec tree sharding the stacked expert kernels over the
    expert axis (dedicated 'expert' axis when present, else aliased onto
    'data'); router + everything else replicated. Kernels whose expert
    count does not divide the axis stay replicated (matching the guard
    MoE.__call__ applies)."""
    from jax.sharding import PartitionSpec as P
    eaxis = _expert_axis(mesh)
    axis = mesh_lib.mesh_axis_size(mesh, eaxis) if eaxis else 0

    def leaf(path, x):
        names = [str(getattr(p, "key", p)) for p in path]
        if "experts" in names and names[-1] in ("wi", "wo") \
                and axis > 1 and x.shape[0] % axis == 0:
            return P(eaxis)
        return P()
    return jax.tree_util.tree_map_with_path(leaf, params)


def apply_with_losses(model, variables, *args, **kwargs):
    """Run a model that contains MoE blocks and return
    ``(output, aux_loss_sum)`` — the documented way for CUSTOM loss
    functions to include the router load-balancing term (the engine's
    default loss does this automatically; a user loss_fn that calls
    ``model.apply`` directly would silently train an unbalanced router).

    Usage inside a loss_fn::

        def loss_fn(params, batch):
            out, aux = moe.apply_with_losses(model, {"params": params}, x)
            return my_loss(out, y) + coeff * aux
    """
    import jax.numpy as jnp
    out, vs = model.apply(variables, *args, mutable=["losses"], **kwargs)
    aux = sum(jnp.sum(l) for l in
              jax.tree_util.tree_leaves(vs.get("losses", {})))
    return out, aux
