"""Mixture-of-Experts layer with expert parallelism — a beyond-reference
capability (the 2021 reference snapshot predates deepspeed/moe; SURVEY §2.3
marks EP "not present"). Built TPU-first:

- experts live stacked on a leading [E] axis sharded over the mesh's
  `expert` axis (aliased onto `data`, parallel/mesh.py:25), so expert
  weights are expert-parallel with zero per-expert module objects;
- top-k gating (Switch/GShard style) with per-group capacity-factor
  truncation and the standard load-balancing auxiliary loss; slot
  positions carry across the k rounds so second choices never collide
  with first choices in an expert's buffer;
- routing is GROUPED (GShard's group axis = batch row): dispatch/combine
  masks are [G, S, E, C] with C ∝ S/E, so their memory and the dispatch
  einsum cost scale with S² per group instead of (B·S)² global;
- dispatch/combine are einsums against one-hot masks — under GSPMD the
  [tokens→experts] regroup lowers to the all_to_all reference-era MoE
  implementations issue by hand;
- everything is dense-shaped and static (capacity fixes the expert
  batch), so XLA tiles it onto the MXU.

The aux loss is sown into the "losses" collection; the engine adds it to
the objective when the model opts in (GPT2Config.moe_experts).
"""

import dataclasses
from typing import Any, Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel import mesh as mesh_lib


def _expert_mesh_pin(t, spec):
    """Sharding anchor applied only under an engine-pinned mesh with a
    live dedicated expert axis. The token→expert regroup flips tensors
    between batch-major (dim 0 tiled over ('data','expert')) and
    expert-major layouts; on dp×ep×tp meshes those two device orders
    are unconvertible for XLA's partitioner and every unanchored edge
    risks degenerating into involuntary full rematerialization (the
    dryrun detector's tripper). Pinning each regroup tensor to ONE
    declared layout keeps all reshards on convertible paths. No-op
    outside engine-pinned GSPMD traces (mesh_lib.layout_pins) and
    inside explicit-comm regions."""
    mesh = mesh_lib.pinned_mesh()
    if mesh is None or mesh_lib.in_manual_region():
        return t
    if mesh_lib.mesh_axis_size(mesh, mesh_lib.EXPERT_AXIS) <= 1:
        return t
    if isinstance(spec, NamedSharding):
        return jax.lax.with_sharding_constraint(t, spec)
    return jax.lax.with_sharding_constraint(t, NamedSharding(mesh, spec))


def _batch_pin(t):
    mesh = mesh_lib.pinned_mesh()
    if mesh is None:
        return t
    return _expert_mesh_pin(t, mesh_lib.batch_sharding(mesh))


def load_balance_loss(gate_probs, expert_mask):
    """Switch-transformer aux loss: E * sum_e f_e * P_e, where f_e is the
    fraction of tokens routed to expert e and P_e the mean gate prob.
    Inputs [T, E]."""
    E = gate_probs.shape[-1]
    f = expert_mask.mean(axis=0)          # [E] fraction of tokens
    p = gate_probs.mean(axis=0)           # [E] mean router prob
    return E * jnp.sum(f * p)


class TopKGate(nn.Module):
    """Router: logits → top-k expert assignment with capacity truncation.

    Input [T, H] → (dispatch [T, E, C] one-hot, combine [T, E, C] weights,
    aux_loss). T = tokens, E = experts, C = capacity per expert. Slot
    occupancy accumulates across the k rounds, so a round-2 assignment
    lands after all round-1 tokens of the same expert and is dropped when
    the expert is full.
    """
    num_experts: int
    k: int = 1
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):                 # x: [T, H]
        T = x.shape[0]
        E = self.num_experts
        C = max(1, int(np.ceil(self.capacity_factor * self.k * T / E)))
        logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                          param_dtype=self.param_dtype,
                          name="wg")(x.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)          # [T, E]

        dispatch = jnp.zeros((T, E, C), jnp.float32)
        combine = jnp.zeros((T, E, C), jnp.float32)
        remaining = probs
        mask_total = jnp.zeros((T, E), jnp.float32)
        occupancy = jnp.zeros((E,), jnp.float32)          # filled slots
        for _ in range(self.k):
            choice = jnp.argmax(remaining, axis=-1)       # [T]
            onehot = jax.nn.one_hot(choice, E)            # [T, E]
            mask_total = mask_total + onehot
            # slot index = this round's order within the expert, offset by
            # slots already filled in earlier rounds
            pos = ((jnp.cumsum(onehot, axis=0) - 1.0)
                   + occupancy[None, :]) * onehot          # [T, E]
            keep = (pos < C).astype(jnp.float32) * onehot
            pos_c = jax.nn.one_hot(
                jnp.clip(pos.sum(axis=-1), 0, C - 1).astype(jnp.int32), C)
            d = keep[:, :, None] * pos_c[:, None, :]      # [T, E, C]
            gate_w = (probs * onehot).sum(axis=-1)        # [T]
            dispatch = dispatch + d
            combine = combine + d * gate_w[:, None, None]
            occupancy = occupancy + keep.sum(axis=0)
            remaining = remaining * (1.0 - onehot)        # mask for next k

        aux = load_balance_loss(probs, jnp.clip(mask_total, 0.0, 1.0))
        return dispatch, combine, aux


class MoEMLP(nn.Module):
    """Expert FFN bank: stacked [E, ...] kernels, expert-sharded over the
    mesh's expert axis when one exists. ``out_init_std`` lets residual
    stacks scale the output projection like their dense c_proj."""
    num_experts: int
    d_model: int
    d_ff: int
    activation: Callable = nn.gelu
    dropout: float = 0.0
    out_init_std: float = 0.02
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xe, deterministic=True):   # [E, C, H]
        E, C, H = xe.shape
        wi = self.param("wi", nn.initializers.normal(0.02),
                        (E, H, self.d_ff), self.param_dtype)
        wo = self.param("wo", nn.initializers.normal(self.out_init_std),
                        (E, self.d_ff, H), self.param_dtype)
        eaxis = _expert_axis(mesh_lib.pinned_mesh())
        h = jnp.einsum("ech,ehf->ecf", xe, wi.astype(self.dtype))
        h = self.activation(h)
        if eaxis:
            h = _expert_mesh_pin(h, P(eaxis))
        if self.dropout > 0:
            h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        y = jnp.einsum("ecf,efh->ech", h, wo.astype(self.dtype))
        return _expert_mesh_pin(y, P(eaxis)) if eaxis else y


class MoE(nn.Module):
    """Drop-in MoE block: [B, S, H] → [B, S, H]. The load-balancing aux
    loss is sown into the 'losses' collection (and returned when
    ``return_aux``); batch rows are the routing groups."""
    num_experts: int
    d_ff: int
    k: int = 1
    capacity_factor: float = 1.25
    dropout: float = 0.0
    out_init_std: float = 0.02
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    return_aux: bool = False

    @nn.compact
    def __call__(self, x, deterministic=True):
        B, S, H = x.shape
        E = self.num_experts
        # one router shared across groups; routing per batch row bounds the
        # one-hot masks at [B, S, E, C] with C ∝ S/E
        gate = nn.vmap(TopKGate, in_axes=0, out_axes=0,
                       variable_axes={"params": None},
                       split_rngs={"params": False})(
            E, k=self.k, capacity_factor=self.capacity_factor,
            param_dtype=self.param_dtype, name="gate")
        dispatch, combine, aux = gate(x)          # [B,S,E,C], aux [B]
        # the regroup masks are consumed from BOTH layouts (batch-major
        # x on one side of each einsum, expert-major xe/ye on the
        # other). Tiled either way, the partitioner must convert them
        # across the (data×expert)-iota ↔ expert-transposed device
        # orders — unconvertible, degenerating to involuntary full
        # rematerialization INSIDE the layer loop. Pinning them
        # REPLICATED declares the broadcast once at a convertible edge
        # (any tiling → replicated is an all-gather); the masks are the
        # small [B,S,E,C] one-hots, not activations.
        # pin the CASTED masks — the exact tensors the einsums consume;
        # pinning before the cast leaves a free convert node between the
        # anchor and the einsum for the partitioner to re-tile
        dispatch = _expert_mesh_pin(dispatch.astype(self.dtype), P())
        combine = _expert_mesh_pin(combine.astype(self.dtype), P())
        aux = aux.mean()

        C = dispatch.shape[-1]
        # [B,S,H] → [E, B*C, H]: the token→expert regroup (GSPMD lowers
        # this to the EP all_to_all when experts are sharded)
        mesh = mesh_lib.current_mesh()
        eaxis = _expert_axis(mesh)
        ep = eaxis is not None and \
            E % mesh_lib.mesh_axis_size(mesh, eaxis) == 0
        xe = jnp.einsum("bsec,bsh->ebch", dispatch, x)
        xe = xe.reshape(E, B * C, H)
        pinned = mesh_lib.pinned_mesh()
        dedicated_ep = pinned is not None and \
            mesh_lib.mesh_axis_size(pinned, mesh_lib.EXPERT_AXIS) > 1
        if dedicated_ep:
            # dedicated-expert meshes: the batch-major ↔ expert-major
            # flip must route THROUGH replicated — direct tiled↔tiled
            # conversion between the (data×expert)-iota and
            # expert-transposed device orders is unconvertible and
            # degenerates to involuntary remat inside the layer loop.
            # The regroup buffers replicate at declared edges; only the
            # expert MLP's internals stay expert-tiled (driven by its
            # expert-sharded weights — a convertible slice).
            xe = _expert_mesh_pin(xe, P())
        elif ep:
            xe = jax.lax.with_sharding_constraint(
                xe, NamedSharding(mesh, P(eaxis)))
        ye = MoEMLP(E, H, self.d_ff, dropout=self.dropout,
                    out_init_std=self.out_init_std, dtype=self.dtype,
                    param_dtype=self.param_dtype,
                    name="experts")(xe, deterministic)
        ye = ye.reshape(E, B, C, H)
        if dedicated_ep:
            ye = _expert_mesh_pin(ye, P())   # see xe: flip via replicated
        y = jnp.einsum("bsec,ebch->bsh", combine, ye)
        y = _batch_pin(y)

        if self.is_mutable_collection("losses"):
            self.sow("losses", "moe_aux", aux)
        if self.return_aux:
            return y, aux
        return y


def _expert_axis(mesh):
    """The mesh axis experts shard over: a dedicated 'expert' axis when the
    mesh has one (EP independent of DP), otherwise aliased onto 'data'
    (classic expert-parallel-over-DP), None when neither is non-trivial."""
    if mesh is None:
        return None
    if mesh_lib.mesh_axis_size(mesh, mesh_lib.EXPERT_AXIS) > 1:
        return mesh_lib.EXPERT_AXIS
    if mesh_lib.mesh_axis_size(mesh, mesh_lib.DATA_AXIS) > 1:
        return mesh_lib.DATA_AXIS
    return None


def expert_shardings(params, mesh):
    """PartitionSpec tree sharding the stacked expert kernels over the
    expert axis (dedicated 'expert' axis when present, else aliased onto
    'data'); router + everything else replicated. Kernels whose expert
    count does not divide the axis stay replicated (matching the guard
    MoE.__call__ applies)."""
    eaxis = _expert_axis(mesh)
    axis = mesh_lib.mesh_axis_size(mesh, eaxis) if eaxis else 0

    def leaf(path, x):
        names = [str(getattr(p, "key", p)) for p in path]
        if "experts" in names and names[-1] in ("wi", "wo") \
                and axis > 1 and x.shape[0] % axis == 0:
            return P(eaxis)
        return P()
    return jax.tree_util.tree_map_with_path(leaf, params)


def apply_with_losses(model, variables, *args, **kwargs):
    """Run a model that contains MoE blocks and return
    ``(output, aux_loss_sum)`` — the documented way for CUSTOM loss
    functions to include the router load-balancing term (the engine's
    default loss does this automatically; a user loss_fn that calls
    ``model.apply`` directly would silently train an unbalanced router).

    Usage inside a loss_fn::

        def loss_fn(params, batch):
            out, aux = moe.apply_with_losses(model, {"params": params}, x)
            return my_loss(out, y) + coeff * aux
    """
    import jax.numpy as jnp
    out, vs = model.apply(variables, *args, mutable=["losses"], **kwargs)
    aux = sum(jnp.sum(l) for l in
              jax.tree_util.tree_leaves(vs.get("losses", {})))
    return out, aux
