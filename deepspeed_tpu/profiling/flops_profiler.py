"""FLOPS profiler — rebuild of
deepspeed/profiling/flops_profiler/profiler.py:11.

The reference monkey-patches torch.nn.functional to count MACs per module.
On TPU the compiler already knows: we ask XLA for the **compiled HLO cost
analysis** of the train step (flops, bytes accessed) — exact, not estimated,
and it includes fusion effects. Per-module breakdown comes from a jaxpr walk
with flax module path annotations.
"""

import time

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger


# per-chip dense bf16 peak FLOPS by device kind — the denominator of
# MFU. The single source of truth: bench.py and the engine's telemetry
# MFU gauge both resolve through peak_device_flops().
PEAK_BF16_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,   # v6e
}
_PEAK_FALLBACK = 197e12


def peak_device_flops(device=None):
    """Dense bf16 peak of ``device`` (default: jax.devices()[0]).
    Unknown kinds (including CPU backends) fall back to the v5e figure
    so an MFU computed against it is a LOWER bound on a real chip and
    an explicitly-absurd number on CPU — callers that care tag the
    device kind next to the gauge (the engine does)."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    for key, val in PEAK_BF16_FLOPS.items():
        if kind.startswith(key):
            return val
    return _PEAK_FALLBACK


def model_flops_per_token(cfg):
    """Analytic GPT-2-family train flops per token: the standard 6·N
    weight-matmul accounting (fwd 2N + bwd 4N) plus the attention
    scores/context term (12·L·S·E per token, fwd+bwd). ``cfg`` needs
    n_layer / n_embd / vocab_size / n_positions."""
    matmul_params = cfg.n_layer * 12 * cfg.n_embd * cfg.n_embd \
        + cfg.vocab_size * cfg.n_embd
    flops = 6 * matmul_params
    flops += 12 * cfg.n_layer * cfg.n_positions * cfg.n_embd
    return flops


def mfu(flops_per_step, step_time_s, device=None, n_devices=1):
    """Model flops utilization: achieved flops/s over the peak of
    ``n_devices`` chips. Returns a fraction in [0, ~1]."""
    if step_time_s <= 0:
        return 0.0
    return flops_per_step / step_time_s / (
        peak_device_flops(device) * max(n_devices, 1))


def flops_of_jitted(fn, *args, **kwargs):
    """Total flops of `fn(*args)` per XLA's cost analysis."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    compiled = lowered.compile()
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return float(cost.get("flops", 0.0)), cost
    except Exception:
        return 0.0, {}


def compiled_step_flops(jitted, *args):
    """Flops of an ALREADY-jitted callable (one exposing ``.lower``)
    per XLA's compiled cost analysis. After the first real call this is
    a compile-cache hit — which is how the engine prices its MFU gauge
    without recompiling any train path."""
    try:
        compiled = jitted.lower(*args).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return float(cost.get("flops", 0.0))
    except Exception as e:
        logger.warning(f"cost analysis unavailable: {e}")
        return 0.0


def params_count(params):
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


class FlopsProfiler:
    """Engine-integrated profiler (reference integration engine.py:1012-1057):
    at `profile_step` it measures the train step's exact flops + wall time
    and logs flops/s and parameter count."""

    def __init__(self, engine=None):
        self.engine = engine
        self.profiled = False
        self.last_profile = None

    def maybe_profile(self, batch):
        eng = self.engine
        cfg = eng._config.flops_profiler_config
        if self.profiled or eng.global_steps < cfg.profile_step:
            return
        self.profiled = True
        self.profile_step(batch)

    def profile_step(self, batch):
        eng = self.engine
        state = eng.state
        rng = jax.random.PRNGKey(0)
        flops, cost = self._measure(state, batch, rng)
        n_params = params_count(state.params)
        self.last_profile = {
            "flops_per_step": flops,
            "params": n_params,
            "cost_analysis": dict(cost) if cost else {},
        }
        logger.info(f"[flops_profiler] params={n_params/1e6:.2f}M "
                    f"flops/step={flops/1e9:.2f} GFLOPs")
        cfg = eng._config.flops_profiler_config
        if getattr(cfg, "detailed", False):
            table = module_breakdown(
                eng.module, eng._model_inputs(batch),
                depth=getattr(cfg, "module_depth", 2))
            if table:
                self.last_profile["module_breakdown"] = table
                logger.info("\n" + table)
        return self.last_profile

    def _measure(self, state, batch, rng):
        eng = self.engine
        lowered = eng._jit_train_batch.lower(state, batch, rng)
        compiled = lowered.compile()
        try:
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0]
            return float(cost.get("flops", 0.0)), cost
        except Exception:
            return 0.0, {}


def duration_of(fn, *args, warmup=1, iters=3):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def module_breakdown(model, example_input, depth=2, rng=None):
    """Per-module flops/params table (the reference's annotated model tree,
    profiler.py:print_model_profile) via flax tabulate over the module
    hierarchy; depth mirrors the `module_depth` config knob."""
    try:
        import flax.linen as nn
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        depth = None if depth is None or depth < 0 else int(depth)
        tab = nn.tabulate(model, rng, compute_flops=True, depth=depth)
        return tab(example_input)
    except Exception as e:  # tabulate needs a traceable example input
        logger.warning(f"module breakdown unavailable: {e}")
        return ""


def get_model_profile(model, input_shape, rng=None, detailed=False):
    """Standalone entry mirroring the reference's get_model_profile: returns
    (flops, macs_estimate, params) for a flax model's forward pass; with
    ``detailed`` also logs the per-module table."""
    import jax.numpy as jnp
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    x = jnp.zeros(input_shape, jnp.int32)
    variables = model.init(rng, x)
    params = variables.get("params", variables)

    def fwd(p, xx):
        return model.apply({"params": p}, xx)

    flops, cost = flops_of_jitted(fwd, params, x)
    if detailed:
        table = module_breakdown(model, x)
        if table:
            logger.info("\n" + table)
    return flops, flops / 2.0, params_count(params)
