"""Config keys + defaults — rebuild of deepspeed/runtime/constants.py (406 LoC)
and zero/constants.py. Key names are kept identical to the reference JSON
schema so existing DeepSpeed configs parse unchanged; TPU-specific aliases
(``*_per_chip``) are accepted alongside the reference's ``*_per_gpu``.
"""

#############################################
# Batch-size triangle (reference config.py:837)
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_CHIP = "train_micro_batch_size_per_chip"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False

MAX_GRAD_NORM = "max_grad_norm"

SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"

# optimizer names (reference engine.py:27-29 DEEPSPEED_OPTIMIZERS)
ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
CPU_ADAM_OPTIMIZER = "cpuadam"
SGD_OPTIMIZER = "sgd"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
    ONEBIT_LAMB_OPTIMIZER, CPU_ADAM_OPTIMIZER, SGD_OPTIMIZER
]

#############################################
# Precision (fp16 parity + TPU-native bf16)
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 32
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1

BF16 = "bf16"
BFLOAT16 = "bfloat16"
BF16_ENABLED = "enabled"
BF16_ENABLED_DEFAULT = False

PRECISION = "precision"  # tpu-native: "bfloat16" | "float32" | "float16"

#############################################
# Gradient handling
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

ALLREDUCE_ALWAYS_FP32 = "fp32_allreduce"
ALLREDUCE_ALWAYS_FP32_DEFAULT = False

DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

#############################################
# Steps / misc
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

GRADIENT_NOISE_SCALE = "gradient_noise_scale"

SEED = "seed"
SEED_DEFAULT = 1234

#############################################
# Tensorboard (reference constants.py TENSORBOARD_*)
#############################################
TENSORBOARD = "tensorboard"
TENSORBOARD_ENABLED = "enabled"
TENSORBOARD_ENABLED_DEFAULT = False
TENSORBOARD_OUTPUT_PATH = "output_path"
TENSORBOARD_OUTPUT_PATH_DEFAULT = ""
TENSORBOARD_JOB_NAME = "job_name"
TENSORBOARD_JOB_NAME_DEFAULT = "DeepSpeedJobName"

#############################################
# ZeRO (reference zero/constants.py)
#############################################
ZERO_OPTIMIZATION = "zero_optimization"
ZERO_STAGE = "stage"
ZERO_STAGE_DEFAULT = 0
ZERO_REDUCE_BUCKET_SIZE = "reduce_bucket_size"
ZERO_REDUCE_BUCKET_SIZE_DEFAULT = 5e8
ZERO_ALLGATHER_BUCKET_SIZE = "allgather_bucket_size"
ZERO_ALLGATHER_BUCKET_SIZE_DEFAULT = 5e8
ZERO_OVERLAP_COMM = "overlap_comm"
ZERO_OVERLAP_COMM_DEFAULT = False
# TPU extension: collective implementation for the overlap_comm bucket
# stream — "ring" (explicit lax.ppermute ring reduce-scatter + all-gather
# per bucket, maximum scheduling freedom) or "fused" (one lax.psum per
# bucket; XLA picks the algorithm). See parallel/overlap.py.
ZERO_OVERLAP_REDUCE = "overlap_reduce"
ZERO_OVERLAP_REDUCE_DEFAULT = "ring"
ZERO_REDUCE_SCATTER = "reduce_scatter"
ZERO_REDUCE_SCATTER_DEFAULT = True
ZERO_CONTIGUOUS_GRADIENTS = "contiguous_gradients"
ZERO_CONTIGUOUS_GRADIENTS_DEFAULT = False
ZERO_ALLGATHER_PARTITIONS = "allgather_partitions"
ZERO_ALLGATHER_PARTITIONS_DEFAULT = True
ZERO_CPU_OFFLOAD = "cpu_offload"
ZERO_CPU_OFFLOAD_DEFAULT = False
ZERO_CPU_OFFLOAD_PARAMS = "cpu_offload_params"
ZERO_ELASTIC_CHECKPOINT = "elastic_checkpoint"
ZERO_ELASTIC_CHECKPOINT_DEFAULT = True
ZERO_LOAD_FROM_FP32_WEIGHTS = "load_from_fp32_weights"
ZERO_LOAD_FROM_FP32_WEIGHTS_DEFAULT = True

ZERO_OFFLOAD_PARAM = "offload_param"
ZERO_OFFLOAD_OPTIMIZER = "offload_optimizer"
OFFLOAD_DEVICE = "device"
OFFLOAD_CPU_DEVICE = "cpu"
OFFLOAD_NVME_DEVICE = "nvme"
OFFLOAD_NONE_DEVICE = "none"
OFFLOAD_NVME_PATH = "nvme_path"
OFFLOAD_BUFFER_COUNT = "buffer_count"
OFFLOAD_BUFFER_COUNT_DEFAULT = 5
OFFLOAD_BUFFER_SIZE = "buffer_size"
OFFLOAD_PIN_MEMORY = "pin_memory"
OFFLOAD_MAX_IN_CPU = "max_in_cpu"
# pipelined swap schedules (reference aio/pipelined_optimizer_swapper
# knobs): pipeline_read streams swap-in through a sliding window of
# buffer_count staging slots; pipeline_write parks leaves write-behind on
# a dedicated aio handle (drain-fenced before any re-read). Host staging
# is bounded at ~2 x buffer_count x largest-leaf bytes.
OFFLOAD_PIPELINE_READ = "pipeline_read"
OFFLOAD_PIPELINE_WRITE = "pipeline_write"
OFFLOAD_PIPELINE_READ_DEFAULT = False
OFFLOAD_PIPELINE_WRITE_DEFAULT = False
OFFLOAD_FAST_INIT = "fast_init"
# TPU extension (ISSUE 7 satellite): fsync-fenced durability for the
# write-behind aio path. Off by default — swap files are per-step
# scratch riding the guest page cache — but the drain fence becomes a
# real durability barrier when on, and elastic snapshots taken FROM the
# parked files require it for their commit fence to mean anything.
OFFLOAD_FSYNC = "fsync"
OFFLOAD_FSYNC_DEFAULT = False
# TPU extension: how the offloaded optimizer step executes (offload_stream.py)
OFFLOAD_STREAM = "stream"
OFFLOAD_STREAM_SEGMENTS = "stream_segments"

# stage-3 tuning knobs (reference zero/constants.py)
ZERO_PREFETCH_BUCKET_SIZE = "stage3_prefetch_bucket_size"
ZERO_PREFETCH_BUCKET_SIZE_DEFAULT = 5e7
# TPU extension: explicit layer-wise parameter-gather prefetch pipeline
# (parallel/prefetch.py) — the train step becomes a shard_map program
# whose per-layer param all-gather issues ONE LAYER AHEAD of use
# (double-buffered, forward and backward), bounding live full params to
# ~2 layers; the reference's stage3_prefetch_bucket_size /
# PartitionedParameterCoordinator behavior made structural.
ZERO_STAGE3_PREFETCH = "stage3_prefetch"
ZERO_STAGE3_PREFETCH_DEFAULT = False
# collective implementation for the prefetch gathers and the backward
# grad reduce-scatter: "ring" (explicit lax.ppermute hops, maximum
# scheduling freedom), "fused" (lax.all_gather/psum_scatter per layer;
# XLA picks the algorithm) — the stage-3 twin of overlap_reduce — or
# "fused_matmul" (ISSUE 8): a layer's dominant projection weights skip
# the materialized full-param buffer entirely and stream chunk-by-chunk
# through tile-granularity fused all-gather+matmul /
# matmul+reduce-scatter kernels (ops/pallas/fused_collective.py);
# everything else rides the ring. Tuning lives in the
# ``collective_matmul`` sub-block below.
ZERO_STAGE3_PREFETCH_GATHER = "stage3_prefetch_gather"
ZERO_STAGE3_PREFETCH_GATHER_DEFAULT = "ring"
ZERO_STAGE3_PREFETCH_GATHER_MODES = ("ring", "fused", "fused_matmul")
# ``zero_optimization.collective_matmul`` sub-block: the fused-kernel
# knobs (only read when stage3_prefetch_gather == "fused_matmul").
ZERO_COLLECTIVE_MATMUL = "collective_matmul"
# "auto" = pallas kernels on TPU, the lax decomposed-ring path
# elsewhere; "fused" / "lax" force one lowering.
CM_BACKEND = "backend"
CM_BACKEND_DEFAULT = "auto"
CM_BACKEND_MODES = ("auto", "fused", "lax")
# m-tile of the fused kernel grid (clamped to a divisor of the actual
# token count)
CM_TILE_M = "tile_m"
CM_TILE_M_DEFAULT = 128
# a weight streams through the fused kernels only when its per-device
# shard is at least this large; smaller sharded leaves stay on the
# packed per-layer ring gather (n chunk GEMMs cost more than one small
# collective)
CM_MIN_SHARD_BYTES = "min_shard_bytes"
CM_MIN_SHARD_BYTES_DEFAULT = 1 << 16
# VMEM ceiling for backend="auto" kernel feasibility: weights whose
# fused-kernel scratch (full-W stash for contracting shards, ring-carry
# slots otherwise) exceeds it take the lax ring instead
CM_VMEM_BUDGET = "vmem_budget_bytes"
CM_VMEM_BUDGET_DEFAULT = 8 << 20
ZERO_PARAM_PERSISTENCE_THRESHOLD = "stage3_param_persistence_threshold"
ZERO_PARAM_PERSISTENCE_THRESHOLD_DEFAULT = 1e5
ZERO_MAX_LIVE_PARAMETERS = "stage3_max_live_parameters"
ZERO_MAX_LIVE_PARAMETERS_DEFAULT = 1e9
ZERO_MAX_REUSE_DISTANCE = "stage3_max_reuse_distance"
ZERO_MAX_REUSE_DISTANCE_DEFAULT = 1e9
ZERO_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE = "stage3_gather_fp16_weights_on_model_save"
ZERO_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE_DEFAULT = False

#############################################
# Activation checkpointing
# (reference activation_checkpointing/checkpointing.py:759-838)
#############################################
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
ACT_CKPT_PARTITION_ACTIVATIONS = "partition_activations"
ACT_CKPT_CPU_CHECKPOINTING = "cpu_checkpointing"
ACT_CKPT_CONTIGUOUS_MEMORY_OPTIMIZATION = "contiguous_memory_optimization"
ACT_CKPT_NUMBER_CHECKPOINTS = "number_checkpoints"
ACT_CKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY = "synchronize_checkpoint_boundary"
ACT_CKPT_PROFILE = "profile"

#############################################
# Sparse attention (reference config.py:236-406)
#############################################
SPARSE_ATTENTION = "sparse_attention"
SPARSE_MODE = "mode"
SPARSE_MODE_DEFAULT = "fixed"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"
SPARSE_BLOCK = "block"
SPARSE_BLOCK_DEFAULT = 16
SPARSE_DIFFERENT_LAYOUT_PER_HEAD = "different_layout_per_head"
SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT = False
SPARSE_NUM_LOCAL_BLOCKS = "num_local_blocks"
SPARSE_NUM_LOCAL_BLOCKS_DEFAULT = 4
SPARSE_NUM_GLOBAL_BLOCKS = "num_global_blocks"
SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT = 1
SPARSE_ATTENTION_TYPE = "attention"
SPARSE_ATTENTION_TYPE_DEFAULT = "bidirectional"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION = "horizontal_global_attention"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT = False
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS = "num_different_global_patterns"
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT = 1
SPARSE_NUM_RANDOM_BLOCKS = "num_random_blocks"
SPARSE_NUM_RANDOM_BLOCKS_DEFAULT = 0
SPARSE_LOCAL_WINDOW_BLOCKS = "local_window_blocks"
SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT = [4]
SPARSE_GLOBAL_BLOCK_INDICES = "global_block_indices"
SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT = [0]
SPARSE_GLOBAL_BLOCK_END_INDICES = "global_block_end_indices"
SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT = None
SPARSE_NUM_SLIDING_WINDOW_BLOCKS = "num_sliding_window_blocks"
SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT = 3

#############################################
# Gradient compression (1-bit) + MoQ quantize
#############################################
QUANTIZE_TRAINING = "quantize_training"
QUANTIZE_TRAINING_ENABLED = "enabled"
QUANTIZE_TRAINING_ENABLED_DEFAULT = False

#############################################
# Parallelism (tpu-native section; absent in reference where
# TP was delegated to the client's mpu — SURVEY §2.3)
#############################################
MESH = "mesh"
MESH_DATA = "data"
MESH_MODEL = "model"
MESH_PIPE = "pipe"
MESH_SEQ = "seq"
MESH_EXPERT = "expert"

# Hierarchical link-aware gradient communication (ISSUE 10): the
# ``comm.hierarchy`` block splits the data axis at the host/process
# boundary so the 1-bit compressed exchange pays sign bits only on the
# slow DCN-class hop. Presence of the hierarchy block enables it.
COMM = "comm"
COMM_HIERARCHY = "hierarchy"
COMM_HIERARCHY_ENABLED = "enabled"
COMM_HIERARCHY_ENABLED_DEFAULT = True
# 0 = auto: derive the slow-axis size from jax.distributed process
# boundaries; >1 = synthetic split into that many slow groups (the
# single-process testing override).
COMM_HIERARCHY_SLOW_AXIS = "slow_axis"
COMM_HIERARCHY_SLOW_AXIS_DEFAULT = 0
COMM_HIERARCHY_COMPRESSION = "compression"
COMM_HIERARCHY_COMPRESSION_DEFAULT = "auto"
COMM_HIERARCHY_COMPRESSION_MODES = ("auto", "always", "never")
COMM_HIERARCHY_MIN_BUCKET_BYTES = "min_bucket_bytes"
COMM_HIERARCHY_MIN_BUCKET_BYTES_DEFAULT = 1 << 16

PIPELINE = "pipeline"
PIPELINE_STAGES = "stages"
PIPELINE_PARTITION = "partition"
PIPELINE_SEED_LAYERS = "seed_layers"
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL = "activation_checkpoint_interval"

#############################################
# Elasticity (reference elasticity/constants.py)
#############################################
ELASTICITY = "elasticity"
ENABLED = "enabled"
ENABLED_DEFAULT = False
MAX_ACCEPTABLE_BATCH_SIZE = "max_train_batch_size"
MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT = 2000
MICRO_BATCHES = "micro_batch_sizes"
MICRO_BATCHES_DEFAULT = [2, 4, 6]
MIN_GPUS = "min_gpus"
MIN_GPUS_DEFAULT = 1
MAX_GPUS = "max_gpus"
MAX_GPUS_DEFAULT = 10000
MIN_TIME = "min_time"
MIN_TIME_DEFAULT = 0
VERSION = "version"
VERSION_DEFAULT = 0.1
LATEST_ELASTICITY_VERSION = 0.1
IGNORE_NON_ELASTIC_BATCH_INFO = "ignore_non_elastic_batch_info"
IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT = False
PREFER_LARGER_BATCH = "prefer_larger_batch"
PREFER_LARGER_BATCH_DEFAULT = True

#############################################
# FLOPS profiler (reference profiling/constants.py)
#############################################
FLOPS_PROFILER = "flops_profiler"
FLOPS_PROFILER_ENABLED = "enabled"
FLOPS_PROFILER_ENABLED_DEFAULT = False
FLOPS_PROFILER_PROFILE_STEP = "profile_step"
FLOPS_PROFILER_PROFILE_STEP_DEFAULT = 1
FLOPS_PROFILER_MODULE_DEPTH = "module_depth"
FLOPS_PROFILER_MODULE_DEPTH_DEFAULT = -1
FLOPS_PROFILER_TOP_MODULES = "top_modules"
FLOPS_PROFILER_TOP_MODULES_DEFAULT = 3
FLOPS_PROFILER_DETAILED = "detailed"
FLOPS_PROFILER_DETAILED_DEFAULT = True

#############################################
# Telemetry monitor (unified metrics stream, deepspeed_tpu/telemetry —
# the role of the reference's monitor family tensorboard/csv/wandb):
# presence of the block + enabled turns on the per-steps_per_print
# registry export (JSONL stream + SummaryEventWriter bridge).
#############################################
MONITOR = "monitor"
MONITOR_ENABLED = "enabled"
MONITOR_ENABLED_DEFAULT = True       # presence of the block enables it
MONITOR_JSONL_PATH = "jsonl_path"
MONITOR_JSONL_PATH_DEFAULT = ""      # "" -> <output_path>/telemetry_rank{r}.jsonl
MONITOR_OUTPUT_PATH = "output_path"
MONITOR_OUTPUT_PATH_DEFAULT = "runs/telemetry"
# JSONL stream rotation (ISSUE 6 satellite): size-bounded so multi-hour
# runs can't grow one unbounded file. 0 MB disables rotation.
MONITOR_JSONL_MAX_MB = "jsonl_max_mb"
MONITOR_JSONL_MAX_MB_DEFAULT = 256
MONITOR_JSONL_MAX_FILES = "jsonl_max_files"
MONITOR_JSONL_MAX_FILES_DEFAULT = 4

#############################################
# Flight recorder + anomaly watchdog (monitor sub-blocks, ISSUE 6 —
# deepspeed_tpu/telemetry/recorder.py + anomaly.py). The recorder is a
# passive in-memory ring and defaults ON (recording is host-only and
# cheap); the watchdog writes dump FILES on anomaly and so gates on the
# presence of its block, like the monitor block itself.
#############################################
MONITOR_FLIGHT_RECORDER = "flight_recorder"
FLIGHT_RECORDER_ENABLED = "enabled"
FLIGHT_RECORDER_ENABLED_DEFAULT = True
FLIGHT_RECORDER_CAPACITY = "capacity"
FLIGHT_RECORDER_CAPACITY_DEFAULT = 4096

MONITOR_WATCHDOG = "watchdog"
WATCHDOG_ENABLED = "enabled"
WATCHDOG_ENABLED_DEFAULT = True      # presence of the block enables it
WATCHDOG_DUMP_DIR = "dump_dir"
WATCHDOG_DUMP_DIR_DEFAULT = "runs/flight"
WATCHDOG_BASELINE_WINDOW = "baseline_window"
WATCHDOG_BASELINE_WINDOW_DEFAULT = 64
WATCHDOG_MIN_SAMPLES = "min_samples"
WATCHDOG_MIN_SAMPLES_DEFAULT = 8
WATCHDOG_STEP_TIME_FACTOR = "step_time_factor"
WATCHDOG_STEP_TIME_FACTOR_DEFAULT = 3.0
WATCHDOG_SWAP_STALL_FACTOR = "swap_stall_factor"
WATCHDOG_SWAP_STALL_FACTOR_DEFAULT = 4.0
WATCHDOG_SWAP_STALL_MIN_S = "swap_stall_min_s"
WATCHDOG_SWAP_STALL_MIN_S_DEFAULT = 0.05
WATCHDOG_TTFT_FACTOR = "ttft_factor"
WATCHDOG_TTFT_FACTOR_DEFAULT = 4.0
WATCHDOG_TTFT_MIN_S = "ttft_min_s"
WATCHDOG_TTFT_MIN_S_DEFAULT = 1.0
WATCHDOG_CHECK_NAN = "check_nan"
WATCHDOG_CHECK_NAN_DEFAULT = True
WATCHDOG_MAX_DUMPS = "max_dumps"
WATCHDOG_MAX_DUMPS_DEFAULT = 0       # 0 = unlimited
# snapshot-stall rule (ISSUE 7): the async-snapshot commit fence is
# supposed to measure ~0 (writes had a whole step to land); a stall
# past factor x baseline (with an absolute floor) means the aio write
# stream fell behind training and snapshots are no longer free.
WATCHDOG_CKPT_STALL_FACTOR = "ckpt_stall_factor"
WATCHDOG_CKPT_STALL_FACTOR_DEFAULT = 4.0
WATCHDOG_CKPT_STALL_MIN_S = "ckpt_stall_min_s"
WATCHDOG_CKPT_STALL_MIN_S_DEFAULT = 0.25
# rank-straggler rule (ISSUE 12): at cluster fences, a rank whose
# step time exceeds straggler_factor x the median of the OTHER ranks
# for straggler_fences CONSECUTIVE fences trips one latched dump
# naming the rank. Leave-one-out median: with small worlds (2 ranks)
# a whole-cluster median would include the straggler itself and the
# ratio could never reach 2x.
WATCHDOG_STRAGGLER_FACTOR = "straggler_factor"
WATCHDOG_STRAGGLER_FACTOR_DEFAULT = 2.0
WATCHDOG_STRAGGLER_FENCES = "straggler_fences"
WATCHDOG_STRAGGLER_FENCES_DEFAULT = 3
WATCHDOG_STRAGGLER_MIN_S = "straggler_min_s"
WATCHDOG_STRAGGLER_MIN_S_DEFAULT = 0.05   # absolute floor: sub-50ms
# per-step host-time skew is dispatch noise, not a straggler

#############################################
# Cluster telemetry plane (monitor sub-block + serve_port, ISSUE 12 —
# deepspeed_tpu/telemetry/cluster.py + serve.py). The cross-rank
# aggregation is a small fp32 allgather at fences the engine already
# pays (the steps_per_print loss readback; snapshot commit fences) and
# defaults ON like the flight recorder (single-process it degenerates
# to local gauges, no collective). serve_port gates the live /metrics
# + /healthz http.server thread; 0 = off.
#############################################
MONITOR_CLUSTER = "cluster"
CLUSTER_ENABLED = "enabled"
CLUSTER_ENABLED_DEFAULT = True
MONITOR_SERVE_PORT = "serve_port"
MONITOR_SERVE_PORT_DEFAULT = 0       # 0 = no endpoint
MONITOR_SERVE_HOST = "serve_host"
MONITOR_SERVE_HOST_DEFAULT = "127.0.0.1"

#############################################
# Windowed SLO plane (monitor.slo sub-block, ISSUE 19 —
# deepspeed_tpu/telemetry/slo.py). Rolling time-bucketed quantiles +
# error-budget burn rate per serving ROLE, aggregated on rank 0 from
# the transport metrics vector and exported as slo/* gauges; the
# roles_signal() recommendation feeds role-aware autoscaling
# (serving.autoscale.scale_signal: "slo"). Default ON when the monitor
# block is present — the plane is a few host floats per tick.
#############################################
MONITOR_SLO = "slo"
SLO_ENABLED = "enabled"
SLO_ENABLED_DEFAULT = True
SLO_WINDOW_S = "window_s"
SLO_WINDOW_S_DEFAULT = 30.0
SLO_TARGETS = "targets"          # {metric: target seconds} overrides
SLO_BUDGET = "budget"            # error-budget fraction of the window
SLO_BUDGET_DEFAULT = 0.1
SLO_UP_BURN = "up_burn"          # burn rate >= this: role scales up
SLO_UP_BURN_DEFAULT = 2.0
SLO_DOWN_BURN = "down_burn"      # every burn <= this: role has slack
SLO_DOWN_BURN_DEFAULT = 0.25
SLO_MIN_SAMPLES = "min_samples"  # windowed samples before a signal
SLO_MIN_SAMPLES_DEFAULT = 8

#############################################
# Programmatic XLA trace window (profiling.trace_dir + trace_steps):
# wraps jax.profiler.start_trace/stop_trace around global steps
# [trace_steps[0], trace_steps[1]) so span annotations land in
# perfetto/xprof. Off unless trace_dir is set.
#############################################
PROFILING = "profiling"
PROFILING_TRACE_DIR = "trace_dir"
PROFILING_TRACE_DIR_DEFAULT = ""
PROFILING_TRACE_STEPS = "trace_steps"
PROFILING_TRACE_STEPS_DEFAULT = ()

#############################################
# Progressive layer drop (reference constants.py)
#############################################
# MoQ quantize-aware training (reference runtime/constants.py
# QUANTIZE_TRAINING section)
QUANTIZE_TRAINING = "quantize_training"
QUANTIZE_TRAINING_ENABLED = "enabled"
QUANTIZE_TRAINING_ENABLED_DEFAULT = False
QUANTIZE_BITS = "quantize_bits"
QUANTIZE_START_BITS = "start_bits"
QUANTIZE_START_BITS_DEFAULT = 16
QUANTIZE_TARGET_BITS = "target_bits"
QUANTIZE_TARGET_BITS_DEFAULT = 8
QUANTIZE_SCHEDULE = "quantize_schedule"
QUANTIZE_PERIOD = "quantize_period"
QUANTIZE_PERIOD_DEFAULT = 1000
QUANTIZE_SCHEDULE_OFFSET = "schedule_offset"
QUANTIZE_OFFSET_DEFAULT = 1000
QUANTIZE_GROUPS = "quantize_groups"
QUANTIZE_GROUPS_DEFAULT = 1
QUANTIZE_ALGO = "quantize_algo"
QUANTIZE_TYPE = "q_type"
QUANTIZE_SYMMETRIC = "symmetric"
QUANTIZE_ASYMMETRIC = "asymmetric"
QUANTIZE_ROUNDING = "rounding"
QUANTIZE_NEAREST_ROUNDING = "nearest"
QUANTIZE_STOCHASTIC_ROUNDING = "stochastic"
FP16_MIXED_QUANTIZE = "fp16_mixed_quantize"
FP16_MIXED_QUANTIZE_ENABLED = "enabled"
FP16_MIXED_QUANTIZE_ENABLED_DEFAULT = False
QUANTIZE_CHANGE_RATIO = "quantize_change_ratio"
QUANTIZE_CHANGE_RATIO_DEFAULT = 0.001
QUANTIZE_VERBOSE = "quantize_verbose"
QUANTIZE_VERBOSE_DEFAULT = False
QUANTIZER_KERNEL = "quantizer_kernel"
QUANTIZER_KERNEL_DEFAULT = True
QUANTIZE_EIGENVALUE = "eigenvalue"
QUANTIZE_EIGENVALUE_ENABLED = "enabled"
QUANTIZE_EIGENVALUE_ENABLED_DEFAULT = False
EIGENVALUE_VERBOSE = "verbose"
EIGENVALUE_VERBOSE_DEFAULT = False
EIGENVALUE_MAX_ITER = "max_iter"
EIGENVALUE_MAX_ITER_DEFAULT = 100
EIGENVALUE_TOL = "tol"
EIGENVALUE_TOL_DEFAULT = 1e-2
EIGENVALUE_STABILITY = "stability"
EIGENVALUE_STABILITY_DEFAULT = 1e-6
EIGENVALUE_GAS_BOUNDARY_RESOLUTION = "gas_boundary_resolution"
EIGENVALUE_GAS_BOUNDARY_RESOLUTION_DEFAULT = 1
EIGENVALUE_LAYER_NAME = "layer_name"
EIGENVALUE_LAYER_NAME_DEFAULT = "bert.encoder.layer"
EIGENVALUE_LAYER_NUM = "layer_num"
EIGENVALUE_LAYER_NUM_DEFAULT = 0

PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
PLD_ENABLED = "enabled"
PLD_ENABLED_DEFAULT = False
PLD_THETA = "theta"
PLD_THETA_DEFAULT = 0.5
PLD_GAMMA = "gamma"
PLD_GAMMA_DEFAULT = 0.001

#############################################
# Checkpoint / aio
#############################################
AIO = "aio"
AIO_BLOCK_SIZE = "block_size"
AIO_BLOCK_SIZE_DEFAULT = 1048576
AIO_QUEUE_DEPTH = "queue_depth"
AIO_QUEUE_DEPTH_DEFAULT = 8
AIO_THREAD_COUNT = "thread_count"
AIO_THREAD_COUNT_DEFAULT = 1
AIO_SINGLE_SUBMIT = "single_submit"
AIO_SINGLE_SUBMIT_DEFAULT = False
AIO_OVERLAP_EVENTS = "overlap_events"
AIO_OVERLAP_EVENTS_DEFAULT = True
# O_DIRECT swap I/O (ISSUE 20): bytes-on-device instead of
# bytes-into-page-cache; requires block_size % page == 0. Latches to
# buffered I/O (with one loud warning) on filesystems that reject it.
AIO_O_DIRECT = "o_direct"
AIO_O_DIRECT_DEFAULT = False

#############################################
# Elastic snapshots (runtime/elastic, ISSUE 7): periodic async
# checkpoints through the swap tier's write-behind aio handle, SIGTERM
# preemption handling with a grace budget, and auto-resume from the
# newest valid manifest. Presence of the block (plus a path) enables it.
#############################################
SNAPSHOT = "snapshot"
SNAPSHOT_ENABLED = "enabled"
SNAPSHOT_ENABLED_DEFAULT = True       # presence of the block enables it
SNAPSHOT_PATH = "path"
SNAPSHOT_PATH_DEFAULT = ""
SNAPSHOT_INTERVAL_STEPS = "interval_steps"
SNAPSHOT_INTERVAL_STEPS_DEFAULT = 100
SNAPSHOT_KEEP = "keep"                # committed snapshot generations
SNAPSHOT_KEEP_DEFAULT = 2
SNAPSHOT_FSYNC = "fsync"              # the commit fence durability
SNAPSHOT_FSYNC_DEFAULT = True
SNAPSHOT_AUTO_RESUME = "auto_resume"
SNAPSHOT_AUTO_RESUME_DEFAULT = True
SNAPSHOT_GRACE_SECS = "grace_secs"    # preemption grace budget
SNAPSHOT_GRACE_SECS_DEFAULT = 30.0
SNAPSHOT_SIGNALS = "signals"
SNAPSHOT_SIGNALS_DEFAULT = ("SIGTERM",)

#############################################
# Fault tolerance (runtime/elastic/{hang,supervisor}.py, ISSUE 15):
# the collective hang watchdog + per-rank heartbeat inside every
# worker, and the knobs the launcher-level supervisor exports into
# child environments (heartbeat dir, rendezvous retry). Presence of
# the block enables the in-process watchdog thread.
#############################################
FAULT_TOLERANCE = "fault_tolerance"
FT_ENABLED = "enabled"
FT_ENABLED_DEFAULT = True             # presence of the block enables it
FT_HANG_DEADLINE_S = "hang_deadline_s"    # blocked-in-collective limit
FT_HANG_DEADLINE_S_DEFAULT = 300.0
FT_HANG_POLL_S = "hang_poll_s"        # 0 → deadline/10, clamped
FT_HANG_POLL_S_DEFAULT = 0.0
FT_HEARTBEAT_DIR = "heartbeat_dir"    # "" → DSTPU_HEARTBEAT_DIR env
FT_HEARTBEAT_DIR_DEFAULT = ""
FT_HEARTBEAT_INTERVAL_S = "heartbeat_interval_s"
FT_HEARTBEAT_INTERVAL_S_DEFAULT = 1.0
FT_RENDEZVOUS_RETRIES = "rendezvous_retries"
FT_RENDEZVOUS_RETRIES_DEFAULT = 8
FT_RENDEZVOUS_BACKOFF_S = "rendezvous_backoff_s"
FT_RENDEZVOUS_BACKOFF_S_DEFAULT = 0.5

#############################################
# Serving (continuous batching + paged KV cache) [tpu]
#############################################
SERVING = "serving"
SERVING_ENABLED = "enabled"
SERVING_ENABLED_DEFAULT = True        # presence of the block enables it
SERVING_SLOTS = "slots"
SERVING_SLOTS_DEFAULT = 8
SERVING_PAGE_SIZE = "page_size"
SERVING_PAGE_SIZE_DEFAULT = 128
SERVING_MAX_PAGES_PER_SLOT = "max_pages_per_slot"
SERVING_MAX_PAGES_PER_SLOT_DEFAULT = 16
SERVING_NUM_BLOCKS = "num_blocks"
SERVING_NUM_BLOCKS_DEFAULT = 0        # 0 → slots * max_pages + 1 (trash)
SERVING_KV_CACHE_BITS = "kv_cache_bits"
SERVING_KV_CACHE_BITS_DEFAULT = 0
SERVING_QUANTIZE_BITS = "quantize_bits"
SERVING_QUANTIZE_BITS_DEFAULT = 0

# serving.prefix_cache — copy-on-write prefix page sharing (ISSUE 9):
# presence of the sub-block enables the refcounted prefix index over
# the paged allocator; repeat-prefix admissions alias resident pages
# read-only and prefill only their suffix
SERVING_PREFIX_CACHE = "prefix_cache"
SERVING_PREFIX_CACHE_ENABLED = "enabled"
SERVING_PREFIX_CACHE_ENABLED_DEFAULT = True   # presence enables
SERVING_PREFIX_CACHE_COW = "cow"
SERVING_PREFIX_CACHE_COW_DEFAULT = True       # share the partial page
#                                               via copy-on-write

# serving.speculative — drafter-based speculative decoding (ISSUE 9):
# presence enables; the drafter proposes `tokens` tokens per round and
# the target verifies the window in one multi-query paged-attention
# dispatch (greedy-only; outputs stay token-for-token identical)
SERVING_SPECULATIVE = "speculative"
SERVING_SPEC_ENABLED = "enabled"
SERVING_SPEC_ENABLED_DEFAULT = True           # presence enables
SERVING_SPEC_TOKENS = "tokens"
SERVING_SPEC_TOKENS_DEFAULT = 3               # drafts per verify round
SERVING_SPEC_DRAFTER = "drafter"
SERVING_SPEC_DRAFTER_DEFAULT = "ngram"        # "ngram" | "model"
SERVING_SPEC_NGRAM_MAX = "ngram_max"
SERVING_SPEC_NGRAM_MAX_DEFAULT = 3
SERVING_SPEC_NGRAM_MIN = "ngram_min"
SERVING_SPEC_NGRAM_MIN_DEFAULT = 1

# serving.elastic — preemption-tolerant serving (ISSUE 11): on SIGTERM
# the engine drains requests that fit the grace budget and snapshots
# the rest (per-slot request state + referenced K/V pages + the prefix
# index) through the elastic snapshot commit path; a restore rebuilds
# them on a different engine/replica count
SERVING_ELASTIC = "elastic"
SERVING_ELASTIC_ENABLED = "enabled"
SERVING_ELASTIC_ENABLED_DEFAULT = True        # presence enables
SERVING_ELASTIC_SNAPSHOT_PATH = "snapshot_path"
SERVING_ELASTIC_SNAPSHOT_PATH_DEFAULT = ""
SERVING_ELASTIC_GRACE_SECS = "grace_secs"     # preemption drain budget
SERVING_ELASTIC_GRACE_SECS_DEFAULT = 30.0
SERVING_ELASTIC_MAX_RETRIES = "max_retries"   # cross-replica requeue cap
SERVING_ELASTIC_MAX_RETRIES_DEFAULT = 3
SERVING_ELASTIC_BACKOFF_S = "backoff_s"       # requeue backoff base
SERVING_ELASTIC_BACKOFF_S_DEFAULT = 0.05      # (jittered, doubles/try)
SERVING_ELASTIC_INTERVAL_TICKS = "interval_ticks"
SERVING_ELASTIC_INTERVAL_TICKS_DEFAULT = 0    # 0 = snapshot only on
#                                               preemption / drain
SERVING_ELASTIC_KEEP = "keep"
SERVING_ELASTIC_KEEP_DEFAULT = 2
SERVING_ELASTIC_FSYNC = "fsync"
SERVING_ELASTIC_FSYNC_DEFAULT = True
SERVING_ELASTIC_SIGNALS = "signals"
SERVING_ELASTIC_SIGNALS_DEFAULT = ("SIGTERM",)

# serving.autoscale — replica-pool autoscaling (ISSUE 11): the
# ReplicaPool supervisor scales up on latched watchdog incidents
# (ttft_blowup / page_pool_exhausted trips) and scales down by
# draining an idle replica through the same snapshot path
SERVING_AUTOSCALE = "autoscale"
SERVING_AUTOSCALE_MIN_REPLICAS = "min_replicas"
SERVING_AUTOSCALE_MIN_REPLICAS_DEFAULT = 1
SERVING_AUTOSCALE_MAX_REPLICAS = "max_replicas"
SERVING_AUTOSCALE_MAX_REPLICAS_DEFAULT = 1
SERVING_AUTOSCALE_SCALE_SIGNAL = "scale_signal"
SERVING_AUTOSCALE_SCALE_SIGNAL_DEFAULT = "watchdog"
# "slo" (ISSUE 19): scale on the windowed per-role error-budget burn
# rate the SLO plane (telemetry/slo.py) exports as slo/* gauges
SERVING_AUTOSCALE_SCALE_SIGNAL_MODES = ("watchdog", "slo", "none")

# serving.disaggregation — prefill/decode role split (ISSUE 14):
# dedicated prefill-role engines admit + prefill, a page-handoff
# transport moves the request, decode-role engines adopt the pages
# and tick. decode_replicas 0 = colocated fallback (role="both").
SERVING_DISAGG = "disaggregation"
SERVING_DISAGG_ENABLED = "enabled"
SERVING_DISAGG_ENABLED_DEFAULT = True          # presence enables
SERVING_DISAGG_PREFILL_REPLICAS = "prefill_replicas"
SERVING_DISAGG_PREFILL_REPLICAS_DEFAULT = 1
SERVING_DISAGG_DECODE_REPLICAS = "decode_replicas"
SERVING_DISAGG_DECODE_REPLICAS_DEFAULT = 1
SERVING_DISAGG_DEDUPE_PAGES = "dedupe_pages"
SERVING_DISAGG_DEDUPE_PAGES_DEFAULT = True     # prefix-index re-share
SERVING_DISAGG_TRANSPORT = "transport"
SERVING_DISAGG_TRANSPORT_DEFAULT = "inproc"
SERVING_DISAGG_TRANSPORT_MODES = ("inproc", "process")  # ISSUE 17:
#   "process" = per-role PROCESS placement over the gloo fabric (rank
#   0 prefill+router, ranks >= 1 decode; serving/transport.py)
SERVING_DISAGG_ADDRESSING = "addressing"
SERVING_DISAGG_ADDRESSING_DEFAULT = "targeted"
SERVING_DISAGG_ADDRESSING_MODES = ("targeted", "broadcast")  # ISSUE 18:
#   "targeted" moves dst-addressed frames point-to-point (payload
#   crosses the wire once, any world size); "broadcast" is the PR-17
#   legacy all-rank allgather (O(world x payload), kept for A/B)
SERVING_DISAGG_PAYLOAD_TIMEOUT_S = "payload_timeout_s"
SERVING_DISAGG_PAYLOAD_TIMEOUT_S_DEFAULT = 60.0  # socket-leg deadline:
#   a dead peer fails LOUD into the supervisor's rank-death path

# serving.router — the SLO-aware multi-engine router over the role
# split (ISSUE 14): prefix-locality admission, decode-page
# reservations, live TTFT/queue-depth scoring
SERVING_ROUTER = "router"
SERVING_ROUTER_PREFIX_ROUTING = "prefix_routing"
SERVING_ROUTER_PREFIX_ROUTING_DEFAULT = True
SERVING_ROUTER_QUEUE_WEIGHT = "queue_weight"
SERVING_ROUTER_QUEUE_WEIGHT_DEFAULT = 1.0
SERVING_ROUTER_TTFT_WEIGHT = "ttft_weight"
SERVING_ROUTER_TTFT_WEIGHT_DEFAULT = 1.0
SERVING_ROUTER_TTFT_WINDOW = "ttft_window"
SERVING_ROUTER_TTFT_WINDOW_DEFAULT = 16
SERVING_ROUTER_MAX_HANDOFF_RETRIES = "max_handoff_retries"
SERVING_ROUTER_MAX_HANDOFF_RETRIES_DEFAULT = 3
SERVING_ROUTER_DECODE_TICK_CAP = "decode_tick_cap"
SERVING_ROUTER_DECODE_TICK_CAP_DEFAULT = 4
SERVING_ROUTER_MAX_INFLIGHT_PAGES = "max_inflight_pages"
SERVING_ROUTER_MAX_INFLIGHT_PAGES_DEFAULT = 0   # 0 = 2x decode pools
SERVING_ROUTER_MAX_INFLIGHT_PAGES_PER_RANK = "max_inflight_pages_per_rank"
SERVING_ROUTER_MAX_INFLIGHT_PAGES_PER_RANK_DEFAULT = 0  # ISSUE 18:
#   0 = the aggregate bound split evenly across decode ranks
SERVING_ROUTER_DECODE_SCHEDULE = "decode_schedule"
SERVING_ROUTER_DECODE_SCHEDULE_DEFAULT = "lpt"
SERVING_ROUTER_DECODE_SCHEDULE_MODES = ("lpt", "fifo")
