"""Typed config system — TPU-native rebuild of deepspeed/runtime/config.py:653.

A JSON file (or dict) becomes a `DeepSpeedConfig` with the same key schema as
the reference, including the batch-size triangle solver
(`_set_batch_related_parameters`, reference config.py:837-888):

    train_batch_size == micro_batch_per_device * gradient_accumulation_steps * dp_world_size

Any two of the three determine the third; given only one, the others default
to make the identity hold.
"""

import json
import os

from deepspeed_tpu.config import constants as C
from deepspeed_tpu.utils.logging import logger


class DeepSpeedConfigError(ValueError):
    pass


def get_scalar_param(d, name, default):
    return d.get(name, default)


class ZeroOffloadConfig:
    """`offload_param` / `offload_optimizer` schema — reference
    zero/offload_config.py."""

    def __init__(self, d, role="optimizer"):
        d = d or {}
        self.device = get_scalar_param(d, C.OFFLOAD_DEVICE, C.OFFLOAD_NONE_DEVICE)
        self.nvme_path = get_scalar_param(d, C.OFFLOAD_NVME_PATH, None)
        self.buffer_count = int(get_scalar_param(
            d, C.OFFLOAD_BUFFER_COUNT, C.OFFLOAD_BUFFER_COUNT_DEFAULT))
        self.buffer_size = int(get_scalar_param(d, C.OFFLOAD_BUFFER_SIZE, int(1e8)))
        self.pin_memory = bool(get_scalar_param(d, C.OFFLOAD_PIN_MEMORY, False))
        self.max_in_cpu = int(get_scalar_param(d, C.OFFLOAD_MAX_IN_CPU, int(1e9)))
        # pipelined swap schedules (consumed by swap_tensor/swapper.py):
        # read = sliding-window swap-in over buffer_count staging slots,
        # write = write-behind park on a dedicated aio handle
        self.pipeline_read = bool(get_scalar_param(
            d, C.OFFLOAD_PIPELINE_READ, C.OFFLOAD_PIPELINE_READ_DEFAULT))
        self.pipeline_write = bool(get_scalar_param(
            d, C.OFFLOAD_PIPELINE_WRITE, C.OFFLOAD_PIPELINE_WRITE_DEFAULT))
        # fsync-fenced durability (ISSUE 7 satellite): the drain fence
        # additionally fsyncs every written swap file, turning it into a
        # real durability barrier (snapshots taken from parked files
        # depend on it; plain training does not and keeps the default)
        self.fsync = bool(get_scalar_param(
            d, C.OFFLOAD_FSYNC, C.OFFLOAD_FSYNC_DEFAULT))
        if self.buffer_count < 1:
            raise DeepSpeedConfigError(
                f"offload {C.OFFLOAD_BUFFER_COUNT} must be >= 1, "
                f"got {self.buffer_count}")
        self.fast_init = bool(get_scalar_param(d, C.OFFLOAD_FAST_INIT, False))
        # TPU extension (offload_optimizer only): how the offloaded
        # optimizer step executes.
        #   "auto"   — device-streamed step with state in pinned_host when
        #              the backend has that memory space (TPU), else host
        #   "device" — require the streamed path (error if unsupported)
        #   "host"   — force the numpy/SIMD host runner (reference shape)
        self.stream = str(get_scalar_param(d, C.OFFLOAD_STREAM, "auto"))
        # TPU extension (offload_param only): >0 selects the ZeRO-Infinity
        # segment-streamed engine (runtime/zero/infinity.py) — the model's
        # scan-stacked layers split into this many segments whose params
        # stream through HBM one at a time; master+moments rest in
        # pinned_host, compute params rest on NVMe.
        self.stream_segments = int(get_scalar_param(
            d, C.OFFLOAD_STREAM_SEGMENTS, 0))
        if role != "optimizer":
            if C.OFFLOAD_STREAM in d:
                raise DeepSpeedConfigError(
                    "'stream' applies to offload_optimizer only (the param "
                    "tier is pinned_host/NVMe residency, not a step mode)")
        elif self.stream_segments:
            raise DeepSpeedConfigError(
                "'stream_segments' applies to offload_param only")
        elif self.stream not in ("auto", "device", "host"):
            raise DeepSpeedConfigError(
                f"offload stream must be auto|device|host, got {self.stream!r}")

    @property
    def enabled(self):
        return self.device not in (None, C.OFFLOAD_NONE_DEVICE)

    def repr_dict(self):
        return {"device": self.device, "nvme_path": self.nvme_path,
                "buffer_count": self.buffer_count,
                "buffer_size": self.buffer_size,
                "pipeline_read": self.pipeline_read,
                "pipeline_write": self.pipeline_write,
                "fsync": self.fsync}


class DeepSpeedZeroConfig:
    """ZeRO section — reference zero/config.py:14."""

    def __init__(self, param_dict):
        zero_dict = param_dict.get(C.ZERO_OPTIMIZATION, {})
        if isinstance(zero_dict, bool):  # legacy "zero_optimization": true == stage 1
            zero_dict = {C.ZERO_STAGE: 1 if zero_dict else 0}
        self.stage = int(get_scalar_param(zero_dict, C.ZERO_STAGE, C.ZERO_STAGE_DEFAULT))
        self.reduce_bucket_size = int(
            get_scalar_param(zero_dict, C.ZERO_REDUCE_BUCKET_SIZE,
                             C.ZERO_REDUCE_BUCKET_SIZE_DEFAULT))
        self.allgather_bucket_size = int(
            get_scalar_param(zero_dict, C.ZERO_ALLGATHER_BUCKET_SIZE,
                             C.ZERO_ALLGATHER_BUCKET_SIZE_DEFAULT))
        self.overlap_comm = bool(
            get_scalar_param(zero_dict, C.ZERO_OVERLAP_COMM, C.ZERO_OVERLAP_COMM_DEFAULT))
        self.overlap_reduce = str(
            get_scalar_param(zero_dict, C.ZERO_OVERLAP_REDUCE,
                             C.ZERO_OVERLAP_REDUCE_DEFAULT))
        if self.overlap_reduce not in ("ring", "fused"):
            raise DeepSpeedConfigError(
                f"zero_optimization.{C.ZERO_OVERLAP_REDUCE} must be 'ring' "
                f"or 'fused', got {self.overlap_reduce!r}")
        self.reduce_scatter = bool(
            get_scalar_param(zero_dict, C.ZERO_REDUCE_SCATTER, C.ZERO_REDUCE_SCATTER_DEFAULT))
        self.contiguous_gradients = bool(
            get_scalar_param(zero_dict, C.ZERO_CONTIGUOUS_GRADIENTS,
                             C.ZERO_CONTIGUOUS_GRADIENTS_DEFAULT))
        self.allgather_partitions = bool(
            get_scalar_param(zero_dict, C.ZERO_ALLGATHER_PARTITIONS,
                             C.ZERO_ALLGATHER_PARTITIONS_DEFAULT))
        self.elastic_checkpoint = bool(
            get_scalar_param(zero_dict, C.ZERO_ELASTIC_CHECKPOINT,
                             C.ZERO_ELASTIC_CHECKPOINT_DEFAULT))
        self.load_from_fp32_weights = bool(
            get_scalar_param(zero_dict, C.ZERO_LOAD_FROM_FP32_WEIGHTS,
                             C.ZERO_LOAD_FROM_FP32_WEIGHTS_DEFAULT))

        # legacy stage-2 flat flag (reference zero/config.py cpu_offload)
        cpu_offload = bool(get_scalar_param(zero_dict, C.ZERO_CPU_OFFLOAD,
                                            C.ZERO_CPU_OFFLOAD_DEFAULT))
        cpu_offload_params = bool(get_scalar_param(zero_dict, C.ZERO_CPU_OFFLOAD_PARAMS, False))

        self.offload_param = ZeroOffloadConfig(
            zero_dict.get(C.ZERO_OFFLOAD_PARAM), role="param")
        self.offload_optimizer = ZeroOffloadConfig(
            zero_dict.get(C.ZERO_OFFLOAD_OPTIMIZER))
        if cpu_offload and not self.offload_optimizer.enabled:
            self.offload_optimizer.device = C.OFFLOAD_CPU_DEVICE
        if cpu_offload_params and not self.offload_param.enabled:
            self.offload_param.device = C.OFFLOAD_CPU_DEVICE

        # only validated where the knob is consumed — the overlap scheduler's
        # bucket budget. With optimizer offload, overlap_comm keeps its
        # reference d2h-streaming meaning and never reads the bucket size;
        # plain parity configs keep accepting any value.
        if self.overlap_comm and not self.offload_optimizer.enabled \
                and self.reduce_bucket_size <= 0:
            raise DeepSpeedConfigError(
                f"zero_optimization.{C.ZERO_REDUCE_BUCKET_SIZE} must be "
                f"positive when {C.ZERO_OVERLAP_COMM} is on, got "
                f"{self.reduce_bucket_size}")

        # stage-3 tuning knobs
        self.prefetch_bucket_size = int(
            get_scalar_param(zero_dict, C.ZERO_PREFETCH_BUCKET_SIZE,
                             C.ZERO_PREFETCH_BUCKET_SIZE_DEFAULT))
        self.stage3_prefetch = bool(
            get_scalar_param(zero_dict, C.ZERO_STAGE3_PREFETCH,
                             C.ZERO_STAGE3_PREFETCH_DEFAULT))
        self.stage3_prefetch_gather = str(
            get_scalar_param(zero_dict, C.ZERO_STAGE3_PREFETCH_GATHER,
                             C.ZERO_STAGE3_PREFETCH_GATHER_DEFAULT))
        if self.stage3_prefetch_gather not in \
                C.ZERO_STAGE3_PREFETCH_GATHER_MODES:
            raise DeepSpeedConfigError(
                f"zero_optimization.{C.ZERO_STAGE3_PREFETCH_GATHER} must "
                f"be one of {C.ZERO_STAGE3_PREFETCH_GATHER_MODES}, got "
                f"{self.stage3_prefetch_gather!r}")
        cm = zero_dict.get(C.ZERO_COLLECTIVE_MATMUL, {}) or {}
        if not isinstance(cm, dict):
            raise DeepSpeedConfigError(
                f"zero_optimization.{C.ZERO_COLLECTIVE_MATMUL} must be a "
                f"dict of {{{C.CM_BACKEND}, {C.CM_TILE_M}, "
                f"{C.CM_MIN_SHARD_BYTES}, {C.CM_VMEM_BUDGET}}}, got "
                f"{cm!r}")
        self.collective_matmul_backend = str(
            cm.get(C.CM_BACKEND, C.CM_BACKEND_DEFAULT))
        if self.collective_matmul_backend not in C.CM_BACKEND_MODES:
            raise DeepSpeedConfigError(
                f"zero_optimization.{C.ZERO_COLLECTIVE_MATMUL}."
                f"{C.CM_BACKEND} must be one of {C.CM_BACKEND_MODES}, "
                f"got {self.collective_matmul_backend!r}")
        self.collective_matmul_tile_m = int(
            cm.get(C.CM_TILE_M, C.CM_TILE_M_DEFAULT))
        self.collective_matmul_min_shard_bytes = int(
            cm.get(C.CM_MIN_SHARD_BYTES, C.CM_MIN_SHARD_BYTES_DEFAULT))
        if self.collective_matmul_tile_m <= 0:
            raise DeepSpeedConfigError(
                f"zero_optimization.{C.ZERO_COLLECTIVE_MATMUL}."
                f"{C.CM_TILE_M} must be positive, got "
                f"{self.collective_matmul_tile_m}")
        if self.collective_matmul_min_shard_bytes < 0:
            raise DeepSpeedConfigError(
                f"zero_optimization.{C.ZERO_COLLECTIVE_MATMUL}."
                f"{C.CM_MIN_SHARD_BYTES} must be >= 0, got "
                f"{self.collective_matmul_min_shard_bytes}")
        self.collective_matmul_vmem_budget_bytes = int(
            cm.get(C.CM_VMEM_BUDGET, C.CM_VMEM_BUDGET_DEFAULT))
        if self.collective_matmul_vmem_budget_bytes <= 0:
            raise DeepSpeedConfigError(
                f"zero_optimization.{C.ZERO_COLLECTIVE_MATMUL}."
                f"{C.CM_VMEM_BUDGET} must be positive, got "
                f"{self.collective_matmul_vmem_budget_bytes}")
        if self.stage3_prefetch and self.stage != 3:
            raise DeepSpeedConfigError(
                f"zero_optimization.{C.ZERO_STAGE3_PREFETCH} requires "
                f"stage 3, got stage {self.stage}")
        self.param_persistence_threshold = int(
            get_scalar_param(zero_dict, C.ZERO_PARAM_PERSISTENCE_THRESHOLD,
                             C.ZERO_PARAM_PERSISTENCE_THRESHOLD_DEFAULT))
        self.max_live_parameters = int(
            get_scalar_param(zero_dict, C.ZERO_MAX_LIVE_PARAMETERS,
                             C.ZERO_MAX_LIVE_PARAMETERS_DEFAULT))
        self.max_reuse_distance = int(
            get_scalar_param(zero_dict, C.ZERO_MAX_REUSE_DISTANCE,
                             C.ZERO_MAX_REUSE_DISTANCE_DEFAULT))
        self.gather_fp16_weights_on_model_save = bool(
            get_scalar_param(zero_dict, C.ZERO_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE,
                             C.ZERO_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE_DEFAULT))

        if not 0 <= self.stage <= 3:
            raise DeepSpeedConfigError(f"invalid ZeRO stage {self.stage}")

    @property
    def cpu_offload(self):
        return self.offload_optimizer.enabled

    def repr_dict(self):
        return {
            "stage": self.stage,
            "reduce_bucket_size": self.reduce_bucket_size,
            "allgather_bucket_size": self.allgather_bucket_size,
            "overlap_comm": self.overlap_comm,
            "overlap_reduce": self.overlap_reduce,
            "stage3_prefetch": self.stage3_prefetch,
            "stage3_prefetch_gather": self.stage3_prefetch_gather,
            "collective_matmul": {
                "backend": self.collective_matmul_backend,
                "tile_m": self.collective_matmul_tile_m,
                "min_shard_bytes": self.collective_matmul_min_shard_bytes,
                "vmem_budget_bytes":
                    self.collective_matmul_vmem_budget_bytes,
            },
            "reduce_scatter": self.reduce_scatter,
            "offload_param": self.offload_param.repr_dict(),
            "offload_optimizer": self.offload_optimizer.repr_dict(),
        }


class ActivationCheckpointingConfig:
    """reference activation_checkpointing/config.py."""

    def __init__(self, param_dict):
        d = param_dict.get(C.ACTIVATION_CHECKPOINTING, {})
        self.partition_activations = bool(d.get(C.ACT_CKPT_PARTITION_ACTIVATIONS, False))
        self.cpu_checkpointing = bool(d.get(C.ACT_CKPT_CPU_CHECKPOINTING, False))
        self.contiguous_memory_optimization = bool(
            d.get(C.ACT_CKPT_CONTIGUOUS_MEMORY_OPTIMIZATION, False))
        self.number_checkpoints = d.get(C.ACT_CKPT_NUMBER_CHECKPOINTS, None)
        self.synchronize_checkpoint_boundary = bool(
            d.get(C.ACT_CKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY, False))
        self.profile = bool(d.get(C.ACT_CKPT_PROFILE, False))


class FlopsProfilerConfig:
    def __init__(self, param_dict):
        d = param_dict.get(C.FLOPS_PROFILER, {})
        self.enabled = bool(d.get(C.FLOPS_PROFILER_ENABLED, C.FLOPS_PROFILER_ENABLED_DEFAULT))
        self.profile_step = int(d.get(C.FLOPS_PROFILER_PROFILE_STEP,
                                      C.FLOPS_PROFILER_PROFILE_STEP_DEFAULT))
        self.module_depth = int(d.get(C.FLOPS_PROFILER_MODULE_DEPTH,
                                      C.FLOPS_PROFILER_MODULE_DEPTH_DEFAULT))
        self.top_modules = int(d.get(C.FLOPS_PROFILER_TOP_MODULES,
                                     C.FLOPS_PROFILER_TOP_MODULES_DEFAULT))
        self.detailed = bool(d.get(C.FLOPS_PROFILER_DETAILED,
                                   C.FLOPS_PROFILER_DETAILED_DEFAULT))


class FlightRecorderConfig:
    """``monitor.flight_recorder`` sub-block (ISSUE 6): the process-wide
    event ring (telemetry/recorder.py). Default ON — recording is an
    in-memory dict append, no files; disable or resize here."""

    def __init__(self, monitor_dict):
        d = monitor_dict.get(C.MONITOR_FLIGHT_RECORDER, {}) or {}
        self.enabled = bool(d.get(C.FLIGHT_RECORDER_ENABLED,
                                  C.FLIGHT_RECORDER_ENABLED_DEFAULT))
        self.capacity = int(d.get(C.FLIGHT_RECORDER_CAPACITY,
                                  C.FLIGHT_RECORDER_CAPACITY_DEFAULT))
        if self.capacity < 32:
            raise DeepSpeedConfigError(
                f"monitor.flight_recorder.capacity must be >= 32 (a "
                f"watchdog dump promises the last 32 events), got "
                f"{self.capacity}")


class WatchdogConfig:
    """``monitor.watchdog`` sub-block (ISSUE 6): fence-point anomaly
    rules + one-shot ring dumps (telemetry/anomaly.py). Presence of the
    block enables it (it writes files on trigger, so it is opt-in,
    unlike the recorder)."""

    def __init__(self, monitor_dict):
        d = monitor_dict.get(C.MONITOR_WATCHDOG, None)
        self.enabled = d is not None and bool(
            d.get(C.WATCHDOG_ENABLED, C.WATCHDOG_ENABLED_DEFAULT))
        d = d or {}
        self.dump_dir = d.get(C.WATCHDOG_DUMP_DIR,
                              C.WATCHDOG_DUMP_DIR_DEFAULT)
        self.baseline_window = int(d.get(
            C.WATCHDOG_BASELINE_WINDOW, C.WATCHDOG_BASELINE_WINDOW_DEFAULT))
        self.min_samples = int(d.get(C.WATCHDOG_MIN_SAMPLES,
                                     C.WATCHDOG_MIN_SAMPLES_DEFAULT))
        self.step_time_factor = d.get(
            C.WATCHDOG_STEP_TIME_FACTOR, C.WATCHDOG_STEP_TIME_FACTOR_DEFAULT)
        self.swap_stall_factor = d.get(
            C.WATCHDOG_SWAP_STALL_FACTOR,
            C.WATCHDOG_SWAP_STALL_FACTOR_DEFAULT)
        self.swap_stall_min_s = d.get(
            C.WATCHDOG_SWAP_STALL_MIN_S, C.WATCHDOG_SWAP_STALL_MIN_S_DEFAULT)
        self.ttft_factor = d.get(C.WATCHDOG_TTFT_FACTOR,
                                 C.WATCHDOG_TTFT_FACTOR_DEFAULT)
        self.ttft_min_s = d.get(C.WATCHDOG_TTFT_MIN_S,
                                C.WATCHDOG_TTFT_MIN_S_DEFAULT)
        self.ckpt_stall_factor = d.get(
            C.WATCHDOG_CKPT_STALL_FACTOR,
            C.WATCHDOG_CKPT_STALL_FACTOR_DEFAULT)
        self.ckpt_stall_min_s = d.get(
            C.WATCHDOG_CKPT_STALL_MIN_S, C.WATCHDOG_CKPT_STALL_MIN_S_DEFAULT)
        self.check_nan = bool(d.get(C.WATCHDOG_CHECK_NAN,
                                    C.WATCHDOG_CHECK_NAN_DEFAULT))
        self.max_dumps = int(d.get(C.WATCHDOG_MAX_DUMPS,
                                   C.WATCHDOG_MAX_DUMPS_DEFAULT))
        # rank-straggler rule (ISSUE 12): evaluated on rank 0 at cluster
        # fences, against the leave-one-out median of the other ranks
        self.straggler_factor = d.get(C.WATCHDOG_STRAGGLER_FACTOR,
                                      C.WATCHDOG_STRAGGLER_FACTOR_DEFAULT)
        self.straggler_fences = int(d.get(
            C.WATCHDOG_STRAGGLER_FENCES, C.WATCHDOG_STRAGGLER_FENCES_DEFAULT))
        self.straggler_min_s = d.get(C.WATCHDOG_STRAGGLER_MIN_S,
                                     C.WATCHDOG_STRAGGLER_MIN_S_DEFAULT)
        if self.straggler_fences < 1:
            raise DeepSpeedConfigError(
                f"monitor.watchdog.straggler_fences must be >= 1 "
                f"(consecutive fences before the rule trips), got "
                f"{self.straggler_fences}")
        for name, v in (("step_time_factor", self.step_time_factor),
                        ("swap_stall_factor", self.swap_stall_factor),
                        ("ttft_factor", self.ttft_factor),
                        ("ckpt_stall_factor", self.ckpt_stall_factor),
                        ("straggler_factor", self.straggler_factor)):
            if not v > 1.0:
                raise DeepSpeedConfigError(
                    f"monitor.watchdog.{name} must be > 1 (an outlier "
                    f"threshold is a multiple of the baseline), got {v!r}")
        if self.enabled and not self.dump_dir:
            raise DeepSpeedConfigError(
                "monitor.watchdog.dump_dir must be set when the "
                "watchdog is enabled (dumps need somewhere to land)")


class ClusterTelemetryConfig:
    """``monitor.cluster`` sub-block (ISSUE 12): cross-rank metric
    aggregation at the engine's existing fence points (the
    ``steps_per_print`` loss readback; snapshot commit fences). Default
    ON — the exchange is a ~7-float allgather at a host sync the engine
    already pays, and single-process it degenerates to local
    ``cluster/*`` gauges with no collective at all."""

    def __init__(self, monitor_dict):
        d = monitor_dict.get(C.MONITOR_CLUSTER, {}) or {}
        self.enabled = bool(d.get(C.CLUSTER_ENABLED,
                                  C.CLUSTER_ENABLED_DEFAULT))


class SloConfig:
    """``monitor.slo`` sub-block (ISSUE 19): the windowed per-role SLO
    plane (telemetry/slo.py) — rolling quantiles + error-budget burn
    rate over TTFT/decode-tick/transport segments, exported as
    ``slo/*`` gauges and distilled into the per-role scale
    recommendation. Default ON (host floats only); the burn thresholds
    must keep ``down_burn < up_burn`` or the hysteresis band inverts."""

    def __init__(self, monitor_dict):
        d = monitor_dict.get(C.MONITOR_SLO, {}) or {}
        self.enabled = bool(d.get(C.SLO_ENABLED, C.SLO_ENABLED_DEFAULT))
        self.window_s = float(d.get(C.SLO_WINDOW_S,
                                    C.SLO_WINDOW_S_DEFAULT))
        self.targets = dict(d.get(C.SLO_TARGETS, {}) or {})
        self.budget = float(d.get(C.SLO_BUDGET, C.SLO_BUDGET_DEFAULT))
        self.up_burn = float(d.get(C.SLO_UP_BURN, C.SLO_UP_BURN_DEFAULT))
        self.down_burn = float(d.get(C.SLO_DOWN_BURN,
                                     C.SLO_DOWN_BURN_DEFAULT))
        self.min_samples = int(d.get(C.SLO_MIN_SAMPLES,
                                     C.SLO_MIN_SAMPLES_DEFAULT))
        if self.window_s <= 0:
            raise DeepSpeedConfigError(
                f"monitor.slo.window_s must be > 0, got {self.window_s!r}")
        if not 0 < self.budget <= 1:
            raise DeepSpeedConfigError(
                f"monitor.slo.budget must be in (0, 1], got "
                f"{self.budget!r}")
        if not self.down_burn < self.up_burn:
            raise DeepSpeedConfigError(
                f"monitor.slo needs down_burn < up_burn (the scale "
                f"hysteresis band), got {self.down_burn!r} >= "
                f"{self.up_burn!r}")
        for k, v in self.targets.items():
            if not (isinstance(v, (int, float)) and v > 0):
                raise DeepSpeedConfigError(
                    f"monitor.slo.targets[{k!r}] must be a positive "
                    f"latency in seconds, got {v!r}")


class MonitorConfig:
    """``monitor`` block: the unified telemetry export gate
    (deepspeed_tpu/telemetry). Presence of the block enables the
    per-``steps_per_print`` registry export — a JSONL stream (one file
    per rank; every event carries ts/rank/step; size-bounded rotation
    via ``jsonl_max_mb``/``jsonl_max_files``) plus, when the
    ``tensorboard`` block is also enabled, a bridge into the
    SummaryEventWriter scalar stream. The ``flight_recorder`` and
    ``watchdog`` sub-blocks (ISSUE 6) are parsed whether or not the
    export itself is enabled — the recorder is passive and the
    watchdog has its own gate."""

    def __init__(self, param_dict):
        d = param_dict.get(C.MONITOR, None)
        self.enabled = d is not None and bool(
            d.get(C.MONITOR_ENABLED, C.MONITOR_ENABLED_DEFAULT))
        d = d or {}
        self.output_path = d.get(C.MONITOR_OUTPUT_PATH,
                                 C.MONITOR_OUTPUT_PATH_DEFAULT)
        self.jsonl_path = d.get(C.MONITOR_JSONL_PATH,
                                C.MONITOR_JSONL_PATH_DEFAULT)
        self.jsonl_max_mb = d.get(C.MONITOR_JSONL_MAX_MB,
                                  C.MONITOR_JSONL_MAX_MB_DEFAULT)
        self.jsonl_max_files = int(d.get(
            C.MONITOR_JSONL_MAX_FILES, C.MONITOR_JSONL_MAX_FILES_DEFAULT))
        if self.jsonl_max_mb < 0 or self.jsonl_max_files < 1:
            raise DeepSpeedConfigError(
                f"monitor.jsonl_max_mb must be >= 0 (0 disables "
                f"rotation) and jsonl_max_files >= 1, got "
                f"{self.jsonl_max_mb!r}/{self.jsonl_max_files!r}")
        # live /metrics + /healthz endpoint (ISSUE 12): a stdlib
        # http.server thread on rank 0; 0 = off (the default — it
        # binds a socket, so it is opt-in like every file-writing gate)
        self.serve_port = int(d.get(C.MONITOR_SERVE_PORT,
                                    C.MONITOR_SERVE_PORT_DEFAULT))
        self.serve_host = str(d.get(C.MONITOR_SERVE_HOST,
                                    C.MONITOR_SERVE_HOST_DEFAULT))
        if not 0 <= self.serve_port <= 65535:
            raise DeepSpeedConfigError(
                f"monitor.serve_port must be 0 (off) or a valid TCP "
                f"port, got {self.serve_port}")
        self.flight_recorder = FlightRecorderConfig(d)
        self.watchdog = WatchdogConfig(d)
        self.cluster = ClusterTelemetryConfig(d)
        self.slo = SloConfig(d)


class SnapshotConfig:
    """``snapshot`` block (ISSUE 7): elastic preemption-tolerant
    training — periodic async checkpoints through the swap tier's
    write-behind aio handle (runtime/elastic/snapshot.py), a SIGTERM
    preemption hook with a grace budget, and auto-resume from the
    newest valid manifest on startup. Presence of the block (plus a
    ``path``) enables it — like the watchdog, it writes files."""

    def __init__(self, param_dict):
        d = param_dict.get(C.SNAPSHOT, None)
        self.enabled = d is not None and bool(
            d.get(C.SNAPSHOT_ENABLED, C.SNAPSHOT_ENABLED_DEFAULT))
        d = d or {}
        self.path = d.get(C.SNAPSHOT_PATH, C.SNAPSHOT_PATH_DEFAULT)
        self.interval_steps = int(d.get(C.SNAPSHOT_INTERVAL_STEPS,
                                        C.SNAPSHOT_INTERVAL_STEPS_DEFAULT))
        self.keep = int(d.get(C.SNAPSHOT_KEEP, C.SNAPSHOT_KEEP_DEFAULT))
        self.fsync = bool(d.get(C.SNAPSHOT_FSYNC, C.SNAPSHOT_FSYNC_DEFAULT))
        self.auto_resume = bool(d.get(C.SNAPSHOT_AUTO_RESUME,
                                      C.SNAPSHOT_AUTO_RESUME_DEFAULT))
        self.grace_secs = float(d.get(C.SNAPSHOT_GRACE_SECS,
                                      C.SNAPSHOT_GRACE_SECS_DEFAULT))
        signals = d.get(C.SNAPSHOT_SIGNALS, C.SNAPSHOT_SIGNALS_DEFAULT)
        if isinstance(signals, str):
            signals = (signals,)   # a bare "SIGTERM" must not iterate
        self.signals = tuple(signals)  # per character
        if self.enabled:
            if not self.path:
                raise DeepSpeedConfigError(
                    "snapshot.path must be set when the snapshot block "
                    "is enabled (snapshots need somewhere to land)")
            if self.interval_steps < 1:
                raise DeepSpeedConfigError(
                    f"snapshot.interval_steps must be >= 1, got "
                    f"{self.interval_steps}")
            if self.keep < 1:
                raise DeepSpeedConfigError(
                    f"snapshot.keep must be >= 1, got {self.keep}")
            if not self.grace_secs > 0:
                raise DeepSpeedConfigError(
                    f"snapshot.grace_secs must be > 0, got "
                    f"{self.grace_secs}")
            import signal as _signal
            for name in self.signals:
                # must be an actual Signals member: "alarm" etc. are
                # signal-module attributes (functions) that would pass
                # a bare getattr probe and crash handler install later
                if not isinstance(getattr(_signal, str(name), None),
                                  _signal.Signals):
                    raise DeepSpeedConfigError(
                        f"snapshot.signals: unknown signal {name!r}")


class FaultToleranceConfig:
    """``fault_tolerance`` block (ISSUE 15): the collective hang
    watchdog + heartbeat inside every worker (runtime/elastic/hang.py)
    and the rendezvous-retry knobs the supervisor exports to children.
    Presence of the block enables the in-process watchdog thread; the
    heartbeat file only appears when a directory is configured (or the
    supervisor provided one via ``DSTPU_HEARTBEAT_DIR``)."""

    def __init__(self, param_dict):
        d = param_dict.get(C.FAULT_TOLERANCE, None)
        self.enabled = d is not None and bool(
            d.get(C.FT_ENABLED, C.FT_ENABLED_DEFAULT))
        d = d or {}
        self.hang_deadline_s = float(d.get(C.FT_HANG_DEADLINE_S,
                                           C.FT_HANG_DEADLINE_S_DEFAULT))
        self.hang_poll_s = float(d.get(C.FT_HANG_POLL_S,
                                       C.FT_HANG_POLL_S_DEFAULT))
        self.heartbeat_dir = d.get(C.FT_HEARTBEAT_DIR,
                                   C.FT_HEARTBEAT_DIR_DEFAULT)
        self.heartbeat_interval_s = float(
            d.get(C.FT_HEARTBEAT_INTERVAL_S,
                  C.FT_HEARTBEAT_INTERVAL_S_DEFAULT))
        self.rendezvous_retries = int(
            d.get(C.FT_RENDEZVOUS_RETRIES, C.FT_RENDEZVOUS_RETRIES_DEFAULT))
        self.rendezvous_backoff_s = float(
            d.get(C.FT_RENDEZVOUS_BACKOFF_S,
                  C.FT_RENDEZVOUS_BACKOFF_S_DEFAULT))
        if self.enabled:
            if not self.hang_deadline_s > 0:
                raise DeepSpeedConfigError(
                    f"fault_tolerance.hang_deadline_s must be > 0, got "
                    f"{self.hang_deadline_s!r}")
            if self.hang_poll_s < 0:
                raise DeepSpeedConfigError(
                    f"fault_tolerance.hang_poll_s must be >= 0 (0 = "
                    f"deadline/10), got {self.hang_poll_s!r}")
            if not self.heartbeat_interval_s > 0:
                raise DeepSpeedConfigError(
                    f"fault_tolerance.heartbeat_interval_s must be > 0, "
                    f"got {self.heartbeat_interval_s!r}")
            if self.rendezvous_retries < 0:
                raise DeepSpeedConfigError(
                    f"fault_tolerance.rendezvous_retries must be >= 0, "
                    f"got {self.rendezvous_retries!r}")
            if not self.rendezvous_backoff_s > 0:
                raise DeepSpeedConfigError(
                    f"fault_tolerance.rendezvous_backoff_s must be > 0, "
                    f"got {self.rendezvous_backoff_s!r}")


class ProfilingConfig:
    """``profiling`` block: the programmatic XLA trace window.
    ``trace_dir`` + ``trace_steps: [start, stop)`` capture that range
    of global steps via jax.profiler.start_trace/stop_trace, so the
    telemetry spans' TraceAnnotations and the train fns' named_scope
    phase labels land in a perfetto/xprof-openable artifact."""

    def __init__(self, param_dict):
        d = param_dict.get(C.PROFILING, {})
        self.trace_dir = d.get(C.PROFILING_TRACE_DIR,
                               C.PROFILING_TRACE_DIR_DEFAULT)
        steps = d.get(C.PROFILING_TRACE_STEPS,
                      C.PROFILING_TRACE_STEPS_DEFAULT)
        if steps:
            steps = list(steps)
            if len(steps) != 2 or not all(
                    isinstance(s, int) and s >= 0 for s in steps) \
                    or steps[1] <= steps[0]:
                raise DeepSpeedConfigError(
                    f"profiling.trace_steps must be [start, stop) with "
                    f"0 <= start < stop, got {steps!r}")
        self.trace_steps = tuple(steps or ())
        if bool(self.trace_dir) != bool(self.trace_steps):
            raise DeepSpeedConfigError(
                "profiling.trace_dir and trace_steps gate the window "
                "together — set both (e.g. trace_dir + trace_steps "
                "[2, 4]) or neither; got "
                f"trace_dir={self.trace_dir!r}, "
                f"trace_steps={list(self.trace_steps)!r}")


class QuantizeTrainingConfig:
    """MoQ section (reference runtime/config.py:184-215
    get_quantize_training): progressive bit reduction + optional eigenvalue
    modulation."""

    def __init__(self, param_dict):
        d = param_dict.get(C.QUANTIZE_TRAINING, {})
        self.enabled = bool(d.get(C.QUANTIZE_TRAINING_ENABLED,
                                  C.QUANTIZE_TRAINING_ENABLED_DEFAULT))
        bits = d.get(C.QUANTIZE_BITS, {})
        self.start_bits = int(bits.get(C.QUANTIZE_START_BITS,
                                       C.QUANTIZE_START_BITS_DEFAULT))
        self.target_bits = int(bits.get(C.QUANTIZE_TARGET_BITS,
                                        C.QUANTIZE_TARGET_BITS_DEFAULT))
        sched = d.get(C.QUANTIZE_SCHEDULE, {})
        self.quantize_period = int(sched.get(C.QUANTIZE_PERIOD,
                                             C.QUANTIZE_PERIOD_DEFAULT))
        self.schedule_offset = int(sched.get(C.QUANTIZE_SCHEDULE_OFFSET,
                                             C.QUANTIZE_OFFSET_DEFAULT))
        self.groups = int(d.get(C.QUANTIZE_GROUPS, C.QUANTIZE_GROUPS_DEFAULT))
        algo = d.get(C.QUANTIZE_ALGO, {})
        self.q_type = 1 if algo.get(C.QUANTIZE_TYPE) == \
            C.QUANTIZE_ASYMMETRIC else 0
        self.q_rounding = 1 if algo.get(C.QUANTIZE_ROUNDING) == \
            C.QUANTIZE_STOCHASTIC_ROUNDING else 0
        mixed = d.get(C.FP16_MIXED_QUANTIZE, {})
        self.fp16_mixed_quantize = bool(mixed.get(
            C.FP16_MIXED_QUANTIZE_ENABLED,
            C.FP16_MIXED_QUANTIZE_ENABLED_DEFAULT))
        self.quantize_change_ratio = float(mixed.get(
            C.QUANTIZE_CHANGE_RATIO, C.QUANTIZE_CHANGE_RATIO_DEFAULT))
        self.verbose = bool(d.get(C.QUANTIZE_VERBOSE,
                                  C.QUANTIZE_VERBOSE_DEFAULT))
        self.quantizer_kernel = bool(d.get(C.QUANTIZER_KERNEL,
                                           C.QUANTIZER_KERNEL_DEFAULT))
        ev = d.get(C.QUANTIZE_EIGENVALUE, {})
        self.eigenvalue_enabled = bool(ev.get(
            C.QUANTIZE_EIGENVALUE_ENABLED,
            C.QUANTIZE_EIGENVALUE_ENABLED_DEFAULT))
        self.eigenvalue_verbose = bool(ev.get(C.EIGENVALUE_VERBOSE,
                                              C.EIGENVALUE_VERBOSE_DEFAULT))
        self.eigenvalue_max_iter = int(ev.get(C.EIGENVALUE_MAX_ITER,
                                              C.EIGENVALUE_MAX_ITER_DEFAULT))
        self.eigenvalue_tol = float(ev.get(C.EIGENVALUE_TOL,
                                           C.EIGENVALUE_TOL_DEFAULT))
        self.eigenvalue_stability = float(ev.get(
            C.EIGENVALUE_STABILITY, C.EIGENVALUE_STABILITY_DEFAULT))
        self.eigenvalue_gas_boundary_resolution = int(ev.get(
            C.EIGENVALUE_GAS_BOUNDARY_RESOLUTION,
            C.EIGENVALUE_GAS_BOUNDARY_RESOLUTION_DEFAULT))
        self.eigenvalue_layer_name = str(ev.get(
            C.EIGENVALUE_LAYER_NAME, C.EIGENVALUE_LAYER_NAME_DEFAULT))
        self.eigenvalue_layer_num = int(ev.get(
            C.EIGENVALUE_LAYER_NUM, C.EIGENVALUE_LAYER_NUM_DEFAULT))


class PLDConfig:
    def __init__(self, param_dict):
        d = param_dict.get(C.PROGRESSIVE_LAYER_DROP, {})
        self.enabled = bool(d.get(C.PLD_ENABLED, C.PLD_ENABLED_DEFAULT))
        self.theta = float(d.get(C.PLD_THETA, C.PLD_THETA_DEFAULT))
        self.gamma = float(d.get(C.PLD_GAMMA, C.PLD_GAMMA_DEFAULT))


class AioConfig:
    """reference swap_tensor/aio_config.py:18."""

    def __init__(self, param_dict):
        d = param_dict.get(C.AIO, {})
        self.block_size = int(d.get(C.AIO_BLOCK_SIZE, C.AIO_BLOCK_SIZE_DEFAULT))
        self.queue_depth = int(d.get(C.AIO_QUEUE_DEPTH, C.AIO_QUEUE_DEPTH_DEFAULT))
        self.thread_count = int(d.get(C.AIO_THREAD_COUNT, C.AIO_THREAD_COUNT_DEFAULT))
        self.single_submit = bool(d.get(C.AIO_SINGLE_SUBMIT, C.AIO_SINGLE_SUBMIT_DEFAULT))
        self.overlap_events = bool(d.get(C.AIO_OVERLAP_EVENTS, C.AIO_OVERLAP_EVENTS_DEFAULT))
        o_direct = d.get(C.AIO_O_DIRECT, C.AIO_O_DIRECT_DEFAULT)
        if not isinstance(o_direct, bool):
            raise DeepSpeedConfigError(
                f"aio.{C.AIO_O_DIRECT} must be a bool, got {o_direct!r}")
        self.o_direct = o_direct
        if self.block_size <= 0:
            raise DeepSpeedConfigError(
                f"aio.{C.AIO_BLOCK_SIZE} must be positive, got "
                f"{self.block_size}")
        if self.o_direct:
            import mmap
            if self.block_size % mmap.PAGESIZE:
                raise DeepSpeedConfigError(
                    f"aio.{C.AIO_O_DIRECT} requires "
                    f"aio.{C.AIO_BLOCK_SIZE} to be a multiple of the "
                    f"page size ({mmap.PAGESIZE}); got {self.block_size}"
                    " — O_DIRECT transfer lengths must stay aligned")


class TensorboardConfig:
    def __init__(self, param_dict):
        d = param_dict.get(C.TENSORBOARD, {})
        self.enabled = bool(d.get(C.TENSORBOARD_ENABLED, C.TENSORBOARD_ENABLED_DEFAULT))
        self.output_path = d.get(C.TENSORBOARD_OUTPUT_PATH, C.TENSORBOARD_OUTPUT_PATH_DEFAULT)
        self.job_name = d.get(C.TENSORBOARD_JOB_NAME, C.TENSORBOARD_JOB_NAME_DEFAULT)


class SparseAttentionConfig:
    """Sparse-attention section parser — reference config.py:236-406. Produces
    the kwargs for the layout generators in
    deepspeed_tpu/ops/sparse_attention/sparsity_config.py."""

    def __init__(self, param_dict):
        d = param_dict.get(C.SPARSE_ATTENTION, None)
        self.enabled = d is not None
        d = d or {}
        self.mode = d.get(C.SPARSE_MODE, C.SPARSE_MODE_DEFAULT)
        self.block = int(d.get(C.SPARSE_BLOCK, C.SPARSE_BLOCK_DEFAULT))
        self.different_layout_per_head = bool(
            d.get(C.SPARSE_DIFFERENT_LAYOUT_PER_HEAD,
                  C.SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT))
        self.num_local_blocks = int(d.get(C.SPARSE_NUM_LOCAL_BLOCKS,
                                          C.SPARSE_NUM_LOCAL_BLOCKS_DEFAULT))
        self.num_global_blocks = int(d.get(C.SPARSE_NUM_GLOBAL_BLOCKS,
                                           C.SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT))
        self.attention = d.get(C.SPARSE_ATTENTION_TYPE, C.SPARSE_ATTENTION_TYPE_DEFAULT)
        self.horizontal_global_attention = bool(
            d.get(C.SPARSE_HORIZONTAL_GLOBAL_ATTENTION,
                  C.SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT))
        self.num_different_global_patterns = int(
            d.get(C.SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS,
                  C.SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT))
        self.num_random_blocks = int(d.get(C.SPARSE_NUM_RANDOM_BLOCKS,
                                           C.SPARSE_NUM_RANDOM_BLOCKS_DEFAULT))
        self.local_window_blocks = d.get(C.SPARSE_LOCAL_WINDOW_BLOCKS,
                                         C.SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT)
        self.global_block_indices = d.get(C.SPARSE_GLOBAL_BLOCK_INDICES,
                                          C.SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT)
        self.global_block_end_indices = d.get(C.SPARSE_GLOBAL_BLOCK_END_INDICES,
                                              C.SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT)
        self.num_sliding_window_blocks = int(
            d.get(C.SPARSE_NUM_SLIDING_WINDOW_BLOCKS,
                  C.SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT))


class PipelineConfig:
    """tpu-native pipeline section (the reference configures PP through
    PipelineModule constructor args instead)."""

    def __init__(self, param_dict):
        d = param_dict.get(C.PIPELINE, {})
        self.stages = int(d.get(C.PIPELINE_STAGES, 1))
        self.partition = d.get(C.PIPELINE_PARTITION, "parameters")
        self.seed_layers = bool(d.get(C.PIPELINE_SEED_LAYERS, False))
        self.activation_checkpoint_interval = int(
            d.get(C.PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL, 0))


class ServingPrefixCacheConfig:
    """``serving.prefix_cache`` sub-block: copy-on-write prefix page
    sharing. Presence enables the refcounted prefix index."""

    def __init__(self, d):
        if d is not None and not isinstance(d, dict):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_PREFIX_CACHE} must be a dict with "
                f"keys [{C.SERVING_PREFIX_CACHE_ENABLED}, "
                f"{C.SERVING_PREFIX_CACHE_COW}], got {d!r}")
        self.enabled = d is not None and bool(
            d.get(C.SERVING_PREFIX_CACHE_ENABLED,
                  C.SERVING_PREFIX_CACHE_ENABLED_DEFAULT))
        d = d or {}
        self.cow = bool(d.get(C.SERVING_PREFIX_CACHE_COW,
                              C.SERVING_PREFIX_CACHE_COW_DEFAULT))

    def __repr__(self):
        return (f"ServingPrefixCacheConfig(enabled={self.enabled}, "
                f"cow={self.cow})")


class ServingSpeculativeConfig:
    """``serving.speculative`` sub-block: drafter-based speculative
    decoding. Presence enables; greedy-only verification."""

    def __init__(self, d):
        if d is not None and not isinstance(d, dict):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_SPECULATIVE} must be a dict with "
                f"keys [{C.SERVING_SPEC_ENABLED}, {C.SERVING_SPEC_TOKENS},"
                f" {C.SERVING_SPEC_DRAFTER}, {C.SERVING_SPEC_NGRAM_MAX}, "
                f"{C.SERVING_SPEC_NGRAM_MIN}], got {d!r}")
        self.enabled = d is not None and bool(
            d.get(C.SERVING_SPEC_ENABLED, C.SERVING_SPEC_ENABLED_DEFAULT))
        d = d or {}
        self.tokens = int(d.get(C.SERVING_SPEC_TOKENS,
                                C.SERVING_SPEC_TOKENS_DEFAULT))
        self.drafter = str(d.get(C.SERVING_SPEC_DRAFTER,
                                 C.SERVING_SPEC_DRAFTER_DEFAULT))
        self.ngram_max = int(d.get(C.SERVING_SPEC_NGRAM_MAX,
                                   C.SERVING_SPEC_NGRAM_MAX_DEFAULT))
        self.ngram_min = int(d.get(C.SERVING_SPEC_NGRAM_MIN,
                                   C.SERVING_SPEC_NGRAM_MIN_DEFAULT))
        if self.enabled and self.tokens < 1:
            raise DeepSpeedConfigError(
                f"serving.speculative.tokens must be >= 1, got "
                f"{self.tokens}")
        if self.drafter not in ("ngram", "model"):
            raise DeepSpeedConfigError(
                f"serving.speculative.drafter must be 'ngram' or "
                f"'model', got {self.drafter!r}")
        if not (self.ngram_max >= self.ngram_min >= 1):
            raise DeepSpeedConfigError(
                f"serving.speculative needs ngram_max >= ngram_min >= 1,"
                f" got {self.ngram_max}/{self.ngram_min}")

    def __repr__(self):
        return (f"ServingSpeculativeConfig(enabled={self.enabled}, "
                f"tokens={self.tokens}, drafter={self.drafter!r}, "
                f"ngram=[{self.ngram_min},{self.ngram_max}])")


class ServingElasticConfig:
    """``serving.elastic`` sub-block (ISSUE 11): preemption-tolerant
    serving. Presence (plus a ``snapshot_path``) enables the SIGTERM
    drain-or-snapshot path: requests that fit the ``grace_secs`` budget
    finish, the rest are snapshotted (slot state + referenced K/V pages
    + prefix index) through the two-rename elastic commit so a restore
    — possibly on a different engine/replica count — resumes them with
    greedy outputs token-for-token identical. ``max_retries`` /
    ``backoff_s`` bound the cross-replica requeue of a failed replica's
    restored requests."""

    def __init__(self, d):
        if d is not None and not isinstance(d, dict):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_ELASTIC} must be a dict with keys "
                f"[{C.SERVING_ELASTIC_ENABLED}, "
                f"{C.SERVING_ELASTIC_SNAPSHOT_PATH}, "
                f"{C.SERVING_ELASTIC_GRACE_SECS}, "
                f"{C.SERVING_ELASTIC_MAX_RETRIES}, "
                f"{C.SERVING_ELASTIC_BACKOFF_S}, "
                f"{C.SERVING_ELASTIC_INTERVAL_TICKS}, "
                f"{C.SERVING_ELASTIC_KEEP}, {C.SERVING_ELASTIC_FSYNC}, "
                f"{C.SERVING_ELASTIC_SIGNALS}], got {d!r}")
        self.enabled = d is not None and bool(
            d.get(C.SERVING_ELASTIC_ENABLED,
                  C.SERVING_ELASTIC_ENABLED_DEFAULT))
        d = d or {}
        self.snapshot_path = d.get(C.SERVING_ELASTIC_SNAPSHOT_PATH,
                                   C.SERVING_ELASTIC_SNAPSHOT_PATH_DEFAULT)

        def _num(key, default, cast, what):
            try:
                return cast(d.get(key, default))
            except (TypeError, ValueError):
                raise DeepSpeedConfigError(
                    f"serving.elastic.{key} must be {what}, got "
                    f"{d.get(key)!r}")

        self.grace_secs = _num(C.SERVING_ELASTIC_GRACE_SECS,
                               C.SERVING_ELASTIC_GRACE_SECS_DEFAULT,
                               float, "a number of seconds")
        self.max_retries = _num(C.SERVING_ELASTIC_MAX_RETRIES,
                                C.SERVING_ELASTIC_MAX_RETRIES_DEFAULT,
                                int, "an integer retry count")
        self.backoff_s = _num(C.SERVING_ELASTIC_BACKOFF_S,
                              C.SERVING_ELASTIC_BACKOFF_S_DEFAULT,
                              float, "a number of seconds")
        self.interval_ticks = _num(
            C.SERVING_ELASTIC_INTERVAL_TICKS,
            C.SERVING_ELASTIC_INTERVAL_TICKS_DEFAULT, int,
            "an integer tick count")
        self.keep = _num(C.SERVING_ELASTIC_KEEP,
                         C.SERVING_ELASTIC_KEEP_DEFAULT, int,
                         "an integer generation count")
        self.fsync = bool(d.get(C.SERVING_ELASTIC_FSYNC,
                                C.SERVING_ELASTIC_FSYNC_DEFAULT))
        signals = d.get(C.SERVING_ELASTIC_SIGNALS,
                        C.SERVING_ELASTIC_SIGNALS_DEFAULT)
        if isinstance(signals, str):
            signals = (signals,)   # a bare "SIGTERM" must not iterate
        self.signals = tuple(signals)  # per character
        if self.enabled:
            if not self.snapshot_path:
                raise DeepSpeedConfigError(
                    "serving.elastic.snapshot_path must be set when the "
                    "elastic block is enabled (snapshots need somewhere "
                    "to land)")
            if not self.grace_secs > 0:
                raise DeepSpeedConfigError(
                    f"serving.elastic.grace_secs must be > 0, got "
                    f"{self.grace_secs}")
            if self.max_retries < 0:
                raise DeepSpeedConfigError(
                    f"serving.elastic.max_retries must be >= 0, got "
                    f"{self.max_retries}")
            if self.backoff_s < 0:
                raise DeepSpeedConfigError(
                    f"serving.elastic.backoff_s must be >= 0, got "
                    f"{self.backoff_s}")
            if self.interval_ticks < 0:
                raise DeepSpeedConfigError(
                    f"serving.elastic.interval_ticks must be >= 0 "
                    f"(0 = snapshot only on preemption), got "
                    f"{self.interval_ticks}")
            if self.keep < 1:
                raise DeepSpeedConfigError(
                    f"serving.elastic.keep must be >= 1, got {self.keep}")
            import signal as _signal
            for name in self.signals:
                if not isinstance(getattr(_signal, str(name), None),
                                  _signal.Signals):
                    raise DeepSpeedConfigError(
                        f"serving.elastic.signals: unknown signal "
                        f"{name!r}")

    def __repr__(self):
        return (f"ServingElasticConfig(enabled={self.enabled}, "
                f"snapshot_path={self.snapshot_path!r}, "
                f"grace_secs={self.grace_secs}, "
                f"max_retries={self.max_retries}, "
                f"backoff_s={self.backoff_s}, "
                f"interval_ticks={self.interval_ticks})")


class ServingAutoscaleConfig:
    """``serving.autoscale`` sub-block (ISSUE 11): replica-pool
    autoscaling bounds + the scale-up signal. ``"watchdog"`` scales up
    on latched ttft_blowup / page_pool_exhausted watchdog trips and
    drains an idle replica (through the elastic snapshot path) to scale
    down; ``"none"`` pins the pool at ``min_replicas``."""

    def __init__(self, d):
        if d is not None and not isinstance(d, dict):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_AUTOSCALE} must be a dict with "
                f"keys [{C.SERVING_AUTOSCALE_MIN_REPLICAS}, "
                f"{C.SERVING_AUTOSCALE_MAX_REPLICAS}, "
                f"{C.SERVING_AUTOSCALE_SCALE_SIGNAL}], got {d!r}")
        d = d or {}

        def _int(key, default):
            try:
                return int(d.get(key, default))
            except (TypeError, ValueError):
                raise DeepSpeedConfigError(
                    f"serving.autoscale.{key} must be an integer, got "
                    f"{d.get(key)!r}")

        self.min_replicas = _int(C.SERVING_AUTOSCALE_MIN_REPLICAS,
                                 C.SERVING_AUTOSCALE_MIN_REPLICAS_DEFAULT)
        self.max_replicas = _int(C.SERVING_AUTOSCALE_MAX_REPLICAS,
                                 C.SERVING_AUTOSCALE_MAX_REPLICAS_DEFAULT)
        self.scale_signal = str(d.get(
            C.SERVING_AUTOSCALE_SCALE_SIGNAL,
            C.SERVING_AUTOSCALE_SCALE_SIGNAL_DEFAULT))
        if self.min_replicas < 1:
            raise DeepSpeedConfigError(
                f"serving.autoscale.min_replicas must be >= 1, got "
                f"{self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise DeepSpeedConfigError(
                f"serving.autoscale.max_replicas {self.max_replicas} < "
                f"min_replicas {self.min_replicas}")
        if self.scale_signal not in C.SERVING_AUTOSCALE_SCALE_SIGNAL_MODES:
            raise DeepSpeedConfigError(
                f"serving.autoscale.scale_signal must be one of "
                f"{list(C.SERVING_AUTOSCALE_SCALE_SIGNAL_MODES)}, got "
                f"{self.scale_signal!r}")

    def __repr__(self):
        return (f"ServingAutoscaleConfig(min={self.min_replicas}, "
                f"max={self.max_replicas}, "
                f"scale_signal={self.scale_signal!r})")


class ServingDisaggregationConfig:
    """``serving.disaggregation`` sub-block (ISSUE 14): the
    prefill/decode role split. Presence enables; ``decode_replicas: 0``
    (or ``enabled: false``) is the colocated fallback — the router
    degrades to an SLO dispatcher over ``prefill_replicas`` colocated
    engines with no handoff."""

    def __init__(self, d):
        if d is not None and not isinstance(d, dict):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_DISAGG} must be a dict with keys "
                f"[{C.SERVING_DISAGG_ENABLED}, "
                f"{C.SERVING_DISAGG_PREFILL_REPLICAS}, "
                f"{C.SERVING_DISAGG_DECODE_REPLICAS}, "
                f"{C.SERVING_DISAGG_DEDUPE_PAGES}, "
                f"{C.SERVING_DISAGG_TRANSPORT}, "
                f"{C.SERVING_DISAGG_ADDRESSING}, "
                f"{C.SERVING_DISAGG_PAYLOAD_TIMEOUT_S}], got {d!r}")
        self.enabled = d is not None and bool(
            d.get(C.SERVING_DISAGG_ENABLED,
                  C.SERVING_DISAGG_ENABLED_DEFAULT))
        d = d or {}

        def _int(key, default, floor, what):
            try:
                v = int(d.get(key, default))
            except (TypeError, ValueError):
                raise DeepSpeedConfigError(
                    f"serving.disaggregation.{key} must be an integer, "
                    f"got {d.get(key)!r}")
            if v < floor:
                raise DeepSpeedConfigError(
                    f"serving.disaggregation.{key} must be {what}, "
                    f"got {v}")
            return v

        self.prefill_replicas = _int(
            C.SERVING_DISAGG_PREFILL_REPLICAS,
            C.SERVING_DISAGG_PREFILL_REPLICAS_DEFAULT, 1, ">= 1")
        self.decode_replicas = _int(
            C.SERVING_DISAGG_DECODE_REPLICAS,
            C.SERVING_DISAGG_DECODE_REPLICAS_DEFAULT, 0,
            ">= 0 (0 = colocated fallback)")
        self.dedupe_pages = bool(d.get(
            C.SERVING_DISAGG_DEDUPE_PAGES,
            C.SERVING_DISAGG_DEDUPE_PAGES_DEFAULT))
        self.transport = str(d.get(C.SERVING_DISAGG_TRANSPORT,
                                   C.SERVING_DISAGG_TRANSPORT_DEFAULT))
        if self.transport not in C.SERVING_DISAGG_TRANSPORT_MODES:
            raise DeepSpeedConfigError(
                f"serving.disaggregation.{C.SERVING_DISAGG_TRANSPORT} "
                f"must be one of "
                f"{list(C.SERVING_DISAGG_TRANSPORT_MODES)} — "
                f"\"inproc\" keeps the handoff on-device inside one "
                f"process, \"process\" places roles on ranks over the "
                f"cross-process fabric "
                f"(serving.build_transport_node) — got "
                f"{self.transport!r}")
        self.addressing = str(d.get(C.SERVING_DISAGG_ADDRESSING,
                                    C.SERVING_DISAGG_ADDRESSING_DEFAULT))
        if self.addressing not in C.SERVING_DISAGG_ADDRESSING_MODES:
            raise DeepSpeedConfigError(
                f"serving.disaggregation.{C.SERVING_DISAGG_ADDRESSING} "
                f"must be one of "
                f"{list(C.SERVING_DISAGG_ADDRESSING_MODES)} — "
                f"\"targeted\" moves destination-addressed frames "
                f"point-to-point so a KV payload crosses the wire "
                f"once, \"broadcast\" keeps the legacy all-rank "
                f"allgather — got {self.addressing!r}")
        try:
            self.payload_timeout_s = float(d.get(
                C.SERVING_DISAGG_PAYLOAD_TIMEOUT_S,
                C.SERVING_DISAGG_PAYLOAD_TIMEOUT_S_DEFAULT))
        except (TypeError, ValueError):
            raise DeepSpeedConfigError(
                f"serving.disaggregation."
                f"{C.SERVING_DISAGG_PAYLOAD_TIMEOUT_S} must be a "
                f"number of seconds, got "
                f"{d.get(C.SERVING_DISAGG_PAYLOAD_TIMEOUT_S)!r}")
        if self.payload_timeout_s <= 0:
            raise DeepSpeedConfigError(
                f"serving.disaggregation."
                f"{C.SERVING_DISAGG_PAYLOAD_TIMEOUT_S} must be > 0 "
                f"(a dead peer must fail loud, never hang), got "
                f"{self.payload_timeout_s}")

    def __repr__(self):
        return (f"ServingDisaggregationConfig(enabled={self.enabled}, "
                f"prefill={self.prefill_replicas}, "
                f"decode={self.decode_replicas}, "
                f"dedupe_pages={self.dedupe_pages}, "
                f"transport={self.transport!r}, "
                f"addressing={self.addressing!r}, "
                f"payload_timeout_s={self.payload_timeout_s})")


class ServingRouterConfig:
    """``serving.router`` sub-block (ISSUE 14): policy knobs for the
    SLO-aware multi-engine router. All knobs have live defaults — the
    block only exists to tune them (presence alone changes nothing;
    the router is built by ``serving.build_router`` /
    ``serving.disaggregation``)."""

    def __init__(self, d):
        if d is not None and not isinstance(d, dict):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_ROUTER} must be a dict with keys "
                f"[{C.SERVING_ROUTER_PREFIX_ROUTING}, "
                f"{C.SERVING_ROUTER_QUEUE_WEIGHT}, "
                f"{C.SERVING_ROUTER_TTFT_WEIGHT}, "
                f"{C.SERVING_ROUTER_TTFT_WINDOW}, "
                f"{C.SERVING_ROUTER_MAX_HANDOFF_RETRIES}, "
                f"{C.SERVING_ROUTER_DECODE_TICK_CAP}, "
                f"{C.SERVING_ROUTER_MAX_INFLIGHT_PAGES}, "
                f"{C.SERVING_ROUTER_MAX_INFLIGHT_PAGES_PER_RANK}, "
                f"{C.SERVING_ROUTER_DECODE_SCHEDULE}], got {d!r}")
        d = d or {}

        def _num(key, default, cast, what, floor):
            try:
                v = cast(d.get(key, default))
            except (TypeError, ValueError):
                raise DeepSpeedConfigError(
                    f"serving.router.{key} must be {what}, got "
                    f"{d.get(key)!r}")
            if v < floor:
                raise DeepSpeedConfigError(
                    f"serving.router.{key} must be >= {floor}, got {v}")
            return v

        self.prefix_routing = bool(d.get(
            C.SERVING_ROUTER_PREFIX_ROUTING,
            C.SERVING_ROUTER_PREFIX_ROUTING_DEFAULT))
        self.queue_weight = _num(
            C.SERVING_ROUTER_QUEUE_WEIGHT,
            C.SERVING_ROUTER_QUEUE_WEIGHT_DEFAULT, float, "a number", 0)
        self.ttft_weight = _num(
            C.SERVING_ROUTER_TTFT_WEIGHT,
            C.SERVING_ROUTER_TTFT_WEIGHT_DEFAULT, float, "a number", 0)
        self.ttft_window = _num(
            C.SERVING_ROUTER_TTFT_WINDOW,
            C.SERVING_ROUTER_TTFT_WINDOW_DEFAULT, int, "an integer", 1)
        self.max_handoff_retries = _num(
            C.SERVING_ROUTER_MAX_HANDOFF_RETRIES,
            C.SERVING_ROUTER_MAX_HANDOFF_RETRIES_DEFAULT, int,
            "an integer", 0)
        self.decode_tick_cap = _num(
            C.SERVING_ROUTER_DECODE_TICK_CAP,
            C.SERVING_ROUTER_DECODE_TICK_CAP_DEFAULT, int,
            "an integer", 1)
        self.max_inflight_pages = _num(
            C.SERVING_ROUTER_MAX_INFLIGHT_PAGES,
            C.SERVING_ROUTER_MAX_INFLIGHT_PAGES_DEFAULT, int,
            "an integer (0 = 2x the decode pools' allocatable total)",
            0)
        self.max_inflight_pages_per_rank = _num(
            C.SERVING_ROUTER_MAX_INFLIGHT_PAGES_PER_RANK,
            C.SERVING_ROUTER_MAX_INFLIGHT_PAGES_PER_RANK_DEFAULT, int,
            "an integer (0 = the aggregate bound split evenly across "
            "decode ranks)", 0)
        self.decode_schedule = str(d.get(
            C.SERVING_ROUTER_DECODE_SCHEDULE,
            C.SERVING_ROUTER_DECODE_SCHEDULE_DEFAULT))
        if self.decode_schedule not in \
                C.SERVING_ROUTER_DECODE_SCHEDULE_MODES:
            raise DeepSpeedConfigError(
                f"serving.router.{C.SERVING_ROUTER_DECODE_SCHEDULE} "
                f"must be one of "
                f"{list(C.SERVING_ROUTER_DECODE_SCHEDULE_MODES)}, got "
                f"{self.decode_schedule!r}")

    def __repr__(self):
        return (f"ServingRouterConfig(prefix_routing="
                f"{self.prefix_routing}, "
                f"queue_weight={self.queue_weight}, "
                f"ttft_weight={self.ttft_weight}, "
                f"ttft_window={self.ttft_window}, "
                f"max_handoff_retries={self.max_handoff_retries}, "
                f"decode_tick_cap={self.decode_tick_cap}, "
                f"max_inflight_pages={self.max_inflight_pages}, "
                f"max_inflight_pages_per_rank="
                f"{self.max_inflight_pages_per_rank}, "
                f"decode_schedule={self.decode_schedule!r})")


class ServingConfig:
    """tpu-native ``serving`` block: the continuous-batching engine with
    a paged KV cache (deepspeed_tpu/serving). Presence of the block
    enables it; geometry maps 1:1 onto PagedCacheSpec. Optional
    sub-blocks: ``prefix_cache`` (COW prefix page sharing),
    ``speculative`` (drafter-based speculative decoding), ``elastic``
    (drain-or-snapshot preemption tolerance), ``autoscale``
    (replica-pool bounds + scale signal), ``disaggregation`` (the
    prefill/decode role split, ISSUE 14) and ``router`` (the SLO-aware
    multi-engine router's policy knobs)."""

    def __init__(self, param_dict):
        d = param_dict.get(C.SERVING, None)
        self.enabled = d is not None and bool(
            d.get(C.SERVING_ENABLED, C.SERVING_ENABLED_DEFAULT))
        d = d or {}
        self.prefix_cache = ServingPrefixCacheConfig(
            d.get(C.SERVING_PREFIX_CACHE, None))
        self.speculative = ServingSpeculativeConfig(
            d.get(C.SERVING_SPECULATIVE, None))
        self.elastic = ServingElasticConfig(
            d.get(C.SERVING_ELASTIC, None))
        self.autoscale = ServingAutoscaleConfig(
            d.get(C.SERVING_AUTOSCALE, None))
        self.disaggregation = ServingDisaggregationConfig(
            d.get(C.SERVING_DISAGG, None))
        self.router = ServingRouterConfig(
            d.get(C.SERVING_ROUTER, None))
        self.slots = int(d.get(C.SERVING_SLOTS, C.SERVING_SLOTS_DEFAULT))
        self.page_size = int(d.get(C.SERVING_PAGE_SIZE,
                                   C.SERVING_PAGE_SIZE_DEFAULT))
        self.max_pages_per_slot = int(
            d.get(C.SERVING_MAX_PAGES_PER_SLOT,
                  C.SERVING_MAX_PAGES_PER_SLOT_DEFAULT))
        self.num_blocks = int(d.get(C.SERVING_NUM_BLOCKS,
                                    C.SERVING_NUM_BLOCKS_DEFAULT))
        self.kv_cache_bits = int(d.get(C.SERVING_KV_CACHE_BITS,
                                       C.SERVING_KV_CACHE_BITS_DEFAULT))
        self.quantize_bits = int(d.get(C.SERVING_QUANTIZE_BITS,
                                       C.SERVING_QUANTIZE_BITS_DEFAULT))
        if self.kv_cache_bits not in (0, 8):
            raise DeepSpeedConfigError(
                f"serving.kv_cache_bits must be 0 or 8, got "
                f"{self.kv_cache_bits}")
        if self.quantize_bits not in (0, 8):
            raise DeepSpeedConfigError(
                f"serving.quantize_bits must be 0 or 8, got "
                f"{self.quantize_bits}")
        if self.slots < 1 or self.page_size < 1 \
                or self.max_pages_per_slot < 1:
            raise DeepSpeedConfigError(
                "serving.slots / page_size / max_pages_per_slot must be "
                f"positive, got {self.slots}/{self.page_size}/"
                f"{self.max_pages_per_slot}")
        min_blocks = self.slots * self.max_pages_per_slot + 1
        if self.num_blocks and self.num_blocks < self.slots + 1:
            raise DeepSpeedConfigError(
                f"serving.num_blocks {self.num_blocks} cannot even hold "
                f"one page per slot (+1 reserved trash block); need >= "
                f"{self.slots + 1} (fully-provisioned: {min_blocks})")


class CommHierarchyConfig:
    """``comm.hierarchy`` block (ISSUE 10): link-aware two-level
    gradient exchange for the 1-bit compressed train path — the fast
    (ICI-class) axis exchanges uncompressed, only the slow (DCN-class)
    inter-host hop carries sign bits. Presence of the block enables it;
    ``slow_axis`` 0 derives the split from real process boundaries,
    >1 forces a synthetic split for single-process testing."""

    def __init__(self, d):
        if d is not None and not isinstance(d, dict):
            raise DeepSpeedConfigError(
                f"comm.{C.COMM_HIERARCHY} must be a dict with keys "
                f"[{C.COMM_HIERARCHY_ENABLED}, {C.COMM_HIERARCHY_SLOW_AXIS},"
                f" {C.COMM_HIERARCHY_COMPRESSION}, "
                f"{C.COMM_HIERARCHY_MIN_BUCKET_BYTES}], got {d!r}")
        self.enabled = d is not None and bool(
            d.get(C.COMM_HIERARCHY_ENABLED, C.COMM_HIERARCHY_ENABLED_DEFAULT))
        d = d or {}
        slow = d.get(C.COMM_HIERARCHY_SLOW_AXIS,
                     C.COMM_HIERARCHY_SLOW_AXIS_DEFAULT)
        if slow in ("auto", None):
            slow = 0
        try:
            self.slow_axis = int(slow)
        except (TypeError, ValueError):
            raise DeepSpeedConfigError(
                f"comm.hierarchy.{C.COMM_HIERARCHY_SLOW_AXIS} must be "
                f"0, \"auto\", or an integer >= 2, got {slow!r}")
        if self.slow_axis < 0 or self.slow_axis == 1:
            raise DeepSpeedConfigError(
                f"comm.hierarchy.{C.COMM_HIERARCHY_SLOW_AXIS} must be 0 "
                f"(auto: process boundaries) or >= 2 (synthetic split), "
                f"got {self.slow_axis}")
        self.compression = str(d.get(C.COMM_HIERARCHY_COMPRESSION,
                                     C.COMM_HIERARCHY_COMPRESSION_DEFAULT))
        if self.compression not in C.COMM_HIERARCHY_COMPRESSION_MODES:
            raise DeepSpeedConfigError(
                f"comm.hierarchy.{C.COMM_HIERARCHY_COMPRESSION} must be "
                f"one of {list(C.COMM_HIERARCHY_COMPRESSION_MODES)}, got "
                f"{self.compression!r}")
        try:
            self.min_bucket_bytes = int(
                d.get(C.COMM_HIERARCHY_MIN_BUCKET_BYTES,
                      C.COMM_HIERARCHY_MIN_BUCKET_BYTES_DEFAULT))
        except (TypeError, ValueError):
            raise DeepSpeedConfigError(
                f"comm.hierarchy.{C.COMM_HIERARCHY_MIN_BUCKET_BYTES} "
                f"must be an integer byte count, got "
                f"{d.get(C.COMM_HIERARCHY_MIN_BUCKET_BYTES)!r}")
        if self.min_bucket_bytes < 0:
            raise DeepSpeedConfigError(
                f"comm.hierarchy.{C.COMM_HIERARCHY_MIN_BUCKET_BYTES} must "
                f"be >= 0, got {self.min_bucket_bytes}")

    def __repr__(self):
        return (f"CommHierarchyConfig(enabled={self.enabled}, "
                f"slow_axis={self.slow_axis}, "
                f"compression={self.compression!r}, "
                f"min_bucket_bytes={self.min_bucket_bytes})")


class CommConfig:
    """Top-level ``comm`` block (tpu-native; the reference's comm knobs
    ride the optimizer/backend objects instead)."""

    def __init__(self, param_dict):
        d = param_dict.get(C.COMM, {})
        if not isinstance(d, dict):
            raise DeepSpeedConfigError(
                f"{C.COMM} must be a dict, got {d!r}")
        self.hierarchy = CommHierarchyConfig(d.get(C.COMM_HIERARCHY, None))


class MeshConfigSection:
    """tpu-native: logical mesh axis sizes. -1 on the data axis means
    "whatever is left" after the explicit axes divide the device count."""

    def __init__(self, param_dict):
        d = param_dict.get(C.MESH, {})
        self.data = int(d.get(C.MESH_DATA, -1))
        self.model = int(d.get(C.MESH_MODEL, 1))
        self.pipe = int(d.get(C.MESH_PIPE, 1))
        self.seq = int(d.get(C.MESH_SEQ, 1))
        self.expert = int(d.get(C.MESH_EXPERT, 1))


class DeepSpeedConfig:
    """Full config object — reference runtime/config.py:653.

    ``config``: path to json, a json string, or a dict.
    ``world_size``: data-parallel world size used by the batch triangle
    (reference passes mpu; here callers pass the mesh's dp axis size).
    """

    @staticmethod
    def load_param_dict(config):
        """Resolve a path / JSON string / dict / DeepSpeedConfig into the raw
        param dict without running validation."""
        if isinstance(config, DeepSpeedConfig):
            return config._param_dict
        if isinstance(config, str):
            if os.path.exists(config):
                with open(config) as f:
                    return json.load(f)
            try:
                return json.loads(config)
            except json.JSONDecodeError:
                raise DeepSpeedConfigError(
                    f"Expected a string path to an existing deepspeed config, "
                    f"or a valid JSON string, but received: {config}")
        if isinstance(config, dict):
            return dict(config)
        raise DeepSpeedConfigError(
            f"Expected a string path, JSON string, or dict; got {type(config)}")

    def __init__(self, config, mpu=None, world_size=None):
        self._param_dict = self.load_param_dict(config)

        if world_size is not None:
            self.world_size = int(world_size)
        elif mpu is not None and hasattr(mpu, "get_data_parallel_world_size"):
            self.world_size = mpu.get_data_parallel_world_size()
        else:
            self.world_size = 1

        self._apply_elasticity()
        self._initialize_params(self._param_dict)
        self._set_batch_related_parameters()
        self._do_sanity_check()

    def _apply_elasticity(self):
        """If elastic training is on, the elastic calculator owns the batch
        triangle — reference config.py:676-728."""
        from deepspeed_tpu import elasticity as el
        from deepspeed_tpu.elasticity import constants as EC

        if not el.elasticity_enabled(self._param_dict):
            return
        logger.info("elasticity support enabled")
        final_batch_size, valid_chips, micro_batch_size = el.compute_elastic_config(
            ds_config=self._param_dict, world_size=self.world_size)
        elastic_dict = self._param_dict[EC.ELASTICITY]
        el.ensure_immutable_elastic_config(elastic_dict)

        if not elastic_dict.get(EC.IGNORE_NON_ELASTIC_BATCH_INFO,
                                EC.IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT):
            batch_keys = (C.TRAIN_BATCH_SIZE, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                          C.TRAIN_MICRO_BATCH_SIZE_PER_CHIP,
                          C.GRADIENT_ACCUMULATION_STEPS)
            if any(k in self._param_dict for k in batch_keys):
                raise el.ElasticityConfigError(
                    "Batch-related parameters found in the config but elastic "
                    "training is enabled, which takes control of them. Set "
                    f"'{EC.IGNORE_NON_ELASTIC_BATCH_INFO}': true to silently "
                    "ignore them instead.")

        grad_accum = final_batch_size // (micro_batch_size * self.world_size)
        logger.info(f"[Elasticity] valid chip counts: {valid_chips}")
        self._param_dict[C.TRAIN_BATCH_SIZE] = final_batch_size
        self._param_dict[C.TRAIN_MICRO_BATCH_SIZE_PER_GPU] = micro_batch_size
        self._param_dict[C.GRADIENT_ACCUMULATION_STEPS] = grad_accum
        self.elastic_valid_chips = valid_chips

    # -- params ------------------------------------------------------------
    def _initialize_params(self, pd):
        self.train_batch_size = pd.get(C.TRAIN_BATCH_SIZE, C.TRAIN_BATCH_SIZE_DEFAULT)
        self.train_micro_batch_size_per_gpu = pd.get(
            C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
            pd.get(C.TRAIN_MICRO_BATCH_SIZE_PER_CHIP,
                   C.TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT))
        self.gradient_accumulation_steps = pd.get(C.GRADIENT_ACCUMULATION_STEPS,
                                                  C.GRADIENT_ACCUMULATION_STEPS_DEFAULT)
        self.steps_per_print = pd.get(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT)
        self.dump_state = pd.get(C.DUMP_STATE, C.DUMP_STATE_DEFAULT)
        self.seed = int(pd.get(C.SEED, C.SEED_DEFAULT))

        self.disable_allgather = pd.get(C.DISABLE_ALLGATHER, C.DISABLE_ALLGATHER_DEFAULT)
        self.allreduce_always_fp32 = pd.get(C.ALLREDUCE_ALWAYS_FP32,
                                            C.ALLREDUCE_ALWAYS_FP32_DEFAULT)
        self.prescale_gradients = pd.get(C.PRESCALE_GRADIENTS, C.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = pd.get(C.GRADIENT_PREDIVIDE_FACTOR,
                                                C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT)
        self.sparse_gradients_enabled = pd.get(C.SPARSE_GRADIENTS, C.SPARSE_GRADIENTS_DEFAULT)

        self.zero_config = DeepSpeedZeroConfig(pd)
        self.zero_optimization_stage = self.zero_config.stage
        self.zero_enabled = self.zero_optimization_stage > 0

        self.activation_checkpointing_config = ActivationCheckpointingConfig(pd)
        self.flops_profiler_config = FlopsProfilerConfig(pd)
        self.pld_config = PLDConfig(pd)
        self.quantize_training_config = QuantizeTrainingConfig(pd)
        self.aio_config = AioConfig(pd)
        self.tensorboard_config = TensorboardConfig(pd)
        self.monitor_config = MonitorConfig(pd)
        self.profiling_config = ProfilingConfig(pd)
        self.snapshot_config = SnapshotConfig(pd)
        self.fault_tolerance_config = FaultToleranceConfig(pd)
        self.sparse_attention_config = SparseAttentionConfig(pd)
        self.pipeline_config = PipelineConfig(pd)
        self.mesh_config = MeshConfigSection(pd)
        self.serving_config = ServingConfig(pd)
        self.comm_config = CommConfig(pd)

        self.gradient_clipping = pd.get(C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT)

        # precision: reference fp16 section kept for parity; "bf16" section and
        # "precision" key are the tpu-native way.
        fp16 = pd.get(C.FP16, {})
        self.fp16_enabled = bool(fp16.get(C.FP16_ENABLED, C.FP16_ENABLED_DEFAULT))
        self.loss_scale = fp16.get(C.FP16_LOSS_SCALE, C.FP16_LOSS_SCALE_DEFAULT)
        self.initial_scale_power = fp16.get(C.FP16_INITIAL_SCALE_POWER,
                                            C.FP16_INITIAL_SCALE_POWER_DEFAULT)
        self.loss_scale_window = fp16.get(C.FP16_LOSS_SCALE_WINDOW,
                                          C.FP16_LOSS_SCALE_WINDOW_DEFAULT)
        self.hysteresis = fp16.get(C.FP16_HYSTERESIS, C.FP16_HYSTERESIS_DEFAULT)
        self.min_loss_scale = fp16.get(C.FP16_MIN_LOSS_SCALE, C.FP16_MIN_LOSS_SCALE_DEFAULT)

        bf16 = pd.get(C.BF16, pd.get(C.BFLOAT16, {}))
        self.bf16_enabled = bool(bf16.get(C.BF16_ENABLED, C.BF16_ENABLED_DEFAULT))
        precision = pd.get(C.PRECISION, None)
        if precision is not None:
            self.bf16_enabled = precision in ("bfloat16", "bf16")
            self.fp16_enabled = precision in ("float16", "fp16")

        # gradient-accumulation buffer dtype (modern DeepSpeed's
        # data_types.grad_accum_dtype; the reference's fp16 engine
        # accumulated in fp16 implicitly). "fp32" (default) or "bf16" —
        # bf16 halves the accumulator HBM for long-gas large models.
        data_types = pd.get("data_types", {})
        self.grad_accum_dtype = data_types.get(
            "grad_accum_dtype",
            bf16.get("grad_accum_dtype", "fp32"))
        if self.grad_accum_dtype not in ("fp32", "bf16"):
            raise DeepSpeedConfigError(
                f"grad_accum_dtype must be 'fp32' or 'bf16', got "
                f"{self.grad_accum_dtype!r}")
        # grad_dtype="bf16": cast fp32 params to bf16 ONCE before the model
        # apply inside the differentiated function, so every parameter
        # cotangent (including layer-scan stack buffers) materializes in
        # bf16 — the reference fp16 engine's grads-in-fp16 semantics
        # (model.half(), engine.py:624), with fp32 master math in the
        # optimizer read.
        self.grad_dtype = data_types.get("grad_dtype", "fp32")
        if self.grad_dtype not in ("fp32", "bf16"):
            raise DeepSpeedConfigError(
                f"grad_dtype must be 'fp32' or 'bf16', got "
                f"{self.grad_dtype!r}")

        self.optimizer_name = None
        self.optimizer_params = None
        opt = pd.get(C.OPTIMIZER, None)
        if opt:
            self.optimizer_name = opt.get(C.TYPE, C.OPTIMIZER_TYPE_DEFAULT)
            if self.optimizer_name:
                self.optimizer_name = self.optimizer_name.lower()
            self.optimizer_params = opt.get(C.OPTIMIZER_PARAMS, {})
        self.optimizer_legacy_fusion = bool(
            (opt or {}).get(C.LEGACY_FUSION, C.LEGACY_FUSION_DEFAULT))

        self.scheduler_name = None
        self.scheduler_params = None
        sched = pd.get(C.SCHEDULER, None)
        if sched:
            self.scheduler_name = sched.get(C.TYPE, C.SCHEDULER_TYPE_DEFAULT)
            self.scheduler_params = sched.get(C.SCHEDULER_PARAMS, {})

        self.wall_clock_breakdown = pd.get(C.WALL_CLOCK_BREAKDOWN,
                                           C.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = pd.get(C.MEMORY_BREAKDOWN, C.MEMORY_BREAKDOWN_DEFAULT)

        quantize = pd.get(C.QUANTIZE_TRAINING, {})
        if isinstance(quantize, dict):
            self.quantize_training_enabled = bool(
                quantize.get(C.QUANTIZE_TRAINING_ENABLED, False))
            self.quantize_training_params = quantize
        else:
            self.quantize_training_enabled = False
            self.quantize_training_params = {}

        self.elasticity_enabled = bool(
            pd.get(C.ELASTICITY, {}).get(C.ENABLED, C.ENABLED_DEFAULT))
        self.elasticity_params = pd.get(C.ELASTICITY, {})

    # -- batch triangle ----------------------------------------------------
    def _batch_assertion(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        assert train_batch > 0, f"Train batch size: {train_batch} has to be greater than 0"
        assert micro_batch > 0, f"Micro batch size per gpu: {micro_batch} has to be greater than 0"
        assert grad_acc > 0, f"Gradient accumulation steps: {grad_acc} has to be greater than 0"
        assert train_batch == micro_batch * grad_acc * self.world_size, (
            f"Check batch related parameters. train_batch_size is not equal "
            f"to micro_batch_per_gpu * gradient_acc_step * world_size "
            f"{train_batch} != {micro_batch} * {grad_acc} * {self.world_size}")

    def _set_batch_related_parameters(self):
        """Solve the batch triangle — logic mirrors reference config.py:837-888."""
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps

        # all three provided → validate
        if all(x is not None for x in (train_batch, micro_batch, grad_acc)):
            pass
        # two of three
        elif train_batch is not None and micro_batch is not None:
            grad_acc = train_batch // micro_batch
            grad_acc //= self.world_size
            self.gradient_accumulation_steps = grad_acc
        elif train_batch is not None and grad_acc is not None:
            micro_batch = train_batch // self.world_size
            micro_batch //= grad_acc
            self.train_micro_batch_size_per_gpu = micro_batch
        elif micro_batch is not None and grad_acc is not None:
            self.train_batch_size = micro_batch * grad_acc * self.world_size
        # one of three
        elif train_batch is not None:
            self.gradient_accumulation_steps = 1
            self.train_micro_batch_size_per_gpu = train_batch // self.world_size
        elif micro_batch is not None:
            self.train_batch_size = micro_batch * self.world_size
            self.gradient_accumulation_steps = 1
        else:
            raise DeepSpeedConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu needs "
                "to be provided")
        self._batch_assertion()

    def _do_sanity_check(self):
        if self.fp16_enabled and self.bf16_enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")
        hcfg = self.comm_config.hierarchy
        if hcfg.enabled and self.zero_config.stage3_prefetch \
                and self.zero_config.stage3_prefetch_gather == "fused":
            raise DeepSpeedConfigError(
                "comm.hierarchy composes with zero_optimization."
                "stage3_prefetch only under explicit collectives "
                "(stage3_prefetch_gather 'ring' or 'fused_matmul'): "
                "'fused' hands the gather schedule to XLA, which cannot "
                "honor the two-level link split")
        if self.zero_enabled and self.optimizer_name is not None:
            if self.optimizer_name not in C.DEEPSPEED_OPTIMIZERS + ["sgd"]:
                logger.warning(
                    f"optimizer {self.optimizer_name} is not a built-in optimizer; "
                    f"ZeRO sharding will still be applied to its state pytree")

    def print(self, name="DeepSpeedConfig"):
        logger.info("{}:".format(name))
        for k in sorted(vars(self)):
            if k.startswith("_"):
                continue
            logger.info("  {} {}".format(k, getattr(self, k)))
