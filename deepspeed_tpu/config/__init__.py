from deepspeed_tpu.config.config import DeepSpeedConfig, DeepSpeedConfigError
