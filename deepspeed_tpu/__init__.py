"""deepspeed_tpu — a TPU-native large-model training framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of 2021-era DeepSpeed
(reference: deepspeed/__init__.py:54 `initialize`, :203 `add_config_arguments`):
ZeRO-style partitioned data parallelism expressed as GSPMD sharding over a
`jax.sharding.Mesh`, pipeline + tensor + sequence parallelism over ICI,
host/NVMe offload through a native C++ async-IO tier, Pallas kernels for the
hot ops, and an engine/config/checkpoint stack mirroring the reference's user
API.

Typical use::

    import deepspeed_tpu as dstpu

    engine, _, loader, scheduler = dstpu.initialize(
        config="ds_config.json", model=model, training_data=data)
    for batch in loader:
        loss = engine.train_batch(batch)
"""

from deepspeed_tpu.version import __version__, git_hash, git_branch

from deepspeed_tpu.config.config import DeepSpeedConfig
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.lr_schedules import add_tuning_arguments
from deepspeed_tpu.parallel.mesh import (
    MeshConfig,
    make_mesh,
    init_distributed,
)
from deepspeed_tpu.runtime.pipe.module import PipelineModule, LayerSpec, TiedLayerSpec
from deepspeed_tpu.utils import logging as _logging

from deepspeed_tpu import elasticity  # noqa: F401
from deepspeed_tpu import module_inject  # noqa: F401
from deepspeed_tpu import ops  # noqa: F401
from deepspeed_tpu import models  # noqa: F401
from deepspeed_tpu.runtime import zero  # noqa: F401  (deepspeed.zero parity)
from deepspeed_tpu import runtime  # noqa: F401

logger = _logging.logger


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mesh=None,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None,
               rng=None,
               loss_fn=None):
    """Initialize the engine — mirrors ``deepspeed.initialize``
    (reference deepspeed/__init__.py:54).

    Arguments:
        args: optional argparse namespace carrying ``deepspeed_config``.
        model: a flax ``nn.Module`` (or any object with ``.init``/``.apply``)
            or a :class:`~deepspeed_tpu.runtime.pipe.module.PipelineModule`.
        optimizer: optional pre-built optimizer (an optax-style gradient
            transform); overrides the config's optimizer section.
        model_parameters: optional pre-initialized parameter pytree; if
            omitted the engine initializes parameters from ``rng``.
        training_data: optional dataset (anything indexable / iterable).
        lr_scheduler: optional schedule fn ``step -> lr`` overriding config.
        mesh: optional ``jax.sharding.Mesh``; built from config if omitted.
        mpu: model-parallelism "unit" for parity with the reference
            (engine.py:636-641) — an object exposing axis sizes; superseded
            by ``mesh`` on TPU.
        config: path to a JSON config, a dict, or a DeepSpeedConfig.
        config_params: legacy alias for ``config``.
        rng: optional ``jax.random.PRNGKey`` used for parameter init.

    Returns:
        A tuple ``(engine, optimizer, training_dataloader, lr_scheduler)``
        exactly like the reference.
    """
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine

    if config is None:
        config = config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)
    if config is None:
        raise ValueError(
            "DeepSpeed requires --deepspeed_config to specify configuration file")

    # ZeRO-Infinity segment-streamed engine: params + optimizer state
    # larger than HBM, streamed per layer-segment (offload_param
    # stream_segments > 0 — runtime/zero/infinity.py). Peek at the RAW
    # dict — a full DeepSpeedConfig parse here would validate the batch
    # triangle against the default world_size=1 and reject multi-chip
    # configs the engine itself parses correctly with the dp world size.
    if isinstance(config, DeepSpeedConfig):
        segs = getattr(config.zero_config.offload_param,
                       "stream_segments", 0)
    else:
        import json as _json
        raw = config if isinstance(config, dict) else _json.load(
            open(config))
        segs = int(raw.get("zero_optimization", {})
                   .get("offload_param", {}).get("stream_segments", 0))
    if segs:
        unsupported = {
            "optimizer": optimizer, "training_data": training_data,
            "lr_scheduler": lr_scheduler, "mpu": mpu,
            "collate_fn": collate_fn, "loss_fn": loss_fn}
        bad = [k for k, v in unsupported.items() if v is not None]
        if bad:
            raise ValueError(
                "offload_param.stream_segments selects the ZeRO-Infinity "
                f"segment-streamed engine, which does not accept {bad}; "
                "it builds its Adam/AdamW step and tied-LM loss from the "
                "config (runtime/zero/infinity.py)")
        from deepspeed_tpu.runtime.zero.infinity import InfinityEngine
        parsed = config if isinstance(config, DeepSpeedConfig) \
            else DeepSpeedConfig(config)
        engine = InfinityEngine.from_config(
            model, parsed, model_parameters=model_parameters,
            device=mesh.devices.flat[0] if mesh is not None else None)
        return engine, engine.optimizer, engine.training_dataloader, \
            engine.lr_scheduler

    engine_cls = DeepSpeedEngine
    if isinstance(model, PipelineModule):
        engine_cls = PipelineEngine

    engine = engine_cls(args=args,
                        model=model,
                        optimizer=optimizer,
                        model_parameters=model_parameters,
                        training_data=training_data,
                        lr_scheduler=lr_scheduler,
                        mesh=mesh,
                        mpu=mpu,
                        collate_fn=collate_fn,
                        config=config,
                        rng=rng,
                        loss_fn=loss_fn)

    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def add_config_arguments(parser):
    """Add ``--deepspeed``/``--deepspeed_config`` CLI flags — parity with
    reference deepspeed/__init__.py:160-201."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed",
                       default=False,
                       action="store_true",
                       help="Enable DeepSpeed (helper flag to ease transition)")
    group.add_argument("--deepspeed_config",
                       default=None,
                       type=str,
                       help="DeepSpeed json configuration file.")
    group.add_argument("--deepscale",
                       default=False,
                       action="store_true",
                       help="Deprecated alias of --deepspeed")
    group.add_argument("--deepscale_config",
                       default=None,
                       type=str,
                       help="Deprecated alias of --deepspeed_config")
    return parser
