"""deepspeed_tpu — a TPU-native large-model training framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of 2021-era DeepSpeed
(reference: deepspeed/__init__.py:54 `initialize`, :203 `add_config_arguments`):
ZeRO-style partitioned data parallelism expressed as GSPMD sharding over a
`jax.sharding.Mesh`, pipeline + tensor + sequence parallelism over ICI,
host/NVMe offload through a native C++ async-IO tier, Pallas kernels for the
hot ops, and an engine/config/checkpoint stack mirroring the reference's user
API.

Typical use::

    import deepspeed_tpu as dstpu

    engine, _, loader, scheduler = dstpu.initialize(
        config="ds_config.json", model=model, training_data=data)
    for batch in loader:
        loss = engine.train_batch(batch)
"""

from deepspeed_tpu.version import __version__, git_hash, git_branch

from deepspeed_tpu.utils import logging as _logging

logger = _logging.logger

# The public surface resolves LAZILY (PEP 562): importing the bare
# package must not drag in jax — the stdlib-only tooling (the flight
# dump viewer `python -m deepspeed_tpu.telemetry.view`, bench.py's
# --candidate compare path, ci/telemetry_gate.sh) runs on machines
# where jax does not exist, and tests/test_metric_names.py pins that
# with a poisoned-jax import. Everything below behaves exactly like
# the old eager imports: `dstpu.DeepSpeedEngine`, `dstpu.zero`,
# `from deepspeed_tpu import MeshConfig` all still work — the import
# just happens on first attribute access.
_LAZY_ATTRS = {
    "DeepSpeedConfig": ("deepspeed_tpu.config.config", "DeepSpeedConfig"),
    "DeepSpeedEngine": ("deepspeed_tpu.runtime.engine", "DeepSpeedEngine"),
    "add_tuning_arguments": ("deepspeed_tpu.runtime.lr_schedules",
                             "add_tuning_arguments"),
    "MeshConfig": ("deepspeed_tpu.parallel.mesh", "MeshConfig"),
    "make_mesh": ("deepspeed_tpu.parallel.mesh", "make_mesh"),
    "init_distributed": ("deepspeed_tpu.parallel.mesh",
                         "init_distributed"),
    "PipelineModule": ("deepspeed_tpu.runtime.pipe.module",
                       "PipelineModule"),
    "LayerSpec": ("deepspeed_tpu.runtime.pipe.module", "LayerSpec"),
    "TiedLayerSpec": ("deepspeed_tpu.runtime.pipe.module",
                      "TiedLayerSpec"),
    # subpackages the old root bound (eager imports made even
    # `deepspeed_tpu.config` / `.parallel` reachable as attributes)
    "config": ("deepspeed_tpu.config", None),
    "parallel": ("deepspeed_tpu.parallel", None),
    "utils": ("deepspeed_tpu.utils", None),
    "elasticity": ("deepspeed_tpu.elasticity", None),
    "module_inject": ("deepspeed_tpu.module_inject", None),
    "ops": ("deepspeed_tpu.ops", None),
    "models": ("deepspeed_tpu.models", None),
    "zero": ("deepspeed_tpu.runtime.zero", None),
    "runtime": ("deepspeed_tpu.runtime", None),
    "serving": ("deepspeed_tpu.serving", None),
    "telemetry": ("deepspeed_tpu.telemetry", None),
}

from deepspeed_tpu.utils.lazy import lazy_attrs  # noqa: E402

__getattr__, __dir__ = lazy_attrs(__name__, _LAZY_ATTRS)


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mesh=None,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None,
               rng=None,
               loss_fn=None):
    """Initialize the engine — mirrors ``deepspeed.initialize``
    (reference deepspeed/__init__.py:54).

    Arguments:
        args: optional argparse namespace carrying ``deepspeed_config``.
        model: a flax ``nn.Module`` (or any object with ``.init``/``.apply``)
            or a :class:`~deepspeed_tpu.runtime.pipe.module.PipelineModule`.
        optimizer: optional pre-built optimizer (an optax-style gradient
            transform); overrides the config's optimizer section.
        model_parameters: optional pre-initialized parameter pytree; if
            omitted the engine initializes parameters from ``rng``.
        training_data: optional dataset (anything indexable / iterable).
        lr_scheduler: optional schedule fn ``step -> lr`` overriding config.
        mesh: optional ``jax.sharding.Mesh``; built from config if omitted.
        mpu: model-parallelism "unit" for parity with the reference
            (engine.py:636-641) — an object exposing axis sizes; superseded
            by ``mesh`` on TPU.
        config: path to a JSON config, a dict, or a DeepSpeedConfig.
        config_params: legacy alias for ``config``.
        rng: optional ``jax.random.PRNGKey`` used for parameter init.

    Returns:
        A tuple ``(engine, optimizer, training_dataloader, lr_scheduler)``
        exactly like the reference.
    """
    # local imports: global-name lookup inside a function bypasses the
    # module-level lazy __getattr__, and initialize() is where the
    # heavy (jax-importing) machinery genuinely becomes necessary
    from deepspeed_tpu.config.config import DeepSpeedConfig
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.runtime.pipe.module import PipelineModule
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine

    if config is None:
        config = config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)
    if config is None:
        raise ValueError(
            "DeepSpeed requires --deepspeed_config to specify configuration file")

    # ZeRO-Infinity segment-streamed engine: params + optimizer state
    # larger than HBM, streamed per layer-segment (offload_param
    # stream_segments > 0 — runtime/zero/infinity.py). Peek at the RAW
    # dict — a full DeepSpeedConfig parse here would validate the batch
    # triangle against the default world_size=1 and reject multi-chip
    # configs the engine itself parses correctly with the dp world size.
    if isinstance(config, DeepSpeedConfig):
        segs = getattr(config.zero_config.offload_param,
                       "stream_segments", 0)
    else:
        import json as _json
        raw = config if isinstance(config, dict) else _json.load(
            open(config))
        segs = int(raw.get("zero_optimization", {})
                   .get("offload_param", {}).get("stream_segments", 0))
    if segs:
        unsupported = {
            "optimizer": optimizer, "training_data": training_data,
            "lr_scheduler": lr_scheduler, "mpu": mpu,
            "collate_fn": collate_fn, "loss_fn": loss_fn}
        bad = [k for k, v in unsupported.items() if v is not None]
        if bad:
            raise ValueError(
                "offload_param.stream_segments selects the ZeRO-Infinity "
                f"segment-streamed engine, which does not accept {bad}; "
                "it builds its Adam/AdamW step and tied-LM loss from the "
                "config (runtime/zero/infinity.py)")
        from deepspeed_tpu.runtime.zero.infinity import InfinityEngine
        parsed = config if isinstance(config, DeepSpeedConfig) \
            else DeepSpeedConfig(config)
        engine = InfinityEngine.from_config(
            model, parsed, model_parameters=model_parameters,
            device=mesh.devices.flat[0] if mesh is not None else None)
        return engine, engine.optimizer, engine.training_dataloader, \
            engine.lr_scheduler

    engine_cls = DeepSpeedEngine
    if isinstance(model, PipelineModule):
        engine_cls = PipelineEngine

    engine = engine_cls(args=args,
                        model=model,
                        optimizer=optimizer,
                        model_parameters=model_parameters,
                        training_data=training_data,
                        lr_scheduler=lr_scheduler,
                        mesh=mesh,
                        mpu=mpu,
                        collate_fn=collate_fn,
                        config=config,
                        rng=rng,
                        loss_fn=loss_fn)

    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def add_config_arguments(parser):
    """Add ``--deepspeed``/``--deepspeed_config`` CLI flags — parity with
    reference deepspeed/__init__.py:160-201."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed",
                       default=False,
                       action="store_true",
                       help="Enable DeepSpeed (helper flag to ease transition)")
    group.add_argument("--deepspeed_config",
                       default=None,
                       type=str,
                       help="DeepSpeed json configuration file.")
    group.add_argument("--deepscale",
                       default=False,
                       action="store_true",
                       help="Deprecated alias of --deepspeed")
    group.add_argument("--deepscale_config",
                       default=None,
                       type=str,
                       help="Deprecated alias of --deepspeed_config")
    return parser
