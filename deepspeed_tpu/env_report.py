"""Environment report — rebuild of deepspeed/env_report.py:109 (`ds_report`):
prints the install/compatibility matrix for this machine: jax/flax versions,
backend + devices, Pallas availability, native C++ op status.
"""

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
NO = f"{YELLOW}[NO]{END}"


def _try_version(modname):
    try:
        mod = __import__(modname)
        return getattr(mod, "__version__", "unknown")
    except Exception:
        return None


def main():
    print("-" * 60)
    print("DeepSpeed-TPU general environment info:")
    print("-" * 60)
    for mod in ("jax", "jaxlib", "flax", "optax", "numpy"):
        v = _try_version(mod)
        print(f"{mod:<20} {v if v else NO}")

    import deepspeed_tpu
    print(f"{'deepspeed_tpu':<20} {deepspeed_tpu.__version__} "
          f"(git {deepspeed_tpu.git_hash()})")

    print("-" * 60)
    print("Accelerator:")
    try:
        import jax
        devs = jax.devices()
        print(f"backend              {jax.default_backend()}")
        print(f"devices              {len(devs)} x "
              f"{getattr(devs[0], 'device_kind', devs[0].platform)}")
    except Exception as e:
        print(f"devices              {RED}[FAIL]{END} {e}")

    print("-" * 60)
    print("op compatibility:")
    rows = []
    try:
        import jax.experimental.pallas  # noqa: F401
        rows.append(("pallas kernels", OKAY))
    except Exception:
        rows.append(("pallas kernels", NO))
    try:
        from deepspeed_tpu.ops.native import cpu_adam
        rows.append(("cpu_adam (C++ SIMD)", OKAY if cpu_adam.load() else NO))
    except Exception:
        rows.append(("cpu_adam (C++ SIMD)", NO))
    try:
        from deepspeed_tpu.ops.native import aio
        rows.append(("async_io (C++)", OKAY if aio.load() else NO))
    except Exception:
        rows.append(("async_io (C++)", NO))
    for name, status in rows:
        print(f"{name:<20} {status}")
    print("-" * 60)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
