"""Cross-process KV page-handoff transport (ISSUE 17 tentpole).

PR 14's disaggregated split moves :class:`HandoffPacket`\\ s between
roles in-process only. This module is the real fabric: the packet's
``(wire doc, per-pool-component page arrays)`` pair — serializable by
design — crosses OS processes over the PR-10/15 gloo harness, so
prefill-role and decode-role engines can live on DIFFERENT hosts.

Three layers:

**Wire codec.** One self-delimiting frame per message::

    magic "DSHP" | version u16 | header_len u32 | header_crc u32
    | header JSON | component payloads (raw array bytes) ...

The header carries ``kind`` ("packet" / "done" / "nack"), ``src`` /
``dst`` ranks, the JSON wire doc, and per-component
``{dtype, shape, crc}`` metadata. Every byte is crc-checked (header and
each payload independently), the version word makes a field addition
LOUD instead of silently corrupting old packets or serving snapshots
(an unknown version raises :class:`WireFormatError`), and unknown
header keys are ignored so a same-version reader tolerates forward
extensions. Encoding is canonical (sorted keys, minimal separators):
re-encoding a decoded frame reproduces the identical bytes — the
golden-test property and the receiver-side cost model
(:func:`frame_nbytes`) both ride on it. Pure numpy + stdlib: the codec
never touches a jax backend.

**Aligned exchange.** The header leg keeps PR 17's fence discipline:
one fixed-width float allgather of ``[sizes, *metrics]`` every rank
calls at the same loop point (the ``ClusterAggregator`` fence), so the
exchange cannot deadlock; the collectives are SEQUENTIAL with one
device per process, the documented gloo-flake-stable recipe
(tests/test_multiprocess_dist). ISSUE 18 splits the PAYLOAD off that
fence: with ``addressing="targeted"`` (default) the header leg also
carries the per-destination traffic matrix, destination-addressed
frames (``dst >= 0`` — packets, done, nack) then move point-to-point
over :func:`~deepspeed_tpu.utils.distributed
.exchange_host_bytes_targeted`'s deterministic socket schedule, and
only dst<0 traffic rides the padded broadcast allgather — a KV payload
crosses the wire ONCE regardless of world size, where the PR-17
broadcast paid O(world x payload). ``addressing="broadcast"`` keeps
the legacy single-leg allgather; either way the bytes a rank received
WITHOUT being addressed (filtered frames + broadcast padding) land in
``router/handoff_wasted_bytes``, so the per-handoff wire cost is
assertable from counters alone.

**Role nodes.** Rank 0 runs :class:`PrefillNode` — the router lives on
the prefill rank: admission (bounded by ``max_inflight_pages`` fed
from the exchanged metrics), prefill engine steps, packet extraction
(``gather_block_kv``), LPT placement across EVERY decode rank (least
exchanged remaining-decode estimate, per-rank inflight-pages caps —
packets with no eligible rank queue HERE), "done"/"nack" intake,
bounded nack replay from the wire doc. Ranks >= 1 run :class:`DecodeNode`:
decode frames, land packets through
:func:`~deepspeed_tpu.serving.router.deliver_handoff` (the receiving
pool's prefix index re-shares resident full prompt pages — the
content-addressed dedupe survives the process boundary; a delivery
crash at the ``serving_deliver`` fault point unwinds the admission and
nacks), tick the decode engine, ship finished streams back.

:class:`LoopbackFabric` runs the same nodes and the same codec inside
ONE process (frames round-trip through encode/decode in memory, no
collectives) — the fast single-process sibling of the 2-real-process
acceptance tests.
"""

import json
import struct
import time
import zlib
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger

WIRE_MAGIC = b"DSHP"
WIRE_VERSION = 1
_HEAD = struct.Struct("<4sHII")   # magic, version, header_len, header_crc
FRAME_BASE_NBYTES = _HEAD.size

# phase-1 metrics-vector layout: one fp32 slot each, published by every
# rank at every exchange. Senders read the decode rows for backpressure
# (free pages/slots, cumulative absorbed pages); everyone reads rank
# 0's MV_STOP to leave the loop at the SAME aligned exchange.
MV_LEN = 8
MV_ROLE = 0            # 0 = prefill/router rank, 1 = decode rank
MV_FREE_PAGES = 1      # decode pool pages currently allocatable
MV_FREE_SLOTS = 2      # decode slots currently free
MV_ABSORBED_PAGES = 3  # cumulative data pages absorbed (delivered)
MV_DONE = 4            # cumulative requests finished on this rank
MV_STOP = 5            # rank 0 sets 1: drain done, leave after this tick
MV_REMAINING = 6       # est. remaining decode tokens (active + waiting)
#   — the LPT balancing signal the router minimizes over decode ranks
MV_TICK_S = 7          # most recent decode-tick latency on this rank
#   (ISSUE 19) — the per-ROLE decode-latency feed the rank-0 SLO plane
#   windows into slo/decode/* quantiles + burn rate; 0 = no tick yet


class WireFormatError(ValueError):
    """A frame failed validation: bad magic, unknown version, crc
    mismatch, or truncation. Deliberately LOUD — a silently-tolerated
    corrupt packet would scatter garbage KV into a decode pool."""


def _jsonable(o):
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)  # sync-ok: numpy scalar, already host
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not wire-serializable: {type(o)!r}")


def encode_frame(kind: str, doc: Optional[dict], comps=(),
                 src: int = 0, dst: int = -1) -> bytes:
    """One message → canonical frame bytes. ``comps`` are array-likes
    (a packet's per-pool-component page gathers); ``dst=-1``
    broadcasts. Canonical JSON (sorted keys, minimal separators) makes
    encoding deterministic: encode(decode(b)) == b."""
    # the serialization point: gathered pages must leave the device to
    # cross the process boundary as bytes
    arrs = [np.ascontiguousarray(np.asarray(c))  # sync-ok: wire encode
            for c in comps]
    meta = [{"dtype": a.dtype.str, "shape": list(a.shape),
             "crc": zlib.crc32(a.tobytes()) & 0xFFFFFFFF}
            for a in arrs]
    header = json.dumps(
        {"v": WIRE_VERSION, "kind": str(kind), "src": int(src),
         "dst": int(dst), "doc": doc, "comps": meta},
        sort_keys=True, separators=(",", ":"),
        default=_jsonable).encode()
    out = [_HEAD.pack(WIRE_MAGIC, WIRE_VERSION, len(header),
                      zlib.crc32(header) & 0xFFFFFFFF), header]
    out.extend(a.tobytes() for a in arrs)
    return b"".join(out)


def decode_frame(buf, offset: int = 0):
    """Decode one frame at ``offset``; returns ``(frame, next_offset)``
    where frame is ``{"kind", "src", "dst", "doc", "comps"}`` with
    comps a tuple of numpy arrays. Raises :class:`WireFormatError` on
    any validation failure."""
    view = memoryview(buf)
    if len(view) - offset < _HEAD.size:
        raise WireFormatError(
            f"truncated frame: {len(view) - offset} bytes < "
            f"{_HEAD.size}-byte fixed header")
    magic, ver, hlen, hcrc = _HEAD.unpack_from(view, offset)
    if magic != WIRE_MAGIC:
        raise WireFormatError(f"bad magic {magic!r} (want {WIRE_MAGIC!r})")
    if ver != WIRE_VERSION:
        # the versioned-header contract: a future field addition bumps
        # WIRE_VERSION, and an old reader REFUSES instead of
        # misparsing old packets/snapshots into silent corruption
        raise WireFormatError(
            f"wire version {ver} not supported (this codec speaks "
            f"{WIRE_VERSION}); refusing to guess at the layout")
    offset += _HEAD.size
    header = bytes(view[offset:offset + hlen])
    if len(header) != hlen:
        raise WireFormatError("truncated frame header")
    if zlib.crc32(header) & 0xFFFFFFFF != hcrc:
        raise WireFormatError("header crc mismatch")
    h = json.loads(header.decode())
    offset += hlen
    comps = []
    for m in h.get("comps", ()):
        dt = np.dtype(m["dtype"])
        n = int(np.prod(m["shape"], dtype=np.int64)) * dt.itemsize
        raw = bytes(view[offset:offset + n])
        if len(raw) != n:
            raise WireFormatError("truncated component payload")
        if zlib.crc32(raw) & 0xFFFFFFFF != int(m["crc"]):
            raise WireFormatError("component payload crc mismatch")
        comps.append(np.frombuffer(raw, dt).reshape(m["shape"]))
        offset += n
    return {"kind": h["kind"], "src": int(h.get("src", 0)),
            "dst": int(h.get("dst", -1)), "doc": h.get("doc"),
            "comps": tuple(comps)}, offset


def decode_frames(buf) -> List[dict]:
    """All frames in a buffer (frames are self-delimiting)."""
    out, offset = [], 0
    while offset < len(buf):
        frame, offset = decode_frame(buf, offset)
        out.append(frame)
    return out


def frame_nbytes(frame: dict) -> int:
    """Receiver-side cost model: the exact wire size of a decoded
    frame, recomputed from its CONTENT (canonical encoding makes this
    equal to the bytes that actually traveled) — what the
    ``router/handoff_bytes_recv`` counter observes, so the acceptance
    test can pin counters against packet sizes independently of the
    sender's arithmetic."""
    return len(encode_frame(frame["kind"], frame["doc"], frame["comps"],
                            frame["src"], frame["dst"]))


def payload_nbytes(comps) -> int:
    """Raw KV payload bytes of a component tuple (frame size minus
    header: ``n_data_pages * cache.page_nbytes`` for a packet)."""
    return sum(int(np.asarray(c).nbytes) for c in comps)  # sync-ok: nbytes only


def encode_packet(packet, src: int = 0, dst: int = -1) -> bytes:
    """A :class:`~deepspeed_tpu.serving.router.HandoffPacket` → one
    "packet" frame. The live ``req`` object does NOT travel — the
    receiver rebuilds it from the wire doc
    (``elastic.resume_request``), exactly the ``req=None`` path
    ``deliver_handoff`` already speaks."""
    return encode_frame("packet", packet.doc, packet.kv, src, dst)


def packet_from_frame(frame: dict):
    """The receiving half: a decoded "packet" frame → HandoffPacket
    with ``req=None`` (rebuild-from-doc delivery)."""
    from deepspeed_tpu.serving.router import HandoffPacket
    return HandoffPacket(dict(frame["doc"]), frame["comps"], None)


# ----------------------------------------------------------- endpoints

class LoopbackFabric:
    """Single-process fabric: endpoints exchange ENCODED frames through
    an in-memory inbox, so the codec and both node state machines run
    for real with no collectives — the fast sibling of the
    N-real-process path. Metrics rows update at each endpoint's
    exchange (last-written wins, like the aligned gather's snapshot).
    ``addressing="targeted"`` (default) routes each frame to its
    destination only, mirroring the socket payload leg;
    ``addressing="broadcast"`` copies every frame to every rank and
    lets receivers filter — the PR-17 wire shape, so the
    ``handoff_wasted_bytes`` accounting is testable without spawning
    processes."""

    def __init__(self, world: int, addressing: str = "targeted"):
        assert world >= 2, world
        assert addressing in ("targeted", "broadcast"), addressing
        self.world = int(world)
        self.addressing = addressing
        self._inbox = [deque() for _ in range(self.world)]
        self._metrics = np.zeros((self.world, MV_LEN), np.float32)

    def endpoint(self, rank: int) -> "LoopbackEndpoint":
        return LoopbackEndpoint(self, rank)


class LoopbackEndpoint:
    def __init__(self, fabric: LoopbackFabric, rank: int):
        assert 0 <= rank < fabric.world
        self.fabric = fabric
        self.rank = int(rank)
        self.world = fabric.world
        self._wasted = 0

    def take_wasted(self) -> int:
        """Bytes this endpoint received without being addressed since
        the last call — the ``router/handoff_wasted_bytes`` feed."""
        w, self._wasted = self._wasted, 0
        return w

    def exchange(self, out, metrics):
        fab = self.fabric
        fab._metrics[self.rank] = np.asarray(  # sync-ok: host metrics vec
            metrics, np.float32).reshape(MV_LEN)
        for dst, buf in out:
            for frame in decode_frames(buf):
                if fab.addressing == "broadcast" or dst < 0:
                    dsts = range(fab.world)
                else:
                    dsts = (int(dst),)
                for r in dsts:
                    if r != self.rank:
                        fab._inbox[r].append(frame)
        inbox = fab._inbox[self.rank]
        frames = []
        for _ in range(len(inbox)):
            frame = inbox.popleft()
            if frame["dst"] < 0 or frame["dst"] == self.rank:
                frames.append(frame)
            else:
                self._wasted += frame_nbytes(frame)
        return frames, fab._metrics.copy()


class ProcessEndpoint:
    """The real thing: frames + metrics cross processes through the
    aligned exchange (see module docstring). Every rank MUST call
    :meth:`exchange` at the same loop point every tick — the fence
    discipline is what makes the fabric deadlock-free. ``out`` is a
    list of ``(dst, frame bytes)``: with ``addressing="targeted"``
    dst>=0 frames ride the point-to-point payload leg (lazy
    :class:`~deepspeed_tpu.utils.distributed.PeerFabric`, created at
    the first exchange — an aligned point every rank reaches
    together); ``addressing="broadcast"`` is the PR-17 legacy
    single-allgather shape."""

    def __init__(self, addressing: str = "targeted",
                 payload_timeout_s: float = 60.0):
        import jax
        assert addressing in ("targeted", "broadcast"), addressing
        self.rank = int(jax.process_index())
        self.world = int(jax.process_count())
        self.addressing = addressing
        self.payload_timeout_s = float(payload_timeout_s)  # sync-ok: cfg
        self._fabric = None
        self._wasted = 0

    def take_wasted(self) -> int:
        w, self._wasted = self._wasted, 0
        return w

    def fabric_health(self) -> dict:
        """Targeted-fabric liveness for /healthz (ISSUE 19 satellite):
        the :class:`PeerFabric`'s per-peer connected flags +
        last-payload ages. Before the fabric's lazy construction (or
        under broadcast addressing, which has no point-to-point leg)
        the doc says so instead of faking peers."""
        if self._fabric is None:
            return {"fabric": {"built": False,
                               "addressing": self.addressing}}
        return {"fabric": dict(self._fabric.liveness(), built=True,
                               addressing=self.addressing)}

    def _filter(self, bufs, me, pad):
        """Broadcast-leg intake: keep frames addressed here (or to
        all), count everything else — mis-addressed frames and the
        padding peers forced onto this rank — as wasted wire bytes."""
        frames = []
        for r, buf in enumerate(bufs):
            if r == me:
                continue
            self._wasted += max(pad - len(buf), 0)
            for frame in decode_frames(buf):
                if frame["dst"] < 0 or frame["dst"] == me:
                    frames.append(frame)
                else:
                    self._wasted += frame_nbytes(frame)
        return frames

    def exchange(self, out, metrics):
        meta = np.asarray(metrics, np.float32).reshape(
            MV_LEN)   # sync-ok: metrics vector is host-built numpy
        if self.addressing == "broadcast":
            from deepspeed_tpu.utils.distributed import \
                allgather_host_bytes
            bufs, mat, me = allgather_host_bytes(
                b"".join(buf for _dst, buf in out),  # sync-ok: wire hop
                meta=meta)
            pad = max((len(b) for b in bufs), default=0)
            return self._filter(bufs, me, pad), mat
        from deepspeed_tpu.utils.distributed import (
            PeerFabric, exchange_host_bytes_targeted)
        if self._fabric is None:
            # collective construction (listener-address allgather) at
            # the first exchange — a point every rank reaches together
            self._fabric = PeerFabric(timeout_s=self.payload_timeout_s)
        bcast, by_dst = [], {}
        for dst, buf in out:
            if dst < 0:
                bcast.append(buf)
            else:
                assert dst != self.rank, "frame addressed to self"
                by_dst[int(dst)] = by_dst.get(int(dst), b"") + buf
        bufs, incoming, mat, me, pad = exchange_host_bytes_targeted(
            b"".join(bcast), by_dst, meta=meta,  # sync-ok: wire hop
            fabric=self._fabric)
        frames = self._filter(bufs, me, pad)
        for src in sorted(incoming):
            frames.extend(decode_frames(incoming[src]))
        return frames, mat


# ---------------------------------------------------------- role nodes

class DecodeNode:
    """Decode-role rank: land packets, tick the engine, ship "done"
    streams back to the router rank. ``on_tick(node)`` runs once per
    exchange loop (heartbeat files, fault hooks); ``on_absorb(node)``
    after each successful delivery (the SIGKILL-mid-stream fault test
    arms its kill there)."""

    def __init__(self, engine, endpoint, registry=None, recorder=None,
                 decode_ticks: int = 4, on_tick=None, on_absorb=None):
        from deepspeed_tpu.telemetry.recorder import default_recorder
        from deepspeed_tpu.telemetry.registry import MetricsRegistry
        assert engine.role in ("decode", "both"), engine.role
        self.engine = engine
        self.endpoint = endpoint
        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        self.recorder = recorder if recorder is not None \
            else default_recorder()
        self.decode_ticks = int(decode_ticks)
        self.on_tick = on_tick
        self.on_absorb = on_absorb
        self._waiting: deque = deque()   # packets waiting on a slot
        self._outbox: List = []          # (dst, frame bytes) pairs
        self.absorbed_pages = 0
        self.done_count = 0
        self.stats = {"delivered": 0, "nacked": 0, "bytes_recv": 0,
                      "wasted_bytes": 0, "decode_busy_s": 0.0,
                      "slot_busy_ticks": 0, "slot_cap_ticks": 0}

    def _vec(self):
        cb = self.engine
        v = np.zeros(MV_LEN, np.float32)
        v[MV_ROLE] = 1.0
        v[MV_FREE_PAGES] = cb.cache.available_pages
        v[MV_FREE_SLOTS] = sum(not s.active for s in cb.slots)
        v[MV_ABSORBED_PAGES] = self.absorbed_pages
        v[MV_DONE] = self.done_count
        # remaining-decode estimate: tokens still owed by active slots
        # plus everything parked in the waiting queue — what the
        # router's LPT placement minimizes across decode ranks
        rem = 0
        for s in cb.slots:
            if s.active and s.request is not None:
                rem += max(int(s.request.max_new_tokens)
                           - len(s.request.generated), 0)
        for frame in self._waiting:
            doc = frame["doc"]
            rem += max(int(doc["max_new_tokens"])
                       - len(doc["generated"]), 0)
        v[MV_REMAINING] = rem
        # the SLO plane's decode-latency feed (ISSUE 19): the engine's
        # most recent tick latency, already a host scalar (the token
        # readback fenced it) — peek, never create, so an idle rank
        # publishes 0 instead of seeding a phantom histogram
        tick_s = cb.metrics.peek_histogram_last("serving/tick_latency_s")
        v[MV_TICK_S] = tick_s or 0.0
        return v

    def _note_wasted(self):
        take = getattr(self.endpoint, "take_wasted", None)
        if take is None:
            return
        wasted = int(take())
        if wasted:
            self.stats["wasted_bytes"] += wasted
            self.metrics.counter("router/handoff_wasted_bytes").inc(
                wasted)

    def _try_deliver(self, frame, out_bufs) -> bool:
        """True when the packet landed or was nacked (consumed);
        False = no slot/pages free yet, caller keeps it waiting."""
        from deepspeed_tpu.runtime.elastic import faults
        from deepspeed_tpu.serving.router import deliver_handoff
        packet = packet_from_frame(frame)
        try:
            slot = deliver_handoff(self.engine, packet,
                                   dedupe=self.engine.prefix_cache)
        except faults.SimulatedCrash as e:
            # admission already unwound inside deliver_handoff; the
            # gathered bytes are suspect — nack with the wire doc so
            # the router replays from the committed stream, bounded
            self.stats["nacked"] += 1
            out_bufs.append((frame["src"], encode_frame(
                "nack", dict(packet.doc, error=str(e)),
                src=self.endpoint.rank, dst=frame["src"])))
            return True
        if slot is None:
            return False
        self.stats["delivered"] += 1
        self.absorbed_pages += int(packet.doc["n_data_pages"])
        if self.on_absorb is not None:
            self.on_absorb(self)
        return True

    def tick(self):
        """One exchange / deliver / decode iteration; returns the
        exchanged metrics matrix (callers check ``mat[0, MV_STOP]``).
        :meth:`run` loops this, and the loopback tests drive it
        directly — same code path either way."""
        t_coll = time.monotonic()
        frames, mat = self.endpoint.exchange(self._outbox, self._vec())
        self.engine.metrics.histogram(
            "serving/transport_collective_s").observe(
            time.monotonic() - t_coll)
        self._outbox = []
        self._note_wasted()
        for frame in frames:
            if frame["kind"] != "packet":
                continue
            nb = frame_nbytes(frame)
            self.stats["bytes_recv"] += nb
            self.metrics.counter("router/handoff_bytes_recv").inc(nb)
            self._waiting.append(frame)
        # deliver in arrival order; stop at the first packet the
        # pool cannot take yet (later ones would jump the queue)
        while self._waiting:
            if not self._try_deliver(self._waiting[0], self._outbox):
                break
            self._waiting.popleft()
        cb = self.engine
        # busy time is THIS THREAD's CPU seconds, not wall clock and
        # not process CPU: on the shared-core harness several decode
        # ranks time-slice one core, so a wall clock bills each rank
        # for slices it spent descheduled, and process CPU bills the
        # XLA pool threads' post-collective spin-wait (which grows
        # with wall time, i.e. with world size). The scheduler thread
        # drives every decode step, so its own CPU measures the
        # per-rank capacity a one-host-per-rank deployment would see
        t_busy = time.thread_time()
        stepped = False
        for _tick in range(self.decode_ticks):
            active = sum(s.active for s in cb.slots)
            self.stats["slot_busy_ticks"] += active
            if not active:
                break
            stepped = True
            for req in cb.step():
                self.done_count += 1
                self._outbox.append((0, encode_frame(
                    "done",
                    {"rid": req.rid,
                     "tokens": [int(t) for t in req.tokens()],
                     "finish_reason": req.finish_reason,
                     "trace_id": getattr(req, "trace_id", None),
                     "span_id": getattr(req, "span_id", None),
                     "generated": len(req.generated)},
                    src=self.endpoint.rank, dst=0)))
        # slot-utilization denominator counts the FULL decode budget of
        # the tick (idle ticks show as low utilization — the bench's
        # honesty signal), busy time only what actually stepped
        self.stats["slot_cap_ticks"] += len(cb.slots) * self.decode_ticks
        if stepped:
            self.stats["decode_busy_s"] += time.thread_time() - t_busy
        if self.on_tick is not None:
            self.on_tick(self)
        return mat

    def run(self, max_ticks: int = 200000) -> dict:
        """Exchange/deliver/tick until rank 0 raises MV_STOP (seen by
        every rank at the same aligned exchange). Returns stats."""
        for _ in range(max_ticks):
            mat = self.tick()
            if mat[0, MV_STOP]:
                break
        return dict(self.stats, absorbed_pages=self.absorbed_pages,
                    done=self.done_count)


class PrefillNode:
    """Prefill-role rank 0 — the router lives here: admission gated by
    ``max_inflight_pages`` (extracted-but-unabsorbed KV, estimated
    from cumulative sent pages minus the decode ranks' exchanged
    ``MV_ABSORBED_PAGES``), prefill steps, extract/encode/send, and
    "done"/"nack" intake with bounded replay from the wire doc —
    the same recovery semantics as
    :meth:`DisaggRouter._requeue_lost_packet`."""

    def __init__(self, engines, endpoint, registry=None, recorder=None,
                 max_inflight_pages: Optional[int] = None,
                 max_inflight_pages_per_rank: Optional[int] = None,
                 max_handoff_retries: int = 3, on_tick=None,
                 on_done=None):
        from deepspeed_tpu.telemetry.recorder import default_recorder
        from deepspeed_tpu.telemetry.registry import MetricsRegistry
        assert engines, "need at least one prefill-role engine"
        for cb in engines:
            assert cb.role == "prefill", cb.role
        self.engines = list(engines)
        self.endpoint = endpoint
        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        self.recorder = recorder if recorder is not None \
            else default_recorder()
        self.max_handoff_retries = int(max_handoff_retries)
        self.max_inflight_pages = None if max_inflight_pages is None \
            else int(max_inflight_pages)
        self.on_tick = on_tick
        self.on_done = on_done
        self.decode_ranks = [r for r in range(endpoint.world)
                             if r != endpoint.rank]
        # per-rank send-time backpressure: default = the aggregate
        # bound split evenly across decode ranks, so one slow rank
        # cannot monopolize the whole inflight budget
        if max_inflight_pages_per_rank is not None:
            self.max_inflight_pages_per_rank = int(
                max_inflight_pages_per_rank)
        elif self.max_inflight_pages is not None:
            self.max_inflight_pages_per_rank = max(
                self.max_inflight_pages // max(len(self.decode_ranks), 1),
                1)
        else:
            self.max_inflight_pages_per_rank = None
        self.queue: deque = deque()
        self._packets: deque = deque()     # extracted, not yet sent
        self._attempts: Dict[Any, int] = {}
        self._sent_pages = {r: 0 for r in self.decode_ranks}
        self._submitted = 0
        self._block_latched = False
        self._rank_blocked = {r: False for r in self.decode_ranks}
        self._host_rng = np.random.RandomState(0)
        self.done: Dict[Any, dict] = {}    # rid -> done doc
        self.lost: Dict[Any, dict] = {}
        self.stats = {"routed": 0, "handoffs": 0, "handoff_requeues": 0,
                      "decode_blocked": 0, "lost": 0, "bytes_sent": 0,
                      "wasted_bytes": 0, "slot_busy_ticks": 0,
                      "slot_cap_ticks": 0}
        # ISSUE 19: the rank-0 SLO plane (telemetry/slo.py), attached
        # by build_transport_node when monitor.slo asks for it. Fed +
        # exported once per aligned exchange — prefill-role TTFT
        # segments from the local registries, decode-role tick latency
        # from every decode rank's MV_TICK_S slot
        self.slo = None

    # ------------------------------------------------------------ intake

    def submit(self, request) -> None:
        from deepspeed_tpu.serving.engine import ensure_trace_id
        ensure_trace_id(request)
        if request.temperature and request.temperature > 0 \
                and getattr(request, "sample_key", None) is None:
            request.sample_key = int(
                self._host_rng.randint(0, 2 ** 31 - 1))  # sync-ok: host
        if getattr(request, "_t_arrived", None) is None:
            request._t_arrived = time.monotonic()
        self._attempts.setdefault(request.rid, 0)
        self._submitted += 1
        self.queue.append(request)
        self.metrics.gauge("router/queue_depth").set(len(self.queue))

    # -------------------------------------------------------- accounting

    def _inflight_pages(self, mat) -> int:
        """Pages committed to the handoff pipeline but not absorbed by
        a decode pool: on-the-wire sends minus the exchanged absorbed
        counters, extracted-unsent packets, and everything routed into
        a prefill engine (those become packets next sweep)."""
        n = sum(self._sent_pages[r]
                - int(mat[r, MV_ABSORBED_PAGES])
                for r in self.decode_ranks)
        n += sum(int(p.doc["n_data_pages"]) for p in self._packets)
        for pcb in self.engines:
            for r in pcb.queue:
                n += pcb.cache.pages_needed(
                    int(np.asarray(r.prompt).shape[0]))  # sync-ok: host
            for s in pcb.slots:
                if s.active:
                    n += pcb.cache.pages_needed(max(s.pos, 1))
        return n

    def _route_admissions(self, mat) -> None:
        while self.queue:
            req = self.queue[0]
            if self.max_inflight_pages is not None:
                need = self.engines[0].cache.pages_needed(
                    int(np.asarray(req.prompt).shape[0]))  # sync-ok
                inflight = self._inflight_pages(mat)
                if inflight + need > self.max_inflight_pages:
                    if not self._block_latched:
                        self._block_latched = True
                        self.stats["decode_blocked"] += 1
                        self.metrics.counter(
                            "router/decode_blocked").inc()
                        self.recorder.record(
                            "router_block", rid=req.rid,
                            trace=req.trace_id, need_pages=need,
                            inflight_pages=inflight,
                            queue_depth=len(self.queue))
                    break
            self._block_latched = False
            self.queue.popleft()
            loads = [len(cb.queue) + sum(s.active for s in cb.slots)
                     for cb in self.engines]
            pidx = int(np.argmin(loads))   # sync-ok: host scores
            self.stats["routed"] += 1
            self.metrics.counter("router/slo_routed").inc()
            self.recorder.record(
                "router_route", rid=req.rid, trace=req.trace_id,
                engine=self.engines[pidx].replica_id, reason="slo")
            self.engines[pidx].submit(req)
        self.metrics.gauge("router/queue_depth").set(len(self.queue))

    # ----------------------------------------------------------- handoff

    def _requeue(self, doc, error) -> None:
        from deepspeed_tpu.serving import elastic
        rid = doc["rid"]
        self.stats["handoff_requeues"] += 1
        self.metrics.counter("router/handoff_requeues").inc()
        self._attempts[rid] = self._attempts.get(rid, 0) + 1
        if self._attempts[rid] > self.max_handoff_retries:
            self.stats["lost"] += 1
            self.lost[rid] = doc
            self.recorder.record(
                "serving_requeue", rid=rid, trace=doc.get("trace_id"),
                outcome="dropped", attempts=self._attempts[rid])
            logger.warning(f"request {rid!r} dropped after "
                           f"{self._attempts[rid] - 1} handoff retries")
            return
        replay = elastic.resume_request(doc)
        self.recorder.record(
            "serving_requeue", rid=rid, trace=doc.get("trace_id"),
            outcome="scheduled", attempts=self._attempts[rid],
            committed=len(doc["generated"]))
        logger.warning(f"cross-process handoff of {rid!r} failed "
                       f"({error}); replaying from the committed stream")
        self.queue.appendleft(replay)

    def _sweep_and_send(self, mat, out_bufs) -> None:
        from deepspeed_tpu.runtime.elastic import faults
        from deepspeed_tpu.serving.router import extract_handoff
        for pcb in self.engines:
            for slot_id, slot in enumerate(pcb.slots):
                if not slot.active:
                    continue
                packet = extract_handoff(pcb, slot_id)
                try:
                    faults.fire("serving_handoff", rid=packet.rid)
                except faults.SimulatedCrash as e:
                    self._requeue(packet.doc, e)
                    continue
                self._packets.append(packet)
        # LPT placement (ISSUE 18): longest-remaining packet first onto
        # the decode rank with the least estimated remaining work (the
        # exchanged MV_REMAINING plus its sent-but-unacknowledged pages
        # as the in-flight lag proxy), subject to the per-rank
        # inflight-pages cap. A rank with no free slot still accepts a
        # frame into its waiting queue (the pages stay counted as
        # inflight here until MV_ABSORBED_PAGES acknowledges them); a
        # packet NO rank can take stays queued HERE — per-rank
        # backpressure at the router — and each refusing rank latches
        # one decode_blocked per episode.
        def _rem(p):
            return max(int(p.doc["max_new_tokens"])
                       - len(p.doc["generated"]), 0)

        unabsorbed = {r: self._sent_pages[r]
                      - int(mat[r, MV_ABSORBED_PAGES])
                      for r in self.decode_ranks}
        load = {r: float(mat[r, MV_REMAINING]) + unabsorbed[r]
                for r in self.decode_ranks}   # sync-ok: mat is the
        #                                       host metrics matrix
        cap = self.max_inflight_pages_per_rank
        held: deque = deque()
        for packet in sorted(self._packets, key=_rem, reverse=True):
            need = int(packet.doc["n_data_pages"])
            if cap is None:
                eligible = self.decode_ranks
            else:
                # an oversized packet (need > cap) may still go to a
                # fully-acknowledged rank: the cap is backpressure,
                # not a validator, and holding it forever would wedge
                eligible = [r for r in self.decode_ranks
                            if unabsorbed[r] + need <= cap
                            or unabsorbed[r] == 0]
            if not eligible:
                for r in self.decode_ranks:
                    self._latch_rank_block(r, packet, unabsorbed[r])
                held.append(packet)
                continue
            dst = min(eligible, key=lambda r: (
                load[r], -float(mat[r, MV_FREE_PAGES]),
                r))   # sync-ok: host metrics matrix, no device read
            self._rank_blocked[dst] = False   # headroom proven: re-arm
            # ISSUE 19: the encode leg gets its own span, child of the
            # handoff span, SHIPPED IN THE DOC before encoding — the
            # receiving rank's handoff_in parents onto it, so the
            # cross-process hop is one connected edge in the merged tree
            from deepspeed_tpu.telemetry.spans import new_span_id
            enc_span = new_span_id()
            packet.doc["encode_span"] = enc_span
            t_enc = time.monotonic()
            buf = encode_frame("packet", packet.doc, packet.kv,
                               src=self.endpoint.rank, dst=dst)
            enc_s = time.monotonic() - t_enc
            self.engines[0].metrics.histogram(
                "serving/transport_encode_s").observe(enc_s)
            self.recorder.record(
                "transport_encode", rid=packet.doc["rid"],
                trace=packet.doc.get("trace_id"), dst=dst,
                nbytes=len(buf), dur_s=enc_s, span_id=enc_span,
                parent_span=packet.doc.get("handoff_span"))
            out_bufs.append((dst, buf))
            self._sent_pages[dst] += need
            unabsorbed[dst] += need
            load[dst] += _rem(packet)
            self.stats["handoffs"] += 1
            self.stats["bytes_sent"] += len(buf)
            self.metrics.counter("router/handoffs").inc()
            self.metrics.counter("router/handoff_bytes_sent").inc(
                len(buf))
        self._packets = held
        self.metrics.gauge("router/inflight_pages").set(
            self._inflight_pages(mat))

    def _latch_rank_block(self, rank, packet, unabsorbed) -> None:
        """One decode_blocked per REFUSING RANK per episode (the
        admission latch's per-rank sibling): a held packet re-checks
        every sweep, and counting each re-check would flood the
        bounded ring at tick rate under sustained pressure."""
        if self._rank_blocked[rank]:
            return
        self._rank_blocked[rank] = True
        self.stats["decode_blocked"] += 1
        self.metrics.counter("router/decode_blocked").inc()
        self.recorder.record(
            "router_block", rid=packet.doc["rid"],
            trace=packet.doc.get("trace_id"), rank=rank,
            need_pages=int(packet.doc["n_data_pages"]),
            inflight_pages=int(unabsorbed),
            queue_depth=len(self._packets))

    def _note_wasted(self) -> None:
        take = getattr(self.endpoint, "take_wasted", None)
        if take is None:
            return
        wasted = int(take())
        if wasted:
            self.stats["wasted_bytes"] += wasted
            self.metrics.counter("router/handoff_wasted_bytes").inc(
                wasted)

    # the prefill-role window sources: (slo metric, registry histogram)
    _SLO_FEEDS = (
        ("ttft_s", "serving/ttft_s"),
        ("queue_wait_s", "serving/ttft_queue_wait_s"),
        ("transport_s", "serving/transport_encode_s"),
        ("transport_s", "serving/transport_collective_s"),
    )

    def _feed_slo(self, mat) -> None:
        """One SLO-plane update per aligned exchange (ISSUE 19): new
        prefill-side histogram tails under role ``prefill``, each
        decode rank's exchanged tick latency under role ``decode``
        (a per-exchange SAMPLE of that rank's current latency — the
        cadence every other backpressure signal already rides), then
        re-export the ``slo/*`` gauges. Host floats only."""
        plane = self.slo
        if plane is None:
            return
        for cb in self.engines:
            reg = cb.metrics
            for metric, src in self._SLO_FEEDS:
                n = reg.peek_histogram_count(src)
                if n:
                    plane.feed_counted(
                        "prefill", metric,
                        reg.peek_histogram_values(src), n,
                        source=f"{cb.replica_id}:{src}")
        for r in self.decode_ranks:
            if mat[r, MV_ROLE] and mat[r, MV_TICK_S] > 0:
                plane.observe("decode", "tick_s",
                              float(mat[r, MV_TICK_S]))   # sync-ok: host metrics matrix
        plane.export(self.metrics)

    def _finish(self, doc) -> None:
        from deepspeed_tpu.telemetry.spans import new_span_id
        self.done[doc["rid"]] = doc
        # the router rank is the completion authority: its ring closes
        # every trace even when a decode rank's ring died with it —
        # the close parents straight onto the request ROOT (doc-borne),
        # never onto a decode-rank span that may not have been dumped
        self.recorder.record(
            "finish", rid=doc["rid"], trace=doc.get("trace_id"),
            reason=doc.get("finish_reason"),
            generated=doc.get("generated"),
            span_id=new_span_id(),
            parent_span=doc.get("span_id"))
        if self.on_done is not None:
            self.on_done(doc)

    # -------------------------------------------------------------- loop

    def serve(self, requests, max_ticks: int = 200000) -> Dict[Any, dict]:
        """Serve every request to completion (or bounded loss) across
        the fabric; returns ``{rid: done doc}`` with the FULL token
        stream per request. Finishes that never left the prefill rank
        (max_new_tokens == 1 / instant EOS) complete locally."""
        for r in requests:
            self.submit(r)
        out_bufs: List = []   # (dst, frame bytes) pairs
        mat = np.zeros((self.endpoint.world, MV_LEN), np.float32)
        for _ in range(max_ticks):
            self._route_admissions(mat)
            for pcb in self.engines:
                for req in pcb.step():
                    self._finish({
                        "rid": req.rid,
                        "tokens": [int(t) for t in req.tokens()],
                        "finish_reason": req.finish_reason,
                        "trace_id": getattr(req, "trace_id", None),
                        "span_id": getattr(req, "span_id", None),
                        "generated": len(req.generated)})
                # occupancy is sampled AFTER the step and BEFORE the
                # sweep extracts the active slots into packets — the
                # only point in the tick where prefill work is visible
                self.stats["slot_busy_ticks"] += sum(
                    s.active for s in pcb.slots)
                self.stats["slot_cap_ticks"] += len(pcb.slots)
            self._sweep_and_send(mat, out_bufs)
            t_coll = time.monotonic()
            frames, mat = self.endpoint.exchange(out_bufs, self._vec(0.0))
            self.engines[0].metrics.histogram(
                "serving/transport_collective_s").observe(
                time.monotonic() - t_coll)
            self._feed_slo(mat)
            self._note_wasted()
            out_bufs = []
            for frame in frames:
                if frame["kind"] == "done":
                    self._finish(frame["doc"])
                elif frame["kind"] == "nack":
                    self._requeue(frame["doc"],
                                  frame["doc"].get("error", "nack"))
            if self.on_tick is not None:
                self.on_tick(self)
            if len(self.done) + len(self.lost) >= self._submitted \
                    and not self.queue and not self._packets:
                break
        # one final aligned exchange raises MV_STOP: every decode rank
        # sees it at the same tick and leaves its loop — no straggler
        # ever blocks alone inside a collective
        self.endpoint.exchange([], self._vec(1.0))
        return dict(self.done)

    def _vec(self, stop: float):
        v = np.zeros(MV_LEN, np.float32)
        v[MV_ROLE] = 0.0
        v[MV_STOP] = stop
        v[MV_DONE] = len(self.done)
        return v
