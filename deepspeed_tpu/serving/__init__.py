"""Continuous-batching serving engine with a paged KV cache.

Entry point for both supported model families::

    import deepspeed_tpu.serving as serving

    engine = serving.build_engine(
        family="gpt2", model_config=gpt2_cfg, params=params,
        config={"serving": {"slots": 8, "page_size": 128,
                            "kv_cache_bits": 8}})
    results = engine.serve([serving.Request(0, prompt_ids,
                                            max_new_tokens=64)])

``config`` is the standard DeepSpeed-style dict/json whose ``serving``
block (docs/CONFIG.md) sizes the engine; keyword overrides win over the
block. See docs/serving.md for the scheduler model and tuning notes.
"""

from deepspeed_tpu.serving.paged_cache import (   # noqa: F401
    PagedCacheSpec, PagedKVCache, TRASH_BLOCK)
from deepspeed_tpu.serving.engine import (        # noqa: F401
    ContinuousBatcher, Request)
from deepspeed_tpu.serving.adapters import (      # noqa: F401
    GPT2ServingAdapter, LlamaServingAdapter)
from deepspeed_tpu.serving.elastic import (       # noqa: F401
    ElasticServingController, capture_state, load_latest_serving,
    load_serving_snapshot, restore_serving, snapshot_serving)
from deepspeed_tpu.serving.replica_pool import ReplicaPool  # noqa: F401
from deepspeed_tpu.serving.router import (        # noqa: F401
    DisaggRouter, HandoffPacket, deliver_handoff, extract_handoff)


def _param_dict(config):
    """Parse a config (dict or json path) ONCE into a param dict; a
    dict passes through cheaply, so callers can pre-parse and thread
    the result to avoid re-reading a file."""
    from deepspeed_tpu.config.config import DeepSpeedConfig
    if config is None:
        return {}
    return DeepSpeedConfig.load_param_dict(config)


def _serving_section(config):
    from deepspeed_tpu.config.config import ServingConfig
    return ServingConfig(_param_dict(config))


def cache_spec_from_config(model_config, family: str, config=None,
                           **overrides) -> PagedCacheSpec:
    """Resolve a PagedCacheSpec from a model config + the ``serving``
    config block (+ keyword overrides: slots, page_size,
    max_pages_per_slot, num_blocks, kv_cache_bits)."""
    sc = _serving_section(config)
    known = ("slots", "page_size", "max_pages_per_slot", "num_blocks",
             "kv_cache_bits")
    unknown = set(overrides) - set(known) - {"quantize_bits"}
    if unknown:
        raise TypeError(f"unknown serving override(s) {sorted(unknown)}; "
                        f"valid: {list(known) + ['quantize_bits']}")
    fields = {k: overrides.get(k, getattr(sc, k)) for k in known}
    if family == "gpt2":
        geom = dict(n_layers=model_config.n_layer,
                    kv_heads=model_config.n_head,
                    head_dim=model_config.n_embd // model_config.n_head,
                    dtype=model_config.dtype)
    elif family == "llama":
        geom = dict(n_layers=model_config.n_layers,
                    kv_heads=model_config.kv_heads,
                    head_dim=model_config.head_dim,
                    dtype=model_config.dtype)
    else:
        raise ValueError(f"unknown serving family {family!r} "
                         "(expected 'gpt2' or 'llama')")
    return PagedCacheSpec(**geom, **fields)


def build_engine(family: str, model_config, params, config=None,
                 registry=None, recorder=None, watchdog=None,
                 drafter_model_config=None, drafter_params=None,
                 **overrides) -> ContinuousBatcher:
    """Build a ContinuousBatcher for ``family``:

    - ``"gpt2"``: ``params`` is either the training ``GPT2LMHeadModel``
      tree or the converted (optionally int8-quantized) inference tree;
    - ``"llama"``: ``params`` is the PACKED serving tree
      (models.llama_inference.convert_llama_serving_params /
      quantize_llama_serving_params / random_int8_serving_params).

    A ``monitor.watchdog`` block in ``config`` attaches an anomaly
    watchdog (telemetry/anomaly.py: TTFT blowup + page-pool exhaustion
    rules, one-shot flight-recorder dumps); pass ``watchdog=`` to
    supply one directly.

    A ``serving.prefix_cache`` sub-block turns on copy-on-write prefix
    page sharing; a ``serving.speculative`` sub-block turns on
    speculative decoding (``drafter: "model"`` additionally needs
    ``drafter_model_config`` + ``drafter_params`` — same family, its
    own smaller geometry).
    """
    from deepspeed_tpu.config import constants as C
    # parse once; pd is a plain dict, so the helpers below re-load it
    # for free instead of re-reading a json file per call
    pd = _param_dict(config)
    if config is not None:
        if C.SERVING in pd and not _serving_section(pd).enabled:
            raise ValueError(
                "the config's serving block sets enabled: false — "
                "drop the block (or flip the flag) to build a serving "
                "engine from it")
    sc = _serving_section(pd)
    if sc.speculative.enabled and sc.speculative.drafter == "model" \
            and (drafter_model_config is None or drafter_params is None):
        raise ValueError(
            "serving.speculative.drafter='model' needs "
            "drafter_model_config= and drafter_params= (a smaller "
            "checkpoint of the SAME family)")
    spec = cache_spec_from_config(model_config, family, pd, **overrides)
    # serving.quantize_bits = 8 quantizes full-precision param trees to
    # the int8 serving storage at build time; trees that already carry
    # int8 codes ("kernel_q") serve as-is either way
    qb = overrides.get("quantize_bits",
                       _serving_section(pd).quantize_bits)
    if family == "gpt2":
        adapter = GPT2ServingAdapter(model_config, params, spec,
                                     quantize_bits=qb)
    else:
        adapter = LlamaServingAdapter(model_config, params, spec,
                                      quantize_bits=qb)
    mc = None
    if C.MONITOR in pd:
        from deepspeed_tpu.config.config import MonitorConfig
        mc = MonitorConfig(pd)   # parsed ONCE for watchdog + endpoint
    if watchdog is None and mc is not None:
        from deepspeed_tpu.telemetry.anomaly import Watchdog
        from deepspeed_tpu.telemetry.recorder import default_recorder
        # reconfigure the process recorder only when THIS config
        # actually carries a monitor block — a serving-only config must
        # not clobber a training engine's explicit recorder settings
        default_recorder().configure(
            enabled=mc.flight_recorder.enabled,
            capacity=mc.flight_recorder.capacity)
        if mc.watchdog.enabled and registry is None:
            # the watchdog's trip counters must land in the SAME
            # registry the batcher records into, or metrics_snapshot /
            # an exporter over the engine registry never sees them
            from deepspeed_tpu.telemetry.registry import MetricsRegistry
            registry = MetricsRegistry()
        watchdog = Watchdog.from_config(mc.watchdog, recorder=recorder,
                                        registry=registry,
                                        source="serving")
    drafter = None
    spec_tokens = sc.speculative.tokens
    if sc.speculative.enabled:
        from deepspeed_tpu.serving.drafter import (NGramDrafter,
                                                   ModelDrafter)
        if sc.speculative.drafter == "model":
            dspec = cache_spec_from_config(drafter_model_config, family,
                                           pd, num_blocks=0, **{
                                               k: v for k, v in
                                               overrides.items()
                                               if k != "num_blocks"})
            if family == "gpt2":
                dadapter = GPT2ServingAdapter(drafter_model_config,
                                              drafter_params, dspec,
                                              quantize_bits=qb)
            else:
                dadapter = LlamaServingAdapter(drafter_model_config,
                                               drafter_params, dspec,
                                               quantize_bits=qb)
            drafter = ModelDrafter(dadapter)
        else:
            drafter = NGramDrafter(spec.slots,
                                   ngram_max=sc.speculative.ngram_max,
                                   ngram_min=sc.speculative.ngram_min)
    # registry: pass telemetry.default_registry() to merge the serving
    # metrics into the process-wide stream; default is per-engine
    cb = ContinuousBatcher(adapter, registry=registry,
                           recorder=recorder, watchdog=watchdog,
                           prefix_cache=sc.prefix_cache.enabled,
                           prefix_cow=sc.prefix_cache.cow,
                           drafter=drafter, spec_tokens=spec_tokens)
    # ISSUE 11: a serving.elastic block attaches the drain-or-snapshot
    # preemption controller (SIGTERM → finish what fits the grace
    # budget, snapshot the rest through the two-rename commit path)
    if sc.elastic.enabled:
        from deepspeed_tpu.serving.elastic import ElasticServingController
        cb.attach_elastic(ElasticServingController.from_config(
            cb, sc.elastic))
    # ISSUE 12: live /metrics + /healthz over THIS engine's registry
    # (monitor.serve_port; a bind failure warns instead of killing the
    # server — e.g. a training engine in the same process won the port)
    if mc is not None and mc.serve_port:
        from deepspeed_tpu.telemetry.serve import start_metrics_server
        cb.metrics_server = start_metrics_server(
            mc.serve_port, host=mc.serve_host, registry=cb.metrics,
            watchdog=cb.watchdog,
            fence_age_fn=lambda: cb._t_last_step_ts)
    return cb


def build_router(family: str, model_config, params, config=None,
                 registry=None, recorder=None, **overrides):
    """Build a :class:`~deepspeed_tpu.serving.router.DisaggRouter`
    from the ``serving.disaggregation`` + ``serving.router`` config
    blocks (ISSUE 14): one shared adapter (the compiled prefill/tick
    programs), ``prefill_replicas`` prefill-role engines (prefix index
    ON by default — the locality-routing signal), ``decode_replicas``
    decode-role engines (prefix index on when ``dedupe_pages`` — the
    handoff re-share signal), each with its OWN paged pool.

    ``decode_replicas: 0`` or ``disaggregation.enabled: false`` falls
    back to colocated engines (``role="both"``) behind the same router
    API — no handoff, pre-disagg behavior per engine."""
    from deepspeed_tpu.serving.router import DisaggRouter

    pd = _param_dict(config)
    sc = _serving_section(pd)
    dg, rt = sc.disaggregation, sc.router
    # loud, not silent: a block that would be dropped on the floor
    # must raise — build_router still wires no drafters onto its role
    # engines (per-role drafter placement stays the follow-up; the
    # serving.elastic lift landed with ISSUE 17: per-engine snapshot
    # dirs below)
    if sc.speculative.enabled:
        raise ValueError(
            "serving.build_router does not compose with the "
            "serving.speculative block yet — drop it from the config, "
            "or construct the role engines and DisaggRouter directly")
    if dg.transport == "process":
        raise ValueError(
            "serving.disaggregation.transport \"process\" places "
            "roles on RANKS, not on in-process engines — each process "
            "builds its own role node with "
            "serving.build_transport_node(...) (build_router builds "
            "the in-process fabric only)")
    spec = cache_spec_from_config(model_config, family, pd, **overrides)
    qb = overrides.get("quantize_bits", sc.quantize_bits)
    if family == "gpt2":
        adapter = GPT2ServingAdapter(model_config, params, spec,
                                     quantize_bits=qb)
    else:
        adapter = LlamaServingAdapter(model_config, params, spec,
                                      quantize_bits=qb)
    disagg = dg.enabled and dg.decode_replicas > 0

    def mk(role, prefix_on):
        return ContinuousBatcher(
            adapter, registry=registry, recorder=recorder,
            prefix_cache=prefix_on, prefix_cow=sc.prefix_cache.cow,
            role=role)

    if disagg:
        prefills = [mk("prefill",
                       sc.prefix_cache.enabled or rt.prefix_routing)
                    for _ in range(dg.prefill_replicas)]
        decodes = [mk("decode", dg.dedupe_pages)
                   for _ in range(dg.decode_replicas)]
    else:
        prefills = [mk("both", sc.prefix_cache.enabled)
                    for _ in range(max(dg.prefill_replicas, 1))]
        decodes = []
    router = DisaggRouter(
        prefills, decodes, registry=registry, recorder=recorder,
        prefix_routing=rt.prefix_routing,
        dedupe_pages=dg.dedupe_pages,
        queue_weight=rt.queue_weight, ttft_weight=rt.ttft_weight,
        ttft_window=rt.ttft_window,
        max_handoff_retries=rt.max_handoff_retries,
        decode_tick_cap=rt.decode_tick_cap,
        max_inflight_pages=rt.max_inflight_pages or None,
        decode_schedule=rt.decode_schedule)
    if sc.elastic.enabled:
        # ISSUE 17 satellite: the serving.elastic lift. Each role
        # engine snapshots into its OWN subdir of snapshot_path (keyed
        # by the replica_id the router just assigned) — N engines
        # writing one dir would race the commit-rename protocol. The
        # installed signal handlers chain through preemption.py's
        # lock-free chain, so one delivered SIGTERM drains every
        # engine; DisaggRouter.close() retires them via release() (the
        # pool discipline — restore() would drop later handlers).
        import os as _os
        e = sc.elastic
        for cb in router.prefill_engines + router.decode_engines:
            cb.attach_elastic(ElasticServingController(
                cb, _os.path.join(e.snapshot_path, cb.replica_id),
                grace_secs=e.grace_secs,
                interval_ticks=e.interval_ticks, keep=e.keep,
                fsync=e.fsync, signals=e.signals,
                max_retries=e.max_retries, backoff_s=e.backoff_s))
    return router


def build_transport_node(family: str, model_config, params, config=None,
                         registry=None, recorder=None, endpoint=None,
                         on_tick=None, on_absorb=None, on_done=None,
                         **overrides):
    """This process's role node for the cross-process handoff fabric
    (ISSUE 17, ``serving.disaggregation.transport: "process"``): roles
    are assigned BY RANK — rank 0 builds the prefill engine(s) plus
    the router (:class:`~deepspeed_tpu.serving.transport.PrefillNode`),
    every other rank builds one decode engine
    (:class:`~deepspeed_tpu.serving.transport.DecodeNode`). One device
    per process, sequential collectives — the documented
    gloo-flake-stable recipe (tests/test_multiprocess_dist.py).

    Every rank must run the SAME config (the decode pool geometry the
    router's backpressure default assumes is the one this rank would
    build). ``endpoint`` defaults to the live
    :class:`~deepspeed_tpu.serving.transport.ProcessEndpoint`; tests
    pass :class:`~deepspeed_tpu.serving.transport.LoopbackFabric`
    endpoints to run both roles in one process."""
    from deepspeed_tpu.serving.transport import (DecodeNode,
                                                 PrefillNode,
                                                 ProcessEndpoint)
    from deepspeed_tpu.config import constants as C
    pd = _param_dict(config)
    sc = _serving_section(pd)
    dg, rt = sc.disaggregation, sc.router
    mc = None
    if C.MONITOR in pd:
        from deepspeed_tpu.config.config import MonitorConfig
        mc = MonitorConfig(pd)   # SLO plane + live endpoint gates
    if endpoint is None:
        # ISSUE 18: addressing "targeted" (default) moves dst-addressed
        # frames point-to-point, "broadcast" keeps the PR-17 legacy leg
        endpoint = ProcessEndpoint(
            addressing=dg.addressing,
            payload_timeout_s=dg.payload_timeout_s)
    assert endpoint.world >= 2, (
        f"the process transport needs >= 2 ranks (prefill + decode), "
        f"got world={endpoint.world}")
    spec = cache_spec_from_config(model_config, family, pd, **overrides)
    qb = overrides.get("quantize_bits", sc.quantize_bits)
    if family == "gpt2":
        adapter = GPT2ServingAdapter(model_config, params, spec,
                                     quantize_bits=qb)
    else:
        adapter = LlamaServingAdapter(model_config, params, spec,
                                      quantize_bits=qb)
    if endpoint.rank == 0:
        prefills = []
        for i in range(max(dg.prefill_replicas, 1)):
            cb = ContinuousBatcher(
                adapter, registry=registry, recorder=recorder,
                prefix_cache=sc.prefix_cache.enabled or rt.prefix_routing,
                prefix_cow=sc.prefix_cache.cow, role="prefill")
            cb.replica_id = f"prefill{i}"
            prefills.append(cb)
        # default backpressure bound mirrors DisaggRouter's: 2x the
        # decode pools' allocatable total (same spec on every rank)
        alloc = prefills[0].cache.num_blocks - 1
        bound = rt.max_inflight_pages \
            or 2 * alloc * (endpoint.world - 1)
        node = PrefillNode(
            prefills, endpoint, registry=registry, recorder=recorder,
            max_inflight_pages=bound,
            max_inflight_pages_per_rank=(
                rt.max_inflight_pages_per_rank or None),
            max_handoff_retries=rt.max_handoff_retries,
            on_tick=on_tick, on_done=on_done)
        if mc is not None:
            # ISSUE 19: the rank-0 SLO plane — windowed per-role
            # quantiles + burn rate over the exchanged metrics vector,
            # exported as slo/* gauges each tick
            from deepspeed_tpu.telemetry.slo import SloPlane
            node.slo = SloPlane.from_config(mc.slo)
            if mc.serve_port:
                # live /metrics + /healthz on the router rank; /healthz
                # carries the targeted-transport fabric liveness
                # (per-peer connected / last-payload age) so a
                # half-dead socket mesh is visible BEFORE a
                # payload_timeout_s trips (ISSUE 19 satellite)
                from deepspeed_tpu.telemetry.serve import \
                    start_metrics_server
                node.metrics_server = start_metrics_server(
                    mc.serve_port, host=mc.serve_host,
                    registry=node.metrics,
                    extra_health_fn=getattr(endpoint, "fabric_health",
                                            None))
        return node
    cb = ContinuousBatcher(adapter, registry=registry, recorder=recorder,
                           prefix_cache=dg.dedupe_pages,
                           prefix_cow=sc.prefix_cache.cow, role="decode")
    cb.replica_id = f"decode{endpoint.rank}"
    return DecodeNode(cb, endpoint, registry=registry,
                      recorder=recorder,
                      decode_ticks=rt.decode_tick_cap,
                      on_tick=on_tick, on_absorb=on_absorb)
