"""Paged KV cache — a pooled block store + host-side page accounting.

The static serving path reserves ``max_batch x max_ctx`` cache rows up
front and every request in a batch pays the longest request's length.
Here the cache is a POOL of fixed-size blocks shared by all slots:

- device side: ``[Lyr, num_blocks, H, page_size, D]`` K/V block arrays
  (int8 codes + ``[Lyr, num_blocks, H, 1, page_size]`` lane-major fp32
  absmax scales — the quantized-cache layout of
  ops/transformer/inference.py — or plain bf16/fp32 blocks), donated
  through every prefill/tick so appends update in place;
- host side: a free list of block ids and per-slot page tables
  ``[slots, max_pages_per_slot]`` int32. A request's pages are allocated
  on admission (enough for prompt + max_new_tokens) and returned to the
  free list the moment it finishes — no other slot's cache moves.

Block 0 is RESERVED as the trash block: idle slots' page-table entries
(and the pad tail of shorter tables) point at it, so the decode tick's
append scatter always has a legal target and idle slots can never
corrupt a live block.

Prefix sharing (``enable_prefix_sharing()``) grows the allocator from
exclusive ownership to REFCOUNTED shared pages: each admitted request's
full prompt pages are registered in a chained-hash prefix index, and a
later request whose prompt matches maps the shared blocks into its own
page table with an incref instead of allocating + prefilling them. K/V
pages are append-only, so a full prompt page is immutable once written
and safe to alias read-only; the first PARTIALLY-filled prompt page is
shared copy-on-write (the sharer gets a device copy of the page and
continues writing its own rows there). Release becomes decref;
refcount-0 registered pages stay RESIDENT as reusable prefix cache and
are evicted LRU only under pool pressure (or an explicit sweep).
"""

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

TRASH_BLOCK = 0


@dataclasses.dataclass(frozen=True)
class PagedCacheSpec:
    """Geometry of a paged pool (see ServingConfig for the config block
    that produces one)."""
    n_layers: int
    kv_heads: int
    head_dim: int
    page_size: int = 128
    num_blocks: int = 0          # 0 → slots * max_pages_per_slot + 1
    max_pages_per_slot: int = 16
    slots: int = 8
    kv_cache_bits: int = 0       # 0 = dtype storage; 8 = int8 + scales
    dtype: Any = jnp.bfloat16

    def resolved_num_blocks(self) -> int:
        if self.num_blocks > 0:
            return self.num_blocks
        return self.slots * self.max_pages_per_slot + 1  # +1: trash

    def max_tokens_per_slot(self) -> int:
        return self.max_pages_per_slot * self.page_size


class PagedKVCache:
    """Device block pool + host page allocator for one model's caches.

    ``pool`` is a tuple of device arrays — ``(k, v)`` for full-precision
    storage or ``(k_codes, k_scale, v_codes, v_scale)`` for int8 — that
    the engine threads through its donated prefill/tick programs and
    reassigns after each call.
    """

    def __init__(self, spec: PagedCacheSpec):
        self.spec = spec
        nb = spec.resolved_num_blocks()
        assert nb >= 2, "need at least one allocatable block past trash"
        Lyr, H, P, D = (spec.n_layers, spec.kv_heads, spec.page_size,
                        spec.head_dim)
        if spec.kv_cache_bits == 8:
            self.pool = (
                jnp.zeros((Lyr, nb, H, P, D), jnp.int8),
                jnp.full((Lyr, nb, H, 1, P), 1e-12, jnp.float32),
                jnp.zeros((Lyr, nb, H, P, D), jnp.int8),
                jnp.full((Lyr, nb, H, 1, P), 1e-12, jnp.float32),
            )
        elif spec.kv_cache_bits == 0:
            self.pool = (jnp.zeros((Lyr, nb, H, P, D), spec.dtype),
                         jnp.zeros((Lyr, nb, H, P, D), spec.dtype))
        else:
            raise ValueError(f"kv_cache_bits must be 0 or 8, got "
                             f"{spec.kv_cache_bits}")
        self.num_blocks = nb
        # LIFO free list: recently-freed blocks are re-used first, which
        # is what the slot-reuse tests lean on to catch stale reads
        self._free: List[int] = list(range(nb - 1, TRASH_BLOCK, -1))
        # per-slot page tables; unused entries point at the trash block
        self.page_table = np.full((spec.slots, spec.max_pages_per_slot),
                                  TRASH_BLOCK, np.int32)
        self._slot_pages: List[List[int]] = [[] for _ in range(spec.slots)]
        # --- prefix sharing (off until enable_prefix_sharing()) ---
        self.prefix_sharing = False
        self._refcount = np.zeros(nb, np.int64)
        # chain-hash key -> _FullEntry (one immutable full prompt page)
        self._full_index: Dict[bytes, "_FullEntry"] = {}
        # chain-hash key of the full-page prefix -> divergent partial
        # last-prompt-page entries (COW sources)
        self._partial_index: Dict[bytes, List["_PartialEntry"]] = {}
        self._block_entry: Dict[int, Any] = {}   # block -> its entry
        # refcount-0 registered blocks, LRU order (resident prefix cache)
        self._evictable: "OrderedDict[int, None]" = OrderedDict()
        self.prefix_stats = {"hit_pages": 0, "cow_hits": 0,
                             "cow_rows": 0, "fresh_pages": 0,
                             "evictions": 0, "registered": 0,
                             "shared_admissions": 0, "admissions": 0}

    def enable_prefix_sharing(self) -> None:
        self.prefix_sharing = True

    # ---------------------------------------------------- host accounting

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def cached_pages(self) -> int:
        """Refcount-0 registered pages held resident as prefix cache."""
        return len(self._evictable)

    @property
    def available_pages(self) -> int:
        """Pages an admission could obtain: free + evictable cache."""
        return len(self._free) + len(self._evictable)

    def pages_needed(self, total_tokens: int) -> int:
        return -(-total_tokens // self.spec.page_size)

    @property
    def page_nbytes(self) -> int:
        """Raw bytes ONE block contributes to a page handoff, summed
        over every pool component (fp: k+v rows; int8: codes + scales)
        — the payload term of the transport's packet-size cost model
        (``router/handoff_bytes_*`` counters, ISSUE 17)."""
        return sum(int(np.prod(comp.shape, dtype=np.int64))
                   // int(comp.shape[1]) * comp.dtype.itemsize
                   for comp in self.pool)

    def _take_fresh(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` blocks from the free list, evicting LRU refcount-0
        prefix entries to cover a shortfall. None (nothing taken) when
        even eviction can't cover it."""
        if n > len(self._free) + len(self._evictable):
            return None
        while len(self._free) < n:
            blk, _ = self._evictable.popitem(last=False)   # LRU
            self._unregister(blk)
            self._free.append(blk)
            self.prefix_stats["evictions"] += 1
        return [self._free.pop() for _ in range(n)]

    def admit(self, slot: int, total_tokens: int) -> Optional[List[int]]:
        """Allocate pages covering ``total_tokens`` rows into ``slot``'s
        page table. Returns the page list, or None (nothing allocated)
        when the pool can't cover it."""
        n = self.pages_needed(total_tokens)
        assert n <= self.spec.max_pages_per_slot, (
            f"request needs {n} pages > max_pages_per_slot "
            f"{self.spec.max_pages_per_slot} (page_size "
            f"{self.spec.page_size})")
        assert not self._slot_pages[slot], f"slot {slot} already admitted"
        pages = self._take_fresh(n)
        if pages is None:
            return None
        self._refcount[pages] = 1
        self._slot_pages[slot] = pages
        row = self.page_table[slot]
        row[:] = TRASH_BLOCK
        row[:n] = pages
        self.prefix_stats["admissions"] += 1
        self.prefix_stats["fresh_pages"] += n
        return pages

    def release(self, slot: int) -> None:
        """Decref ``slot``'s pages (on EOS/finish). Pages reaching
        refcount 0 return to the free list — unless they are registered
        prefix entries, which stay resident (evictable) so a later
        request with the same prompt prefix can re-share them."""
        for blk in self._slot_pages[slot]:
            self._refcount[blk] -= 1
            assert self._refcount[blk] >= 0, f"block {blk} over-released"
            if self._refcount[blk] == 0:
                if blk in self._block_entry:
                    self._evictable[blk] = None   # newest = MRU end
                else:
                    self._free.append(blk)
        self._slot_pages[slot] = []
        self.page_table[slot, :] = TRASH_BLOCK

    def slot_pages(self, slot: int) -> List[int]:
        return list(self._slot_pages[slot])

    # ------------------------------------------------------ prefix index

    def _page_hashes(self, prompt: np.ndarray) -> List[bytes]:
        """Chained page-granularity hashes: h_i covers tokens
        [0, (i+1) * page_size) — a full page of K/V is reusable only if
        EVERY token before its end matches, since row t's K/V depends on
        tokens 0..t."""
        P = self.spec.page_size
        out, h = [], b""
        for i in range(len(prompt) // P):
            h = hashlib.sha1(h + prompt[i * P:(i + 1) * P]
                             .tobytes()).digest()
            out.append(h)
        return out

    def match_prefix(self, prompt: np.ndarray,
                     cow: bool = True) -> "PrefixMatch":
        """Longest resident prefix for ``prompt``: shared FULL pages
        (hash-chain walk, content-verified) plus an optional partial
        last-prompt-page COW source (skipped entirely when ``cow`` is
        off — page-aligned sharing only, no phantom COW stats). At
        least one suffix token is always left for prefill — the
        admission needs last-position logits. The full hash chain rides
        the returned match so register_prefix can reuse it instead of
        rehashing the prompt."""
        prompt = np.asarray(prompt, np.int32)  # sync-ok: host prompt
        S = len(prompt)
        P = self.spec.page_size
        shared: List[int] = []
        chain = b""
        hashes = self._page_hashes(prompt)
        # a fully matched prompt still recomputes its last page, so the
        # walk stops at (S-1)//P full pages
        limit = (S - 1) // P
        for i, h in enumerate(hashes[:limit]):
            ent = self._full_index.get(h)
            if ent is None or not np.array_equal(
                    ent.tokens, prompt[i * P:(i + 1) * P]):
                break
            shared.append(ent.block)
            chain = h
        cow_src = None
        if cow and len(shared) == limit and limit == S // P:
            # full pages all matched and the prompt's last page is
            # partial — look for a divergent-partial COW source
            rest = prompt[len(shared) * P:]
            best_r = 0
            for ent in self._partial_index.get(chain, []):
                m = min(len(ent.tokens), len(rest) - 1)
                if m <= 0:
                    continue
                r = int(np.argmin(ent.tokens[:m] == rest[:m])) \
                    if not np.array_equal(ent.tokens[:m], rest[:m]) \
                    else m
                if r > best_r:
                    best_r, cow_src = r, (ent.block, r)
        return PrefixMatch(shared_blocks=shared, cow=cow_src,
                           start_pos=len(shared) * P
                           + (cow_src[1] if cow_src else 0),
                           hashes=hashes)

    def admit_prefix(self, slot: int, prompt: np.ndarray,
                     total_tokens: int,
                     cow: bool = True) -> Optional["AdmitPlan"]:
        """Prefix-sharing admission: map the matched resident prefix
        pages into ``slot``'s table (incref, zero allocation, zero
        prefill for the shared span), allocate fresh pages for the rest.
        Returns the plan, or None (nothing allocated/increffed) when
        fresh pages can't be covered even after eviction."""
        assert self.prefix_sharing, "enable_prefix_sharing() first"
        assert not self._slot_pages[slot], f"slot {slot} already admitted"
        n = self.pages_needed(total_tokens)
        assert n <= self.spec.max_pages_per_slot
        m = self.match_prefix(prompt, cow=cow)
        n_shared = len(m.shared_blocks)
        # pin the matched blocks (and the read-once COW source) out of
        # the evictable set BEFORE taking fresh pages — the shortfall
        # eviction must never reap a block this admission is sharing
        cow_src = m.cow[0] if m.cow is not None else None
        pinned = []
        for b in m.shared_blocks + ([cow_src] if cow_src is not None
                                    else []):
            if b in self._evictable:
                del self._evictable[b]
                pinned.append(b)
        fresh = self._take_fresh(n - n_shared)
        if fresh is None:
            for blk in pinned:                   # undo: nothing taken
                self._evictable[blk] = None
            return None
        for blk in m.shared_blocks:
            self._refcount[blk] += 1
        if cow_src is not None and self._refcount[cow_src] == 0:
            # the COW source is only READ (once, at the copy) — it goes
            # back resident at the MRU end, not owned by this slot
            self._evictable[cow_src] = None
        self._refcount[fresh] = 1
        pages = m.shared_blocks + fresh
        self._slot_pages[slot] = pages
        row = self.page_table[slot]
        row[:] = TRASH_BLOCK
        row[:n] = pages
        st = self.prefix_stats
        st["admissions"] += 1
        st["hit_pages"] += n_shared
        st["fresh_pages"] += n - n_shared
        if n_shared or m.cow:
            st["shared_admissions"] += 1
        cow_plan = None
        if m.cow is not None:
            src, r = m.cow
            cow_plan = (src, fresh[0], r)
            st["cow_hits"] += 1
            st["cow_rows"] += r
        return AdmitPlan(pages=pages, start_pos=m.start_pos,
                         cow=cow_plan, hashes=m.hashes)

    def register_prefix(self, slot: int, prompt: np.ndarray,
                        hashes: Optional[List[bytes]] = None) -> int:
        """Register ``slot``'s prompt pages in the prefix index (after
        prefill wrote them): every full prompt page becomes a shareable
        read-only entry, the partial last prompt page (if any) a COW
        source. Already-indexed content is skipped. Returns the number
        of new entries. Pass the hash chain from the admission's
        AdmitPlan to skip rehashing the prompt."""
        assert self.prefix_sharing
        prompt = np.asarray(prompt, np.int32)  # sync-ok: host prompt
        P = self.spec.page_size
        pages = self._slot_pages[slot]
        added, chain = 0, b""
        if hashes is None:
            hashes = self._page_hashes(prompt)
        for i, h in enumerate(hashes):
            blk = pages[i]
            if h not in self._full_index and blk not in self._block_entry:
                ent = _FullEntry(block=blk, key=h,
                                 tokens=prompt[i * P:(i + 1) * P].copy())
                self._full_index[h] = ent
                self._block_entry[blk] = ent
                added += 1
            chain = h
        r = len(prompt) % P
        if r:
            blk = pages[len(prompt) // P]
            toks = prompt[len(prompt) - r:].copy()
            peers = self._partial_index.setdefault(chain, [])
            dup = any(len(e.tokens) >= r
                      and np.array_equal(e.tokens[:r], toks)
                      for e in peers)
            if not dup and blk not in self._block_entry:
                ent = _PartialEntry(block=blk, chain=chain, tokens=toks)
                peers.append(ent)
                self._block_entry[blk] = ent
                added += 1
        self.prefix_stats["registered"] += added
        return added

    def _unregister(self, blk: int) -> None:
        ent = self._block_entry.pop(blk, None)
        if ent is None:
            return
        if isinstance(ent, _FullEntry):
            self._full_index.pop(ent.key, None)
        else:
            peers = self._partial_index.get(ent.chain, [])
            if ent in peers:
                peers.remove(ent)
            if not peers:
                self._partial_index.pop(ent.chain, None)

    # ------------------------------------- page transport (handoff/restore)

    def gather_block_kv(self, blocks: List[int]):
        """DEVICE-side gather of ``blocks``' bytes, one array per pool
        component (``[Lyr, n_blocks, ...]``) — the sending half of a
        page handoff (ISSUE 14). Stays on device: the in-process
        transport never round-trips through the host (a cross-process
        transport would ``np.asarray`` the result — that is the whole
        difference, which is what makes it a drop-in)."""
        sel = jnp.asarray(np.asarray(blocks, np.int32))  # sync-ok: host
        #                                                  block-id list
        return tuple(comp[:, sel] for comp in self.pool)

    def scatter_block_kv(self, blocks: List[int], comps,
                         src_offset: int = 0) -> None:
        """Write gathered component arrays into this pool at
        ``blocks`` — the receiving half of a page handoff. ``comps``
        is ``gather_block_kv``'s tuple (device or host arrays);
        ``src_offset`` skips leading source pages the target already
        holds (a prefix-index dedupe hit)."""
        if not blocks:
            return
        dst = jnp.asarray(np.asarray(blocks, np.int32))  # sync-ok: host
        n = len(blocks)
        self.pool = tuple(
            comp.at[:, dst].set(jnp.asarray(
                c[:, src_offset:src_offset + n]))
            for comp, c in zip(self.pool, comps))

    # ------------------------------------------- elastic snapshot/restore

    def take_blocks(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` fresh blocks (evicting LRU refcount-0 prefix
        entries to cover a shortfall), refcount 0 — the elastic-restore
        allocation primitive. The caller distributes refcounts through
        :meth:`adopt_slot` / :meth:`import_prefix_entry`; unreferenced
        blocks must go back through :meth:`return_blocks`."""
        if n == 0:
            return []
        return self._take_fresh(n)

    def return_blocks(self, blocks: List[int]) -> None:
        """Give back refcount-0 blocks from :meth:`take_blocks` (a
        restore that could not finish must not leak the pool)."""
        for blk in blocks:
            assert self._refcount[blk] == 0, f"block {blk} still held"
            assert blk not in self._block_entry, f"block {blk} registered"
            self._free.append(blk)

    def adopt_slot(self, slot: int, blocks: List[int]) -> None:
        """Map an explicit block list into ``slot``'s page table with an
        incref per block — the elastic-restore admission (blocks were
        allocated by :meth:`take_blocks` and may be SHARED between
        restored slots; refcount ends at the number of holders, exactly
        the invariant :meth:`release` decrefs against)."""
        n = len(blocks)
        assert n <= self.spec.max_pages_per_slot, (n, slot)
        assert not self._slot_pages[slot], f"slot {slot} already admitted"
        for blk in blocks:
            self._refcount[blk] += 1
            if blk in self._evictable:   # re-shared resident entry
                del self._evictable[blk]
        self._slot_pages[slot] = list(blocks)
        row = self.page_table[slot]
        row[:] = TRASH_BLOCK
        row[:n] = blocks

    def export_prefix_entries(self):
        """JSON-able dump of the prefix index: every registered full /
        partial entry as ``{"block", "key"/"chain" (hex), "tokens"}`` —
        the content a restore needs to rebuild :attr:`_full_index` /
        :attr:`_partial_index` on a different engine without rehashing
        (and without the original prompt streams)."""
        full, partial = [], []
        for key, ent in self._full_index.items():
            full.append({"block": int(ent.block), "key": key.hex(),
                         "tokens": ent.tokens.tolist()})
        for chain, peers in self._partial_index.items():
            for ent in peers:
                partial.append({"block": int(ent.block),
                                "chain": chain.hex(),
                                "tokens": ent.tokens.tolist()})
        return {"full": full, "partial": partial}

    def import_prefix_entry(self, block: int, tokens, key: bytes = None,
                            chain: bytes = None) -> bool:
        """Re-register one exported prefix entry against ``block`` (a
        restored page): ``key`` makes a full entry, ``chain`` a partial
        one. Refcount-0 blocks become resident prefix cache (MRU end).
        Returns False (nothing registered) when the content is already
        indexed or the block carries an entry."""
        assert self.prefix_sharing
        assert (key is None) != (chain is None), "key XOR chain"
        toks = np.asarray(tokens, np.int32)  # sync-ok: host token list
        if block in self._block_entry:
            return False
        if key is not None:
            if key in self._full_index:
                return False
            ent = _FullEntry(block=block, key=key, tokens=toks)
            self._full_index[key] = ent
        else:
            peers = self._partial_index.setdefault(chain, [])
            r = len(toks)
            if any(len(e.tokens) >= r
                   and np.array_equal(e.tokens[:r], toks)
                   for e in peers):
                return False
            ent = _PartialEntry(block=block, chain=chain, tokens=toks)
            peers.append(ent)
        self._block_entry[block] = ent
        self.prefix_stats["registered"] += 1
        if self._refcount[block] == 0:
            self._evictable[block] = None
        return True

    def sweep_prefix_cache(self) -> int:
        """Evict EVERY refcount-0 resident prefix entry back to the free
        list (the leak-test / shutdown fence: after a drained workload +
        sweep, free_pages must equal the allocatable pool)."""
        n = 0
        while self._evictable:
            blk, _ = self._evictable.popitem(last=False)
            self._unregister(blk)
            self._free.append(blk)
            n += 1
        return n


@dataclasses.dataclass(frozen=True)
class _FullEntry:
    block: int
    key: bytes
    tokens: np.ndarray            # the page's P prompt tokens


@dataclasses.dataclass(frozen=True)
class _PartialEntry:
    block: int
    chain: bytes                  # hash of the full-page prefix before it
    tokens: np.ndarray            # the page's PARTIAL prompt tokens


@dataclasses.dataclass(frozen=True)
class PrefixMatch:
    shared_blocks: List[int]
    cow: Optional[Tuple[int, int]]     # (source block, matched rows)
    start_pos: int                     # prefill resumes here
    hashes: List[bytes] = dataclasses.field(default_factory=list)
    #                                  # full chain, for register_prefix


@dataclasses.dataclass(frozen=True)
class AdmitPlan:
    pages: List[int]
    start_pos: int
    cow: Optional[Tuple[int, int, int]]  # (src block, dst block, rows)
    hashes: List[bytes] = dataclasses.field(default_factory=list)


def pow2_page_bucket(need: int, max_pages: int) -> int:
    """Next-pow2 bucket of a page count, clamped to the position budget
    — prefill programs compile O(log max_pages) variants, not one per
    prompt length. ONE rule shared by padded_prefill_inputs and the
    engine's suffix/prefix bucket picks so they can't drift apart."""
    b = 1
    while b < need:
        b *= 2
    return min(b, max_pages)


def padded_prefill_inputs(prompt: np.ndarray, pages: List[int],
                          page_size: int, max_pages: int):
    """Pow2-bucketed prefill inputs: token ids zero-padded to the page
    bucket, page vector TRASH-padded to the same bucket. ONE contract
    shared by the engine's admission prefill and the ModelDrafter's
    mirror prefill so the page-padding rules can't drift apart."""
    S = len(prompt)
    n_pages = pow2_page_bucket(max(1, -(-S // page_size)), max_pages)
    ids = np.zeros((1, n_pages * page_size), np.int32)
    ids[0, :S] = prompt
    page_vec = np.full((n_pages,), TRASH_BLOCK, np.int32)
    k = min(n_pages, len(pages))
    page_vec[:k] = pages[:k]
    return ids, page_vec
