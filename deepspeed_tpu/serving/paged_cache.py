"""Paged KV cache — a pooled block store + host-side page accounting.

The static serving path reserves ``max_batch x max_ctx`` cache rows up
front and every request in a batch pays the longest request's length.
Here the cache is a POOL of fixed-size blocks shared by all slots:

- device side: ``[Lyr, num_blocks, H, page_size, D]`` K/V block arrays
  (int8 codes + ``[Lyr, num_blocks, H, 1, page_size]`` lane-major fp32
  absmax scales — the quantized-cache layout of
  ops/transformer/inference.py — or plain bf16/fp32 blocks), donated
  through every prefill/tick so appends update in place;
- host side: a free list of block ids and per-slot page tables
  ``[slots, max_pages_per_slot]`` int32. A request's pages are allocated
  on admission (enough for prompt + max_new_tokens) and returned to the
  free list the moment it finishes — no other slot's cache moves.

Block 0 is RESERVED as the trash block: idle slots' page-table entries
(and the pad tail of shorter tables) point at it, so the decode tick's
append scatter always has a legal target and idle slots can never
corrupt a live block.
"""

import dataclasses
from typing import Any, List, Optional

import numpy as np
import jax.numpy as jnp

TRASH_BLOCK = 0


@dataclasses.dataclass(frozen=True)
class PagedCacheSpec:
    """Geometry of a paged pool (see ServingConfig for the config block
    that produces one)."""
    n_layers: int
    kv_heads: int
    head_dim: int
    page_size: int = 128
    num_blocks: int = 0          # 0 → slots * max_pages_per_slot + 1
    max_pages_per_slot: int = 16
    slots: int = 8
    kv_cache_bits: int = 0       # 0 = dtype storage; 8 = int8 + scales
    dtype: Any = jnp.bfloat16

    def resolved_num_blocks(self) -> int:
        if self.num_blocks > 0:
            return self.num_blocks
        return self.slots * self.max_pages_per_slot + 1  # +1: trash

    def max_tokens_per_slot(self) -> int:
        return self.max_pages_per_slot * self.page_size


class PagedKVCache:
    """Device block pool + host page allocator for one model's caches.

    ``pool`` is a tuple of device arrays — ``(k, v)`` for full-precision
    storage or ``(k_codes, k_scale, v_codes, v_scale)`` for int8 — that
    the engine threads through its donated prefill/tick programs and
    reassigns after each call.
    """

    def __init__(self, spec: PagedCacheSpec):
        self.spec = spec
        nb = spec.resolved_num_blocks()
        assert nb >= 2, "need at least one allocatable block past trash"
        Lyr, H, P, D = (spec.n_layers, spec.kv_heads, spec.page_size,
                        spec.head_dim)
        if spec.kv_cache_bits == 8:
            self.pool = (
                jnp.zeros((Lyr, nb, H, P, D), jnp.int8),
                jnp.full((Lyr, nb, H, 1, P), 1e-12, jnp.float32),
                jnp.zeros((Lyr, nb, H, P, D), jnp.int8),
                jnp.full((Lyr, nb, H, 1, P), 1e-12, jnp.float32),
            )
        elif spec.kv_cache_bits == 0:
            self.pool = (jnp.zeros((Lyr, nb, H, P, D), spec.dtype),
                         jnp.zeros((Lyr, nb, H, P, D), spec.dtype))
        else:
            raise ValueError(f"kv_cache_bits must be 0 or 8, got "
                             f"{spec.kv_cache_bits}")
        self.num_blocks = nb
        # LIFO free list: recently-freed blocks are re-used first, which
        # is what the slot-reuse tests lean on to catch stale reads
        self._free: List[int] = list(range(nb - 1, TRASH_BLOCK, -1))
        # per-slot page tables; unused entries point at the trash block
        self.page_table = np.full((spec.slots, spec.max_pages_per_slot),
                                  TRASH_BLOCK, np.int32)
        self._slot_pages: List[List[int]] = [[] for _ in range(spec.slots)]

    # ---------------------------------------------------- host accounting

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_needed(self, total_tokens: int) -> int:
        return -(-total_tokens // self.spec.page_size)

    def admit(self, slot: int, total_tokens: int) -> Optional[List[int]]:
        """Allocate pages covering ``total_tokens`` rows into ``slot``'s
        page table. Returns the page list, or None (nothing allocated)
        when the pool can't cover it."""
        n = self.pages_needed(total_tokens)
        assert n <= self.spec.max_pages_per_slot, (
            f"request needs {n} pages > max_pages_per_slot "
            f"{self.spec.max_pages_per_slot} (page_size "
            f"{self.spec.page_size})")
        assert not self._slot_pages[slot], f"slot {slot} already admitted"
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._slot_pages[slot] = pages
        row = self.page_table[slot]
        row[:] = TRASH_BLOCK
        row[:n] = pages
        return pages

    def release(self, slot: int) -> None:
        """Return ``slot``'s pages to the free list (on EOS/finish)."""
        self._free.extend(self._slot_pages[slot])
        self._slot_pages[slot] = []
        self.page_table[slot, :] = TRASH_BLOCK

    def slot_pages(self, slot: int) -> List[int]:
        return list(self._slot_pages[slot])
