"""Disaggregated prefill/decode serving with an SLO-aware router
(ISSUE 14 tentpole).

Mixed traffic head-of-line blocks a colocated engine: its slots are
decode residency, so an arriving prompt waits for some long request to
FINISH before it can even prefill (BENCH_r08: TTFT p99 4.96 s vs p50
3.05 s), and symmetrically a long prefill dispatch sits between two
decode ticks of every in-flight request. The split:

- **prefill-role engines** (``ContinuousBatcher(role="prefill")``)
  admit prompts and run the page-bucketed prefill, nothing else. Their
  slots free the moment the produced pages are handed off, so prompt
  admission is never blocked on decode residency — TTFT collapses to
  router-queue + prefill time.
- a **page-handoff transport** moves the request: the wire format is
  ``elastic._req_doc`` (+ slot position) next to a device-side gather
  of the request's DATA pages (``PagedKVCache.gather_block_kv``). The
  in-process fast path keeps the gather on device and lands it with
  one scatter per pool component (``scatter_block_kv``) into blocks
  the decode engine's REFCOUNTED allocator handed out
  (``admit``/``admit_prefix``) — a cross-process transport only has to
  serialize the same (doc, component arrays) pair, so it is a drop-in
  (PAPERS.md 2408.13356: page movement is a transport concern, not an
  engine concern).
- **decode-role engines** adopt the pages (incref through the shared
  refcounted allocator path; a prefix-index dedupe hit re-shares
  resident pages instead of copying them) and continue token-for-token
  identically to a colocated run — they never execute a prefill
  program, so decode tick latency stops depending on prompt-arrival
  luck.

The :class:`DisaggRouter` schedules on three signals:

- **prefix locality**: a prompt routes to the prefill replica whose
  index already holds its prefix chain (``match_prefix`` probe — the
  hit skips the shared span's prefill compute there);
- **page-pool pressure**: the undelivered handoff KV is bounded
  (``max_inflight_pages``, default 2x the decode pools' allocatable
  total) — when exhausted decode pools leave a packet backlog at the
  bound, new prompts queue AT THE ROUTER, so an in-flight request can
  never hit ``pool_exhausted`` (delivery only takes pages when a slot
  freed them);
- **SLO**: otherwise prompts go to the prefill replica with the best
  live score (queue depth + recent-TTFT tail from the engines'
  ``metrics_snapshot()`` reservoirs), and packets land on the decode
  replica with the most free pages.

Colocated fallback: built with ``decode_replicas == 0`` (or
``serving.disaggregation.enabled: false`` through
:func:`deepspeed_tpu.serving.build_router`) every engine runs
``role="both"`` and the router degrades to an SLO dispatcher over N
colocated replicas — no handoff, pre-ISSUE-14 semantics per engine.

Recovery: a crash between extract and deliver (the ``serving_handoff``
fault point — the gathered bytes died with the transport) replays the
request from its wire doc: the committed stream becomes the admission
prompt, so greedy (and, with PR-14's persisted ``sample_key``, sampled)
decoding regenerates the identical continuation. Bounded by
``max_handoff_retries``. A crash INSIDE delivery (``serving_deliver``,
ISSUE 15 satellite — the decode pool already admitted the packet's
pages) additionally unwinds the admission in ``deliver_handoff``
before the same replay, so the pool never leaks the pages of a
half-delivered request.
"""

import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.runtime.elastic import faults
from deepspeed_tpu.serving import elastic
from deepspeed_tpu.serving.engine import Request, ensure_trace_id
from deepspeed_tpu.telemetry.recorder import default_recorder
from deepspeed_tpu.telemetry.registry import MetricsRegistry
from deepspeed_tpu.utils.logging import logger


def router_metric_names():
    """Every ``router/*`` metric the router can emit — pinned EXACTLY
    (both directions) against docs/observability.md by
    tests/test_metric_names.py, like the cluster namespace."""
    return (
        "router/queue_depth",        # prompts waiting at the router
        "router/inflight_packets",   # extracted, not yet delivered
        "router/inflight_pages",     # KV pages those packets hold
        "router/handoffs",           # delivered prefill→decode moves
        "router/handoff_requeues",   # transport-crash replays
        "router/decode_blocked",     # admissions deferred on pressure
        "router/prefix_routed",      # admissions routed by locality
        "router/slo_routed",         # admissions routed by SLO score
        "router/handoff_bytes_sent",  # wire bytes extracted/sent
        "router/handoff_bytes_recv",  # wire bytes delivered
        "router/handoff_wasted_bytes",  # wire bytes received unaddressed
    )


# ------------------------------------------------------------ transport

class HandoffPacket:
    """One request in flight between roles: the JSON-able wire doc
    (``elastic._req_doc`` + slot position + page counts) and the
    device-side gather of its data pages. ``req`` is the live Request
    object — the in-process fast path hands the same object across so
    submit-time identity (trace, timing bases) survives; a
    cross-process transport would rebuild it from ``doc``."""

    __slots__ = ("doc", "kv", "req")

    def __init__(self, doc, kv, req):
        self.doc = doc
        self.kv = kv
        self.req = req

    @property
    def rid(self):
        return self.doc["rid"]


def extract_handoff(pcb, slot_id: int) -> HandoffPacket:
    """Detach ``slot_id`` from a prefill-role engine as a packet: the
    wire doc captures the committed stream + position, the kv tuple is
    a device gather of the pages that hold real rows (``pos`` of them
    — the tail pages admission allocated for decode headroom carry no
    data and never travel). The slot's pages decref immediately; the
    gathered arrays are independent buffers."""
    cache = pcb.cache
    slot = pcb.slots[slot_id]
    req = slot.request
    pos = slot.pos
    n_data = cache.pages_needed(pos)
    pages = cache.slot_pages(slot_id)
    kv = cache.gather_block_kv(pages[:n_data])
    # t_sent: wall clock (time.time, comparable ACROSS processes —
    # monotonic bases aren't) stamped at extraction; the delivery side
    # observes serving/transport_s against it
    doc = dict(elastic._req_doc(req), pos=int(pos),
               last_tok=int(slot.last_tok), n_data_pages=int(n_data),
               t_sent=time.time())
    req_out, _pos, _last = pcb.export_slot(slot_id)
    # ISSUE 19: export_slot just minted the handoff span — ship it in
    # the wire doc so the RECEIVING rank's handoff_in / transport spans
    # parent onto it across the process boundary (the codec ignores
    # keys it doesn't know, so older peers are unaffected)
    doc["handoff_span"] = getattr(req_out, "_handoff_span", None)
    return HandoffPacket(doc, kv, req_out)


def deliver_handoff(dcb, packet: HandoffPacket,
                    dedupe: bool = True) -> Optional[int]:
    """Land a packet on a decode-role engine: allocate the request's
    full page set through the refcounted allocator (``admit_prefix``
    when the engine's prefix index is on — full prompt pages the index
    already holds are RE-SHARED with an incref instead of copied, the
    cross-request sharing a colocated prefix cache would have kept),
    scatter the transported bytes into the fresh blocks, register the
    prompt pages for future dedupe, and adopt the slot. Returns the
    slot id, or None (nothing allocated) when no free slot or the pool
    cannot cover the fresh pages — the router keeps the packet queued.
    """
    free = [i for i, s in enumerate(dcb.slots) if not s.active]
    if not free:
        return None
    slot_id = free[0]
    doc = packet.doc
    prompt_np = np.asarray(doc["prompt"], np.int32)  # sync-ok: wire doc
    total = len(prompt_np) + int(doc["max_new_tokens"]) \
        + len(doc["generated"]) - 1
    # capacity mirrors what a colocated admission of the ORIGINAL
    # request reserved: prompt + max_new rows (generated rows beyond
    # the first token are already appended — pos covers them)
    total = max(total, int(doc["pos"]) + 1)
    n_data = int(doc["n_data_pages"])
    shared = 0
    cache = dcb.cache
    plan = None
    if dedupe and dcb.prefix_cache:
        plan = cache.admit_prefix(slot_id, prompt_np, total, cow=False)
        if plan is None:
            return None
        pages = plan.pages
        shared = plan.start_pos // cache.spec.page_size
    else:
        pages = cache.admit(slot_id, total)
        if pages is None:
            return None
    # From here pages are ADMITTED (allocated/increffed into slot_id's
    # table): any failure before adoption completes must UNWIND the
    # admission — decref the pages and clear the slot — or the pool
    # leaks them until restart (the PR-14 review bug, ISSUE 15
    # satellite). The ``serving_deliver`` fault point models the
    # delivery side dying right inside that window. Prefix
    # registration happens only AFTER the scatter wrote the blocks, so
    # an unwound delivery can never leave index entries pointing at
    # never-written pages.
    t_land = time.monotonic()
    try:
        faults.fire("serving_deliver", rid=packet.rid, slot=slot_id)
        # one scatter per pool component writes the non-shared data
        # pages
        cache.scatter_block_kv(pages[shared:n_data], packet.kv,
                               src_offset=shared)
        if plan is not None:
            cache.register_prefix(slot_id, prompt_np, hashes=plan.hashes)
        req = packet.req if packet.req is not None \
            else elastic.resume_request(doc)
        # span parents off the wire (ISSUE 19): a rebuilt request lost
        # its in-process attributes — restore the handoff/encode span
        # ids the doc carried so adopt_request parents correctly
        if getattr(req, "_handoff_span", None) is None \
                and doc.get("handoff_span"):
            req._handoff_span = doc["handoff_span"]
        if getattr(req, "_encode_span", None) is None \
                and doc.get("encode_span"):
            req._encode_span = doc["encode_span"]
        dcb.adopt_request(slot_id, req, int(doc["pos"]),
                          int(doc["last_tok"]))
        # the landing segment of the transport: scatter + adopt on the
        # receiver, monotonic (single-process span)
        dcb.metrics.histogram("serving/transport_decode_s").observe(
            time.monotonic() - t_land)
        if doc.get("t_sent") is not None:
            # the wire/move segment of the handoff: extraction stamp to
            # adoption, wall clock so it survives the process boundary
            dcb.metrics.histogram("serving/transport_s").observe(
                max(time.time() - float(doc["t_sent"]), 0.0))  # sync-ok: wall clock
    except BaseException:
        cache.release(slot_id)
        slot = dcb.slots[slot_id]
        slot.request, slot.pos, slot.last_tok = None, -1, 0
        raise
    return slot_id


# --------------------------------------------------------------- router

class DisaggRouter:
    """See module docstring. Build directly from engine lists, or from
    a config through :func:`deepspeed_tpu.serving.build_router`."""

    def __init__(self, prefill_engines, decode_engines,
                 registry: Optional[MetricsRegistry] = None,
                 recorder=None, prefix_routing: bool = True,
                 dedupe_pages: bool = True, queue_weight: float = 1.0,
                 ttft_weight: float = 1.0, ttft_window: int = 16,
                 max_handoff_retries: int = 3, decode_tick_cap: int = 4,
                 max_inflight_pages: Optional[int] = None,
                 decode_schedule: str = "lpt"):
        assert prefill_engines, "need at least one prefill-role engine"
        self.prefill_engines = list(prefill_engines)
        self.decode_engines = list(decode_engines)
        self.colocated = not self.decode_engines
        for i, cb in enumerate(self.prefill_engines):
            if cb.replica_id is None:
                cb.replica_id = f"prefill{i}" if not self.colocated \
                    else f"colo{i}"
        for i, cb in enumerate(self.decode_engines):
            if cb.replica_id is None:
                cb.replica_id = f"decode{i}"
        if not self.colocated:
            for cb in self.prefill_engines:
                assert cb.role == "prefill", \
                    "disaggregated mode needs prefill-role engines"
            for cb in self.decode_engines:
                assert cb.role in ("decode", "both")
        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        self.recorder = recorder if recorder is not None \
            else default_recorder()
        self.prefix_routing = bool(prefix_routing)
        self.dedupe_pages = bool(dedupe_pages)
        self.queue_weight = float(queue_weight)   # sync-ok: config
        self.ttft_weight = float(ttft_weight)     # sync-ok: config
        self.ttft_window = int(ttft_window)
        self.max_handoff_retries = int(max_handoff_retries)
        self.decode_tick_cap = int(decode_tick_cap)
        assert decode_schedule in ("lpt", "fifo"), decode_schedule
        self.decode_schedule = decode_schedule
        # decode-side backpressure: the KV pages held by extracted-but-
        # undelivered packets are device memory OUTSIDE every pool, so
        # they must be bounded — default 2x the decode pools' total
        # allocatable pages (an exhausted decode pool under a sustained
        # backlog queues prompts AT THE ROUTER, never mid-flight).
        # Reserving per-request pages instead would double-count: a
        # waiting packet claims no pool pages until a slot (and with it
        # its previous occupant's pages) frees.
        alloc = sum(cb.cache.num_blocks - 1 for cb in self.decode_engines)
        self.max_inflight_pages = int(max_inflight_pages) \
            if max_inflight_pages is not None else 2 * alloc
        self.queue: deque = deque()
        self._packets: deque = deque()
        # handoff-crash replay state lives in the PACKET's wire doc
        # (unlike ReplicaPool there is no whole-replica loss to
        # re-serve from a submit-time ledger)
        self._attempts: Dict[Any, int] = {}
        self._block_latched = False   # one decode_blocked per episode
        self.done: Dict[Any, Request] = {}
        self.lost: Dict[Any, dict] = {}
        self._host_rng = np.random.RandomState(0)
        self.stats = {"routed": 0, "prefix_routed": 0, "slo_routed": 0,
                      "handoffs": 0, "handoff_requeues": 0,
                      "decode_blocked": 0, "lost": 0}

    # ------------------------------------------------------------ intake

    def submit(self, request: Request) -> None:
        ensure_trace_id(request)
        if request.temperature and request.temperature > 0 \
                and request.sample_key is None:
            # stamped BEFORE the ledger doc freezes, so a handoff-crash
            # replay of a sampled request keeps its key (the engine's
            # own stamp would come too late for the router ledger)
            request.sample_key = int(
                self._host_rng.randint(0, 2 ** 31 - 1))  # sync-ok: host
        if not self.colocated:
            # feasibility: a request no decode pool could EVER hold
            # would orbit as an undeliverable packet forever
            S = int(np.asarray(request.prompt).shape[0])  # sync-ok: host
            need = self.decode_engines[0].cache.pages_needed(
                S + request.max_new_tokens)
            assert any(need <= cb.cache.num_blocks - 1
                       for cb in self.decode_engines), (
                f"request {request.rid!r} needs {need} pages but no "
                f"decode pool can hold that many")
        if getattr(request, "_t_arrived", None) is None:
            # TTFT/queue-wait reference = ROUTER entry (run() pre-stamps
            # wall-clock arrivals; the engine's own submit stamp would
            # start the clock only after routing)
            request._t_arrived = time.monotonic()
        self._attempts.setdefault(request.rid, 0)
        self.queue.append(request)
        self.metrics.gauge("router/queue_depth").set(len(self.queue))

    @property
    def pending(self) -> int:
        n = len(self.queue) + len(self._packets)
        for cb in self.prefill_engines + self.decode_engines:
            n += cb.pending
        return n

    # -------------------------------------------------------- scheduling

    def _ttft_tail(self, cb) -> float:
        vals = cb.metrics.peek_histogram_values("serving/ttft_s")
        if not vals:
            return 0.0
        tail = vals[-self.ttft_window:]
        return float(sum(tail) / len(tail))   # sync-ok: host reservoir

    def _route_prefill(self, prompt_np):
        """(engine index, reason): longest resident prefix chain wins
        (locality — the hit skips that span's prefill compute); ties
        and cold prompts go to the best live SLO score."""
        if self.prefix_routing and len(self.prefill_engines) >= 1:
            best, best_hit = None, 0
            for i, cb in enumerate(self.prefill_engines):
                if not cb.prefix_cache:
                    continue
                hit = cb.cache.match_prefix(prompt_np,
                                            cow=False).start_pos
                if hit > best_hit:
                    best, best_hit = i, hit
            if best is not None:
                return best, "prefix"
        scores = []
        for i, cb in enumerate(self.prefill_engines):
            load = len(cb.queue) + sum(s.active for s in cb.slots)
            scores.append(self.queue_weight * load
                          + self.ttft_weight * self._ttft_tail(cb))
        return int(np.argmin(scores)), "slo"   # sync-ok: host scores

    def _inflight_pages(self) -> int:
        """KV pages committed to the handoff pipeline but not yet
        absorbed by a decode pool: extracted packets' data pages PLUS
        the prompt pages of everything already routed into a prefill
        engine (queued or prefilling) — those become packets next
        sweep, so the backpressure gate must see them coming."""
        n = sum(p.doc["n_data_pages"] for p in self._packets)
        for pcb in self.prefill_engines:
            for r in pcb.queue:
                n += pcb.cache.pages_needed(
                    int(np.asarray(r.prompt).shape[0]))  # sync-ok: host
            for s in pcb.slots:
                if s.active:
                    n += pcb.cache.pages_needed(max(s.pos, 1))
        return n

    def _route_admissions(self, now):
        while self.queue:
            req = self.queue[0]
            if now is not None and req.arrival_time > now:
                break                  # FIFO against the arrival clock
            prompt_np = np.asarray(req.prompt, np.int32)  # sync-ok: host
            if not self.colocated:
                need = self.decode_engines[0].cache.pages_needed(
                    len(prompt_np))
                inflight = self._inflight_pages()
                if inflight + need > self.max_inflight_pages:
                    # decode-side backpressure: the undelivered handoff
                    # KV is at its bound — the decode pools cannot
                    # absorb more, so the prompt queues AT THE ROUTER
                    # (an admitted request can therefore never hit
                    # pool_exhausted mid-flight; waiting packets claim
                    # no pool pages, so reserving per-request pages
                    # here would double-count against the slots that
                    # will free them). LATCHED per episode — a blocked
                    # head request re-checks every round, and counting/
                    # recording each re-check would flood the bounded
                    # ring at tick rate under sustained pressure.
                    if not self._block_latched:
                        self._block_latched = True
                        self.stats["decode_blocked"] += 1
                        self.metrics.counter(
                            "router/decode_blocked").inc()
                        self.recorder.record(
                            "router_block", rid=req.rid,
                            trace=req.trace_id, need_pages=need,
                            inflight_pages=inflight,
                            queue_depth=len(self.queue))
                    break
            self._block_latched = False   # an admission re-arms
            pidx, reason = self._route_prefill(prompt_np)
            self.queue.popleft()
            self.stats["routed"] += 1
            self.stats[f"{reason}_routed"] += 1
            self.metrics.counter(f"router/{reason}_routed").inc()
            self.recorder.record(
                "router_route", rid=req.rid, trace=req.trace_id,
                engine=self.prefill_engines[pidx].replica_id,
                reason=reason)
            self.prefill_engines[pidx].submit(req)
        self.metrics.gauge("router/queue_depth").set(len(self.queue))

    # ----------------------------------------------------------- handoff

    def _requeue_lost_packet(self, packet, error) -> None:
        """The transport died between extract and deliver: the gathered
        bytes are gone, but the wire doc survives — replay the request
        through prefill (committed stream as prompt), bounded."""
        rid = packet.rid
        self.stats["handoff_requeues"] += 1
        self.metrics.counter("router/handoff_requeues").inc()
        self._attempts[rid] = self._attempts.get(rid, 0) + 1
        if self._attempts[rid] > self.max_handoff_retries:
            self.stats["lost"] += 1
            self.lost[rid] = packet.doc
            self.recorder.record(
                "serving_requeue", rid=rid,
                trace=packet.doc.get("trace_id"), outcome="dropped",
                attempts=self._attempts[rid])
            logger.warning(f"request {rid!r} dropped after "
                           f"{self._attempts[rid] - 1} handoff retries")
            return
        replay = elastic.resume_request(packet.doc)
        self.recorder.record(
            "serving_requeue", rid=rid,
            trace=packet.doc.get("trace_id"), outcome="scheduled",
            attempts=self._attempts[rid],
            committed=len(packet.doc["generated"]))
        logger.warning(f"handoff of {rid!r} failed ({error}); "
                       f"replaying from the committed stream")
        self.queue.appendleft(replay)

    def _sweep_handoffs(self) -> None:
        """Every active slot on a prefill-role engine is handoff-ready
        (its prefill ran at admission). Extract each into a packet;
        the ``serving_handoff`` fault point models the transport dying
        with the bytes in flight."""
        for pcb in self.prefill_engines:
            for slot_id, slot in enumerate(pcb.slots):
                if not slot.active:
                    continue
                packet = extract_handoff(pcb, slot_id)
                try:
                    faults.fire("serving_handoff", rid=packet.rid)
                except faults.SimulatedCrash as e:
                    self._requeue_lost_packet(packet, e)
                    continue
                # in-process, "bytes on the wire" = the payload the
                # gather materialized (data pages x per-block bytes);
                # the cross-process transport counts encoded frame
                # lengths instead and recv == sent holds either way
                self.metrics.counter("router/handoff_bytes_sent").inc(
                    packet.doc["n_data_pages"] * pcb.cache.page_nbytes)
                self._packets.append(packet)
        self._note_inflight()

    def _note_inflight(self):
        self.metrics.gauge("router/inflight_packets").set(
            len(self._packets))
        self.metrics.gauge("router/inflight_pages").set(
            self._inflight_pages())

    def _deliver_packets(self) -> None:
        if self.decode_schedule == "lpt" and len(self._packets) > 1:
            # longest-remaining-first: the router's scheduling freedom
            # — first tokens are already delivered, so reordering the
            # DECODE start order trades nothing on TTFT and the LPT
            # rule packs the slot makespan tighter (long decodes start
            # early instead of draining solo at the tail). Under a
            # sustained overload this favors long requests' completion;
            # decode_schedule="fifo" restores arrival order.
            self._packets = deque(sorted(
                self._packets, key=lambda p:
                -(p.doc["max_new_tokens"] - len(p.doc["generated"]))))
        still = deque()
        while self._packets:
            packet = self._packets.popleft()
            order = sorted(
                range(len(self.decode_engines)), key=lambda i:
                -self.decode_engines[i].cache.available_pages)
            slot = None
            crashed = None
            for di in order:
                # the serving_deliver fault point (ISSUE 15 satellite)
                # fires INSIDE delivery, after the decode pool admitted
                # the packet's pages — deliver_handoff unwinds the
                # admission before re-raising, so the pool cannot leak;
                # the router replays the request from its wire doc like
                # a transport crash (the gathered bytes are suspect)
                try:
                    slot = deliver_handoff(self.decode_engines[di],
                                           packet,
                                           dedupe=self.dedupe_pages)
                except faults.SimulatedCrash as e:
                    crashed = e
                    break
                if slot is not None:
                    self.stats["handoffs"] += 1
                    self.metrics.counter("router/handoffs").inc()
                    self.metrics.counter(
                        "router/handoff_bytes_recv").inc(
                        packet.doc["n_data_pages"]
                        * self.decode_engines[di].cache.page_nbytes)
                    break
            if crashed is not None:
                self._requeue_lost_packet(packet, crashed)
            elif slot is None:
                still.append(packet)   # waiting on a decode slot/pages
        self._packets = still
        self._note_inflight()

    # -------------------------------------------------------------- step

    def step(self, now: Optional[float] = None) -> List[Request]:
        """One router round: route due prompts, step the prefill
        engines (admission + prefill), sweep/deliver handoffs, then
        step the decode engines (ticks). Returns requests finished
        this round across every engine."""
        self._route_admissions(now)
        finished: List[Request] = []
        for pcb in self.prefill_engines:
            finished.extend(pcb.step())
        if not self.colocated:
            self._sweep_handoffs()
            self._deliver_packets()
        # short decode ticks only while PROMPT work is pending (router
        # queue / prefill engines) so prefills interleave; packets
        # waiting on a decode SLOT don't need short ticks — slots free
        # at finishes, which long ticks reach with less dispatch
        # overhead
        busy = (bool(self.queue) or any(
            cb.queue or any(s.active for s in cb.slots)
            for cb in self.prefill_engines)) if not self.colocated \
            else False
        for dcb in self.decode_engines:
            dcb.tick_step_cap = self.decode_tick_cap if busy else None
            if any(s.active for s in dcb.slots) or dcb.queue:
                finished.extend(dcb.step())
        if self._packets:
            # second chance: slots this round's ticks just freed take
            # waiting packets NOW instead of idling until next round
            self._deliver_packets()
        for req in finished:
            self.done[req.rid] = req
        return finished

    def run(self, requests, respect_arrival_times: bool = False,
            timeout_s: Optional[float] = None) -> Dict[Any, Request]:
        """Serve every request to completion (or loss) — the
        disaggregated ``serve()``. Arrival semantics match the single
        engine's: with ``respect_arrival_times`` a request becomes
        routable at its ``arrival_time`` against a wall clock started
        on entry (and TTFT is measured from that arrival)."""
        todo = deque(sorted(requests, key=lambda r: r.arrival_time))
        t0 = time.monotonic()
        if respect_arrival_times:
            for r in todo:
                r._t_arrived = t0 + r.arrival_time
        else:
            while todo:
                self.submit(todo.popleft())
        while True:
            now = time.monotonic() - t0
            while todo and todo[0].arrival_time <= now:
                self.submit(todo.popleft())
            if not todo and not self.pending:
                break
            if timeout_s is not None and now > timeout_s:
                logger.warning(f"router run timed out with "
                               f"{self.pending} pending")
                break
            stepped = self.step(now if respect_arrival_times else None)
            if not stepped and not any(
                    any(s.active for s in cb.slots) or cb.queue
                    for cb in self.prefill_engines
                    + self.decode_engines):
                time.sleep(0.002)      # waiting on arrivals
        return dict(self.done)

    # --------------------------------------------------------- telemetry

    def metrics_snapshot(self) -> Dict[str, Any]:
        """Router + per-role aggregation (the document the serving
        bench embeds): merged TTFT/breakdown percentiles over the
        prefill engines' raw reservoirs, per-engine role rows, the
        reservation/queue state and the handoff counters."""
        from deepspeed_tpu.serving.replica_pool import (
            merged_reservoir as merged, percentile_summary as pct)
        pe = self.prefill_engines
        de = self.decode_engines
        per_engine = {}
        for cb in pe + de:
            per_engine[cb.replica_id] = {
                "role": cb.role,
                "active_slots": sum(s.active for s in cb.slots),
                "queue_depth": len(cb.queue),
                "page_pool_available": cb.cache.available_pages,
                "handoffs_out": cb.stats["handoffs_out"],
                "handoffs_in": cb.stats["handoffs_in"],
                "decode_tokens": cb.stats["decode_tokens"],
            }
        return {
            "mode": "colocated" if self.colocated else "disaggregated",
            "prefill_engines": len(pe),
            "decode_engines": len(de),
            "queue_depth": len(self.queue),
            "inflight_packets": len(self._packets),
            "inflight_pages": self._inflight_pages(),
            "ttft_s": pct(merged(pe, "serving/ttft_s")),
            "ttft_breakdown": {
                "queue_wait_s": pct(
                    merged(pe, "serving/ttft_queue_wait_s")),
                "prefill_s": pct(merged(pe, "serving/ttft_prefill_s")),
                "handoff_s": pct(merged(de, "serving/handoff_s")),
                "transport_s": pct(merged(de, "serving/transport_s")),
                # ISSUE 18: the transport term split into attributable
                # segments (encode at the sender, the aligned exchange,
                # scatter/adopt at the receiver); in-process delivery
                # observes only the landing segment
                "transport_encode_s": pct(
                    merged(pe, "serving/transport_encode_s")),
                "transport_collective_s": pct(
                    merged(pe + de, "serving/transport_collective_s")),
                "transport_decode_s": pct(
                    merged(de, "serving/transport_decode_s")),
                "first_decode_tick_s": pct(
                    merged(pe + de, "serving/first_decode_tick_s")),
            },
            "per_engine": per_engine,
            "done": len(self.done),
            # "lost" rides self.stats (kept in lockstep with the
            # self.lost dict by _requeue_lost_packet — one source)
            **self.stats,
        }

    def close(self) -> None:
        for cb in self.prefill_engines + self.decode_engines:
            if cb.elastic is not None:
                cb.elastic.release()
