"""Continuous-batching scheduler over the paged KV cache.

The static serving path (`models/gpt2_inference.generate`) runs one
batch per call: every request shares the prompt pass, pads to the
longest sequence, and the whole batch drains before any new request
starts. Here the batch is a set of SLOTS that requests flow through
independently:

- a request is admitted into any free slot the moment enough pool pages
  are free for ``prompt + max_new_tokens``; its prompt prefills into its
  own pages while other slots keep decoding;
- every scheduler step runs ONE compiled decode tick over all slots
  (idle slots masked by pos < 0); a slot that hits EOS/max_new frees its
  pages immediately and the next queued request takes it on the same
  step — the chip never waits for the slowest request in a gang.

The device work per step is one fixed-shape donated-pool program (plus
one bucketed prefill per admission), so any arrival pattern replays a
small fixed set of executables — the restructuring that turns mixed
traffic from serialized batches into interleaved independent work (the
fused computation-collective argument applied to prefill/decode).
"""

import dataclasses
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import jax.numpy as jnp

from deepspeed_tpu.runtime.elastic import faults
from deepspeed_tpu.serving.paged_cache import (PagedKVCache,
                                               padded_prefill_inputs,
                                               pow2_page_bucket)
from deepspeed_tpu.telemetry.recorder import default_recorder
from deepspeed_tpu.telemetry.registry import MetricsRegistry
from deepspeed_tpu.telemetry.spans import new_span_id


@dataclasses.dataclass
class Request:
    """One generation request. ``arrival_time`` is seconds relative to
    the serve() clock (0 = already queued); requests become admissible
    only once arrived."""
    rid: Any
    prompt: Any                       # [S] int array-like
    max_new_tokens: int = 16
    eos_token_id: Optional[int] = None
    temperature: float = 0.0
    arrival_time: float = 0.0
    # request-scoped distributed tracing (ISSUE 12): stamped once at
    # first submit, carried through every lifecycle ring event and
    # across snapshot -> restore -> requeue replica handoffs, so
    # telemetry/view.py can stitch one cross-replica timeline per
    # request from N dump files. Never re-stamped: a replayed or
    # restored request keeps the identity it was born with.
    trace_id: Optional[str] = None
    # ISSUE 19: the request's ROOT span id, minted next to trace_id at
    # first submit and persisted through the same snapshot / restore /
    # handoff docs. Every lifecycle span (prefill, handoff, transport
    # legs, first decode tick) parents onto it — directly or through an
    # intermediate span — so N per-role dump files merge into ONE
    # causal tree per trace_id (telemetry/perfetto.py).
    span_id: Optional[str] = None
    # ISSUE 14: per-request sampling identity (temperature > 0 only).
    # Stamped once at first submit and persisted through snapshot /
    # restore / handoff docs; every sampled token's key is
    # fold_in(sample_key, global_token_index), so replays regenerate
    # the identical sampled stream instead of drawing fresh rng.
    sample_key: Optional[int] = None
    # filled by the engine:
    generated: List[int] = dataclasses.field(default_factory=list)
    finish_reason: Optional[str] = None

    def tokens(self) -> np.ndarray:
        return np.concatenate([          # sync-ok: host-side lists
            np.asarray(self.prompt, np.int32),
            np.asarray(self.generated, np.int32)])  # sync-ok: host


def ensure_trace_id(request) -> str:
    """Stamp a stable ``trace_id`` at first submit (idempotent — a
    restored/replayed request arrives with the one it was born with).
    ISSUE 19: the root ``span_id`` is minted here too, under the same
    never-re-stamped contract — it is the anchor every downstream
    lifecycle span parents onto."""
    if getattr(request, "trace_id", None) is None:
        request.trace_id = uuid.uuid4().hex[:16]
    if getattr(request, "span_id", None) is None:
        from deepspeed_tpu.telemetry.spans import new_span_id
        request.span_id = new_span_id()
    return request.trace_id


@dataclasses.dataclass
class _Slot:
    request: Optional[Request] = None
    pos: int = -1                     # rows already in cache; -1 = idle
    last_tok: int = 0                 # token to feed on the next tick

    @property
    def active(self) -> bool:
        return self.request is not None


class ContinuousBatcher:
    """Host-side slot scheduler around one adapter's compiled programs.

    Usage::

        engine = serving.build_engine(family="gpt2", model_config=cfg,
                                      params=params, config=ds_config)
        results = engine.serve([Request(0, prompt, max_new_tokens=32)])

    or incrementally: ``submit()`` then ``step()`` until it returns
    everything (each call runs at most one admission sweep + one tick).
    """

    def __init__(self, adapter,
                 registry: Optional[MetricsRegistry] = None,
                 recorder=None, watchdog=None, prefix_cache: bool = False,
                 prefix_cow: bool = True, drafter=None,
                 spec_tokens: int = 3, role: str = "both"):
        self.adapter = adapter
        self.spec = adapter.spec
        self.cache: PagedKVCache = adapter.make_cache()
        # ISSUE 14 (disaggregation): a "prefill"-role engine admits and
        # prefills but NEVER runs a decode program — its active slots
        # are handoff candidates the router exports; a "decode"-role
        # engine only receives handoffs (its queue stays empty). "both"
        # is the colocated engine every pre-disagg config builds.
        assert role in ("both", "prefill", "decode"), role
        self.role = role
        assert not (role == "prefill" and drafter is not None), \
            "a prefill-role engine never decodes — no drafter"
        # ISSUE 9 (a): copy-on-write prefix page sharing — admission
        # consults the refcounted prefix index before allocating, and a
        # hit skips both the pages AND the prefill compute for the
        # shared span (prefill_suffix starts at start_pos)
        self.prefix_cache = bool(prefix_cache)
        self.prefix_cow = bool(prefix_cow)
        if self.prefix_cache:
            self.cache.enable_prefix_sharing()
        # ISSUE 9 (b): speculative decoding — a drafter proposes
        # spec_tokens tokens per round and the target model verifies the
        # whole window in ONE multi-query paged-attention dispatch;
        # greedy accept/reject keeps outputs token-for-token identical
        # to the plain engine (verify is greedy-only: any active sampled
        # request falls the whole step back to the normal tick)
        self.drafter = drafter
        self.spec_tokens = int(spec_tokens)
        self.slots = [_Slot() for _ in range(self.spec.slots)]
        self.queue: deque = deque()
        # sampling is STATELESS per request (fold_in(sample_key, index)
        # — ISSUE 14); the only engine-held rng is the host stream that
        # stamps fresh requests' sample keys at submit
        self._host_rng = np.random.RandomState(0)
        self.last_logits = None       # [slots, V] of the latest tick
        self.stats = {"ticks": 0, "tick_steps": 0, "decode_tokens": 0,
                      "prefills": 0, "prefill_tokens": 0,
                      "spec_rounds": 0, "spec_proposed": 0,
                      "spec_accepted": 0, "prefix_tokens_shared": 0,
                      "prefix_tokens_prompt": 0, "prefix_pages_saved": 0,
                      "handoffs_out": 0, "handoffs_in": 0}
        # per-engine metrics registry (serving/* names) — pass the
        # process-wide default_registry() to merge into one JSONL
        # stream with a training engine. All recording is host-side;
        # the only device readbacks in this scheduler are the token /
        # logits consumptions it already cannot avoid.
        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        # flight recorder (ISSUE 6): request lifecycle events — admit ->
        # prefill -> ticks -> EOS — land in the process-wide ring by
        # default; the optional watchdog (telemetry/anomaly.py)
        # evaluates TTFT-blowup / pool-exhaustion rules at the admission
        # sweep, the one place those values already exist as host
        # scalars (never a new device sync)
        self.recorder = recorder if recorder is not None \
            else default_recorder()
        self.watchdog = watchdog
        self._t_first_decode = None   # engine-lifetime tokens/sec base
        # ISSUE 11: elastic preemption tolerance — an
        # ElasticServingController (serving/elastic.py) attached here
        # runs the drain-or-snapshot policy at every tick end; while it
        # drains, _admitting gates new admissions off so the snapshot
        # set stops growing
        self.elastic = None
        self._admitting = True
        # ISSUE 12: a ReplicaPool stamps its replica id here so ring
        # events self-identify (replicas share the process-wide ring);
        # _t_last_step_ts feeds the /healthz fence age
        self.replica_id = None
        self._t_last_step_ts = None
        self.metrics_server = None
        # ISSUE 14: a router sets this while prompts/handoffs are
        # pending so a decode-role engine's multi-step ticks stay short
        # enough to interleave with prefill work on one host thread
        self.tick_step_cap = None

    def _record(self, kind, **fields):
        """Ring event with the replica identity stamped (ISSUE 12):
        cross-replica trace stitching needs to know which engine
        emitted what when N replicas share one recorder."""
        if self.replica_id is not None and "replica" not in fields:
            fields["replica"] = self.replica_id
        self.recorder.record(kind, **fields)

    @property
    def preempted(self) -> bool:
        """True once the elastic controller finished its
        drain-or-snapshot pass — serve() stops stepping and the
        leftover requests live in the committed snapshot."""
        return self.elastic is not None and self.elastic.preempted

    def attach_elastic(self, controller) -> None:
        self.elastic = controller

    # ----------------------------------------------------------- metrics

    def _note_pool(self) -> None:
        """Record page-pool occupancy (+ high-water mark) — called
        after admissions (the local peak) and after ticks (releases).
        Refcount-0 resident prefix-cache pages count as CACHED, not
        live — they free on demand under pool pressure."""
        alloc = self.cache.num_blocks - 1
        cached = self.cache.cached_pages
        used = alloc - self.cache.free_pages - cached
        m = self.metrics
        m.gauge("serving/page_pool_used_pages").set(used)
        m.gauge("serving/prefix_cache_pages").set(cached)
        occ = used / max(alloc, 1)
        m.gauge("serving/page_pool_occupancy").set(occ)
        m.gauge("serving/page_pool_occupancy_hwm").set_max(occ)

    def _note_first_decode_tick(self, req, now) -> None:
        """TTFT attribution tail (ISSUE 14): time from first-token
        delivery (prefill readback — or handoff completion on a decode
        engine) to the request's first committed decode-tick token.
        Observed once per request."""
        if getattr(req, "_first_tick_noted", False):
            return
        req._first_tick_noted = True
        base = getattr(req, "_t_handoff_done", None)
        if base is None:
            base = getattr(req, "_t_first_tok", None)
        if base is not None:
            self.metrics.histogram(
                "serving/first_decode_tick_s").observe(
                max(now - base, 0.0))

    def metrics_snapshot(self) -> Dict[str, Any]:
        """One JSON-able dict of the serving observables: queue depth,
        admission wait, time-to-first-token, per-tick decode latency,
        tokens/sec, slot utilization, page-pool occupancy (+ HWM), and
        the watchdog state — a monotonic ``dump_id`` plus the last
        anomaly (ISSUE 6 satellite; 0/None when no watchdog is
        attached)."""
        snap = self.metrics.snapshot()
        hists = snap["histograms"]
        gauges = snap["gauges"]
        now = time.monotonic()
        lifetime = (now - self._t_first_decode) \
            if self._t_first_decode is not None else 0.0
        alloc = self.cache.num_blocks - 1
        st = self.stats
        prompt_toks = st["prefix_tokens_prompt"]
        return {
            "role": self.role,
            "queue_depth": len(self.queue),
            "active_slots": sum(s.active for s in self.slots),
            "slots": len(self.slots),
            "page_pool": {
                "allocatable_pages": alloc,
                "used_pages": alloc - self.cache.free_pages
                - self.cache.cached_pages,
                "prefix_cached_pages": self.cache.cached_pages,
                "occupancy": gauges.get("serving/page_pool_occupancy", 0.0),
                "occupancy_hwm": gauges.get(
                    "serving/page_pool_occupancy_hwm", 0.0),
            },
            "prefix_cache": {
                "enabled": self.prefix_cache,
                # token-level hit rate: shared prompt tokens (skipped
                # prefill compute AND skipped page writes) over all
                # prompt tokens admitted
                "hit_rate": (st["prefix_tokens_shared"] / prompt_toks)
                if prompt_toks else 0.0,
                "pages_saved": st["prefix_pages_saved"],
                **({k: v for k, v in self.cache.prefix_stats.items()}
                   if self.prefix_cache else {}),
            },
            "speculative": {
                "enabled": self.drafter is not None,
                "rounds": st["spec_rounds"],
                "proposed": st["spec_proposed"],
                "accepted": st["spec_accepted"],
                "accept_rate": (st["spec_accepted"] / st["spec_proposed"])
                if st["spec_proposed"] else 0.0,
            },
            "admission_wait_s": hists.get("serving/admission_wait_s",
                                          {"count": 0}),
            "ttft_s": hists.get("serving/ttft_s", {"count": 0}),
            # TTFT attribution (ISSUE 14 satellite): the head-of-line
            # gap decomposed — queue-wait + prefill sum to ttft_s;
            # handoff + first-decode-tick are the post-first-token path
            # a disaggregated request additionally crosses
            "ttft_breakdown": {
                "queue_wait_s": hists.get("serving/ttft_queue_wait_s",
                                          {"count": 0}),
                "prefill_s": hists.get("serving/ttft_prefill_s",
                                       {"count": 0}),
                "handoff_s": hists.get("serving/handoff_s",
                                       {"count": 0}),
                "transport_s": hists.get("serving/transport_s",
                                         {"count": 0}),
                "transport_encode_s": hists.get(
                    "serving/transport_encode_s", {"count": 0}),
                "transport_collective_s": hists.get(
                    "serving/transport_collective_s", {"count": 0}),
                "transport_decode_s": hists.get(
                    "serving/transport_decode_s", {"count": 0}),
                "first_decode_tick_s": hists.get(
                    "serving/first_decode_tick_s", {"count": 0}),
            },
            "tick_latency_s": hists.get("serving/tick_latency_s",
                                        {"count": 0}),
            "decode_latency_per_token_s": hists.get(
                "serving/decode_latency_per_token_s", {"count": 0}),
            "slot_utilization": hists.get("serving/slot_utilization",
                                          {"count": 0}),
            "decode_tokens_per_sec": (self.stats["decode_tokens"] / lifetime)
            if lifetime > 0 else 0.0,
            "dump_id": self.watchdog.dump_id
            if self.watchdog is not None else 0,
            "last_anomaly": self.watchdog.last_anomaly
            if self.watchdog is not None else None,
            "watchdog": self.watchdog.snapshot()
            if self.watchdog is not None else None,
            **self.stats,
        }

    # ------------------------------------------------------------- queue

    def submit(self, request: Request) -> None:
        S = int(np.asarray(request.prompt).shape[0])  # sync-ok: host prompt
        assert S >= 1, "empty prompt"
        # prefill unconditionally samples the first token, so a zero
        # budget would still emit one — reject instead of over-serving
        assert request.max_new_tokens >= 1, (
            f"max_new_tokens must be >= 1, got {request.max_new_tokens}")
        total = S + request.max_new_tokens
        # every decoded position needs a real learned position — past
        # the model budget the wpe gather would clamp and silently
        # corrupt (same contract as the dense generate() paths)
        assert total <= self.adapter.max_prompt_len(), (
            f"prompt {S} + max_new_tokens {request.max_new_tokens} "
            f"exceeds the model's position budget "
            f"{self.adapter.max_prompt_len()}")
        cap = self.spec.max_tokens_per_slot()
        assert total <= cap, (
            f"prompt {S} + max_new_tokens {request.max_new_tokens} "
            f"exceeds the per-slot page capacity {cap} "
            f"(max_pages_per_slot {self.spec.max_pages_per_slot} x "
            f"page_size {self.spec.page_size})")
        # an oversubscribed pool (num_blocks set low) must still be able
        # to hold this request once everything else drains — otherwise
        # FIFO admission would wait on it forever
        assert self.cache.pages_needed(total) <= self.cache.num_blocks - 1, (
            f"request needs {self.cache.pages_needed(total)} pages but "
            f"the whole pool has {self.cache.num_blocks - 1} allocatable "
            f"blocks (serving.num_blocks)")
        # the prefill bucket pads the prompt to WHOLE pages, so the
        # prompt must fit the model's position budget in page units —
        # with a page size that doesn't divide it, the last partial
        # page is unusable for prompts (admission would otherwise
        # allocate pages and then crash inside prefill)
        max_prompt_pages = self.adapter.max_prompt_len() \
            // self.spec.page_size
        assert self.cache.pages_needed(S) <= max_prompt_pages, (
            f"prompt {S} needs {self.cache.pages_needed(S)} pages but "
            f"only {max_prompt_pages} whole pages of "
            f"{self.spec.page_size} fit the model's "
            f"{self.adapter.max_prompt_len()}-position budget")
        ensure_trace_id(request)
        if request.temperature and request.temperature > 0 \
                and request.sample_key is None:
            # per-request sampling identity (idempotent: a restored /
            # replayed request arrives with the key it was born with)
            request.sample_key = int(
                self._host_rng.randint(0, 2 ** 31 - 1))  # sync-ok: host
        request._t_submit = time.monotonic()
        self.queue.append(request)
        self.metrics.gauge("serving/queue_depth").set(len(self.queue))

    @property
    def pending(self) -> int:
        return len(self.queue) + sum(s.active for s in self.slots)

    # --------------------------------------------------------- admission

    def _bucket_count(self, need: int) -> int:
        """pow2_page_bucket against the position budget (the full
        prefill path buckets inside padded_prefill_inputs; the
        suffix/prefix prefill buckets here). submit() guarantees the
        prompt itself fits in whole pages, so the clamp only trims
        pad."""
        return pow2_page_bucket(
            need, self.adapter.max_prompt_len() // self.spec.page_size)

    @staticmethod
    def _sample_base(req) -> int:
        """Global token index of the request's FIRST not-yet-sampled
        token minus len(generated): tokens committed in previous
        incarnations (folded into a replay prompt) shift the sampling
        index so a restored request keeps drawing the same stream."""
        return int(getattr(req, "resumed_committed", 0) or 0)

    def _pick_token(self, logits: np.ndarray, req: Request) -> int:
        if req.temperature and req.temperature > 0:
            from deepspeed_tpu.serving.adapters import sample_token
            idx = self._sample_base(req) + len(req.generated)
            return sample_token(logits, req.sample_key or 0, idx,
                                req.temperature)
        return int(np.argmax(logits))

    def _admit(self, now: Optional[float]) -> List[Request]:
        finished = []
        free = [i for i, s in enumerate(self.slots) if not s.active]
        while free and self.queue:
            req = self.queue[0]
            if now is not None and req.arrival_time > now:
                break                 # FIFO: don't skip ahead of arrivals
            prompt_np = np.asarray(req.prompt, np.int32)  # sync-ok: host prompt
            S = int(prompt_np.shape[0])
            slot_id = free[0]
            plan = None
            if self.prefix_cache:
                plan = self.cache.admit_prefix(
                    slot_id, prompt_np, S + req.max_new_tokens,
                    cow=self.prefix_cow)
                pages = plan.pages if plan is not None else None
            else:
                pages = self.cache.admit(slot_id, S + req.max_new_tokens)
            if pages is None:
                # pool exhausted; retry next step. The watchdog rule is
                # latched per episode — one dump until pages free again
                need = self.cache.pages_needed(S + req.max_new_tokens)
                self._record(
                    "pool_exhausted", rid=req.rid,
                    trace=getattr(req, "trace_id", None), need_pages=need,
                    free_pages=self.cache.available_pages,
                    queue_depth=len(self.queue),
                    parent_span=getattr(req, "span_id", None))
                if self.watchdog is not None:
                    self.watchdog.note_pool_exhausted(
                        queue_depth=len(self.queue),
                        free_pages=self.cache.available_pages,
                        need_pages=need)
                break
            self.queue.popleft()
            free.pop(0)
            # fault point (ISSUE 11): pages are allocated, nothing is
            # prefilled yet — a replica dying HERE models the
            # mid-prefill crash the pool recovery tests drive
            faults.fire("serving_admit", rid=req.rid, slot=slot_id)
            t_admit = time.monotonic()
            # wait since the request became ADMISSIBLE (its arrival
            # under respect_arrival_times, its submit otherwise)
            t_ref = getattr(req, "_t_arrived", None)
            if t_ref is None:
                t_ref = getattr(req, "_t_submit", t_admit)
            wait_s = max(t_admit - t_ref, 0.0)
            self.metrics.histogram("serving/admission_wait_s").observe(
                wait_s)
            # TTFT attribution (ISSUE 14 satellite): queue-wait ends
            # here, the prefill component starts — the two sum to the
            # colocated ttft_s; handoff / first-decode-tick components
            # land later (zero on a colocated engine's TTFT)
            self.metrics.histogram("serving/ttft_queue_wait_s").observe(
                wait_s)
            t_pf0 = time.monotonic()
            start = plan.start_pos if plan is not None else 0
            # the admit event IS the request's root span (ISSUE 19):
            # span_id = the id minted at first submit, no parent — every
            # downstream lifecycle span in any rank's dump parents onto
            # it, so the merged export has zero orphans by construction
            self._record("admit", rid=req.rid, slot=slot_id,
                         trace=getattr(req, "trace_id", None),
                         pages=len(pages), wait_s=wait_s,
                         shared_tokens=start,
                         span_id=getattr(req, "span_id", None))
            if self.watchdog is not None:
                self.watchdog.note_pool_ok()   # re-arm the pool rule
            P = self.spec.page_size
            if plan is not None and plan.cow is not None:
                # COW: the matched rows of the partially-filled prefix
                # page are device-copied into this slot's own page; the
                # suffix prefill continues writing mid-page. (With
                # prefix_cow off the cache never matches partial pages,
                # so plan.cow is None by construction.)
                src, dst, _rows = plan.cow
                self.cache.pool = self.adapter.copy_block(
                    self.cache.pool, src, dst)
            if start > 0:
                # prefix hit: prefill ONLY the suffix — the shared
                # span's K/V is already resident through the page table
                suf_len = S - start
                n_pre = min(self._bucket_count(-(-start // P)),
                            self.spec.max_pages_per_slot)
                # same pow2 page bucket + zero-pad contract as the full
                # prefill (the page_vec is unused — prefill_suffix reads
                # through the slot's page-table row)
                ids, _ = padded_prefill_inputs(
                    prompt_np[start:], [], P,
                    self.adapter.max_prompt_len() // P)
                pool, logits = self.adapter.prefill_suffix(
                    self.cache.pool, jnp.asarray(ids), S, start, n_pre,
                    self.cache.page_table[slot_id])
                self.stats["prefill_tokens"] += suf_len
            else:
                ids, page_vec = padded_prefill_inputs(
                    prompt_np, pages, P,
                    self.adapter.max_prompt_len() // P)
                pool, logits = self.adapter.prefill(
                    self.cache.pool, jnp.asarray(ids),
                    jnp.asarray(S, jnp.int32), jnp.asarray(page_vec))
                self.stats["prefill_tokens"] += S
            self.cache.pool = pool
            self.stats["prefills"] += 1
            if self.prefix_cache:
                self.cache.register_prefix(
                    slot_id, prompt_np, hashes=plan.hashes)
                n_shared = start // P
                self.stats["prefix_tokens_shared"] += start
                self.stats["prefix_tokens_prompt"] += S
                self.stats["prefix_pages_saved"] += n_shared
                m = self.metrics
                m.counter("serving/prefix_tokens_shared").inc(start)
                m.counter("serving/prefix_tokens_prompt").inc(S)
                m.counter("serving/prefix_pages_saved").inc(n_shared)
            tok = self._pick_token(
                np.asarray(logits, np.float32),  # sync-ok: scheduler
                req)                             # consumes the sample
            req.generated.append(tok)
            # the prefill logits readback above IS first-token delivery
            t_tok = time.monotonic()
            ttft_s = max(t_tok - t_ref, 0.0)
            self.metrics.histogram("serving/ttft_s").observe(ttft_s)
            self.metrics.histogram("serving/ttft_prefill_s").observe(
                max(t_tok - t_pf0, 0.0))
            req._t_first_tok = t_tok   # base for the first-decode-tick
            #                            (and handoff) TTFT components
            self._record("prefill", rid=req.rid,
                         trace=getattr(req, "trace_id", None),
                         prompt_tokens=S, ttft_s=ttft_s,
                         prefill_s=max(t_tok - t_pf0, 0.0),
                         span_id=new_span_id(),
                         parent_span=getattr(req, "span_id", None))
            if self.watchdog is not None:
                # the readback above was the fence — the rule sees only
                # the host scalar it produced
                self.watchdog.observe_ttft(ttft_s, rid=req.rid)
            if self._t_first_decode is None:
                self._t_first_decode = time.monotonic()
            slot = self.slots[slot_id]
            slot.request, slot.pos, slot.last_tok = req, S, tok
            done = self._maybe_finish(slot_id)
            if done is not None:      # max_new_tokens == 1 / instant EOS
                finished.append(done)
                free.insert(0, slot_id)
            elif self.drafter is not None:
                # drafter mirrors the admission (its own prefill for a
                # ModelDrafter, host history for the n-gram fallback)
                self.drafter.admit(slot_id, prompt_np, tok,
                                   S + req.max_new_tokens)
        self.metrics.gauge("serving/queue_depth").set(len(self.queue))
        self._note_pool()
        return finished

    # -------------------------------------------------------------- tick

    def _maybe_finish(self, slot_id: int) -> Optional[Request]:
        slot = self.slots[slot_id]
        req = slot.request
        if req is None:
            return None
        if req.eos_token_id is not None \
                and req.generated[-1] == req.eos_token_id:
            req.finish_reason = "eos"
        elif len(req.generated) >= req.max_new_tokens:
            req.finish_reason = "length"
        else:
            return None
        # with prefix sharing this is a DECREF: shared pages stay
        # resident for other holders (or as refcount-0 prefix cache)
        self.cache.release(slot_id)
        if self.drafter is not None:
            self.drafter.release(slot_id)
        slot.request, slot.pos, slot.last_tok = None, -1, 0
        self._record("finish", rid=req.rid,
                     trace=getattr(req, "trace_id", None),
                     reason=req.finish_reason,
                     generated=len(req.generated),
                     span_id=new_span_id(),
                     parent_span=getattr(req, "span_id", None))
        return req

    # multi-step dispatch caps: a tick of K steps amortizes the host
    # dispatch over K tokens. K = min remaining budget is LOSSLESS (no
    # slot can finish or free pages before that many steps anyway);
    # EOS-capable requests cap K low so an early stop wastes at most
    # max_eos_tick_steps - 1 speculative steps (the appends stay inside
    # the slot's own admitted pages either way).
    max_tick_steps = 32
    max_eos_tick_steps = 4

    def _pick_tick_steps(self) -> int:
        if self.queue and any(not s.active for s in self.slots):
            return 1                  # admission pending — stay responsive
        active = [s.request for s in self.slots if s.active]
        rem = min(r.max_new_tokens - len(r.generated) for r in active)
        cap = self.max_eos_tick_steps if any(
            r.eos_token_id is not None for r in active) \
            else self.max_tick_steps
        if self.tick_step_cap:
            cap = min(cap, self.tick_step_cap)
        k = 1
        while k * 2 <= min(rem, cap):  # pow2 bucket → few compiles
            k *= 2
        return k

    def _tick(self) -> List[Request]:
        steps = self._pick_tick_steps()
        n_active = sum(s.active for s in self.slots)
        toks = np.array([s.last_tok for s in self.slots], np.int32)
        pos = np.array([s.pos if s.active else -1 for s in self.slots],
                       np.int32)
        temps = np.array(
            [s.request.temperature if s.active else 0.0
             for s in self.slots], np.float32)
        # per-slot stateless sampling identity: (request sample_key,
        # global index of the slot's next token) — engine rng state
        # plays no part, so restores/handoffs replay sampled streams
        seeds = np.array(
            [(s.request.sample_key or 0) if s.active else 0
             for s in self.slots], np.uint32)
        idxs = np.array(
            [(self._sample_base(s.request) + len(s.request.generated))
             if s.active else 0 for s in self.slots], np.int32)
        t0 = time.monotonic()
        pool, toks_seq, logits = self.adapter.tick(
            self.cache.pool, jnp.asarray(toks), jnp.asarray(pos),
            jnp.asarray(self.cache.page_table), jnp.asarray(seeds),
            jnp.asarray(idxs), jnp.asarray(temps), steps=steps)
        self.cache.pool = pool
        self.last_logits = logits
        toks_seq = np.asarray(toks_seq)  # sync-ok: scheduler consumes
        #                                  the sampled tokens [steps,slots]
        tick_s = time.monotonic() - t0   # real: the asarray fenced it
        self._record("tick", steps=steps, active=n_active,
                     tick_s=tick_s,
                     traces=[s.request.trace_id for s in self.slots
                             if s.active])
        m = self.metrics
        m.histogram("serving/tick_latency_s").observe(tick_s)
        m.histogram("serving/decode_latency_per_token_s").observe(
            tick_s / max(steps, 1))
        m.histogram("serving/slot_utilization").observe(
            n_active / max(len(self.slots), 1))
        self.stats["ticks"] += 1
        self.stats["tick_steps"] += steps
        finished = []
        tokens_before = self.stats["decode_tokens"]
        t_commit = time.monotonic()
        for i, slot in enumerate(self.slots):
            if not slot.active:
                continue
            self._note_first_decode_tick(slot.request, t_commit)
            for t in range(steps):
                self.stats["decode_tokens"] += 1
                tok = int(toks_seq[t, i])   # sync-ok: host array already
                slot.request.generated.append(tok)
                slot.pos += 1
                slot.last_tok = tok
                done = self._maybe_finish(i)
                if done is not None:
                    # steps past an EOS were speculative; their appends
                    # landed in pages this slot owned until right now
                    finished.append(done)
                    break
        m.counter("serving/decode_tokens").inc(
            self.stats["decode_tokens"] - tokens_before)
        if self.drafter is not None:
            # keep the drafter aligned with the committed stream: a
            # plain tick (sampled slot live / admission pending / 1-token
            # budget) commits tokens the drafter never saw, and a
            # ModelDrafter's KV cache would otherwise hold NO rows for
            # those positions — accept rate silently collapses for the
            # rest of the request. Survivors committed all `steps`
            # tokens (an early EOS releases the slot in the loop above).
            survivors = [i for i in range(len(self.slots))
                         if pos[i] >= 0 and self.slots[i].active]
            if survivors:
                feed = np.vstack([toks[None, :], toks_seq[:-1]])
                self.drafter.observe_plain(survivors, feed, toks_seq)
        self._note_pool()
        return finished

    # ------------------------------------------------------- speculative

    def _pick_verify_rows(self) -> int:
        """Verification window (feed token + drafts): exactly the
        configured window while every active request has budget for it
        (ONE compiled verify program in steady state), pow2-bucketed
        only when the min remaining budget clamps it (O(log) extra
        end-of-request programs) — the appended rows always land inside
        the slot's admitted pages either way."""
        active = [s.request for s in self.slots if s.active]
        rem = min(r.max_new_tokens - len(r.generated) for r in active)
        cap = min(self.spec_tokens + 1, self.max_tick_steps)
        if rem >= cap:
            return cap
        k = 1
        while k * 2 <= rem:
            k *= 2
        return k

    def _spec_tick(self, V: int, active: List[int]) -> List[Request]:
        """One speculative round: draft V-1 tokens per active slot,
        verify the whole window in ONE multi-query dispatch, commit the
        longest greedy-matching prefix (+ the correction token).
        Rollback of rejected drafts is a pointer move — the appended
        rows past the committed position are overwritten by the next
        round's appends and never read (per-slot pos masking)."""
        B = len(self.slots)
        drafts = self.drafter.draft(active, V - 1)        # [n_act, V-1]
        toks = np.zeros((B, V), np.int32)
        toks[:, 0] = [s.last_tok for s in self.slots]
        for row, i in zip(drafts, active):
            toks[i, 1:] = row
        pos = np.array([s.pos if s.active else -1 for s in self.slots],
                       np.int32)
        t0 = time.monotonic()
        pool, greedy, logits = self.adapter.verify(
            self.cache.pool, toks, pos, self.cache.page_table)
        self.cache.pool = pool
        greedy = np.asarray(greedy)   # sync-ok: scheduler consumes the
        #                               verified tokens [B, V]; fences
        #                               the dispatch, so tick_s is real.
        #                               logits stay on device — only one
        #                               row per slot feeds last_logits.
        tick_s = time.monotonic() - t0
        n_active = len(active)
        # fault point (ISSUE 11): the verify dispatch ran but NOTHING is
        # committed yet — a crash here models dying mid-spec-verify;
        # every slot's pos still points at its last committed token, so
        # a snapshot/restore sees only verified tokens
        faults.fire("serving_spec_verify", rows=V, active=n_active)
        self._record("spec_round", rows=V, active=n_active,
                     tick_s=tick_s,
                     traces=[self.slots[i].request.trace_id
                             for i in active])
        m = self.metrics
        m.histogram("serving/tick_latency_s").observe(tick_s)
        m.histogram("serving/slot_utilization").observe(
            n_active / max(B, 1))
        self.stats["ticks"] += 1
        self.stats["tick_steps"] += 1  # one dispatched model step/round
        self.stats["spec_rounds"] += 1
        # drafters that keep their own KV state (ModelDrafter) can only
        # fast-forward through rows they actually appended — the free
        # correction token is dropped in the all-accepted case
        aligned = getattr(self.drafter, "aligned", False)
        finished = []
        tokens_before = self.stats["decode_tokens"]
        last_row = np.zeros(B, np.int32)
        t_commit = time.monotonic()
        for i in active:
            slot = self.slots[i]
            self._note_first_decode_tick(slot.request, t_commit)
            g, d = greedy[i], toks[i]
            a = 0
            while a < V - 1 and d[a + 1] == g[a]:
                a += 1
            ncommit = a + 1
            if aligned:
                ncommit = min(ncommit, V - 1)
            committed = []
            for t in range(ncommit):
                tok = int(g[t])
                self.stats["decode_tokens"] += 1
                slot.request.generated.append(tok)
                slot.pos += 1
                slot.last_tok = tok
                committed.append(tok)
                done = self._maybe_finish(i)
                if done is not None:
                    finished.append(done)
                    break
            self.stats["spec_proposed"] += V - 1
            self.stats["spec_accepted"] += min(a, len(committed))
            last_row[i] = len(committed) - 1
            if slot.active:
                self.drafter.commit(i, committed, slot.pos,
                                    slot.last_tok)
        # device-side gather of each slot's last committed row — the
        # last_logits contract without hauling [B, V, vocab] to host
        self.last_logits = logits[jnp.arange(B), jnp.asarray(last_row)]
        n_committed = self.stats["decode_tokens"] - tokens_before
        m.counter("serving/decode_tokens").inc(n_committed)
        # per-token latency stays live under speculation: one dispatch
        # commits up to V tokens per slot
        m.histogram("serving/decode_latency_per_token_s").observe(
            tick_s / max(n_committed / max(n_active, 1), 1e-9))
        m.counter("serving/spec_proposed").inc(n_active * (V - 1))
        m.gauge("serving/spec_accept_rate").set(
            self.stats["spec_accepted"]
            / max(self.stats["spec_proposed"], 1))
        self._note_pool()
        return finished

    def _decode_step(self) -> List[Request]:
        """One decode dispatch: the speculative round when a drafter is
        attached and every active request is greedy, else the plain
        multi-step tick (speculative verify is greedy-only — sampling
        would need rejection-sampling verification to stay lossless)."""
        if self.drafter is None:
            return self._tick()
        active = [i for i, s in enumerate(self.slots) if s.active]
        if any(self.slots[i].request.temperature > 0 for i in active):
            return self._tick()
        if self.queue and any(not s.active for s in self.slots):
            return self._tick()       # admission pending: 1-step tick
        V = self._pick_verify_rows()
        if V < 2:
            return self._tick()
        return self._spec_tick(V, active)

    # ------------------------------------------------------------- abort

    def abort(self, request_id) -> Optional[Request]:
        """Abort one admitted-or-queued request (ISSUE 11 satellite):
        decref its pages NOW instead of leaking them until EOS, release
        the drafter's mirror state, emit a ``serving_abort`` ring event.
        Returns the request with ``finish_reason="aborted"`` (its
        committed ``generated`` tokens intact), or None when the id is
        unknown (already finished)."""
        for slot_id, slot in enumerate(self.slots):
            if slot.active and slot.request.rid == request_id:
                req = slot.request
                self.cache.release(slot_id)
                if self.drafter is not None:
                    self.drafter.release(slot_id)
                slot.request, slot.pos, slot.last_tok = None, -1, 0
                req.finish_reason = "aborted"
                self._record("serving_abort", rid=req.rid,
                             trace=getattr(req, "trace_id", None),
                             slot=slot_id, where="slot",
                             generated=len(req.generated))
                self._note_pool()
                return req
        for req in self.queue:
            if req.rid == request_id:
                self.queue.remove(req)
                req.finish_reason = "aborted"
                self._record("serving_abort", rid=req.rid,
                             trace=getattr(req, "trace_id", None),
                             slot=None, where="queue",
                             generated=0)
                self.metrics.gauge("serving/queue_depth").set(
                    len(self.queue))
                return req
        return None

    def drain(self) -> List[Request]:
        """Abort EVERY in-flight and queued request (shutdown /
        scale-down fence): after drain() the pool holds no live pages —
        only refcount-0 resident prefix cache, which
        ``sweep_prefix_cache()`` returns to the free list."""
        out = []
        for slot in list(self.slots):
            if slot.active:
                out.append(self.abort(slot.request.rid))
        while self.queue:
            out.append(self.abort(self.queue[0].rid))
        return out

    # ----------------------------------------------------------- handoff

    def export_slot(self, slot_id: int):
        """Detach an active slot for a prefill→decode page handoff
        (ISSUE 14): the request leaves WITHOUT a finish event and its
        pages decref NOW — the caller (serving/router.py) must already
        hold a device-side gather of the slot's data pages. Returns
        ``(request, pos, last_tok)``."""
        slot = self.slots[slot_id]
        req, pos, last_tok = slot.request, slot.pos, slot.last_tok
        assert req is not None, f"slot {slot_id} idle"
        self.cache.release(slot_id)
        slot.request, slot.pos, slot.last_tok = None, -1, 0
        self.stats["handoffs_out"] += 1
        self.metrics.counter("serving/handoffs_out").inc()
        # ISSUE 19: mint the HANDOFF span here — the transport legs
        # (encode on this rank, decode/adopt on the receiving rank)
        # parent onto it, and extract_handoff ships it in the wire doc
        # so the receiving rank's events can reference it
        req._handoff_span = new_span_id()
        self._record("handoff_out", rid=req.rid,
                     trace=getattr(req, "trace_id", None),
                     slot=slot_id, pos=pos,
                     generated=len(req.generated),
                     span_id=req._handoff_span,
                     parent_span=getattr(req, "span_id", None))
        self._note_pool()
        return req, pos, last_tok

    def adopt_request(self, slot_id: int, req: Request, pos: int,
                      last_tok: int) -> None:
        """Install an already-prefilled request into a free slot (the
        receiving half of a handoff / elastic restore): the caller has
        already mapped the request's pages into ``slot_id``'s page
        table (cache ``admit``/``admit_prefix`` + scatter) — this
        rebuilds the host slot state and realigns any drafter."""
        slot = self.slots[slot_id]
        assert slot.request is None, f"slot {slot_id} busy"
        slot.request, slot.pos, slot.last_tok = req, pos, last_tok
        if self.drafter is not None:
            prompt_np = np.asarray(req.prompt, np.int32)  # sync-ok: host
            self.drafter.restore_slot(
                slot_id, prompt_np, req.generated,
                len(prompt_np) + req.max_new_tokens)
        self.stats["handoffs_in"] += 1
        self.metrics.counter("serving/handoffs_in").inc()
        t_done = time.monotonic()
        # always the first-decode-tick base — a request rebuilt from a
        # cross-process wire doc arrives WITHOUT _t_first_tok (that
        # monotonic stamp died with the sending process) but its
        # first-tick latency on THIS engine is still well-defined
        req._t_handoff_done = t_done
        t_first = getattr(req, "_t_first_tok", None)
        if t_first is not None:
            self.metrics.histogram("serving/handoff_s").observe(
                max(t_done - t_first, 0.0))
        # parent preference (ISSUE 19): the transport ENCODE span when
        # the packet crossed the process fabric, else the handoff span
        # minted at export, else the request root — whichever leg this
        # packet actually traversed, the tree stays connected
        parent = (getattr(req, "_encode_span", None)
                  or getattr(req, "_handoff_span", None)
                  or getattr(req, "span_id", None))
        self._record("handoff_in", rid=req.rid,
                     trace=getattr(req, "trace_id", None),
                     slot=slot_id, pos=pos,
                     generated=len(req.generated),
                     span_id=new_span_id(), parent_span=parent)
        self._note_pool()

    def step(self, now: Optional[float] = None) -> List[Request]:
        """One scheduler iteration: admit whatever fits, then one decode
        tick (or speculative verify round) over the active slots.
        Returns requests finished this step (including any that finished
        at prefill with max_new_tokens=1). A prefill-role engine skips
        the decode dispatch — its active slots wait for the router's
        handoff sweep."""
        finished = self._admit(now) if self._admitting else []
        if self.role != "prefill" and any(s.active for s in self.slots):
            finished.extend(self._decode_step())
        # fault point + elastic policy (ISSUE 11): the tick boundary is
        # the only place slot state is consistent (no speculation in
        # flight), so SIGTERM handling, periodic snapshot begin/commit
        # and the drain-or-snapshot decision all live here
        faults.fire("serving_tick_end", tick=self.stats["ticks"],
                    pending=self.pending)
        self._t_last_step_ts = time.time()   # /healthz fence age
        if self.elastic is not None:
            self.elastic.on_tick_end()
        return finished

    # ------------------------------------------------------------- serve

    def serve(self, requests: Sequence[Request],
              respect_arrival_times: bool = False) -> Dict[Any, Request]:
        """Run the scheduler until every request completes. With
        ``respect_arrival_times`` the queue honours each request's
        ``arrival_time`` against a wall clock started on entry —
        the Poisson-workload mode the serving bench drives."""
        for r in sorted(requests, key=lambda r: r.arrival_time):
            self.submit(r)
        done: Dict[Any, Request] = {}
        t0 = time.monotonic()
        if respect_arrival_times:
            # TTFT/admission-wait reference: when arrivals are honoured
            # a request only becomes admissible at its arrival time
            for r in requests:
                r._t_arrived = t0 + r.arrival_time
        while self.pending and not self.preempted:
            now = (time.monotonic() - t0) if respect_arrival_times \
                else None
            if respect_arrival_times and not any(
                    s.active for s in self.slots) and self.queue:
                wait = self.queue[0].arrival_time - (
                    time.monotonic() - t0)
                if wait > 0:
                    time.sleep(min(wait, 0.05))
                    if self.elastic is not None:
                        # a SIGTERM landing while we idle between
                        # arrivals must not wait for the next tick —
                        # the queued (never-admitted) requests snapshot
                        # here exactly like at a tick boundary (idle:
                        # the sleep must not feed the tick-latency EMA)
                        self.elastic.on_tick_end(idle=True)
                    continue
            for req in self.step(now):
                done[req.rid] = req
        # requests that finished at admission time (max_new_tokens == 1
        # or instant EOS) are collected by step(); nothing else pending
        return done
