"""Model adapters for the continuous-batching serving engine.

Each adapter compiles exactly TWO kinds of programs per model/storage
combination, so arbitrary request arrival patterns replay a small fixed
set of executables instead of retracing per request:

- ``tick``: ONE decode step over the whole slot set — [B_slots] tokens
  at per-slot positions, paged-attention reads through the page table,
  donated pool, idle slots masked by ``pos[b] < 0``. Compiled once per
  engine.
- ``prefill``: one request's prompt pass at a BUCKETED padded length
  (pages rounded up to the next power of two), writing K/V straight
  into the slot's assigned pool pages and returning last-position
  logits. Compiled once per bucket — log2(max_pages) programs total.

The decode tick reuses the stacked fused kernels the dense fast path
serves through (ops/pallas/decode.py): ``ln_qkv_int8_stacked`` /
``out_ffn_int8_stacked`` for the projections (dtype-agnostic — bf16
stacks run with scale 1) and ``decode_attention_paged`` for the
cached-attention read. Appends are XLA scatters into the donated pool:
row ``pos[b] % page`` of block ``page_table[b, pos[b] // page]``.
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.serving.paged_cache import PagedCacheSpec, PagedKVCache


# ----------------------------------------------------------- pool append

def _append_rows(pool, cache_q8, l, blk_ids, rows, k3, v3):
    """Scatter one new K/V row per slot into the paged pool at layer
    ``l``: (block blk_ids[b], row rows[b]). Idle slots arrive pointed at
    the trash block, so the scatter is always legal."""
    from deepspeed_tpu.ops.pallas.decode import kv_quant_int8
    if cache_q8:
        kc, ks, vc, vs = pool
        kq8, ksc, vq8, vsc = kv_quant_int8(k3, v3)
        kc = kc.at[l, blk_ids, :, rows, :].set(kq8)
        vc = vc.at[l, blk_ids, :, rows, :].set(vq8)
        ks = ks.at[l, blk_ids, :, 0, rows].set(ksc[..., 0])
        vs = vs.at[l, blk_ids, :, 0, rows].set(vsc[..., 0])
        return (kc, ks, vc, vs)
    kc, vc = pool
    kc = kc.at[l, blk_ids, :, rows, :].set(k3.astype(kc.dtype))
    vc = vc.at[l, blk_ids, :, rows, :].set(v3.astype(vc.dtype))
    return (kc, vc)


@functools.partial(jax.jit, donate_argnums=(0,))
def _copy_pool_block(pool, src, dst):
    """COW helper: duplicate one pool block (all layers, K and V and any
    scale arrays) — the sharer of a partially-filled prefix page
    continues appending into its own copy. Model-independent: every
    pool array indexes pages on axis 1."""
    return tuple(a.at[:, dst].set(a[:, src]) for a in pool)


def _quant_prompt_rows(t):
    """Per-(.., head, pos) symmetric int8 over the trailing D axis."""
    tf = t.astype(jnp.float32)
    sc = jnp.maximum(jnp.max(jnp.abs(tf), axis=-1) / 127.0, 1e-12)
    codes = jnp.clip(jnp.round(tf / sc[..., None]), -127,
                     127).astype(jnp.int8)
    return codes, sc


def _write_prompt_pages(pool, cache_q8, k, v, pages, page):
    """Blockify a prompt's K/V ([Lyr, H, Sp, D], Sp = len(pages)*page)
    and scatter the blocks into the pool at ``pages``. Page-table tails
    past the slot's allocation arrive as the trash block — duplicate
    trash writes are harmless by construction."""
    Lyr, H, Sp, D = k.shape
    npg = pages.shape[0]
    assert npg * page == Sp, (Sp, npg, page)

    def to_blocks(t):                       # → [Lyr, npg, H, page, D]
        return t.reshape(Lyr, H, npg, page, D).transpose(0, 2, 1, 3, 4)

    def to_scale_blocks(sc):                # [Lyr, H, Sp] → [Lyr,npg,H,1,page]
        return sc.reshape(Lyr, H, npg, page).transpose(0, 2, 1, 3)[
            :, :, :, None, :]

    if cache_q8:
        kc, ks, vc, vs = pool
        kq, ksc = _quant_prompt_rows(k)
        vq, vsc = _quant_prompt_rows(v)
        kc = kc.at[:, pages].set(to_blocks(kq))
        vc = vc.at[:, pages].set(to_blocks(vq))
        ks = ks.at[:, pages].set(to_scale_blocks(ksc))
        vs = vs.at[:, pages].set(to_scale_blocks(vsc))
        return (kc, ks, vc, vs)
    kc, vc = pool
    kc = kc.at[:, pages].set(to_blocks(k).astype(kc.dtype))
    vc = vc.at[:, pages].set(to_blocks(v).astype(vc.dtype))
    return (kc, vc)


def _write_suffix_rows(pool, cache_q8, k, v, blks, rows):
    """Scatter per-position K/V rows (k/v [Lyr, H, Ssuf, D]) into pool
    blocks at (blks[i], rows[i]) — the mid-page generalization of
    _write_prompt_pages for SUFFIX prefill: after a prefix-cache share
    the suffix may start mid-page (COW), so each row lands at its own
    (block, row) pair. Pad positions arrive pointed at the trash
    block."""
    if cache_q8:
        kc, ks, vc, vs = pool
        kq, ksc = _quant_prompt_rows(k)     # [Lyr,H,S,D] / [Lyr,H,S]
        vq, vsc = _quant_prompt_rows(v)
        # two advanced indices split by a slice put the row axis FIRST:
        # value layout [S, Lyr, H, ...]
        kc = kc.at[:, blks, :, rows, :].set(kq.transpose(2, 0, 1, 3))
        vc = vc.at[:, blks, :, rows, :].set(vq.transpose(2, 0, 1, 3))
        ks = ks.at[:, blks, :, 0, rows].set(ksc.transpose(2, 0, 1))
        vs = vs.at[:, blks, :, 0, rows].set(vsc.transpose(2, 0, 1))
        return (kc, ks, vc, vs)
    kc, vc = pool
    kc = kc.at[:, blks, :, rows, :].set(
        k.transpose(2, 0, 1, 3).astype(kc.dtype))
    vc = vc.at[:, blks, :, rows, :].set(
        v.transpose(2, 0, 1, 3).astype(vc.dtype))
    return (kc, vc)


def _gather_prefix_kv(pool, cache_q8, l, pre_ids, dtype):
    """Gather (and dequantize) a slot's resident prefix K/V from the
    pool at layer ``l``: pre_ids = the slot's leading page-table
    entries, padded with trash past the real prefix (those rows are
    masked off by position in the caller). Returns K, V [H, NPRE*P, D]
    in ``dtype``."""
    def fold(x):                             # [NPRE, H, P, D] -> [H, L, D]
        npg, H, P, D = x.shape
        return x.transpose(1, 0, 2, 3).reshape(H, npg * P, D)

    if cache_q8:
        kc, ks, vc, vs = pool
        kd = kc[l, pre_ids].astype(jnp.float32) \
            * ks[l, pre_ids].transpose(0, 1, 3, 2)
        vd = vc[l, pre_ids].astype(jnp.float32) \
            * vs[l, pre_ids].transpose(0, 1, 3, 2)
        return fold(kd).astype(dtype), fold(vd).astype(dtype)
    kc, vc = pool
    return (fold(kc[l, pre_ids]).astype(dtype),
            fold(vc[l, pre_ids]).astype(dtype))


def _suffix_attn_bias(start, pos_q, n_prefix_rows):
    """Additive attention bias [1, 1, Ssuf, LPRE+Ssuf] for suffix
    prefill: prefix rows are valid iff their absolute position < start
    (rows past the live prefix in the gathered pages are stale), suffix
    rows mask causally at absolute positions."""
    lpre = n_prefix_rows
    kp = jnp.concatenate([jnp.arange(lpre, dtype=jnp.int32), pos_q])
    kvalid = jnp.concatenate([
        jnp.arange(lpre, dtype=jnp.int32) < start,
        jnp.ones(pos_q.shape, bool)])
    mask = (kp[None, :] <= pos_q[:, None]) & kvalid[None, :]
    return jnp.where(mask, 0.0, -1e30).astype(jnp.float32)[None, None]


def _verify_append_ids(pos, pt, K, page, maxp):
    """(block ids, row offsets) [B*K] for appending the verification
    rows of a K-token speculative window at positions pos[b]..pos[b]+K-1
    per slot. Idle slots (pos < 0) resolve inside their all-trash table
    rows, same as _gather_blocks."""
    B = pos.shape[0]
    posf = (pos[:, None]
            + jnp.arange(K, dtype=jnp.int32)[None, :]).reshape(B * K)
    bidx = jnp.clip(posf // page, 0, maxp - 1)
    batch = jnp.repeat(jnp.arange(B, dtype=jnp.int32), K)
    return pt[batch, bidx], posf % page, posf


def _sample_keys(seeds, idxs):
    """Per-slot stateless sampling keys: fold the request's persistent
    ``sample_key`` and its GLOBAL token index (committed tokens before
    this one, across restores) into one base key. The key depends only
    on (request, position) — never on engine-global rng state — so a
    snapshot/restore or a prefill->decode handoff replays a sampled
    request token-for-token (ISSUE 14 satellite; the PR-11 fresh-rng
    caveat)."""
    base = jax.random.PRNGKey(0)

    def one(s, i):
        return jax.random.fold_in(jax.random.fold_in(base, s), i)

    return jax.vmap(one)(seeds, idxs)


def _pick_next(logits, seeds, idxs, temps):
    """Greedy/per-slot-temperature sampling; the Gumbel pass only runs
    when some slot actually asked for it (same cond-not-where rule as
    the dense decode loops)."""
    logits32 = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits32, axis=-1)

    def _sampled():
        t = jnp.maximum(temps, 1e-6)[:, None]
        keys = _sample_keys(seeds, idxs)
        s = jax.vmap(jax.random.categorical)(keys, logits32 / t)
        return jnp.where(temps > 0, s, greedy)

    return jax.lax.cond(jnp.max(temps) > 0.0, _sampled, lambda: greedy), \
        logits32


def sample_token(logits32, seed, idx, temperature):
    """One-row invocation of the tick's sampling rule — the HOST-side
    prefill pick for sampled requests. Same fold_in key schedule and
    categorical as `_pick_next`, so a replayed request whose next token
    falls at prefill (admission) samples the token the uninterrupted
    run's decode tick would have produced."""
    tok, _ = _pick_next(
        jnp.asarray(logits32, jnp.float32)[None, :],
        jnp.asarray([seed], jnp.uint32), jnp.asarray([idx], jnp.int32),
        jnp.asarray([temperature], jnp.float32))
    return int(tok[0])   # sync-ok: the scheduler consumes the sample


def _gather_blocks(pt, pos, page):
    """(block ids, row offsets) for appending each slot's next row.
    Idle slots (pos < 0) resolve inside their all-trash table rows."""
    maxp = pt.shape[1]
    idx = jnp.clip(pos // page, 0, maxp - 1)
    blk_ids = jnp.take_along_axis(pt, idx[:, None], axis=1)[:, 0]
    rows = pos % page
    return blk_ids, rows


# ------------------------------------------------------------- GPT-2

class GPT2ServingAdapter:
    """Paged serving over converted (optionally int8) GPT-2 inference
    params — the scan-stacked tree `convert_gpt2_params` produces."""

    def __init__(self, cfg, params, spec: PagedCacheSpec,
                 quantize_bits: int = 0):
        from deepspeed_tpu.models.gpt2_inference import (
            convert_gpt2_params, quantize_gpt2_inference_params)
        assert cfg.tie_word_embeddings, \
            "paged GPT-2 serving assumes the tied-embedding LM head"
        assert cfg.n_embd % cfg.n_head == 0
        converted = "h" in params and "blk" in params.get("h", {}) and \
            "attn_qkvw" in params["h"]["blk"]
        self.iparams = params if converted \
            else convert_gpt2_params(params, cfg)
        if quantize_bits == 8 \
                and "kernel_q" not in self.iparams["h"]["blk"]["attn_qkvw"]:
            # serving.quantize_bits: quantize a full-precision tree to
            # the int8 serving storage at build time
            self.iparams = quantize_gpt2_inference_params(self.iparams)
        self.cfg = cfg
        self.spec = spec
        self.weights_q8 = "kernel_q" in self.iparams["h"]["blk"]["attn_qkvw"]
        self.cache_q8 = spec.kv_cache_bits == 8
        assert spec.n_layers == cfg.n_layer
        assert spec.kv_heads == cfg.n_head
        assert spec.head_dim == cfg.n_embd // cfg.n_head
        self._p = {"wte": self.iparams["wte"], "wpe": self.iparams["wpe"],
                   "ln_f": self.iparams["ln_f"]}
        self._blk = self.iparams["h"]["blk"]
        # per-ADAPTER compiled-fn cache: the closures capture the params
        # tree, so a module-global cache would pin every model's weights
        # for process lifetime; here they free with the engine
        self._fns = {}

    @property
    def eos_default(self):
        return None

    def make_cache(self) -> PagedKVCache:
        return PagedKVCache(self.spec)

    def max_prompt_len(self):
        return self.cfg.n_positions

    # -- compiled programs -------------------------------------------------

    def _tick_fn(self, steps: int = 1):
        cfg, spec = self.cfg, self.spec
        key = ("tick", steps)
        if key in self._fns:
            return self._fns[key]
        from deepspeed_tpu.ops.pallas.decode import (
            ln_qkv_int8_stacked, decode_attention_paged,
            out_ffn_int8_stacked)
        E, H = cfg.n_embd, cfg.n_head
        D = E // H
        Lyr = cfg.n_layer
        P = spec.page_size
        eps = cfg.layer_norm_epsilon
        cache_q8 = self.cache_q8
        wkey = "kernel_q" if self.weights_q8 else "kernel"

        def _wscale(proj):
            if self.weights_q8:
                return proj["kernel_scale"].reshape(Lyr)
            return jnp.ones((Lyr,), jnp.float32)

        def _ln_f(x, w, b):
            xf = x.astype(jnp.float32)
            mu = jnp.mean(xf, axis=-1, keepdims=True)
            var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
            y = (xf - mu) * jax.lax.rsqrt(var + eps)
            return (y * w.astype(jnp.float32)
                    + b.astype(jnp.float32)).astype(x.dtype)

        @functools.partial(jax.jit, donate_argnums=(2,))
        def tick(p, blk, pool, toks, pos, pt, seeds, idxs0, temps):
            wte = jnp.asarray(p["wte"]).astype(cfg.dtype)
            wpe = jnp.asarray(p["wpe"]).astype(cfg.dtype)
            Wq, Wp = blk["attn_qkvw"][wkey], blk["attn_ow"][wkey]
            W1, W2 = blk["inter_w"][wkey], blk["output_w"][wkey]
            r3 = lambda a: a.reshape(Lyr, 1, a.shape[-1])  # noqa: E731
            ln1_w = r3(blk["attn_nw"]["scale"])
            ln1_b = r3(blk["attn_nw"]["bias"])
            ln2_w = r3(blk["norm_w"]["scale"])
            ln2_b = r3(blk["norm_w"]["bias"])
            bq = r3(blk["attn_qkvw"]["bias"])
            bp = r3(blk["attn_ow"]["bias"])
            b1 = r3(blk["inter_w"]["bias"])
            b2 = r3(blk["output_w"]["bias"])
            sq, sp_ = _wscale(blk["attn_qkvw"]), _wscale(blk["attn_ow"])
            s1, s2 = _wscale(blk["inter_w"]), _wscale(blk["output_w"])
            B = toks.shape[0]

            def one(carry, t):
                pool, toks, pos, _ = carry
                x = wte[toks] + wpe[jnp.clip(pos, 0,
                                             cfg.n_positions - 1)]
                blk_ids, rows = _gather_blocks(pt, pos, P)

                def layer(car, l):
                    x, pool = car
                    qkv = ln_qkv_int8_stacked(x, ln1_w, ln1_b, Wq, sq,
                                              bq, l, eps=eps)
                    qh = qkv[:, :E].reshape(B, H, 1, D)
                    k3 = qkv[:, E:2 * E].reshape(B, H, D)
                    v3 = qkv[:, 2 * E:].reshape(B, H, D)
                    pool = _append_rows(pool, cache_q8, l, blk_ids,
                                        rows, k3, v3)
                    if cache_q8:
                        kc, ks, vc, vs = pool
                        ctx = decode_attention_paged(
                            qh, kc, vc, pos, pt, l, k_scale=ks,
                            v_scale=vs, scale=1.0 / np.sqrt(D))
                    else:
                        kc, vc = pool
                        ctx = decode_attention_paged(
                            qh, kc, vc, pos, pt, l,
                            scale=1.0 / np.sqrt(D))
                    ctx2 = ctx.reshape(B, E)
                    x = out_ffn_int8_stacked(
                        ctx2, x, Wp, sp_, bp, ln2_w, ln2_b, W1, s1, b1,
                        W2, s2, b2, l, act="gelu_tanh", eps=eps)
                    return (x, pool), None

                (x, pool), _ = jax.lax.scan(
                    layer, (x, pool), jnp.arange(Lyr, dtype=jnp.int32))
                logits = jnp.einsum(
                    "be,ve->bv",
                    _ln_f(x, p["ln_f"]["scale"], p["ln_f"]["bias"]), wte)
                nxt, logits32 = _pick_next(logits, seeds, idxs0 + t,
                                           temps)
                return (pool, nxt, pos + 1, logits32), nxt

            logits0 = jnp.zeros((B, cfg.vocab_size), jnp.float32)
            (pool, _, _, logits32), toks_seq = jax.lax.scan(
                one, (pool, toks, pos, logits0),
                jnp.arange(steps, dtype=jnp.int32))
            return pool, toks_seq, logits32

        self._fns[key] = tick
        return tick

    def _prefill_fn(self, n_pages: int):
        cfg, spec = self.cfg, self.spec
        key = ("prefill", n_pages)
        if key in self._fns:
            return self._fns[key]
        from deepspeed_tpu.ops.attention import dot_product_attention
        E, H = cfg.n_embd, cfg.n_head
        D = E // H
        Lyr = cfg.n_layer
        P = spec.page_size
        Sp = n_pages * P
        assert Sp <= cfg.n_positions, (
            f"prefill bucket {Sp} exceeds n_positions {cfg.n_positions}")
        eps = cfg.layer_norm_epsilon
        cache_q8 = self.cache_q8
        wkey = "kernel_q" if self.weights_q8 else "kernel"

        def deq(sub, l):
            w = sub[wkey][l]
            if self.weights_q8:
                s = sub["kernel_scale"].reshape(Lyr)[l]
                return (w.astype(jnp.float32) * s).astype(cfg.dtype)
            return w.astype(cfg.dtype)

        def _ln(x, w, b):
            xf = x.astype(jnp.float32)
            mu = jnp.mean(xf, axis=-1, keepdims=True)
            var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
            y = (xf - mu) * jax.lax.rsqrt(var + eps)
            return (y * w.astype(jnp.float32)
                    + b.astype(jnp.float32)).astype(x.dtype)

        @functools.partial(jax.jit, donate_argnums=(2,))
        def prefill(p, blk, pool, ids, length, pages):
            wte = jnp.asarray(p["wte"]).astype(cfg.dtype)
            wpe = jnp.asarray(p["wpe"]).astype(cfg.dtype)
            x = wte[ids] + wpe[:Sp][None]            # [1, Sp, E]

            def layer(x, l):
                u = _ln(x, blk["attn_nw"]["scale"][l],
                        blk["attn_nw"]["bias"][l])
                qkv = u @ deq(blk["attn_qkvw"], l) \
                    + blk["attn_qkvw"]["bias"][l].astype(cfg.dtype)
                q = qkv[..., :E].reshape(1, Sp, H, D).transpose(0, 2, 1, 3)
                k = qkv[..., E:2 * E].reshape(1, Sp, H, D) \
                    .transpose(0, 2, 1, 3)
                v = qkv[..., 2 * E:].reshape(1, Sp, H, D) \
                    .transpose(0, 2, 1, 3)
                ctx = dot_product_attention(q, k, v, causal=True)
                ctx = ctx.transpose(0, 2, 1, 3).reshape(1, Sp, E)
                x = x + ctx @ deq(blk["attn_ow"], l) \
                    + blk["attn_ow"]["bias"][l].astype(cfg.dtype)
                u2 = _ln(x, blk["norm_w"]["scale"][l],
                         blk["norm_w"]["bias"][l])
                h = jax.nn.gelu(
                    u2 @ deq(blk["inter_w"], l)
                    + blk["inter_w"]["bias"][l].astype(cfg.dtype),
                    approximate=True)
                x = x + h @ deq(blk["output_w"], l) \
                    + blk["output_w"]["bias"][l].astype(cfg.dtype)
                return x, (k[0], v[0])

            x, (ks, vs) = jax.lax.scan(
                layer, x, jnp.arange(Lyr, dtype=jnp.int32))
            pool = _write_prompt_pages(pool, cache_q8, ks, vs, pages, P)
            xl = x[0, length - 1]
            xf = xl.astype(jnp.float32)
            mu = jnp.mean(xf, keepdims=True)
            var = jnp.mean((xf - mu) ** 2, keepdims=True)
            y = (xf - mu) * jax.lax.rsqrt(var + eps)
            y = y * p["ln_f"]["scale"].astype(jnp.float32) \
                + p["ln_f"]["bias"].astype(jnp.float32)
            logits = y.astype(cfg.dtype) @ wte.T
            return pool, logits.astype(jnp.float32)

        self._fns[key] = prefill
        return prefill

    def _prefill_suffix_fn(self, n_suf_pages: int, n_pre_pages: int):
        """Suffix-only prefill for prefix-cache hits: computes (and
        writes) K/V ONLY for prompt positions >= ``start``, reading the
        shared-prefix K/V back through the slot's page table. One
        compiled program per (suffix-pages, prefix-pages) pow2 bucket
        pair."""
        cfg, spec = self.cfg, self.spec
        key = ("prefill_sfx", n_suf_pages, n_pre_pages)
        if key in self._fns:
            return self._fns[key]
        from deepspeed_tpu.ops.attention import dot_product_attention
        E, H = cfg.n_embd, cfg.n_head
        D = E // H
        Lyr = cfg.n_layer
        P = spec.page_size
        MAXP = spec.max_pages_per_slot
        Ssuf = n_suf_pages * P
        LPRE = n_pre_pages * P
        eps = cfg.layer_norm_epsilon
        cache_q8 = self.cache_q8
        wkey = "kernel_q" if self.weights_q8 else "kernel"

        def deq(sub, l):
            w = sub[wkey][l]
            if self.weights_q8:
                s = sub["kernel_scale"].reshape(Lyr)[l]
                return (w.astype(jnp.float32) * s).astype(cfg.dtype)
            return w.astype(cfg.dtype)

        def _ln(x, w, b):
            xf = x.astype(jnp.float32)
            mu = jnp.mean(xf, axis=-1, keepdims=True)
            var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
            y = (xf - mu) * jax.lax.rsqrt(var + eps)
            return (y * w.astype(jnp.float32)
                    + b.astype(jnp.float32)).astype(x.dtype)

        @functools.partial(jax.jit, donate_argnums=(2,))
        def prefill_sfx(p, blk, pool, ids, length, start, pt_row):
            wte = jnp.asarray(p["wte"]).astype(cfg.dtype)
            wpe = jnp.asarray(p["wpe"]).astype(cfg.dtype)
            pos_q = start + jnp.arange(Ssuf, dtype=jnp.int32)
            x = wte[ids] + wpe[jnp.clip(pos_q, 0,
                                        cfg.n_positions - 1)][None]
            pre_ids = pt_row[:n_pre_pages]
            bias = _suffix_attn_bias(start, pos_q, LPRE)

            def layer(x, l):
                u = _ln(x, blk["attn_nw"]["scale"][l],
                        blk["attn_nw"]["bias"][l])
                qkv = u @ deq(blk["attn_qkvw"], l) \
                    + blk["attn_qkvw"]["bias"][l].astype(cfg.dtype)
                q = qkv[..., :E].reshape(1, Ssuf, H, D) \
                    .transpose(0, 2, 1, 3)
                k = qkv[..., E:2 * E].reshape(1, Ssuf, H, D) \
                    .transpose(0, 2, 1, 3)
                v = qkv[..., 2 * E:].reshape(1, Ssuf, H, D) \
                    .transpose(0, 2, 1, 3)
                kpre, vpre = _gather_prefix_kv(pool, cache_q8, l,
                                               pre_ids, cfg.dtype)
                ka = jnp.concatenate([kpre[None], k], axis=2)
                va = jnp.concatenate([vpre[None], v], axis=2)
                ctx = dot_product_attention(q, ka, va, bias=bias)
                ctx = ctx.transpose(0, 2, 1, 3).reshape(1, Ssuf, E)
                x = x + ctx @ deq(blk["attn_ow"], l) \
                    + blk["attn_ow"]["bias"][l].astype(cfg.dtype)
                u2 = _ln(x, blk["norm_w"]["scale"][l],
                         blk["norm_w"]["bias"][l])
                h = jax.nn.gelu(
                    u2 @ deq(blk["inter_w"], l)
                    + blk["inter_w"]["bias"][l].astype(cfg.dtype),
                    approximate=True)
                x = x + h @ deq(blk["output_w"], l) \
                    + blk["output_w"]["bias"][l].astype(cfg.dtype)
                return x, (k[0], v[0])

            x, (ks, vs) = jax.lax.scan(
                layer, x, jnp.arange(Lyr, dtype=jnp.int32))
            valid = pos_q < length
            blks = jnp.where(
                valid, pt_row[jnp.clip(pos_q // P, 0, MAXP - 1)],
                jnp.int32(0))
            pool_out = _write_suffix_rows(pool, cache_q8, ks, vs,
                                          blks, pos_q % P)
            xl = x[0, length - 1 - start]
            xf = xl.astype(jnp.float32)
            mu = jnp.mean(xf, keepdims=True)
            var = jnp.mean((xf - mu) ** 2, keepdims=True)
            y = (xf - mu) * jax.lax.rsqrt(var + eps)
            y = y * p["ln_f"]["scale"].astype(jnp.float32) \
                + p["ln_f"]["bias"].astype(jnp.float32)
            logits = y.astype(cfg.dtype) @ wte.T
            return pool_out, logits.astype(jnp.float32)

        self._fns[key] = prefill_sfx
        return prefill_sfx

    def _verify_fn(self, n_rows: int):
        """Speculative verification: feed ``n_rows`` tokens per slot
        (the pending token + n_rows-1 drafts) in ONE dispatch; the
        paged attention runs in multi-query mode so every drafted
        position attends through the page table at its own offset.
        Returns (pool, greedy [B, n_rows], logits32 [B, n_rows, V])."""
        cfg, spec = self.cfg, self.spec
        key = ("verify", n_rows)
        if key in self._fns:
            return self._fns[key]
        from deepspeed_tpu.ops.pallas.decode import (
            ln_qkv_int8_stacked, decode_attention_paged,
            out_ffn_int8_stacked)
        E, H = cfg.n_embd, cfg.n_head
        D = E // H
        Lyr = cfg.n_layer
        P = spec.page_size
        MAXP = spec.max_pages_per_slot
        K = n_rows
        eps = cfg.layer_norm_epsilon
        cache_q8 = self.cache_q8
        wkey = "kernel_q" if self.weights_q8 else "kernel"

        def _wscale(proj):
            if self.weights_q8:
                return proj["kernel_scale"].reshape(Lyr)
            return jnp.ones((Lyr,), jnp.float32)

        def _ln_f(x, w, b):
            xf = x.astype(jnp.float32)
            mu = jnp.mean(xf, axis=-1, keepdims=True)
            var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
            y = (xf - mu) * jax.lax.rsqrt(var + eps)
            return (y * w.astype(jnp.float32)
                    + b.astype(jnp.float32)).astype(x.dtype)

        @functools.partial(jax.jit, donate_argnums=(2,))
        def verify(p, blk, pool, toks, pos, pt):
            wte = jnp.asarray(p["wte"]).astype(cfg.dtype)
            wpe = jnp.asarray(p["wpe"]).astype(cfg.dtype)
            Wq, Wp = blk["attn_qkvw"][wkey], blk["attn_ow"][wkey]
            W1, W2 = blk["inter_w"][wkey], blk["output_w"][wkey]
            r3 = lambda a: a.reshape(Lyr, 1, a.shape[-1])  # noqa: E731
            ln1_w = r3(blk["attn_nw"]["scale"])
            ln1_b = r3(blk["attn_nw"]["bias"])
            ln2_w = r3(blk["norm_w"]["scale"])
            ln2_b = r3(blk["norm_w"]["bias"])
            bq = r3(blk["attn_qkvw"]["bias"])
            bp = r3(blk["attn_ow"]["bias"])
            b1 = r3(blk["inter_w"]["bias"])
            b2 = r3(blk["output_w"]["bias"])
            sq, sp_ = _wscale(blk["attn_qkvw"]), _wscale(blk["attn_ow"])
            s1, s2 = _wscale(blk["inter_w"]), _wscale(blk["output_w"])
            B = toks.shape[0]
            blk_ids, rows, posf = _verify_append_ids(pos, pt, K, P, MAXP)
            x = (wte[toks]
                 + wpe[jnp.clip(posf.reshape(B, K), 0,
                                cfg.n_positions - 1)]).reshape(B * K, E)

            def layer(car, l):
                x, pool = car
                qkv = ln_qkv_int8_stacked(x, ln1_w, ln1_b, Wq, sq,
                                          bq, l, eps=eps)
                qh = qkv[:, :E].reshape(B, K, H, D).transpose(0, 2, 1, 3)
                k3 = qkv[:, E:2 * E].reshape(B * K, H, D)
                v3 = qkv[:, 2 * E:].reshape(B * K, H, D)
                pool = _append_rows(pool, cache_q8, l, blk_ids,
                                    rows, k3, v3)
                if cache_q8:
                    kc, ks, vc, vs = pool
                    ctx = decode_attention_paged(
                        qh, kc, vc, pos, pt, l, k_scale=ks,
                        v_scale=vs, scale=1.0 / np.sqrt(D),
                        rows_per_step=1)
                else:
                    kc, vc = pool
                    ctx = decode_attention_paged(
                        qh, kc, vc, pos, pt, l,
                        scale=1.0 / np.sqrt(D), rows_per_step=1)
                ctx2 = ctx.transpose(0, 2, 1, 3).reshape(B * K, E)
                x = out_ffn_int8_stacked(
                    ctx2, x, Wp, sp_, bp, ln2_w, ln2_b, W1, s1, b1,
                    W2, s2, b2, l, act="gelu_tanh", eps=eps)
                return (x, pool), None

            (x, pool), _ = jax.lax.scan(
                layer, (x, pool), jnp.arange(Lyr, dtype=jnp.int32))
            logits = jnp.einsum(
                "be,ve->bv",
                _ln_f(x, p["ln_f"]["scale"], p["ln_f"]["bias"]), wte)
            logits32 = logits.astype(jnp.float32)
            greedy = jnp.argmax(logits32, axis=-1).astype(jnp.int32)
            return (pool, greedy.reshape(B, K),
                    logits32.reshape(B, K, -1))

        self._fns[key] = verify
        return verify

    # -- engine-facing calls -----------------------------------------------

    def tick(self, pool, toks, pos, pt, seeds, idxs, temps, steps=1):
        """Run ``steps`` decode steps in ONE dispatch. ``seeds``/
        ``idxs`` [B] drive the per-slot stateless sampling keys (global
        token index of each slot's NEXT token — greedy slots pass
        zeros). Returns (pool, tokens [steps, B], last-step logits
        [B, V])."""
        return self._tick_fn(steps)(self._p, self._blk, pool, toks, pos,
                                    pt, seeds, idxs, temps)

    def prefill(self, pool, ids, length, pages):
        return self._prefill_fn(ids.shape[1] // self.spec.page_size)(
            self._p, self._blk, pool, ids, length, pages)

    def prefill_suffix(self, pool, ids, length, start, n_pre_pages,
                       pt_row):
        """Prefix-cache-hit prefill: compute/write only positions
        [start, length), attending over the shared prefix through the
        slot's page table row."""
        return self._prefill_suffix_fn(
            ids.shape[1] // self.spec.page_size, n_pre_pages)(
            self._p, self._blk, pool, ids,
            jnp.asarray(length, jnp.int32), jnp.asarray(start, jnp.int32),
            jnp.asarray(pt_row))

    def verify(self, pool, toks, pos, pt):
        """One speculative verification dispatch over toks [B, n_rows]."""
        return self._verify_fn(toks.shape[1])(
            self._p, self._blk, pool, jnp.asarray(toks),
            jnp.asarray(pos), jnp.asarray(pt))

    def copy_block(self, pool, src, dst):
        return _copy_pool_block(pool, jnp.asarray(src, jnp.int32),
                                jnp.asarray(dst, jnp.int32))


# ------------------------------------------------------------- LLaMA

def _rope_rows(x, pos, theta):
    """RoPE on [B, Hx, D] rows at PER-SLOT positions ``pos`` [B] (the
    dense fast loop's _rope_one takes one shared scalar position —
    continuous batching decodes every slot at its own offset)."""
    B, H, D = x.shape
    inv = 1.0 / (theta ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
    ang = pos.astype(jnp.float32)[:, None] * inv[None]    # [B, D//2]
    cos = jnp.cos(ang)[:, None].astype(x.dtype)           # [B, 1, D//2]
    sin = jnp.sin(ang)[:, None].astype(x.dtype)
    half = D // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x2 * cos + x1 * sin], axis=-1)


class LlamaServingAdapter:
    """Paged serving over PACKED LLaMA serving params (the tree
    convert_llama_serving_params / quantize_llama_serving_params /
    random_int8_serving_params produce). GQA: the pool holds Hkv heads;
    the paged attention kernel takes rep = H/Hkv query rows per head."""

    def __init__(self, cfg, sparams, spec: PagedCacheSpec,
                 quantize_bits: int = 0):
        if quantize_bits == 8 \
                and "kernel_q" not in sparams["blk"]["qkv_w"]:
            from deepspeed_tpu.models.llama_inference import \
                quantize_llama_serving_params
            sparams = quantize_llama_serving_params(sparams)
        self.cfg = cfg
        self.sparams = sparams
        self.spec = spec
        self.weights_q8 = "kernel_q" in sparams["blk"]["qkv_w"]
        self.cache_q8 = spec.kv_cache_bits == 8
        assert spec.n_layers == cfg.n_layers
        assert spec.kv_heads == cfg.kv_heads
        assert spec.head_dim == cfg.head_dim
        self._p = {k: v for k, v in sparams.items() if k != "blk"}
        self._blk = sparams["blk"]
        self._fns = {}    # per-adapter compiled-fn cache (see GPT-2)

    @property
    def eos_default(self):
        return None

    def make_cache(self) -> PagedKVCache:
        return PagedKVCache(self.spec)

    def max_prompt_len(self):
        return self.cfg.max_seq_len

    def _tick_fn(self, steps: int = 1):
        cfg, spec = self.cfg, self.spec
        key = ("tick", steps)
        if key in self._fns:
            return self._fns[key]
        from deepspeed_tpu.ops.pallas.decode import (
            ln_qkv_int8_stacked, decode_attention_paged,
            out_ffn_int8_stacked, matvec_int8_stacked)
        from deepspeed_tpu.models.llama_inference import _weights
        E, H, Hkv, D = (cfg.hidden_size, cfg.n_heads, cfg.kv_heads,
                        cfg.head_dim)
        Lyr = cfg.n_layers
        rep = H // Hkv
        P = spec.page_size
        eps = cfg.rms_eps
        cache_q8 = self.cache_q8

        def _rms(x, w):
            xf = x.astype(jnp.float32)
            n = xf * jax.lax.rsqrt(
                jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
            return (n * w.astype(jnp.float32)).astype(x.dtype)

        @functools.partial(jax.jit, donate_argnums=(2,))
        def tick(p, blk, pool, toks, pos, pt, seeds, idxs0, temps):
            embed = p["embed"].astype(cfg.dtype)
            head = p["head"].astype(cfg.dtype)
            Wq, sq = _weights(blk, "qkv_w", Lyr)
            Wo, so = _weights(blk, "o_w", Lyr)
            Wg, sg = _weights(blk, "gate_w", Lyr)
            Wu, su = _weights(blk, "up_w", Lyr)
            Wd, sd = _weights(blk, "down_w", Lyr)
            n1 = blk["norm1"].reshape(Lyr, 1, E)
            n2 = blk["norm2"].reshape(Lyr, 1, E)
            B = toks.shape[0]

            def one(carry, t):
                pool, toks, pos, _ = carry
                x = embed[toks]
                blk_ids, rows = _gather_blocks(pt, pos, P)

                def layer(car, l):
                    x, pool = car
                    qkv = ln_qkv_int8_stacked(x, n1, None, Wq, sq, None,
                                              l, eps=eps, norm="rms")
                    q3 = qkv[:, :H * D].reshape(B, H, D)
                    k3 = qkv[:, H * D:(H + Hkv) * D].reshape(B, Hkv, D)
                    v3 = qkv[:, (H + Hkv) * D:].reshape(B, Hkv, D)
                    q3 = _rope_rows(q3, pos, cfg.rope_theta)
                    k3 = _rope_rows(k3, pos, cfg.rope_theta)
                    qg = q3.reshape(B, Hkv, rep, D)
                    pool = _append_rows(pool, cache_q8, l, blk_ids,
                                        rows, k3, v3)
                    if cache_q8:
                        kc, ks, vc, vs = pool
                        ctx = decode_attention_paged(
                            qg, kc, vc, pos, pt, l, k_scale=ks,
                            v_scale=vs, scale=1.0 / np.sqrt(D))
                    else:
                        kc, vc = pool
                        ctx = decode_attention_paged(
                            qg, kc, vc, pos, pt, l,
                            scale=1.0 / np.sqrt(D))
                    ctx2 = ctx.reshape(B, H * D)
                    if E * E * Wo.dtype.itemsize <= (6 << 20):
                        x = out_ffn_int8_stacked(
                            ctx2, x, Wo, so, None, n2, None, Wg, sg,
                            None, Wd, sd, None, l, act="swiglu",
                            eps=eps, norm="rms", w1b_stack=Wu, s1b=su)
                    else:
                        x1 = x + matvec_int8_stacked(ctx2, Wo, so, l)
                        x = out_ffn_int8_stacked(
                            None, x1, None, None, None, n2, None, Wg,
                            sg, None, Wd, sd, None, l, act="swiglu",
                            eps=eps, norm="rms", w1b_stack=Wu, s1b=su,
                            fuse_proj=False)
                    return (x, pool), None

                (x, pool), _ = jax.lax.scan(
                    layer, (x, pool), jnp.arange(Lyr, dtype=jnp.int32))
                logits = jnp.einsum("be,ve->bv",
                                    _rms(x, p["norm_scale"]), head)
                nxt, logits32 = _pick_next(logits, seeds, idxs0 + t,
                                           temps)
                return (pool, nxt, pos + 1, logits32), nxt

            logits0 = jnp.zeros((B, cfg.vocab_size), jnp.float32)
            (pool, _, _, logits32), toks_seq = jax.lax.scan(
                one, (pool, toks, pos, logits0),
                jnp.arange(steps, dtype=jnp.int32))
            return pool, toks_seq, logits32

        self._fns[key] = tick
        return tick

    def _prefill_fn(self, n_pages: int):
        cfg, spec = self.cfg, self.spec
        key = ("prefill", n_pages)
        if key in self._fns:
            return self._fns[key]
        from deepspeed_tpu.ops.attention import dot_product_attention
        from deepspeed_tpu.models.llama import rope_angles, apply_rope
        from deepspeed_tpu.models.llama_inference import _weights
        E, H, Hkv, D = (cfg.hidden_size, cfg.n_heads, cfg.kv_heads,
                        cfg.head_dim)
        Lyr = cfg.n_layers
        P = spec.page_size
        Sp = n_pages * P
        eps = cfg.rms_eps
        cache_q8 = self.cache_q8

        def _rms(x, w):
            xf = x.astype(jnp.float32)
            n = xf * jax.lax.rsqrt(
                jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
            return (n * w.astype(jnp.float32)).astype(x.dtype)

        @functools.partial(jax.jit, donate_argnums=(2,))
        def prefill(p, blk, pool, ids, length, pages):
            x = p["embed"][ids].astype(cfg.dtype)    # [1, Sp, E]
            positions = jnp.arange(Sp)
            cos, sin = rope_angles(positions, D, cfg.rope_theta)
            Wq, sq = _weights(blk, "qkv_w", Lyr)
            Wo, so = _weights(blk, "o_w", Lyr)
            Wg, sg = _weights(blk, "gate_w", Lyr)
            Wu, su = _weights(blk, "up_w", Lyr)
            Wd, sd = _weights(blk, "down_w", Lyr)

            def deq(stack, scale, l):
                w = stack[l]
                if stack.dtype == jnp.int8:
                    return (w.astype(jnp.float32)
                            * scale[l]).astype(cfg.dtype)
                return w.astype(cfg.dtype)

            def layer(x, l):
                u = _rms(x, blk["norm1"][l])
                qkv = u @ deq(Wq, sq, l)
                q = qkv[..., :H * D].reshape(1, Sp, H, D) \
                    .transpose(0, 2, 1, 3)
                k = qkv[..., H * D:(H + Hkv) * D] \
                    .reshape(1, Sp, Hkv, D).transpose(0, 2, 1, 3)
                v = qkv[..., (H + Hkv) * D:] \
                    .reshape(1, Sp, Hkv, D).transpose(0, 2, 1, 3)
                q = apply_rope(q, cos, sin)
                k = apply_rope(k, cos, sin)
                ctx = dot_product_attention(q, k, v, causal=True)
                ctx = ctx.transpose(0, 2, 1, 3).reshape(1, Sp, H * D)
                x = x + ctx @ deq(Wo, so, l)
                u2 = _rms(x, blk["norm2"][l])
                h = jax.nn.silu(u2 @ deq(Wg, sg, l)) \
                    * (u2 @ deq(Wu, su, l))
                x = x + h @ deq(Wd, sd, l)
                return x, (k[0], v[0])

            x, (ks, vs) = jax.lax.scan(
                layer, x, jnp.arange(Lyr, dtype=jnp.int32))
            pool = _write_prompt_pages(pool, cache_q8, ks, vs, pages, P)
            xl = x[0, length - 1]
            logits = _rms(xl, p["norm_scale"]) \
                @ p["head"].astype(cfg.dtype).T
            return pool, logits.astype(jnp.float32)

        self._fns[key] = prefill
        return prefill

    def _prefill_suffix_fn(self, n_suf_pages: int, n_pre_pages: int):
        """Suffix-only prefill (prefix-cache hits) — LLaMA twin of the
        GPT-2 variant: RoPE at absolute positions, RMS norms, GQA
        attention over [shared prefix ++ suffix] K/V."""
        cfg, spec = self.cfg, self.spec
        key = ("prefill_sfx", n_suf_pages, n_pre_pages)
        if key in self._fns:
            return self._fns[key]
        from deepspeed_tpu.ops.attention import dot_product_attention
        from deepspeed_tpu.models.llama import rope_angles, apply_rope
        from deepspeed_tpu.models.llama_inference import _weights
        E, H, Hkv, D = (cfg.hidden_size, cfg.n_heads, cfg.kv_heads,
                        cfg.head_dim)
        Lyr = cfg.n_layers
        P = spec.page_size
        MAXP = spec.max_pages_per_slot
        Ssuf = n_suf_pages * P
        LPRE = n_pre_pages * P
        eps = cfg.rms_eps
        cache_q8 = self.cache_q8

        def _rms(x, w):
            xf = x.astype(jnp.float32)
            n = xf * jax.lax.rsqrt(
                jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
            return (n * w.astype(jnp.float32)).astype(x.dtype)

        @functools.partial(jax.jit, donate_argnums=(2,))
        def prefill_sfx(p, blk, pool, ids, length, start, pt_row):
            x = p["embed"][ids].astype(cfg.dtype)    # [1, Ssuf, E]
            pos_q = start + jnp.arange(Ssuf, dtype=jnp.int32)
            cos, sin = rope_angles(pos_q, D, cfg.rope_theta)
            Wq, sq = _weights(blk, "qkv_w", Lyr)
            Wo, so = _weights(blk, "o_w", Lyr)
            Wg, sg = _weights(blk, "gate_w", Lyr)
            Wu, su = _weights(blk, "up_w", Lyr)
            Wd, sd = _weights(blk, "down_w", Lyr)
            pre_ids = pt_row[:n_pre_pages]
            bias = _suffix_attn_bias(start, pos_q, LPRE)

            def deq(stack, scale, l):
                w = stack[l]
                if stack.dtype == jnp.int8:
                    return (w.astype(jnp.float32)
                            * scale[l]).astype(cfg.dtype)
                return w.astype(cfg.dtype)

            def layer(x, l):
                u = _rms(x, blk["norm1"][l])
                qkv = u @ deq(Wq, sq, l)
                q = qkv[..., :H * D].reshape(1, Ssuf, H, D) \
                    .transpose(0, 2, 1, 3)
                k = qkv[..., H * D:(H + Hkv) * D] \
                    .reshape(1, Ssuf, Hkv, D).transpose(0, 2, 1, 3)
                v = qkv[..., (H + Hkv) * D:] \
                    .reshape(1, Ssuf, Hkv, D).transpose(0, 2, 1, 3)
                q = apply_rope(q, cos, sin)
                k = apply_rope(k, cos, sin)
                kpre, vpre = _gather_prefix_kv(pool, cache_q8, l,
                                               pre_ids, cfg.dtype)
                ka = jnp.concatenate([kpre[None], k], axis=2)
                va = jnp.concatenate([vpre[None], v], axis=2)
                ctx = dot_product_attention(q, ka, va, bias=bias)
                ctx = ctx.transpose(0, 2, 1, 3).reshape(1, Ssuf, H * D)
                x = x + ctx @ deq(Wo, so, l)
                u2 = _rms(x, blk["norm2"][l])
                h = jax.nn.silu(u2 @ deq(Wg, sg, l)) \
                    * (u2 @ deq(Wu, su, l))
                x = x + h @ deq(Wd, sd, l)
                return x, (k[0], v[0])

            x, (ks, vs) = jax.lax.scan(
                layer, x, jnp.arange(Lyr, dtype=jnp.int32))
            valid = pos_q < length
            blks = jnp.where(
                valid, pt_row[jnp.clip(pos_q // P, 0, MAXP - 1)],
                jnp.int32(0))
            pool_out = _write_suffix_rows(pool, cache_q8, ks, vs,
                                          blks, pos_q % P)
            xl = x[0, length - 1 - start]
            logits = _rms(xl, p["norm_scale"]) \
                @ p["head"].astype(cfg.dtype).T
            return pool_out, logits.astype(jnp.float32)

        self._fns[key] = prefill_sfx
        return prefill_sfx

    def _verify_fn(self, n_rows: int):
        """Speculative verification — LLaMA twin: GQA query rows ride
        the multi-query paged kernel STEP-major (row = step * rep + r,
        rows_per_step = rep)."""
        cfg, spec = self.cfg, self.spec
        key = ("verify", n_rows)
        if key in self._fns:
            return self._fns[key]
        from deepspeed_tpu.ops.pallas.decode import (
            ln_qkv_int8_stacked, decode_attention_paged,
            out_ffn_int8_stacked, matvec_int8_stacked)
        from deepspeed_tpu.models.llama_inference import _weights
        E, H, Hkv, D = (cfg.hidden_size, cfg.n_heads, cfg.kv_heads,
                        cfg.head_dim)
        Lyr = cfg.n_layers
        rep = H // Hkv
        P = spec.page_size
        MAXP = spec.max_pages_per_slot
        K = n_rows
        eps = cfg.rms_eps
        cache_q8 = self.cache_q8

        def _rms(x, w):
            xf = x.astype(jnp.float32)
            n = xf * jax.lax.rsqrt(
                jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
            return (n * w.astype(jnp.float32)).astype(x.dtype)

        @functools.partial(jax.jit, donate_argnums=(2,))
        def verify(p, blk, pool, toks, pos, pt):
            embed = p["embed"].astype(cfg.dtype)
            head = p["head"].astype(cfg.dtype)
            Wq, sq = _weights(blk, "qkv_w", Lyr)
            Wo, so = _weights(blk, "o_w", Lyr)
            Wg, sg = _weights(blk, "gate_w", Lyr)
            Wu, su = _weights(blk, "up_w", Lyr)
            Wd, sd = _weights(blk, "down_w", Lyr)
            n1 = blk["norm1"].reshape(Lyr, 1, E)
            n2 = blk["norm2"].reshape(Lyr, 1, E)
            B = toks.shape[0]
            blk_ids, rows, posf = _verify_append_ids(pos, pt, K, P, MAXP)
            x = embed[toks].reshape(B * K, E)

            def layer(car, l):
                x, pool = car
                qkv = ln_qkv_int8_stacked(x, n1, None, Wq, sq, None,
                                          l, eps=eps, norm="rms")
                q3 = qkv[:, :H * D].reshape(B * K, H, D)
                k3 = qkv[:, H * D:(H + Hkv) * D].reshape(B * K, Hkv, D)
                v3 = qkv[:, (H + Hkv) * D:].reshape(B * K, Hkv, D)
                q3 = _rope_rows(q3, posf, cfg.rope_theta)
                k3 = _rope_rows(k3, posf, cfg.rope_theta)
                # STEP-major multi-query rows: row j = step * rep + r
                qg = q3.reshape(B, K, Hkv, rep, D) \
                    .transpose(0, 2, 1, 3, 4).reshape(B, Hkv, K * rep, D)
                pool = _append_rows(pool, cache_q8, l, blk_ids,
                                    rows, k3, v3)
                if cache_q8:
                    kc, ks, vc, vs = pool
                    ctx = decode_attention_paged(
                        qg, kc, vc, pos, pt, l, k_scale=ks,
                        v_scale=vs, scale=1.0 / np.sqrt(D),
                        rows_per_step=rep)
                else:
                    kc, vc = pool
                    ctx = decode_attention_paged(
                        qg, kc, vc, pos, pt, l,
                        scale=1.0 / np.sqrt(D), rows_per_step=rep)
                ctx2 = ctx.reshape(B, Hkv, K, rep, D) \
                    .transpose(0, 2, 1, 3, 4).reshape(B * K, H * D)
                if E * E * Wo.dtype.itemsize <= (6 << 20):
                    x = out_ffn_int8_stacked(
                        ctx2, x, Wo, so, None, n2, None, Wg, sg,
                        None, Wd, sd, None, l, act="swiglu",
                        eps=eps, norm="rms", w1b_stack=Wu, s1b=su)
                else:
                    x1 = x + matvec_int8_stacked(ctx2, Wo, so, l)
                    x = out_ffn_int8_stacked(
                        None, x1, None, None, None, n2, None, Wg,
                        sg, None, Wd, sd, None, l, act="swiglu",
                        eps=eps, norm="rms", w1b_stack=Wu, s1b=su,
                        fuse_proj=False)
                return (x, pool), None

            (x, pool), _ = jax.lax.scan(
                layer, (x, pool), jnp.arange(Lyr, dtype=jnp.int32))
            logits = jnp.einsum("be,ve->bv",
                                _rms(x, p["norm_scale"]), head)
            logits32 = logits.astype(jnp.float32)
            greedy = jnp.argmax(logits32, axis=-1).astype(jnp.int32)
            return (pool, greedy.reshape(B, K),
                    logits32.reshape(B, K, -1))

        self._fns[key] = verify
        return verify

    def tick(self, pool, toks, pos, pt, seeds, idxs, temps, steps=1):
        """Run ``steps`` decode steps in ONE dispatch (see the GPT-2
        twin for the seeds/idxs sampling contract). Returns
        (pool, tokens [steps, B], last-step logits [B, V])."""
        return self._tick_fn(steps)(self._p, self._blk, pool, toks, pos,
                                    pt, seeds, idxs, temps)

    def prefill(self, pool, ids, length, pages):
        return self._prefill_fn(ids.shape[1] // self.spec.page_size)(
            self._p, self._blk, pool, ids, length, pages)

    def prefill_suffix(self, pool, ids, length, start, n_pre_pages,
                       pt_row):
        return self._prefill_suffix_fn(
            ids.shape[1] // self.spec.page_size, n_pre_pages)(
            self._p, self._blk, pool, ids,
            jnp.asarray(length, jnp.int32), jnp.asarray(start, jnp.int32),
            jnp.asarray(pt_row))

    def verify(self, pool, toks, pos, pt):
        return self._verify_fn(toks.shape[1])(
            self._p, self._blk, pool, jnp.asarray(toks),
            jnp.asarray(pos), jnp.asarray(pt))

    def copy_block(self, pool, src, dst):
        return _copy_pool_block(pool, jnp.asarray(src, jnp.int32),
                                jnp.asarray(dst, jnp.int32))
