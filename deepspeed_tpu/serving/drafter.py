"""Draft-token proposers for speculative decoding.

The engine's speculative tick needs K-1 cheap draft tokens per active
slot per round; the target model then verifies the whole window in ONE
multi-query paged-attention dispatch (adapters ``verify``) and greedy
accept/reject keeps outputs token-for-token identical to the plain
engine. Two proposers:

- ``NGramDrafter`` — self-drafting / prompt-lookup: match the request's
  trailing n-gram against its own history (prompt + generated) and
  propose the continuation of the most recent earlier occurrence. Pure
  host numpy, no second checkpoint, no device work — the bench's
  default. Wins exactly when generation is repetitive (greedy decode
  loops, structured output, quote-the-prompt tasks); on novel text the
  accept rate collapses toward 0 and each round degenerates to one
  committed token per verify call (see docs/serving.md for when that
  still breaks even).
- ``ModelDrafter`` — a small drafter MODEL (e.g. a GPT-2-small config)
  served through its OWN adapter + paged cache, drafting K-1 greedy
  tokens through the existing multi-step tick machinery. Rollback after
  a rejection is a pointer move: the drafter's cache rows for the
  accepted span were produced by the same fed tokens as the target's,
  so its ``pos`` simply rewinds to the target's committed position and
  stale rows are overwritten by the next round's appends.
"""

from typing import List, Optional

import numpy as np
import jax


def _realign_restored(drafter, slot: int, prompt: np.ndarray,
                      generated: List[int], total_tokens: int) -> None:
    """ONE restore-realignment rule for both drafters: admit the prompt
    with the first committed token, then feed the remaining committed
    tokens through observe_plain (generated[:-1] fed -> generated[1:]
    committed — the engine's own feed/commit alignment, so the
    drafter's pos lands at S + len(generated) - 1 exactly like the
    engine slot's)."""
    prompt = np.asarray(prompt, np.int32)  # sync-ok: host token list
    gen = [int(t) for t in generated]
    assert gen, "a restored slot always holds the prefill-sampled token"
    drafter.admit(slot, prompt, gen[0], total_tokens)
    feed_all = gen[:-1]
    committed_all = gen[1:]
    B = len(getattr(drafter, "pos", getattr(drafter, "_hist", [])))
    off = 0
    while off < len(feed_all):
        # pow2 chunks (largest-first decomposition, capped at the
        # engine's tick ceiling): a ModelDrafter's observe_plain
        # compiles one verify program per distinct row count, and the
        # tick/verify paths only ever dispatch pow2 rows — an
        # arbitrary-length realign here would compile a fresh program
        # per restored progress value, right on the restore hot path
        n = 32
        while n > len(feed_all) - off:
            n //= 2
        cols_feed = np.zeros((n, B), np.int32)
        cols_committed = np.zeros((n, B), np.int32)
        cols_feed[:, slot] = np.asarray(       # sync-ok: host lists
            feed_all[off:off + n], np.int32)
        cols_committed[:, slot] = np.asarray(  # sync-ok: host lists
            committed_all[off:off + n], np.int32)
        drafter.observe_plain([slot], cols_feed, cols_committed)
        off += n


class NGramDrafter:
    """Prompt-lookup drafting: propose the continuation of the most
    recent earlier occurrence of the request's trailing n-gram."""

    aligned = False   # no drafter-side KV state: the engine may commit
    #                   the free correction token on an all-accept round

    def __init__(self, slots: int, ngram_max: int = 3,
                 ngram_min: int = 1):
        assert ngram_max >= ngram_min >= 1, (ngram_max, ngram_min)
        self.ngram_max = ngram_max
        self.ngram_min = ngram_min
        self._hist: List[Optional[np.ndarray]] = [None] * slots

    # -- slot lifecycle (host bookkeeping only) ---------------------------

    def admit(self, slot: int, prompt: np.ndarray,
              first_tok: int, total_tokens: int) -> None:
        self._hist[slot] = np.append(  # sync-ok: prompt is a host
            np.asarray(prompt, np.int32), np.int32(first_tok))  # array

    def release(self, slot: int) -> None:
        self._hist[slot] = None

    def commit(self, slot: int, committed: List[int], new_pos: int,
               last_tok: int) -> None:
        """Append the verifier's committed tokens to the slot history
        (the drafts were speculative — only what the target accepted
        becomes context for the next round)."""
        self._hist[slot] = np.append(  # sync-ok: committed is a host
            self._hist[slot], np.asarray(committed, np.int32))  # list

    def observe_plain(self, slots: List[int], feed: np.ndarray,
                      committed: np.ndarray) -> None:
        """The engine committed ``committed[:, s]`` tokens per slot in a
        PLAIN (non-speculative) tick — history-only realignment here."""
        for s in slots:
            self._hist[s] = np.append(  # sync-ok: host arrays
                self._hist[s], np.asarray(committed[:, s], np.int32))

    def _propose(self, h: np.ndarray, k: int) -> np.ndarray:
        L = len(h)
        for n in range(min(self.ngram_max, L - 1), self.ngram_min - 1,
                       -1):
            pat = h[L - n:]
            if L - 1 < n:
                continue
            win = np.lib.stride_tricks.sliding_window_view(h[:L - 1], n)
            hits = np.nonzero((win == pat).all(axis=1))[0]
            if len(hits):
                i = int(hits[-1])
                cont = h[i + n:i + n + k]
                if len(cont) < k:
                    cont = np.concatenate(
                        [cont, np.full(k - len(cont), h[-1], np.int32)])
                return cont.astype(np.int32)
        return np.full(k, h[-1], np.int32)   # cold: repeat last token

    def draft(self, active_slots: List[int], k: int) -> np.ndarray:
        """[slots..., k] draft tokens for the given active slots (rows
        align with ``active_slots`` order)."""
        return np.stack([self._propose(self._hist[s], k)
                         for s in active_slots])

    def restore_slot(self, slot: int, prompt: np.ndarray,
                     generated: List[int], total_tokens: int) -> None:
        """Realign after an elastic restore (ISSUE 11): the slot's
        committed stream is ``prompt + generated`` and the drafter saw
        none of it — ``admit`` + the existing ``observe_plain``
        contract rebuild exactly the state an uninterrupted run would
        hold (for a ModelDrafter that includes the K/V rows, fed
        through one teacher-forcing verify dispatch)."""
        _realign_restored(self, slot, prompt, generated, total_tokens)


class ModelDrafter:
    """A second (smaller) serving adapter drafting greedy tokens
    through its own paged cache. The drafter's pool is always fully
    provisioned (``num_blocks=0`` default geometry), so its admission
    can never fail after the target's succeeded."""

    aligned = True    # KV state: commits are capped at the drafted rows
    #                   so the drafter cache never claims unwritten rows

    def __init__(self, adapter):
        self.adapter = adapter
        self.cache = adapter.make_cache()
        slots = adapter.spec.slots
        self.pos = np.full(slots, -1, np.int64)
        self.last = np.zeros(slots, np.int64)
        # drafting is greedy-only: no rng anywhere in this class
        self._temps = np.zeros(slots, np.float32)

    def admit(self, slot: int, prompt: np.ndarray, first_tok: int,
              total_tokens: int) -> None:
        prompt = np.asarray(prompt, np.int32)  # sync-ok: host prompt
        S = len(prompt)
        pages = self.cache.admit(slot, total_tokens)
        assert pages is not None, \
            "drafter pool exhausted — size it fully provisioned"
        # bucketed prompt prefill, the engine admission's page-padding
        # contract (shared helper — the two paths must not drift)
        from deepspeed_tpu.serving.paged_cache import \
            padded_prefill_inputs
        import jax.numpy as jnp
        P = self.adapter.spec.page_size
        ids, page_vec = padded_prefill_inputs(
            prompt, pages, P, self.adapter.max_prompt_len() // P)
        pool, _ = self.adapter.prefill(
            self.cache.pool, jnp.asarray(ids), jnp.asarray(S, jnp.int32),
            jnp.asarray(page_vec))
        self.cache.pool = pool
        # the target's first (prefill-sampled) token is the drafter's
        # next feed — its own prefill prediction is discarded so the
        # two caches stay aligned on the committed stream
        self.pos[slot] = S
        self.last[slot] = first_tok

    def release(self, slot: int) -> None:
        self.cache.release(slot)
        self.pos[slot] = -1
        self.last[slot] = 0

    def commit(self, slot: int, committed: List[int], new_pos: int,
               last_tok: int) -> None:
        """Rollback/fast-forward to the verifier's outcome: rows for the
        accepted span were fed the same tokens on both models, so the
        drafter just adopts the target's committed position (stale draft
        rows beyond it are overwritten by the next round's appends)."""
        self.pos[slot] = new_pos
        self.last[slot] = last_tok

    def observe_plain(self, slots: List[int], feed: np.ndarray,
                      committed: np.ndarray) -> None:
        """The engine committed tokens in a PLAIN tick the drafter never
        drafted for: teacher-force the fed tokens through the drafter's
        own cache (one ``verify`` append dispatch — its greedy output is
        discarded, only the K/V rows matter) so ``pos`` can fast-forward
        over rows that actually exist. Skipping this would leave the
        drafter's cache holding NO rows at the committed positions and
        every later draft round attending garbage."""
        B = len(self.pos)
        V = feed.shape[0]
        toks = np.zeros((B, V), np.int32)
        pos = np.full((B,), -1, np.int32)
        for s in slots:
            toks[s] = feed[:, s]
            pos[s] = self.pos[s]
        pool, _, _ = self.adapter.verify(self.cache.pool, toks, pos,
                                         self.cache.page_table)
        self.cache.pool = pool
        for s in slots:
            self.pos[s] += V
            self.last[s] = int(committed[-1, s])

    def draft(self, active_slots: List[int], k: int) -> np.ndarray:
        """k greedy draft tokens per active slot via one k-step tick
        over the drafter's own paged cache."""
        import jax.numpy as jnp
        toks = np.asarray(self.last, np.int32)  # sync-ok: host ints
        pos = np.asarray(self.pos, np.int32)    # sync-ok: host ints
        B = len(self.pos)
        # greedy drafting: the per-slot sampling seeds are never used
        # (temps stay 0), zeros keep the compiled tick signature shared
        # with the target engine's
        pool, toks_seq, _ = self.adapter.tick(
            self.cache.pool, jnp.asarray(toks), jnp.asarray(pos),
            jnp.asarray(self.cache.page_table),
            jnp.zeros((B,), jnp.uint32), jnp.zeros((B,), jnp.int32),
            jnp.asarray(self._temps), steps=k)
        self.cache.pool = pool
        toks_seq = np.asarray(toks_seq)   # sync-ok: drafts feed the
        #                                   host accept/reject loop
        for s in active_slots:
            self.pos[s] += k              # provisional; commit() rewinds
            self.last[s] = toks_seq[-1, s]
        return toks_seq[:, active_slots].T.astype(np.int32)

    def restore_slot(self, slot: int, prompt: np.ndarray,
                     generated: List[int], total_tokens: int) -> None:
        """Elastic-restore realignment (see NGramDrafter.restore_slot):
        re-prefill the prompt through the drafter's own cache, then
        teacher-force the committed tokens so its K/V holds real rows
        at every committed position."""
        _realign_restored(self, slot, prompt, generated, total_tokens)
