"""Replica-pool supervisor: N serving engines, cross-replica resume,
watchdog-driven autoscaling (ISSUE 11).

One :class:`ReplicaPool` runs N in-process
:class:`~deepspeed_tpu.serving.engine.ContinuousBatcher` replicas
(sharing one adapter's compiled programs — the long-lived-server shape
the serving bench measures) and owns the request ledger above them:

- **dispatch**: arrivals go to the least-loaded live replica;
- **recovery**: a replica that dies (an injected ``SimulatedCrash``
  unwinding out of its ``step()``, or :meth:`kill_replica`) is
  recovered from its last COMMITTED elastic snapshot — the snapshotted
  requests restore onto the least-loaded survivor
  (``elastic.restore_serving``: direct slot rebuilds + replay
  requeues), and anything the snapshot predates is re-served from the
  pool's own ledger. Every re-serve attempt is bounded
  (``max_retries``) with jittered exponential backoff (``backoff_s``)
  so a poisoned request cannot ping-pong across the pool forever.
  Greedy decoding makes every recovery path token-for-token lossless:
  replayed requests regenerate exactly the continuation the dead
  replica would have produced.
- **autoscale** (``scale_signal="watchdog"``): the PR 6 watchdog's
  LATCHED incident rules are the scale-up signal — new
  ``ttft_blowup`` / ``page_pool_exhausted`` trips on any replica add a
  replica (up to ``max_replicas``); a pool that stays overprovisioned
  for ``scale_down_idle_rounds`` consecutive rounds drains its
  least-loaded replica through the SAME snapshot path (preempt →
  drain-or-snapshot → restore onto survivors) down to
  ``min_replicas``. Both directions land a ``replica_scale`` ring
  event.

The pool is deliberately host-side and single-threaded: one round of
:meth:`step` steps every replica once, so the device work interleaves
exactly like the single-engine scheduler's and the fault points fire
at deterministic places (the property the recovery tests pin).
"""

import json
import os
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.runtime.elastic.faults import SimulatedCrash
from deepspeed_tpu.serving import elastic
from deepspeed_tpu.serving.engine import Request, ensure_trace_id
from deepspeed_tpu.telemetry.recorder import default_recorder
from deepspeed_tpu.utils.logging import logger


def _req_to_doc(req):
    """Pool-ledger doc for a request as SUBMITTED (no progress) — the
    fresh re-serve fallback when no snapshot covers it. Same schema as
    the snapshot's slot docs (ONE serializer, progress zeroed)."""
    return dict(elastic._req_doc(req), generated=[])


def save_ledger(path, docs) -> None:
    """Persist a ``{rid: submitted doc}`` ledger atomically (tmp +
    rename — a SIGKILL mid-write leaves the previous valid file, never
    a torn one). ISSUE 17: the supervisor-respawned router rank
    re-serves the UNFINISHED slice of this ledger; greedy replay from
    the submitted docs is token-lossless, the PR-11 pool-ledger
    recovery rule applied across a process death."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump({str(rid): doc for rid, doc in docs.items()}, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def load_ledger(path):
    """The saved ``{rid: doc}`` map (string rids — the caller's docs
    carry the native rid in ``doc["rid"]``), or None when absent."""
    try:
        with open(path) as fh:
            return json.load(fh)
    except OSError:
        return None


def percentile_summary(vals):
    """count/mean/p50/p90/p99 of a raw host reservoir — ONE percentile
    rule shared by the pool's and the router's metrics_snapshot so the
    two aggregation documents can't drift."""
    if not vals:
        return {"count": 0}
    v = np.asarray(vals, np.float64)       # sync-ok: host reservoirs
    return {"count": int(v.size),
            "mean": float(v.mean()),                  # sync-ok: host
            "p50": float(np.percentile(v, 50)),       # sync-ok: host
            "p90": float(np.percentile(v, 90)),       # sync-ok: host
            "p99": float(np.percentile(v, 99))}       # sync-ok: host


def merged_reservoir(engines, name):
    """Concatenate one histogram's raw values across engines, counting
    a SHARED registry once (the bench's merged-stream case)."""
    vals, seen = [], set()
    for cb in engines:
        if id(cb.metrics) in seen:
            continue
        seen.add(id(cb.metrics))
        vals += cb.metrics.peek_histogram_values(name)
    return vals


class ReplicaPool:
    """See module docstring. ``factory(replica_id)`` builds one
    batcher — give each replica its OWN elastic snapshot dir (e.g.
    ``snapshot_root/replica_<id>``) and its own watchdog; crash
    recovery needs the former, autoscaling the latter."""

    def __init__(self, factory, n_replicas=1, min_replicas=1,
                 max_replicas=None, scale_signal="watchdog",
                 max_retries=3, backoff_s=0.05,
                 scale_down_idle_rounds=40, recorder=None,
                 watchdog=None, seed=0, slo_registry=None):
        self.factory = factory
        # ISSUE 19: scale_signal="slo" reads the windowed slo/* gauge
        # plane from here (an exported registry — typically the rank-0
        # node's); None falls back to the first live replica's registry
        self.slo_registry = slo_registry
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas
                                if max_replicas is not None
                                else max(n_replicas, min_replicas))
        self.scale_signal = str(scale_signal)
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)   # sync-ok: config scalar
        self.scale_down_idle_rounds = int(scale_down_idle_rounds)
        self.recorder = recorder if recorder is not None \
            else default_recorder()
        self.watchdog = watchdog
        self._rng = np.random.RandomState(seed)
        self._next_id = 0
        self.replicas: "OrderedDict[int, Any]" = OrderedDict()
        self._draining = set()          # replica ids scaling down
        self._trip_base: Dict[int, int] = {}
        self._assign: Dict[Any, int] = {}      # rid -> replica id
        self._ledger: Dict[Any, dict] = {}     # rid -> submitted doc
        self._attempts: Dict[Any, int] = {}
        self._resume_q = deque()        # (ready_time, doc) re-serves
        self.done: Dict[Any, Request] = {}
        self.lost: Dict[Any, dict] = {}
        self.parked_snapshots: List[str] = []
        self._idle_rounds = 0
        # latched when a replica parks from a NON-scale-down preemption
        # (a process-wide SIGTERM): the pool stops respawning — the
        # final snapshots on disk are the hand-off, not a restart
        self.shutdown = False
        self.stats = {"kills": 0, "preempts": 0, "recovered_direct": 0,
                      "recovered_requeued": 0, "resubmitted_fresh": 0,
                      "lost": 0, "scale_ups": 0, "scale_downs": 0,
                      "restore_s_total": 0.0}
        for _ in range(max(int(n_replicas), self.min_replicas)):
            self._spawn(reason="init", record=False)

    @classmethod
    def from_config(cls, factory, config, n_replicas=None, **kw):
        """Build from the ``serving.autoscale`` + ``serving.elastic``
        blocks of a DeepSpeed-style config (dict or json path)."""
        from deepspeed_tpu.serving import _serving_section
        sc = _serving_section(config)
        a, e = sc.autoscale, sc.elastic
        return cls(factory,
                   n_replicas=(a.min_replicas if n_replicas is None
                               else n_replicas),
                   min_replicas=a.min_replicas,
                   max_replicas=a.max_replicas,
                   scale_signal=a.scale_signal,
                   max_retries=e.max_retries if e.enabled
                   else kw.pop("max_retries", 3),
                   backoff_s=e.backoff_s if e.enabled
                   else kw.pop("backoff_s", 0.05),
                   **kw)

    # ---------------------------------------------------------- replicas

    def _spawn(self, reason="scale_up", record=True):
        rid = self._next_id
        self._next_id += 1
        cb = self.factory(rid)
        # ISSUE 12: ring events from this batcher self-identify — the
        # replicas share one process-wide recorder, and the stitched
        # per-trace timeline needs to know which replica emitted what
        cb.replica_id = rid
        self.replicas[rid] = cb
        wd = cb.watchdog
        self._trip_base[rid] = self._trips_of(wd)
        if record:
            self.stats["scale_ups"] += 1
            self.recorder.record("replica_scale", direction="up",
                                 replica=rid, reason=reason,
                                 replicas=len(self.replicas))
        return rid

    @staticmethod
    def _trips_of(wd):
        if wd is None:
            return 0
        return wd.trips.get("ttft_blowup", 0) \
            + wd.trips.get("page_pool_exhausted", 0)

    def _live(self):
        return [(rid, cb) for rid, cb in self.replicas.items()
                if rid not in self._draining]

    def _least_loaded(self, exclude=()):
        best, best_load = None, None
        for rid, cb in self._live():
            if rid in exclude:
                continue
            load = len(cb.queue) + sum(s.active for s in cb.slots)
            if best_load is None or load < best_load:
                best, best_load = rid, load
        return best

    @property
    def pending(self) -> int:
        n = len(self._resume_q)
        for _rid, cb in self.replicas.items():
            n += cb.pending
        return n

    # ----------------------------------------------------------- ledger

    def submit(self, request: Request) -> None:
        ensure_trace_id(request)   # before the ledger doc freezes it
        self._ledger[request.rid] = _req_to_doc(request)
        self._attempts.setdefault(request.rid, 0)
        self._dispatch(request)

    def _dispatch(self, request: Request) -> None:
        target = self._least_loaded()
        if target is None:
            # no live replica (whole-pool preemption): hold as a
            # resume doc so a later spawn can pick it up
            self._resume_q.append((0.0, _req_to_doc(request)))
            return
        self._assign[request.rid] = target
        self.replicas[target].submit(request)

    def _schedule_reserve(self, doc, immediate=False):
        """Queue one snapshot/ledger doc for re-serving, with bounded
        retries + jittered exponential backoff."""
        rid = doc["rid"]
        self._attempts[rid] = self._attempts.get(rid, 0) + 1
        if self._attempts[rid] > self.max_retries:
            self.stats["lost"] += 1
            self.lost[rid] = doc
            self.recorder.record("serving_requeue", rid=rid,
                                 trace=doc.get("trace_id"),
                                 outcome="dropped",
                                 attempts=self._attempts[rid])
            logger.warning(f"request {rid!r} dropped after "
                           f"{self._attempts[rid] - 1} recovery retries")
            return
        delay = 0.0
        if not immediate:
            delay = self.backoff_s * (2 ** (self._attempts[rid] - 1)) \
                * float(self._rng.uniform(0.5, 1.5))  # sync-ok: host rng
        self._resume_q.append((time.monotonic() + delay, doc))
        self.recorder.record("serving_requeue", rid=rid,
                             trace=doc.get("trace_id"),
                             outcome="scheduled",
                             attempts=self._attempts[rid],
                             backoff_s=delay,
                             committed=len(doc["generated"]))

    def _drain_resume_q(self):
        now = time.monotonic()
        later = deque()
        while self._resume_q:
            ready, doc = self._resume_q.popleft()
            if ready > now or self._least_loaded() is None:
                later.append((ready, doc))
                continue
            req = elastic.resume_request(doc)
            target = self._least_loaded()
            self._assign[doc["rid"]] = target
            self.replicas[target].submit(req)
            if doc["generated"]:
                self.stats["recovered_requeued"] += 1
            else:
                self.stats["resubmitted_fresh"] += 1
        self._resume_q = later

    # --------------------------------------------------------- recovery

    def kill_replica(self, replica_id, reason="killed") -> None:
        """Hard-kill one replica (the injected-fault stand-in for a
        dead process): its batcher is discarded WITHOUT a final
        snapshot — recovery runs from its last committed one."""
        assert replica_id in self.replicas, replica_id
        self.stats["kills"] += 1
        self.recorder.record("replica_kill", replica=replica_id,
                             reason=reason)
        if self.watchdog is not None:
            self.watchdog.note_preempt(source=f"replica_{replica_id}_"
                                       f"{reason}")
            self.watchdog.note_preempt_ok()   # a pool outlives its
            #                              replicas: re-arm for the next
        self._recover(replica_id, final_snapshot=False)

    def preempt_replica(self, replica_id, source="scale_down") -> None:
        """Graceful removal: request preemption on the replica's
        elastic controller; its next steps run the drain-or-snapshot
        path and the pool recovers the snapshot once it parks."""
        cb = self.replicas[replica_id]
        assert cb.elastic is not None, \
            "preempt_replica needs an elastic controller on the replica"
        self._draining.add(replica_id)
        cb.elastic.request_preemption(source)

    def _recover(self, replica_id, final_snapshot):
        cb = self.replicas.pop(replica_id)
        self._draining.discard(replica_id)
        self._trip_base.pop(replica_id, None)
        snap_dir = None
        if cb.elastic is not None:
            snap_dir = cb.elastic.last_snapshot_dir if final_snapshot \
                else None
            if snap_dir is None:
                snap_dir = cb.elastic.snapshot_dir
            # release, NOT close: restoring the signal table mid-chain
            # would drop every later-installed replica's handler (the
            # dead controller's own handler is a weakref pass-through)
            cb.elastic.release()
        assigned = {rid for rid, r in self._assign.items()
                    if r == replica_id and rid not in self.done}
        recovered = set()
        t0 = time.perf_counter()
        if snap_dir and os.path.isdir(snap_dir) and assigned:
            loaded = self._load_snapshot(snap_dir)
            if loaded is not None:
                host, kv = loaded
                # the snapshot may predate finishes the pool already
                # collected — and may cover rids later re-assigned
                # elsewhere; serve only what is still this replica's
                host = dict(host)
                host["slots"] = [d for d in host["slots"]
                                 if d["rid"] in assigned]
                host["queued"] = [d for d in host["queued"]
                                  if d["rid"] in assigned]
                target = self._least_loaded()
                if target is not None:
                    try:
                        res = elastic.restore_serving(
                            self.replicas[target], host, kv,
                            requeue_overflow=False)
                    except elastic.ServingRestoreError as e:
                        # e.g. a replay prompt outgrew the target's
                        # prompt-page budget: the snapshot can't land
                        # here — fall through to ledger re-serves
                        # (fresh replays always fit what submit once
                        # accepted) rather than crash the supervisor
                        logger.warning(
                            f"snapshot of replica {replica_id} not "
                            f"restorable onto replica {target}: {e}")
                        res = None
                    if res is not None:
                        for req in res["restored"]:
                            self._assign[req.rid] = target
                            recovered.add(req.rid)
                        self.stats["recovered_direct"] += \
                            len(res["restored"])
                        for doc in res["overflow"]:
                            recovered.add(doc["rid"])
                            self._schedule_reserve(doc, immediate=True)
        for rid in sorted(assigned - recovered, key=str):
            # no snapshot coverage: re-serve from the pool ledger
            self._schedule_reserve(self._ledger[rid])
        self.stats["restore_s_total"] += time.perf_counter() - t0

    def _load_snapshot(self, snap_dir):
        if elastic.is_snapshot_dir(snap_dir):
            try:
                return elastic.load_serving_snapshot(snap_dir)
            except elastic.SnapshotCorrupt as e:
                logger.warning(f"replica snapshot {snap_dir} invalid: "
                               f"{e}")
                return None
        loaded = elastic.load_latest_serving(snap_dir)
        if loaded is None:
            return None
        host, kv, _cand = loaded
        return host, kv

    # ------------------------------------------------------------- step

    def step(self, now: Optional[float] = None) -> List[Request]:
        """One pool round: due re-serves dispatch, every replica steps
        once (crashes and drain-completions recover inline), autoscale
        runs last. Returns requests finished this round."""
        # a supervisor maintains its floor: kills respawn up to
        # min_replicas — unless the pool itself is being preempted
        while not self.shutdown \
                and len(self.replicas) < self.min_replicas \
                and (self.pending or len(self.replicas) == 0):
            self._spawn(reason="min_replicas")
        self._drain_resume_q()
        finished = []
        for replica_id, cb in list(self.replicas.items()):
            if replica_id not in self.replicas:
                continue            # recovered away mid-round
            try:
                out = cb.step(now)
            except SimulatedCrash as e:
                self.stats["kills"] += 1
                self.recorder.record("replica_kill", replica=replica_id,
                                     reason=repr(e))
                if self.watchdog is not None:
                    self.watchdog.note_preempt(
                        source=f"replica_{replica_id}_crash")
                    self.watchdog.note_preempt_ok()
                self._recover(replica_id, final_snapshot=False)
                continue
            for req in out:
                self.done[req.rid] = req
                self._assign.pop(req.rid, None)
            finished.extend(out)
            if cb.preempted:
                # drain-or-snapshot finished (scale-down or SIGTERM):
                # recover its committed snapshot onto survivors
                self.stats["preempts"] += 1
                was_scaling = replica_id in self._draining
                if not was_scaling:
                    self.shutdown = True   # a real preemption, not our
                    #                        own scale-down: stop
                    #                        respawning
                self._recover(replica_id, final_snapshot=True)
                if cb.elastic is not None \
                        and cb.elastic.last_snapshot_dir \
                        and not self._live():
                    # whole-pool preemption: nothing to requeue onto —
                    # the snapshot on disk IS the hand-off
                    self.parked_snapshots.append(
                        cb.elastic.last_snapshot_dir)
                if was_scaling:
                    self.stats["scale_downs"] += 1
                    self.recorder.record(
                        "replica_scale", direction="down",
                        replica=replica_id, reason="idle",
                        replicas=len(self.replicas))
        self._autoscale()
        return finished

    def _autoscale(self):
        if self.scale_signal == "slo":
            self._autoscale_slo()
            return
        if self.scale_signal != "watchdog":
            return
        trips = 0
        for rid, cb in list(self.replicas.items()):
            t = self._trips_of(cb.watchdog)
            base = self._trip_base.get(rid, 0)
            if t > base:
                trips += t - base
            self._trip_base[rid] = t
        if trips and len(self.replicas) < self.max_replicas:
            self._idle_rounds = 0
            new = self._spawn(reason=f"watchdog_trips:{trips}")
            logger.info(f"replica pool scaled UP to "
                        f"{len(self.replicas)} (replica {new}; "
                        f"{trips} new watchdog trips)")
            return
        # scale-down hysteresis: the pool must look overprovisioned
        # (all pending work fits comfortably in n-1 replicas' slots)
        # for scale_down_idle_rounds consecutive rounds
        live = self._live()
        if len(live) <= self.min_replicas or self._draining:
            self._idle_rounds = 0
            return
        slots_per = [len(cb.slots) for _, cb in live]
        capacity_wo_one = sum(slots_per) - max(slots_per)
        if self.pending <= capacity_wo_one // 2:
            self._idle_rounds += 1
        else:
            self._idle_rounds = 0
        if self._idle_rounds >= self.scale_down_idle_rounds:
            self._idle_rounds = 0
            victim = self._least_loaded()
            if victim is not None:
                self.preempt_replica(victim, source="scale_down")

    def slo_recommendation(self):
        """The per-role ``{"prefill"|"decode": "up"|"down"|"hold"}``
        the windowed SLO plane (telemetry/slo.py) last exported —
        derived PURELY from ``slo/*`` gauges, never from the plane
        object (the consumer contract ISSUE 19 pins). Empty when no
        registry is reachable yet."""
        from deepspeed_tpu.telemetry.slo import roles_signal
        reg = self.slo_registry
        if reg is None:
            live = self._live()
            reg = live[0][1].metrics if live else None
        return roles_signal(reg) if reg is not None else {}

    def _autoscale_slo(self):
        """Burn-rate autoscaling (ISSUE 19): a role whose windowed
        error-budget burn crossed ``up_burn`` spawns immediately (the
        window IS the hysteresis — 30s of sustained violations, not
        one bad request); scale-down needs a "down" verdict, no "up"
        anywhere, and the same consecutive-round patience as the
        watchdog path (two hysteresis layers on the shrink side,
        because a wrong shrink costs a drain + restore)."""
        roles = self.slo_recommendation()
        if not roles:
            return
        hot = sorted(r for r, a in roles.items() if a == "up")
        if hot and len(self.replicas) < self.max_replicas:
            self._idle_rounds = 0
            new = self._spawn(reason="slo_burn:" + ",".join(hot))
            logger.info(f"replica pool scaled UP to "
                        f"{len(self.replicas)} (replica {new}; "
                        f"slo burn on {','.join(hot)})")
            return
        live = self._live()
        if len(live) <= self.min_replicas or self._draining or hot:
            self._idle_rounds = 0
            return
        if any(a == "down" for a in roles.values()):
            self._idle_rounds += 1
        else:
            self._idle_rounds = 0
        if self._idle_rounds >= self.scale_down_idle_rounds:
            self._idle_rounds = 0
            victim = self._least_loaded()
            if victim is not None:
                self.preempt_replica(victim, source="scale_down")

    # -------------------------------------------------------------- run

    def run(self, requests, respect_arrival_times=False,
            timeout_s=None) -> Dict[Any, Request]:
        """Serve every request to completion (or loss) across the pool
        — the multi-replica ``serve()``. Poisson arrival semantics
        match the single engine's: with ``respect_arrival_times`` a
        request becomes dispatchable at its ``arrival_time`` against a
        wall clock started on entry."""
        todo = deque(sorted(requests, key=lambda r: r.arrival_time))
        t0 = time.monotonic()
        if not respect_arrival_times:
            while todo:
                self.submit(todo.popleft())
        while True:
            now = time.monotonic() - t0
            while todo and (todo[0].arrival_time <= now):
                self.submit(todo.popleft())
            if not todo and not self.pending:
                break
            if timeout_s is not None and now > timeout_s:
                logger.warning(f"replica pool run timed out with "
                               f"{self.pending} pending")
                break
            if self.shutdown and not self.replicas:
                break   # whole pool preempted: the parked snapshots
                #         are the hand-off. (A mere crash of the last
                #         replica is NOT this — step() respawns to
                #         min_replicas and the pending work continues.)
            stepped = self.step(now if respect_arrival_times else None)
            if not stepped and not any(
                    any(s.active for s in cb.slots) or cb.queue
                    for cb in self.replicas.values()):
                time.sleep(0.002)   # waiting on arrivals / backoff
        return dict(self.done)

    def close(self):
        # release (not close) every controller: restoring chained
        # signal handlers out of install order corrupts the chain; the
        # leftover handlers are inert weakref pass-throughs
        for rid in list(self.replicas):
            cb = self.replicas.pop(rid)
            if cb.elastic is not None:
                cb.elastic.release()

    def snapshot_stats(self) -> Dict[str, Any]:
        return {
            "replicas": len(self.replicas),
            "draining": len(self._draining),
            "pending": self.pending,
            "done": len(self.done),
            "lost": len(self.lost),
            **self.stats,
        }

    def metrics_snapshot(self) -> Dict[str, Any]:
        """Pool-level aggregation of every replica's
        ``metrics_snapshot()`` (ISSUE 12): pool TTFT percentiles over
        the MERGED raw reservoirs (averaging per-replica percentiles
        would be wrong under skewed load), per-replica slot
        utilization / queue depth, and the pool's lost / retried /
        recovered counters — the document the serving bench embeds and
        a disaggregated router would schedule on."""
        per_replica = {}
        active = slots = queued = 0
        # peek, don't histogram(): get-or-create would seed an idle
        # replica's registry with phantom empty metrics
        ttft = merged_reservoir(self.replicas.values(), "serving/ttft_s")
        waits = merged_reservoir(self.replicas.values(),
                                 "serving/admission_wait_s")
        for rid, cb in self.replicas.items():
            a = sum(s.active for s in cb.slots)
            active += a
            slots += len(cb.slots)
            queued += len(cb.queue)
            per_replica[rid] = {
                "active_slots": a,
                "slots": len(cb.slots),
                "slot_utilization": a / max(len(cb.slots), 1),
                "queue_depth": len(cb.queue),
                "draining": rid in self._draining,
                "decode_tokens": cb.stats["decode_tokens"],
                "dump_id": cb.watchdog.dump_id
                if cb.watchdog is not None else 0,
            }

        return {
            "replicas": len(self.replicas),
            "per_replica": per_replica,
            "pool_ttft_s": percentile_summary(ttft),
            "pool_admission_wait_s": percentile_summary(waits),
            "active_slots": active,
            "total_slots": slots,
            "slot_utilization": active / max(slots, 1),
            "queue_depth": queued,
            "pending": self.pending,
            "done": len(self.done),
            "lost": len(self.lost),
            "retried": sum(1 for a in self._attempts.values() if a > 0),
            **self.stats,
        }
