"""Elastic preemption-tolerant serving (ISSUE 11 tentpole).

A preempted serving process used to lose every in-flight request and
the whole prefix cache. This module points PR 7's elastic machinery at
the continuous-batching engine:

- :func:`capture_state` — one consistent host-side capture of a
  :class:`~deepspeed_tpu.serving.engine.ContinuousBatcher` at a tick
  boundary: per-slot request state (token stream, sampling params,
  page-table rows), the queued requests, the prefix index, and the
  K/V bytes of every REFERENCED pool block (one device gather + d2h
  per pool component — never the whole pool).
- :func:`snapshot_serving` — the capture written through
  :class:`~deepspeed_tpu.runtime.elastic.snapshot.AsyncSnapshotter`:
  async aio writes, crc32-manifested index, and the two-rename
  ``commit_dir_swap`` commit, so a crash mid-commit recovers to the
  previous valid snapshot exactly like a training checkpoint.
- :func:`restore_serving` — rebuild the requests on a DIFFERENT
  engine (different slot count, different pool size, different
  replica): saved pages re-register through the refcounted allocator
  (shared pages stay shared), the prefix index re-imports its entries
  so the hit-rate survives the restore, spec drafters realign through
  the existing ``observe_plain`` contract, and requests that don't fit
  the target's free slots REQUEUE as replay requests (the committed
  stream becomes the admission prompt — greedy decoding regenerates
  the same continuation token for token).
- :class:`ElasticServingController` — the drain-or-snapshot policy at
  every tick boundary: on SIGTERM (``runtime/elastic/preemption.py``'s
  lock-free handler chain) the engine stops admitting and keeps
  ticking while the closest-to-done request still fits the remaining
  grace budget; when nothing more can finish in time, everything left
  is snapshotted and the engine parks (``cb.preempted``). Periodic
  snapshots (``interval_ticks``) overlap the following ticks the same
  way training snapshots overlap the next step.

K/V pages are APPEND-ONLY and ``slot.pos`` advances only on commit, so
a snapshot taken at a tick boundary contains committed tokens only —
a SIGTERM landing mid-speculation rolls back to the last verified
token by construction (the rows past ``pos`` are never captured as
state, only as dead bytes in their pages).
"""

import os
import time

import numpy as np
import jax.numpy as jnp

from deepspeed_tpu.runtime.elastic import faults
from deepspeed_tpu.runtime.elastic.preemption import PreemptionHandler
from deepspeed_tpu.runtime.elastic.snapshot import (
    AsyncSnapshotter, SnapshotCorrupt, SnapshotReader, is_snapshot_dir)
from deepspeed_tpu.serving.engine import Request
from deepspeed_tpu.utils.logging import logger

SERVING_KIND = "dstpu-serving-elastic-1"


class ServingRestoreError(ValueError):
    """The snapshot cannot be restored onto this engine (incompatible
    cache geometry) — distinct from SnapshotCorrupt: the snapshot is
    fine, the target is wrong."""


# --------------------------------------------------------------- capture

def _req_doc(req):
    return {
        "rid": req.rid,
        "prompt": np.asarray(req.prompt, np.int32).tolist(),  # sync-ok:
        #                                             host token arrays
        "generated": [int(t) for t in req.generated],
        "max_new_tokens": int(req.max_new_tokens),
        "eos_token_id": None if req.eos_token_id is None
        else int(req.eos_token_id),
        "temperature": float(req.temperature),
        # ISSUE 12: the trace identity survives snapshot -> restore ->
        # requeue handoffs — the stitched cross-replica timeline hangs
        # off this field
        "trace_id": getattr(req, "trace_id", None),
        # ISSUE 19: the root span id travels with the trace identity —
        # a request restored or handed off on another rank keeps
        # parenting its lifecycle spans onto the tree it was born into
        "span_id": getattr(req, "span_id", None),
        # ISSUE 14 (PR-11 caveat fix): the sampling identity. With
        # sample_key + the CUMULATIVE committed-token count persisted,
        # a sampled (temperature > 0) request restores/replays with the
        # same per-token fold_in keys the uninterrupted run uses — not
        # fresh rng. committed_total counts across incarnations (a
        # replay folds generated into the prompt; the index must not
        # reset with it).
        "sample_key": getattr(req, "sample_key", None),
        "committed_total": int(getattr(req, "resumed_committed", 0) or 0)
        + len(req.generated),
    }


def capture_state(cb):
    """One consistent capture of a batcher at a tick boundary. Returns
    ``(host_state, kv)``: ``host_state`` is a JSON-able dict (slots,
    queue, prefix index, page map) and ``kv`` maps ``c<j>`` to the
    j-th pool component's referenced blocks ``[Lyr, n_pages, ...]``
    (host numpy — the snapshot's only device readback)."""
    cache = cb.cache
    blocks, index_of = [], {}

    def sidx(blk):
        blk = int(blk)
        if blk not in index_of:
            index_of[blk] = len(blocks)
            blocks.append(blk)
        return index_of[blk]

    slots_doc = []
    for i, slot in enumerate(cb.slots):
        if not slot.active:
            continue
        slots_doc.append({
            **_req_doc(slot.request),
            "pos": int(slot.pos),
            "last_tok": int(slot.last_tok),
            "pages": [sidx(b) for b in cache.slot_pages(i)],
        })
    queued_doc = [_req_doc(r) for r in cb.queue]
    prefix_doc = None
    if cb.prefix_cache:
        exp = cache.export_prefix_entries()
        prefix_doc = {
            "full": [{"page": sidx(e["block"]), "key": e["key"],
                      "tokens": e["tokens"]} for e in exp["full"]],
            "partial": [{"page": sidx(e["block"]), "chain": e["chain"],
                         "tokens": e["tokens"]} for e in exp["partial"]],
        }
    host = {
        "format": SERVING_KIND,
        "slots": slots_doc,
        "queued": queued_doc,
        "prefix": prefix_doc,
        "n_pages": len(blocks),
        "page_size": int(cache.spec.page_size),
        "kv_cache_bits": int(cache.spec.kv_cache_bits),
    }
    kv = {}
    if blocks:
        sel = jnp.asarray(np.asarray(blocks, np.int32))  # sync-ok: host
        #                                                  block-id list
        for j, comp in enumerate(cache.pool):
            # the one deliberate d2h of the snapshot: only REFERENCED
            # blocks leave the device, gathered in one op per component
            kv[f"c{j}"] = np.asarray(comp[:, sel])  # sync-ok: snapshot
            #                                         capture d2h
    return host, kv


# -------------------------------------------------------------- snapshot

def snapshot_serving(cb, snapshotter, tag, meta=None, finalize=True):
    """Write one committed serving snapshot through ``snapshotter``
    (an :class:`AsyncSnapshotter` rooted at the serving snapshot dir).
    With ``finalize=False`` the aio writes are left in flight so they
    overlap the following ticks — call ``snapshotter.finalize()`` at a
    later tick boundary (the controller's periodic mode). Returns the
    committed directory (or None when not finalizing)."""
    host, kv = capture_state(cb)
    # the marker leaf keeps a request-only snapshot (queued work, zero
    # pages) readable — SnapshotReader rejects an empty leaf index
    trees = {"serving_kv": dict(kv, marker=np.zeros(1, np.uint8))}
    n_req = len(host["slots"]) + len(host["queued"])
    snapshotter.begin(tag, trees, extra={"serving": host},
                      meta={"kind": SERVING_KIND, **(meta or {})})
    cb._record("serving_snapshot", tag=str(tag), requests=n_req,
               slots=len(host["slots"]),
               queued=len(host["queued"]),
               pages=host["n_pages"],
               traces=[d.get("trace_id") for d in
                       host["slots"] + host["queued"]])
    if finalize:
        path, _stall = snapshotter.finalize()
        return path
    return None


def load_serving_snapshot(snap_dir, verify=True):
    """Validated load of one committed serving snapshot: manifest +
    per-file crc32 checks up front (:class:`SnapshotReader`), then the
    host state doc and the K/V component arrays. Raises
    :class:`SnapshotCorrupt` on any validation failure."""
    reader = SnapshotReader(snap_dir, verify=verify)
    if reader.manifest.get("kind") != SERVING_KIND:
        raise SnapshotCorrupt(
            f"{snap_dir} is not a serving snapshot "
            f"(kind={reader.manifest.get('kind')!r})")
    host = (reader.manifest.get("extra") or {}).get("serving")
    if not isinstance(host, dict) or host.get("format") != SERVING_KIND:
        raise SnapshotCorrupt(f"{snap_dir} carries no serving state doc")
    kv = reader.assemble("serving_kv")
    kv.pop("marker", None)
    reader.close()
    return host, kv


def load_latest_serving(snapshot_dir, on_corrupt=None, verify=True):
    """Newest serving snapshot under ``snapshot_dir`` that validates,
    as ``(host_state, kv, snap_dir)`` — or None. Same recovery policy
    as training resume (mtime order, ``latest`` pointer as tie-break,
    ``.old`` crash-window siblings): corrupt candidates invoke
    ``on_corrupt(path, exc)`` and are skipped."""
    from deepspeed_tpu.runtime.elastic.resume import _candidates
    for cand in _candidates(snapshot_dir):
        if not is_snapshot_dir(cand):
            continue
        try:
            host, kv = load_serving_snapshot(cand, verify=verify)
            return host, kv, cand
        except SnapshotCorrupt as e:
            logger.warning(f"serving snapshot {cand} invalid ({e}); "
                           f"falling back to an older one")
            if on_corrupt is not None:
                on_corrupt(cand, e)
    return None


# --------------------------------------------------------------- restore

def resume_request(doc):
    """A REPLAY request for one snapshotted request doc: the committed
    stream (prompt + generated) becomes the admission prompt, so the
    prefill recomputes its K/V and — greedy decoding being
    deterministic — the continuation is token-for-token the one the
    uninterrupted run would have produced. ``tokens()`` of the finished
    replay equals ``tokens()`` of the uninterrupted original (the
    prompt/generated split moves; the stream doesn't)."""
    prompt = np.asarray(list(doc["prompt"]) + list(doc["generated"]),
                        np.int32)   # sync-ok: host snapshot doc
    rem = int(doc["max_new_tokens"]) - len(doc["generated"])
    assert rem >= 1, "a finished request never lands in a snapshot"
    req = Request(doc["rid"], prompt, max_new_tokens=rem,
                  eos_token_id=doc.get("eos_token_id"),  # sync-ok: host
                  temperature=float(doc.get("temperature", 0.0)),
                  trace_id=doc.get("trace_id"),
                  span_id=doc.get("span_id"),
                  sample_key=doc.get("sample_key"))
    # cumulative committed count — the sampling-index base AND the
    # prompt/generated split marker (older docs carry only this
    # incarnation's generated list; that is the right base for them)
    req.resumed_committed = int(doc.get("committed_total",
                                        len(doc["generated"])))
    return req


def restore_serving(cb, host, kv, requeue_overflow=True):
    """Rebuild snapshotted requests on ``cb`` (any slot/pool geometry
    with the same model): the most-progressed requests take free slots
    DIRECTLY — their pages are re-allocated through the refcounted
    allocator, the saved K/V bytes scattered back in one device op per
    pool component, page tables and slot state rebuilt, drafters
    realigned — and everything that doesn't fit (plus the snapshot's
    queue) is requeued as replay requests. Prefix-index entries
    re-import against the restored pages (refcount-0 entries become
    resident cache again) so the hit-rate survives; they are the first
    thing dropped under pool pressure.

    Returns ``{"restored": [...], "requeued": [...],
    "dropped_prefix_pages": n, "restore_s": s}``."""
    t0 = time.perf_counter()
    cache = cb.cache
    n_pages = int(host.get("n_pages", 0))
    comps = [kv.get(f"c{j}") for j in range(len(cache.pool))]
    if n_pages:
        for j, comp in enumerate(cache.pool):
            arr = comps[j]
            if arr is None or arr.shape[0] != comp.shape[0] \
                    or tuple(arr.shape[2:]) != tuple(comp.shape[2:]) \
                    or arr.shape[1] != n_pages:
                raise ServingRestoreError(
                    f"snapshot KV component c{j} "
                    f"{None if arr is None else arr.shape} does not fit "
                    f"the target pool {comp.shape} (same model/page "
                    f"geometry required)")
    if int(host.get("page_size", cache.spec.page_size)) \
            != cache.spec.page_size:
        raise ServingRestoreError(
            f"snapshot page_size {host.get('page_size')} != target "
            f"{cache.spec.page_size}")

    # a request over the TARGET's per-slot/prompt capacity can neither
    # rebuild directly nor replay (submit enforces the same ceilings)
    # — surface the geometry mismatch BEFORE mutating the target,
    # instead of a deep admission assert after pages were adopted
    P = cache.spec.page_size
    max_prompt_pages = cb.adapter.max_prompt_len() // P
    over = []
    for sd in list(host.get("slots", [])) + list(host.get("queued", [])):
        total = len(sd["prompt"]) + int(sd["max_new_tokens"])
        # the replay prompt folds committed tokens in, so its
        # whole-page prefill constraint covers prompt+generated
        replay_prompt = len(sd["prompt"]) + len(sd["generated"])
        if cache.pages_needed(total) > cache.spec.max_pages_per_slot \
                or cache.pages_needed(max(replay_prompt, 1)) \
                > max_prompt_pages:
            over.append(sd["rid"])
    if over:
        raise ServingRestoreError(
            f"request(s) {over} exceed the target's per-slot page "
            f"capacity ({cache.spec.max_pages_per_slot} pages of {P}) "
            f"or prompt-page budget — restore onto an engine with at "
            f"least the snapshot engine's capacity")

    # most-progressed first: replaying those would cost the most
    saved = sorted(host.get("slots", []),
                   key=lambda s: -len(s["generated"]))
    free_slots = [i for i, s in enumerate(cb.slots) if not s.active]
    chosen = saved[:len(free_slots)]
    overflow = saved[len(free_slots):] + list(host.get("queued", []))

    # allocate the direct slots' pages (shared saved pages allocate
    # ONCE — sharing survives the restore); on shortfall the least-
    # progressed chosen slot falls back to the requeue path and we try
    # again with the smaller set
    while True:
        uniq, seen = [], set()
        for sd in chosen:
            for p in sd["pages"]:
                if p not in seen:
                    seen.add(p)
                    uniq.append(p)
        fresh = cache.take_blocks(len(uniq))
        if fresh is not None:
            break
        if not chosen:
            fresh, uniq = [], []
            break
        overflow.insert(0, chosen.pop())
    blk_map = dict(zip(uniq, fresh))

    # prefix entries ride along best-effort: entries over slot pages
    # share the mapping, cache-only entries get their own block while
    # the pool can spare one (tracked in extra_blocks — a failed
    # import must hand such a block straight back or it leaks:
    # refcount 0, unregistered, on no list)
    prefix_entries = []
    dropped_prefix = 0
    extra_blocks = {}
    if cb.prefix_cache and host.get("prefix"):
        for kind in ("full", "partial"):
            for e in host["prefix"].get(kind, []):
                prefix_entries.append((kind, e))
        for _, e in prefix_entries:
            p = e["page"]
            if p in blk_map:
                continue
            got = cache.take_blocks(1)
            if not got:
                dropped_prefix += 1
                continue
            blk_map[p] = extra_blocks[p] = got[0]
        prefix_entries = [(k, e) for k, e in prefix_entries
                          if e["page"] in blk_map]

    # ONE scatter per pool component writes every restored block
    if blk_map:
        pairs = sorted(blk_map.items())
        src = np.asarray([p for p, _ in pairs], np.int32)  # sync-ok:
        dst = jnp.asarray(                                 # host ids
            np.asarray([b for _, b in pairs], np.int32))   # sync-ok: host
        cache.pool = tuple(
            comp.at[:, dst].set(jnp.asarray(comps[j][:, src]))
            for j, comp in enumerate(cache.pool))

    restored = []
    now = time.monotonic()
    for sd, slot_id in zip(chosen, free_slots):
        cache.adopt_slot(slot_id, [blk_map[p] for p in sd["pages"]])
        req = Request(sd["rid"],
                      np.asarray(sd["prompt"], np.int32),  # sync-ok:
                      max_new_tokens=int(sd["max_new_tokens"]),  # host
                      eos_token_id=sd.get("eos_token_id"),  # snapshot doc
                      temperature=float(sd.get("temperature", 0.0)),
                      trace_id=sd.get("trace_id"),
                      span_id=sd.get("span_id"),
                      sample_key=sd.get("sample_key"))
        req.generated = [int(t) for t in sd["generated"]]
        # sampling-index base: committed_total counts THROUGH this
        # incarnation's generated list, which the direct rebuild keeps
        # as generated (nothing folds into the prompt)
        req.resumed_committed = int(
            sd.get("committed_total", len(sd["generated"]))) \
            - len(sd["generated"])
        req._t_submit = now
        slot = cb.slots[slot_id]
        slot.request = req
        slot.pos = int(sd["pos"])
        slot.last_tok = int(sd["last_tok"])
        if cb.drafter is not None:
            cb.drafter.restore_slot(
                slot_id, req.prompt, req.generated,
                len(sd["prompt"]) + int(sd["max_new_tokens"]))
        restored.append(req)

    # import the prefix index AFTER adoption: entries over live slot
    # pages register at refcount > 0, cache-only entries at refcount 0
    # become resident (evictable) exactly as they were. A DUPLICATE
    # (the target already indexes the same content — e.g. a survivor
    # that served the same prompts) returns False without registering:
    # a block allocated solely for that entry goes straight back
    for kind, e in prefix_entries:
        blk = blk_map[e["page"]]
        if kind == "full":
            ok = cache.import_prefix_entry(blk, e["tokens"],
                                           key=bytes.fromhex(e["key"]))
        else:
            ok = cache.import_prefix_entry(
                blk, e["tokens"], chain=bytes.fromhex(e["chain"]))
        if not ok and e["page"] in extra_blocks:
            cache.return_blocks([extra_blocks.pop(e["page"])])
            del blk_map[e["page"]]
            dropped_prefix += 1

    requeued = []
    if requeue_overflow:
        for sd in overflow:
            req = resume_request(sd)
            cb.submit(req)
            cb._record("serving_requeue", rid=sd["rid"],
                       trace=sd.get("trace_id"),
                       committed=len(sd["generated"]),
                       remaining=req.max_new_tokens)
            requeued.append(req)
    restore_s = time.perf_counter() - t0
    cb._record("serving_restore", restored=len(restored),
               requeued=len(requeued), pages=len(blk_map),
               dropped_prefix_pages=dropped_prefix,
               restore_s=restore_s,
               traces=[getattr(r, "trace_id", None) for r in restored])
    m = cb.metrics
    m.counter("serving/restored_requests").inc(len(restored))
    m.counter("serving/requeued_requests").inc(len(requeued))
    m.histogram("serving/restore_s").observe(restore_s)
    cb._note_pool()
    return {"restored": restored, "requeued": requeued,
            "overflow": list(overflow),
            "dropped_prefix_pages": dropped_prefix,
            "restore_s": restore_s}


# ------------------------------------------------------------ controller

class ElasticServingController:
    """Drain-or-snapshot policy for one batcher (see module docstring).
    Attach with ``cb.attach_elastic(controller)`` — ``build_engine``
    does it from a ``serving.elastic`` config block. The engine calls
    :meth:`on_tick_end` at every step boundary."""

    def __init__(self, cb, snapshot_path, grace_secs=30.0,
                 interval_ticks=0, keep=2, fsync=True,
                 signals=("SIGTERM",), max_retries=3, backoff_s=0.05,
                 watchdog=None, aio_config=None, install_signals=True):
        self.cb = cb
        self.snapshot_dir = str(snapshot_path)
        self.grace_secs = float(grace_secs)   # sync-ok: config scalar
        self.interval_ticks = int(interval_ticks)
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)     # sync-ok: config scalar
        self.watchdog = watchdog
        self.snapshotter = AsyncSnapshotter(
            self.snapshot_dir, aio_config=aio_config, fsync=fsync,
            keep=keep, recorder=cb.recorder)
        self.preemption = PreemptionHandler(
            signals if install_signals else (), grace_s=self.grace_secs,
            recorder=cb.recorder)
        self.preempted = False
        self.last_snapshot_dir = None
        self._draining = False
        self._preempt_pending_rids = None
        self._begin_tick = None
        self._begin_info = None
        self._last_snap_tick = -1
        self._seq = 0
        self._t_last = None
        self._est_step_s = None

    @classmethod
    def from_config(cls, cb, elastic_cfg, watchdog=None,
                    install_signals=True):
        """None when the block is off (mirrors Watchdog.from_config)."""
        if not getattr(elastic_cfg, "enabled", False):
            return None
        return cls(cb, elastic_cfg.snapshot_path,
                   grace_secs=elastic_cfg.grace_secs,
                   interval_ticks=elastic_cfg.interval_ticks,
                   keep=elastic_cfg.keep, fsync=elastic_cfg.fsync,
                   signals=elastic_cfg.signals,
                   max_retries=elastic_cfg.max_retries,
                   backoff_s=elastic_cfg.backoff_s, watchdog=watchdog,
                   install_signals=install_signals)

    def _wd(self):
        return self.watchdog if self.watchdog is not None \
            else self.cb.watchdog

    def _next_tag(self):
        self._seq += 1
        return f"serving_{os.getpid()}_{self._seq:04d}"

    def request_preemption(self, source="manual"):
        """Programmatic preemption (scale-down drain, tests) — same
        path as a delivered signal."""
        self.preemption.request(source)

    # ------------------------------------------------------------- tick

    def on_tick_end(self, idle=False):
        if self.preempted:
            return
        now = time.monotonic()
        if idle:
            # an arrival-wait poll, not a decode tick: handle a pending
            # signal below but keep the ~50ms sleeps OUT of the
            # tick-latency EMA the drain budget divides by
            self._t_last = None
        else:
            if self._t_last is not None:
                dt = now - self._t_last
                self._est_step_s = dt if self._est_step_s is None \
                    else 0.5 * self._est_step_s + 0.5 * dt
            self._t_last = now
        tick = self.cb.stats["ticks"]
        if self.snapshotter.in_flight and not self._draining \
                and tick > self._begin_tick:
            self._finalize_periodic()
        if self.preemption.requested:
            self.preemption.poll_event()
            self._preempt_tick()
            return
        if self.interval_ticks and self.cb.pending \
                and not self.snapshotter.in_flight \
                and tick >= self._last_snap_tick + self.interval_ticks:
            # periodic snapshot: begin now, writes overlap the next
            # tick(s), commit at the next boundary past this tick
            self._last_snap_tick = tick
            self._begin_tick = tick
            tag = self._next_tag()
            snapshot_serving(self.cb, self.snapshotter, tag,
                             finalize=False)

    def _finalize_periodic(self):
        try:
            path, stall = self.snapshotter.finalize()
        except faults.SimulatedCrash:
            raise
        except Exception as e:   # ENOSPC etc: serving must outlive it
            logger.warning(f"serving snapshot commit failed: {e}")
            return
        self.last_snapshot_dir = path
        wd = self._wd()
        if wd is not None:
            wd.observe_ckpt_stall(stall, step=self.cb.stats["ticks"])

    # ---------------------------------------------------------- preempt

    def _pending_rids(self):
        cb = self.cb
        rids = [s.request.rid for s in cb.slots if s.active]
        rids += [r.rid for r in cb.queue]
        return rids

    def _preempt_tick(self):
        cb = self.cb
        if not self._draining:
            self._draining = True
            self._preempt_pending_rids = list(self._pending_rids())
            cb._admitting = False   # the snapshot set must stop growing
            if self.snapshotter.in_flight:
                # a periodic snapshot in flight predates the drain's
                # finishes — the final snapshot supersedes it
                self.snapshotter.abort("superseded by final snapshot")
        active = [s.request for s in cb.slots if s.active]
        if active:
            rem = self.preemption.remaining()
            est = self._est_step_s or 0.0
            margin = min(0.25 * self.grace_secs, 2.0)
            budget = (rem if rem is not None else self.grace_secs) \
                - margin
            min_rem_toks = min(r.max_new_tokens - len(r.generated)
                               for r in active)
            if budget > max(min_rem_toks, 1) * est:
                return          # the closest-to-done request still fits
        self._final_snapshot()

    def _final_snapshot(self):
        cb = self.cb
        left = self._pending_rids()
        drained = [r for r in self._preempt_pending_rids
                   if r not in left]
        snapshotted = False
        if not left:
            # clean drain: every request finished inside the grace
            # budget, so any PERIODIC snapshot still on disk is stale —
            # leaving it would make a later recovery replay completed
            # requests. The engine owes nothing; prune the dir.
            self._prune_all()
        else:
            # attempted even past the grace deadline: the commit is
            # atomic (two-rename), so losing the race to the external
            # killer leaves the previous valid snapshot — while NOT
            # attempting guarantees these requests are lost (unlike
            # training, no older snapshot holds them)
            tag = self._next_tag()
            try:
                self.last_snapshot_dir = snapshot_serving(
                    cb, self.snapshotter, tag)
                snapshotted = True
            except faults.SimulatedCrash:
                # the injected crash-between-renames: disk is left
                # as the crash would leave it; the engine still parks
                cb._record(
                    "serving_drain", drained=len(drained),
                    left=len(left), snapshotted=False,
                    grace_s=self.grace_secs)
                self.preempted = True
                raise
            except Exception as e:
                logger.warning(f"final serving snapshot failed: {e}")
        cb._record("serving_drain", drained=len(drained),
                   left=len(left), snapshotted=snapshotted,
                   grace_s=self.grace_secs,
                   source=self.preemption.source)
        wd = self._wd()
        if wd is not None:
            wd.note_preempt(step=cb.stats["ticks"],
                            snapshotted=snapshotted,
                            grace_s=self.grace_secs,
                            source=self.preemption.source)
        self.preempted = True

    def _prune_all(self):
        """Remove every committed snapshot (clean-drain cleanup — see
        _final_snapshot). Other engines' dirs are untouched: each
        controller owns its own snapshot_dir."""
        import shutil
        from deepspeed_tpu.runtime import checkpointing as ckpt
        self.last_snapshot_dir = None
        try:
            names = os.listdir(self.snapshot_dir)
        except OSError:
            return
        pruned = 0
        for name in names:
            path = os.path.join(self.snapshot_dir, name)
            if os.path.isdir(path) and (
                    is_snapshot_dir(path)
                    or name.endswith((".old", ".saving"))):
                shutil.rmtree(path, ignore_errors=True)
                pruned += 1
        try:
            os.remove(os.path.join(self.snapshot_dir, ckpt.LATEST_FILE))
        except OSError:
            pass
        if pruned:
            self.cb._record("serving_snapshot_prune",
                            pruned=pruned, reason="clean_drain")

    # ------------------------------------------------------------ close

    def finalize_pending(self):
        """Commit an in-flight periodic snapshot (clean-shutdown hook,
        mirrors engine.finalize_pending_snapshot)."""
        if self.snapshotter.in_flight:
            self._finalize_periodic()

    def release(self):
        """Retire the controller WITHOUT touching the signal table:
        aborts any in-flight snapshot and leaves the installed handlers
        as weakref pass-throughs. This is what a pool supervisor must
        use when retiring ONE replica — ``restore()`` would reinstall
        the pre-replica handler and silently drop every LATER-installed
        replica's handler from the chain, so a real SIGTERM would never
        reach them."""
        if self.snapshotter.in_flight:
            self.snapshotter.abort("controller released")

    def close(self):
        """Drop any in-flight snapshot and reinstall the previous
        signal handlers — tests and short-lived single engines call
        this (a pool retiring one of several replicas must use
        :meth:`release` instead; see its docstring)."""
        self.release()
        self.preemption.restore()
