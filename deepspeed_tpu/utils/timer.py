"""Wall-clock + throughput timers — rebuild of deepspeed/utils/timer.py:19,97.

The reference synchronizes CUDA before reading the clock; here we call
``jax.block_until_ready``-style synchronization via
``jax.effects_barrier``/device sync only when asked, since under jit the
dispatch is async.
"""

import time

from deepspeed_tpu.utils.logging import logger


_sync_token = None


def _sync_device():
    """Block until previously dispatched work is done — the TPU analog of
    torch.cuda.synchronize(). Enqueues one cached tiny computation behind the
    in-flight work and waits on it (a fresh device_put per call costs a full
    host→device transfer round trip on tunneled backends)."""
    global _sync_token
    try:
        import jax
        if _sync_token is None:
            import jax.numpy as jnp
            _sync_token = jax.jit(lambda: jnp.zeros((), jnp.int32))
        _sync_token().block_until_ready()
    except Exception:
        pass


class SynchronizedWallClockTimer:
    """Named timer group; ``elapsed`` synchronizes the device first."""

    class Timer:
        def __init__(self, name):
            self.name_ = name
            self.elapsed_ = 0.0
            self.started_ = False
            self.start_time = time.time()

        def start(self, sync=True):
            assert not self.started_, f"{self.name_} timer has already been started"
            if sync:
                _sync_device()
            self.start_time = time.time()
            self.started_ = True

        def stop(self, sync=True, reset=False):
            assert self.started_, "timer is not started"
            if sync:
                _sync_device()
            if reset:
                self.elapsed_ = time.time() - self.start_time
            else:
                self.elapsed_ += time.time() - self.start_time
            self.started_ = False

        def reset(self):
            self.elapsed_ = 0.0
            self.started_ = False

        def elapsed(self, reset=True):
            started_ = self.started_
            if self.started_:
                self.stop()
            elapsed_ = self.elapsed_
            if reset:
                self.reset()
            if started_:
                self.start()
            return elapsed_

        def mean(self, count):
            return self.elapsed(reset=False) / max(count, 1)

    def __init__(self):
        self.timers = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += " | {}: {:.2f}".format(name, elapsed_time)
        logger.info(string)


class ThroughputTimer:
    """Samples/sec reporting — reference utils/timer.py:97, used by the engine
    for per-step throughput lines (engine.py:176-180)."""

    def __init__(self,
                 batch_size,
                 num_workers=1,
                 start_step=2,
                 steps_per_output=50,
                 monitor_memory=False,
                 logging_fn=None):
        self.start_time = 0
        self.end_time = 0
        self.started = False
        self.batch_size = max(batch_size, 1)
        self.num_workers = num_workers
        self.start_step = start_step
        self.epoch_count = 0
        self.local_step_count = 0
        self.total_step_count = 0
        self.total_elapsed_time = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or logger.info
        self.initialized = False

    def update_epoch_count(self):
        self.epoch_count += 1
        self.local_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True
        if self.total_step_count == self.start_step:
            # timeline accounting: sync once at the start of the measured
            # region, then measure contiguous wall time window-by-window.
            # Syncing every step would serialize dispatch against execution;
            # skipping sync but summing per-step gaps would silently drop
            # device work that runs during host-side gaps. Wall-clock windows
            # bounded by syncs count everything exactly once.
            _sync_device()
            self._window_start = time.time()
            self._steps_in_windows = 0

    def _fold_window(self):
        """Close the current window: sync, add its wall time, start a new
        window."""
        _sync_device()
        now = time.time()
        self.total_elapsed_time += now - self._window_start
        self._steps_in_windows = self.total_step_count - self.start_step
        self._window_start = now

    def stop(self, report_speed=True):
        if not self.started:
            return
        self.started = False
        self.total_step_count += 1
        self.local_step_count += 1
        if self.total_step_count > self.start_step:
            self.end_time = time.time()
            if report_speed and \
                    self.local_step_count % self.steps_per_output == 0:
                self._fold_window()
                self.logging(
                    "{}/{}, SamplesPerSec={}".format(self.epoch_count,
                                                     self.local_step_count,
                                                     self.avg_samples_per_sec()))

    def avg_samples_per_sec(self, fold=False):
        if self.total_step_count > self.start_step:
            if fold or not getattr(self, "_steps_in_windows", 0):
                self._fold_window()
            steps = max(getattr(self, "_steps_in_windows", 0), 1)
            samples_per_step = self.batch_size * self.num_workers
            avg_time_per_step = self.total_elapsed_time / steps
            return samples_per_step / max(avg_time_per_step, 1e-12)
        return float("-inf")
