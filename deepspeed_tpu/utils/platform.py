"""Backend detection.

Pallas kernels must compile (not interpret) whenever the execution target is
a real TPU. That is *not* the same as ``jax.default_backend() == "tpu"``:
tunneled/proxied PJRT plugins (e.g. an `axon` terminal fronting a TPU chip)
register under their own platform name while still executing TPU programs.
Detect TPU by the device kind, which the plugin reports faithfully
("TPU v4", "TPU v5 lite", ...).
"""

def is_tpu_backend() -> bool:
    # evaluated per call (no cache): a process may legitimately switch
    # backends, e.g. run on the TPU then move to a forced-CPU device mesh
    # (jax.config.update("jax_platforms", "cpu") + clear_backends)
    try:
        import jax
        if jax.default_backend() == "tpu":
            return True
        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", "") or ""
        platform = getattr(dev, "platform", "") or ""
        return kind.upper().startswith("TPU") or platform == "tpu"
    except Exception:
        return False
