"""Distributed rendezvous — rebuild of deepspeed/utils/distributed.py:12-142.

The reference resolves RANK/WORLD_SIZE/MASTER_ADDR from the environment
(with OpenMPI / Azure-ML discovery fallbacks) and calls
``torch.distributed.init_process_group``. Here the rendezvous target is
``jax.distributed.initialize``; sources, in priority order:

1. explicit arguments;
2. the launcher contract (``DSTPU_COORDINATOR_ADDR/PORT``,
   ``DSTPU_NUM_PROCESSES``, ``DSTPU_PROCESS_ID``,
   ``DSTPU_LOCAL_DEVICE_IDS`` — set by launcher/launch.py);
3. generic env (``COORDINATOR_ADDRESS``/``NUM_PROCESSES``/``PROCESS_ID``);
4. OpenMPI discovery (``OMPI_COMM_WORLD_*`` — reference mpi_discovery
   utils/distributed.py:54) — requires an explicit ``MASTER_ADDR`` for the
   coordinator; mpirun gives ranks but no rendezvous host;
5. single-process no-op (TPU-VM single host, unit tests).
"""

import os
import random
import time
from typing import Optional, Sequence

from deepspeed_tpu.utils.logging import log_dist, logger

_initialized = False

# connection-flavored failure markers worth retrying (ISSUE 15): under
# a supervisor restart the coordinator (rank 0) races every other
# rank's dial — "refused" for the first second of every epoch is the
# EXPECTED shape, not an error. Config/usage errors never match and
# raise immediately.
_RETRYABLE_MARKERS = ("unavailable", "deadline_exceeded", "deadline",
                      "connection refused", "failed to connect",
                      "connection reset", "timed out", "timeout")


def jittered_backoff(base_s, attempt, cap_s=None, rng=None):
    """Full-upward-jitter exponential backoff delay (ISSUE 15):
    ``min(base·2^attempt, cap) · (1 + U[0,1))`` — restarted/retrying
    peers must not re-dial in sync. Shared by the rendezvous retry
    below and the supervisor's restart loop (serving/replica_pool.py
    predates this helper with a deliberately different ±50% jitter;
    its retry cadence is pinned by tests, so it keeps its own)."""
    rng = rng if rng is not None else random.random
    delay = base_s * (2 ** attempt)
    if cap_s is not None:
        delay = min(delay, cap_s)
    return delay * (1.0 + rng())


def _rendezvous_retry_env(environ=None):
    """(retries, backoff_s) from the supervisor's env contract
    (``DSTPU_RENDEZVOUS_RETRIES``/``DSTPU_RENDEZVOUS_BACKOFF_S`` — the
    ``fault_tolerance`` config block's knobs, exported by
    runtime/elastic/supervisor.py), with defaults that make a bare
    multi-process launch survive a slow-starting coordinator."""
    from deepspeed_tpu.config import constants as C   # stdlib-safe;
    #   ONE source of truth with the fault_tolerance config block
    env = os.environ if environ is None else environ
    try:
        retries = int(env.get("DSTPU_RENDEZVOUS_RETRIES", "")
                      or C.FT_RENDEZVOUS_RETRIES_DEFAULT)
    except ValueError:
        retries = C.FT_RENDEZVOUS_RETRIES_DEFAULT
    try:
        backoff = float(env.get("DSTPU_RENDEZVOUS_BACKOFF_S", "")
                        or C.FT_RENDEZVOUS_BACKOFF_S_DEFAULT)
    except ValueError:
        backoff = C.FT_RENDEZVOUS_BACKOFF_S_DEFAULT
    return max(retries, 0), max(backoff, 0.0)


def _retry_rendezvous(connect, retries, backoff_s, cap_s=10.0,
                      sleep=time.sleep, rng=None):
    """Run ``connect()`` with jittered exponential backoff on
    connection-flavored failures (up to ``retries`` retries). Anything
    that does not look like a transport failure — a config error, a
    rank mismatch — raises immediately: retrying those would turn a
    5-second crash into a 5-minute one."""
    rng = rng if rng is not None else random.random
    attempt = 0
    while True:
        try:
            return connect()
        except Exception as e:
            msg = str(e).lower()
            retryable = any(m in msg for m in _RETRYABLE_MARKERS)
            if attempt >= retries or not retryable:
                raise
            delay = jittered_backoff(backoff_s, attempt, cap_s=cap_s,
                                     rng=rng)
            logger.warning(
                f"rendezvous attempt {attempt + 1}/{retries + 1} failed "
                f"({str(e)[:120]}); retrying in {delay:.2f}s")
            sleep(delay)
            attempt += 1


def discover_rendezvous(environ=None, auto_mpi_discovery=True):
    """Resolve (coordinator_address, num_processes, process_id,
    local_device_ids) from the environment without side effects. Fields that
    cannot be resolved come back None."""
    env = os.environ if environ is None else environ

    def geti(name):
        val = env.get(name)
        return int(val) if val not in (None, "") else None

    addr = num = pid = None
    if env.get("DSTPU_COORDINATOR_ADDR"):
        port = env.get("DSTPU_COORDINATOR_PORT", "8476")
        addr = f"{env['DSTPU_COORDINATOR_ADDR']}:{port}"
        num = geti("DSTPU_NUM_PROCESSES")
        pid = geti("DSTPU_PROCESS_ID")
    elif env.get("COORDINATOR_ADDRESS"):
        addr = env["COORDINATOR_ADDRESS"]
        num = geti("NUM_PROCESSES")
        pid = geti("PROCESS_ID")
    elif auto_mpi_discovery and env.get("OMPI_COMM_WORLD_SIZE"):
        num = geti("OMPI_COMM_WORLD_SIZE")
        pid = geti("OMPI_COMM_WORLD_RANK")
        # mpirun provides ranks but no rendezvous host: require MASTER_ADDR
        # rather than guessing localhost (every rank dialing its own
        # loopback would hang, not fail).
        if env.get("MASTER_ADDR"):
            port = env.get("MASTER_PORT", "8476")
            addr = f"{env['MASTER_ADDR']}:{port}"

    ids = env.get("DSTPU_LOCAL_DEVICE_IDS", "")
    local_device_ids = [int(x) for x in ids.split(",") if x != ""] or None
    return addr, num, pid, local_device_ids


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids: Optional[Sequence[int]] = None,
                     auto_mpi_discovery: bool = True,
                     rendezvous_retries: Optional[int] = None,
                     rendezvous_backoff_s: Optional[float] = None):
    """Idempotent multi-host init; single-process is a no-op. Explicit
    arguments always win; env discovery fills in only the missing fields.

    Rendezvous retries (ISSUE 15): a supervisor restart makes the
    coordinator-not-up-yet race routine (rank 0 of the NEW epoch may be
    milliseconds behind its peers), so connection-flavored
    ``jax.distributed.initialize`` failures retry with jittered
    exponential backoff instead of crashing the fresh epoch on first
    refusal. Knobs: explicit args > ``DSTPU_RENDEZVOUS_RETRIES``/
    ``DSTPU_RENDEZVOUS_BACKOFF_S`` env (what the supervisor exports
    from the ``fault_tolerance`` config block) > defaults (8, 0.5s)."""
    global _initialized
    if _initialized:
        return
    addr, num, pid, ids = discover_rendezvous(
        auto_mpi_discovery=auto_mpi_discovery)
    coordinator_address = coordinator_address if coordinator_address \
        else addr
    num_processes = num_processes if num_processes is not None else num
    process_id = process_id if process_id is not None else pid
    local_device_ids = local_device_ids if local_device_ids is not None \
        else ids

    if coordinator_address and num_processes and num_processes > 1:
        import jax
        _enable_cpu_cross_process_collectives(jax)
        env_retries, env_backoff = _rendezvous_retry_env()
        retries = rendezvous_retries if rendezvous_retries is not None \
            else env_retries
        backoff = rendezvous_backoff_s \
            if rendezvous_backoff_s is not None else env_backoff
        _retry_rendezvous(
            lambda: jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                local_device_ids=local_device_ids),
            retries=retries, backoff_s=backoff)
        # log only AFTER initialize: rank-aware logging touches the backend,
        # and jax.distributed.initialize must precede any backend init
        log_dist(f"jax.distributed.initialize({coordinator_address}, "
                 f"n={num_processes}, id={process_id}, "
                 f"local_device_ids={local_device_ids})", ranks=[0])
    _initialized = True


def _enable_cpu_cross_process_collectives(jax):
    """The XLA CPU backend refuses to compile cross-process computations
    unless a collectives transport is wired into the client — jax
    defaults to "none" and every multi-host program dies with
    "Multiprocess computations aren't implemented on the CPU backend".
    Select gloo (TCP, rendezvous through the same distributed KV store)
    before the first backend touch so multi-process CPU rendezvous —
    the DCN-proxy test harnesses and any CPU fallback of a multi-host
    job — just works. Only the CPU client reads the flag; TPU/GPU
    backends ignore it. NOTE: must run before jax.distributed.initialize
    per the backend-init ordering this function already documents; a
    jaxlib built without gloo keeps the default."""
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass


def is_initialized():
    return _initialized


def allgather_host_floats(vec):
    """Allgather one small host fp32 vector across processes; returns
    ``(matrix [world, n], process_index)``.

    The cross-rank telemetry fence (ISSUE 12, telemetry/cluster.py):
    every process must call this at the SAME aligned point (the
    steps_per_print boundary / a snapshot commit fence — places every
    rank reaches in SPMD lockstep), exactly like the preemption
    agreement collective in runtime/engine._preempt_agreed. Single
    process short-circuits to a reshape — no jax.distributed needed,
    no collective compiled."""
    import numpy as np

    import jax
    arr = np.asarray(vec, np.float32).reshape(-1)
    if jax.process_count() == 1:
        return arr[None, :], 0
    from jax.experimental import multihost_utils
    mat = multihost_utils.process_allgather(arr)
    return (np.asarray(mat, np.float32).reshape(jax.process_count(), -1),
            int(jax.process_index()))


def allgather_host_bytes(buf, meta=None):
    """Two-phase aligned byte allgather (ISSUE 17: the serving handoff
    fabric's wire move); returns ``(per-rank bytes list, meta matrix
    [world, len(meta)], process_index)``.

    Phase 1 is one fixed-width :func:`allgather_host_floats` of
    ``[nbytes, *meta]`` — the piggy-backed ``meta`` vector is how the
    transport exchanges its backpressure counters without a second
    fence. Phase 2 — entered by EVERY rank iff any rank has payload —
    is one uint8 allgather padded to the max length. Both phases are
    collectives at a single aligned call site, SEQUENTIAL (never
    concurrent), one device per process: the documented gloo-flake-
    stable recipe. The fp32 size word is exact below 2**24, asserted —
    a serving handoff frame is KBs, nowhere near it. Single process
    short-circuits like allgather_host_floats."""
    import numpy as np

    import jax
    buf = bytes(buf)
    assert len(buf) < 2 ** 24, (
        f"{len(buf)}-byte buffer exceeds the fp32-exact size word")
    meta = np.asarray([] if meta is None else meta,
                      np.float32).reshape(-1)
    mat, me = allgather_host_floats(
        np.concatenate([np.float32([len(buf)]), meta]))
    sizes = mat[:, 0].astype(np.int64)
    world = mat.shape[0]
    pad = int(sizes.max())
    if pad == 0:
        return [b""] * world, mat[:, 1:], me
    arr = np.zeros(pad, np.uint8)
    if buf:
        arr[:len(buf)] = np.frombuffer(buf, np.uint8)
    if jax.process_count() == 1:
        rows = arr[None, :]
    else:
        from jax.experimental import multihost_utils
        rows = np.asarray(
            multihost_utils.process_allgather(arr)).reshape(world, pad)
    return ([rows[r, :sizes[r]].tobytes() for r in range(world)],
            mat[:, 1:], me)
