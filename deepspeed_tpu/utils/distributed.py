"""Distributed rendezvous — rebuild of deepspeed/utils/distributed.py:12-142.

The reference resolves RANK/WORLD_SIZE/MASTER_ADDR from the environment
(with OpenMPI / Azure-ML discovery fallbacks) and calls
``torch.distributed.init_process_group``. Here the rendezvous target is
``jax.distributed.initialize``; sources, in priority order:

1. explicit arguments;
2. the launcher contract (``DSTPU_COORDINATOR_ADDR/PORT``,
   ``DSTPU_NUM_PROCESSES``, ``DSTPU_PROCESS_ID``,
   ``DSTPU_LOCAL_DEVICE_IDS`` — set by launcher/launch.py);
3. generic env (``COORDINATOR_ADDRESS``/``NUM_PROCESSES``/``PROCESS_ID``);
4. OpenMPI discovery (``OMPI_COMM_WORLD_*`` — reference mpi_discovery
   utils/distributed.py:54) — requires an explicit ``MASTER_ADDR`` for the
   coordinator; mpirun gives ranks but no rendezvous host;
5. single-process no-op (TPU-VM single host, unit tests).
"""

import os
import random
import socket
import struct
import time
from typing import Optional, Sequence

from deepspeed_tpu.utils.logging import log_dist, logger

_initialized = False

# connection-flavored failure markers worth retrying (ISSUE 15): under
# a supervisor restart the coordinator (rank 0) races every other
# rank's dial — "refused" for the first second of every epoch is the
# EXPECTED shape, not an error. Config/usage errors never match and
# raise immediately.
_RETRYABLE_MARKERS = ("unavailable", "deadline_exceeded", "deadline",
                      "connection refused", "failed to connect",
                      "connection reset", "timed out", "timeout")


def jittered_backoff(base_s, attempt, cap_s=None, rng=None):
    """Full-upward-jitter exponential backoff delay (ISSUE 15):
    ``min(base·2^attempt, cap) · (1 + U[0,1))`` — restarted/retrying
    peers must not re-dial in sync. Shared by the rendezvous retry
    below and the supervisor's restart loop (serving/replica_pool.py
    predates this helper with a deliberately different ±50% jitter;
    its retry cadence is pinned by tests, so it keeps its own)."""
    rng = rng if rng is not None else random.random
    delay = base_s * (2 ** attempt)
    if cap_s is not None:
        delay = min(delay, cap_s)
    return delay * (1.0 + rng())


def _rendezvous_retry_env(environ=None):
    """(retries, backoff_s) from the supervisor's env contract
    (``DSTPU_RENDEZVOUS_RETRIES``/``DSTPU_RENDEZVOUS_BACKOFF_S`` — the
    ``fault_tolerance`` config block's knobs, exported by
    runtime/elastic/supervisor.py), with defaults that make a bare
    multi-process launch survive a slow-starting coordinator."""
    from deepspeed_tpu.config import constants as C   # stdlib-safe;
    #   ONE source of truth with the fault_tolerance config block
    env = os.environ if environ is None else environ
    try:
        retries = int(env.get("DSTPU_RENDEZVOUS_RETRIES", "")
                      or C.FT_RENDEZVOUS_RETRIES_DEFAULT)
    except ValueError:
        retries = C.FT_RENDEZVOUS_RETRIES_DEFAULT
    try:
        backoff = float(env.get("DSTPU_RENDEZVOUS_BACKOFF_S", "")
                        or C.FT_RENDEZVOUS_BACKOFF_S_DEFAULT)
    except ValueError:
        backoff = C.FT_RENDEZVOUS_BACKOFF_S_DEFAULT
    return max(retries, 0), max(backoff, 0.0)


def _retry_rendezvous(connect, retries, backoff_s, cap_s=10.0,
                      sleep=time.sleep, rng=None):
    """Run ``connect()`` with jittered exponential backoff on
    connection-flavored failures (up to ``retries`` retries). Anything
    that does not look like a transport failure — a config error, a
    rank mismatch — raises immediately: retrying those would turn a
    5-second crash into a 5-minute one."""
    rng = rng if rng is not None else random.random
    attempt = 0
    while True:
        try:
            return connect()
        except Exception as e:
            msg = str(e).lower()
            retryable = any(m in msg for m in _RETRYABLE_MARKERS)
            if attempt >= retries or not retryable:
                raise
            delay = jittered_backoff(backoff_s, attempt, cap_s=cap_s,
                                     rng=rng)
            logger.warning(
                f"rendezvous attempt {attempt + 1}/{retries + 1} failed "
                f"({str(e)[:120]}); retrying in {delay:.2f}s")
            sleep(delay)
            attempt += 1


def discover_rendezvous(environ=None, auto_mpi_discovery=True):
    """Resolve (coordinator_address, num_processes, process_id,
    local_device_ids) from the environment without side effects. Fields that
    cannot be resolved come back None."""
    env = os.environ if environ is None else environ

    def geti(name):
        val = env.get(name)
        return int(val) if val not in (None, "") else None

    addr = num = pid = None
    if env.get("DSTPU_COORDINATOR_ADDR"):
        port = env.get("DSTPU_COORDINATOR_PORT", "8476")
        addr = f"{env['DSTPU_COORDINATOR_ADDR']}:{port}"
        num = geti("DSTPU_NUM_PROCESSES")
        pid = geti("DSTPU_PROCESS_ID")
    elif env.get("COORDINATOR_ADDRESS"):
        addr = env["COORDINATOR_ADDRESS"]
        num = geti("NUM_PROCESSES")
        pid = geti("PROCESS_ID")
    elif auto_mpi_discovery and env.get("OMPI_COMM_WORLD_SIZE"):
        num = geti("OMPI_COMM_WORLD_SIZE")
        pid = geti("OMPI_COMM_WORLD_RANK")
        # mpirun provides ranks but no rendezvous host: require MASTER_ADDR
        # rather than guessing localhost (every rank dialing its own
        # loopback would hang, not fail).
        if env.get("MASTER_ADDR"):
            port = env.get("MASTER_PORT", "8476")
            addr = f"{env['MASTER_ADDR']}:{port}"

    ids = env.get("DSTPU_LOCAL_DEVICE_IDS", "")
    local_device_ids = [int(x) for x in ids.split(",") if x != ""] or None
    return addr, num, pid, local_device_ids


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids: Optional[Sequence[int]] = None,
                     auto_mpi_discovery: bool = True,
                     rendezvous_retries: Optional[int] = None,
                     rendezvous_backoff_s: Optional[float] = None):
    """Idempotent multi-host init; single-process is a no-op. Explicit
    arguments always win; env discovery fills in only the missing fields.

    Rendezvous retries (ISSUE 15): a supervisor restart makes the
    coordinator-not-up-yet race routine (rank 0 of the NEW epoch may be
    milliseconds behind its peers), so connection-flavored
    ``jax.distributed.initialize`` failures retry with jittered
    exponential backoff instead of crashing the fresh epoch on first
    refusal. Knobs: explicit args > ``DSTPU_RENDEZVOUS_RETRIES``/
    ``DSTPU_RENDEZVOUS_BACKOFF_S`` env (what the supervisor exports
    from the ``fault_tolerance`` config block) > defaults (8, 0.5s)."""
    global _initialized
    if _initialized:
        return
    addr, num, pid, ids = discover_rendezvous(
        auto_mpi_discovery=auto_mpi_discovery)
    coordinator_address = coordinator_address if coordinator_address \
        else addr
    num_processes = num_processes if num_processes is not None else num
    process_id = process_id if process_id is not None else pid
    local_device_ids = local_device_ids if local_device_ids is not None \
        else ids

    if coordinator_address and num_processes and num_processes > 1:
        import jax
        _enable_cpu_cross_process_collectives(jax)
        env_retries, env_backoff = _rendezvous_retry_env()
        retries = rendezvous_retries if rendezvous_retries is not None \
            else env_retries
        backoff = rendezvous_backoff_s \
            if rendezvous_backoff_s is not None else env_backoff
        _retry_rendezvous(
            lambda: jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                local_device_ids=local_device_ids),
            retries=retries, backoff_s=backoff)
        # log only AFTER initialize: rank-aware logging touches the backend,
        # and jax.distributed.initialize must precede any backend init
        log_dist(f"jax.distributed.initialize({coordinator_address}, "
                 f"n={num_processes}, id={process_id}, "
                 f"local_device_ids={local_device_ids})", ranks=[0])
    _initialized = True


def _enable_cpu_cross_process_collectives(jax):
    """The XLA CPU backend refuses to compile cross-process computations
    unless a collectives transport is wired into the client — jax
    defaults to "none" and every multi-host program dies with
    "Multiprocess computations aren't implemented on the CPU backend".
    Select gloo (TCP, rendezvous through the same distributed KV store)
    before the first backend touch so multi-process CPU rendezvous —
    the DCN-proxy test harnesses and any CPU fallback of a multi-host
    job — just works. Only the CPU client reads the flag; TPU/GPU
    backends ignore it. NOTE: must run before jax.distributed.initialize
    per the backend-init ordering this function already documents; a
    jaxlib built without gloo keeps the default."""
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass


def is_initialized():
    return _initialized


def allgather_host_floats(vec):
    """Allgather one small host fp32 vector across processes; returns
    ``(matrix [world, n], process_index)``.

    The cross-rank telemetry fence (ISSUE 12, telemetry/cluster.py):
    every process must call this at the SAME aligned point (the
    steps_per_print boundary / a snapshot commit fence — places every
    rank reaches in SPMD lockstep), exactly like the preemption
    agreement collective in runtime/engine._preempt_agreed. Single
    process short-circuits to a reshape — no jax.distributed needed,
    no collective compiled."""
    import numpy as np

    import jax
    arr = np.asarray(vec, np.float32).reshape(-1)
    if jax.process_count() == 1:
        return arr[None, :], 0
    from jax.experimental import multihost_utils
    mat = multihost_utils.process_allgather(arr)
    return (np.asarray(mat, np.float32).reshape(jax.process_count(), -1),
            int(jax.process_index()))


def allgather_host_bytes(buf, meta=None):
    """Two-phase aligned byte allgather (ISSUE 17: the serving handoff
    fabric's wire move); returns ``(per-rank bytes list, meta matrix
    [world, len(meta)], process_index)``.

    Phase 1 is one fixed-width :func:`allgather_host_floats` of
    ``[nbytes, *meta]`` — the piggy-backed ``meta`` vector is how the
    transport exchanges its backpressure counters without a second
    fence. Phase 2 — entered by EVERY rank iff any rank has payload —
    is one uint8 allgather padded to the max length. Both phases are
    collectives at a single aligned call site, SEQUENTIAL (never
    concurrent), one device per process: the documented gloo-flake-
    stable recipe. The fp32 size word is exact below 2**24, asserted —
    a serving handoff frame is KBs, nowhere near it. Single process
    short-circuits like allgather_host_floats."""
    import numpy as np

    import jax
    buf = bytes(buf)
    assert len(buf) < 2 ** 24, (
        f"{len(buf)}-byte buffer exceeds the fp32-exact size word")
    meta = np.asarray([] if meta is None else meta,
                      np.float32).reshape(-1)
    mat, me = allgather_host_floats(
        np.concatenate([np.float32([len(buf)]), meta]))
    sizes = mat[:, 0].astype(np.int64)
    world = mat.shape[0]
    pad = int(sizes.max())
    if pad == 0:
        return [b""] * world, mat[:, 1:], me
    arr = np.zeros(pad, np.uint8)
    if buf:
        arr[:len(buf)] = np.frombuffer(buf, np.uint8)
    if jax.process_count() == 1:
        rows = arr[None, :]
    else:
        from jax.experimental import multihost_utils
        rows = np.asarray(
            multihost_utils.process_allgather(arr)).reshape(world, pad)
    return ([rows[r, :sizes[r]].tobytes() for r in range(world)],
            mat[:, 1:], me)


# ------------------------------------------------- targeted payload leg

def _advertise_ip():
    """The address peers should dial to reach this host's payload
    listener: the local interface that routes toward the rendezvous
    coordinator (UDP connect performs routing only — no packet is
    sent), falling back to loopback for single-host worlds."""
    coord = os.environ.get("DSTPU_COORDINATOR_ADDR") or "127.0.0.1"
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect((coord, 9))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


def _recv_exact(sock, n):
    chunks, got = [], 0
    while got < n:
        b = sock.recv(min(n - got, 1 << 20))
        if not b:
            raise ConnectionError(
                f"peer closed with {n - got} of {n} bytes outstanding")
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)


class PeerFabric:
    """Point-to-point TCP channels between ranks — the
    destination-addressed payload leg of the serving transport
    (ISSUE 18). Construction is a COLLECTIVE: every rank binds an
    ephemeral listener and allgathers its ``host:port`` through
    :func:`allgather_host_bytes`, so it must happen at an aligned call
    site (the transport creates it lazily at the first exchange, a
    point every rank reaches together). Connections dial lazily and
    persist; a 4-byte hello tags each inbound connection with its
    source rank. Every blocking call carries ``timeout_s`` — a dead
    peer fails LOUD (the supervisor's rank-death path), never hangs."""

    def __init__(self, timeout_s: float = 60.0):
        import jax
        self.rank = int(jax.process_index())
        self.world = int(jax.process_count())
        self.timeout_s = float(timeout_s)  # sync-ok: host config
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(max(self.world, 1))
        self._listener.settimeout(self.timeout_s)
        port = self._listener.getsockname()[1]
        bufs, _meta, _me = allgather_host_bytes(
            f"{_advertise_ip()}:{port}".encode())
        self.addrs = [b.decode() for b in bufs]
        self._out = {}   # dst rank -> connected socket
        self._in = {}    # src rank -> accepted socket
        # liveness timestamps (ISSUE 19 satellite): wall clock of the
        # last payload each direction per peer — /healthz surfaces the
        # age so a half-dead socket mesh (connected but silent) is
        # visible before any payload_timeout_s trips
        self.last_send_ts = {}   # dst rank -> time.time() of last send
        self.last_recv_ts = {}   # src rank -> time.time() of last recv

    def send(self, dst: int, buf: bytes) -> None:
        s = self._out.get(dst)
        if s is None:
            host, port = self.addrs[dst].rsplit(":", 1)
            s = socket.create_connection((host, int(port)),
                                         timeout=self.timeout_s)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(self.timeout_s)
            s.sendall(struct.pack("<I", self.rank))
            self._out[dst] = s
        s.sendall(buf)
        self.last_send_ts[int(dst)] = time.time()

    def recv(self, src: int, nbytes: int) -> bytes:
        while src not in self._in:
            # accept until the expected peer's hello arrives; other
            # peers dialing early are registered, not dropped
            conn, _addr = self._listener.accept()
            conn.settimeout(self.timeout_s)
            peer = struct.unpack("<I", _recv_exact(conn, 4))[0]
            self._in[int(peer)] = conn
        out = _recv_exact(self._in[src], nbytes)
        self.last_recv_ts[int(src)] = time.time()
        return out

    def liveness(self) -> dict:
        """Per-peer fabric liveness for /healthz: whether each
        direction is connected and the seconds since its last payload
        (None = no payload yet). Host state only — reading it can
        never block or sync."""
        now = time.time()

        def _age(ts):
            return None if ts is None else max(now - ts, 0.0)

        peers = {}
        for r in range(self.world):
            if r == self.rank:
                continue
            peers[str(r)] = {
                "out_connected": r in self._out,
                "in_connected": r in self._in,
                "last_send_age_s": _age(self.last_send_ts.get(r)),
                "last_recv_age_s": _age(self.last_recv_ts.get(r)),
            }
        return {"rank": self.rank, "world": self.world, "peers": peers}

    def close(self) -> None:
        for s in list(self._out.values()) + list(self._in.values()) \
                + [self._listener]:
            try:
                s.close()
            except OSError:
                pass
        self._out, self._in = {}, {}


def exchange_host_bytes_targeted(bcast_buf, targeted, meta=None,
                                 fabric=None):
    """Three-leg aligned exchange (ISSUE 18: the scale-out serving
    transport). Returns ``(per-rank broadcast bytes list,
    {src: targeted bytes}, meta matrix, process_index, bcast_pad)``.

    Leg 1 (header/fence) is one fixed-width
    :func:`allgather_host_floats` of ``[bcast_nbytes,
    per-destination sizes row, *meta]`` — every rank learns the full
    traffic matrix at the fence. Leg 2 — entered by EVERY rank iff any
    rank broadcast bytes — is the PR-17 padded uint8 allgather,
    carrying only dst<0 traffic. Leg 3 moves the destination-addressed
    payloads point-to-point over ``fabric`` (:class:`PeerFabric`) in
    one deterministic global ``(src, dst)`` order every rank walks
    identically: sizes and schedule were agreed at the fence, the
    globally-earliest incomplete transfer always has both its sender
    and its receiver engaged (all their earlier transfers are
    complete), so by induction the schedule cannot deadlock — and a
    payload crosses the wire exactly ONCE regardless of world size,
    the O(payload) wire cost the broadcast leg could not provide.
    fp32 exactness below 2**24 per buffer, asserted."""
    import numpy as np

    import jax
    bcast_buf = bytes(bcast_buf)
    world = int(jax.process_count())
    assert len(bcast_buf) < 2 ** 24, (
        f"{len(bcast_buf)}-byte broadcast buffer exceeds the "
        f"fp32-exact size word")
    row = np.zeros(world, np.float32)
    for dst, b in targeted.items():
        assert 0 <= int(dst) < world, (dst, world)
        assert len(b) < 2 ** 24, (
            f"{len(b)}-byte targeted buffer exceeds the fp32-exact "
            f"size word")
        row[int(dst)] = len(b)
    meta = np.asarray([] if meta is None else meta,
                      np.float32).reshape(-1)
    mat, me = allgather_host_floats(
        np.concatenate([np.float32([len(bcast_buf)]), row, meta]))
    assert not targeted or me not in targeted, \
        f"rank {me} addressed a payload to itself"
    bsizes = mat[:, 0].astype(np.int64)
    T = mat[:, 1:1 + world].astype(np.int64)   # traffic matrix [src,dst]
    meta_mat = mat[:, 1 + world:]
    pad = int(bsizes.max())
    bufs = [b""] * world
    if pad:
        arr = np.zeros(pad, np.uint8)
        if bcast_buf:
            arr[:len(bcast_buf)] = np.frombuffer(bcast_buf, np.uint8)
        if world == 1:
            rows = arr[None, :]
        else:
            from jax.experimental import multihost_utils
            rows = np.asarray(
                multihost_utils.process_allgather(arr)).reshape(world,
                                                                pad)
        bufs = [rows[r, :bsizes[r]].tobytes() for r in range(world)]
    incoming = {}
    if world > 1 and T.any():
        assert fabric is not None, \
            "targeted payloads pending but no PeerFabric supplied"
        for src in range(world):
            for dst in range(world):
                n = int(T[src, dst])
                if n == 0 or src == dst:
                    continue
                if me == src:
                    fabric.send(dst, targeted[dst])
                elif me == dst:
                    incoming[src] = fabric.recv(src, n)
    return bufs, incoming, meta_mat, me, pad
