"""Offline ZeRO-checkpoint → consolidated fp32 weights converter.

Reference: utils/zero_to_fp32.py:70 — the script DeepSpeed copies into every
checkpoint directory so users can extract a plain fp32 state dict without
the training stack. This file is therefore fully standalone: stdlib + numpy
only, no deepspeed_tpu imports (it is shipped by copyfile at save time,
runtime/checkpointing.py).

Checkpoint layout (runtime/checkpointing.py docstring): a ``latest`` pointer
file, tag subdirectories holding per-rank ``model_states_shard_{r}.npz``
piece files plus ``shard_index_{r}.json`` indexes describing the global
index window each piece covers. Consolidation = union all indexes, paste
pieces into full arrays, strip the 'params/' prefix. (The older
single-file ``mp_rank_00_model_states.npz`` layout is also read.)

    python zero_to_fp32.py <checkpoint_dir> <output_file>
"""

import argparse
import json
import os

import numpy as np

LATEST_FILE = "latest"
MODEL_STATES_FILE = "mp_rank_00_model_states.npz"


def read_latest_tag(checkpoint_dir):
    latest_path = os.path.join(checkpoint_dir, LATEST_FILE)
    if os.path.isfile(latest_path):
        with open(latest_path) as f:
            return f.read().strip()
    return None


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag=None):
    """Return {path: np.ndarray(fp32)} of consolidated weights (reference
    zero_to_fp32.py get_fp32_state_dict_from_zero_checkpoint)."""
    if tag is None:
        tag = read_latest_tag(checkpoint_dir)
        if tag is None:
            raise FileNotFoundError(
                f"no 'latest' file in {checkpoint_dir}; pass an explicit tag")
    ckpt_dir = os.path.join(checkpoint_dir, str(tag))
    if not os.path.isdir(ckpt_dir) and os.path.isdir(ckpt_dir + ".old"):
        # a crash between save_checkpoint's renames leaves the only valid
        # save under the `.old` staging name
        ckpt_dir = ckpt_dir + ".old"
    indexes = [f for f in sorted(os.listdir(ckpt_dir))
               if f.startswith("shard_index_") and f.endswith(".json")]
    if indexes:
        return _assemble_sharded(ckpt_dir, indexes)
    model_path = os.path.join(ckpt_dir, MODEL_STATES_FILE)
    if not os.path.isfile(model_path):
        raise FileNotFoundError(
            f"no shard_index_*.json and no {MODEL_STATES_FILE} in {ckpt_dir}")
    out = {}
    with np.load(model_path, allow_pickle=False) as data:
        for key in data.files:
            if key.startswith("params/"):
                out[key[len("params/"):]] = np.asarray(data[key], np.float32)
    return out


def _assemble_sharded(ckpt_dir, index_files):
    """Merge every rank's model-state pieces into full fp32 arrays."""
    leaves = {}
    for fname in index_files:
        with open(os.path.join(ckpt_dir, fname)) as f:
            for full, info in json.load(f).items():
                stem, path = full.split(":", 1)
                if stem != "model_states" or not path.startswith("params/"):
                    continue
                entry = leaves.setdefault(
                    path[len("params/"):],
                    {"shape": tuple(info["shape"]),
                     "dtype": info["dtype"], "pieces": []})
                for p in info["pieces"]:
                    entry["pieces"].append({"file": info["file"], **p})
    out = {}
    files = {}
    for path, info in leaves.items():
        arr = np.zeros(info["shape"], np.float32)
        filled = 0
        for p in info["pieces"]:
            if p["file"] not in files:
                files[p["file"]] = np.load(
                    os.path.join(ckpt_dir, p["file"]), allow_pickle=False)
            shape = [b - a for a, b in zip(p["start"], p["stop"])]
            piece = _decode(files[p["file"]][p["key"]], info["dtype"], shape)
            sl = tuple(slice(a, b) for a, b in zip(p["start"], p["stop"]))
            arr[sl] = piece
            filled += int(np.prod(shape))
        if filled != arr.size:
            raise IOError(
                f"{path}: assembled {filled} of {arr.size} elements — a "
                f"rank's shard files are missing from {ckpt_dir}")
        out[path] = arr
    for f in files.values():
        f.close()
    return out


def _decode(raw, dtype, shape):
    """Pieces are stored as raw bytes (npz can't round-trip bfloat16);
    decode without requiring ml_dtypes: bf16 widens via a <<16 bit shift."""
    buf = raw.tobytes()
    if dtype == "bfloat16":
        u16 = np.frombuffer(buf, np.uint16).astype(np.uint32) << 16
        return u16.view(np.float32).astype(np.float32).reshape(shape)
    return np.asarray(
        np.frombuffer(buf, np.dtype(dtype)).reshape(shape), np.float32)


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir, output_file,
                                               tag=None):
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    np.savez(output_file, **sd)
    total = sum(int(np.prod(v.shape)) for v in sd.values())
    print(f"saved {len(sd)} tensors / {total:,} params to {output_file}")
    return output_file


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Merge a deepspeed_tpu ZeRO checkpoint into a single "
                    "fp32 weights file")
    parser.add_argument("checkpoint_dir",
                        help="directory containing the 'latest' file and "
                             "tag subdirectories")
    parser.add_argument("output_file",
                        help="path for the consolidated fp32 .npz")
    parser.add_argument("-t", "--tag", default=None,
                        help="checkpoint tag (default: read 'latest')")
    args = parser.parse_args(argv)
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir,
                                               args.output_file, args.tag)


if __name__ == "__main__":
    main()
