"""Offline ZeRO-checkpoint → consolidated fp32 weights converter.

Reference: utils/zero_to_fp32.py:70 — the script DeepSpeed copies into every
checkpoint directory so users can extract a plain fp32 state dict without
the training stack.

Here checkpoints store the full logical fp32 master tree per tag
(runtime/checkpointing.py docstring), so consolidation = load + strip
non-param state + write one npz. Multi-host shard merging goes through
`merge_zero_shards`. Usable as a module or CLI:

    python -m deepspeed_tpu.utils.zero_to_fp32 <checkpoint_dir> <output_file>
"""

import argparse
import os
import sys

import numpy as np


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag=None):
    """Return {path: np.ndarray(fp32)} of consolidated weights (reference
    zero_to_fp32.py get_fp32_state_dict_from_zero_checkpoint)."""
    from deepspeed_tpu.runtime.checkpointing import (
        read_latest_tag, merge_zero_shards, _flatten)
    if tag is None:
        tag = read_latest_tag(checkpoint_dir)
        if tag is None:
            raise FileNotFoundError(
                f"no 'latest' file in {checkpoint_dir}; pass an explicit tag")
    ckpt_dir = os.path.join(checkpoint_dir, str(tag))
    if not os.path.isdir(ckpt_dir):
        raise FileNotFoundError(f"checkpoint tag dir not found: {ckpt_dir}")
    params = merge_zero_shards(ckpt_dir)
    return {k: np.asarray(v, np.float32)
            for k, v in _flatten(params).items()}


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir, output_file,
                                               tag=None):
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    np.savez(output_file, **sd)
    total = sum(int(np.prod(v.shape)) for v in sd.values())
    print(f"saved {len(sd)} tensors / {total:,} params to {output_file}")
    return output_file


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Merge a deepspeed_tpu ZeRO checkpoint into a single "
                    "fp32 weights file")
    parser.add_argument("checkpoint_dir",
                        help="directory containing the 'latest' file and "
                             "tag subdirectories")
    parser.add_argument("output_file",
                        help="path for the consolidated fp32 .npz")
    parser.add_argument("-t", "--tag", default=None,
                        help="checkpoint tag (default: read 'latest')")
    args = parser.parse_args(argv)
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir,
                                               args.output_file, args.tag)


if __name__ == "__main__":
    main()
