"""Offline ZeRO-checkpoint → consolidated fp32 weights converter.

Reference: utils/zero_to_fp32.py:70 — the script DeepSpeed copies into every
checkpoint directory so users can extract a plain fp32 state dict without
the training stack. This file is therefore fully standalone: stdlib + numpy
only, no deepspeed_tpu imports (it is shipped by copyfile at save time,
runtime/checkpointing.py).

Checkpoint layout (runtime/checkpointing.py docstring): a ``latest`` pointer
file, tag subdirectories holding ``mp_rank_00_model_states.npz`` with
'/'-joined tree paths as npz keys; fp32 master weights live in the params
tree itself, so consolidation = load + strip the 'params/' prefix.

    python zero_to_fp32.py <checkpoint_dir> <output_file>
"""

import argparse
import os

import numpy as np

LATEST_FILE = "latest"
MODEL_STATES_FILE = "mp_rank_00_model_states.npz"


def read_latest_tag(checkpoint_dir):
    latest_path = os.path.join(checkpoint_dir, LATEST_FILE)
    if os.path.isfile(latest_path):
        with open(latest_path) as f:
            return f.read().strip()
    return None


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag=None):
    """Return {path: np.ndarray(fp32)} of consolidated weights (reference
    zero_to_fp32.py get_fp32_state_dict_from_zero_checkpoint)."""
    if tag is None:
        tag = read_latest_tag(checkpoint_dir)
        if tag is None:
            raise FileNotFoundError(
                f"no 'latest' file in {checkpoint_dir}; pass an explicit tag")
    ckpt_dir = os.path.join(checkpoint_dir, str(tag))
    model_path = os.path.join(ckpt_dir, MODEL_STATES_FILE)
    if not os.path.isfile(model_path):
        raise FileNotFoundError(f"model states not found: {model_path}")
    out = {}
    with np.load(model_path, allow_pickle=False) as data:
        for key in data.files:
            if key.startswith("params/"):
                out[key[len("params/"):]] = np.asarray(data[key], np.float32)
    return out


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir, output_file,
                                               tag=None):
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    np.savez(output_file, **sd)
    total = sum(int(np.prod(v.shape)) for v in sd.values())
    print(f"saved {len(sd)} tensors / {total:,} params to {output_file}")
    return output_file


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Merge a deepspeed_tpu ZeRO checkpoint into a single "
                    "fp32 weights file")
    parser.add_argument("checkpoint_dir",
                        help="directory containing the 'latest' file and "
                             "tag subdirectories")
    parser.add_argument("output_file",
                        help="path for the consolidated fp32 .npz")
    parser.add_argument("-t", "--tag", default=None,
                        help="checkpoint tag (default: read 'latest')")
    args = parser.parse_args(argv)
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir,
                                               args.output_file, args.tag)


if __name__ == "__main__":
    main()
