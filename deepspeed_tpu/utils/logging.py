"""Rank-aware logging — TPU-native rebuild of deepspeed/utils/logging.py:7,40.

On TPU-VM there is one process per host; "rank" here is ``jax.process_index``.
"""

import logging
import sys

LOG_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"


class LoggerFactory:
    @staticmethod
    def create_logger(name=None, level=logging.INFO):
        if name is None:
            raise ValueError("name for logger cannot be None")
        formatter = logging.Formatter(LOG_FORMAT)
        logger_ = logging.getLogger(name)
        logger_.setLevel(level)
        logger_.propagate = False
        if not logger_.handlers:
            handler = logging.StreamHandler(stream=sys.stdout)
            handler.setFormatter(formatter)
            logger_.addHandler(handler)
        return logger_


logger = LoggerFactory.create_logger(name="deepspeed_tpu", level=logging.INFO)


def _process_index():
    """Current process rank WITHOUT forcing backend initialization: calling
    jax.process_index() before jax.distributed.initialize would both break
    the multi-host rendezvous (backend init must come after) and pin the
    rank to 0. Uncached — the rank changes when distributed init runs."""
    try:
        import jax
        from jax._src import xla_bridge as xb
        if not xb._backends:
            return 0
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log on selected process ranks only (reference utils/logging.py:40).

    ``ranks=None`` or ``[-1]`` logs everywhere; otherwise only listed
    ``jax.process_index`` values log, prefixed with the rank.
    """
    my_rank = _process_index()
    if ranks is None or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")
