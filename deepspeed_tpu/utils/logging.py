"""Rank-aware logging — TPU-native rebuild of deepspeed/utils/logging.py:7,40.

On TPU-VM there is one process per host; "rank" here is ``jax.process_index``.
"""

import logging
import sys

LOG_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"


class LoggerFactory:
    @staticmethod
    def create_logger(name=None, level=logging.INFO):
        if name is None:
            raise ValueError("name for logger cannot be None")
        formatter = logging.Formatter(LOG_FORMAT)
        logger_ = logging.getLogger(name)
        logger_.setLevel(level)
        logger_.propagate = False
        if not logger_.handlers:
            handler = logging.StreamHandler(stream=sys.stdout)
            handler.setFormatter(formatter)
            logger_.addHandler(handler)
        return logger_


logger = LoggerFactory.create_logger(name="deepspeed_tpu", level=logging.INFO)


def _process_index():
    """Current process rank WITHOUT forcing backend initialization: calling
    jax.process_index() before jax.distributed.initialize would both break
    the multi-host rendezvous (backend init must come after) and pin the
    rank to 0. Uncached — the rank changes when distributed init runs."""
    try:
        import jax
    except Exception:
        return 0
    try:
        # private API (jax 0.4.x): the only way to ask "is a backend already
        # initialized" without initializing one. If a jax upgrade moves the
        # symbol, fall through to jax.process_index() — by then callers are
        # typically past distributed init, so the cure is worse only in the
        # narrow pre-init window, and we accept that rather than guessing 0
        # forever (which re-enables duplicated logging on every process).
        from jax._src import xla_bridge as xb
        backends_initialized = bool(xb._backends)
    except Exception:
        backends_initialized = None
    try:
        if backends_initialized is False:
            return 0
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log on selected process ranks only (reference utils/logging.py:40).

    ``ranks=None`` or ``[-1]`` logs everywhere; otherwise only listed
    ``jax.process_index`` values log, prefixed with the rank.
    """
    my_rank = _process_index()
    if ranks is None or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")
