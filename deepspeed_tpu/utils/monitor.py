"""Training monitor — the engine's TensorBoard scalar stream (reference
engine.py:162-163 SummaryWriter construction and the scalar writes at
:291-316, :1095-1105, :1272-1298).

Uses torch.utils.tensorboard when importable (tensorboard is in the base
image); otherwise falls back to a JSONL event log with the same tags, so
monitoring never becomes a hard dependency.
"""

import atexit
import json
import os
import time

from deepspeed_tpu.utils.logging import logger


class SummaryEventWriter:
    """add_scalar/flush/close facade over SummaryWriter or a JSONL file."""

    def __init__(self, output_path="runs/", job_name="DeepSpeedJobName"):
        self.log_dir = os.path.join(output_path, job_name)
        os.makedirs(self.log_dir, exist_ok=True)
        self._tb = None
        self._fh = None
        # every JSONL event self-identifies for multi-process merge:
        # {tag, value, step} alone cannot be interleaved across ranks
        from deepspeed_tpu.telemetry.registry import _process_rank
        self._rank = _process_rank()
        try:
            from torch.utils.tensorboard import SummaryWriter
            self._tb = SummaryWriter(log_dir=self.log_dir)
        except Exception as e:
            logger.warning(f"tensorboard unavailable ({e}); "
                           f"writing JSONL events to {self.log_dir}")
            # one file per rank: concurrent appends from several
            # processes into one file would interleave mid-line
            name = "events.jsonl" if self._rank == 0 \
                else f"events_rank{self._rank}.jsonl"
            self._fh = open(os.path.join(self.log_dir, name), "a")
            # the engine has no teardown hook that reliably runs on process
            # exit; without this, scalars buffered since the last
            # steps_per_print flush are lost
            atexit.register(self.close)

    def add_scalar(self, tag, value, step):
        if self._tb is not None:
            self._tb.add_scalar(tag, float(value), int(step))
        else:
            self._fh.write(json.dumps(
                {"tag": tag, "value": float(value), "step": int(step),
                 "ts": time.time(), "rank": self._rank}) + "\n")

    def flush(self):
        if self._tb is not None:
            self._tb.flush()
        elif self._fh is not None:
            self._fh.flush()

    def close(self):
        if self._tb is not None:
            self._tb.close()
        elif self._fh is not None:
            self._fh.close()
            atexit.unregister(self.close)
