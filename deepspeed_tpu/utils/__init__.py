from deepspeed_tpu.utils.logging import logger, log_dist
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer
from deepspeed_tpu.utils.memory import see_memory_usage
