"""Memory reporting — rebuild of ``see_memory_usage`` (deepspeed/runtime/utils.py).

Reports host RSS plus per-device HBM stats where the backend exposes
``memory_stats()`` (TPU runtime does; CPU backend returns nothing).
"""

import resource

from deepspeed_tpu.utils.logging import logger


def _device_memory_stats():
    try:
        import jax
        stats = []
        for d in jax.local_devices():
            s = getattr(d, "memory_stats", None)
            s = s() if callable(s) else None
            if s:
                stats.append((str(d), s.get("bytes_in_use", 0), s.get("bytes_limit", 0)))
        return stats
    except Exception:
        return []


def see_memory_usage(message, force=False):
    if not force:
        return
    rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
    lines = [message, f"Host MaxRSS {rss_mb:.1f} MB"]
    for name, in_use, limit in _device_memory_stats():
        lines.append(f"{name}: HBM in use {in_use / 2**30:.2f} GB / {limit / 2**30:.2f} GB")
    logger.info(" | ".join(lines))
