"""Memory reporting — rebuild of ``see_memory_usage`` (deepspeed/runtime/utils.py).

Reports host RSS plus per-device HBM stats where the backend exposes
``memory_stats()`` (TPU runtime does; CPU backend returns nothing).
"""

import resource

from deepspeed_tpu.utils.logging import logger

# peak live gathered-parameter bytes of the stage3_prefetch pipeline
# (parallel/prefetch.py): STATIC accounting from the layer plan — two
# gathered layers (current + in-flight double buffer) plus the
# persistent (outer + below-threshold) full leaves. Recorded by the
# engine when it builds the prefetch train path, so
# ``stage3_max_live_parameters`` is observable/assertable instead of
# on-faith. None until a prefetch engine has been built.
_live_gathered_param_bytes = None


def record_live_gathered_param_bytes(nbytes):
    global _live_gathered_param_bytes
    _live_gathered_param_bytes = int(nbytes) if nbytes is not None else None


def live_gathered_param_bytes():
    """Peak live gathered-parameter bytes of the most recently built
    stage3_prefetch train path (None when no prefetch engine exists)."""
    return _live_gathered_param_bytes


def _device_memory_stats():
    try:
        import jax
        stats = []
        for d in jax.local_devices():
            s = getattr(d, "memory_stats", None)
            s = s() if callable(s) else None
            if s:
                stats.append((str(d), s.get("bytes_in_use", 0), s.get("bytes_limit", 0)))
        return stats
    except Exception:
        return []


def host_max_rss_mb():
    """Host peak RSS in MB (ru_maxrss is KB on Linux)."""
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def memory_metrics():
    """One flat dict of the memory observables, for the telemetry
    scalar stream: host RSS, per-device HBM in use where the backend
    exposes it, and the stage3_prefetch live-gathered window."""
    out = {"host_max_rss_mb": host_max_rss_mb()}
    if _live_gathered_param_bytes is not None:
        out["live_gathered_param_bytes"] = _live_gathered_param_bytes
    for i, (_, in_use, limit) in enumerate(_device_memory_stats()):
        out[f"device{i}_bytes_in_use"] = in_use
        out[f"device{i}_bytes_limit"] = limit
    return out


def see_memory_usage(message, force=False):
    if not force:
        return
    rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
    lines = [message, f"Host MaxRSS {rss_mb:.1f} MB"]
    for name, in_use, limit in _device_memory_stats():
        lines.append(f"{name}: HBM in use {in_use / 2**30:.2f} GB / {limit / 2**30:.2f} GB")
    if _live_gathered_param_bytes is not None:
        lines.append(f"stage3_prefetch live gathered params "
                     f"{_live_gathered_param_bytes / 2**20:.1f} MB")
    logger.info(" | ".join(lines))
