"""PEP 562 lazy-attribute machinery shared by the packages whose bare
import must stay dependency-free (the root ``deepspeed_tpu/__init__``
must not drag in jax, ``telemetry/__init__`` must not drag in numpy —
the stdlib-only dump-viewer contract, pinned by
tests/test_metric_names.py with poisoned stubs). This module itself
imports nothing beyond importlib, so it sits below that chain."""


def lazy_attrs(module_name, mapping):
    """Build the ``(__getattr__, __dir__)`` pair for a lazily-resolved
    module surface. ``mapping``: attribute name -> ``(target_module,
    target_attr_or_None)`` (None = the module itself). Resolved values
    are cached onto the requesting module, so each attribute pays the
    import once."""
    import sys

    def __getattr__(name):
        try:
            target_module, attr = mapping[name]
        except KeyError:
            raise AttributeError(
                f"module {module_name!r} has no attribute {name!r}")
        import importlib
        mod = importlib.import_module(target_module)
        value = mod if attr is None else getattr(mod, attr)
        setattr(sys.modules[module_name], name, value)
        return value

    def __dir__():
        return sorted(set(vars(sys.modules[module_name])) | set(mapping))

    return __getattr__, __dir__
