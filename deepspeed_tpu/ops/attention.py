"""Attention dispatch: Pallas flash attention on TPU, jnp reference elsewhere.

This is the TPU answer to the reference's fused softmax/attention CUDA kernels
(csrc/transformer/softmax_kernels.cu and the attention-score path of
ds_transformer_cuda.cpp): one fused kernel that never materializes the
[S, S] score matrix in HBM.
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp


def reference_attention(q, k, v, causal=False, bias=None, scale=None,
                        segment_ids=None):
    """Pure-XLA attention on [B, H, S, D] tensors. Numerically the ground
    truth for the Pallas kernels (the test methodology of the reference's
    test_cuda_forward.py, SURVEY §4). K/V may carry Hkv < H heads
    (grouped-query); the reference repeats them (the kernels do not)."""
    B, H, S, D = q.shape
    if k.shape[1] != H:
        rep = H // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scale = scale if scale is not None else 1.0 / np.sqrt(D)
    scores = jnp.einsum("bhsd,bhtd->bhst", q, k).astype(jnp.float32) * scale
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    neg = jnp.float32(-1e30)
    if causal:
        causal_mask = jnp.tril(jnp.ones((S, k.shape[2]), dtype=bool))
        scores = jnp.where(causal_mask[None, None], scores, neg)
    if segment_ids is not None:
        seg_mask = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        scores = jnp.where(seg_mask, scores, neg)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhst,bhtd->bhsd", probs.astype(q.dtype), v)


def _on_tpu():
    from deepspeed_tpu.utils.platform import is_tpu_backend
    return is_tpu_backend()


def dot_product_attention(q, k, v, causal=False, bias=None, scale=None,
                          segment_ids=None, use_flash=None):
    """[B, H, S, D] attention. ``use_flash=None`` auto-selects the Pallas
    flash kernel on TPU for flash-compatible shapes. K/V may carry
    Hkv < H heads (grouped-query): the flash kernel streams the reduced
    cache directly via Hkv-aware block maps — full-head K/V is never
    materialized in the forward."""
    if use_flash is None:
        use_flash = _on_tpu() and bias is None and segment_ids is None
    if use_flash:
        try:
            from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
            return flash_attention(q, k, v, causal=causal, scale=scale)
        except Exception:
            pass
    return reference_attention(q, k, v, causal=causal, bias=bias, scale=scale,
                               segment_ids=segment_ids)
