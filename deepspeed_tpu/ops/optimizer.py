"""Optimizer base protocol for the TPU engine.

The reference wraps torch optimizers (mutable ``param_groups``); here an
optimizer is a pure function pair over pytrees:

    state = opt.init(params)
    new_params, new_state = opt.step(params, grads, state, lr)

State entries mirror params' tree structure leaf-for-leaf (``exp_avg`` etc.),
which is what lets ZeRO stages 1-3 shard optimizer state with the same
PartitionSpecs as the parameters (SURVEY §7 design stance). ``lr`` is traced,
so LR schedules run under jit without recompilation.

Any optax ``GradientTransformation`` can be adapted via :class:`OptaxOptimizer`.
"""

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp


class TpuOptimizer:
    """Base class: subclasses implement init/step as pure functions."""

    #: state-tree fields that have the same shape as the params tree; ZeRO
    #: uses this to extend param shardings onto the optimizer state.
    param_like_state_fields = ()

    #: True when ``step`` is purely elementwise over each leaf (no
    #: per-tensor statistics like LAMB's trust ratio), i.e. updating a
    #: slice of a leaf with the matching moment slice is exact. The
    #: overlap_comm train path relies on this to run the per-shard ZeRO
    #: update inside shard_map (engine._build_overlap_train_fn).
    elementwise_update = False

    def init(self, params) -> Dict[str, Any]:
        raise NotImplementedError

    def step(self, params, grads, state, lr):
        raise NotImplementedError

    # torch-API-style param-group compat used by LR schedulers
    @property
    def defaults(self):
        return dataclasses.asdict(self) if dataclasses.is_dataclass(self) else {}


class OptaxOptimizer(TpuOptimizer):
    """Adapter for an optax GradientTransformation. The optax state tuple
    does not mirror the params-tree structure, so ZeRO leaves it replicated
    (no entry in param_like_state_fields); use the native optimizers for
    sharded optimizer state."""

    param_like_state_fields = ()

    def __init__(self, tx):
        self.tx = tx

    def init(self, params):
        return {"optax": self.tx.init(params)}

    def step(self, params, grads, state, lr):
        # lr is ignored here — bake schedules into the optax chain instead.
        updates, new_inner = self.tx.update(grads, state["optax"], params)
        new_params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return new_params, {"optax": new_inner}


def tree_zeros_like(params, dtype=None):
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, dtype or p.dtype), params)
