"""Quantizer op (reference deepspeed/ops/quantizer/quantizer.py
`ds_quantizer`)."""

from deepspeed_tpu.ops.pallas.quantize import (
    quantize,
    quantize_jnp,
    quantize_packed,
    dequantize_packed,
)


def ds_quantizer(input, groups=1, bit_num=8, sr=False, asym=False, key=None):
    """API-parity entry (ops/quantizer/quantizer.py:10-30): dispatches to the
    grouped Pallas kernel; `sr` = stochastic rounding, `asym` = asymmetric."""
    return quantize(input, bits=bit_num, groups=groups, sym=not asym,
                    stochastic=sr, key=key)


__all__ = ["ds_quantizer", "quantize", "quantize_jnp", "quantize_packed",
           "dequantize_packed"]
