"""Fused transformer layer (reference deepspeed/ops/transformer/__init__.py)."""

from deepspeed_tpu.ops.transformer.transformer import (
    DeepSpeedTransformerConfig,
    DeepSpeedTransformerLayer,
    transformer_layer,
)

__all__ = [
    "DeepSpeedTransformerConfig",
    "DeepSpeedTransformerLayer",
    "transformer_layer",
]
