"""Fused transformer encoder layer — TPU rebuild of the reference's CUDA
transformer kernels.

Reference surface: ops/transformer/transformer.py:39 `DeepSpeedTransformerConfig`
and :462 `DeepSpeedTransformerLayer`, backed by csrc/transformer/
ds_transformer_cuda.cpp:1029-1046 (forward_fp16/backward_fp16) plus the kernel
files (normalize/dropout/softmax/transform/gelu_kernels.cu).

TPU design (not a port):

- The layer is a flax module compiled by XLA. The CUDA version exists because
  2021 torch couldn't fuse LN+GEMM+bias+gelu+dropout; XLA fuses all the
  elementwise work into the surrounding matmuls natively, and the one kernel
  XLA can't produce — attention without materializing the [S,S] score matrix
  — is the Pallas flash kernel (ops/pallas/flash_attention.py).
- The reference's memory-saving config knobs map to remat policy, not custom
  kernels: `normalize_invertible`, `gelu_checkpoint` and
  `attn_dropout_checkpoint` (transformer.py:109-112) all mean "recompute this
  activation in backward instead of storing it". Here they select names
  excluded from the saveable set of a `jax.checkpoint` policy
  (`DeepSpeedTransformerConfig.remat_policy`).
- `stochastic_mode` (transformer.py:130, ~2% speedup via relaxed determinism)
  has no TPU meaning: XLA is deterministic at no cost. Accepted and ignored.
- `batch_size`/`max_seq_length` preallocation arguments are unnecessary
  (XLA specializes on shapes at trace time); accepted for API parity.
- fp16 → bf16: the MXU-native dtype needs no loss scaling; `fp16=True`
  selects bf16 compute (pass `dtype=jnp.float16` explicitly for true fp16).
"""

import dataclasses
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.ad_checkpoint import checkpoint_name

from deepspeed_tpu.ops.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class DeepSpeedTransformerConfig:
    """Config parity with reference ops/transformer/transformer.py:95-142,
    re-interpreted for TPU (see module docstring for the mapping)."""
    batch_size: int = -1            # parity only; XLA shape-specializes
    max_seq_length: int = -1        # parity only
    hidden_size: int = -1
    intermediate_size: int = -1     # -1 → 4*hidden (reference transformer.py:144)
    heads: int = -1
    attn_dropout_ratio: float = 0.0
    hidden_dropout_ratio: float = 0.0
    num_hidden_layers: int = -1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    local_rank: int = -1            # parity only
    seed: int = -1                  # parity only; flax RNG is explicit
    fp16: bool = False              # → bf16 compute (MXU native)
    pre_layer_norm: bool = True
    normalize_invertible: bool = False      # remat LN outputs
    gelu_checkpoint: bool = False           # remat the [B,S,4E] gelu output
    adjust_init_range: bool = True          # output-proj init / sqrt(2L)
    attn_dropout_checkpoint: bool = False   # remat attention context
    stochastic_mode: bool = False           # no-op on TPU (deterministic XLA)
    huggingface: bool = False               # HF additive-mask semantics
    training: bool = True
    dtype: Any = None               # explicit compute dtype override
    param_dtype: Any = jnp.float32
    # block-sparse attention: a SparsityConfig routes the attention through
    # the Pallas block-sparse kernel (the reference integrates sparse
    # attention into BERT via module surgery; here it is a config knob)
    sparsity_config: Any = None

    @property
    def compute_dtype(self):
        if self.dtype is not None:
            return self.dtype
        return jnp.bfloat16 if self.fp16 else jnp.float32

    @property
    def ffn_size(self):
        return self.intermediate_size if self.intermediate_size > 0 \
            else 4 * self.hidden_size

    @property
    def head_dim(self):
        return self.hidden_size // self.heads

    def remat_policy(self):
        """Checkpoint policy implementing the reference's memory knobs.

        Returns None when no knob is set (store everything). Otherwise a
        policy that saves everything EXCEPT the named residuals the knobs
        mark recomputable — the jax.checkpoint analog of the reference
        freeing exactly those buffers in forward and regenerating them in
        backward (csrc/transformer/ds_transformer_cuda.cpp gelu/LN/
        attn-context checkpoint branches), with every other intermediate
        still stored.
        """
        if not (self.normalize_invertible or self.gelu_checkpoint
                or self.attn_dropout_checkpoint):
            return None
        dropped = set()
        if self.normalize_invertible:
            dropped |= {"attn_ln", "ffn_ln"}
        if self.gelu_checkpoint:
            dropped |= {"gelu_out"}
        if self.attn_dropout_checkpoint:
            dropped |= {"attn_context"}
        return jax.checkpoint_policies.save_anything_except_these_names(
            *sorted(dropped))


class DeepSpeedTransformerLayer(nn.Module):
    """Fused BERT-style encoder layer (reference transformer.py:462).

    Input: hidden states [B, S, E]; `attention_mask` either an additive bias
    broadcastable to [B, 1, S, S] (huggingface=True semantics) or a [B, S]
    1/0 key-validity mask. Output: [B, S, E].

    Parameter names follow the reference's layer attributes
    (attn_qkvw/attn_ow/inter_w/output_w..., transformer.py:467-489) so that
    module injection (module_inject/replace_module.py:8) can copy weights
    between HF layers and this one mechanically.
    """
    config: DeepSpeedTransformerConfig

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None, deterministic=True):
        cfg = self.config
        B, S, E = hidden_states.shape
        dt = cfg.compute_dtype
        init = nn.initializers.normal(cfg.initializer_range)
        out_scale = 1.0
        if cfg.adjust_init_range and cfg.num_hidden_layers > 0:
            # reference transformer.py:152-155: shrink output-proj init by
            # sqrt(2*num_layers) for training stability
            out_scale = 1.0 / np.sqrt(2.0 * cfg.num_hidden_layers)
        out_init = nn.initializers.normal(cfg.initializer_range * out_scale)

        x = hidden_states.astype(dt)
        bias, segment_ids = _canonical_mask(attention_mask)

        ln_kw = dict(epsilon=cfg.layer_norm_eps, dtype=dt,
                     param_dtype=cfg.param_dtype)

        def attn_block(h):
            qkv = nn.Dense(3 * E, dtype=dt, param_dtype=cfg.param_dtype,
                           kernel_init=init, name="attn_qkvw")(h)
            q, k, v = jnp.split(qkv, 3, axis=-1)

            def heads(t):
                return t.reshape(B, S, cfg.heads, cfg.head_dim) \
                        .transpose(0, 2, 1, 3)

            if cfg.sparsity_config is not None:
                from deepspeed_tpu.ops.sparse_attention.sparse_self_attention \
                    import sparse_attention
                layout = cfg.sparsity_config.make_layout(S)
                kpm = None
                if segment_ids is not None:
                    kpm = segment_ids != 0
                ctx = sparse_attention(heads(q), heads(k), heads(v),
                                       layout, cfg.sparsity_config.block,
                                       key_padding_mask=kpm,
                                       attn_mask=None)
            elif cfg.attn_dropout_ratio > 0 and not deterministic:
                # reference semantics: dropout on the softmax PROBABILITIES
                # (csrc/transformer attn_prob dropout), not the context —
                # needs materialized probs, so this training-with-attn-dropout
                # path bypasses the flash kernel. attn_dropout_ratio=0 (the
                # common modern recipe) keeps the Pallas flash path.
                D = cfg.head_dim
                scores = jnp.einsum("bhsd,bhtd->bhst", heads(q),
                                    heads(k)).astype(jnp.float32) / np.sqrt(D)
                if bias is not None:
                    scores = scores + bias
                if segment_ids is not None:
                    seg = segment_ids[:, None, :, None] == \
                        segment_ids[:, None, None, :]
                    scores = jnp.where(seg, scores, jnp.float32(-1e30))
                probs = jax.nn.softmax(scores, axis=-1)
                probs = nn.Dropout(cfg.attn_dropout_ratio)(
                    probs, deterministic=False)
                ctx = jnp.einsum("bhst,bhtd->bhsd", probs.astype(dt), heads(v))
            else:
                ctx = dot_product_attention(heads(q), heads(k), heads(v),
                                            causal=False, bias=bias,
                                            segment_ids=segment_ids)
            ctx = checkpoint_name(ctx, "attn_context")
            ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, E)
            return nn.Dense(E, dtype=dt, param_dtype=cfg.param_dtype,
                            kernel_init=out_init, name="attn_ow")(ctx)

        def ffn_block(h):
            inter = nn.Dense(cfg.ffn_size, dtype=dt,
                             param_dtype=cfg.param_dtype,
                             kernel_init=init, name="inter_w")(h)
            inter = checkpoint_name(nn.gelu(inter, approximate=False),
                                    "gelu_out")
            return nn.Dense(E, dtype=dt, param_dtype=cfg.param_dtype,
                            kernel_init=out_init, name="output_w")(inter)

        def dropout(h):
            if cfg.hidden_dropout_ratio > 0:
                return nn.Dropout(cfg.hidden_dropout_ratio)(
                    h, deterministic=deterministic)
            return h

        if cfg.pre_layer_norm:
            h = checkpoint_name(
                nn.LayerNorm(**ln_kw, name="attn_nw")(x), "attn_ln")
            x = x + dropout(attn_block(h))
            h = checkpoint_name(
                nn.LayerNorm(**ln_kw, name="norm_w")(x), "ffn_ln")
            x = x + dropout(ffn_block(h))
        else:  # post-LN (original BERT)
            x = checkpoint_name(
                nn.LayerNorm(**ln_kw, name="attn_nw")(x + dropout(attn_block(x))),
                "attn_ln")
            x = checkpoint_name(
                nn.LayerNorm(**ln_kw, name="norm_w")(x + dropout(ffn_block(x))),
                "ffn_ln")
        return x


def _canonical_mask(attention_mask):
    """Normalize the two mask conventions the reference supports
    (huggingface additive bias vs raw kernel mask, transformer.py:133-136)
    into (bias, segment_ids) for dot_product_attention.

    Dispatch is by SHAPE, never dtype: a 2-D [B, S] mask is always a
    key-validity mask (1/True = attend, 0/False = pad — HF's raw
    `attention_mask` input, in any dtype); 3-D/4-D masks are additive
    biases broadcastable to [B, 1/H, S, S] (HF's extended/preprocessed
    form, 0 for attend / large-negative for pad)."""
    if attention_mask is None:
        return None, None
    m = jnp.asarray(attention_mask)
    if m.ndim == 2:
        # valid=1 / pad=0 partitions as segment ids
        return None, (m > 0.5).astype(jnp.int32) if not \
            jnp.issubdtype(m.dtype, jnp.integer) else m
    if m.ndim == 3:
        m = m[:, None]
    return m.astype(jnp.float32), None


def transformer_layer(config: DeepSpeedTransformerConfig):
    """Build the layer, applying the config's remat policy — the analog of
    the reference choosing the checkpointing CUDA kernel variants at
    layer-construction time (transformer.py:530-560)."""
    policy = config.remat_policy()
    if policy is None:
        return DeepSpeedTransformerLayer(config)
    # static_argnums counts self as 0: 3 = `deterministic`, which drives
    # python-level dropout branching and must stay concrete under the remat
    # trace. NOTE: the lifted checkpoint requires the static index to fall
    # inside the actual positional args, so a rematted layer must be called
    # as layer(x, attention_mask, deterministic) — all three positional.
    layer_cls = nn.remat(DeepSpeedTransformerLayer, policy=policy,
                         prevent_cse=False, static_argnums=(3,))
    return layer_cls(config)
