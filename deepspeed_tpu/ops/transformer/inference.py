"""Fused inference transformer layer — TPU rebuild of the reference's
inference kernels (csrc/transformer/inference/csrc/pt_binding.cpp, Python
wrapper ops/transformer/inference/transformer_inference.py:102-473).

TPU design:

- One flax module serves both phases the CUDA path special-cases: full-context
  ("prompt") processing and incremental single-token decode with a KV cache.
  The cache is flax's standard ``cache`` variable collection — static
  HEAD-MAJOR shapes ([B, H, max_out_tokens, D]) so the decode step compiles
  once, XLA keeps it resident in HBM, and the decode contraction is a
  (B,H)-batched dot_general with L on the lane axis (the [B, L, H, D]
  einsum form measured 3.7x over the read bound; docs/perf_tuning.md r4).
- The CUDA custom GEMM + fused softmax (custom_gemm.cu, softmax.cu) become
  MXU matmuls with XLA-fused masking; decode attention is one [B,H,1,L]
  score row against the cache — bandwidth-bound, which HBM handles natively.
- Tensor-parallel inference (module_inject's mp_size sharding,
  replace_module.py:16-17) is PartitionSpecs over the mesh 'model' axis
  (`inference_tp_specs`): qkv/intermediate column-parallel, output
  projections row-parallel; XLA inserts the psum.
- Parameter names match the training layer (attn_qkvw/attn_ow/inter_w/
  output_w/attn_nw/norm_w) so one injection policy feeds both.
"""

import dataclasses
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.mesh import MODEL_AXIS


@dataclasses.dataclass(frozen=True)
class DeepSpeedInferenceConfig:
    """Parity surface of transformer_inference.py's DeepSpeedInferenceConfig
    (hidden_size/heads/fp16/pre_layer_norm/mp_size/triangular_masking...)."""
    hidden_size: int = -1
    intermediate_size: int = -1          # -1 → 4*hidden
    heads: int = -1
    layer_norm_eps: float = 1e-12
    pre_layer_norm: bool = True
    fp16: bool = False                   # → bf16 compute
    mp_size: int = 1
    triangular_masking: bool = True      # causal (decoder) vs encoder
    max_out_tokens: int = 1024           # KV cache length
    gelu_approximate: bool = False       # tanh-approx GELU (GPT-2) vs exact
    # int8-storage serving (the reference's quantized inference kernels,
    # module_inject/module_quantize.py + inference int8 GEMMs): weight
    # matrices live in HBM as int8 codes + per-group fp32 scales and
    # dequantize at the matmul read — 4x weight-memory reduction
    quantize_bits: int = 0               # 0 = off; 8 = int8 storage
    quantize_groups: int = 1
    # MoE FFN serving: the block's dense FFN is replaced by the
    # expert-parallel MoE bank (deepspeed_tpu/moe), routed per token at
    # decode time. Expert weights are served unquantized. NOTE on
    # capacity semantics: training-time routing truncates to a capacity
    # derived from the ROUTED sequence length, so its outputs are
    # length-dependent whenever truncation binds; decode routes each new
    # token alone (capacity never binds for it — no decoded token is
    # dropped) and prompt tokens at prompt length. The two coincide
    # exactly when capacity_factor is high enough that truncation never
    # binds; under binding capacity there is no single "training
    # equivalent" to match.
    moe_experts: int = 0
    moe_k: int = 1
    moe_capacity_factor: float = 1.25
    # int8 KV-cache storage: cached K/V live as int8 codes + per
    # (batch, position, head) fp32 absmax scales — 2x less cache HBM and
    # read traffic vs bf16 (4x vs fp32), the difference between a 2k x
    # batch-32 GPT-2-large cache fitting a 16 GB chip or not. Symmetric
    # per-head-per-token quantization; scores compute on dequantized
    # values in the activation dtype.
    kv_cache_bits: int = 0               # 0 = off; 8 = int8 storage
    dtype: Any = None
    param_dtype: Any = jnp.float32

    def __post_init__(self):
        if self.kv_cache_bits not in (0, 8):
            raise ValueError(
                f"kv_cache_bits must be 0 (off) or 8 (int8 storage), got "
                f"{self.kv_cache_bits} — silently serving a full-precision "
                f"cache would defeat the memory sizing the caller did")
        if self.quantize_bits not in (0, 8):
            raise ValueError(
                f"quantize_bits must be 0 or 8, got {self.quantize_bits}")

    @property
    def compute_dtype(self):
        if self.dtype is not None:
            return self.dtype
        return jnp.bfloat16 if self.fp16 else jnp.float32

    @property
    def ffn_size(self):
        return self.intermediate_size if self.intermediate_size > 0 \
            else 4 * self.hidden_size

    @property
    def head_dim(self):
        return self.hidden_size // self.heads


class QuantDense(nn.Module):
    """Dense layer over int8-stored weights: params are `kernel_q`
    (int8 [in, out]) + `kernel_scale` (fp32 [groups, 1]) + `bias`; the
    dequantize fuses into the matmul's weight read under XLA."""
    features: int
    groups: int = 1
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def raw(self, in_features):
        """Declare and return this projection's (codes, scale, bias)
        without running the matmul — lets the parent layer feed the
        fused decode kernels (ops/pallas/decode.py) with several
        projections' params in one pallas_call. Param names/shapes are
        identical either way, so checkpoints and injection policies see
        one layout regardless of path. NOTE: per-Pallas-call overhead is
        ~9 µs on v5e, so per-projection matvec kernels LOSE to XLA at
        decode shapes — only multi-matmul fusions (whole FFN) win."""
        kq = self.param("kernel_q", nn.initializers.zeros,
                        (in_features, self.features), jnp.int8)
        scale = self.param("kernel_scale", nn.initializers.ones,
                           (self.groups, 1), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), self.param_dtype)
        return kq, scale, bias

    def __call__(self, x):
        in_features = x.shape[-1]
        kq, scale, bias = self.raw(in_features)
        w = (kq.astype(jnp.float32).reshape(self.groups, -1)
             * scale).reshape(in_features, self.features)
        y = x @ w.astype(self.dtype)
        return y + bias.astype(self.dtype)


class _LNParams(nn.Module):
    """Declares LayerNorm params (same names/shapes/init as nn.LayerNorm)
    without running the normalization — the fused decode kernels compute
    LN in-kernel but the param tree must stay checkpoint-identical."""
    features: int
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self):
        scale = self.param("scale", nn.initializers.ones,
                           (self.features,), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), self.param_dtype)
        return scale, bias


class DeepSpeedTransformerInference(nn.Module):
    """Inference encoder/decoder layer with optional KV cache.

    Modes:
      - encoder (``triangular_masking=False``): plain bidirectional layer.
      - decoder prompt pass: ``mutable=["cache"]`` with S>1 fills the cache.
      - decode step: S==1 with an initialized cache appends and attends to
        the prefix.
    """
    config: DeepSpeedInferenceConfig

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None):
        cfg = self.config
        B, S, E = hidden_states.shape
        dt = cfg.compute_dtype
        H, D = cfg.heads, cfg.head_dim
        x = hidden_states.astype(dt)

        if (cfg.quantize_bits == 8 and cfg.kv_cache_bits == 8 and S == 1
                and attention_mask is None and cfg.pre_layer_norm
                and cfg.triangular_masking and not cfg.moe_experts
                and cfg.quantize_groups == 1 and B <= 8
                and cfg.mp_size == 1
                and E % 128 == 0 and self.config.ffn_size % 128 == 0
                and (self.has_variable("cache", "cached_key_q8")
                     or self.is_mutable_collection("cache"))):
            # mp_size > 1 keeps the GSPMD path: the fused kernels are
            # opaque custom calls XLA cannot shard over the model axis
            return self._decode_step_fused(x, B, E, H, D, dt)

        ln_kw = dict(epsilon=cfg.layer_norm_eps, dtype=dt,
                     param_dtype=cfg.param_dtype)
        dense_kw = dict(dtype=dt, param_dtype=cfg.param_dtype)

        def make_dense(features, name):
            if cfg.quantize_bits:
                return QuantDense(features, groups=cfg.quantize_groups,
                                  dtype=dt, param_dtype=cfg.param_dtype,
                                  name=name)
            return nn.Dense(features, **dense_kw, name=name)

        def attn(h):
            qkv = make_dense(3 * E, "attn_qkvw")(h)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, S, H, D)
            k = k.reshape(B, S, H, D)
            v = v.reshape(B, S, H, D)
            ctx = self._attend(q, k, v, attention_mask)
            ctx = ctx.reshape(B, S, E)
            return make_dense(E, "attn_ow")(ctx)

        def ffn(h):
            if cfg.moe_experts:
                from deepspeed_tpu.moe import MoE
                return MoE(num_experts=cfg.moe_experts, d_ff=cfg.ffn_size,
                           k=cfg.moe_k,
                           capacity_factor=cfg.moe_capacity_factor,
                           dtype=dt, param_dtype=cfg.param_dtype,
                           name="moe")(h, deterministic=True)
            inter = make_dense(cfg.ffn_size, "inter_w")(h)
            # must match the training model's GELU variant bit-for-bit or
            # injected params serve shifted logits (GPT-2 trains with the
            # tanh approximation; BERT with exact GELU)
            inter = nn.gelu(inter, approximate=cfg.gelu_approximate)
            return make_dense(E, "output_w")(inter)

        if cfg.pre_layer_norm:
            x = x + attn(nn.LayerNorm(**ln_kw, name="attn_nw")(x))
            x = x + ffn(nn.LayerNorm(**ln_kw, name="norm_w")(x))
        else:
            x = nn.LayerNorm(**ln_kw, name="attn_nw")(x + attn(x))
            x = nn.LayerNorm(**ln_kw, name="norm_w")(x + ffn(x))
        return x

    def _decode_step_fused(self, x, B, E, H, D, dt):
        """Single-token serving fast path (int8 weights + int8 KV cache):
        FOUR Pallas kernels per layer — LN+qkv (decode.ln_qkv_int8),
        per-head KV quant (decode.kv_quant_int8; the cache append itself
        stays an XLA dynamic_update_slice), head-batched cached attention
        (decode.decode_attention_int8), and proj+residual+LN+FFN+residual
        (decode.out_ffn_int8) — instead of ~35 XLA ops. Param trees and
        cache variables are IDENTICAL to the general path, so the same
        weights serve both and the prompt pass fills the cache through
        the general path. Measured 5.2 -> 3.82 ms/token (262 tok/s) at
        GPT-2-large b1/ctx2048 on v5e (docs/perf_tuning.md r4b)."""
        from deepspeed_tpu.ops.pallas.decode import (
            ln_qkv_int8, kv_quant_int8, decode_attention_int8,
            out_ffn_int8)
        cfg = self.config
        L = cfg.max_out_tokens
        ln1 = _LNParams(E, cfg.param_dtype, name="attn_nw")()
        ln2 = _LNParams(E, cfg.param_dtype, name="norm_w")()
        kqkv, sqkv, bqkv = QuantDense(
            3 * E, groups=1, dtype=dt, param_dtype=cfg.param_dtype,
            name="attn_qkvw").raw(E)
        kp, sp, bp = QuantDense(
            E, groups=1, dtype=dt, param_dtype=cfg.param_dtype,
            name="attn_ow").raw(E)
        k1, s1, b1 = QuantDense(
            cfg.ffn_size, groups=1, dtype=dt, param_dtype=cfg.param_dtype,
            name="inter_w").raw(E)
        k2, s2, b2 = QuantDense(
            E, groups=1, dtype=dt, param_dtype=cfg.param_dtype,
            name="output_w").raw(cfg.ffn_size)
        ck = self.variable("cache", "cached_key_q8",
                           jnp.zeros, (B, H, L, D), jnp.int8)
        cv = self.variable("cache", "cached_value_q8",
                           jnp.zeros, (B, H, L, D), jnp.int8)
        ks = self.variable("cache", "key_scale",
                           jnp.zeros, (B, H, L), jnp.float32)
        vs = self.variable("cache", "value_scale",
                           jnp.zeros, (B, H, L), jnp.float32)
        idx = self.variable("cache", "cache_index",
                            lambda: jnp.zeros((), jnp.int32))
        start = idx.value
        x2 = x.reshape(B, E)
        # overflow: clamped cache writes would silently serve stale
        # context — poison like the general path does
        x2 = jnp.where(start >= L, jnp.float32(jnp.nan).astype(x2.dtype),
                       x2)
        qkv = ln_qkv_int8(x2, ln1[0], ln1[1], kqkv, sqkv.reshape(()),
                          bqkv, eps=cfg.layer_norm_eps)
        q = qkv[:, :E]
        k3 = qkv[:, E:2 * E].reshape(B, H, D)
        v3 = qkv[:, 2 * E:].reshape(B, H, D)
        kq8, ksc, vq8, vsc = kv_quant_int8(k3, v3)
        dus = jax.lax.dynamic_update_slice
        ck.value = dus(ck.value, kq8[:, :, None, :], (0, 0, start, 0))
        cv.value = dus(cv.value, vq8[:, :, None, :], (0, 0, start, 0))
        ks.value = dus(ks.value, ksc.reshape(B, H, 1), (0, 0, start))
        vs.value = dus(vs.value, vsc.reshape(B, H, 1), (0, 0, start))
        idx.value = start + 1
        qh = q.reshape(B, 1, H, D).transpose(0, 2, 1, 3)
        ctx = decode_attention_int8(
            qh, ck.value, ks.value, cv.value, vs.value, start,
            scale=1.0 / np.sqrt(D))
        ctx2 = ctx.transpose(0, 2, 1, 3).reshape(B, E)
        y = out_ffn_int8(
            ctx2, x2, kp, sp.reshape(()), bp, ln2[0], ln2[1],
            k1, s1.reshape(()), b1, k2, s2.reshape(()), b2,
            act="gelu_tanh" if cfg.gelu_approximate else "gelu",
            eps=cfg.layer_norm_eps)
        return y.reshape(B, 1, E)

    def _cache_int8(self, kh, vh, B, L, H, D):
        """int8 KV cache write (kv_cache_bits=8) in the head-major
        [B, H, L, D] layout: returns codes + scales; the caller keeps the
        contractions in the int8 domain so the full-precision cache is
        never re-materialized (the scales are constant along D and factor
        out of both contractions)."""
        S = kh.shape[2]

        def quant(t):
            scale = jnp.max(jnp.abs(t.astype(jnp.float32)), axis=-1) / 127.0
            scale = jnp.maximum(scale, 1e-12)
            codes = jnp.clip(jnp.round(t.astype(jnp.float32)
                                       / scale[..., None]), -127, 127)
            return codes.astype(jnp.int8), scale

        ck = self.variable("cache", "cached_key_q8",
                           jnp.zeros, (B, H, L, D), jnp.int8)
        cv = self.variable("cache", "cached_value_q8",
                           jnp.zeros, (B, H, L, D), jnp.int8)
        ks = self.variable("cache", "key_scale",
                           jnp.zeros, (B, H, L), jnp.float32)
        vs = self.variable("cache", "value_scale",
                           jnp.zeros, (B, H, L), jnp.float32)
        idx = self.variable("cache", "cache_index",
                            lambda: jnp.zeros((), jnp.int32))
        start = idx.value
        kq, ksc = quant(kh)
        vq, vsc = quant(vh)
        ck.value = jax.lax.dynamic_update_slice(ck.value, kq,
                                                (0, 0, start, 0))
        cv.value = jax.lax.dynamic_update_slice(cv.value, vq,
                                                (0, 0, start, 0))
        ks.value = jax.lax.dynamic_update_slice(ks.value, ksc,
                                                (0, 0, start))
        vs.value = jax.lax.dynamic_update_slice(vs.value, vsc,
                                                (0, 0, start))
        idx.value = start + S
        return ck.value, cv.value, ks.value, vs.value, start

    def _attend(self, q, k, v, attention_mask):
        """[B,S,H,D] q/k/v → [B,S,H,D] context; routes through the KV cache
        when one exists (decoder use)."""
        cfg = self.config
        B, S, H, D = q.shape
        scale = 1.0 / np.sqrt(D)

        use_cache = cfg.triangular_masking and \
            (self.has_variable("cache", "cached_key") or
             self.has_variable("cache", "cached_key_q8") or
             self.is_mutable_collection("cache"))
        if use_cache:
            # HEAD-MAJOR cache layout [B, H, L, D]: the decode contraction
            # becomes a (B,H)-batched dot_general with L on the lane axis —
            # measured 0.57 ms/token at the read bound for 36 layers where
            # the [B, L, H, D] einsum form cost 2.13 ms (r4 ablation,
            # docs/perf_tuning.md)
            L = cfg.max_out_tokens
            kh = k.transpose(0, 2, 1, 3)
            vh = v.transpose(0, 2, 1, 3)
            kv_scales = None
            if cfg.kv_cache_bits == 8:
                k_all, v_all, k_scale, v_scale, start = self._cache_int8(
                    kh, vh, B, L, H, D)
                kv_scales = (k_scale, v_scale)
            else:
                ck = self.variable("cache", "cached_key",
                                   jnp.zeros, (B, H, L, D), k.dtype)
                cv = self.variable("cache", "cached_value",
                                   jnp.zeros, (B, H, L, D), v.dtype)
                idx = self.variable("cache", "cache_index",
                                    lambda: jnp.zeros((), jnp.int32))
                start = idx.value
                ck.value = jax.lax.dynamic_update_slice(
                    ck.value, kh, (0, 0, start, 0))
                cv.value = jax.lax.dynamic_update_slice(
                    cv.value, vh, (0, 0, start, 0))
                idx.value = start + S
                k_all, v_all = ck.value, cv.value
            # overflow guard: dynamic_update_slice clamps the write offset,
            # which would silently return stale context past max_out_tokens.
            # Shapes are static under jit so we can't raise — poison the
            # output with NaN instead so overflow is loud and detectable.
            overflow = (start + S) > L
            q = jnp.where(overflow, jnp.float32(jnp.nan).astype(q.dtype), q)
            if kv_scales is not None and S == 1 \
                    and attention_mask is None and cfg.mp_size == 1 \
                    and B <= 8:
                # mp_size > 1 stays on the XLA contractions: the Pallas
                # kernel is an opaque custom call GSPMD cannot shard, so
                # under TP it would all-gather the head-sharded caches
                # to every shard each token. Large batches also stay on
                # XLA: the kernel grid is (B, L/block) and grid steps
                # cost ~1 us each, so per-token overhead scales with B
                # while the XLA batched dots amortize it
                # fused decode-attention kernel: scores + masked online
                # softmax + context in ONE program over the int8 cache
                # (compute past `pos` is skipped; the block DMAs still
                # stream all L rows — cache reads are ~6 us/layer here)
                from deepspeed_tpu.ops.pallas.decode import (
                    decode_attention_int8)
                k_scale, v_scale = kv_scales
                ctx = decode_attention_int8(
                    q.transpose(0, 2, 1, 3), k_all, k_scale, v_all,
                    v_scale, start, scale=scale)
                return ctx.transpose(0, 2, 1, 3)           # (B,1,H,D)
            # position j visible to query i (absolute i = start + i_local)
            q_pos = start + jnp.arange(S)[:, None]
            k_pos = jnp.arange(L)[None, :]
            visible = k_pos <= q_pos                       # [S, L]
            qh = q.transpose(0, 2, 1, 3)                   # (B,H,S,D)
            dn_qk = (((3,), (3,)), ((0, 1), (0, 1)))       # contract D
            if kv_scales is not None:
                # int8 domain: scales are constant along D, so they factor
                # out — the contraction reads 1 byte/element and the full-
                # precision cache is never materialized
                k_scale, v_scale = kv_scales
                scores = jax.lax.dot_general(
                    qh, k_all.astype(q.dtype), dn_qk).astype(jnp.float32)
                scores = scores * k_scale[:, :, None, :] * scale
            else:
                scores = jax.lax.dot_general(
                    qh, k_all, dn_qk).astype(jnp.float32) * scale
            scores = jnp.where(visible[None, None], scores,
                               jnp.float32(-1e30))
            if attention_mask is not None:
                scores = scores + _as_bias(attention_mask, L)
            probs = jax.nn.softmax(scores, axis=-1)
            dn_pv = (((3,), (2,)), ((0, 1), (0, 1)))       # contract L
            if kv_scales is not None:
                probs = probs * v_scale[:, :, None, :]
                ctx = jax.lax.dot_general(
                    probs.astype(q.dtype), v_all.astype(q.dtype), dn_pv)
            else:
                ctx = jax.lax.dot_general(probs.astype(q.dtype), v_all,
                                          dn_pv)
            return ctx.transpose(0, 2, 1, 3)               # (B,S,H,D)

        # no cache: route through the shared attention dispatch so encoder
        # inference gets the Pallas flash kernel on TPU when unmasked
        from deepspeed_tpu.ops.attention import dot_product_attention
        bias = _as_bias(attention_mask, S) if attention_mask is not None \
            else None
        ctx = dot_product_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=cfg.triangular_masking,
            bias=bias, scale=scale)
        return ctx.transpose(0, 2, 1, 3)


def _as_bias(attention_mask, L):
    """[B,S_k] validity mask or [B,1,1,S_k]/[B,1,S_q,S_k] additive bias →
    additive fp32 bias padded/cropped to key length L."""
    m = jnp.asarray(attention_mask)
    if m.ndim == 2:
        m = (1.0 - (m > 0.5).astype(jnp.float32))[:, None, None, :] * -1e30
    elif m.ndim == 3:
        m = m[:, None].astype(jnp.float32)
    else:
        m = m.astype(jnp.float32)
    k_len = m.shape[-1]
    if k_len < L:
        m = jnp.pad(m, [(0, 0)] * (m.ndim - 1) + [(0, L - k_len)])
    elif k_len > L:
        m = m[..., :L]
    return m


def quantize_inference_params(params, bits=8, groups=1):
    """Fused-layer params → int8-storage params for `quantize_bits` serving:
    every `kernel` under the four weight names becomes `kernel_q` (int8,
    same shape) + `kernel_scale` ([groups, 1] fp32, per leading layer-stack
    entry when the tree is scan-stacked). Biases and layernorms stay fp32.
    Symmetric per-group quantization (ops.quantizer)."""
    assert bits == 8, "int8 storage only"
    weight_names = ("attn_qkvw", "attn_ow", "inter_w", "output_w")

    def convert(tree):
        if not isinstance(tree, dict):
            return tree
        out = {}
        for key, sub in tree.items():
            if key in weight_names and isinstance(sub, dict) \
                    and "kernel" in sub:
                w = jnp.asarray(sub["kernel"])
                if w.ndim == 3:      # scan-stacked [L, in, out]
                    L = w.shape[0]
                    flat = w.reshape(L * groups, -1).astype(jnp.float32)
                    amax = jnp.max(jnp.abs(flat), axis=1, keepdims=True)
                    scale = jnp.maximum(amax / 127.0, 1e-12)
                    q = jnp.clip(jnp.round(flat / scale), -128, 127)
                    out[key] = {
                        "kernel_q": q.astype(jnp.int8).reshape(w.shape),
                        "kernel_scale": scale.reshape(L, groups, 1),
                        "bias": sub["bias"],
                    }
                else:
                    flat = w.reshape(groups, -1).astype(jnp.float32)
                    amax = jnp.max(jnp.abs(flat), axis=1, keepdims=True)
                    scale = jnp.maximum(amax / 127.0, 1e-12)
                    q = jnp.clip(jnp.round(flat / scale), -128, 127)
                    out[key] = {
                        "kernel_q": q.astype(jnp.int8).reshape(w.shape),
                        "kernel_scale": scale,
                        "bias": sub["bias"],
                    }
            else:
                out[key] = convert(sub)
        return out

    return convert(params)


def inference_tp_specs(params):
    """PartitionSpec tree for TP-sharded inference over the 'model' mesh axis
    (the mp_size sharding module_inject applies, replace_module.py:16-17):
    qkv + intermediate column-parallel, output projections row-parallel,
    everything else replicated."""
    def leaf_spec(path, leaf):
        names = [str(getattr(k, "key", k)) for k in path]
        shape = getattr(leaf, "shape", ())
        col = any(n in ("attn_qkvw", "inter_w") for n in names)
        row = any(n in ("attn_ow", "output_w") for n in names)
        last = names[-1] if names else ""
        if col and last == "kernel" and len(shape) == 2:
            return P(None, MODEL_AXIS)
        if col and last == "bias" and len(shape) == 1:
            return P(MODEL_AXIS)
        if row and last == "kernel" and len(shape) == 2:
            return P(MODEL_AXIS, None)
        return P()
    return jax.tree_util.tree_map_with_path(leaf_spec, params)
