from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
from deepspeed_tpu.ops.pallas.fused_collective import (
    CollectiveMatmulConfig, all_gather_matmul, collective_matmul,
    matmul_reduce_scatter)
