"""Fused decode (S=1) kernels for int8- and bf16-weight serving.

The scan-decode step at GPT-2-large b1/ctx2048 spends ~1.6 ms/token on
weight+cache reads but ~5.2 ms/token wall — the rest is per-op fixed cost
across ~30 small XLA ops per layer (docs/perf_tuning.md r4 ablation).
These kernels collapse the big ones:

- ``matvec_int8``: y = act(x @ dequant(Wq)·s + b) — one kernel per
  projection instead of dequant+dot+bias(+act) chains. The int8 codes are
  cast to the compute dtype INSIDE the kernel (VMEM), so HBM traffic is
  the 1-byte codes — the XLA path materializes a bf16 weight copy for
  some shapes, which doubles effective weight read.
- ``decode_attention_int8``: one (B,H)-grid kernel for the S=1 cached-
  attention read: scores over the int8 K cache, masked online softmax,
  context over the int8 V cache — replaces the dequant/dot/mask/softmax/
  dot chain (~10 ops).

Reference role: csrc/transformer/inference/csrc/pt_binding.cpp ships
fused decode GEMM+softmax CUDA kernels for exactly this regime.

All kernels are bandwidth-bound at decode shapes; grids are sized so each
program's working set fits VMEM with double-buffered DMA.

The weight-consuming kernels are dtype-agnostic: the in-kernel
``astype(compute)`` that dequantizes int8 codes is an identity cast for
bf16 stacks, and the per-tensor scale multiply is harmless at 1.0 — so
the SAME kernels serve plain bf16 weights (the reference's fp16-first
inference kernels, csrc/transformer/inference/csrc/pt_binding.cpp) by
passing the raw kernel stacks with scale=1. Only the cached-attention
kernel needs a real variant (``decode_attention_fp_stacked``): its int8
form reads per-(b,h,pos) scale ARRAYS, which have no fp counterpart.
Block budgets are byte-based, so bf16 tiles automatically halve their
column counts to stay inside VMEM.
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret_default():
    from deepspeed_tpu.utils.platform import is_tpu_backend
    return not is_tpu_backend()


def _pick_block(n, budget_cols):
    """Largest lane-aligned (multiple-of-128) divisor of ``n`` whose
    column count stays within the VMEM tile budget; falls back to ``n``
    itself for small/irregular shapes (one whole-array block)."""
    cap = min(n, max(128, budget_cols))
    for cand in range(cap - cap % 128, 0, -128):
        if n % cand == 0:
            return cand
    return n


# ------------------------------------------------------------ int8 matvec

def _matvec_kernel(x_ref, w_ref, s_ref, b_ref, o_ref, *, act, out_dtype):
    x = x_ref[...]                              # [B, E] compute dtype
    w = w_ref[...].astype(x.dtype)              # [E, bn] int8 -> compute
    y = jax.lax.dot(x, w, preferred_element_type=jnp.float32)
    y = y * s_ref[0, 0] + b_ref[...].astype(jnp.float32)
    if act == "gelu_tanh":
        y = jax.nn.gelu(y, approximate=True)
    elif act == "gelu":
        y = jax.nn.gelu(y, approximate=False)
    o_ref[...] = y.astype(out_dtype)


def matvec_int8(x, wq, scale, bias, act=None, block_n=None, interpret=None):
    """x [B, E] @ int8 Wq [E, N] · scale (+ bias, + act) → [B, N].

    ``scale`` is the per-tensor (quantize_groups=1) symmetric scale; the
    kernel applies it to the fp32 accumulator, so dequantized weights
    never exist outside VMEM."""
    if interpret is None:
        interpret = _interpret_default()
    B, E = x.shape
    E2, N = wq.shape
    assert E == E2, (x.shape, wq.shape)
    if block_n is None:
        block_n = _pick_block(
            N, budget_cols=(1 << 21) // max(E * wq.dtype.itemsize, 1))
    assert N % block_n == 0, (N, block_n)
    scale = jnp.asarray(scale, jnp.float32).reshape(1, 1)
    bias2 = jnp.asarray(bias).reshape(1, N)     # 2-D: Mosaic tiles 1-D
    out = pl.pallas_call(                       # operands at 1024
        functools.partial(_matvec_kernel, act=act, out_dtype=x.dtype),
        grid=(N // block_n,),
        in_specs=[
            pl.BlockSpec((B, E), lambda j: (0, 0)),
            pl.BlockSpec((E, block_n), lambda j: (0, j)),
            pl.BlockSpec((1, 1), lambda j: (0, 0)),
            pl.BlockSpec((1, block_n), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((B, block_n), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((B, N), x.dtype),
        interpret=interpret,
    )(x, wq, scale, bias2)
    return out


# ------------------------------------------- fused int8-cache decode attn

def _decode_attn_kernel(pos_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref,
                        o_ref, m_ref, l_ref, acc_ref, *, scale, block_l,
                        seq_len):
    """grid=(B, L/block_l): ALL heads of one batch element per program —
    a per-(b,h) grid pays ~4 us of program overhead x H x layers, which
    measured 3.0 of 4.7 ms/token at GPT-2-large (H=20, 36 layers). Head-
    batched MXU dot_generals give [H, 1, bl] scores LANE-major, matching
    the [B, H, 1, L] scale layout (lane-major scales — a trailing-1
    [B,H,L,1] layout pads every scale block to 128 lanes and made DMA the
    bottleneck). Softmax state is carried across L-blocks in scratch with
    online rescaling; blocks past ``pos`` skip compute."""
    lb = pl.program_id(1)
    nb = seq_len // block_l
    pos = pos_ref[0]

    @pl.when(lb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref[...], -1e30)
        l_ref[...] = jnp.zeros_like(l_ref[...])
        acc_ref[...] = jnp.zeros_like(acc_ref[...])

    base = lb * block_l

    @pl.when(base <= pos)
    def _block():
        q = q_ref[0]                                # [H, 1, D]
        k = k_ref[0].astype(q.dtype)                # [H, bl, D]
        s = jax.lax.dot_general(                    # [H, 1, bl]
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        s = s * ks_ref[0] * scale                   # ks [H, 1, bl]
        k_pos = base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(k_pos <= pos, s, -1e30)
        m_acc = m_ref[...]                          # [H, 1, 1]
        m_new = jnp.maximum(m_acc, jnp.max(s, axis=2, keepdims=True))
        m_ref[...] = m_new
        alpha = jnp.exp(m_acc - m_new)              # [H, 1, 1]
        p = jnp.exp(s - m_new)                      # [H, 1, bl]
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=2,
                                                  keepdims=True)
        pv = (p * vs_ref[0]).astype(q.dtype)        # [H, 1, bl]
        v = v_ref[0].astype(q.dtype)                # [H, bl, D]
        ctx = jax.lax.dot_general(                  # [H, 1, D]
            pv, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + ctx

    @pl.when(lb == nb - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[...], 1e-30)     # [H, 1, 1]
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def decode_attention_int8(q, k_codes, k_scale, v_codes, v_scale, pos,
                          scale=None, block_l=None, interpret=None):
    """S=1 cached attention over the int8 head-major cache.

    q [B, H, 1, D]; k_codes/v_codes [B, H, L, D] int8;
    k_scale/v_scale [B, H, L] fp32; pos: scalar int32 — index of the
    newest valid cache row (queries attend to positions <= pos).
    Returns [B, H, 1, D] in q.dtype."""
    if interpret is None:
        interpret = _interpret_default()
    B, H, S, D = q.shape
    assert S == 1, "decode kernel is S=1 only"
    L = k_codes.shape[2]
    scale = float(scale) if scale is not None else 1.0 / float(np.sqrt(D))
    if block_l is None:
        block_l = min(L, 512)
        while L % block_l:
            block_l //= 2
    assert L % block_l == 0, (L, block_l)
    ks4 = k_scale.reshape(B, H, 1, L)
    vs4 = v_scale.reshape(B, H, 1, L)
    pos = jnp.asarray(pos, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, L // block_l),
        in_specs=[
            pl.BlockSpec((1, H, 1, D), lambda b, lb, *_: (b, 0, 0, 0)),
            pl.BlockSpec((1, H, block_l, D),
                         lambda b, lb, *_: (b, 0, lb, 0)),
            pl.BlockSpec((1, H, 1, block_l),
                         lambda b, lb, *_: (b, 0, 0, lb)),
            pl.BlockSpec((1, H, block_l, D),
                         lambda b, lb, *_: (b, 0, lb, 0)),
            pl.BlockSpec((1, H, 1, block_l),
                         lambda b, lb, *_: (b, 0, 0, lb)),
        ],
        out_specs=pl.BlockSpec((1, H, 1, D),
                               lambda b, lb, *_: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 1, 1), jnp.float32),
            pltpu.VMEM((H, 1, 1), jnp.float32),
            pltpu.VMEM((H, 1, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_attn_kernel, scale=scale,
                          block_l=block_l, seq_len=L),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, 1, D), q.dtype),
        interpret=interpret,
    )(pos, q, k_codes, ks4, v_codes, vs4)
    return out


def _ln(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return y * w.astype(jnp.float32) + b.astype(jnp.float32)


def _ln_qkv_kernel(x_ref, lnw_ref, lnb_ref, w_ref, s_ref, b_ref,
                   o_ref, u_ref, *, eps):
    """grid over column tiles of the packed qkv projection: j=0 computes
    LN once into scratch; every j projects one tile. No in-kernel
    reshapes (Mosaic cannot shape-cast across lanes)."""
    j = pl.program_id(0)
    dt = x_ref.dtype

    @pl.when(j == 0)
    def _ln_pass():
        u_ref[...] = _ln(x_ref[...], lnw_ref[...], lnb_ref[...],
                         eps).astype(dt)

    u = u_ref[...]                                  # [B, E]
    w = w_ref[...].astype(dt)                       # [E, bn]
    y = jax.lax.dot(u, w, preferred_element_type=jnp.float32)
    o_ref[...] = (y * s_ref[0, 0]
                  + b_ref[...].astype(jnp.float32)).astype(dt)


def ln_qkv_int8(x, ln_w, ln_b, wq, s, b, eps=1e-5, block_n=None,
                interpret=None):
    """Fused LayerNorm + int8 qkv projection: x [B, E] -> qkv [B, 3E]
    (one kernel instead of LN + dequant + matmul + bias chains)."""
    if interpret is None:
        interpret = _interpret_default()
    B, E = x.shape
    N = 3 * E
    assert wq.shape == (E, N)
    if block_n is None:
        block_n = _pick_block(
            N, budget_cols=(1 << 23) // max(E * wq.dtype.itemsize, 1))
    assert N % block_n == 0
    s = jnp.asarray(s, jnp.float32).reshape(1, 1)
    out = pl.pallas_call(
        functools.partial(_ln_qkv_kernel, eps=eps),
        grid=(N // block_n,),
        in_specs=[
            pl.BlockSpec((B, E), lambda j: (0, 0)),
            pl.BlockSpec((1, E), lambda j: (0, 0)),
            pl.BlockSpec((1, E), lambda j: (0, 0)),
            pl.BlockSpec((E, block_n), lambda j: (0, j)),
            pl.BlockSpec((1, 1), lambda j: (0, 0)),
            pl.BlockSpec((1, block_n), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((B, block_n), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((B, N), x.dtype),
        scratch_shapes=[pltpu.VMEM((B, E), x.dtype)],
        interpret=interpret,
    )(x, ln_w.reshape(1, E), ln_b.reshape(1, E), wq, s,
      jnp.asarray(b).reshape(1, N))
    return out


def _kv_quant_kernel(k_ref, v_ref, kq_ref, ks_ref, vq_ref, vs_ref):
    """Per-head symmetric int8 quant of the new K/V rows ([B, H, D],
    head axis on sublanes — no reshape needed). The cache append itself
    stays an XLA dynamic_update_slice: Mosaic cannot DMA a single row of
    a sublane-tiled cache axis (slices on tiled dims must be 8-aligned),
    and XLA updates the donated cache in place anyway."""
    def quant(t_ref, q_ref, s_ref):
        t = t_ref[...].astype(jnp.float32)          # [B, H, D]
        amax = jnp.max(jnp.abs(t), axis=-1, keepdims=True)
        sc = jnp.maximum(amax / 127.0, 1e-12)       # [B, H, 1]
        q_ref[...] = jnp.clip(jnp.round(t / sc), -127,
                              127).astype(jnp.int8)
        s_ref[...] = sc.astype(jnp.float32)

    quant(k_ref, kq_ref, ks_ref)
    quant(v_ref, vq_ref, vs_ref)


def kv_quant_int8(k, v, interpret=None):
    """Quantize new K/V rows per head in one kernel. k/v: [B, H, D] ->
    (k_codes int8 [B,H,D], k_scale fp32 [B,H,1], v_codes, v_scale)."""
    if interpret is None:
        interpret = _interpret_default()
    B, H, D = k.shape
    spec = pl.BlockSpec((B, H, D), lambda: (0, 0, 0))
    sspec = pl.BlockSpec((B, H, 1), lambda: (0, 0, 0))
    kq, ks, vq, vs = pl.pallas_call(
        _kv_quant_kernel,
        in_specs=[spec, spec],
        out_specs=[spec, sspec, spec, sspec],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, D), jnp.int8),
            jax.ShapeDtypeStruct((B, H, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, H, D), jnp.int8),
            jax.ShapeDtypeStruct((B, H, 1), jnp.float32),
        ],
        interpret=interpret,
    )(k, v)
    return kq, ks, vq, vs


def _out_ffn_kernel(ctx_ref, x_ref, wp_ref, lnw_ref, lnb_ref, sc_ref,
                    bp_ref, w1_ref, b1_ref, w2_ref, b2_ref, o_ref,
                    x1_ref, u_ref, acc_ref, *, eps, act, n_tiles):
    """grid=(n_tiles,) over FFN columns: j=0 additionally runs the
    attention output projection + residual + LN; every j accumulates one
    FFN tile; the last j adds the second residual and writes out."""
    j = pl.program_id(0)
    dt = ctx_ref.dtype

    @pl.when(j == 0)
    def _proj():
        ctx = ctx_ref[...]
        wp = wp_ref[...].astype(dt)
        t = jax.lax.dot(ctx, wp, preferred_element_type=jnp.float32)
        t = t * sc_ref[0, 0] + bp_ref[...].astype(jnp.float32)
        x1 = x_ref[...].astype(jnp.float32) + t
        x1_ref[...] = x1.astype(dt)
        u_ref[...] = _ln(x1, lnw_ref[...], lnb_ref[...], eps).astype(dt)
        acc_ref[...] = jnp.zeros_like(acc_ref[...])

    u = u_ref[...]
    w1 = w1_ref[...].astype(dt)
    h = jax.lax.dot(u, w1, preferred_element_type=jnp.float32)
    h = h * sc_ref[0, 1] + b1_ref[...].astype(jnp.float32)
    if act == "gelu_tanh":
        h = jax.nn.gelu(h, approximate=True)
    else:
        h = jax.nn.gelu(h, approximate=False)
    w2 = w2_ref[...].astype(dt)
    acc_ref[...] += jax.lax.dot(h.astype(dt), w2,
                                preferred_element_type=jnp.float32)

    @pl.when(j == n_tiles - 1)
    def _finish():
        o_ref[...] = (x1_ref[...].astype(jnp.float32)
                      + acc_ref[...] * sc_ref[0, 2]
                      + b2_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def out_ffn_int8(ctx, x, wp, sp, bp, ln_w, ln_b, w1, s1, b1, w2, s2, b2,
                 act="gelu_tanh", eps=1e-5, block_f=None, interpret=None):
    """Fused decode output path: x + proj(ctx), then LN and the whole
    FFN with a second residual — one kernel instead of ~12 ops. All
    weights int8 with per-tensor scales."""
    if interpret is None:
        interpret = _interpret_default()
    B, E = ctx.shape
    Ew, F = w1.shape
    assert Ew == E and w2.shape == (F, E) and wp.shape == (E, E)
    if block_f is None:
        block_f = _pick_block(
            F, budget_cols=(1 << 21) // max(E * w1.dtype.itemsize, 1))
    assert F % block_f == 0, (F, block_f)
    n_tiles = F // block_f
    scales = jnp.stack([jnp.asarray(v, jnp.float32).reshape(())
                        for v in (sp, s1, s2)]).reshape(1, 3)
    out = pl.pallas_call(
        functools.partial(_out_ffn_kernel, eps=eps, act=act,
                          n_tiles=n_tiles),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((B, E), lambda j: (0, 0)),
            pl.BlockSpec((B, E), lambda j: (0, 0)),
            pl.BlockSpec((E, E), lambda j: (0, 0)),
            pl.BlockSpec((1, E), lambda j: (0, 0)),
            pl.BlockSpec((1, E), lambda j: (0, 0)),
            pl.BlockSpec((1, 3), lambda j: (0, 0)),
            pl.BlockSpec((1, E), lambda j: (0, 0)),
            pl.BlockSpec((E, block_f), lambda j: (0, j)),
            pl.BlockSpec((1, block_f), lambda j: (0, j)),
            pl.BlockSpec((block_f, E), lambda j: (j, 0)),
            pl.BlockSpec((1, E), lambda j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((B, E), lambda j: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, E), ctx.dtype),
        scratch_shapes=[
            pltpu.VMEM((B, E), ctx.dtype),
            pltpu.VMEM((B, E), ctx.dtype),
            pltpu.VMEM((B, E), jnp.float32),
        ],
        interpret=interpret,
    )(ctx, x, wp, ln_w.reshape(1, E), ln_b.reshape(1, E), scales,
      jnp.asarray(bp).reshape(1, E), w1, jnp.asarray(b1).reshape(1, F),
      w2, jnp.asarray(b2).reshape(1, E))
    return out


# ----------------------------- stacked-weight serving kernels (no slices)
#
# flax's nn.scan over layers SLICES every stacked array before the layer
# body sees it: per tick per layer that is ~24 us of weight-slice copies
# plus ~37 us of cache slice/unslice (device trace, b1/ctx2048 int8 —
# ~60% of the token). These variants take the FULL [L, ...] stacks and
# index the layer via scalar-prefetched block index maps, so the kernels
# DMA exactly the tiles they need straight from the stacked HBM arrays.
# The manual serving loop (models/gpt2_inference._fast_decode_scan) scans
# layer INDICES and keeps the caches whole, updating one row in place.
#
# EVERY per-layer parameter is stacked — LN scales/biases and projection
# biases ride [Lyr, ...] operands with layer-indexed block maps, and the
# per-tensor weight scales ride SMEM as scalar-prefetch vectors indexed
# at the layer id in-kernel. The r5 device trace showed the alternative
# (per-layer xs through lax.scan) costs ~15-20 us of slice/copy fixed
# overhead PER ARRAY PER LAYER on this target — ~2.5 ms/tick at 13 xs.

def _rms(x, w, eps):
    xf = x.astype(jnp.float32)
    n = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True)
                           + eps)
    return n * w.astype(jnp.float32)


def ln_qkv_int8_stacked(x, ln_w, ln_b, wq_stack, s, b, layer, eps=1e-5,
                        block_n=None, interpret=None, norm="layer"):
    """Fused norm + packed qkv projection over stacked weights: wq_stack
    [L, E, N] (int8 or bf16) indexed at ``layer`` by the block index map
    — no layer-slice copy. ln_w/ln_b [L, 1, E], b [L, 1, N] (the middle
    unit axis makes the per-layer block (1, 1, cols), which the TPU
    block-shape rules accept; serving loops pre-reshape ONCE outside the
    layer scan — 2-D [L, cols] is accepted here but reshapes per call, a
    layout copy); s [L] fp32 per-tensor scales (SMEM-prefetched, indexed
    in-kernel — pass ones for bf16 stacks).

    ``norm='rms'`` selects RMSNorm (LLaMA): ``ln_b`` is unused and the
    projection is bias-free — pass ``None`` for both. N may be any
    lane-aligned packed width (GPT-2 packs 3E; LLaMA packs
    (H + 2*Hkv) * head_dim at reduced-KV widths)."""
    if interpret is None:
        interpret = _interpret_default()
    B, E = x.shape
    Lyr, Ew, N = wq_stack.shape
    assert Ew == E
    use_bias = norm != "rms"
    ln_w = ln_w.reshape(Lyr, 1, E)
    if block_n is None:
        # 7 MiB per weight block: 2x (double-buffered DMA) + the x/u
        # scratch must stay under the 16 MiB scoped-VMEM limit — 8 MiB
        # blocks hit it exactly and overflow by the scratch bytes at
        # LLaMA-7B widths (E=4096, N=12288)
        block_n = _pick_block(
            N, budget_cols=(7 << 20) // max(E * wq_stack.dtype.itemsize, 1))
    assert N % block_n == 0
    s = jnp.asarray(s, jnp.float32).reshape(Lyr)
    layer = jnp.asarray(layer, jnp.int32).reshape(1)
    in_specs = [
        pl.BlockSpec((B, E), lambda j, l, s: (0, 0)),
        pl.BlockSpec((1, 1, E), lambda j, l, s: (l[0], 0, 0)),
    ]
    operands = [x, ln_w]
    if use_bias:
        in_specs.append(pl.BlockSpec((1, 1, E),
                                     lambda j, l, s: (l[0], 0, 0)))
        operands.append(ln_b.reshape(Lyr, 1, E))
    in_specs.append(pl.BlockSpec((1, E, block_n),
                                 lambda j, l, s: (l[0], 0, j)))
    operands.append(wq_stack)
    if use_bias:
        in_specs.append(pl.BlockSpec((1, 1, block_n),
                                     lambda j, l, s: (l[0], 0, j)))
        operands.append(jnp.asarray(b).reshape(Lyr, 1, N))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(N // block_n,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((B, block_n), lambda j, l, s: (0, j)),
        scratch_shapes=[pltpu.VMEM((B, E), x.dtype)],
    )
    out = pl.pallas_call(
        functools.partial(_ln_qkv_stacked_kernel, eps=eps, norm=norm),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, N), x.dtype),
        interpret=interpret,
    )(layer, s, *operands)
    return out


def _ln_qkv_stacked_kernel(l_ref, s_ref, x_ref, lnw_ref, *rest, eps,
                           norm):
    if norm == "rms":
        w_ref, o_ref, u_ref = rest
        lnb_ref = b_ref = None
    else:
        lnb_ref, w_ref, b_ref, o_ref, u_ref = rest
    j = pl.program_id(0)
    dt = x_ref.dtype

    @pl.when(j == 0)
    def _norm_pass():
        if norm == "rms":
            u_ref[...] = _rms(x_ref[...], lnw_ref[0], eps).astype(dt)
        else:
            u_ref[...] = _ln(x_ref[...], lnw_ref[0], lnb_ref[0],
                             eps).astype(dt)

    u = u_ref[...]
    w = w_ref[0].astype(dt)                        # [E, bn]
    y = jax.lax.dot(u, w, preferred_element_type=jnp.float32)
    y = y * s_ref[l_ref[0]]
    if b_ref is not None:
        y = y + b_ref[0].astype(jnp.float32)
    o_ref[...] = y.astype(dt)


def matvec_int8_stacked(x, w_stack, s, layer, block_n=None,
                        interpret=None):
    """x [B, E] @ stacked (int8 or bf16) w [L, E, N] · s[layer] → [B, N],
    bias-free, layer-indexed block maps — the large-E o_proj path where
    fusing the whole [E, E] matrix into the ffn kernel's first grid step
    would blow scoped VMEM (LLaMA-7B: 16.7 MB at E=4096)."""
    if interpret is None:
        interpret = _interpret_default()
    B, E = x.shape
    Lyr, Ew, N = w_stack.shape
    assert Ew == E
    if block_n is None:
        block_n = _pick_block(
            N, budget_cols=(7 << 20) // max(E * w_stack.dtype.itemsize, 1))
    assert N % block_n == 0
    s = jnp.asarray(s, jnp.float32).reshape(Lyr)
    layer = jnp.asarray(layer, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(N // block_n,),
        in_specs=[
            pl.BlockSpec((B, E), lambda j, l, s: (0, 0)),
            pl.BlockSpec((1, E, block_n), lambda j, l, s: (l[0], 0, j)),
        ],
        out_specs=pl.BlockSpec((B, block_n), lambda j, l, s: (0, j)),
    )
    out = pl.pallas_call(
        _matvec_stacked_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, N), x.dtype),
        interpret=interpret,
    )(layer, s, x, w_stack)
    return out


def _matvec_stacked_kernel(l_ref, s_ref, x_ref, w_ref, o_ref):
    x = x_ref[...]
    w = w_ref[0].astype(x.dtype)
    y = jax.lax.dot(x, w, preferred_element_type=jnp.float32)
    o_ref[...] = (y * s_ref[l_ref[0]]).astype(x.dtype)


def decode_attention_int8_stacked(q, k_stack, k_scale, v_stack, v_scale,
                                  pos, layer, scale=None, block_l=None,
                                  interpret=None):
    """decode_attention_int8 over the stacked caches: k/v [L_layers, B,
    H, L, D] int8 + scales [L_layers, B, H, 1, L] fp32 indexed at
    ``layer`` by the block maps — the serving loop never slices a
    per-layer cache out (which copied the full multi-MB cache each layer
    each tick).

    Scales must arrive ALREADY lane-major 5-D: a 4-D [Lyr, B, H, L]
    array is accepted but reshaped here, and because the tiled layouts
    differ (T(8,128) vs T(1,128)) XLA materializes that reshape as a
    full-stack copy PER CALL — the r5 b32 trace measured it at 5.4
    ms/tick. Serving loops reshape once outside the layer scan.

    Grouped-query attention: q may carry R > 1 query rows per cache
    head ([B, Hkv, R, D] — the rep = H/Hkv query heads sharing each KV
    head fold into the row dim, consecutive-grouping as in the LLaMA
    layout). All R rows share the decode position, so the mask/softmax
    state just grows a row axis; the cache is read ONCE for all R."""
    if interpret is None:
        interpret = _interpret_default()
    B, H, R, D = q.shape
    Lyr = k_stack.shape[0]
    L = k_stack.shape[3]
    assert k_stack.shape[2] == H, (q.shape, k_stack.shape)
    scale = float(scale) if scale is not None else 1.0 / float(np.sqrt(D))
    if block_l is None:
        block_l = _pick_block_l(L, H, D, k_stack.dtype.itemsize)
    assert L % block_l == 0, (L, block_l)
    ks5 = k_scale.reshape(Lyr, B, H, 1, L)
    vs5 = v_scale.reshape(Lyr, B, H, 1, L)
    scalars = jnp.stack([jnp.asarray(layer, jnp.int32).reshape(()),
                         jnp.asarray(pos, jnp.int32).reshape(())])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, L // block_l),
        in_specs=[
            pl.BlockSpec((1, H, R, D), lambda b, lb, sc: (b, 0, 0, 0)),
            pl.BlockSpec((1, 1, H, block_l, D),
                         lambda b, lb, sc: (sc[0], b, 0, lb, 0)),
            pl.BlockSpec((1, 1, H, 1, block_l),
                         lambda b, lb, sc: (sc[0], b, 0, 0, lb)),
            pl.BlockSpec((1, 1, H, block_l, D),
                         lambda b, lb, sc: (sc[0], b, 0, lb, 0)),
            pl.BlockSpec((1, 1, H, 1, block_l),
                         lambda b, lb, sc: (sc[0], b, 0, 0, lb)),
        ],
        out_specs=pl.BlockSpec((1, H, R, D),
                               lambda b, lb, sc: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, R, 1), jnp.float32),
            pltpu.VMEM((H, R, 1), jnp.float32),
            pltpu.VMEM((H, R, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_attn_stacked_kernel, scale=scale,
                          block_l=block_l, seq_len=L, quantized=True),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, R, D), q.dtype),
        interpret=interpret,
    )(scalars, q, k_stack, ks5, v_stack, vs5)
    return out


def _pick_block_l(L, H, D, itemsize, budget_bytes=1 << 21):
    """Largest cache-row block (≤512, dividing L) whose [H, block, D]
    tile stays inside the per-block VMEM byte budget — bf16 caches halve
    their row count vs int8 automatically."""
    blk = min(L, 512)
    while blk > 128 and H * blk * D * itemsize > budget_bytes:
        blk //= 2
    while L % blk:
        blk //= 2
    return max(blk, 1)


def _decode_attn_stacked_kernel(sc_ref, q_ref, *rest, scale, block_l,
                                seq_len, quantized):
    """One online-softmax body for BOTH cache storages: ``quantized``
    (static) selects whether per-(b,h,pos) scale refs exist in the
    operand list — the masking/rescale/finish logic stays single-copy."""
    if quantized:
        k_ref, ks_ref, v_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = rest
    lb = pl.program_id(1)
    nb = seq_len // block_l
    pos = sc_ref[1]

    @pl.when(lb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref[...], -1e30)
        l_ref[...] = jnp.zeros_like(l_ref[...])
        acc_ref[...] = jnp.zeros_like(acc_ref[...])

    base = lb * block_l

    @pl.when(base <= pos)
    def _block():
        q = q_ref[0]                                # [H, R, D]
        k = k_ref[0, 0].astype(q.dtype)             # [H, bl, D]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)     # [H, R, bl]
        s = s * scale
        if quantized:
            s = s * ks_ref[0, 0]                    # ks [H, 1, bl]
        k_pos = base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(k_pos <= pos, s, -1e30)
        m_acc = m_ref[...]
        m_new = jnp.maximum(m_acc, jnp.max(s, axis=2, keepdims=True))
        m_ref[...] = m_new
        alpha = jnp.exp(m_acc - m_new)
        p = jnp.exp(s - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=2,
                                                  keepdims=True)
        if quantized:
            p = p * vs_ref[0, 0]
        pv = p.astype(q.dtype)
        v = v_ref[0, 0].astype(q.dtype)
        ctx = jax.lax.dot_general(
            pv, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)     # [H, R, D]
        acc_ref[...] = acc_ref[...] * alpha + ctx

    @pl.when(lb == nb - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def out_ffn_int8_stacked(ctx, x, wp_stack, sp, bp, ln_w, ln_b, w1_stack,
                         s1, b1, w2_stack, s2, b2, layer, act="gelu_tanh",
                         eps=1e-5, block_f=None, interpret=None,
                         norm="layer", w1b_stack=None, s1b=None,
                         fuse_proj=True):
    """out_ffn_int8 over stacked weights: wp [L,E,E], w1 [L,E,F],
    w2 [L,F,E] (int8 or bf16) indexed at ``layer`` by the block maps.
    Per-layer params are stacked too: ln_w/ln_b/bp/b2 [L, 1, E],
    b1 [L, 1, F] (2-D accepted, reshaped — see ln_qkv_int8_stacked);
    sp/s1/s2 [L] fp32 scale vectors ride SMEM via scalar prefetch.

    ``norm='rms'`` (LLaMA) drops ln_b and ALL projection biases (pass
    None); ``act='swiglu'`` takes the gate stack as ``w1_stack`` and
    the up stack as ``w1b_stack`` (scales ``s1``/``s1b``) — each tile
    computes silu(u@Wg)*(u@Wu) @ W2-tile with both [E, block_f] tiles
    streamed together.

    ``fuse_proj=False`` drops the attention-output projection phase:
    ``x`` must arrive as the POST-residual x1 (caller runs o_proj via
    matvec_int8_stacked + an XLA add) and ``ctx``/``wp_stack``/``sp``/
    ``bp`` are ignored — the large-E escape where a whole [E, E] proj
    block would blow scoped VMEM."""
    if interpret is None:
        interpret = _interpret_default()
    B, E = x.shape
    Lyr, Ew, F = w1_stack.shape
    assert Ew == E and w2_stack.shape[1:] == (F, E)
    assert (not fuse_proj) or wp_stack.shape[1:] == (E, E)
    use_bias = norm != "rms"
    assert (act == "swiglu") == (w1b_stack is not None), \
        "act='swiglu' takes the up-projection stack via w1b_stack"
    ln_w = ln_w.reshape(Lyr, 1, E)
    if block_f is None:
        block_f = _pick_block(
            F, budget_cols=(1 << 21) // max(E * w1_stack.dtype.itemsize, 1))
    assert F % block_f == 0, (F, block_f)
    n_tiles = F // block_f
    if not fuse_proj:
        sp = jnp.ones((Lyr,), jnp.float32)   # keep the scale layout
    svecs = [sp, s1, s2] + ([s1b] if act == "swiglu" else [])
    scales = jnp.stack([jnp.asarray(v, jnp.float32).reshape(Lyr)
                        for v in svecs], axis=1)        # [L, 3 or 4]
    layer = jnp.asarray(layer, jnp.int32).reshape(1)
    spec_be = pl.BlockSpec((B, E), lambda j, l, s: (0, 0))
    spec_e = pl.BlockSpec((1, 1, E), lambda j, l, s: (l[0], 0, 0))
    spec_w1 = pl.BlockSpec((1, E, block_f),
                           lambda j, l, s: (l[0], 0, j))
    if fuse_proj:
        in_specs = [spec_be, spec_be,
                    pl.BlockSpec((1, E, E), lambda j, l, s: (l[0], 0, 0)),
                    spec_e]
        operands = [ctx, x, wp_stack, ln_w]
    else:
        in_specs = [spec_be, spec_e]
        operands = [x, ln_w]
    if use_bias:
        in_specs += [spec_e, spec_e]
        operands += [ln_b.reshape(Lyr, 1, E),
                     jnp.asarray(bp).reshape(Lyr, 1, E)]
    in_specs.append(spec_w1)
    operands.append(w1_stack)
    if act == "swiglu":
        in_specs.append(spec_w1)
        operands.append(w1b_stack)
    if use_bias:
        in_specs.append(pl.BlockSpec((1, 1, block_f),
                                     lambda j, l, s: (l[0], 0, j)))
        operands.append(jnp.asarray(b1).reshape(Lyr, 1, F))
    in_specs.append(pl.BlockSpec((1, block_f, E),
                                 lambda j, l, s: (l[0], j, 0)))
    operands.append(w2_stack)
    if use_bias:
        in_specs.append(spec_e)
        operands.append(jnp.asarray(b2).reshape(Lyr, 1, E))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_tiles,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((B, E), lambda j, l, s: (0, 0)),
        scratch_shapes=[
            pltpu.VMEM((B, E), x.dtype),
            pltpu.VMEM((B, E), x.dtype),
            pltpu.VMEM((B, E), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_out_ffn_stacked_kernel, eps=eps, act=act,
                          n_tiles=n_tiles, norm=norm,
                          fuse_proj=fuse_proj),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, E), x.dtype),
        interpret=interpret,
    )(layer, scales, *operands)
    return out


def decode_attention_fp_stacked(q, k_stack, v_stack, pos, layer,
                                scale=None, block_l=None, interpret=None):
    """decode_attention over stacked FULL-PRECISION (bf16/fp32) caches:
    k/v [L_layers, B, H, L, D] indexed at ``layer`` by the block maps.
    Same online-softmax structure as the int8 variant minus the per-
    (b, h, pos) scale arrays (which have no fp counterpart). Supports
    grouped-query rows R > 1 like the int8 variant."""
    if interpret is None:
        interpret = _interpret_default()
    B, H, R, D = q.shape
    L = k_stack.shape[3]
    assert k_stack.shape[2] == H, (q.shape, k_stack.shape)
    scale = float(scale) if scale is not None else 1.0 / float(np.sqrt(D))
    if block_l is None:
        block_l = _pick_block_l(L, H, D, k_stack.dtype.itemsize)
    assert L % block_l == 0, (L, block_l)
    scalars = jnp.stack([jnp.asarray(layer, jnp.int32).reshape(()),
                         jnp.asarray(pos, jnp.int32).reshape(())])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, L // block_l),
        in_specs=[
            pl.BlockSpec((1, H, R, D), lambda b, lb, sc: (b, 0, 0, 0)),
            pl.BlockSpec((1, 1, H, block_l, D),
                         lambda b, lb, sc: (sc[0], b, 0, lb, 0)),
            pl.BlockSpec((1, 1, H, block_l, D),
                         lambda b, lb, sc: (sc[0], b, 0, lb, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, R, D),
                               lambda b, lb, sc: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, R, 1), jnp.float32),
            pltpu.VMEM((H, R, 1), jnp.float32),
            pltpu.VMEM((H, R, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_attn_stacked_kernel, scale=scale,
                          block_l=block_l, seq_len=L, quantized=False),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, R, D), q.dtype),
        interpret=interpret,
    )(scalars, q, k_stack, v_stack)
    return out


# ------------------------------------------------- paged decode attention
#
# The serving engine (deepspeed_tpu/serving) stores the KV cache as a POOL
# of fixed-size blocks [Lyr, NB, H, page, D] plus a per-slot page table
# [B, MAXP] int32; a slot's cache rows for positions [p*page, (p+1)*page)
# live in pool block page_table[b, p]. The kernel grid is (B, MAXP) and the
# K/V block index maps GATHER through the scalar-prefetched page table —
# same online-softmax body as the dense stacked kernel, but the slot's
# pages can live anywhere in the pool, so slots are admitted/freed without
# reshaping anyone else's cache. Per-slot ``pos`` (a VECTOR, unlike the
# dense kernels' scalar) masks each slot independently: slots decode at
# different sequence lengths in the same program, and pos[b] < 0 marks an
# idle slot (every page skipped, output rows zero).

def decode_attention_paged(q, k_pool, v_pool, pos, page_table, layer,
                           k_scale=None, v_scale=None, scale=None,
                           interpret=None, rows_per_step=None):
    """S=1 cached attention through a paged KV pool.

    q [B, H, R, D] (R = grouped-query rows per KV head, 1 for MHA);
    k_pool/v_pool [Lyr, NB, H, page, D] int8 or bf16/fp32 blocks;
    k_scale/v_scale [Lyr, NB, H, 1, page] fp32 per-(block, head, row)
    absmax scales — pass None for full-precision pools (both or neither);
    pos [B] int32 — per-slot index of the newest valid cache row (< 0 →
    idle slot, output zeros); page_table [B, MAXP] int32 — pool block ids
    per slot page; entries past the slot's live pages must still be VALID
    pool indices (the engine points them at the reserved trash block 0).
    layer: scalar int32. Returns [B, H, R, D] in q.dtype.

    ``rows_per_step`` switches the kernel into MULTI-QUERY mode
    (speculative-decode verification): q's row axis carries
    ``n_steps x rows_per_step`` query rows in STEP-MAJOR order (row j is
    spec step ``j // rows_per_step``), and row j masks keys at
    ``k_pos <= pos[b] + j // rows_per_step`` — each drafted token
    attends through the page table at its own successive position, so
    the target model verifies all K draft tokens in ONE paged-attention
    call instead of K sequential ticks. ``rows_per_step=None`` keeps the
    single-position mask (all rows share ``pos``)."""
    if interpret is None:
        interpret = _interpret_default()
    quantized = k_scale is not None
    assert (v_scale is not None) == quantized
    B, H, R, D = q.shape
    if rows_per_step is not None:
        assert R % rows_per_step == 0, (R, rows_per_step)
    Lyr, NB, Hp, page, Dp = k_pool.shape
    assert (Hp, Dp) == (H, D), (q.shape, k_pool.shape)
    MAXP = page_table.shape[1]
    assert page_table.shape == (B, MAXP), (page_table.shape, B)
    scale = float(scale) if scale is not None else 1.0 / float(np.sqrt(D))
    layer = jnp.asarray(layer, jnp.int32).reshape(1)
    pos = jnp.asarray(pos, jnp.int32).reshape(B)
    page_table = jnp.asarray(page_table, jnp.int32)
    kv_spec = pl.BlockSpec(
        (1, 1, H, page, D),
        lambda b, pb, lr, pr, pt: (lr[0], pt[b, pb], 0, 0, 0))
    sc_spec = pl.BlockSpec(
        (1, 1, H, 1, page),
        lambda b, pb, lr, pr, pt: (lr[0], pt[b, pb], 0, 0, 0))
    in_specs = [pl.BlockSpec((1, H, R, D),
                             lambda b, pb, lr, pr, pt: (b, 0, 0, 0))]
    operands = [q]
    if quantized:
        in_specs += [kv_spec, sc_spec, kv_spec, sc_spec]
        operands += [k_pool, k_scale, v_pool, v_scale]
    else:
        in_specs += [kv_spec, kv_spec]
        operands += [k_pool, v_pool]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, MAXP),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H, R, D),
                               lambda b, pb, lr, pr, pt: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, R, 1), jnp.float32),
            pltpu.VMEM((H, R, 1), jnp.float32),
            pltpu.VMEM((H, R, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_attn_paged_kernel, scale=scale,
                          page=page, quantized=quantized,
                          rows_per_step=rows_per_step),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, R, D), q.dtype),
        interpret=interpret,
    )(layer, pos, page_table, *operands)
    return out


def _decode_attn_paged_kernel(lyr_ref, pos_ref, pt_ref, q_ref, *rest,
                              scale, page, quantized, rows_per_step=None):
    """grid=(B, MAXP): same online-softmax state machine as the dense
    stacked kernel, but the block index maps already gathered this
    program's K/V page through the page table, and ``pos`` is read per
    slot so every batch row masks at its own length. In multi-query mode
    (``rows_per_step``) each query row masks at its own spec-step offset
    and pages up to the LAST step's position participate."""
    if quantized:
        k_ref, ks_ref, v_ref, vs_ref, o_ref, m_ref, d_ref, acc_ref = rest
    else:
        k_ref, v_ref, o_ref, m_ref, d_ref, acc_ref = rest
    b = pl.program_id(0)
    pb = pl.program_id(1)
    npg = pl.num_programs(1)
    pos = pos_ref[b]
    n_rows = q_ref.shape[2]
    max_step = 0 if rows_per_step is None \
        else n_rows // rows_per_step - 1

    @pl.when(pb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref[...], -1e30)
        d_ref[...] = jnp.zeros_like(d_ref[...])
        acc_ref[...] = jnp.zeros_like(acc_ref[...])

    base = pb * page

    # idle slots (pos < 0) must skip EVERY page even when max_step > 0
    # would otherwise pull page 0 in — their output stays zeros
    @pl.when((pos >= 0) & (base <= pos + max_step))
    def _block():
        q = q_ref[0]                                # [H, R, D]
        k = k_ref[0, 0].astype(q.dtype)             # [H, page, D]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)     # [H, R, page]
        s = s * scale
        if quantized:
            s = s * ks_ref[0, 0]                    # [H, 1, page]
        k_pos = base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        if rows_per_step is None:
            s = jnp.where(k_pos <= pos, s, -1e30)
        else:
            step = jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1) // rows_per_step
            s = jnp.where(k_pos <= pos + step, s, -1e30)
        m_acc = m_ref[...]
        m_new = jnp.maximum(m_acc, jnp.max(s, axis=2, keepdims=True))
        m_ref[...] = m_new
        alpha = jnp.exp(m_acc - m_new)
        p = jnp.exp(s - m_new)
        d_ref[...] = d_ref[...] * alpha + jnp.sum(p, axis=2,
                                                  keepdims=True)
        if quantized:
            p = p * vs_ref[0, 0]
        pv = p.astype(q.dtype)
        v = v_ref[0, 0].astype(q.dtype)
        ctx = jax.lax.dot_general(
            pv, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)     # [H, R, D]
        acc_ref[...] = acc_ref[...] * alpha + ctx

    @pl.when(pb == npg - 1)
    def _finish():
        d_safe = jnp.maximum(d_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / d_safe).astype(o_ref.dtype)


def _out_ffn_stacked_kernel(l_ref, sc_ref, *args, eps, act, n_tiles,
                            norm, fuse_proj=True):
    if fuse_proj:
        ctx_ref, x_ref, wp_ref, lnw_ref, *rest = args
    else:
        x_ref, lnw_ref, *rest = args
        ctx_ref = wp_ref = None
    if norm == "rms":
        if act == "swiglu":
            w1_ref, w1b_ref, w2_ref, o_ref, x1_ref, u_ref, acc_ref = rest
        else:
            w1_ref, w2_ref, o_ref, x1_ref, u_ref, acc_ref = rest
            w1b_ref = None
        lnb_ref = bp_ref = b1_ref = b2_ref = None
    else:
        assert act != "swiglu", "swiglu implies the bias-free rms layout"
        (lnb_ref, bp_ref, w1_ref, b1_ref, w2_ref, b2_ref, o_ref,
         x1_ref, u_ref, acc_ref) = rest
        w1b_ref = None
    j = pl.program_id(0)
    dt = x_ref.dtype
    lidx = l_ref[0]

    @pl.when(j == 0)
    def _proj():
        if fuse_proj:
            ctx = ctx_ref[...]
            wp = wp_ref[0].astype(dt)
            t = jax.lax.dot(ctx, wp, preferred_element_type=jnp.float32)
            t = t * sc_ref[lidx, 0]
            if bp_ref is not None:
                t = t + bp_ref[0].astype(jnp.float32)
            x1 = x_ref[...].astype(jnp.float32) + t
        else:
            x1 = x_ref[...].astype(jnp.float32)
        x1_ref[...] = x1.astype(dt)
        if norm == "rms":
            u_ref[...] = _rms(x1, lnw_ref[0], eps).astype(dt)
        else:
            u_ref[...] = _ln(x1, lnw_ref[0], lnb_ref[0], eps).astype(dt)
        acc_ref[...] = jnp.zeros_like(acc_ref[...])

    u = u_ref[...]
    w1 = w1_ref[0].astype(dt)
    h = jax.lax.dot(u, w1, preferred_element_type=jnp.float32)
    h = h * sc_ref[lidx, 1]
    if b1_ref is not None:
        h = h + b1_ref[0].astype(jnp.float32)
    if act == "swiglu":
        up = jax.lax.dot(u, w1b_ref[0].astype(dt),
                         preferred_element_type=jnp.float32)
        h = jax.nn.silu(h) * (up * sc_ref[lidx, 3])
    elif act == "gelu_tanh":
        h = jax.nn.gelu(h, approximate=True)
    else:
        h = jax.nn.gelu(h, approximate=False)
    w2 = w2_ref[0].astype(dt)
    acc_ref[...] += jax.lax.dot(h.astype(dt), w2,
                                preferred_element_type=jnp.float32)

    @pl.when(j == n_tiles - 1)
    def _finish():
        y = x1_ref[...].astype(jnp.float32) + acc_ref[...] * sc_ref[lidx, 2]
        if b2_ref is not None:
            y = y + b2_ref[0].astype(jnp.float32)
        o_ref[...] = y.astype(o_ref.dtype)
