"""Block-sparse attention Pallas kernels — the TPU replacement for the
reference's Triton SDD/DSD/DDS matmuls + block softmax
(ops/sparse_attention/matmul.py:16, softmax.py:17), used under autograd for
training exactly like the reference's sparse_self_attention.py:14.

Strategy (splash-attention style): the static layout [H, nb, nb] is
compiled into, per (head, q-block), the list of active k-blocks, and that
table drives the KERNEL GRID — the innermost grid dimension walks the
active blocks of the current row, and the k/v BlockSpec index maps read the
scalar-prefetched table to pick which [block, D] tile streams into VMEM
each step. Compute and HBM traffic scale with nnz blocks (matching the
reference's 6x speedup story, SURVEY §6), and VMEM holds only one tile per
operand — no whole-[S, D] row ever becomes resident, so sequence length is
bounded by HBM, not by the 16 MB VMEM (the pre-streaming kernel capped at
S·D ≈ 256k; BigBird at S=16k-32k now stays in-kernel).

Backward mirrors ops/pallas/flash_attention.py's chunked family: a dq pass
over the layout rows and a dk/dv pass over the layout's TRANSPOSE (per
k-block, the list of q-blocks that attend to it), both rematerializing p
from the forward's logsumexp, accumulating into revisited output blocks
(init on the first grid step, finalize on the last). The softmax scale is
folded into the q-loads; nothing here is autodiff-traced —
`blocksparse_attention` carries a custom VJP.

Grid cost note: every q-block row runs max_nnz steps (the table is padded
to the widest row), so heads/rows with far fewer active blocks than the
maximum waste steps; the standard layouts (fixed, bigbird, bslongformer)
are near-uniform per row, where the padding overhead is small.
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
POS_INF = 1e30


def _interpret_default():
    from deepspeed_tpu.utils.platform import is_tpu_backend
    return not is_tpu_backend()


def _layout_tables(layout):
    """layout [H, nb, nb] → (counts [H, nb], cols [H, nb, max_nnz]) padded
    with zeros; static host-side preprocessing."""
    H, nb, _ = layout.shape
    counts = layout.sum(axis=2).astype(np.int32)
    max_nnz = int(counts.max()) if counts.size else 0
    cols = np.zeros((H, nb, max(max_nnz, 1)), np.int32)
    for h in range(H):
        for r in range(nb):
            idx = np.nonzero(layout[h, r])[0]
            cols[h, r, :len(idx)] = idx
    return counts, cols, max(max_nnz, 1)


# ---------------------------------------------------------------- forward

def _bs_fwd_kernel(counts_ref, cols_ref, q_ref, k_ref, v_ref,
                   o_ref, stat_ref, *, scale, num_heads, max_nnz):
    """One grid step = one (q-block, active k-block) pair. The k/v tiles
    for step j were already selected by the BlockSpec index maps from the
    prefetched cols table; this body only does the online-softmax update.
    stat holds (m, l) interleaved on the last axis: [block, 2]."""
    b, r, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    h = b % num_heads

    @pl.when(j == 0)
    def _init():
        o_ref[0] = jnp.zeros_like(o_ref[0])
        stat_ref[0, :, 0] = jnp.full_like(stat_ref[0, :, 0], NEG_INF)
        stat_ref[0, :, 1] = jnp.zeros_like(stat_ref[0, :, 1])

    active = j < counts_ref[h, r]
    q = q_ref[0].astype(jnp.float32) * scale
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    o_acc = o_ref[0].astype(jnp.float32)
    m_acc = stat_ref[0, :, 0]
    l_acc = stat_ref[0, :, 1]

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    m_new = jnp.maximum(m_acc, jnp.max(s, axis=1))
    alpha = jnp.exp(m_acc - m_new)
    p = jnp.exp(s - m_new[:, None])
    l_new = l_acc * alpha + jnp.sum(p, axis=1)
    o_new = o_acc * alpha[:, None] + jax.lax.dot(
        p, v, preferred_element_type=jnp.float32)

    o = jnp.where(active, o_new, o_acc)
    m = jnp.where(active, m_new, m_acc)
    l = jnp.where(active, l_new, l_acc)

    last = j == max_nnz - 1
    l_safe = jnp.maximum(l, 1e-30)
    o_final = jnp.where((l > 0)[:, None], o / l_safe[:, None], 0.0)
    o_ref[0] = jnp.where(last, o_final, o)
    # rows with no active blocks get +inf so backward's exp(s - lse) is 0
    lse = jnp.where(l > 0, m + jnp.log(l_safe), POS_INF)
    stat_ref[0, :, 0] = jnp.where(last, lse, m)
    stat_ref[0, :, 1] = l


# ---------------------------------------------------------------- backward

def _bs_dq_kernel(counts_ref, cols_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                  delta_ref, dq_ref, *, scale, num_heads, max_nnz):
    b, r, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    h = b % num_heads

    @pl.when(j == 0)
    def _init():
        dq_ref[0] = jnp.zeros_like(dq_ref[0])

    active = j < counts_ref[h, r]
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    p = jnp.exp(s - lse[:, None])
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None])
    contrib = jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    dq = dq_ref[0].astype(jnp.float32) + jnp.where(active, contrib, 0.0)
    # accumulate unscaled; apply the folded-scale chain rule on the last step
    dq_ref[0] = jnp.where(j == max_nnz - 1, dq * scale, dq).astype(
        dq_ref.dtype)


def _bs_dkv_kernel(countsT_ref, rows_ref, q_ref, k_ref, v_ref, do_ref,
                   lse_ref, delta_ref, dk_ref, dv_ref, *, scale, num_heads,
                   max_nnzT):
    b, c, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    h = b % num_heads

    @pl.when(j == 0)
    def _init():
        dk_ref[0] = jnp.zeros_like(dk_ref[0])
        dv_ref[0] = jnp.zeros_like(dv_ref[0])

    active = j < countsT_ref[h, c]
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    p = jnp.exp(s - lse[:, None])
    dv_c = jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None])
    # dk = dsᵀ·(scale·q): q was pre-scaled, so this is exact
    dk_c = jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)

    dk_ref[0] = (dk_ref[0].astype(jnp.float32)
                 + jnp.where(active, dk_c, 0.0)).astype(dk_ref.dtype)
    dv_ref[0] = (dv_ref[0].astype(jnp.float32)
                 + jnp.where(active, dv_c, 0.0)).astype(dv_ref.dtype)


# ---------------------------------------------------------------- plumbing

def _bs_fwd(qf, kf, vf, tables, scale, block, interpret):
    (counts_bh, cols_bh, max_nnz, _, _, _, H) = tables
    BH, S, D = qf.shape
    nb = S // block
    kernel = functools.partial(_bs_fwd_kernel, scale=scale, num_heads=H,
                               max_nnz=max_nnz)

    # k/v tiles are chosen by the index map from the prefetched cols table
    # (the splash-attention move): VMEM sees one [block, D] tile per step
    def kv_map(b, i, j, counts, cols):
        return (b, cols[b % H, i, j], 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BH, nb, max_nnz),
        in_specs=[
            pl.BlockSpec((1, block, D), lambda b, i, j, *_: (b, i, 0)),
            pl.BlockSpec((1, block, D), kv_map),
            pl.BlockSpec((1, block, D), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block, D), lambda b, i, j, *_: (b, i, 0)),
            pl.BlockSpec((1, block, 2), lambda b, i, j, *_: (b, i, 0)),
        ],
    )
    # fp32 out buffer: the revisited o block doubles as the softmax
    # accumulator across grid steps, and rounding it to bf16 per active
    # block would compound error per block (flash's chunked family does
    # the same)
    o32, stat = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, S, 2), jnp.float32),
        ],
        interpret=interpret,
    )(counts_bh, cols_bh, qf, kf, vf)
    return o32, stat[:, :, :1]


def _bs_bwd(qf, kf, vf, o, lse, do, tables, scale, block, interpret):
    (counts_bh, cols_bh, max_nnz,
     countsT_bh, rows_bh, max_nnzT, H) = tables
    BH, S, D = qf.shape
    nb = S // block
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, :, None]

    def kv_map(b, i, j, counts, cols):
        return (b, cols[b % H, i, j], 0)

    dq = pl.pallas_call(
        functools.partial(_bs_dq_kernel, scale=scale, num_heads=H,
                          max_nnz=max_nnz),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(BH, nb, max_nnz),
            in_specs=[
                pl.BlockSpec((1, block, D), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, block, D), kv_map),
                pl.BlockSpec((1, block, D), kv_map),
                pl.BlockSpec((1, block, D), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, block, 1), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, block, 1), lambda b, i, j, *_: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, block, D),
                                   lambda b, i, j, *_: (b, i, 0)),
        ),
        # fp32 revisited accumulator (see forward)
        out_shape=jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
        interpret=interpret,
    )(counts_bh, cols_bh, qf, kf, vf, do, lse, delta)

    # transpose pass: grid walks each K-block's attending q-blocks
    def q_map(b, i, j, counts, rows):
        return (b, rows[b % H, i, j], 0)

    dk, dv = pl.pallas_call(
        functools.partial(_bs_dkv_kernel, scale=scale, num_heads=H,
                          max_nnzT=max_nnzT),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(BH, nb, max_nnzT),
            in_specs=[
                pl.BlockSpec((1, block, D), q_map),
                pl.BlockSpec((1, block, D), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, block, D), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, block, D), q_map),
                pl.BlockSpec((1, block, 1), q_map),
                pl.BlockSpec((1, block, 1), q_map),
            ],
            out_specs=[
                pl.BlockSpec((1, block, D), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, block, D), lambda b, i, j, *_: (b, i, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
        ],
        interpret=interpret,
    )(countsT_bh, rows_bh, qf, kf, vf, do, lse, delta)
    # cotangent dtypes must match the primals
    return (dq.astype(qf.dtype), dk.astype(kf.dtype), dv.astype(vf.dtype))


def blocksparse_attention(q, k, v, layout, block, scale=None,
                          key_padding_mask=None, attn_mask=None,
                          interpret=None):
    """[B, H, S, D] attention restricted to `layout` [H, S//block, S//block].

    Differentiable (custom VJP; used for training like the reference's
    Triton path). Extra element-level masks are not supported in the kernel
    path (the reference applied them inside the Triton softmax); callers
    pass masks via the dense fallback in sparse_self_attention.py.

    Sequence length is bounded by HBM only: K/V stream one [block, D] tile
    per grid step (selected by the layout table), never materializing a
    whole [S, D] row in VMEM.
    """
    if key_padding_mask is not None or attn_mask is not None:
        raise NotImplementedError("mask args use the dense fallback path")
    B, H, S, D = q.shape
    nb = S // block
    layout = np.asarray(layout)[:, :nb, :nb]
    if layout.shape[0] == 1 and H > 1:
        layout = np.broadcast_to(layout, (H, nb, nb))
    scale = float(scale) if scale is not None else 1.0 / float(np.sqrt(D))
    if interpret is None:
        interpret = _interpret_default()
    if S % block or block < 8:
        raise NotImplementedError("layout block too small for kernel tiling")

    counts, cols, max_nnz = _layout_tables(layout)
    countsT, rows, max_nnzT = _layout_tables(layout.transpose(0, 2, 1))
    # per-head tables (identical across batch); kernels index with
    # program_id(0) % H — [B*H]-expanded tables overflow the 1 MB SMEM
    tables = (jnp.asarray(counts), jnp.asarray(cols), max_nnz,
              jnp.asarray(countsT), jnp.asarray(rows), max_nnzT, H)

    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)

    @jax.custom_vjp
    def run(qf, kf, vf):
        o, _ = _bs_fwd(qf, kf, vf, tables, scale, block, bool(interpret))
        return o

    def run_fwd(qf, kf, vf):
        o, lse = _bs_fwd(qf, kf, vf, tables, scale, block, bool(interpret))
        return o, (qf, kf, vf, o, lse)

    def run_bwd(res, do):
        qf, kf, vf, o, lse = res
        return _bs_bwd(qf, kf, vf, o, lse, do, tables, scale, block,
                       bool(interpret))

    run.defvjp(run_fwd, run_bwd)
    # the kernel's fp32 output casts back to the caller dtype here, outside
    # the custom VJP, so backward's delta uses the unrounded o
    return run(qf, kf, vf).astype(q.dtype).reshape(B, H, S, D)
