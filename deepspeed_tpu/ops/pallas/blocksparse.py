"""Block-sparse attention Pallas kernel — the TPU replacement for the
reference's Triton SDD/DSD/DDS matmuls + block softmax
(ops/sparse_attention/matmul.py:16, softmax.py:17).

Strategy (splash-attention style): the static layout [H, nb, nb] is
compiled into, per (head, q-block), the list of active k-blocks; the kernel
iterates only those, with online softmax — so compute and HBM traffic scale
with nnz blocks, matching the reference's 6x speedup story (SURVEY §6).
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _interpret_default():
    from deepspeed_tpu.utils.platform import is_tpu_backend
    return not is_tpu_backend()


def _layout_tables(layout):
    """layout [H, nb, nb] → (counts [H, nb], cols [H, nb, max_nnz]) padded
    with zeros; static host-side preprocessing."""
    H, nb, _ = layout.shape
    counts = layout.sum(axis=2).astype(np.int32)
    max_nnz = int(counts.max()) if counts.size else 0
    cols = np.zeros((H, nb, max(max_nnz, 1)), np.int32)
    for h in range(H):
        for r in range(nb):
            idx = np.nonzero(layout[h, r])[0]
            cols[h, r, :len(idx)] = idx
    return counts, cols, max(max_nnz, 1)


def _bs_fwd_kernel(counts_ref, cols_ref, q_ref, k_ref, v_ref, o_ref,
                   *, scale, block):
    q = q_ref[0].astype(jnp.float32)  # [block, D]
    nnz = counts_ref[0, 0]

    def body(j, carry):
        o_acc, m_acc, l_acc = carry
        kb = cols_ref[0, 0, j]
        k = k_ref[0, pl.ds(kb * block, block), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block, block), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        m_new = jnp.maximum(m_acc, jnp.max(s, axis=1))
        alpha = jnp.exp(m_acc - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_acc * alpha + jnp.sum(p, axis=1)
        o_new = o_acc * alpha[:, None] + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    o0 = jnp.zeros((q.shape[0], q.shape[1]), jnp.float32)
    m0 = jnp.full((q.shape[0],), NEG_INF, jnp.float32)
    l0 = jnp.zeros((q.shape[0],), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, nnz, body, (o0, m0, l0))
    l_safe = jnp.maximum(l, 1e-30)
    o = jnp.where((l > 0)[:, None], o / l_safe[:, None], 0.0)
    o_ref[0] = o.astype(o_ref.dtype)


def blocksparse_attention(q, k, v, layout, block, scale=None,
                          key_padding_mask=None, attn_mask=None,
                          interpret=None):
    """[B, H, S, D] attention restricted to `layout` [H, S//block, S//block].

    Extra element-level masks are not supported in the kernel path (the
    reference applied them inside the Triton softmax); callers pass masks via
    the dense fallback in sparse_self_attention.py.
    """
    if key_padding_mask is not None or attn_mask is not None:
        raise NotImplementedError("mask args use the dense fallback path")
    B, H, S, D = q.shape
    nb = S // block
    layout = np.asarray(layout)[:, :nb, :nb]
    if layout.shape[0] == 1 and H > 1:
        layout = np.broadcast_to(layout, (H, nb, nb))
    scale = float(scale) if scale is not None else 1.0 / float(np.sqrt(D))
    if interpret is None:
        interpret = _interpret_default()
    if S % block or block < 8:
        raise NotImplementedError("layout block too small for kernel tiling")

    counts, cols, max_nnz = _layout_tables(layout)
    counts = jnp.asarray(counts)  # [H, nb]
    cols = jnp.asarray(cols)      # [H, nb, max_nnz]

    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)
    # expand tables to BH by head index
    head_idx = np.arange(B * H) % H
    counts_bh = counts[head_idx]          # [BH, nb]
    cols_bh = cols[head_idx]              # [BH, nb, max_nnz]

    kernel = functools.partial(_bs_fwd_kernel, scale=scale, block=block)
    o = pl.pallas_call(
        kernel,
        grid=(B * H, nb),
        in_specs=[
            pl.BlockSpec((1, 1), lambda b, i: (b, i)),
            pl.BlockSpec((1, 1, max_nnz), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        interpret=interpret,
    )(counts_bh, cols_bh, qf, kf, vf)
    return o.reshape(B, H, S, D)
