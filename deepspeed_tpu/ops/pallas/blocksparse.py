"""Block-sparse attention Pallas kernels — the TPU replacement for the
reference's Triton SDD/DSD/DDS matmuls + block softmax
(ops/sparse_attention/matmul.py:16, softmax.py:17), used under autograd for
training exactly like the reference's sparse_self_attention.py:14.

Strategy (splash-attention style): the static layout [H, nb, nb] is
compiled into, per (head, q-block), the list of active k-blocks, and that
table drives the KERNEL GRID — the innermost grid dimension walks the
active blocks of the current row, and the k/v BlockSpec index maps read the
scalar-prefetched table to pick which [block, D] tile streams into VMEM
each step. Compute and HBM traffic scale with nnz blocks (matching the
reference's 6x speedup story, SURVEY §6), and VMEM holds only one tile per
operand — no whole-[S, D] row ever becomes resident, so sequence length is
bounded by HBM, not by the 16 MB VMEM (the pre-streaming kernel capped at
S·D ≈ 256k; BigBird at S=16k-32k now stays in-kernel).

Backward mirrors ops/pallas/flash_attention.py's chunked family: a dq pass
over the layout rows and a dk/dv pass over the layout's TRANSPOSE (per
k-block, the list of q-blocks that attend to it), both rematerializing p
from the forward's logsumexp, accumulating into revisited output blocks
(init on the first grid step, finalize on the last). The softmax scale is
folded into the q-loads; nothing here is autodiff-traced —
`blocksparse_attention` carries a custom VJP.

Grid cost note: every q-block row runs max_nnz steps (the table is padded
to the widest row), so heads/rows with far fewer active blocks than the
maximum waste steps; the standard layouts (fixed, bigbird, bslongformer)
are near-uniform per row, where the padding overhead is small.
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
POS_INF = 1e30


def _interpret_default():
    from deepspeed_tpu.utils.platform import is_tpu_backend
    return not is_tpu_backend()


def _layout_tables(layout):
    """layout [H, nb, nb] → (counts [H, nb], cols [H, nb, max_nnz]) padded
    with zeros; static host-side preprocessing."""
    H, nb, _ = layout.shape
    counts = layout.sum(axis=2).astype(np.int32)
    max_nnz = int(counts.max()) if counts.size else 0
    cols = np.zeros((H, nb, max(max_nnz, 1)), np.int32)
    for h in range(H):
        for r in range(nb):
            idx = np.nonzero(layout[h, r])[0]
            cols[h, r, :len(idx)] = idx
    return counts, cols, max(max_nnz, 1)


def _grouped_tables(layout, R):
    """Fuse R consecutive q-block rows per grid step: per group the
    UNION of the rows' active k-blocks + an R-bit membership mask per
    union entry (bit i = row g*R+i attends to this k-block). Adjacent
    BigBird/longformer rows share their window blocks, so the union is
    far smaller than R separate lists — the DMA-issue amortization the
    kernel is bound by (docs/perf_tuning.md r4: ~1.4 us per tile)."""
    H, nb, _ = layout.shape
    ng = nb // R
    counts = np.zeros((H, ng), np.int32)
    cols_l, bits_l = [], []
    for h in range(H):
        hc, hb = [], []
        for g in range(ng):
            rows = layout[h, g * R:(g + 1) * R]          # [R, nb]
            union = np.nonzero(rows.any(axis=0))[0]
            counts[h, g] = len(union)
            bits = np.zeros(len(union), np.int32)
            for i in range(R):
                bits |= (rows[i, union].astype(np.int32) << i)
            hc.append(union)
            hb.append(bits)
        cols_l.append(hc)
        bits_l.append(hb)
    mx = max(1, int(counts.max()) if counts.size else 1)
    cols = np.zeros((H, ng, mx), np.int32)
    bits = np.zeros((H, ng, mx), np.int32)
    for h in range(H):
        for g in range(ng):
            n = counts[h, g]
            cols[h, g, :n] = cols_l[h][g]
            bits[h, g, :n] = bits_l[h][g]
    return counts, cols, bits, mx


# ---------------------------------------------------------------- forward

def _kv_copy(hbm, buf, sem, b, kb, slot, block):
    """Async HBM→VMEM copy descriptor for one [block, D] tile (slot of the
    double buffer). The source is block-major (BH, nb, block, D) so every
    copy is a contiguous chunk — Mosaic rejects strided DMA slices when
    D < the 128-lane tile. The same descriptor is rebuilt to wait()."""
    return pltpu.make_async_copy(hbm.at[b, kb], buf.at[slot], sem.at[slot])


def _bs_fwd_kernel(counts_ref, cols_ref, *rest, scale, block, d_head,
                   num_heads, table_heads, rgroup=1):
    """One grid step = one q-block ROW (or a GROUP of ``rgroup``
    consecutive rows): loop over the row/group's nnz active k-blocks (no
    max_nnz padding — a BigBird global row costs nb steps, a window row
    costs ~4), double-buffering the K/V tile DMAs against the
    online-softmax update. Grouped mode streams each UNION k-block once
    for all rgroup rows and masks non-member row-blocks via the R-bit
    membership table — the probability of a masked entry is ZEROED
    (where(act, p, 0)), not just -1e30'd: NEG_INF is finite, so
    exp(s - m) at a fully-masked row would otherwise be exp(0)."""
    if rgroup > 1:
        (bits_ref, q_ref, k_hbm, v_hbm, o_ref, lse_ref,
         k_buf, v_buf, k_sem, v_sem) = rest
    else:
        (q_ref, k_hbm, v_hbm, o_ref, lse_ref,
         k_buf, v_buf, k_sem, v_sem) = rest
        bits_ref = None
    b, r = pl.program_id(0), pl.program_id(1)
    h = (b % num_heads) if table_heads > 1 else 0
    nnz = counts_ref[h, r]
    q = q_ref[0].astype(jnp.float32) * scale
    rows = rgroup * block
    row_blk = jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0) // block

    def copies(j, slot):
        kb = cols_ref[h, r, j]
        return (_kv_copy(k_hbm, k_buf, k_sem, b, kb, slot, block),
                _kv_copy(v_hbm, v_buf, v_sem, b, kb, slot, block))

    @pl.when(nnz > 0)
    def _prefetch_first():
        ck, cv = copies(0, 0)
        ck.start()
        cv.start()

    def body(j, carry):
        o_acc, m_acc, l_acc = carry
        slot = jax.lax.rem(j, 2)

        @pl.when(j + 1 < nnz)
        def _prefetch_next():
            ck, cv = copies(j + 1, jax.lax.rem(j + 1, 2))
            ck.start()
            cv.start()

        ck, cv = copies(j, slot)
        ck.wait()
        cv.wait()
        # tiles are streamed lane-padded to 128; compute on the real D
        k = k_buf[slot, :, :d_head].astype(jnp.float32)
        v = v_buf[slot, :, :d_head].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if bits_ref is not None:
            act = ((bits_ref[h, r, j] >> row_blk) & 1) == 1   # [rows, 1]
            s = jnp.where(act, s, NEG_INF)
        m_new = jnp.maximum(m_acc, jnp.max(s, axis=1))
        alpha = jnp.exp(m_acc - m_new)
        p = jnp.exp(s - m_new[:, None])
        if bits_ref is not None:
            p = jnp.where(act, p, 0.0)
        l_new = l_acc * alpha + jnp.sum(p, axis=1)
        o_new = o_acc * alpha[:, None] + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    o0 = jnp.zeros((rows, q.shape[1]), jnp.float32)
    m0 = jnp.full((rows,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((rows,), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, nnz, body, (o0, m0, l0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = jnp.where((l > 0)[:, None], o / l_safe[:, None],
                         0.0).astype(o_ref.dtype)
    # rows with no active blocks get +inf so backward's exp(s - lse) is 0
    lse_ref[0, :, 0] = jnp.where(l > 0, m + jnp.log(l_safe), POS_INF)


# ---------------------------------------------------------------- backward

def _bs_dq_kernel(counts_ref, cols_ref, *rest, scale, block, d_head,
                  num_heads, table_heads, rgroup=1):
    if rgroup > 1:
        (bits_ref, q_ref, k_hbm, v_hbm, do_ref, lse_ref, delta_ref,
         dq_ref, k_buf, v_buf, k_sem, v_sem) = rest
    else:
        (q_ref, k_hbm, v_hbm, do_ref, lse_ref, delta_ref, dq_ref,
         k_buf, v_buf, k_sem, v_sem) = rest
        bits_ref = None
    b, r = pl.program_id(0), pl.program_id(1)
    h = (b % num_heads) if table_heads > 1 else 0
    nnz = counts_ref[h, r]
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    rows = rgroup * block
    row_blk = jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0) // block

    def copies(j, slot):
        kb = cols_ref[h, r, j]
        return (_kv_copy(k_hbm, k_buf, k_sem, b, kb, slot, block),
                _kv_copy(v_hbm, v_buf, v_sem, b, kb, slot, block))

    @pl.when(nnz > 0)
    def _prefetch_first():
        ck, cv = copies(0, 0)
        ck.start()
        cv.start()

    def body(j, dq_acc):
        slot = jax.lax.rem(j, 2)

        @pl.when(j + 1 < nnz)
        def _prefetch_next():
            ck, cv = copies(j + 1, jax.lax.rem(j + 1, 2))
            ck.start()
            cv.start()

        ck, cv = copies(j, slot)
        ck.wait()
        cv.wait()
        k = k_buf[slot, :, :d_head].astype(jnp.float32)
        v = v_buf[slot, :, :d_head].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        p = jnp.exp(s - lse[:, None])
        if bits_ref is not None:
            # zero non-member row-blocks: their contribution belongs to
            # a different k-block's grid step (or none)
            act = ((bits_ref[h, r, j] >> row_blk) & 1) == 1
            p = jnp.where(act, p, 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return dq_acc + jax.lax.dot(ds, k,
                                    preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, nnz, body,
                           jnp.zeros((rows, q.shape[1]), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bs_dkv_kernel(countsT_ref, rows_ref, q_hbm, k_ref, v_ref, do_hbm,
                   lse_ref, delta_ref, dk_ref, dv_ref, q_buf, do_buf,
                   q_sem, do_sem, *, scale, block, d_head, num_heads,
                   table_heads):
    """Transpose pass: per K-block COLUMN, loop over the q-blocks that
    attend to it, streaming q/do tiles; lse/delta are 1 float per token,
    packed (nb, block) with the block on the LANE axis — a (S, 1) layout
    would lane-pad 1→128 (8 MB at 16k), this stays S·4 B — so the whole
    row is VMEM-resident and read per q-block in-kernel."""
    b, c = pl.program_id(0), pl.program_id(1)
    h = (b % num_heads) if table_heads > 1 else 0
    nnz = countsT_ref[h, c]
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)

    def copies(j, slot):
        qb = rows_ref[h, c, j]
        return (_kv_copy(q_hbm, q_buf, q_sem, b, qb, slot, block),
                _kv_copy(do_hbm, do_buf, do_sem, b, qb, slot, block))

    @pl.when(nnz > 0)
    def _prefetch_first():
        for cp in copies(0, 0):
            cp.start()

    def body(j, carry):
        dk_acc, dv_acc = carry
        slot = jax.lax.rem(j, 2)

        @pl.when(j + 1 < nnz)
        def _prefetch_next():
            for cp in copies(j + 1, jax.lax.rem(j + 1, 2)):
                cp.start()

        for cp in copies(j, slot):
            cp.wait()
        qb = rows_ref[h, c, j]
        q = q_buf[slot, :, :d_head].astype(jnp.float32) * scale
        do = do_buf[slot, :, :d_head].astype(jnp.float32)
        lse = lse_ref[0, qb, :]
        delta = delta_ref[0, qb, :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        p = jnp.exp(s - lse[:, None])
        dv_new = dv_acc + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        # dk = dsT·(scale·q): q was pre-scaled, so this is exact
        dk_new = dk_acc + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    z = jnp.zeros((block, k.shape[1]), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, nnz, body, (z, z))
    dk_ref[0] = dk
    dv_ref[0] = dv


# ---------------------------------------------------------------- plumbing

def _block_major(x, nb, block, Dp):
    """[BH, S, D] → [BH, nb, block, Dp]: block-major, lane-padded to 128 so
    every streamed DMA chunk is contiguous and tile-aligned (Mosaic
    requires the copied chunk's last dim to be a multiple of 128)."""
    BH, S, D = x.shape
    x = x.reshape(BH, nb, block, D)
    if Dp != D:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, Dp - D)))
    return x


def _bs_fwd(qf, kf, vf, tables, scale, block, interpret):
    (counts_bh, cols_bh, max_nnz, _, _, _, H, TH, grouped, R) = tables
    BH, S, D = qf.shape
    nb = S // block
    rows = R * block
    Dp = ((D + 127) // 128) * 128    # lane-pad streamed tiles to 128
    kernel = functools.partial(_bs_fwd_kernel, scale=scale, block=block,
                               d_head=D, num_heads=H, table_heads=TH,
                               rgroup=R)
    if grouped is not None:
        prefetch = (grouped[0], grouped[1], grouped[2])
    else:
        prefetch = (counts_bh, cols_bh)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(prefetch),
        grid=(BH, nb // R),
        in_specs=[
            pl.BlockSpec((1, rows, D), lambda b, i, *_: (b, i, 0)),
            pl.BlockSpec(memory_space=pl.ANY),   # k stays in HBM; DMA'd
            pl.BlockSpec(memory_space=pl.ANY),   # v stays in HBM; DMA'd
        ],
        out_specs=[
            pl.BlockSpec((1, rows, D), lambda b, i, *_: (b, i, 0)),
            pl.BlockSpec((1, rows, 1), lambda b, i, *_: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, block, Dp), kf.dtype),
            pltpu.VMEM((2, block, Dp), vf.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    kb4 = _block_major(kf, nb, block, Dp)
    vb4 = _block_major(vf, nb, block, Dp)
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            # fp32: run_fwd saves this o as the residual, so backward's
            # delta = sum(do*o) sees the unrounded values; the cast to the
            # caller dtype happens outside the custom VJP
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, S, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*prefetch, qf, kb4, vb4)
    return o, lse


def _bs_bwd(qf, kf, vf, o, lse, do, tables, scale, block, interpret):
    (counts_bh, cols_bh, max_nnz,
     countsT_bh, rows_bh, max_nnzT, H, TH, grouped, R) = tables
    BH, S, D = qf.shape
    nb = S // block
    rows = R * block
    Dp = ((D + 127) // 128) * 128
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, :, None]

    if grouped is not None:
        prefetch = (grouped[0], grouped[1], grouped[2])
    else:
        prefetch = (counts_bh, cols_bh)
    dq = pl.pallas_call(
        functools.partial(_bs_dq_kernel, scale=scale, block=block,
                          d_head=D, num_heads=H, table_heads=TH,
                          rgroup=R),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(prefetch),
            grid=(BH, nb // R),
            in_specs=[
                pl.BlockSpec((1, rows, D), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec((1, rows, D), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec((1, rows, 1), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec((1, rows, 1), lambda b, i, *_: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, rows, D),
                                   lambda b, i, *_: (b, i, 0)),
            scratch_shapes=[
                pltpu.VMEM((2, block, Dp), kf.dtype),
                pltpu.VMEM((2, block, Dp), vf.dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), qf.dtype),
        interpret=interpret,
    )(*prefetch, qf, _block_major(kf, nb, block, Dp),
      _block_major(vf, nb, block, Dp), do, lse, delta)

    # transpose pass: per K-block column, stream its attending q-blocks
    dk, dv = pl.pallas_call(
        functools.partial(_bs_dkv_kernel, scale=scale, block=block,
                          d_head=D, num_heads=H, table_heads=TH),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(BH, nb),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),   # q streamed
                pl.BlockSpec((1, block, D), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec((1, block, D), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec(memory_space=pl.ANY),   # do streamed
                pl.BlockSpec((1, nb, block), lambda b, i, *_: (b, 0, 0)),
                pl.BlockSpec((1, nb, block), lambda b, i, *_: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block, D), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec((1, block, D), lambda b, i, *_: (b, i, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, block, Dp), qf.dtype),
                pltpu.VMEM((2, block, Dp), do.dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
        ],
        interpret=interpret,
    )(countsT_bh, rows_bh, _block_major(qf, nb, block, Dp), kf, vf,
      _block_major(do, nb, block, Dp), lse.reshape(BH, nb, block),
      delta.reshape(BH, nb, block))
    # cotangent dtypes must match the primals
    return (dq.astype(qf.dtype), dk.astype(kf.dtype), dv.astype(vf.dtype))


def blocksparse_attention(q, k, v, layout, block, scale=None,
                          key_padding_mask=None, attn_mask=None,
                          interpret=None):
    """[B, H, S, D] attention restricted to `layout` [H, S//block, S//block].

    Differentiable (custom VJP; used for training like the reference's
    Triton path). Extra element-level masks are not supported in the kernel
    path (the reference applied them inside the Triton softmax); callers
    pass masks via the dense fallback in sparse_self_attention.py.

    Sequence length is bounded by HBM only: K/V stream one [block, D] tile
    per grid step (selected by the layout table), never materializing a
    whole [S, D] row in VMEM.
    """
    if key_padding_mask is not None or attn_mask is not None:
        raise NotImplementedError("mask args use the dense fallback path")
    B, H, S, D = q.shape
    nb = S // block
    layout = np.asarray(layout)[:, :nb, :nb]
    shared_layout = layout.shape[0] == 1 and H > 1
    if shared_layout:
        layout = np.broadcast_to(layout, (H, nb, nb))
    scale = float(scale) if scale is not None else 1.0 / float(np.sqrt(D))
    if interpret is None:
        interpret = _interpret_default()
    if S % block or block < 8:
        raise NotImplementedError("layout block too small for kernel tiling")

    # SMEM budget: the tables live in SMEM (1 MB). The transpose table of
    # a layout with global columns is dense in those columns (max_nnzT =
    # nb), i.e. O(H·nb²) ints — at 16k/128 with 16 heads that alone is
    # ~1 MB. Layouts are usually shared across heads
    # (different_layout_per_head=False propagates head 0), so collapse to
    # a single-head table whenever all heads match.
    if shared_layout:
        table_layout = layout[:1]
    elif H > 1 and bool(np.all(layout == layout[:1])):
        table_layout = layout[:1]
    else:
        table_layout = layout
    counts, cols, max_nnz = _layout_tables(table_layout)
    countsT, rows, max_nnzT = _layout_tables(table_layout.transpose(0, 2, 1))
    # q-row fusion: R consecutive rows share each union k-block's DMA
    # (and grid step) — the kernel is DMA-ISSUE bound, so fewer, fatter
    # steps win. Cap fused rows at 1024 (VMEM: fp32 q/o/acc rows) and
    # the bitmask at 32 rows.
    R = 1
    cand = min(max(1024 // block, 1), 32, nb)
    while cand > 1 and nb % cand:
        cand //= 2
    grouped = None
    if cand > 1:
        gc, gcol, gbits, _ = _grouped_tables(table_layout, cand)
        # group only when rows actually SHARE k-blocks: the grouped
        # step multiplies all R row-blocks against every union tile, so
        # when the union is ~R disjoint lists (dense layouts) grouping
        # pays R x masked compute for no DMA saving — measured 1.04x ->
        # 0.85x at S=4096/density 0.73 before this gate
        counts_total = int(np.asarray(counts).sum())
        union_total = int(gc.sum())
        if counts_total and union_total <= 0.6 * counts_total:
            # grouping cuts DMA issues to <=60% — worth the mask cost
            R = cand
            grouped = (jnp.asarray(gc), jnp.asarray(gcol),
                       jnp.asarray(gbits))
    # budget counts what actually ships to SMEM: grouping REPLACES the
    # ungrouped row tables in the fwd/dq passes (dkv keeps countsT/rows)
    if grouped is not None:
        smem_bytes = 4 * (countsT.size + rows.size + grouped[0].size
                          + grouped[1].size + grouped[2].size)
    else:
        smem_bytes = 4 * (counts.size + cols.size + countsT.size
                          + rows.size)
    if smem_bytes > 900_000:
        raise NotImplementedError(
            f"layout tables need ~{smem_bytes} B of SMEM (>1 MB budget): "
            f"{table_layout.shape[0]} distinct head layouts at "
            f"nb={nb} with max_nnz={max_nnz}/{max_nnzT}; reduce "
            f"different_layout_per_head or the global-column count")
    tables = (jnp.asarray(counts), jnp.asarray(cols), max_nnz,
              jnp.asarray(countsT), jnp.asarray(rows), max_nnzT, H,
              table_layout.shape[0], grouped, R)

    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)

    @jax.custom_vjp
    def run(qf, kf, vf):
        o, _ = _bs_fwd(qf, kf, vf, tables, scale, block, bool(interpret))
        return o

    def run_fwd(qf, kf, vf):
        o, lse = _bs_fwd(qf, kf, vf, tables, scale, block, bool(interpret))
        return o, (qf, kf, vf, o, lse)

    def run_bwd(res, do):
        qf, kf, vf, o, lse = res
        return _bs_bwd(qf, kf, vf, o, lse, do, tables, scale, block,
                       bool(interpret))

    run.defvjp(run_fwd, run_bwd)
    # the kernel's fp32 output casts back to the caller dtype here, outside
    # the custom VJP, so backward's delta uses the unrounded o
    return run(qf, kf, vf).astype(q.dtype).reshape(B, H, S, D)
