"""Block-sparse attention Pallas kernels — the TPU replacement for the
reference's Triton SDD/DSD/DDS matmuls + block softmax
(ops/sparse_attention/matmul.py:16, softmax.py:17), used under autograd for
training exactly like the reference's sparse_self_attention.py:14.

Strategy (splash-attention style): the static layout [H, nb, nb] is
compiled into, per (head, q-block), the list of active k-blocks; the kernel
iterates only those, with online softmax — so compute and HBM traffic scale
with nnz blocks, matching the reference's 6x speedup story (SURVEY §6).

Backward mirrors ops/pallas/flash_attention.py: a dq pass over the layout
rows and a dk/dv pass over the layout's TRANSPOSE (per k-block, the list of
q-blocks that attend to it), both rematerializing p from the forward's
logsumexp. The softmax scale is folded into the q-loads; nothing here is
autodiff-traced — `blocksparse_attention` carries a custom VJP.
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
POS_INF = 1e30


def _interpret_default():
    from deepspeed_tpu.utils.platform import is_tpu_backend
    return not is_tpu_backend()


def _layout_tables(layout):
    """layout [H, nb, nb] → (counts [H, nb], cols [H, nb, max_nnz]) padded
    with zeros; static host-side preprocessing."""
    H, nb, _ = layout.shape
    counts = layout.sum(axis=2).astype(np.int32)
    max_nnz = int(counts.max()) if counts.size else 0
    cols = np.zeros((H, nb, max(max_nnz, 1)), np.int32)
    for h in range(H):
        for r in range(nb):
            idx = np.nonzero(layout[h, r])[0]
            cols[h, r, :len(idx)] = idx
    return counts, cols, max(max_nnz, 1)


# ---------------------------------------------------------------- forward

def _bs_fwd_kernel(counts_ref, cols_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                   *, scale, block, num_heads):
    # counts/cols are scalar-prefetched whole into SMEM (Mosaic requires
    # ≥(8,128) tiles for VMEM blocks; control tables belong in SMEM anyway).
    # Tables are per-HEAD (identical across the batch) to fit SMEM.
    h, r = pl.program_id(0) % num_heads, pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [block, D]
    nnz = counts_ref[h, r]

    def body(j, carry):
        o_acc, m_acc, l_acc = carry
        kb = cols_ref[h, r, j]
        k = k_ref[0, pl.ds(kb * block, block), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block, block), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        m_new = jnp.maximum(m_acc, jnp.max(s, axis=1))
        alpha = jnp.exp(m_acc - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_acc * alpha + jnp.sum(p, axis=1)
        o_new = o_acc * alpha[:, None] + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    o0 = jnp.zeros((q.shape[0], q.shape[1]), jnp.float32)
    m0 = jnp.full((q.shape[0],), NEG_INF, jnp.float32)
    l0 = jnp.zeros((q.shape[0],), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, nnz, body, (o0, m0, l0))
    l_safe = jnp.maximum(l, 1e-30)
    o = jnp.where((l > 0)[:, None], o / l_safe[:, None], 0.0)
    o_ref[0] = o.astype(o_ref.dtype)
    # rows with no active blocks get +inf so backward's exp(s - lse) is 0
    lse_ref[0, :, 0] = jnp.where(l > 0, m + jnp.log(l_safe), POS_INF)


# ---------------------------------------------------------------- backward

def _bs_dq_kernel(counts_ref, cols_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                  delta_ref, dq_ref, *, scale, block, num_heads):
    h, r = pl.program_id(0) % num_heads, pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    nnz = counts_ref[h, r]

    def body(j, dq_acc):
        kb = cols_ref[h, r, j]
        k = k_ref[0, pl.ds(kb * block, block), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block, block), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return dq_acc + jax.lax.dot(ds, k,
                                    preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, nnz, body, jnp.zeros_like(q))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bs_dkv_kernel(countsT_ref, rows_ref, q_ref, k_ref, v_ref, do_ref,
                   lse_ref, delta_ref, dk_ref, dv_ref, *, scale, block,
                   num_heads):
    h, c = pl.program_id(0) % num_heads, pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)   # [block, D]
    v = v_ref[0].astype(jnp.float32)
    nnz = countsT_ref[h, c]

    def body(j, carry):
        dk_acc, dv_acc = carry
        qb = rows_ref[h, c, j]
        q = q_ref[0, pl.ds(qb * block, block), :].astype(jnp.float32) * scale
        do = do_ref[0, pl.ds(qb * block, block), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qb * block, block), 0]
        delta = delta_ref[0, pl.ds(qb * block, block), 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        p = jnp.exp(s - lse[:, None])
        dv_new = dv_acc + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        # dk = dsᵀ·(scale·q): q was pre-scaled, so this is exact
        dk_new = dk_acc + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk, dv = jax.lax.fori_loop(0, nnz, body,
                               (jnp.zeros_like(k), jnp.zeros_like(v)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------- plumbing

def _bs_fwd(qf, kf, vf, tables, scale, block, interpret):
    (counts_bh, cols_bh, _, _, _, _, _) = tables
    BH, S, D = qf.shape
    nb = S // block
    kernel = functools.partial(_bs_fwd_kernel, scale=scale, block=block,
                               num_heads=tables[-1])
    # index maps under scalar prefetch receive the scalar refs after the
    # grid indices; the q/k/v blocks don't depend on them
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BH, nb),
        in_specs=[
            pl.BlockSpec((1, block, D), lambda b, i, *_: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i, *_: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i, *_: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, D), lambda b, i, *_: (b, i, 0)),
            pl.BlockSpec((1, block, 1), lambda b, i, *_: (b, i, 0)),
        ],
    )
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), qf.dtype),
            jax.ShapeDtypeStruct((BH, S, 1), jnp.float32),
        ],
        interpret=interpret,
    )(counts_bh, cols_bh, qf, kf, vf)
    return o, lse


def _bs_bwd(qf, kf, vf, o, lse, do, tables, scale, block, interpret):
    (counts_bh, cols_bh, max_nnz,
     countsT_bh, rows_bh, max_nnzT, _) = tables
    BH, S, D = qf.shape
    nb = S // block
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, :, None]

    dq = pl.pallas_call(
        functools.partial(_bs_dq_kernel, scale=scale, block=block,
                          num_heads=tables[-1]),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(BH, nb),
            in_specs=[
                pl.BlockSpec((1, block, D), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec((1, S, D), lambda b, i, *_: (b, 0, 0)),
                pl.BlockSpec((1, S, D), lambda b, i, *_: (b, 0, 0)),
                pl.BlockSpec((1, block, D), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec((1, block, 1), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec((1, block, 1), lambda b, i, *_: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, block, D), lambda b, i, *_: (b, i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), qf.dtype),
        interpret=interpret,
    )(counts_bh, cols_bh, qf, kf, vf, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bs_dkv_kernel, scale=scale, block=block,
                          num_heads=tables[-1]),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(BH, nb),
            in_specs=[
                pl.BlockSpec((1, S, D), lambda b, i, *_: (b, 0, 0)),
                pl.BlockSpec((1, block, D), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec((1, block, D), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec((1, S, D), lambda b, i, *_: (b, 0, 0)),
                pl.BlockSpec((1, S, 1), lambda b, i, *_: (b, 0, 0)),
                pl.BlockSpec((1, S, 1), lambda b, i, *_: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block, D), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec((1, block, D), lambda b, i, *_: (b, i, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), qf.dtype),
            jax.ShapeDtypeStruct((BH, S, D), qf.dtype),
        ],
        interpret=interpret,
    )(countsT_bh, rows_bh, qf, kf, vf, do, lse, delta)
    return dq, dk, dv


def blocksparse_attention(q, k, v, layout, block, scale=None,
                          key_padding_mask=None, attn_mask=None,
                          interpret=None):
    """[B, H, S, D] attention restricted to `layout` [H, S//block, S//block].

    Differentiable (custom VJP; used for training like the reference's
    Triton path). Extra element-level masks are not supported in the kernel
    path (the reference applied them inside the Triton softmax); callers
    pass masks via the dense fallback in sparse_self_attention.py.
    """
    if key_padding_mask is not None or attn_mask is not None:
        raise NotImplementedError("mask args use the dense fallback path")
    B, H, S, D = q.shape
    nb = S // block
    layout = np.asarray(layout)[:, :nb, :nb]
    if layout.shape[0] == 1 and H > 1:
        layout = np.broadcast_to(layout, (H, nb, nb))
    scale = float(scale) if scale is not None else 1.0 / float(np.sqrt(D))
    if interpret is None:
        interpret = _interpret_default()
    if S % block or block < 8:
        raise NotImplementedError("layout block too small for kernel tiling")
    if S * D > 262144:
        # the bwd kernels keep whole [S, D] q/do rows resident in VMEM
        # (plus double buffering); measured ceiling on v5e is S·D ≈ 256k
        # (S=4096 at D=64 fits, S=8192 overflows the 16 MB scoped vmem).
        # Beyond that the caller's dense fallback handles it; the long-S
        # regime belongs to ring attention (parallel/ring_attention.py)
        # which shards S before attention runs.
        raise NotImplementedError(
            f"S*D={S * D} exceeds the kernel's VMEM row budget")

    counts, cols, max_nnz = _layout_tables(layout)
    countsT, rows, max_nnzT = _layout_tables(layout.transpose(0, 2, 1))
    # per-head tables (identical across batch); kernels index with
    # program_id(0) % H — [B*H]-expanded tables overflow the 1 MB SMEM
    tables = (jnp.asarray(counts), jnp.asarray(cols), max_nnz,
              jnp.asarray(countsT), jnp.asarray(rows), max_nnzT, H)

    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)

    @jax.custom_vjp
    def run(qf, kf, vf):
        o, _ = _bs_fwd(qf, kf, vf, tables, scale, block, bool(interpret))
        return o

    def run_fwd(qf, kf, vf):
        o, lse = _bs_fwd(qf, kf, vf, tables, scale, block, bool(interpret))
        return o, (qf, kf, vf, o, lse)

    def run_bwd(res, do):
        qf, kf, vf, o, lse = res
        return _bs_bwd(qf, kf, vf, o, lse, do, tables, scale, block,
                       bool(interpret))

    run.defvjp(run_fwd, run_bwd)
    return run(qf, kf, vf).reshape(B, H, S, D)
