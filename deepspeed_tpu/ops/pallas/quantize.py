"""Grouped quantization kernels — TPU replacement for the reference's CUDA
quantizer (csrc/quantization/quantizer.cu: ds_quantize_fp16,
ds_sr_quantize_fp16 and the asym variants, bound in quantizer.cpp:63-73).

Design: per-group scale/offset from a row-max reduction, then an elementwise
round (nearest or stochastic via the TPU per-core PRNG) — one Pallas program
per group row, data staged through VMEM so the whole quantize-dequantize is
one HBM round-trip. Non-TPU backends run the same kernel in interpreter mode
(conftest CPU tests), and `quantize_jnp` is the pure-XLA reference the kernel
is tested against.
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only resolves on TPU builds; interpret mode works without it
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None


def _interpret_default():
    from deepspeed_tpu.utils.platform import is_tpu_backend
    return not is_tpu_backend()


def _qparams(flat, bits, sym):
    """Per-group (scale, zero) in fp32. flat: [G, N]."""
    qmax = 2.0 ** (bits - 1) - 1
    if sym:
        scale = jnp.max(jnp.abs(flat), axis=-1, keepdims=True) / qmax
        scale = jnp.where(scale == 0, 1.0, scale)
        zero = jnp.zeros_like(scale)
    else:
        lo = jnp.min(flat, axis=-1, keepdims=True)
        hi = jnp.max(flat, axis=-1, keepdims=True)
        scale = (hi - lo) / (2.0 ** bits - 1)
        scale = jnp.where(scale == 0, 1.0, scale)
        zero = lo
    return scale, zero


def quantize_jnp(x, bits=8, groups=1, sym=True, stochastic=False, key=None):
    """Pure-XLA grouped fake quantization (quantize→dequantize), the numeric
    ground truth for the Pallas kernel."""
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.reshape(groups, -1).astype(jnp.float32)
    scale, zero = _qparams(flat, bits, sym)
    if sym:
        qmax = 2.0 ** (bits - 1) - 1
        t = flat / scale
        if stochastic:
            u = jax.random.uniform(key, t.shape)
            q = jnp.floor(t + u)
        else:
            q = jnp.round(t)
        q = jnp.clip(q, -qmax - 1, qmax)
        out = q * scale
    else:
        levels = 2.0 ** bits - 1
        t = (flat - zero) / scale
        if stochastic:
            u = jax.random.uniform(key, t.shape)
            q = jnp.floor(t + u)
        else:
            q = jnp.round(t)
        q = jnp.clip(q, 0, levels)
        out = q * scale + zero
    return out.reshape(orig_shape).astype(orig_dtype)


def _quant_kernel(seed_ref, x_ref, o_ref, *, bits, sym, stochastic):
    if stochastic and pltpu is not None:
        pltpu.prng_seed(seed_ref[0, 0] + pl.program_id(0))
    x = x_ref[...].astype(jnp.float32)
    qmax = 2.0 ** (bits - 1) - 1
    if sym:
        scale = jnp.max(jnp.abs(x)) / qmax
        scale = jnp.where(scale == 0, 1.0, scale)
        t = x / scale
        if stochastic:
            rbits = pltpu.prng_random_bits(t.shape).astype(jnp.uint32)
            u = (rbits >> 8).astype(jnp.float32) * (1.0 / (1 << 24))
            q = jnp.floor(t + u)
        else:
            q = jnp.round(t)
        q = jnp.clip(q, -qmax - 1, qmax)
        o_ref[...] = (q * scale).astype(o_ref.dtype)
    else:
        levels = 2.0 ** bits - 1
        lo, hi = jnp.min(x), jnp.max(x)
        scale = (hi - lo) / levels
        scale = jnp.where(scale == 0, 1.0, scale)
        t = (x - lo) / scale
        if stochastic:
            rbits = pltpu.prng_random_bits(t.shape).astype(jnp.uint32)
            u = (rbits >> 8).astype(jnp.float32) * (1.0 / (1 << 24))
            q = jnp.floor(t + u)
        else:
            q = jnp.round(t)
        q = jnp.clip(q, 0, levels)
        o_ref[...] = (q * scale + lo).astype(o_ref.dtype)


def quantize(x, bits=8, groups=1, sym=True, stochastic=False, key=None,
             interpret=None):
    """Grouped fake quantization via the Pallas kernel (grid = one program
    per group). Matches quantize_jnp bit-for-bit with nearest rounding."""
    if interpret is None:
        interpret = _interpret_default()
    if stochastic and key is None:
        key = jax.random.PRNGKey(0)   # ds_quantizer API parity: key optional
    if stochastic and (pltpu is None or interpret):
        # interpreter mode has no TPU PRNG — use the jnp path
        return quantize_jnp(x, bits, groups, sym, stochastic=True, key=key)
    orig_shape = x.shape
    numel = int(np.prod(orig_shape))
    if numel % groups != 0:
        raise ValueError(f"numel {numel} not divisible by groups {groups}")
    n = numel // groups
    flat = x.reshape(groups, n)
    if key is None:
        seed = jnp.zeros((1, 1), jnp.int32)
    else:
        seed = jax.random.key_data(key).reshape(-1)[:1].astype(
            jnp.int32).reshape(1, 1)
    kernel = functools.partial(_quant_kernel, bits=bits, sym=sym,
                               stochastic=stochastic)
    out = pl.pallas_call(
        kernel,
        grid=(groups,),
        in_specs=[pl.BlockSpec((1, 1), lambda g: (0, 0)),
                  pl.BlockSpec((1, n), lambda g: (g, 0))],
        out_specs=pl.BlockSpec((1, n), lambda g: (g, 0)),
        out_shape=jax.ShapeDtypeStruct((groups, n), x.dtype),
        interpret=interpret,
    )(seed, flat)
    return out.reshape(orig_shape)


def quantize_packed(x, bits=8, groups=1, sym=True):
    """Storage quantization: → (int8 codes, fp32 scales[, fp32 zeros]) for
    int8 serving (the inference-kernel weight format)."""
    assert bits <= 8
    flat = x.reshape(groups, -1).astype(jnp.float32)
    scale, zero = _qparams(flat, bits, sym)
    if sym:
        qmax = 2.0 ** (bits - 1) - 1
        q = jnp.clip(jnp.round(flat / scale), -qmax - 1, qmax)
        return q.astype(jnp.int8), scale, None
    # asymmetric codes span [0, 2^bits-1] — unsigned storage
    levels = 2.0 ** bits - 1
    q = jnp.clip(jnp.round((flat - zero) / scale), 0, levels)
    return q.astype(jnp.uint8), scale, zero


def dequantize_packed(q, scale, zero, shape, dtype=jnp.float32):
    flat = q.astype(jnp.float32) * scale
    if zero is not None:
        flat = flat + zero
    return flat.reshape(shape).astype(dtype)
