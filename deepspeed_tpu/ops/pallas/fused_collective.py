"""Tile-granularity fused matmul+collective kernels (ISSUE 8).

The stage3_prefetch pipeline (parallel/prefetch.py) overlaps parameter
gathers with compute at LAYER granularity: layer i+1's packed shards
ride the ring while layer i computes, but layer i's own first GEMM
still waits on its full all-gather, and backward's per-layer grad
reduce-scatters serialize against the same ring. T3 (arxiv 2401.16677)
and the fused computation-collective work (arxiv 2305.06942) show the
remaining win comes from TILE granularity: decompose the ring
collective into its per-chunk hops and interleave them with the GEMM's
own k/m-loop, so each hop hides inside the matmul tile it feeds. This
module is that decomposition, three ways:

  * ``all_gather_matmul`` — ``y = x @ W_full`` where ``W`` rests as a
    ZeRO-3 shard: each ring step computes the GEMM tile over the chunk
    already on-device while the next chunk is in flight. When the
    shard cuts W's contraction dim the chunk GEMMs accumulate
    (``y += x[:, c] @ W_c``, fp32); when it cuts the output dim they
    assemble output column blocks. ``transpose_w`` serves the backward
    ``dx = dy @ W^T`` from the SAME resting shard — no transposed copy.
  * ``matmul_reduce_scatter`` — the param-grad transpose:
    ``dW_shard = RS_axis(lhs^T @ rhs)`` as a ring of partial-block
    GEMMs. Each step computes the [*, chunk] partial destined for one
    device and ring-shifts the running accumulation, so every device
    ends holding ONLY its reduced output shard — the full [K, N]
    gradient never materializes.
  * ``collective_matmul`` — the custom-VJP pairing of the two: forward
    all-gather+matmul, backward matmul+reduce-scatter for dW (shard-
    shaped, already SUMMED over the axis) and a transposed
    all-gather+matmul for dx.

Each op has two interchangeable lowerings, chosen per call:

  backend="fused"  one ``pallas_call`` per GEMM: grid (ring_step,
                   m_tile), the next chunk ppermutes via in-kernel
                   RDMA (``make_async_remote_copy`` + a neighbor
                   credit semaphore) while the current chunk's tiles
                   multiply. Interpret-mode runs on CPU for numerics;
                   Mosaic lowering of ppermute-inside-pallas is
                   real-chip-gated (ROADMAP axon backlog).
  backend="lax"    the decomposed-ring reference: the same chunk
                   schedule as ``lax.ppermute`` hops + per-chunk
                   ``dot_general`` tiles, valid on any mesh/dtype —
                   the fallback for shapes the kernel doesn't cover
                   and the CPU-proxy bench path.

Everything here is pure, jit-able, and must run INSIDE ``shard_map``
binding ``axis_name``. Ring schedules mirror parallel/overlap.py
(chunk k lands on device k), so layouts compose with the prefetch
pipeline's ring mode; numerics match a single ``jnp.einsum`` to fp32
partial-sum rounding (pinned by tests/test_fused_collective.py).
"""

import dataclasses
import functools
import threading
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


# ---------------------------------------------------------------------------
# config + trace-scoped context
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RingHierarchy:
    """Two-level split of the collective axis for link-aware lowering
    (ISSUE 16): the flat ring of ``inter * intra`` devices becomes
    ``inter`` slow-link blocks (DCN-class, ``inter_axis``) of ``intra``
    fast-link devices (ICI-class, ``intra_axis``) each. Frozen/hashable
    so it can ride `CollectiveMatmulConfig` through the custom-VJP
    builder cache. Axis names must be bound by the enclosing shard_map
    (the `mesh.split_data_axis` view); the flat data axis name does NOT
    exist on that mesh, so a hierarchical call never touches it."""
    inter_axis: str
    intra_axis: str
    inter: int
    intra: int


@dataclasses.dataclass(frozen=True)
class CollectiveMatmulConfig:
    """Static per-train-fn configuration (hashable: keys custom-VJP
    builder caches and rides the trace-scoped gather context).

    ``backend``: "auto" (fused on TPU, lax elsewhere) | "fused" | "lax".
    ``tile_m``: requested m-tile of the fused kernel's grid (clamped to
    a divisor of the actual M).
    ``min_shard_bytes``: a weight qualifies for fused consumption only
    when its per-device shard is at least this large — below it the
    packed layer-gather of prefetch ring mode is cheaper than n chunk
    GEMMs.
    ``interpret``: force pallas interpret mode (None = auto: interpret
    everywhere except a real TPU backend).
    ``vmem_budget_bytes``: ceiling on the contracting kernel's chunk
    stash (it holds the FULL weight in VMEM — see _ag_matmul_fused);
    bigger weights take the lax ring under backend="auto".
    ``hierarchy``: optional two-level split — when set, both collective
    ops run the link-aware schedule (ONE inter-block hop per operand,
    the per-block ring over the fast axis; see _hier_ag_matmul) and the
    per-block intra rings run the lax decomposed ring regardless of
    ``backend`` (pallas remote DMA cannot address a two-named-axis
    env — see _sub_cfg)."""
    axis_name: str = "data"
    axis_size: int = 1
    backend: str = "auto"
    tile_m: int = 128
    min_shard_bytes: int = 1 << 16
    interpret: Optional[bool] = None
    vmem_budget_bytes: int = 8 << 20
    hierarchy: Optional[RingHierarchy] = None


class _CtxState(threading.local):
    def __init__(self):
        self.stack = []


_ctx_state = _CtxState()


class gather_scope:
    """Trace-scoped activation of fused gather+matmul consumption: while
    entered, models whose dense layers are collective-matmul-aware
    (models/gpt2.py CollectiveDense) treat a shard-shaped kernel in
    their param tree as a ZeRO-3 resting shard and feed it to
    ``collective_matmul`` instead of a materialized full weight. The
    prefetch pipeline enters it exactly around its per-layer body
    invocations (forward and backward-vjp traces) — like
    mesh_lib.layout_pins, this is a Python-call-scoped fact, reliable
    wherever jax re-traces the body. Re-entrant; innermost wins."""

    def __init__(self, cfg: Optional[CollectiveMatmulConfig]):
        self.cfg = cfg

    def __enter__(self):
        _ctx_state.stack.append(self.cfg)
        return self

    def __exit__(self, *exc):
        _ctx_state.stack.pop()
        return False


def gather_ctx() -> Optional[CollectiveMatmulConfig]:
    """The active fused-gather config, or None outside the prefetch
    pipeline's fused_matmul body traces."""
    stack = _ctx_state.stack
    return stack[-1] if stack else None


def infer_shard_dim(shard_shape, in_dim: int, features: int,
                    axis_size: int) -> Optional[int]:
    """Which dim of a [in_dim, features] weight a shard cuts: 0, 1, or
    None when ``shard_shape`` IS the full shape (not a shard). Raises
    on a shape that is neither — a wiring bug, not a fallback case."""
    if tuple(shard_shape) == (in_dim, features):
        return None
    if in_dim % axis_size == 0 and \
            tuple(shard_shape) == (in_dim // axis_size, features):
        return 0
    if features % axis_size == 0 and \
            tuple(shard_shape) == (in_dim, features // axis_size):
        return 1
    raise ValueError(
        f"kernel value of shape {tuple(shard_shape)} is neither the full "
        f"({in_dim}, {features}) weight nor its 1/{axis_size} shard on "
        f"either dim")


# ---------------------------------------------------------------------------
# shared ring arithmetic
# ---------------------------------------------------------------------------

def _ring_perm(n):
    return [(j, (j + 1) % n) for j in range(n)]


def _divisor_tile(m: int, requested: int) -> int:
    """Largest divisor of ``m`` that is <= requested (>=1): the fused
    kernels require the grid to tile M exactly."""
    t = max(1, min(int(requested), m))
    while m % t:
        t -= 1
    return t


def _breadcrumb(op, site, backend, **fields):
    # trace-time only (dispatch runs once per compile, never per step)
    from deepspeed_tpu.telemetry.recorder import default_recorder
    default_recorder().record("collective_matmul", op=op, site=site,
                              backend=backend, **fields)


# ---------------------------------------------------------------------------
# lax decomposed-ring reference path
# ---------------------------------------------------------------------------

def _ag_matmul_lax(x, w_shard, *, contracting, transpose_w, axis_name, n,
                   out_dtype, precision=None):
    """Decomposed-ring all-gather+matmul: chunk held at ring step s is
    chunk id (axis_index - s) mod n (the overlap.ring_all_gather
    schedule); its GEMM tile issues while the next hop is in flight —
    per-chunk dots with no data dependency between hop s+1 and tile s,
    so XLA's latency-hiding scheduler floats the ppermutes over the
    MXU work."""
    idx = jax.lax.axis_index(axis_name)
    perm = _ring_perm(n)
    m = x.shape[0]
    cdim = 1 if transpose_w else 0          # chunked dim of the dot's rhs
    dnums = (((1,), (cdim,)), ((), ()))
    chunk = w_shard
    if contracting:
        ck = w_shard.shape[1] if transpose_w else w_shard.shape[0]
        n_out = w_shard.shape[0] if transpose_w else w_shard.shape[1]
        acc = jnp.zeros((m, n_out), jnp.float32)
        for s in range(n):
            c = jax.lax.rem(idx - s + n, n)
            xs = jax.lax.dynamic_slice_in_dim(x, c * ck, ck, axis=1)
            acc = acc + jax.lax.dot_general(
                xs, chunk, dnums, preferred_element_type=jnp.float32,
                precision=precision)
            if s < n - 1:
                chunk = jax.lax.ppermute(chunk, axis_name, perm)
        return acc.astype(out_dtype)
    ck_out = w_shard.shape[0] if transpose_w else w_shard.shape[1]
    out = jnp.zeros((m, n * ck_out), out_dtype)
    for s in range(n):
        c = jax.lax.rem(idx - s + n, n)
        blk = jax.lax.dot_general(
            x, chunk, dnums, preferred_element_type=jnp.float32,
            precision=precision).astype(out_dtype)
        out = jax.lax.dynamic_update_slice_in_dim(out, blk, c * ck_out,
                                                  axis=1)
        if s < n - 1:
            chunk = jax.lax.ppermute(chunk, axis_name, perm)
    return out


def _mm_rs_lax(lhs, rhs, *, chunk_lhs, axis_name, n, precision=None):
    """Decomposed-ring matmul+reduce-scatter: the partial for chunk k
    is born on device (k+1) mod n as a chunk GEMM and accumulates one
    local partial per hop until it lands on device k — the
    overlap.ring_reduce_scatter schedule with the pack/GEMM fused, so
    the full [K, N] product never materializes. Returns this device's
    fp32 shard, SUMMED over the axis."""
    idx = jax.lax.axis_index(axis_name)
    perm = _ring_perm(n)
    ck = (lhs.shape[1] if chunk_lhs else rhs.shape[1]) // n
    dnums = (((0,), (0,)), ((), ()))

    def partial(c):
        if chunk_lhs:
            ls = jax.lax.dynamic_slice_in_dim(lhs, c * ck, ck, axis=1)
            return jax.lax.dot_general(
                ls, rhs, dnums, preferred_element_type=jnp.float32,
                precision=precision)
        rs = jax.lax.dynamic_slice_in_dim(rhs, c * ck, ck, axis=1)
        return jax.lax.dot_general(
            lhs, rs, dnums, preferred_element_type=jnp.float32,
            precision=precision)

    carry = partial(jax.lax.rem(idx - 1 + n, n))
    for s in range(1, n):
        carry = jax.lax.ppermute(carry, axis_name, perm)
        carry = carry + partial(jax.lax.rem(idx - 1 - s + 2 * n, n))
    return carry


# ---------------------------------------------------------------------------
# fused pallas kernels (ring RDMA inside the GEMM grid)
# ---------------------------------------------------------------------------
#
# Both kernels share the grid shape (ring_step s, m_tile i) and the
# neighbor-credit protocol that makes the 2-slot comm buffer race-free:
#
#   * a chunk ppermutes right (device i -> i+1) via make_async_remote_copy
#     into alternating slots (step s lives in slot s % 2);
#   * before sending into the right neighbor's slot, a device waits ONE
#     credit on a counting semaphore; the neighbor signals that credit
#     only after it has (a) finished every GEMM tile that read the slot
#     being recycled and (b) seen its own send out of that slot complete
#     (wait_send) — without (b), an in-flight send's source could be
#     overwritten by the incoming copy (the classic 2-slot WAR race);
#   * signals and waits are balanced exactly (n-2 of each), so the
#     scratch semaphores drain to zero by kernel exit;
#   * interpret mode SKIPS the credit exchange (a Python-level gate, not
#     a traced branch): the interpreter executes the remote copies
#     synchronously so the WAR race cannot occur, and its discharge
#     rules do not implement remote semaphore_signal. The credit path is
#     therefore Mosaic-only — verified with the real-chip parity test
#     (ROADMAP axon backlog), like the rest of the Mosaic lowering.

def _ag_matmul_fused(x, w_shard, *, contracting, transpose_w, axis_name,
                     n, tile_m, interpret, out_dtype, precision=None):
    m, k_x = x.shape
    ck_w = tuple(w_shard.shape)
    tile = _divisor_tile(m, tile_m)
    mt = m // tile
    cdim = 1 if transpose_w else 0
    dnums = (((1,), (cdim,)), ((), ()))
    idx = jax.lax.axis_index(axis_name)
    order = jax.lax.rem(idx - jnp.arange(n, dtype=jnp.int32) + n, n)

    if contracting:
        # Chunks CONTRACT (y += x[:, c] @ W_c): the output block must
        # accumulate across ring steps, so the grid runs (m_tile, step)
        # with steps INNERMOST — the out block stays VMEM-resident over
        # its consecutive revisits (the canonical pallas accumulation
        # pattern; an aliased HBM round-trip is NOT interpretable, jax
        # b/370563936). The ring completes during the first m-tile's
        # step sweep into a per-chunk stash (each slot written exactly
        # once — no credit protocol needed); later m-tiles replay the
        # chunk GEMMs from the stash. VMEM holds the full stashed W: the
        # dispatcher falls back to the lax ring when that exceeds the
        # configured budget.
        ck_x = ck_w[1] if transpose_w else ck_w[0]
        n_out = ck_w[0] if transpose_w else ck_w[1]
        out_shape = (m, n_out)

        def kernel(order_ref, x_ref, w_ref, o_ref, stash,
                   send_sem, recv_sem):
            i = pl.program_id(0)
            s = pl.program_id(1)
            my = jax.lax.axis_index(axis_name)
            right = jax.lax.rem(my + 1, n)

            def hop(step):
                return pltpu.make_async_remote_copy(
                    src_ref=stash.at[step], dst_ref=stash.at[step + 1],
                    send_sem=send_sem.at[step],
                    recv_sem=recv_sem.at[step + 1],
                    device_id=right,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)

            @pl.when(i == 0)
            def _():
                @pl.when(s == 0)
                def _():
                    stash[0] = w_ref[:]

                @pl.when(s > 0)
                def _():
                    hop(s - 1).wait_recv()      # chunk for step s landed

                # drain send semaphores two steps behind (send s-1 is
                # usually still flying under step s's GEMM) plus the
                # final one at the last step — n-1 sends, n-1 waits
                @pl.when(s > 1)
                def _():
                    hop(s - 2).wait_send()

                # forward the chunk while its GEMM tile runs below
                @pl.when(s < n - 1)
                def _():
                    hop(s).start()

                @pl.when(s == n - 1)
                def _():
                    hop(n - 2).wait_send()

            tile_out = jax.lax.dot_general(
                x_ref[:], stash[s], dnums,
                preferred_element_type=jnp.float32, precision=precision)

            @pl.when(s == 0)
            def _():
                o_ref[:] = tile_out

            @pl.when(s > 0)
            def _():
                o_ref[:] = o_ref[:] + tile_out

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(mt, n),
            in_specs=[
                pl.BlockSpec((tile, ck_x),
                             lambda i, s, order: (i, order[s])),
                pl.BlockSpec(ck_w, lambda i, s, order: (0, 0)),
            ],
            out_specs=pl.BlockSpec((tile, n_out),
                                   lambda i, s, order: (i, 0)),
            scratch_shapes=[
                pltpu.VMEM((n,) + ck_w, w_shard.dtype),
                pltpu.SemaphoreType.DMA((n,)),
                pltpu.SemaphoreType.DMA((n,)),
            ])
        y = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct(out_shape, jnp.float32),
            compiler_params=pltpu.TPUCompilerParams(
                dimension_semantics=("arbitrary", "arbitrary"),
                collective_id=0),
            interpret=interpret)(order, x, w_shard)
        return y.astype(out_dtype)

    # Chunks produce OUTPUT COLUMN BLOCKS (y[:, c] = x @ W_c): no
    # accumulation, so the grid runs (step, m_tile) with the 2-slot
    # comm buffer + neighbor-credit protocol — maximum overlap (the
    # hop for step s+1 flies under ALL of step s's m-tiles) at 2-chunk
    # VMEM cost.
    ck_out = ck_w[0] if transpose_w else ck_w[1]
    out_shape = (m, n * ck_out)

    def kernel(order_ref, x_ref, w_ref, o_ref, comm, send_sem,
               recv_sem, credit_sem):
        s = pl.program_id(0)
        i = pl.program_id(1)
        last_i = pl.num_programs(1) - 1
        my = jax.lax.axis_index(axis_name)
        right = jax.lax.rem(my + 1, n)
        left = jax.lax.rem(my + n - 1, n)
        cur = jax.lax.rem(s, 2)
        nxt = jax.lax.rem(s + 1, 2)

        def hop(src_slot, dst_slot):
            return pltpu.make_async_remote_copy(
                src_ref=comm.at[src_slot], dst_ref=comm.at[dst_slot],
                send_sem=send_sem.at[src_slot],
                recv_sem=recv_sem.at[dst_slot],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)

        @pl.when(i == 0)
        def _():
            @pl.when(s == 0)
            def _():
                comm[0] = w_ref[:]

            @pl.when(s > 0)
            def _():
                hop(nxt, cur).wait_recv()   # chunk c(s) has landed

            @pl.when(s < n - 1)
            def _():
                if not interpret:
                    @pl.when(s > 0)
                    def _():
                        # right neighbor recycled the slot we target
                        pltpu.semaphore_wait(credit_sem, 1)
                hop(cur, nxt).start()

        o_ref[:] = jax.lax.dot_general(
            x_ref[:], comm[cur], dnums,
            preferred_element_type=jnp.float32,
            precision=precision).astype(o_ref.dtype)

        @pl.when(jnp.logical_and(i == last_i, s < n - 1))
        def _():
            hop(cur, nxt).wait_send()
            if not interpret:
                @pl.when(s < n - 2)
                def _():
                    pltpu.semaphore_signal(
                        credit_sem, 1, device_id=left,
                        device_id_type=pltpu.DeviceIdType.LOGICAL)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n, mt),
        in_specs=[
            pl.BlockSpec((tile, k_x), lambda s, i, order: (i, 0)),
            pl.BlockSpec(ck_w, lambda s, i, order: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tile, ck_out),
                               lambda s, i, order: (i, order[s])),
        scratch_shapes=[
            pltpu.VMEM((2,) + ck_w, w_shard.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ])
    y = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(out_shape, out_dtype),
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
            collective_id=0),
        interpret=interpret)(order, x, w_shard)
    return y


def _mm_rs_fused(lhs, rhs, *, chunk_lhs, axis_name, n, tile_m, interpret,
                 precision=None):
    m = lhs.shape[0]
    tile = _divisor_tile(m, tile_m)
    mt = m // tile
    ck = (lhs.shape[1] if chunk_lhs else rhs.shape[1]) // n
    if chunk_lhs:
        out_shape = (ck, rhs.shape[1])
    else:
        out_shape = (lhs.shape[1], ck)
    dnums = (((0,), (0,)), ((), ()))

    def kernel(order_ref, lhs_ref, rhs_ref, o_ref, acc, comm,
               send_sem, recv_sem, credit_sem):
        s = pl.program_id(0)
        i = pl.program_id(1)
        last_i = pl.num_programs(1) - 1
        my = jax.lax.axis_index(axis_name)
        right = jax.lax.rem(my + 1, n)
        left = jax.lax.rem(my + n - 1, n)
        cur = jax.lax.rem(s, 2)
        nxt = jax.lax.rem(s + 1, 2)

        def hop(src_slot, dst_slot):
            return pltpu.make_async_remote_copy(
                src_ref=comm.at[src_slot], dst_ref=comm.at[dst_slot],
                send_sem=send_sem.at[src_slot],
                recv_sem=recv_sem.at[dst_slot],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)

        part = jax.lax.dot_general(
            lhs_ref[:], rhs_ref[:], dnums,
            preferred_element_type=jnp.float32, precision=precision)

        @pl.when(i == 0)
        def _():
            acc[:] = part

        @pl.when(i > 0)
        def _():
            acc[:] = acc[:] + part

        # the carry hop for step s flew while this step's tiles above
        # were multiplying — combine and forward only at the tail
        @pl.when(i == last_i)
        def _():
            @pl.when(s == 0)
            def _():
                comm[0] = acc[:]

            @pl.when(jnp.logical_and(s > 0, s < n - 1))
            def _():
                hop(nxt, cur).wait_recv()
                comm[cur] = comm[cur] + acc[:]

            @pl.when(s < n - 1)
            def _():
                if not interpret:
                    @pl.when(s > 0)
                    def _():
                        pltpu.semaphore_wait(credit_sem, 1)
                hop(cur, nxt).start()
                # the carry is small (1/n of the gather bytes): waiting
                # the send here, inside the step tail, keeps the 2-slot
                # credit accounting simple at the cost of overlapping
                # only the RECV side of the carry hop with step s+1
                hop(cur, nxt).wait_send()
                if not interpret:
                    @pl.when(s < n - 2)
                    def _():
                        pltpu.semaphore_signal(
                            credit_sem, 1, device_id=left,
                            device_id_type=pltpu.DeviceIdType.LOGICAL)

            @pl.when(s == n - 1)
            def _():
                hop(nxt, cur).wait_recv()
                o_ref[:] = comm[cur] + acc[:]

    if chunk_lhs:
        in_specs = [
            pl.BlockSpec((tile, ck), lambda s, i, order: (i, order[s])),
            pl.BlockSpec((tile, rhs.shape[1]), lambda s, i, order: (i, 0)),
        ]
    else:
        in_specs = [
            pl.BlockSpec((tile, lhs.shape[1]), lambda s, i, order: (i, 0)),
            pl.BlockSpec((tile, ck), lambda s, i, order: (i, order[s])),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n, mt),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(out_shape, lambda s, i, order: (0, 0)),
        scratch_shapes=[
            pltpu.VMEM(out_shape, jnp.float32),          # acc
            pltpu.VMEM((2,) + out_shape, jnp.float32),   # ring carry
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ])
    idx = jax.lax.axis_index(axis_name)
    order = jax.lax.rem(idx - 1 - jnp.arange(n, dtype=jnp.int32) + 2 * n, n)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(out_shape, jnp.float32),
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
            collective_id=1),
        interpret=interpret)(order, lhs, rhs)


# ---------------------------------------------------------------------------
# two-level link-aware lowering (ISSUE 16)
# ---------------------------------------------------------------------------
#
# The flat ring pays every hop equally; on a multi-host slice ni of the
# n ring edges are DCN-class, so a shard of c bytes costs an AVERAGE of
# (n-1)·c·ni/n slow-link bytes per device. The two-level schedule pays
# the slow links exactly once per operand: one lax.all_gather of the
# RAW resting shard over the inter axis ((ni-1)·c slow bytes), then ni
# per-block invocations of the flat dispatch over the intra axis — the
# existing lax/pallas lowerings (and their backend="auto" feasibility
# gates) serve each block unchanged. Block b of the full weight is the
# contiguous run of intra-ring shards from inter group b, because the
# split mesh is row-major (data index = inter_index·intra +
# intra_index) — so per-block results concatenate (non-contracting) or
# accumulate (contracting) in natural order and numerics match the
# flat ring to fp32 partial-sum ordering.

def _sub_cfg(cfg: CollectiveMatmulConfig, h: RingHierarchy):
    # the per-block intra ring runs with BOTH split axes bound in the
    # shard_map axis env, and pallas remote DMA (dma_start with LOGICAL
    # device ids) refuses a >1-named-axis env in this jax version — so
    # the intra hop always takes the lax decomposed ring; "fused" under
    # a hierarchy means fused-at-the-flat-level only
    return dataclasses.replace(cfg, axis_name=h.intra_axis,
                               axis_size=h.intra, hierarchy=None,
                               backend="lax")


def _hier_ag_matmul(x2, w_shard, *, h, shard_dim, contracting,
                    transpose_w, cfg, out_dtype, precision, site):
    ni, k = h.inter, h.intra
    sub = _sub_cfg(cfg, h)
    # ONE slow hop: the ni same-intra-position shards; stacked[b] is the
    # intra-position-t shard of full-weight block b
    stacked = jax.lax.all_gather(w_shard, h.inter_axis)
    _breadcrumb("all_gather_matmul", site, "two_level", fallback=None,
                m=int(x2.shape[0]), shard_shape=tuple(w_shard.shape),
                shard_dim=int(shard_dim), transpose_w=bool(transpose_w),
                contracting=bool(contracting), inter=ni, intra=k)
    if contracting:
        ck = w_shard.shape[1] if transpose_w else w_shard.shape[0]
        acc = None
        for b in range(ni):
            xs = jax.lax.slice_in_dim(x2, b * k * ck, (b + 1) * k * ck,
                                      axis=1)
            y = all_gather_matmul(xs, stacked[b], shard_dim=shard_dim,
                                  axis_name=h.intra_axis, axis_size=k,
                                  transpose_w=transpose_w, cfg=sub,
                                  out_dtype=jnp.float32,
                                  precision=precision,
                                  site=site + f"/blk{b}")
            acc = y if acc is None else acc + y
        return acc.astype(out_dtype)
    blocks = [all_gather_matmul(x2, stacked[b], shard_dim=shard_dim,
                                axis_name=h.intra_axis, axis_size=k,
                                transpose_w=transpose_w, cfg=sub,
                                out_dtype=out_dtype, precision=precision,
                                site=site + f"/blk{b}")
              for b in range(ni)]
    return jnp.concatenate(blocks, axis=1)


def _hier_mm_rs(l2, r2, *, h, shard_dim, cfg, precision, site):
    from deepspeed_tpu.parallel import overlap
    ni, k = h.inter, h.intra
    sub = _sub_cfg(cfg, h)
    chunk_lhs = shard_dim == 0
    _breadcrumb("matmul_reduce_scatter", site, "two_level", fallback=None,
                m=int(l2.shape[0]), k=int(l2.shape[1]),
                nn=int(r2.shape[1]), shard_dim=int(shard_dim),
                inter=ni, intra=k)
    blk = (l2.shape[1] if chunk_lhs else r2.shape[1]) // ni
    parts = []
    for b in range(ni):
        if chunk_lhs:
            ls = jax.lax.slice_in_dim(l2, b * blk, (b + 1) * blk, axis=1)
            p = matmul_reduce_scatter(ls, r2, shard_dim=0,
                                      axis_name=h.intra_axis, axis_size=k,
                                      cfg=sub, precision=precision,
                                      site=site + f"/blk{b}")
        else:
            rs = jax.lax.slice_in_dim(r2, b * blk, (b + 1) * blk, axis=1)
            p = matmul_reduce_scatter(l2, rs, shard_dim=1,
                                      axis_name=h.intra_axis, axis_size=k,
                                      cfg=sub, precision=precision,
                                      site=site + f"/blk{b}")
        parts.append(p)
    piece_shape = parts[0].shape
    stack = jnp.stack([p.reshape(-1) for p in parts])   # [ni, piece]
    # exact fp32 slow hop: device's inter index keeps its own block's
    # piece, summed over the ni host groups
    out = overlap.ring_reduce_scatter(stack.reshape(-1), h.inter_axis, ni)
    return out.reshape(piece_shape)


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

def _resolve(cfg: Optional[CollectiveMatmulConfig]):
    cfg = cfg or CollectiveMatmulConfig()
    backend = cfg.backend
    if backend == "auto":
        backend = "fused" if jax.default_backend() == "tpu" else "lax"
    if backend not in ("fused", "lax"):
        raise ValueError(f"collective_matmul backend must be 'auto', "
                         f"'fused' or 'lax', got {cfg.backend!r}")
    interpret = cfg.interpret
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return cfg, backend, bool(interpret)


def _as_2d(x):
    return x.reshape(-1, x.shape[-1])


def _ag_auto_fallback(cfg, shard_shape, itemsize, contracting, n,
                      interpret):
    """Why backend="auto" must route this all-gather+matmul through the
    lax ring instead of the pallas kernel, or None when the kernel is
    feasible. Pure (host ints only) so the gates are unit-testable off
    a TPU."""
    full_w_bytes = int(np.prod(shard_shape)) * n * itemsize
    if contracting and full_w_bytes > cfg.vmem_budget_bytes:
        # the contracting kernel stashes the full gathered W in VMEM
        # (interpret-safe accumulation; see _ag_matmul_fused)
        return "vmem_budget"
    if not contracting and 2 * (full_w_bytes // n) > cfg.vmem_budget_bytes:
        # the non-contracting kernel's ring carry is 2 chunk-sized comm
        # slots (the (2,)+ck_w VMEM scratch in _ag_matmul_fused)
        return "vmem_budget"
    if not interpret and (shard_shape[-1] % 128 or shard_shape[0] % 128):
        # Mosaic lane alignment: BOTH shard dims appear as a block
        # minor somewhere across the fwd/bwd kernel family (e.g. a
        # dim-0 shard's ck is the x-block minor in the contracting
        # forward and the output-block minor in the transposed dx) —
        # unaligned minors lower poorly or not at all on real hardware
        return "lane_alignment"
    return None


def _rs_auto_fallback(cfg, k, nn, chunk_lhs, n, interpret):
    """matmul+reduce-scatter twin of ``_ag_auto_fallback``: acc + the
    2 carry slots are all fp32 shard-sized VMEM scratch."""
    shard_bytes = (k // n) * nn * 4 if chunk_lhs else k * (nn // n) * 4
    if 3 * shard_bytes > cfg.vmem_budget_bytes:
        return "vmem_budget"
    # block minors: the chunked operand's ck and the un-chunked minor
    minors = (k // n, nn) if chunk_lhs else (k, nn // n)
    if not interpret and any(m % 128 for m in minors):
        return "lane_alignment"
    return None


def all_gather_matmul(x, w_shard, *, shard_dim, axis_name, axis_size,
                      transpose_w=False, cfg=None, out_dtype=None,
                      precision=None, site="unsited"):
    """``x @ W_full`` (or ``x @ W_full^T``) where ``W`` rests as this
    device's 1/n shard cut on ``shard_dim`` — the all-gather decomposed
    into ring chunks interleaved with the GEMM tiles they feed. Must
    run inside shard_map binding ``axis_name``. ``x``: [..., K]; output
    [..., N]. fp32 accumulation, output in ``out_dtype`` (default
    ``x.dtype``)."""
    out_dtype = out_dtype or x.dtype
    n = int(axis_size)
    lead = x.shape[:-1]
    x2 = _as_2d(x)
    if n == 1:
        dnums = (((1,), (1 if transpose_w else 0,)), ((), ()))
        y = jax.lax.dot_general(
            x2, w_shard, dnums, preferred_element_type=jnp.float32,
            precision=precision).astype(out_dtype)
        return y.reshape(lead + (y.shape[-1],))
    contracting = (shard_dim == 0) != bool(transpose_w)
    if cfg is not None and cfg.hierarchy is not None:
        h = cfg.hierarchy
        assert h.inter * h.intra == n, (h, n)
        y = _hier_ag_matmul(x2, w_shard, h=h, shard_dim=shard_dim,
                            contracting=contracting,
                            transpose_w=transpose_w, cfg=cfg,
                            out_dtype=out_dtype, precision=precision,
                            site=site)
        return y.reshape(lead + (y.shape[-1],))
    cfg, backend, interpret = _resolve(cfg)
    fallback = None
    if backend == "fused" and cfg.backend == "auto":
        # feasibility gates for the auto-chosen kernel lowering; a
        # forced backend="fused" is trusted (and will fail loudly)
        fallback = _ag_auto_fallback(cfg, tuple(w_shard.shape),
                                     jnp.dtype(w_shard.dtype).itemsize,
                                     contracting, n, interpret)
        if fallback:
            backend = "lax"
    _breadcrumb("all_gather_matmul", site, backend, fallback=fallback,
                m=int(x2.shape[0]), shard_shape=tuple(w_shard.shape),
                shard_dim=int(shard_dim), transpose_w=bool(transpose_w),
                contracting=bool(contracting), axis_size=n)
    if backend == "fused":
        y = _ag_matmul_fused(x2, w_shard, contracting=contracting,
                             transpose_w=transpose_w, axis_name=axis_name,
                             n=n, tile_m=cfg.tile_m, interpret=interpret,
                             out_dtype=out_dtype, precision=precision)
    else:
        y = _ag_matmul_lax(x2, w_shard, contracting=contracting,
                           transpose_w=transpose_w, axis_name=axis_name,
                           n=n, out_dtype=out_dtype, precision=precision)
    return y.reshape(lead + (y.shape[-1],))


def matmul_reduce_scatter(lhs, rhs, *, shard_dim, axis_name, axis_size,
                          cfg=None, precision=None, site="unsited"):
    """This device's shard of ``sum_over_axis(lhs^T @ rhs)`` — the
    param-grad GEMM fused with its ring reduce-scatter, partial
    accumulations ring-shifting between chunk GEMMs so the full
    product never materializes. ``lhs``: [..., K]; ``rhs``: [..., N];
    returns fp32 [K/n, N] (shard_dim 0) or [K, N/n] (shard_dim 1),
    SUMMED (not meaned) over the axis. Must run inside shard_map."""
    n = int(axis_size)
    l2, r2 = _as_2d(lhs), _as_2d(rhs)
    if n == 1:
        return jax.lax.dot_general(
            l2, r2, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
    chunk_lhs = shard_dim == 0
    if cfg is not None and cfg.hierarchy is not None:
        h = cfg.hierarchy
        assert h.inter * h.intra == n, (h, n)
        return _hier_mm_rs(l2, r2, h=h, shard_dim=shard_dim, cfg=cfg,
                           precision=precision, site=site)
    cfg, backend, interpret = _resolve(cfg)
    fallback = None
    if backend == "fused" and cfg.backend == "auto":
        fallback = _rs_auto_fallback(cfg, int(l2.shape[1]),
                                     int(r2.shape[1]), chunk_lhs, n,
                                     interpret)
        if fallback:
            backend = "lax"
    _breadcrumb("matmul_reduce_scatter", site, backend, fallback=fallback,
                m=int(l2.shape[0]), k=int(l2.shape[1]), nn=int(r2.shape[1]),
                shard_dim=int(shard_dim), axis_size=n)
    if backend == "fused":
        return _mm_rs_fused(l2, r2, chunk_lhs=chunk_lhs,
                            axis_name=axis_name, n=n, tile_m=cfg.tile_m,
                            interpret=interpret, precision=precision)
    return _mm_rs_lax(l2, r2, chunk_lhs=chunk_lhs, axis_name=axis_name,
                      n=n, precision=precision)


# ---------------------------------------------------------------------------
# the fused dense op (custom VJP): forward AG+matmul, backward
# matmul+RS for dW and transposed AG+matmul for dx
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _collective_matmul_fn(shard_dim, axis_name, axis_size, cfg, site,
                          precision=None):
    @jax.custom_vjp
    def f(x, w_shard):
        return all_gather_matmul(x, w_shard, shard_dim=shard_dim,
                                 axis_name=axis_name, axis_size=axis_size,
                                 cfg=cfg, precision=precision, site=site)

    def fwd(x, w_shard):
        return f(x, w_shard), (x, w_shard)

    def bwd(res, dy):
        x, w_shard = res
        # dx = dy @ W^T from the SAME resting shard (no transposed copy)
        dx = all_gather_matmul(dy, w_shard, shard_dim=shard_dim,
                               axis_name=axis_name, axis_size=axis_size,
                               transpose_w=True, cfg=cfg,
                               out_dtype=x.dtype, precision=precision,
                               site=site + "/dx")
        # dW shard = RS_axis(x^T @ dy): already reduce-scattered and
        # SUMMED over the axis (the caller normalizes to a mean), the
        # contract parallel/prefetch.py's sharded-leaf grads follow
        dw = matmul_reduce_scatter(x, dy, shard_dim=shard_dim,
                                   axis_name=axis_name,
                                   axis_size=axis_size, cfg=cfg,
                                   precision=precision,
                                   site=site + "/dw")
        return dx.reshape(x.shape), dw.astype(w_shard.dtype)

    f.defvjp(fwd, bwd)
    return f


def collective_matmul(x, w_shard, *, shard_dim, axis_name, axis_size,
                      cfg=None, precision=None, site="unsited"):
    """Differentiable fused dense op over a ZeRO-3 resting shard: the
    forward gathers W through the GEMM it feeds; the backward routes
    dW through matmul+reduce-scatter (returning the shard-shaped SUM
    over the axis — NOT the full gradient) and dx through a transposed
    all-gather+matmul. The param-grad contract matches the prefetch
    pipeline's sharded leaves (caller scales by 1/n for the mean)."""
    cfg = cfg or CollectiveMatmulConfig(axis_name=axis_name,
                                        axis_size=axis_size)
    return _collective_matmul_fn(int(shard_dim), axis_name,
                                 int(axis_size), cfg, site,
                                 precision)(x, w_shard)
