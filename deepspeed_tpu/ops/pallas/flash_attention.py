"""Flash attention — the TPU replacement for the reference's fused attention
CUDA path (csrc/transformer/softmax_kernels.cu + the score/context matmuls in
ds_transformer_cuda.cpp): one Pallas kernel per pass that never materializes
the [S, S] score matrix in HBM, with online softmax and a recompute-based
backward (custom VJP), accumulating in fp32 on the MXU.

Layout: q/k/v as [B, H, S, D] → kernels run on [B*H] × q-block grid. Two
kernel families share the same per-block math (`_fwd_block_step` /
`_bwd_ds_block`):

- **plain**: K/V (fwd, dq) or Q/dO (dkv) rows for one (batch, head) live
  whole in VMEM — fastest, used while S·D·itemsize fits the measured
  ~512 KB row budget (S=4k at D=64 in bf16).
- **chunked**: a third grid dimension streams sequence CHUNKS and
  accumulates into revisited fp32 output blocks (forward softmax m/l state
  rides in revisited outputs; normalization happens in-kernel on the last
  chunk). This is how single-chip attention training reaches 32k context;
  beyond that, sequence parallelism shards S first
  (deepspeed_tpu/parallel/ring_attention.py).

The softmax scale is folded into the [block, D] q-loads (one small VPU
multiply instead of one per [block_q, block_k] score tile), and causal
loops split into unmasked below-diagonal blocks + masked diagonal blocks —
at D < 128 the kernels are VPU-bound, so score-tile passes are the cost
that matters.

On non-TPU backends the kernels run in interpreter mode so unit tests check
the same code path numerically against the jnp reference (the
test_cuda_forward.py methodology, SURVEY §4).
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30

# measured scoped-VMEM ceiling for whole-row residency on v5e. The r4
# FUSED backward additionally keeps a fp32 [S, D] dq row resident, which
# moved the ceiling DOWN: bf16 S=4096, D=64 compiled in a small harness
# but the same shapes inside a larger program (bench.py's S=4096 dense
# case, BH=64) overflow scoped vmem by 284 KB — so the unchunked cutoff
# is now S*D*itemsize <= 256 KB (S=2048 at D=64 bf16) and S=4096 routes
# to the chunked kernels, whose per-chunk residency is bounded. The
# chunked kernels use half of this per chunk for pipeline double
# buffering (chunk 4096 at S=32k overflowed by 0.9 MB; 2048 fits).
_UNCHUNKED_ROW_BYTES = 262144
# per-chunk budget for the CHUNKED kernels (independent of the unchunked
# cutoff above — they have no resident dq row): measured on v5e, chunk
# 4096 at S=32k overflowed by 0.9 MB; 2048 fits
_CHUNK_ROW_BYTES = 524288


def _interpret_default():
    from deepspeed_tpu.utils.platform import is_tpu_backend
    return not is_tpu_backend()


# ------------------------------------------------------ shared block math

def _causal_mask(s, q_pos0, k_pos0, block_q, block_k):
    q_pos = q_pos0 + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = k_pos0 + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _fwd_block_step(q, k, v, carry, q_pos0, k_pos0, block_q, block_k,
                    masked, scale):
    """One k-block of online-softmax forward. q/k/v stay in their native
    (typically bf16) dtype so the MXU runs at full rate — fp32 dot inputs
    run the systolic array at ~1/8 throughput, which made attention ~10%
    of peak and THE forward bottleneck at S=1k (r4 measurement). All dots
    accumulate fp32 (preferred_element_type); softmax state is fp32; the
    scale is applied to the fp32 scores (exactly equivalent to pre-scaled
    q up to bf16 rounding of q·scale, and independent of D).
    carry = (o_acc [bq, D] f32, m_acc [bq] f32, l_acc [bq] f32)."""
    o_acc, m_acc, l_acc = carry
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if masked:
        s = _causal_mask(s, q_pos0, k_pos0, block_q, block_k)
    m_new = jnp.maximum(m_acc, jnp.max(s, axis=1))
    alpha = jnp.exp(m_acc - m_new)
    p = jnp.exp(s - m_new[:, None])
    l_new = l_acc * alpha + jnp.sum(p, axis=1)
    o_new = o_acc * alpha[:, None] + jax.lax.dot(
        p.astype(v.dtype), v, preferred_element_type=jnp.float32)
    return o_new, m_new, l_new


def _bwd_ds_block(q, do, lse, delta, k, v, q_pos0, k_pos0, block_q, block_k,
                  masked, scale):
    """(p, ds) fp32 for one score tile of the backward; dot inputs stay in
    the native dtype (see _fwd_block_step). ds is d(loss)/d(s) with
    s = scale·q·kᵀ, so dq = scale·(ds·k) and dk = scale·(dsᵀ·q) — callers
    apply the final ·scale once on the accumulated result."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if masked:
        s = _causal_mask(s, q_pos0, k_pos0, block_q, block_k)
    p = jnp.exp(s - lse[:, None])
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None])
    return p, ds


def _causal_split_loop(lo, full, hi, body, carry):
    """fori_loop [lo, full) unmasked + [full, hi) masked."""
    carry = jax.lax.fori_loop(lo, full, lambda i, c: body(i, c, False),
                              carry)
    return jax.lax.fori_loop(full, hi, lambda i, c: body(i, c, True), carry)


# ---------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0]
    num_kb = seq_len // block_k

    def body(kb, carry, masked):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        return _fwd_block_step(q, k, v, carry, qi * block_q, kb * block_k,
                               block_q, block_k, masked, scale)

    carry0 = (jnp.zeros((block_q, q.shape[1]), jnp.float32),
              jnp.full((block_q,), NEG_INF, jnp.float32),
              jnp.zeros((block_q,), jnp.float32))
    if causal:
        num_full = (qi * block_q) // block_k
        num_active = ((qi + 1) * block_q + block_k - 1) // block_k
        o, m, l = _causal_split_loop(0, num_full, num_active, body, carry0)
    else:
        o, m, l = _causal_split_loop(0, num_kb, num_kb, body, carry0)

    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (o / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, :, 0] = m + jnp.log(l_safe)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
               heads=0, kv_heads=0):
    """``heads``/``kv_heads`` > 0 enable grouped-query K/V: q is
    [B*heads, S, D] while k/v stay [B*kv_heads, S, D] — the K/V block
    index maps fold the q head onto its KV head, so the reduced-head
    cache streams once per rep q heads and the full-head K/V is NEVER
    materialized in HBM (the GQA memory promise, models/llama.py)."""
    BH, S, D = q.shape
    grid = (BH, S // block_q)
    if heads and kv_heads and heads != kv_heads:
        rep = heads // kv_heads
        H = heads

        def kv_map(b, i):
            return ((b // H) * kv_heads + (b % H) // rep, 0, 0)
    else:
        def kv_map(b, i):
            return (b, 0, 0)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, seq_len=S)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), kv_map),
            pl.BlockSpec((1, S, D), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------- backward

def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, *, scale, causal, block_q,
                      block_k, seq_len):
    """Single-pass backward: the grid walks k-blocks; dk/dv accumulate
    block-locally over the q-blocks of the inner loop, while dq
    accumulates into a VMEM-resident full row (its index map ignores the
    k-block grid dim, so Pallas keeps the block resident across grid
    steps). Each (q-block, k-block) score tile — the dots AND the exp —
    is computed ONCE, where the split dq/dkv kernels computed everything
    but the final products twice; the exp on [bq, bk] fp32 tiles is
    VPU-bound, so halving it is the biggest attention-bwd lever at
    training shapes (measured 2.4 ms/layer -> target <1.5 at the 774M
    headline: B*H=160, S=1024, D=64)."""
    ki = pl.program_id(1)
    num_kb = seq_len // block_k
    num_qb = seq_len // block_q
    k = k_ref[0]   # [block_k, D]
    v = v_ref[0]

    @pl.when(ki == 0)
    def _init():
        dq_ref[0] = jnp.zeros_like(dq_ref[0])

    def body(qb, carry, masked):
        dk_acc, dv_acc = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :]
        do = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(qb * block_q, block_q), 0]
        delta = delta_ref[0, pl.ds(qb * block_q, block_q), 0]
        p, ds = _bwd_ds_block(q, do, lse, delta, k, v, qb * block_q,
                              ki * block_k, block_q, block_k, masked,
                              scale)
        dsl = ds.astype(q.dtype)
        dv_new = dv_acc + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_new = dk_acc + jax.lax.dot_general(
            dsl, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        sl = pl.ds(qb * block_q, block_q)
        dq_ref[0, sl, :] += jax.lax.dot(
            dsl, k, preferred_element_type=jnp.float32)
        return dk_new, dv_new

    carry0 = (jnp.zeros(k.shape, jnp.float32),
              jnp.zeros(v.shape, jnp.float32))
    if causal:
        first_active = (ki * block_k) // block_q
        first_full = ((ki + 1) * block_k + block_q - 1) // block_q
        carry = jax.lax.fori_loop(
            first_active, jnp.minimum(first_full, num_qb),
            lambda qb, c: body(qb, c, True), carry0)
        dk, dv = jax.lax.fori_loop(
            first_full, num_qb, lambda qb, c: body(qb, c, False), carry)
    else:
        dk, dv = _causal_split_loop(0, num_qb, num_qb, body, carry0)
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)   # dk = scale·Σ dsᵀ·q
    dv_ref[0] = dv.astype(dv_ref.dtype)

    @pl.when(ki == num_kb - 1)
    def _finish():
        # dq = scale·Σ ds·k, applied once after every k-block contributed
        dq_ref[0] *= scale


def _flash_bwd(q, k, v, o, lse, do, scale, causal, block_q, block_k,
               interpret):
    BH, S, D = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, :, None]  # [BH, S, 1]

    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=S),
        grid=(BH, S // block_k),
        in_specs=[
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq.astype(q.dtype), dk, dv


# ------------------------------------------------- long-S chunked variants

def _fwd_kernel_chunked(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                        *, scale, causal, block_q, block_k, chunk,
                        n_chunks):
    qi = pl.program_id(1)
    kc = pl.program_id(2)
    cb = chunk // block_k                      # k-blocks per chunk
    q = q_ref[0]

    @pl.when(kc == 0)
    def _init():
        o_ref[0] = jnp.zeros_like(o_ref[0])
        m_ref[0] = jnp.full_like(m_ref[0], NEG_INF)
        l_ref[0] = jnp.zeros_like(l_ref[0])

    def body(j, carry, masked):
        kb = kc * cb + j                       # global k-block index
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        return _fwd_block_step(q, k, v, carry, qi * block_q, kb * block_k,
                               block_q, block_k, masked, scale)

    carry0 = (o_ref[0], m_ref[0, :, 0], l_ref[0, :, 0])
    if causal:
        num_full = (qi * block_q) // block_k
        num_active = ((qi + 1) * block_q + block_k - 1) // block_k
        j_full = jnp.clip(num_full - kc * cb, 0, cb)
        j_hi = jnp.clip(num_active - kc * cb, 0, cb)
        o, m, l = _causal_split_loop(0, j_full, j_hi, body, carry0)
    else:
        o, m, l = _causal_split_loop(0, cb, cb, body, carry0)

    # accumulate raw (o, m, l) across chunk revisits; the last chunk holds
    # the final softmax state, so normalize in-kernel there — no separate
    # [BH, S, D] normalization pass in HBM
    last = kc == n_chunks - 1
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = jnp.where(last,
                         jnp.where((l > 0)[:, None], o / l_safe[:, None],
                                   0.0),
                         o)
    m_ref[0, :, 0] = jnp.where(last, m + jnp.log(l_safe), m)
    l_ref[0, :, 0] = l


def _flash_fwd_chunked(q, k, v, scale, causal, block_q, block_k, chunk,
                       interpret):
    BH, S, D = q.shape
    n_chunks = S // chunk
    kernel = functools.partial(_fwd_kernel_chunked, scale=scale,
                               causal=causal, block_q=block_q,
                               block_k=block_k, chunk=chunk,
                               n_chunks=n_chunks)
    o32, lse, _ = pl.pallas_call(
        kernel,
        grid=(BH, S // block_q, n_chunks),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, c: (b, i, 0)),
            pl.BlockSpec((1, chunk, D), lambda b, i, c: (b, c, 0)),
            pl.BlockSpec((1, chunk, D), lambda b, i, c: (b, c, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, c: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, c: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, c: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, S, 1), jnp.float32),
            jax.ShapeDtypeStruct((BH, S, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o32.astype(q.dtype), lse


def _bwd_dq_kernel_chunked(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           dq_ref, *, scale, causal, block_q, block_k,
                           chunk, n_chunks):
    qi = pl.program_id(1)
    kc = pl.program_id(2)
    cb = chunk // block_k
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]

    @pl.when(kc == 0)
    def _init():
        dq_ref[0] = jnp.zeros_like(dq_ref[0])

    def body(j, dq_acc, masked):
        kb = kc * cb + j
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        _, ds = _bwd_ds_block(q, do, lse, delta, k, v, qi * block_q,
                              kb * block_k, block_q, block_k, masked,
                              scale)
        return dq_acc + jax.lax.dot(ds.astype(k.dtype), k,
                                    preferred_element_type=jnp.float32)

    if causal:
        num_full = (qi * block_q) // block_k
        num_active = ((qi + 1) * block_q + block_k - 1) // block_k
        j_full = jnp.clip(num_full - kc * cb, 0, cb)
        j_hi = jnp.clip(num_active - kc * cb, 0, cb)
        dq = _causal_split_loop(0, j_full, j_hi, body, dq_ref[0])
    else:
        dq = _causal_split_loop(0, cb, cb, body, dq_ref[0])
    # accumulate UNscaled across chunk revisits; apply the folded-scale
    # chain rule once on the final chunk (dq = scale · Σ ds·k)
    dq_ref[0] = jnp.where(pl.program_id(2) == n_chunks - 1, dq * scale, dq)


def _bwd_dkv_kernel_chunked(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                            dk_ref, dv_ref, *, scale, causal, block_q,
                            block_k, chunk, n_chunks):
    ki = pl.program_id(1)
    qc = pl.program_id(2)
    cb = chunk // block_q
    k = k_ref[0]
    v = v_ref[0]

    @pl.when(qc == 0)
    def _init():
        dk_ref[0] = jnp.zeros_like(dk_ref[0])
        dv_ref[0] = jnp.zeros_like(dv_ref[0])

    def body(j, carry, masked):
        dk_acc, dv_acc = carry
        qb = qc * cb + j
        q = q_ref[0, pl.ds(j * block_q, block_q), :]
        do = do_ref[0, pl.ds(j * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(j * block_q, block_q), 0]
        delta = delta_ref[0, pl.ds(j * block_q, block_q), 0]
        p, ds = _bwd_ds_block(q, do, lse, delta, k, v, qb * block_q,
                              ki * block_k, block_q, block_k, masked,
                              scale)
        dv_new = dv_acc + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_new = dk_acc + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    carry0 = (dk_ref[0], dv_ref[0])
    if causal:
        # within this q-chunk: blocks before the diagonal skip entirely,
        # blocks straddling it run masked, strictly-after blocks unmasked
        first_active = (ki * block_k) // block_q
        first_full = ((ki + 1) * block_k + block_q - 1) // block_q
        j_lo = jnp.clip(first_active - qc * cb, 0, cb)
        j_mid = jnp.clip(first_full - qc * cb, 0, cb)
        carry = jax.lax.fori_loop(
            j_lo, j_mid, lambda j, c: body(j, c, True), carry0)
        dk, dv = jax.lax.fori_loop(
            j_mid, cb, lambda j, c: body(j, c, False), carry)
    else:
        dk, dv = _causal_split_loop(0, cb, cb, body, carry0)
    # dk accumulates UNscaled across chunk revisits; the folded-scale
    # chain rule (dk = scale·Σ dsᵀ·q) lands once on the final chunk
    dk_ref[0] = jnp.where(qc == n_chunks - 1, dk * scale, dk)
    dv_ref[0] = dv


def _flash_bwd_chunked(q, k, v, o, lse, do, scale, causal, block_q, block_k,
                       chunk, interpret):
    BH, S, D = q.shape
    n_chunks = S // chunk
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, :, None]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_chunked, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, chunk=chunk,
                          n_chunks=n_chunks),
        grid=(BH, S // block_q, n_chunks),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, c: (b, i, 0)),
            pl.BlockSpec((1, chunk, D), lambda b, i, c: (b, c, 0)),
            pl.BlockSpec((1, chunk, D), lambda b, i, c: (b, c, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i, c: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, c: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, c: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, c: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_chunked, scale=scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          chunk=chunk, n_chunks=n_chunks),
        grid=(BH, S // block_k, n_chunks),
        in_specs=[
            pl.BlockSpec((1, chunk, D), lambda b, i, c: (b, c, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, c: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, c: (b, i, 0)),
            pl.BlockSpec((1, chunk, D), lambda b, i, c: (b, c, 0)),
            pl.BlockSpec((1, chunk, 1), lambda b, i, c: (b, c, 0)),
            pl.BlockSpec((1, chunk, 1), lambda b, i, c: (b, c, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, i, c: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, c: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq.astype(q.dtype), dk.astype(q.dtype), dv.astype(q.dtype)


# ---------------------------------------------------------------- public op

def _dispatch_fwd(q, k, v, scale, causal, block_q, block_k, chunk,
                  interpret, heads=0, kv_heads=0):
    if chunk:
        assert not (heads and kv_heads and heads != kv_heads), \
            "GQA rides the unchunked kernel (caller repeats for chunked)"
        return _flash_fwd_chunked(q, k, v, scale, causal, block_q, block_k,
                                  chunk, interpret)
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                      heads=heads, kv_heads=kv_heads)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash_attention(q, k, v, scale, causal, block_q, block_k, chunk,
                     interpret, heads=0, kv_heads=0):
    o, _ = _dispatch_fwd(q, k, v, scale, causal, block_q, block_k, chunk,
                         interpret, heads, kv_heads)
    return o


def _flash_attention_fwd(q, k, v, scale, causal, block_q, block_k, chunk,
                         interpret, heads=0, kv_heads=0):
    o, lse = _dispatch_fwd(q, k, v, scale, causal, block_q, block_k, chunk,
                           interpret, heads, kv_heads)
    # name the residuals so remat policies can elect to keep them: saving
    # o (+tiny lse) lets the backward kernels run without re-executing the
    # forward kernel under rematerialization (models/gpt2.py "dots_flash")
    from jax.ad_checkpoint import checkpoint_name
    o = checkpoint_name(o, "flash_o")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o, lse)


def _flash_attention_bwd(scale, causal, block_q, block_k, chunk, interpret,
                         heads, kv_heads, residuals, do):
    q, k, v, o, lse = residuals
    gqa = bool(heads and kv_heads and heads != kv_heads)
    if gqa:
        # backward still runs the full-head kernels: K/V repeat to
        # [B*H, S, D] HERE (transient, bwd-only) and dk/dv sum back over
        # the rep query heads sharing each KV head. A dk/dv-accumulating
        # GQA backward kernel would remove this transient — the forward
        # and prefill (the steady-state memory) no longer materialize it.
        B = q.shape[0] // heads
        rep = heads // kv_heads
        S, D = k.shape[1], k.shape[2]

        def rep_kv(t):
            return jnp.repeat(t.reshape(B, kv_heads, S, D), rep,
                              axis=1).reshape(B * heads, S, D)
        k = rep_kv(k)
        v = rep_kv(v)
    if chunk:
        dq, dk, dv = _flash_bwd_chunked(q, k, v, o, lse, do, scale, causal,
                                        block_q, block_k, chunk, interpret)
    else:
        dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, scale, causal,
                                block_q, block_k, interpret)
    if gqa:
        def sum_rep(t):
            return t.reshape(B, kv_heads, rep, S, D).sum(axis=2) \
                .astype(t.dtype).reshape(B * kv_heads, S, D)
        dk = sum_rep(dk)
        dv = sum_rep(dv)
    return dq, dk, dv


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, interpret=None, chunk=None):
    """[B, H, S, D] flash attention. Falls back to the jnp reference for
    shapes the kernel can't tile (tiny S/D in unit tests). ``chunk``
    forces the long-S chunked kernels (auto-selected past the VMEM row
    budget); it must divide S and be a multiple of both block sizes."""
    B, H, S, D = q.shape
    scale = float(scale) if scale is not None else 1.0 / float(np.sqrt(D))
    if interpret is None:
        interpret = _interpret_default()
    # 512/512 measured fastest on v5e at S=1k-4k, D=64 (27% over 256/256:
    # fewer grid steps amortize the half-rate D<128 contraction better).
    # For S not divisible by 512 take the largest power-of-two divisor so
    # e.g. S=768/1280/2560 keep the flash kernel instead of silently
    # materializing [S, S] scores in the reference fallback.
    def pick_block(requested):
        if requested:
            return requested
        top = 64 if interpret else 512
        for cand in (top, 256, 128, 64, 32):
            if cand <= top and S % cand == 0:
                return cand
        # irregular short sequences (e.g. S=80): one block spanning S keeps
        # the kernel path, matching the old min(block, S) behavior
        return S if S <= top else 0
    block_q = pick_block(block_q)
    block_k = pick_block(block_k)
    Hkv = k.shape[1]
    assert v.shape[1] == Hkv and H % Hkv == 0, (q.shape, k.shape)

    if not block_q or not block_k or S % block_q or S % block_k:
        from deepspeed_tpu.ops.attention import reference_attention
        # reference_attention repeats reduced-head K/V itself
        return reference_attention(q, k, v, causal=causal, scale=scale)
    if chunk is not None:
        if S % chunk or chunk % block_q or chunk % block_k:
            raise ValueError(
                f"chunk={chunk} must divide S={S} and be a multiple of "
                f"block_q={block_q} and block_k={block_k}")
    itemsize = jnp.dtype(q.dtype).itemsize
    if chunk is None and S * D * itemsize > _UNCHUNKED_ROW_BYTES:
        # whole-row residency stops fitting scoped VMEM — stream chunks
        budget = max(_CHUNK_ROW_BYTES // 2 // (D * itemsize), 1)
        for cand in (4096, 2048, 1024, 512, 256, 128, 64):
            if cand <= budget and S % cand == 0 \
                    and cand % block_q == 0 and cand % block_k == 0:
                chunk = cand
                break
        else:
            from deepspeed_tpu.ops.attention import reference_attention
            return reference_attention(q, k, v, causal=causal,
                                       scale=scale)

    qf = q.reshape(B * H, S, D)
    if chunk and Hkv != H:
        # the chunked kernels keep full-head maps; GQA rides the
        # unchunked kernel — repeat here for the long-S streaming path
        k = jnp.repeat(k, H // Hkv, axis=1)
        v = jnp.repeat(v, H // Hkv, axis=1)
        Hkv = H
    kf = k.reshape(B * k.shape[1], S, D)
    vf = v.reshape(B * v.shape[1], S, D)
    o = _flash_attention(qf, kf, vf, scale, causal, block_q, block_k,
                         int(chunk) if chunk else 0, bool(interpret),
                         H, Hkv)
    return o.reshape(B, H, S, D)
