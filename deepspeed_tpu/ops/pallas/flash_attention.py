"""Flash attention — the TPU replacement for the reference's fused attention
CUDA path (csrc/transformer/softmax_kernels.cu + the score/context matmuls in
ds_transformer_cuda.cpp): one Pallas kernel per pass that never materializes
the [S, S] score matrix in HBM, with online softmax and a recompute-based
backward (custom VJP), accumulating in fp32 on the MXU.

Layout: q/k/v as [B, H, S, D] → kernels run on [B*H] × q-block grid; K/V for
one (batch, head) live in VMEM (S·D·2 bytes each — fits comfortably for
S ≤ 8k at D=128; beyond that, sequence parallelism splits S first, see
deepspeed_tpu/parallel/ring_attention.py).

On non-TPU backends the kernels run in interpreter mode so unit tests check
the same code path numerically against the jnp reference (the
test_cuda_forward.py methodology, SURVEY §4).
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _interpret_default():
    from deepspeed_tpu.utils.platform import is_tpu_backend
    return not is_tpu_backend()


# ---------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k, seq_len):
    qi = pl.program_id(1)
    # fold the softmax scale into the [block_q, D] q-load: one small VPU
    # multiply here instead of one [block_q, block_k] multiply per k-block
    q = q_ref[0].astype(jnp.float32) * scale
    num_kb = seq_len // block_k

    def body(kb, carry, masked):
        o_acc, m_acc, l_acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if masked:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m_acc, jnp.max(s, axis=1))
        alpha = jnp.exp(m_acc - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_acc * alpha + jnp.sum(p, axis=1)
        o_new = o_acc * alpha[:, None] + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)

    if causal:
        # split the k-loop: blocks fully below the diagonal skip the iota
        # mask (3 fewer VPU passes over [block_q, block_k] — at D < 128 the
        # kernels are VPU-bound, so this is the hot path), then the blocks
        # straddling the diagonal run masked.
        num_full = (qi * block_q) // block_k
        num_active = ((qi + 1) * block_q + block_k - 1) // block_k
        carry = jax.lax.fori_loop(
            0, num_full, lambda kb, c: body(kb, c, False), (o0, m0, l0))
        o, m, l = jax.lax.fori_loop(
            num_full, num_active, lambda kb, c: body(kb, c, True), carry)
    else:
        o, m, l = jax.lax.fori_loop(
            0, num_kb, lambda kb, c: body(kb, c, False), (o0, m0, l0))

    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (o / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, :, 0] = m + jnp.log(l_safe)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    BH, S, D = q.shape
    grid = (BH, S // block_q)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, seq_len=S)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------- backward

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, block_q, block_k, seq_len):
    qi = pl.program_id(1)
    # s is computed against pre-scaled q; the chain rule's ds·scale then
    # collapses into one [block_q, D] multiply on the accumulated dq below
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    num_kb = seq_len // block_k

    def body(kb, dq_acc, masked):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if masked:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return dq_acc + jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    dq0 = jnp.zeros_like(q)
    if causal:
        num_full = (qi * block_q) // block_k
        num_active = ((qi + 1) * block_q + block_k - 1) // block_k
        dq = jax.lax.fori_loop(0, num_full,
                               lambda kb, c: body(kb, c, False), dq0)
        dq = jax.lax.fori_loop(num_full, num_active,
                               lambda kb, c: body(kb, c, True), dq)
    else:
        dq = jax.lax.fori_loop(0, num_kb,
                               lambda kb, c: body(kb, c, False), dq0)
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, block_k,
                    seq_len):
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)   # [block_k, D]
    v = v_ref[0].astype(jnp.float32)
    num_qb = seq_len // block_q

    def body(qb, carry, masked):
        dk_acc, dv_acc = carry
        # pre-scaled q: s needs no [block_q, block_k] multiply, and
        # dk = dsᵀ·(scale·q) absorbs the chain-rule scale exactly
        q = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(
            jnp.float32) * scale
        do = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qb * block_q, block_q), 0]
        delta = delta_ref[0, pl.ds(qb * block_q, block_q), 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if masked:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dv_new = dv_acc + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        dk_new = dk_acc + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk0 = jnp.zeros_like(k)
    dv0 = jnp.zeros_like(v)
    if causal:
        # q-blocks straddling the diagonal run masked; strictly-below-
        # diagonal q-blocks (q_pos >= all k_pos of this k-block) don't
        first_active = (ki * block_k) // block_q
        first_full = ((ki + 1) * block_k + block_q - 1) // block_q
        carry = jax.lax.fori_loop(
            first_active, jnp.minimum(first_full, num_qb),
            lambda qb, c: body(qb, c, True), (dk0, dv0))
        dk, dv = jax.lax.fori_loop(
            first_full, num_qb, lambda qb, c: body(qb, c, False), carry)
    else:
        dk, dv = jax.lax.fori_loop(
            0, num_qb, lambda qb, c: body(qb, c, False), (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, scale, causal, block_q, block_k, interpret):
    BH, S, D = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, :, None]  # [BH, S, 1]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=S),
        grid=(BH, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=S),
        grid=(BH, S // block_k),
        in_specs=[
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------- public op

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, scale, causal, block_q, block_k, interpret):
    o, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return o


def _flash_attention_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    # name the residuals so remat policies can elect to keep them: saving
    # o (+tiny lse) lets the backward kernels run without re-executing the
    # forward kernel under rematerialization (models/gpt2.py "dots_flash")
    from jax.ad_checkpoint import checkpoint_name
    o = checkpoint_name(o, "flash_o")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o, lse)


def _flash_attention_bwd(scale, causal, block_q, block_k, interpret,
                         residuals, do):
    q, k, v, o, lse = residuals
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, scale, causal,
                            block_q, block_k, interpret)
    return dq, dk, dv


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, interpret=None):
    """[B, H, S, D] flash attention. Falls back to the jnp reference for
    shapes the kernel can't tile (tiny S/D in unit tests)."""
    B, H, S, D = q.shape
    scale = float(scale) if scale is not None else 1.0 / float(np.sqrt(D))
    if interpret is None:
        interpret = _interpret_default()
    # 512/512 measured fastest on v5e at S=1k-4k, D=64 (27% over 256/256:
    # fewer grid steps amortize the half-rate D<128 contraction better).
    # For S not divisible by 512 take the largest power-of-two divisor so
    # e.g. S=768/1280/2560 keep the flash kernel instead of silently
    # materializing [S, S] scores in the reference fallback.
    def pick_block(requested):
        if requested:
            return requested
        top = 64 if interpret else 512
        for cand in (top, 256, 128, 64, 32):
            if cand <= top and S % cand == 0:
                return cand
        # irregular short sequences (e.g. S=80): one block spanning S keeps
        # the kernel path, matching the old min(block, S) behavior
        return S if S <= top else 0
    block_q = pick_block(block_q)
    block_k = pick_block(block_k)
    if not block_q or not block_k or S % block_q or S % block_k:
        from deepspeed_tpu.ops.attention import reference_attention
        return reference_attention(q, k, v, causal=causal, scale=scale)

    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)
    o = _flash_attention(qf, kf, vf, scale, causal, block_q, block_k,
                         bool(interpret))
    return o.reshape(B, H, S, D)
