"""Adam/AdamW — TPU-native rebuild of the reference's fused GPU Adam
(csrc/adam/multi_tensor_adam.cu:163 via ops/adam/fused_adam.py:15) and the
host-side DeepSpeedCPUAdam (csrc/adam/cpu_adam.cpp:21 via ops/adam/cpu_adam.py:12).

On TPU there is nothing to "fuse" by hand: the whole update is a handful of
elementwise ops that XLA fuses into one kernel per parameter (and across
parameters once the trees are flattened under jit). The CPU variant drives
the C++ SIMD library in deepspeed_tpu/csrc/cpu_adam.cpp for the
ZeRO-Offload optimizer step on host DRAM.
"""

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import TpuOptimizer, tree_zeros_like


@dataclasses.dataclass
class FusedAdam(TpuOptimizer):
    """Adam/AdamW with decoupled or L2 weight decay.

    ``adam_w_mode=True`` → AdamW (decoupled decay), matching reference
    fused_adam.py:15's flag of the same name. Bias correction matches
    torch.optim.Adam semantics, which the reference kernels implement
    (multi_tensor_adam.cu:103-140).
    """
    lr: float = 1e-3
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0
    adam_w_mode: bool = True
    bias_correction: bool = True
    amsgrad: bool = False
    # storage dtype for exp_avg: "fp32" (default, the reference's
    # fp32-master semantics) or "bf16" — compute is still fp32 (read →
    # widen → update → round). exp_avg_sq deliberately stays fp32 either
    # way: at beta2=0.999 its per-step relative update (~1e-3) is below
    # bf16 ulp (3.9e-3), so a bf16 EMA freezes (in particular it can never
    # decay when gradients shrink) — a systematic bias, not noise.
    moment_dtype: str = "fp32"

    param_like_state_fields = ("exp_avg", "exp_avg_sq")
    elementwise_update = True

    def __post_init__(self):
        if self.amsgrad:
            raise ValueError("FusedAdam does not support the AMSGrad variant "
                             "(parity with reference fused_adam.py:40)")
        if self.moment_dtype not in ("fp32", "bf16"):
            raise ValueError(f"moment_dtype must be 'fp32' or 'bf16', got "
                             f"{self.moment_dtype!r}")

    def _mdtype(self):
        return jnp.bfloat16 if self.moment_dtype == "bf16" else jnp.float32

    def init(self, params):
        return {
            "step": jnp.zeros((), jnp.int32),
            # Optimizer ("master") state stays fp32 by default even when
            # params are bf16 — the ZeRO fp32-partition analog (reference
            # stage2.py:~300); moment_dtype="bf16" opts exp_avg into half
            # storage (exp_avg_sq must stay fp32, see field comment).
            "exp_avg": tree_zeros_like(params, self._mdtype()),
            "exp_avg_sq": tree_zeros_like(params, jnp.float32),
        }

    def step(self, params, grads, state, lr=None, grad_scale=None):
        """``grad_scale`` folds loss-scale inverse and clip coefficient into
        the Adam gradient read — the engine passes it instead of
        materializing unscaled/clipped copies of the full gradient tree
        (two saved read+write passes per step)."""
        lr = self.lr if lr is None else lr
        beta1, beta2 = self.betas
        count = state["step"] + 1
        cf = count.astype(jnp.float32)
        if self.bias_correction:
            bc1 = 1.0 - beta1 ** cf
            bc2 = 1.0 - beta2 ** cf
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def update_leaf(p, g, m, v):
            g32 = g.astype(jnp.float32)
            if grad_scale is not None:
                g32 = g32 * grad_scale
            p32 = p.astype(jnp.float32)
            if self.weight_decay != 0.0 and not self.adam_w_mode:
                g32 = g32 + self.weight_decay * p32
            m_new = beta1 * m.astype(jnp.float32) + (1.0 - beta1) * g32
            v_new = beta2 * v.astype(jnp.float32) + (1.0 - beta2) * (g32 * g32)
            denom = jnp.sqrt(v_new / bc2) + self.eps
            update = (m_new / bc1) / denom
            if self.weight_decay != 0.0 and self.adam_w_mode:
                update = update + self.weight_decay * p32
            p_new = p32 - lr * update
            return (p_new.astype(p.dtype), m_new.astype(m.dtype),
                    v_new.astype(v.dtype))

        flat = jax.tree_util.tree_map(
            update_leaf, params, grads, state["exp_avg"], state["exp_avg_sq"])
        # unzip 3-tuples back into trees
        new_params = jax.tree_util.tree_map(
            lambda t: t[0], flat, is_leaf=lambda x: isinstance(x, tuple))
        new_m = jax.tree_util.tree_map(
            lambda t: t[1], flat, is_leaf=lambda x: isinstance(x, tuple))
        new_v = jax.tree_util.tree_map(
            lambda t: t[2], flat, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"step": count, "exp_avg": new_m, "exp_avg_sq": new_v}


@dataclasses.dataclass
class Adam(FusedAdam):
    """Plain Adam (L2 decay)."""
    adam_w_mode: bool = False


class DeepSpeedCPUAdam(FusedAdam):
    """Host-resident Adam for ZeRO-Offload — reference ops/adam/cpu_adam.py:12.

    When the native library (deepspeed_tpu/csrc/cpu_adam.cpp, AVX/NEON
    SIMD + OpenMP — the reference's csrc/adam/cpu_adam.cpp:21 equivalent) is
    built, the step runs there on host-DRAM-resident numpy views; otherwise it
    falls back to running the same math with jax on the CPU backend. The
    engine routes the step here when ``offload_optimizer.device == "cpu"``.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._native = None
        try:
            from deepspeed_tpu.ops.native import cpu_adam as native_cpu_adam
            self._native = native_cpu_adam.load()
        except Exception:
            self._native = None

    @property
    def has_native(self):
        return self._native is not None

    def step_numpy(self, params_np, grads_np, m_np, v_np, step_count, lr):
        """In-place native SIMD update on flat fp32 numpy arrays (one call per
        flattened leaf). Used by the offload path outside jit."""
        import numpy as np
        if self._native is None:
            # numpy fallback with identical math
            beta1, beta2 = self.betas
            bc1 = 1.0 - beta1 ** step_count
            bc2 = 1.0 - beta2 ** step_count
            g = grads_np.astype(np.float32)
            if self.weight_decay != 0.0 and not self.adam_w_mode:
                g = g + self.weight_decay * params_np
            m_np *= beta1
            m_np += (1.0 - beta1) * g
            v_np *= beta2
            v_np += (1.0 - beta2) * g * g
            denom = np.sqrt(v_np / bc2) + self.eps
            update = (m_np / bc1) / denom
            if self.weight_decay != 0.0 and self.adam_w_mode:
                update += self.weight_decay * params_np
            params_np -= lr * update
            return
        self._native.adam_step(params_np, grads_np, m_np, v_np,
                               int(step_count), float(lr),
                               float(self.betas[0]), float(self.betas[1]),
                               float(self.eps), float(self.weight_decay),
                               bool(self.adam_w_mode))
