"""LAMB — TPU-native rebuild of the reference fused LAMB kernel
(csrc/lamb/fused_lamb_cuda_kernel.cu:469 via ops/lamb/fused_lamb.py:12).

Per-tensor trust ratio: r = ||p|| / ||adam_update||, with the reference's
max_coeff/min_coeff clamping (fused_lamb_cuda_kernel.cu lamb_coeff logic).
XLA handles the two reductions + update as fused kernels; the reference
needed a two-pass CUDA reduction workspace for the same thing.
"""

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import TpuOptimizer, tree_zeros_like


@dataclasses.dataclass
class FusedLamb(TpuOptimizer):
    lr: float = 1e-3
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0
    bias_correction: bool = True
    max_coeff: float = 10.0
    min_coeff: float = 0.01
    # storage dtype for exp_avg ("fp32" | "bf16"); compute stays fp32 and
    # exp_avg_sq stays fp32 regardless (see FusedAdam.moment_dtype for why
    # a bf16 second moment freezes at beta2=0.999)
    moment_dtype: str = "fp32"

    param_like_state_fields = ("exp_avg", "exp_avg_sq")

    def __post_init__(self):
        if self.moment_dtype not in ("fp32", "bf16"):
            raise ValueError(f"moment_dtype must be 'fp32' or 'bf16', got "
                             f"{self.moment_dtype!r}")

    def init(self, params):
        mdtype = jnp.bfloat16 if self.moment_dtype == "bf16" else jnp.float32
        return {
            "step": jnp.zeros((), jnp.int32),
            "exp_avg": tree_zeros_like(params, mdtype),
            "exp_avg_sq": tree_zeros_like(params, jnp.float32),
        }

    def step(self, params, grads, state, lr=None, grad_scale=None):
        lr = self.lr if lr is None else lr
        beta1, beta2 = self.betas
        count = state["step"] + 1
        cf = count.astype(jnp.float32)
        if self.bias_correction:
            bc1 = 1.0 - beta1 ** cf
            bc2 = 1.0 - beta2 ** cf
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def update_leaf(p, g, m, v):
            g32 = g.astype(jnp.float32)
            if grad_scale is not None:
                g32 = g32 * grad_scale
            p32 = p.astype(jnp.float32)
            m_new = beta1 * m.astype(jnp.float32) + (1.0 - beta1) * g32
            v_new = beta2 * v + (1.0 - beta2) * (g32 * g32)
            update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)
            if self.weight_decay != 0.0:
                update = update + self.weight_decay * p32
            p_norm = jnp.sqrt(jnp.sum(p32 * p32))
            u_norm = jnp.sqrt(jnp.sum(update * update))
            trust = jnp.where((p_norm > 0) & (u_norm > 0),
                              p_norm / jnp.maximum(u_norm, 1e-12),
                              jnp.float32(1.0))
            trust = jnp.clip(trust, self.min_coeff, self.max_coeff)
            p_new = p32 - lr * trust * update
            return p_new.astype(p.dtype), m_new.astype(m.dtype), v_new

        flat = jax.tree_util.tree_map(update_leaf, params, grads,
                                      state["exp_avg"], state["exp_avg_sq"])
        new_params = jax.tree_util.tree_map(
            lambda t: t[0], flat, is_leaf=lambda x: isinstance(x, tuple))
        new_m = jax.tree_util.tree_map(
            lambda t: t[1], flat, is_leaf=lambda x: isinstance(x, tuple))
        new_v = jax.tree_util.tree_map(
            lambda t: t[2], flat, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"step": count, "exp_avg": new_m, "exp_avg_sq": new_v}
