"""Op registry. Public surface resolves LAZILY (PEP 562, same idiom as
the root package): the optimizer/transformer ops import jax, but
``deepspeed_tpu.ops.native.aio`` must stay importable without an
accelerator stack — the swap tier constructs on machines where jax does
not exist (ci/swap_gate.sh pins that with a poisoned-jax import).
`ops.FusedAdam` etc. behave exactly like the old eager imports."""

_LAZY_ATTRS = {
    "FusedAdam": ("deepspeed_tpu.ops.adam", "FusedAdam"),
    "DeepSpeedCPUAdam": ("deepspeed_tpu.ops.adam", "DeepSpeedCPUAdam"),
    "FusedLamb": ("deepspeed_tpu.ops.lamb", "FusedLamb"),
    "SGD": ("deepspeed_tpu.ops.sgd", "SGD"),
    "DeepSpeedTransformerConfig": ("deepspeed_tpu.ops.transformer",
                                   "DeepSpeedTransformerConfig"),
    "DeepSpeedTransformerLayer": ("deepspeed_tpu.ops.transformer",
                                  "DeepSpeedTransformerLayer"),
    # submodules the old eager imports bound as attributes
    "adam": ("deepspeed_tpu.ops.adam", None),
    "lamb": ("deepspeed_tpu.ops.lamb", None),
    "sgd": ("deepspeed_tpu.ops.sgd", None),
    "sparse_attention": ("deepspeed_tpu.ops.sparse_attention", None),
    "transformer": ("deepspeed_tpu.ops.transformer", None),
    "native": ("deepspeed_tpu.ops.native", None),
}

from deepspeed_tpu.utils.lazy import lazy_attrs  # noqa: E402

__getattr__, __dir__ = lazy_attrs(__name__, _LAZY_ATTRS)
