from deepspeed_tpu.ops.adam import FusedAdam, DeepSpeedCPUAdam
from deepspeed_tpu.ops.lamb import FusedLamb
from deepspeed_tpu.ops.sgd import SGD
from deepspeed_tpu.ops import sparse_attention  # noqa: F401
from deepspeed_tpu.ops import transformer  # noqa: F401
from deepspeed_tpu.ops.transformer import (
    DeepSpeedTransformerConfig,
    DeepSpeedTransformerLayer,
)
