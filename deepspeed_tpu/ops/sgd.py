"""SGD with momentum (torch.optim.SGD-compatible semantics, incl. Nesterov).

The reference dispatches unrecognized optimizer names to torch
(engine.py:704-759 falls through to client optimizers); we provide SGD
natively so config `"type": "SGD"` works out of the box.
"""

import dataclasses

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import TpuOptimizer, tree_zeros_like


@dataclasses.dataclass
class SGD(TpuOptimizer):
    lr: float = 1e-3
    momentum: float = 0.0
    weight_decay: float = 0.0
    dampening: float = 0.0
    nesterov: bool = False

    param_like_state_fields = ("momentum_buffer",)
    elementwise_update = True

    def init(self, params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "momentum_buffer": tree_zeros_like(params, jnp.float32),
        }

    def step(self, params, grads, state, lr=None):
        lr = self.lr if lr is None else lr
        count = state["step"] + 1
        first = state["step"] == 0

        def update_leaf(p, g, buf):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if self.weight_decay != 0.0:
                g32 = g32 + self.weight_decay * p32
            if self.momentum != 0.0:
                # torch semantics: buf = g on first step, else buf*mu + (1-damp)*g
                buf_new = jnp.where(first, g32,
                                    self.momentum * buf + (1.0 - self.dampening) * g32)
                d = g32 + self.momentum * buf_new if self.nesterov else buf_new
            else:
                buf_new = buf
                d = g32
            return (p32 - lr * d).astype(p.dtype), buf_new

        flat = jax.tree_util.tree_map(update_leaf, params, grads,
                                      state["momentum_buffer"])
        new_params = jax.tree_util.tree_map(
            lambda t: t[0], flat, is_leaf=lambda x: isinstance(x, tuple))
        new_buf = jax.tree_util.tree_map(
            lambda t: t[1], flat, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"step": count, "momentum_buffer": new_buf}
