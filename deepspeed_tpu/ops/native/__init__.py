from deepspeed_tpu.ops.native.builder import OpBuilder, ALL_OPS
